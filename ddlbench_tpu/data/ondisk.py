"""On-disk dataset adapter with the SyntheticData batch interface.

Backs the real-data path (CLI ``-s``): raw uint8 batches come from the native
prefetching loader (data/native_loader.py), are uploaded to device, and are
normalized — and, for training image batches, augmented — inside jit. The
per-dataset train transforms mirror the reference drivers:

* mnist: normalize only (mnist_pytorch.py:176-178)
* cifar10: RandomCrop(32, padding=4) + RandomHorizontalFlip
  (cifar10_pytorch.py:164-168)
* imagenet/highres: RandomHorizontalFlip (imagenet_pytorch.py:73-74).
  Documented deviation: the reference's RandomResizedCrop re-scales from
  larger source photos; the on-disk store holds target-size images, so the
  scale-jitter part has no source pixels to act on (and per-sample resize is
  XLA-hostile anyway) — the flip is the remaining stochastic transform.

Augmentation runs on device as one jitted map (pad + per-sample
dynamic_slice gather + flip), deterministic per (seed, epoch, step).
"""

from __future__ import annotations

import functools
import json
import os
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ddlbench_tpu.config import DatasetSpec
from ddlbench_tpu.data.native_loader import NativeDataLoader, generate_dataset

# dataset -> train-time augmentation policy (see module docstring)
_AUGMENT = {
    "cifar10": dict(pad=4, flip=True),
    "imagenet": dict(pad=0, flip=True),
    "highres": dict(pad=0, flip=True),
}


@functools.partial(jax.jit, static_argnums=(2,))
def _normalize(imgs_u8, labels, dtype_name: str):
    x = imgs_u8.astype(jnp.float32) / 255.0
    x = (x - 0.5) / 0.2887  # match the synthetic path's statistics
    return x.astype(jnp.dtype(dtype_name)), labels


@functools.partial(jax.jit, static_argnums=(2, 3))
def _augment_u8(imgs, key, pad: int, flip: bool):
    """Random pad-crop + horizontal flip on a uint8 batch [B, H, W, C]."""
    B, H, W, C = imgs.shape
    kc, kf = jax.random.split(key)
    if pad:
        padded = jnp.pad(imgs, ((0, 0), (pad, pad), (pad, pad), (0, 0)))
        offs = jax.random.randint(kc, (B, 2), 0, 2 * pad + 1)

        def crop(img, off):
            return jax.lax.dynamic_slice(img, (off[0], off[1], 0), (H, W, C))

        imgs = jax.vmap(crop)(padded, offs)
    if flip:
        m = jax.random.bernoulli(kf, 0.5, (B,))
        imgs = jnp.where(m[:, None, None, None], imgs[:, :, ::-1, :], imgs)
    return imgs


class OnDiskData:
    """Mirrors SyntheticData's interface over generated raw datasets."""

    # batch() advances the native loader's sequential stream (unlike the
    # random-access synthetic/translation sources) — probes must use a
    # throwaway instance (train/loop.py input-cost measurement)
    stateful_stream = True

    def __init__(self, data_dir: str, spec: DatasetSpec, batch_size: int,
                 seed: int = 1, dtype=jnp.float32,
                 train_count: int | None = None, test_count: int | None = None,
                 augment: bool = True, prefetch_depth: int = 2):
        self.spec = spec
        self.batch_size = batch_size
        self.dtype_name = str(jnp.dtype(dtype))
        self.seed = seed
        self.prefetch_depth = prefetch_depth
        self.augment_policy = _AUGMENT.get(spec.name) if augment else None
        self._loaders = {}
        if spec.kind in ("tokens", "seq2seq"):
            want_hwc = (spec.seq_len + 1, 4, 1)
        else:
            want_hwc = tuple(spec.image_size)
        for split, count in (("train", train_count), ("test", test_count)):
            # Real-data ingest first (VERDICT r1 #4): a recognized
            # ImageFolder/MNIST/CIFAR layout under data_dir is imported into
            # the native raw store on first use (data/imagefolder.py);
            # otherwise fall back to generating synthetic raw data.
            from ddlbench_tpu.data.imagefolder import resolve_split

            split_dir = resolve_split(data_dir, spec, split)
            if split_dir is None:
                split_dir = os.path.join(data_dir, spec.name, split)
                if not os.path.exists(os.path.join(split_dir, "meta.json")):
                    generate_dataset(data_dir, spec, split, count=count,
                                     seed=seed)
            meta_path = os.path.join(split_dir, "meta.json")
            with open(meta_path) as f:
                meta = json.load(f)
            got_hwc = (meta["h"], meta["w"], meta["c"])
            if got_hwc != want_hwc or meta.get("kind", "image") != spec.kind:
                raise ValueError(
                    f"dataset at {split_dir} was generated for "
                    f"kind={meta.get('kind', 'image')} shape={got_hwc}, but the "
                    f"spec wants kind={spec.kind} shape={want_hwc}; delete the "
                    f"directory or point --data-dir elsewhere"
                )
            # prefetch_depth sizes the loader's zero-copy buffer ring; the
            # actual lifetime invariant is batch()'s execution barrier
            # below, which fully consumes each batch before the next
            # next() call (native_loader.NativeDataLoader.next)
            self._loaders[split] = NativeDataLoader(
                split_dir, batch_size, seed=seed, shuffle=(split == "train"),
                prefetch_depth=prefetch_depth,
            )

    def steps_per_epoch(self, train: bool = True) -> int:
        return self._loaders["train" if train else "test"].steps_per_epoch

    def batch(self, epoch: int, step: int, train: bool = True) -> Tuple[jax.Array, jax.Array]:
        imgs, labels = self._loaders["train" if train else "test"].next()
        if self.spec.kind in ("tokens", "seq2seq"):
            # raw store holds (T+1) x 4 bytes per sample; view as int32 ids
            # and return the two length-T next-token shifts (matching
            # data/synthetic.py's convention); seq2seq masks source-internal
            # label positions
            flat = np.ascontiguousarray(imgs).reshape(imgs.shape[0], -1)
            ids = flat.view("<i4") % self.spec.num_classes
            ids = jnp.asarray(ids)
            labels = ids[:, 1:]
            if self.spec.kind == "seq2seq":
                from ddlbench_tpu.data.synthetic import mask_source_labels

                labels = mask_source_labels(labels, self.spec.src_len)
            return ids[:, :-1], labels
        if self.prefetch_depth == 0:
            # Synchronous mode (--no-prefetch): batch() runs ON the train
            # loop's critical path, so keep the pre-pipeline semantics —
            # copy out of the loader's ring and return lazy arrays (the
            # loop syncs only at log intervals). A per-batch execution
            # barrier here would tax the A/B baseline the async path never
            # pays inline.
            imgs, labels = imgs.copy(), labels.copy()
        imgs = jnp.asarray(imgs)
        labels = jnp.asarray(labels)
        if train and self.augment_policy:
            steps = self.steps_per_epoch(train=True)
            key = jax.random.fold_in(jax.random.key(self.seed),
                                     epoch * steps + step)
            imgs = _augment_u8(imgs, key, self.augment_policy["pad"],
                               self.augment_policy["flip"])
        x, y = _normalize(imgs, labels, self.dtype_name)
        if self.prefetch_depth > 0:
            # Ring-buffer lifetime guard (async mode, zero-copy ring): the
            # native loader recycles the host buffers behind imgs/labels
            # after prefetch_depth further batches, and jax may ZERO-COPY
            # alias an aligned host buffer (CPU backend) or still have its
            # upload in flight — so force the jitted augment/normalize
            # pipeline to EXECUTE before returning: jit outputs are fresh
            # device buffers (even for passthrough args of aliased inputs —
            # pinned by tests/test_prefetch.py), after which recycling the
            # ring cannot touch them. A device->host transfer, not
            # block_until_ready, because on the axon TPU tunnel the latter
            # can return early (tools/timing.py caveat). The wait sits on
            # the prefetch producer thread, off the loop's critical path.
            jax.device_get((x.ravel()[0:1], y.ravel()[0:1]))
        return x, y

    def close(self) -> None:
        for l in self._loaders.values():
            l.close()
