from ddlbench_tpu.data.synthetic import SyntheticData, make_synthetic

__all__ = ["SyntheticData", "make_synthetic"]
