"""Real-data accuracy anchor: sklearn's handwritten-digits dataset as MNIST IDX.

The reference's protocol is anchored on per-epoch validation accuracy on real
datasets (benchmark/mnist/mnist_pytorch.py:102-133, summary :225-226) — loss
decreasing on synthetic random-label batches proves nothing about BN
semantics, lr scaling, stashing staleness, or the hetero conveyor's batch
split (VERDICT r3 missing #1). This environment has zero egress and ships no
MNIST/CIFAR archives, so the one real image dataset available offline is
scikit-learn's bundled ``load_digits``: 1797 genuine handwritten digit
scans (8x8, the classic UCI optdigits test set). This module exports them in
the MNIST IDX container at the mnist spec's 28x28 (PIL bilinear upscale,
0..16 -> 0..255), with a deterministic stratified train/test split — after
which the framework's EXISTING real-data path (data/imagefolder.import_mnist_idx
-> native raw store -> OnDiskData) serves them to every engine unchanged.

A linear model reaches ~95% on digits; a LeNet-class CNN trained for a few
epochs should exceed 97% — the accuracy-parity gate tools/accparity.py builds
on (artifact perf_runs/accuracy_parity.json).
"""

from __future__ import annotations

import os
import struct
from typing import Tuple

import numpy as np

# deterministic stratified split: ~1500 train / ~297 test, every class
# represented in both splits in the same proportion
TEST_FRACTION = 1.0 / 6.0
_SEED = 20260731


def _upscale(images8: np.ndarray, hw: Tuple[int, int]) -> np.ndarray:
    """[N, 8, 8] float 0..16 -> [N, H, W] uint8 0..255 (PIL bilinear)."""
    from PIL import Image

    h, w = hw
    scaled = np.clip(images8 * (255.0 / 16.0), 0, 255).astype(np.uint8)
    out = np.empty((scaled.shape[0], h, w), np.uint8)
    for i, im in enumerate(scaled):
        out[i] = np.asarray(
            Image.fromarray(im, mode="L").resize((w, h), Image.BILINEAR))
    return out


def _write_idx_images(path: str, images: np.ndarray) -> None:
    n, h, w = images.shape
    with open(path, "wb") as f:
        f.write(struct.pack(">BBBB", 0, 0, 0x08, 3))
        f.write(struct.pack(">3I", n, h, w))
        f.write(np.ascontiguousarray(images, np.uint8).tobytes())


def _write_idx_labels(path: str, labels: np.ndarray) -> None:
    with open(path, "wb") as f:
        f.write(struct.pack(">BBBB", 0, 0, 0x08, 1))
        f.write(struct.pack(">I", labels.shape[0]))
        f.write(np.ascontiguousarray(labels, np.uint8).tobytes())


def split_indices(labels: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic stratified (train_idx, test_idx)."""
    rng = np.random.default_rng(_SEED)
    train, test = [], []
    for cls in np.unique(labels):
        idx = np.flatnonzero(labels == cls)
        rng.shuffle(idx)
        k = max(1, int(round(len(idx) * TEST_FRACTION)))
        test.append(idx[:k])
        train.append(idx[k:])
    train_idx = np.concatenate(train)
    test_idx = np.concatenate(test)
    rng.shuffle(train_idx)
    rng.shuffle(test_idx)
    return train_idx, test_idx


def export_digits_idx(data_dir: str, hw: Tuple[int, int] = (28, 28)) -> str:
    """Write train/t10k IDX pairs for the digits dataset under ``data_dir``.

    Returns ``data_dir``; a second call with the files present is a no-op
    (the export is deterministic). Point the benchmark at it with
    ``--data-dir data_dir -b mnist`` (non-synthetic): resolve_split imports
    the IDX files into the native raw store on first use.
    """
    names = ("train-images-idx3-ubyte", "train-labels-idx1-ubyte",
             "t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte")
    paths = [os.path.join(data_dir, n) for n in names]
    if all(os.path.exists(p) for p in paths):
        return data_dir
    from sklearn.datasets import load_digits

    ds = load_digits()
    images = _upscale(ds.images, hw)  # [1797, H, W]
    labels = ds.target.astype(np.uint8)
    train_idx, test_idx = split_indices(labels)
    os.makedirs(data_dir, exist_ok=True)
    _write_idx_images(paths[0], images[train_idx])
    _write_idx_labels(paths[1], labels[train_idx])
    _write_idx_images(paths[2], images[test_idx])
    _write_idx_labels(paths[3], labels[test_idx])
    return data_dir
