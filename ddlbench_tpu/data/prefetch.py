"""Asynchronous input pipeline: background device prefetch + stall accounting.

The reference hides input cost behind torch DataLoader worker processes and
prices residual input time into pipeline stage 0 (profiler main.py:388-407);
our loop was fully synchronous — every step paid ``data.batch()`` plus the
strategy's ``shard_batch`` (a blocking ``device_put``) on the critical path
before the device could start. :class:`Prefetcher` restores the overlap
TPU-natively: a producer thread runs BOTH the host-side batch production and
the H2D placement ``prefetch_depth`` steps ahead of consumption through a
bounded ring (a ``queue.Queue``), so step N's transfer overlaps step N-1's
compute. ``depth=0`` degrades to the old synchronous behavior through the
same interface (that is what ``--no-prefetch`` selects).

Determinism: the producer asks the data source for ``batch(epoch, step)`` in
strictly increasing step order — sources address batches by (epoch, step),
so thread timing can never reorder or resample anything, and a prefetched
run is bitwise-identical to a synchronous one (pinned by
tests/test_prefetch.py). Sequential streams (OnDiskData) are likewise safe:
one producer thread per epoch consumes the stream in order.

Epoch boundaries: each :meth:`Prefetcher.stream` owns one epoch and one
producer thread; the stream's iterator joins the thread when the epoch's
batches are exhausted (and ``close()`` tears it down early on exceptions),
so no batch of epoch E+1 can be produced — let alone consumed — during
epoch E.

Input-stall accounting: the consumer clocks every blocking wait on the ring
(``stall_s``/``stall_ms``). In synchronous mode the whole inline fetch
counts — the semantic is uniform: *time the training loop spent blocked
waiting for input*. The per-epoch figure is reported by
``MetricLogger.epoch_done`` and lands in bench.py's JSON next to
samples/sec, so throughput curves can distinguish input-bound from
compute-bound regimes.

Watchdog heartbeat: on streams with ``heartbeat`` enabled (the default for
eval streams), every produced and consumed batch kicks the (optional)
``HangWatchdog``, covering phases where slow input production is the
bottleneck. Heartbeat kicks prove HOST progress only — the armed
watchdog's device-hang deadline is still enforced by per-step ``float()``
syncs in both the train and eval loops (train/loop.py). Train streams
default to ``heartbeat=False`` so input-side kicks can never postpone that
per-step deadline by depth x batch-production-time.

Thread-safety contract: ``shard_batch`` runs on the producer thread (see
parallel/api.py). JAX dispatch and ``device_put`` are thread-safe; the
strategies keep no per-call mutable host state in ``shard_batch``.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, NamedTuple, Optional, Tuple

from ddlbench_tpu import faults
from ddlbench_tpu.telemetry import get_tracer
from ddlbench_tpu.train.watchdog import TrainingFailure

# Sentinel step index marking an exception delivery from the producer.
_ERROR = -1


class Fetched(NamedTuple):
    """One prepared step: the sharded batch-args tuple plus (optionally) the
    raw host-side (x, y) pair — kept only when a consumer (the activation
    logger) asked for it, so the ring does not pin extra buffers."""

    batch: Tuple[Any, ...]
    raw: Optional[Tuple[Any, Any]]


class EpochStream:
    """Iterator over one epoch's prepared batches (one producer thread).

    Iterate it (``for fetched in stream``) and call :meth:`close` in a
    ``finally`` — closing is idempotent and also happens automatically when
    the epoch is exhausted. ``stall_ms`` is valid at any point and final
    after exhaustion.
    """

    def __init__(self, data, shard_fn: Callable, epoch: int, steps: int,
                 train: bool, depth: int, watchdog=None,
                 keep_raw: bool = False, heartbeat: bool = True,
                 start_step: int = 0):
        if not heartbeat:
            watchdog = None
        self._data = data
        self._shard_fn = shard_fn
        self._epoch = epoch
        self._steps = steps
        self._train = train
        self._watchdog = watchdog
        self._keep_raw = keep_raw
        # mid-epoch resume (train/checkpoint.py step-granular checkpoints):
        # the stream serves steps [start_step, steps). Random-access sources
        # jump straight to start_step; sequential streams (OnDiskData) are
        # fast-forwarded — earlier batches are fetched and DISCARDED so the
        # underlying reader/shuffle state matches an uninterrupted epoch.
        self._start = start_step
        self._ff_pending = (start_step if getattr(data, "stateful_stream",
                                                  False) else 0)
        self._served = 0
        self.stall_s = 0.0
        self._queue: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        if depth > 0:
            self._queue = queue.Queue(maxsize=depth)
            self._thread = threading.Thread(
                target=self._produce, daemon=True,
                name=f"ddlbench-prefetch-e{epoch}-{'train' if train else 'eval'}",
            )
            self._thread.start()

    # ---- producer (background thread) ----

    def _fetch(self, step: int) -> Fetched:
        # Telemetry (telemetry/tracer.py): the producer's two phases —
        # host-side batch production and shard/device_put — become separate
        # spans on the producer thread's track, so an input-bound epoch
        # shows WHERE the producer spends its time. Disabled: one flag
        # check, no clock reads.
        tr = get_tracer()
        if not tr.enabled:
            bx, by = self._data.batch(self._epoch, step, train=self._train)
            batch = self._shard_fn(bx, by)
            return Fetched(batch, (bx, by) if self._keep_raw else None)
        args = {"epoch": self._epoch, "step": step, "train": self._train}
        t0 = time.perf_counter_ns()
        bx, by = self._data.batch(self._epoch, step, train=self._train)
        t1 = time.perf_counter_ns()
        batch = self._shard_fn(bx, by)
        t2 = time.perf_counter_ns()
        tr.complete("batch_produce", t0, t1, args)
        tr.complete("shard_device_put", t1, t2, args)
        return Fetched(batch, (bx, by) if self._keep_raw else None)

    def _put(self, item) -> bool:
        """Bounded put that polls the stop flag — backpressure without ever
        deadlocking against a consumer that already gave up."""
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _produce(self) -> None:
        try:
            if self._ff_pending:
                self._fast_forward()
            for step in range(self._start, self._steps):
                if self._stop.is_set():
                    return
                # fault hook: `prefetch-die` kills this producer thread here
                faults.prefetch_producer(self._epoch, step)
                item = self._fetch(step)
                if not self._put((step, item)):
                    return
                if self._watchdog is not None:
                    self._watchdog.kick()
        except BaseException as e:  # delivered to the consumer, then re-raised there
            self._put((_ERROR, e))

    def _fast_forward(self) -> None:
        """Advance a sequential source past the resumed-over steps."""
        tr = get_tracer()
        t0 = time.perf_counter_ns()
        for step in range(self._ff_pending):
            if self._stop.is_set():
                return
            self._data.batch(self._epoch, step, train=self._train)
        self._ff_pending = 0
        if tr.enabled:
            tr.complete("resume_fastforward", t0, time.perf_counter_ns(),
                        {"epoch": self._epoch, "steps": self._start})

    # ---- consumer ----

    def __iter__(self) -> "EpochStream":
        return self

    def __next__(self) -> Fetched:
        if self._start + self._served >= self._steps:
            self.close()
            raise StopIteration
        tr = get_tracer()
        if self._queue is None:  # synchronous (depth 0): inline fetch is the stall
            t0 = time.perf_counter_ns()
            if self._ff_pending:
                self._fast_forward()
            item = self._fetch(self._start + self._served)
            t1 = time.perf_counter_ns()
            self.stall_s += (t1 - t0) / 1e9
        else:
            t0 = time.perf_counter_ns()
            step, item = self._get_or_fail()
            t1 = time.perf_counter_ns()
            self.stall_s += (t1 - t0) / 1e9
            if step == _ERROR:
                self.close()
                # TrainingFailure with the producer's exception CHAINED, so
                # the consumer-side abort carries the original traceback
                # (a dead producer must not surface only as a watchdog
                # timeout or an anonymous hang)
                raise TrainingFailure(
                    f"prefetch producer failed in epoch {self._epoch}: "
                    f"{item}") from item
        if tr.enabled:
            # the consumer-side blocking wait on the ring (or the inline
            # fetch in synchronous mode) — today's stall scalar, visible
            # as spans on the consuming thread's timeline
            tr.complete("ring_wait", t0, t1,
                        {"epoch": self._epoch,
                         "step": self._start + self._served,
                         "train": self._train})
        self._served += 1
        if self._watchdog is not None:
            self._watchdog.kick()
        return item

    def _get_or_fail(self):
        """Ring get that notices a dead producer instead of blocking forever.

        The producer delivers its own exceptions through the ring; this
        covers the remaining gap — a producer that died WITHOUT managing a
        delivery (e.g. killed hard, or the interpreter tore the thread
        down) — by polling thread liveness while waiting."""
        while True:
            try:
                return self._queue.get(timeout=0.2)
            except queue.Empty:
                t = self._thread
                if t is not None and not t.is_alive():
                    try:  # a final drain beats the race where the producer
                        return self._queue.get_nowait()  # put then exited
                    except queue.Empty:
                        self.close()
                        raise TrainingFailure(
                            f"prefetch producer for epoch {self._epoch} "
                            f"died without delivering a batch") from None

    @property
    def stall_ms(self) -> float:
        return self.stall_s * 1e3

    def close(self, grace_s: float = 2.0) -> None:
        """Stop the producer and join its thread. Idempotent; safe mid-epoch
        (e.g. from a ``finally`` after a training exception) — the producer's
        polling put means it can never stay blocked on a full ring. If the
        producer is wedged INSIDE a fetch (e.g. a hung device_put on a dead
        TPU tunnel), the join is abandoned after ``grace_s`` so a
        propagating training exception surfaces instead of hanging the
        teardown — the thread is a daemon and cannot outlive the process."""
        self._stop.set()
        if self._thread is not None:
            deadline = time.monotonic() + grace_s
            while self._thread.is_alive():
                try:  # drain so a blocked put wakes immediately
                    self._queue.get_nowait()
                except queue.Empty:
                    pass
                self._thread.join(timeout=0.05)
                if time.monotonic() > deadline and self._thread.is_alive():
                    import sys

                    print(f"prefetch: producer thread {self._thread.name} "
                          f"did not exit within {grace_s:.0f}s (stuck in a "
                          f"fetch?); abandoning join", file=sys.stderr,
                          flush=True)
                    break
            self._thread = None

    def __enter__(self) -> "EpochStream":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class Prefetcher:
    """Factory for per-epoch :class:`EpochStream`s over one (data, shard_fn).

    ``depth`` is the ring capacity (``RunConfig.prefetch_depth``); 0 selects
    the synchronous fallback. One Prefetcher serves both train and eval
    epochs; the loop reads each stream's ``stall_ms`` after the epoch.
    """

    def __init__(self, data, shard_fn: Callable, depth: int = 2,
                 watchdog=None):
        if depth < 0:
            raise ValueError("prefetch depth must be >= 0")
        self.data = data
        self.shard_fn = shard_fn
        self.depth = depth
        self.watchdog = watchdog

    def stream(self, epoch: int, train: bool = True, keep_raw: bool = False,
               heartbeat: Optional[bool] = None,
               start_step: int = 0) -> EpochStream:
        """``heartbeat`` defaults to eval-only (``not train``): an armed
        watchdog's train-path deadline stays per-step (driven by the loop's
        own float() syncs), while eval — which never syncs mid-epoch —
        takes its liveness from the pipeline. ``start_step`` serves only
        steps [start_step, steps) — the mid-epoch resume entry point."""
        if heartbeat is None:
            heartbeat = not train
        steps = self.data.steps_per_epoch(train=train)
        if not 0 <= start_step <= steps:
            raise ValueError(
                f"start_step {start_step} outside epoch of {steps} steps")
        return EpochStream(self.data, self.shard_fn, epoch, steps, train,
                          self.depth, watchdog=self.watchdog,
                          keep_raw=keep_raw, heartbeat=heartbeat,
                          start_step=start_step)
