"""Byte-pair-encoding subword tokenizer for the translation workload.

The reference tokenizes WMT with subword-nmt BPE: a vocab file of learned
merges, ``@@ ``-style continuation markers, and BOS/EOS/PAD/UNK specials
(pipedream-fork/profiler/translation/seq2seq/data/tokenizer.py). This module
implements the same capability self-contained: train merges on a corpus,
encode/decode text, save/load the vocab — no external models or downloads.

Implementation: classic BPE over whitespace-split words. Words are symbol
sequences ending in the end-of-word marker; training repeatedly merges the
most frequent adjacent symbol pair; encoding applies the learned merges in
rank order (lowest rank first), with a per-word cache.
"""

from __future__ import annotations

import collections
import json
from typing import Dict, Iterable, List, Optional, Tuple

EOW = "</w>"

PAD, UNK, BOS, EOS = 0, 1, 2, 3
SPECIALS = ["<pad>", "<unk>", "<s>", "</s>"]


class BpeTokenizer:
    def __init__(self, merges: List[Tuple[str, str]], vocab: List[str]):
        self.merges = list(merges)
        self.ranks = {pair: i for i, pair in enumerate(merges)}
        self.vocab = list(vocab)
        self.token_to_id = {t: i for i, t in enumerate(self.vocab)}
        self._cache: Dict[str, List[str]] = {}

    # -- training ----------------------------------------------------------

    @classmethod
    def train(cls, lines: Iterable[str], num_merges: int = 512,
              min_pair_freq: int = 2) -> "BpeTokenizer":
        """Learn ``num_merges`` merges from an iterable of text lines."""
        word_freq = collections.Counter()
        for line in lines:
            word_freq.update(line.split())
        # each word as a tuple of symbols; char coverage forms the base vocab
        words = {w: tuple(w) + (EOW,) for w in word_freq}
        chars = sorted({c for w in words.values() for c in w})
        merges: List[Tuple[str, str]] = []
        for _ in range(num_merges):
            pair_freq = collections.Counter()
            for w, sym in words.items():
                f = word_freq[w]
                for a, b in zip(sym, sym[1:]):
                    pair_freq[(a, b)] += f
            if not pair_freq:
                break
            (a, b), f = pair_freq.most_common(1)[0]
            if f < min_pair_freq:
                break
            merges.append((a, b))
            merged = a + b
            new_words = {}
            for w, sym in words.items():
                out: List[str] = []
                i = 0
                while i < len(sym):
                    if i + 1 < len(sym) and sym[i] == a and sym[i + 1] == b:
                        out.append(merged)
                        i += 2
                    else:
                        out.append(sym[i])
                        i += 1
                new_words[w] = tuple(out)
            words = new_words
        # vocab = base chars + EVERY merge product (not just final-state
        # symbols): an unseen word can stop merging at an intermediate
        # product (e.g. 'th' when training text always reached 'the'), which
        # must still encode — subword-nmt keeps all merge outputs too
        symbols = ({s for w in words.values() for s in w} | set(chars)
                   | {a + b for a, b in merges})
        vocab = SPECIALS + sorted(symbols)
        return cls(merges, vocab)

    # -- encoding ----------------------------------------------------------

    def _bpe_word(self, word: str) -> List[str]:
        cached = self._cache.get(word)
        if cached is not None:
            return cached
        sym: List[str] = list(word) + [EOW]
        while len(sym) > 1:
            best = None
            best_rank = None
            for i, pair in enumerate(zip(sym, sym[1:])):
                r = self.ranks.get(pair)
                if r is not None and (best_rank is None or r < best_rank):
                    best, best_rank = i, r
            if best is None:
                break
            sym[best:best + 2] = [sym[best] + sym[best + 1]]
        self._cache[word] = sym
        return sym

    def encode(self, text: str, add_bos: bool = False,
               add_eos: bool = True) -> List[int]:
        ids: List[int] = [BOS] if add_bos else []
        for word in text.split():
            for tok in self._bpe_word(word):
                ids.append(self.token_to_id.get(tok, UNK))
        if add_eos:
            ids.append(EOS)
        return ids

    def decode(self, ids: Iterable[int]) -> str:
        out: List[str] = []
        for i in ids:
            if i in (PAD, BOS, EOS):
                continue
            tok = self.vocab[i] if 0 <= i < len(self.vocab) else SPECIALS[UNK]
            out.append(tok)
        text = "".join(t for t in out)
        return text.replace(EOW, " ").strip()

    @property
    def vocab_size(self) -> int:
        return len(self.vocab)

    # -- persistence -------------------------------------------------------

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump({"merges": self.merges, "vocab": self.vocab}, f)

    @classmethod
    def load(cls, path: str) -> "BpeTokenizer":
        with open(path) as f:
            d = json.load(f)
        return cls([tuple(m) for m in d["merges"]], d["vocab"])
