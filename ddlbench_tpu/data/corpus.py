"""Shared machinery for real-corpus data sources (translation + plain text).

One home for the two things every corpus-backed source needs, so the copies
cannot drift (ADVICE r3): the BPE tokenizer bootstrap (load the cached vocab
next to the corpus, else train on it and cache) and the fixed-shape
row-stream batcher (deterministic per-epoch shuffle, wrap-around tail,
steps-per-epoch override).
"""

from __future__ import annotations

import os
from typing import Callable, Dict, Iterable, Iterator, Optional

import numpy as np

from ddlbench_tpu.data.bpe import BpeTokenizer


def bootstrap_tokenizer(data_dir: str, lines: Callable[[], Iterable[str]],
                        vocab_budget: int, num_merges: int,
                        tokenizer: Optional[BpeTokenizer]) -> BpeTokenizer:
    """Load ``bpe_vocab.json`` next to the corpus, else train on ``lines()``
    and cache it. Enforces the dataset spec's vocab budget."""
    vocab_path = os.path.join(data_dir, "bpe_vocab.json")
    if tokenizer is None:
        if os.path.exists(vocab_path):
            tokenizer = BpeTokenizer.load(vocab_path)
        else:
            tokenizer = BpeTokenizer.train(lines(), num_merges=num_merges)
            try:
                tokenizer.save(vocab_path)
            except OSError:
                pass
    if tokenizer.vocab_size > vocab_budget:
        raise ValueError(
            f"tokenizer vocab {tokenizer.vocab_size} exceeds the spec's "
            f"{vocab_budget}; lower num_merges")
    return tokenizer


class RowStreamData:
    """Fixed-shape [N, W] row matrices per split, served as shuffled batches.

    Subclasses fill ``self._rows[split]`` (tiled up to one batch if tiny)
    and implement ``batch`` by post-processing ``take_rows``. The epoch
    permutation is seeded, cached only for the current epoch, and the tail
    wraps so every batch has full shape (one XLA compile).
    """

    def __init__(self, batch_size: int, seed: int, salt: int,
                 steps_per_epoch: Optional[int]):
        self.batch_size = batch_size
        self.seed = seed
        self._salt = salt
        self._steps_override = steps_per_epoch
        self._perm_cache: dict = {}
        self._rows: Dict[str, np.ndarray] = {}

    def _store_rows(self, split: str, rows: np.ndarray) -> None:
        if len(rows) < self.batch_size:
            rows = np.tile(rows, (-(-self.batch_size // len(rows)),)
                           + (1,) * (rows.ndim - 1))
        self._rows[split] = rows

    def steps_per_epoch(self, train: bool = True) -> int:
        n = max(1, len(self._rows["train" if train else "test"])
                // self.batch_size)
        if self._steps_override:
            n = min(n, self._steps_override)
        return n

    def _order(self, epoch: int, train: bool) -> np.ndarray:
        if not train:
            return np.arange(len(self._rows["test"]))
        order = self._perm_cache.get(epoch)
        if order is None:
            order = np.random.default_rng(
                (self.seed, epoch, self._salt)).permutation(
                    len(self._rows["train"]))
            self._perm_cache = {epoch: order}  # keep only the current epoch
        return order

    def take_rows(self, epoch: int, step: int, train: bool) -> np.ndarray:
        split = "train" if train else "test"
        rows = self._rows[split]
        n = len(rows)
        order = self._order(epoch, train)
        idx = order[(step * self.batch_size) % n:][:self.batch_size]
        if len(idx) < self.batch_size:  # wrap the tail
            idx = np.concatenate([idx, order[:self.batch_size - len(idx)]])
        return rows[idx]

    def epoch_iter(self, epoch: int, train: bool = True) -> Iterator:
        for step in range(self.steps_per_epoch(train)):
            yield self.batch(epoch, step, train)

    def close(self) -> None:
        pass
