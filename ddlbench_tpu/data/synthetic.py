"""Synthetic dataset factory — device-side, PRNG-generated.

The reference writes random JPEGs to disk in ImageFolder layout with a
multiprocess pool and re-reads them through torchvision
(benchmark/generate_synthetic_data.py:21-107); that round-trip exists only
because torch DataLoaders want files. On TPU the idiomatic equivalent generates
batches directly on device from a JAX PRNG: zero host I/O, deterministic per
(seed, epoch, step), and shape-compatible with the same four dataset blueprints
(mnist 60k 28x28x1, cifar10 50k 32x32x3, imagenet 1.28M 224x224x3/1000cls,
highres 50k 512x512x3/1000cls).

An on-disk loader for *real* data is planned (gated on torchvision); synthetic
is the benchmark default, as in the reference (run/run/run.sh:9).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Iterator, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ddlbench_tpu.config import DatasetSpec


# Channel statistics used only to make synthetic pixels roughly unit-normal,
# mirroring the normalization transforms in the reference drivers
# (benchmark/mnist/mnist_pytorch.py:172-216).
@dataclasses.dataclass(frozen=True)
class SyntheticData:
    """Iterable synthetic dataset bound to one DatasetSpec.

    Batches are generated inside jit directly on the default device; ``epoch``
    and ``step`` are folded into the key so every batch is distinct but
    reproducible.
    """

    spec: DatasetSpec
    batch_size: int  # global batch produced per step (callers shard it)
    seed: int = 1
    dtype: jnp.dtype = jnp.float32
    train_size_override: int | None = None
    test_size_override: int | None = None

    @property
    def train_size(self) -> int:
        return self.train_size_override or self.spec.train_size

    @property
    def test_size(self) -> int:
        return self.test_size_override or self.spec.test_size

    def steps_per_epoch(self, train: bool = True) -> int:
        n = self.train_size if train else self.test_size
        return max(1, n // self.batch_size)

    def batch(self, epoch: int, step: int, train: bool = True) -> Tuple[jax.Array, jax.Array]:
        return _gen_batch(
            self.seed + (0 if train else 1_000_003),
            epoch,
            step,
            self.batch_size,
            self.spec.image_size,
            self.spec.num_classes,
            self.dtype,
            self.spec.kind,
            self.spec.src_len,
        )

    def epoch_iter(self, epoch: int, train: bool = True) -> Iterator[Tuple[jax.Array, jax.Array]]:
        for step in range(self.steps_per_epoch(train)):
            yield self.batch(epoch, step, train)


def mask_source_labels(labels: jax.Array, src_len: int) -> jax.Array:
    """Mask (-1) the source-internal label positions of a seq2seq stream.

    Shared by the synthetic and on-disk data paths so the boundary convention
    lives in exactly one place: position src_len-1 predicts the first target
    token, so positions < src_len-1 are masked and loss covers exactly the
    target segment (GNMT objective analog).
    """
    pos = jnp.arange(labels.shape[-1])
    return jnp.where(pos >= src_len - 1, labels, -1)


@functools.partial(jax.jit, static_argnums=(1, 2))
def _synthetic_images(key: jax.Array, shape: Tuple[int, ...], dtype) -> jax.Array:
    # Uniform pixels in [0,1) then normalized — matches the statistics of the
    # reference's random-uint8 JPEGs after its Normalize transform.
    x = jax.random.uniform(key, shape, dtype=jnp.float32)
    x = (x - 0.5) / 0.2887  # std of U[0,1)
    return x.astype(dtype)


def _gen_batch(seed, epoch, step, batch, image_size, num_classes, dtype,
               kind="image", src_len=None):
    key = jax.random.fold_in(jax.random.fold_in(jax.random.key(seed), epoch), step)
    kx, ky = jax.random.split(key)
    if kind == "tokens":
        # Next-token LM setup: sample T+1 tokens; inputs/labels are the two
        # length-T shifts.
        T = image_size[0]
        seq = jax.random.randint(kx, (batch, T + 1), 0, num_classes, jnp.int32)
        return seq[:, :-1], seq[:, 1:]
    if kind == "seq2seq":
        # Synthetic translation stream: [source | target] tokens; labels are
        # the next-token shift with source positions masked (see
        # mask_source_labels).
        T = image_size[0]
        seq = jax.random.randint(kx, (batch, T + 1), 0, num_classes, jnp.int32)
        return seq[:, :-1], mask_source_labels(seq[:, 1:], src_len)
    x = _synthetic_images(kx, (batch, *image_size), dtype)
    y = jax.random.randint(ky, (batch,), 0, num_classes, dtype=jnp.int32)
    return x, y


def make_synthetic(spec: DatasetSpec, batch_size: int, seed: int = 1,
                   dtype=jnp.float32, steps_per_epoch: int | None = None) -> SyntheticData:
    """Build a SyntheticData; ``steps_per_epoch`` overrides dataset-size-derived
    step counts (useful for smoke tests and the 3-epoch benchmark protocol on
    imagenet-scale specs)."""
    train_override = steps_per_epoch * batch_size if steps_per_epoch else None
    test_override = max(batch_size, (steps_per_epoch or 0) * batch_size // 5) if steps_per_epoch else None
    return SyntheticData(
        spec=spec,
        batch_size=batch_size,
        seed=seed,
        dtype=dtype,
        train_size_override=train_override,
        test_size_override=test_override,
    )
