"""Plain-text LM ingest: text file(s) -> BPE -> packed causal-LM windows.

Closes the tokens-kind real-data gap (VERDICT r2 #5): synthtext/longctx with
``--data-dir`` previously reinterpreted raw random bytes as token ids
(data/ondisk.py); now a directory holding ``train.txt`` (+ optional
``test.txt``/``val.txt``) is tokenized with the self-contained BPE
(data/bpe.py — trained on the corpus itself on first use and cached next to
it), document-packed into one id stream with EOS separators, and served as
fixed-shape [B, T+1] windows with the synthetic path's (inputs, labels) =
(row[:-1], row[1:]) convention. Reference analog: the lazily loaded corpus
machinery of GNMT (pipedream-fork/runtime/translation/seq2seq/data/
dataset.py:1-60), redesigned as packed fixed shapes for XLA (one compile,
no ragged batches).
"""

from __future__ import annotations

import os
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ddlbench_tpu.config import DatasetSpec
from ddlbench_tpu.data.bpe import BpeTokenizer
from ddlbench_tpu.data.corpus import RowStreamData, bootstrap_tokenizer

_SPLIT_FILES = {"train": ("train",), "test": ("test", "val", "valid")}


def find_text_corpus(data_dir: str, split: str) -> Optional[str]:
    """Path of the split's text file under data_dir, or None."""
    for base in _SPLIT_FILES[split]:
        path = os.path.join(data_dir, f"{base}.txt")
        if os.path.exists(path):
            return path
    return None


class TextCorpusData(RowStreamData):
    """SyntheticData-interface batches from a plain text corpus.

    Windows are contiguous [T+1] slices of the EOS-joined token stream
    (document packing — no padding, every label position valid), shuffled
    per epoch with a seeded permutation.
    """

    def __init__(self, data_dir: str, spec: DatasetSpec, batch_size: int,
                 seed: int = 1, num_merges: int = 512,
                 tokenizer: Optional[BpeTokenizer] = None,
                 steps_per_epoch: Optional[int] = None):
        assert spec.kind == "tokens", spec
        super().__init__(batch_size, seed, salt=2,
                         steps_per_epoch=steps_per_epoch)
        self.spec = spec
        T = spec.image_size[0]
        train_path = find_text_corpus(data_dir, "train")
        if train_path is None:
            raise FileNotFoundError(
                f"no text corpus (train.txt) under {data_dir}")
        test_path = find_text_corpus(data_dir, "test")

        def train_lines():
            with open(train_path) as f:
                return list(f)

        self.tokenizer = bootstrap_tokenizer(
            data_dir, train_lines, spec.num_classes, num_merges, tokenizer)

        self._store_rows("train", self._windows_of(train_path, T))
        if test_path is None:
            self._rows["test"] = self._rows["train"]  # no re-tokenize
        else:
            self._store_rows("test", self._windows_of(test_path, T))
        self.num_tokens = int(self._rows["train"].size)

    def _windows_of(self, path: str, T: int) -> np.ndarray:
        stream = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    stream.extend(self.tokenizer.encode(line, add_eos=True))
        if not stream:
            raise ValueError(f"text corpus {path} is empty")
        W = T + 1
        if len(stream) < W:
            reps = -(-W // len(stream))
            stream = stream * (reps + 1)
        n = len(stream) // W
        return np.asarray(stream[:n * W], np.int32).reshape(n, W)

    def batch(self, epoch: int, step: int, train: bool = True):
        ids = jnp.asarray(self.take_rows(epoch, step, train))
        return ids[:, :-1], ids[:, 1:]
