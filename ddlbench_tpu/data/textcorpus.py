"""Plain-text LM ingest: text file(s) -> BPE -> packed causal-LM windows.

Closes the tokens-kind real-data gap (VERDICT r2 #5): synthtext/longctx with
``--data-dir`` previously reinterpreted raw random bytes as token ids
(data/ondisk.py); now a directory holding ``train.txt`` (+ optional
``test.txt``/``val.txt``) is tokenized with the self-contained BPE
(data/bpe.py — trained on the corpus itself on first use and cached next to
it), document-packed into one id stream with EOS separators, and served as
fixed-shape [B, T+1] windows with the synthetic path's (inputs, labels) =
(row[:-1], row[1:]) convention. Reference analog: the lazily loaded corpus
machinery of GNMT (pipedream-fork/runtime/translation/seq2seq/data/
dataset.py:1-60), redesigned as packed fixed shapes for XLA (one compile,
no ragged batches).
"""

from __future__ import annotations

import os
from typing import Iterator, Optional

import jax.numpy as jnp
import numpy as np

from ddlbench_tpu.config import DatasetSpec
from ddlbench_tpu.data.bpe import BpeTokenizer

_SPLIT_FILES = {"train": ("train",), "test": ("test", "val", "valid")}


def find_text_corpus(data_dir: str, split: str) -> Optional[str]:
    """Path of the split's text file under data_dir, or None."""
    for base in _SPLIT_FILES[split]:
        path = os.path.join(data_dir, f"{base}.txt")
        if os.path.exists(path):
            return path
    return None


class TextCorpusData:
    """SyntheticData-interface batches from a plain text corpus.

    Windows are contiguous [T+1] slices of the EOS-joined token stream
    (document packing — no padding, every label position valid), shuffled
    per epoch with a seeded permutation.
    """

    def __init__(self, data_dir: str, spec: DatasetSpec, batch_size: int,
                 seed: int = 1, num_merges: int = 512,
                 tokenizer: Optional[BpeTokenizer] = None,
                 steps_per_epoch: Optional[int] = None):
        assert spec.kind == "tokens", spec
        self.spec = spec
        self.batch_size = batch_size
        self.seed = seed
        self._steps_override = steps_per_epoch
        self._perm_cache: dict = {}
        T = spec.image_size[0]
        train_path = find_text_corpus(data_dir, "train")
        if train_path is None:
            raise FileNotFoundError(
                f"no text corpus (train.txt) under {data_dir}")
        test_path = find_text_corpus(data_dir, "test") or train_path

        vocab_path = os.path.join(data_dir, "bpe_vocab.json")
        if tokenizer is not None:
            self.tokenizer = tokenizer
        elif os.path.exists(vocab_path):
            self.tokenizer = BpeTokenizer.load(vocab_path)
        else:
            with open(train_path) as f:
                self.tokenizer = BpeTokenizer.train(list(f),
                                                    num_merges=num_merges)
            try:
                self.tokenizer.save(vocab_path)
            except OSError:
                pass
        if self.tokenizer.vocab_size > spec.num_classes:
            raise ValueError(
                f"tokenizer vocab {self.tokenizer.vocab_size} exceeds the "
                f"spec's {spec.num_classes}; lower num_merges")

        self._windows = {}
        for split, path in (("train", train_path), ("test", test_path)):
            stream = []
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        stream.extend(self.tokenizer.encode(line,
                                                            add_eos=True))
            W = T + 1
            if len(stream) < W:
                reps = -(-W // max(1, len(stream)))
                stream = stream * (reps + 1)
            n = len(stream) // W
            rows = np.asarray(stream[:n * W], np.int32).reshape(n, W)
            if n < batch_size:  # tile tiny corpora up to one batch
                rows = np.tile(rows, (-(-batch_size // n), 1))
            self._windows[split] = rows
        self.num_tokens = int(self._windows["train"].size)

    def steps_per_epoch(self, train: bool = True) -> int:
        n = max(1, len(self._windows["train" if train else "test"])
                // self.batch_size)
        if self._steps_override:
            n = min(n, self._steps_override)
        return n

    def _order(self, epoch: int, train: bool) -> np.ndarray:
        if not train:
            return np.arange(len(self._windows["test"]))
        order = self._perm_cache.get(epoch)
        if order is None:
            order = np.random.default_rng(
                (self.seed, epoch, 2)).permutation(len(self._windows["train"]))
            self._perm_cache = {epoch: order}  # keep only the current epoch
        return order

    def batch(self, epoch: int, step: int, train: bool = True):
        split = "train" if train else "test"
        rows = self._windows[split]
        n = len(rows)
        order = self._order(epoch, train)
        idx = order[(step * self.batch_size) % n:][:self.batch_size]
        if len(idx) < self.batch_size:  # wrap the tail
            idx = np.concatenate([idx, order[:self.batch_size - len(idx)]])
        ids = jnp.asarray(rows[idx])
        return ids[:, :-1], ids[:, 1:]

    def epoch_iter(self, epoch: int, train: bool = True) -> Iterator:
        for step in range(self.steps_per_epoch(train)):
            yield self.batch(epoch, step, train)

    def close(self) -> None:
        pass
