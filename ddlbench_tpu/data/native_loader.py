"""ctypes binding for the native data pipeline (native/dataloader.cpp):
on-disk raw-tensor dataset factory + mmap-backed prefetching batch loader.

This is the real-data path behind the CLI's ``-s`` flag (the reference stages
random JPEGs and torch-DataLoader-reads them back,
benchmark/generate_synthetic_data.py); the default benchmark path remains
device-side PRNG synthesis (data/synthetic.py). Datasets are stored as
``images.bin`` (N*H*W*C uint8) + ``labels.bin`` (N int32) + ``meta.json``.
"""

from __future__ import annotations

import ctypes
import json
import os
import subprocess
from typing import Iterator, Optional, Tuple

import numpy as np

from ddlbench_tpu.config import DatasetSpec

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libdataloader.so")

_lib = None
_lib_failed = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    try:
        if not os.path.exists(_LIB_PATH):
            subprocess.run(["make", "-C", _NATIVE_DIR, "-s"], check=True,
                           capture_output=True, timeout=120)
        lib = ctypes.CDLL(_LIB_PATH)
        lib.dataset_generate.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int64, ctypes.c_uint64, ctypes.c_int,
        ]
        lib.dataset_generate.restype = ctypes.c_int
        lib.loader_open.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int64, ctypes.c_int, ctypes.c_uint64,
            ctypes.c_int, ctypes.c_int,
        ]
        lib.loader_open.restype = ctypes.c_void_p
        lib.loader_next.argtypes = [
            ctypes.c_void_p,
            np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        ]
        lib.loader_next.restype = ctypes.c_int
        lib.loader_destroy.argtypes = [ctypes.c_void_p]
        lib.loader_destroy.restype = None
        _lib = lib
    except Exception:
        _lib_failed = True
    return _lib


def available() -> bool:
    return _load() is not None


def generate_dataset(data_dir: str, spec: DatasetSpec, split: str = "train",
                     count: Optional[int] = None, seed: int = 1,
                     threads: int = 4) -> str:
    """Write a raw synthetic dataset for one split; returns its directory.

    generate_synthetic_data.py parity: same blueprint sizes by default, raw
    uint8 tensors instead of JPEGs (no decode cost on a benchmark that never
    looks at the pixels).
    """
    lib = _load()
    if lib is None:
        raise RuntimeError("native dataloader unavailable (no toolchain?)")
    count = count or (spec.train_size if split == "train" else spec.test_size)
    if spec.kind in ("tokens", "seq2seq"):
        # token sequences ride the same raw-uint8 store: one sample is T+1
        # tokens x 4 little-endian bytes (viewed as int32 % vocab on read;
        # the +1 gives the next-token label shift, data/synthetic.py:90-95;
        # seq2seq's source-position masking happens at read time in ondisk.py)
        h, w, c = spec.seq_len + 1, 4, 1
    else:
        h, w, c = spec.image_size
    out = os.path.join(data_dir, spec.name, split)
    os.makedirs(out, exist_ok=True)
    rc = lib.dataset_generate(out.encode(), h, w, c, spec.num_classes,
                              count, seed, threads)
    if rc != 0:
        raise RuntimeError(f"dataset_generate failed rc={rc}")
    with open(os.path.join(out, "meta.json"), "w") as f:
        json.dump({"h": h, "w": w, "c": c, "classes": spec.num_classes,
                   "count": count, "seed": seed, "kind": spec.kind}, f)
    return out


class NativeDataLoader:
    """Prefetching batch iterator over a generated dataset directory."""

    def __init__(self, dataset_dir: str, batch_size: int, seed: int = 1,
                 shuffle: bool = True, ring_depth: int = 4,
                 prefetch_depth: int = 2):
        lib = _load()
        if lib is None:
            raise RuntimeError("native dataloader unavailable")
        with open(os.path.join(dataset_dir, "meta.json")) as f:
            meta = json.load(f)
        self.meta = meta
        self.batch_size = batch_size
        self._lib = lib
        self._handle = lib.loader_open(
            dataset_dir.encode(), meta["h"], meta["w"], meta["c"],
            meta["classes"], meta["count"], batch_size, seed,
            int(shuffle), ring_depth,
        )
        if not self._handle:
            raise RuntimeError(f"loader_open failed for {dataset_dir}")
        # Rotating ring of preallocated buffer pairs: next() hands out a
        # pair WITHOUT copying (the old implementation memcpy'd both
        # buffers per call). THE SAFETY INVARIANT IS THE CONSUMER'S
        # BARRIER, NOT THE RING SIZE: data/ondisk.py fully consumes every
        # batch before requesting the next one — synchronous numpy
        # arithmetic on the token path, a device_get execution barrier on
        # the jitted normalize/augment image path — so even a 2-buffer
        # ring would be safe, and no ring size alone would be (jax can
        # zero-copy alias an aligned host buffer, leaving nothing a
        # lifetime window could protect). The prefetch_depth+1 sizing just
        # keeps a grace window for that contract's documented lifetime.
        nbuf = max(2, prefetch_depth + 1)
        self._bufs = [
            (np.empty((batch_size, meta["h"], meta["w"], meta["c"]), np.uint8),
             np.empty((batch_size,), np.int32))
            for _ in range(nbuf)
        ]
        self._buf_i = 0

    @property
    def steps_per_epoch(self) -> int:
        return self.meta["count"] // self.batch_size

    def next(self) -> Tuple[np.ndarray, np.ndarray]:
        """Return the next (images, labels) batch.

        The arrays are views into a rotating ring of ``max(2,
        prefetch_depth + 1)`` preallocated pairs: a returned batch stays
        valid for ``ring_size - 1`` further ``next()`` calls and is
        overwritten by the ``ring_size``-th.
        FULLY consume (or copy) a batch before calling ``next()`` again —
        a jax array built from these views may zero-copy alias them, so
        deferring consumption to any later point is unsafe regardless of
        the ring size (see data/ondisk.py's execution barrier)."""
        img_buf, lbl_buf = self._bufs[self._buf_i]
        self._buf_i = (self._buf_i + 1) % len(self._bufs)
        rc = self._lib.loader_next(self._handle, img_buf.reshape(-1),
                                   lbl_buf)
        if rc != 0:
            raise RuntimeError(f"loader_next rc={rc}")
        return img_buf, lbl_buf

    def close(self) -> None:
        if getattr(self, "_handle", None):
            self._lib.loader_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
