"""Functional flat-layer model representation.

The reference maintains THREE parallel model families per architecture because
each engine has different structural needs: idiomatic nn.Modules for
pytorch/horovod, flattened nn.Sequential with @skippable stash/pop residuals
for torchgpipe, and tracer-friendly module-only graphs for PipeDream
(SURVEY.md §2 B5-B7; gpipemodels/resnet/block.py:31-51 for the skip API).

Here a model is ONE flat ``list[Layer]``; residual blocks are single layers
(closures over their sub-params), so there is no stash/pop machinery, partitioning
a pipeline is slicing the list, and the same definition serves every strategy.

Each ``Layer`` is a pair of pure functions:

* ``init(key, in_shape) -> (params, state, out_shape)`` — shapes are per-example
  (no batch dim), NHWC.
* ``apply(params, state, x, train) -> (y, new_state)`` — x is batched [B, ...];
  ``state`` carries BatchNorm running statistics (functional analog of torch's
  buffers). In train mode BN uses batch statistics and returns updated running
  stats; in eval mode it uses running stats unchanged.

Everything is NHWC with HWIO kernels — the TPU-native convolution layout.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, Callable, List, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

Params = Any
State = Any
Shape = Tuple[int, ...]

CONV_DIMS = ("NHWC", "HWIO", "NHWC")
BN_MOMENTUM = 0.1  # torch's default BatchNorm momentum
BN_EPS = 1e-5


class axis_context:
    """Trace-time marker that a named mesh axis is active for model applies.

    Subclasses declare their own class-level ``_stack``; entering pushes the
    axis name and ``current()`` peeks it. This is how one model definition
    serves multiple execution modes: sequence_parallel (ring attention,
    models/transformer.py) and expert_parallel (MoE all_to_all dispatch,
    models/moe.py) are both instances.
    """

    _stack: List[str]

    def __init__(self, axis: str):
        self.axis = axis

    def __enter__(self):
        type(self)._stack.append(self.axis)
        return self

    def __exit__(self, *exc):
        type(self)._stack.pop()
        return False

    @classmethod
    def current(cls):
        return cls._stack[-1] if cls._stack else None


class batch_parallel(axis_context):
    """Trace-time marker: the model is applied inside a shard_map whose
    named axis shards the BATCH dimension (the dp sharded-update engine,
    parallel/dp.py). batchnorm then computes cross-replica (global-batch)
    statistics explicitly via :func:`sync_batch_mean` — the same sync-BN
    semantics GSPMD derives automatically when the batch axis is sharded
    under one jit. The entry carries (axis_name, world) because the
    unbiased-variance correction needs the static global count."""

    _stack: List[Any] = []

    def __init__(self, axis: str, world: int):
        super().__init__(axis)
        self.world = int(world)

    def __enter__(self):
        type(self)._stack.append((self.axis, self.world))
        return self


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def sync_batch_mean(x, shape, axis, world):
    """Global-batch mean of ``x`` over all-but-last axes, f32-accumulated,
    inside a shard_map whose ``axis`` shards the leading (batch) dim.

    Mirrors the op order of GSPMD's partitioned ``jnp.mean(x, axes,
    dtype=f32)`` — local reduce, cross-replica sum, divide by the GLOBAL
    count — and defines the matching backward explicitly: the stat
    cotangents are genuinely partial per device (each device's backward
    only sees its local rows' contributions), so they are psum'd, divided
    by the global count, and broadcast over the local rows; exactly the
    reduce/divide/broadcast sequence of the partitioned transpose.
    ``shape`` is the static LOCAL shape of x, ``world`` the axis size.
    """
    axes = tuple(range(len(shape) - 1))
    local = 1
    for a in axes:
        local *= shape[a]
    return lax.psum(jnp.sum(x, axis=axes, dtype=jnp.float32), axis) / (
        local * world)


def _sync_batch_mean_fwd(x, shape, axis, world):
    return sync_batch_mean(x, shape, axis, world), jnp.zeros((), x.dtype)


def _sync_batch_mean_bwd(shape, axis, world, res, ct):
    axes = tuple(range(len(shape) - 1))
    local = 1
    for a in axes:
        local *= shape[a]
    ct = lax.psum(ct, axis) / (local * world)
    bshape = [1] * len(shape)
    bshape[-1] = shape[-1]
    return (jnp.broadcast_to(ct.reshape(bshape), shape).astype(res.dtype),)


sync_batch_mean.defvjp(_sync_batch_mean_fwd, _sync_batch_mean_bwd)


@dataclasses.dataclass(frozen=True)
class Layer:
    """One pipeline-atomic unit of a model.

    The three optional fields support KV-cached incremental decoding
    (models/decode.py) and default to None for layers that don't need them:

    * ``init_cache(params, batch, max_len, dtype) -> cache`` — allocate the
      layer's decode cache (e.g. K/V buffers for attention blocks).
    * ``prefill(params, state, cache, x, start) -> (y, cache)`` — process the
      whole decode prompt at once, populating the cache from position
      ``start``. Current implementations require ``start == 0`` (the prompt
      opens the stream); chunked prefill against an existing cache is future
      work. Layers without one are prefilled via ``apply``.
    * ``decode(params, state, cache, x, pos) -> (y, cache)`` — process ONE
      token (x is [B, 1, ...]) at dynamic position ``pos`` against the cache.
      Layers without one decode via ``apply`` (correct only for
      position-independent layers; position-dependent layers like embeddings
      must provide it).
    """

    name: str
    init: Callable[[jax.Array, Shape], Tuple[Params, State, Shape]]
    apply: Callable[[Params, State, jax.Array, bool], Tuple[jax.Array, State]]
    init_cache: Any = None
    prefill: Any = None
    decode: Any = None
    # True if ``apply`` on a single position equals its full-sequence result
    # (no position dependence, no cross-position mixing) — such layers can be
    # decoded via apply without a cache (e.g. the LM head).
    pointwise: bool = False
    # Output-head layers may provide a fused projection+loss path
    # ``fused_loss(params, x, labels, smoothing) -> (obj_sum, ce_sum, correct)``
    # that never materializes the [N, num_classes] logits (ops/fused_xent.py);
    # strategies use it on the training path when cfg.fused_head_loss is set.
    fused_loss: Any = None
    # Eval-side sibling: ``fused_eval(params, x, labels) ->
    # (ce_sum, correct, correct_top5, valid)`` — same fusion for the
    # validation metrics (incl. prec@5 with torch.topk tie order).
    fused_eval: Any = None
    # Per-example spatial factor for the analytic FLOP heuristic
    # (parallel/packing.layer_flop_costs): conv FLOPs ~ 2*params*H*W, read
    # from the layer's OUTPUT shape by default. Layers whose output shape
    # hides the compute geometry set this — packed composite spans
    # (models/branchy._packed_span) emit flat [N] boundaries whose spatial
    # would read as 1, underweighting convolutional spans by orders of
    # magnitude in the balanced stage split.
    cost_spatial: Any = None
    # Optional paged-KV-cache decode protocol (ops/paged_decode.py): the
    # copy-on-write fast path for beam search. Layers that allocate a cache
    # (init_cache) may also provide a PagedOps; cache-free decode layers
    # participate through their ordinary ``decode``.
    paged: Any = None
    # Optional continuous-batching serving protocol (serve/engine.py): a
    # ServeOps whose ops take per-ROW stream positions and go through a
    # shared free-list page pool. Pointwise layers participate through
    # ``apply``; everything else needs a ServeOps to be servable.
    serve: Any = None


@dataclasses.dataclass(frozen=True)
class PagedOps:
    """Paged-cache decode protocol (models/decode.py paged loops).

    Same shapes/positions as the dense protocol; ``reorder`` is the
    copy-on-write replacement for the full-cache gather in beam search, and
    ``decode`` must be traced inside a ``live_pages`` segment (the static
    page count the attention kernel grid needs)."""

    init_cache: Callable  # (params, batch, max_len, dtype) -> cache
    prefill: Callable  # (params, state, cache, x, start) -> (y, cache)
    decode: Callable  # (params, state, cache, x, pos) -> (y, cache)
    reorder: Callable  # (cache, parent, pos) -> cache


@dataclasses.dataclass(frozen=True)
class ServeOps:
    """Continuous-batching serving protocol (serve/engine.py).

    Unlike :class:`PagedOps` — whose rows march in lockstep through one
    shared position — serving rows are independent requests at per-row
    stream positions, borrowing K/V slots from a SHARED free-list pool
    (ops/paged_decode.py serve primitives). The engine owns ONE page table
    ([max_batch, n_pages] int32, slot 0 = scratch) shared by every layer:
    slot allocation is per-request across all layers at once, vLLM-style,
    so each layer indexes its own pool with the same table.

    * ``pool_init(params, n_pages, page, dtype) -> pool`` — the layer's
      slice of the shared pool ({} / None for cache-free layers).
    * ``prefill(p, s, pool, table, x, start, npl, page) -> (y, pool)`` —
      one page-aligned prompt chunk x [R, C] at positions
      [start, start + C) (``start`` dynamic, ``npl``/``page``/C static).
    * ``decode(p, s, pool, table, x, pos, npl, page) -> (y, pool)`` —
      one token per row, x [B, 1] at per-row positions ``pos`` [B].
    * ``verify`` (optional) — the speculative-decoding scoring pass:
      x [B, W] token spans at page-UNALIGNED per-row positions
      [pos0_r, pos0_r + W) (each row's pending token + its drafts). Same
      contract as ``decode`` — write the span's K/V through the table,
      then causal attention at absolute positions — but W positions per
      row in one call (ops/paged_decode.paged_table_span_write +
      per-row-start chunk attention). None = the layer cannot serve
      speculative traffic (the engine rejects the config at build).
    """

    pool_init: Any  # None for cache-free layers (e.g. the embedding)
    prefill: Callable
    decode: Callable
    verify: Any = None


@dataclasses.dataclass(frozen=True)
class LayerModel:
    """A named flat stack of layers plus metadata the strategies need."""

    name: str
    layers: List[Layer]
    in_shape: Shape  # (H, W, C) for images; (T,) for tokens
    num_classes: int  # classes, or vocab size for token models
    # "float" (images/features) or "tokens" (int32 ids into a vocab of
    # num_classes) — tells the profiler and tools how to synthesize inputs.
    input_kind: str = "float"
    # seq2seq models only: the prefix-LM source-segment length baked into the
    # attention masks (decode entry points validate against it).
    src_len: int | None = None


def init_model(model: LayerModel, key: jax.Array):
    """Initialize every layer; returns (params_list, state_list, shapes).

    ``shapes[i]`` is the per-example input shape of layer i; ``shapes[-1]`` is
    the final output shape. These boundary shapes drive pipeline activation
    buffers and the profiler's activation_size fields.
    """
    params, states, shapes = [], [], [model.in_shape]
    shape = model.in_shape
    for layer in model.layers:
        key, sub = jax.random.split(key)
        p, s, shape = layer.init(sub, shape)
        params.append(p)
        states.append(s)
        shapes.append(shape)
    return params, states, shapes


def apply_slice(layers: Sequence[Layer], params, states, x, train: bool,
                remat: bool = False):
    """Run ``layers`` in order. With ``remat`` each layer is wrapped in
    jax.checkpoint: the backward recomputes the layer instead of saving its
    interior activations, capping live memory at one layer's working set —
    at 8k context the XLA-attention score matrix is 2 GB/layer, so without
    this every layer's matrix is resident at once and a single v5e chip
    OOMs (perf_runs, round 3). FLOPs-for-HBM, the jax.checkpoint analog of
    the pipeline strategies' per-(microbatch, stage) cfg.remat_stages."""
    new_states = []
    for layer, p, s in zip(layers, params, states):
        if remat:
            x, s2 = jax.checkpoint(
                functools.partial(layer.apply, train=train))(p, s, x)
        else:
            x, s2 = layer.apply(p, s, x, train)
        new_states.append(s2)
    return x, new_states


def apply_model(model: LayerModel, params, states, x, train: bool,
                remat: bool = False):
    return apply_slice(model.layers, params, states, x, train, remat)


# ---------------------------------------------------------------------------
# Parameter initializers (match torch defaults where the reference relies on
# them: kaiming-normal fan_out for convs, BN gamma=1 beta=0, linear kaiming-uniform).
# ---------------------------------------------------------------------------

def _conv_kernel_init(key, kh, kw, cin, cout):
    fan_out = kh * kw * cout
    std = math.sqrt(2.0 / fan_out)
    return jax.random.normal(key, (kh, kw, cin, cout), jnp.float32) * std


def _linear_init(key, cin, cout):
    bound = 1.0 / math.sqrt(cin)
    kw, kb = jax.random.split(key)
    w = jax.random.uniform(kw, (cin, cout), jnp.float32, -bound, bound)
    b = jax.random.uniform(kb, (cout,), jnp.float32, -bound, bound)
    return w, b


def _conv_out_hw(h, w, kh, kw, stride, padding):
    if padding == "SAME":
        return math.ceil(h / stride), math.ceil(w / stride)
    return (h - kh) // stride + 1, (w - kw) // stride + 1


# ---------------------------------------------------------------------------
# Stateless primitive helpers used *inside* composite layers.
# ---------------------------------------------------------------------------

def conv2d(x, kernel, stride=1, padding="SAME", groups=1):
    return lax.conv_general_dilated(
        x,
        kernel.astype(x.dtype),
        window_strides=(stride, stride),
        padding=padding,
        dimension_numbers=CONV_DIMS,
        feature_group_count=groups,
    )


def batchnorm(p, s, x, train: bool):
    """Returns (y, new_state). p = {scale, bias}; s = {mean, var}.

    Statistics accumulate in float32 (f32-accumulated reductions over the bf16
    activations); normalization itself stays in the compute dtype so no f32
    copy of the activation tensor is ever materialized in HBM.
    """
    axes = tuple(range(x.ndim - 1))
    if train:
        sync = batch_parallel.current()
        # One-pass stats; the f32 converts fuse into the reductions (no f32
        # copy of x hits HBM, unlike a two-pass mean-then-var). Under a
        # batch_parallel axis (the dp sharded-update engine) the means are
        # explicit cross-replica psums over the global batch — the sync-BN
        # semantics the sharded-jit strategies get from GSPMD.
        if sync is not None:
            axis, world = sync
            mean = sync_batch_mean(x, x.shape, axis, world)
            mean2 = sync_batch_mean(lax.square(x.astype(jnp.float32)),
                                    x.shape, axis, world)
        else:
            world = 1
            mean = jnp.mean(x, axis=axes, dtype=jnp.float32)
            mean2 = jnp.mean(lax.square(x.astype(jnp.float32)), axis=axes,
                             dtype=jnp.float32)
        var = jnp.maximum(mean2 - lax.square(mean), 0.0)
        # Running var uses the unbiased estimator (torch BatchNorm semantics);
        # normalization below uses the biased batch var, also matching torch.
        n = (x.size // x.shape[-1]) * world
        unbiased = var * (n / max(1, n - 1))
        new_s = {
            "mean": (1 - BN_MOMENTUM) * s["mean"] + BN_MOMENTUM * mean,
            "var": (1 - BN_MOMENTUM) * s["var"] + BN_MOMENTUM * unbiased,
        }
    else:
        mean, var = s["mean"], s["var"]
        new_s = s
    inv = lax.rsqrt(var + BN_EPS) * p["scale"]
    shift = p["bias"] - mean * inv
    y = x * inv.astype(x.dtype) + shift.astype(x.dtype)
    return y, new_s


def bn_init(c):
    params = {"scale": jnp.ones((c,), jnp.float32), "bias": jnp.zeros((c,), jnp.float32)}
    state = {"mean": jnp.zeros((c,), jnp.float32), "var": jnp.ones((c,), jnp.float32)}
    return params, state


# ---------------------------------------------------------------------------
# Layer constructors.
# ---------------------------------------------------------------------------

def conv_bn(name: str, out_ch: int, kernel: int = 3, stride: int = 1,
            relu: bool = True, padding: str = "SAME", groups: int = 1) -> Layer:
    def init(key, in_shape):
        h, w, c = in_shape
        k = _conv_kernel_init(key, kernel, kernel, c // groups, out_ch)
        bn_p, bn_s = bn_init(out_ch)
        oh, ow = _conv_out_hw(h, w, kernel, kernel, stride, padding)
        return {"kernel": k, "bn": bn_p}, {"bn": bn_s}, (oh, ow, out_ch)

    def apply(p, s, x, train):
        y = conv2d(x, p["kernel"], stride, padding, groups)
        y, bn_s = batchnorm(p["bn"], s["bn"], y, train)
        if relu:
            y = jax.nn.relu(y)
        return y, {"bn": bn_s}

    return Layer(name, init, apply)


def max_pool(name: str, window: int = 2, stride: int | None = None, padding: str = "VALID") -> Layer:
    stride = stride or window

    def init(key, in_shape):
        h, w, c = in_shape
        oh, ow = _conv_out_hw(h, w, window, window, stride, padding)
        return {}, {}, (oh, ow, c)

    def apply(p, s, x, train):
        y = lax.reduce_window(
            x, -jnp.inf, lax.max,
            (1, window, window, 1), (1, stride, stride, 1), padding,
        )
        return y, s

    return Layer(name, init, apply)


def avg_pool(name: str, window: int = 3, stride: int = 1,
             padding: str = "SAME") -> Layer:
    """Average pooling (count includes SAME padding — torch
    count_include_pad=True, the AvgPool2d default the reference's models
    rely on)."""

    def init(key, in_shape):
        h, w, c = in_shape
        oh, ow = _conv_out_hw(h, w, window, window, stride, padding)
        return {}, {}, (oh, ow, c)

    def apply(p, s, x, train):
        y = lax.reduce_window(
            x, 0.0, lax.add,
            (1, window, window, 1), (1, stride, stride, 1), padding,
        ) / float(window * window)
        return y, s

    return Layer(name, init, apply)


def sep_conv_bn(name: str, out_ch: int, kernel: int = 3,
                stride: int = 1) -> Layer:
    """Depthwise-separable conv: relu -> depthwise kxk (stride) ->
    pointwise 1x1 -> BN — the NASNet cell operation (one pass of the
    paper's relu-sepconv-bn pair; the mini family applies it once)."""

    def init(key, in_shape):
        h, w, c = in_shape
        k1, k2 = jax.random.split(key)
        p = {"dw": _conv_kernel_init(k1, kernel, kernel, 1, c),
             "pw": _conv_kernel_init(k2, 1, 1, c, out_ch)}
        bn_p, bn_s = bn_init(out_ch)
        p["bn"] = bn_p
        oh, ow = _conv_out_hw(h, w, kernel, kernel, stride, "SAME")
        return p, {"bn": bn_s}, (oh, ow, out_ch)

    def apply(p, s, x, train):
        y = jax.nn.relu(x)
        y = conv2d(y, p["dw"], stride, groups=p["dw"].shape[-1])
        y = conv2d(y, p["pw"], 1)
        y, bn_s = batchnorm(p["bn"], s["bn"], y, train)
        return y, {"bn": bn_s}

    return Layer(name, init, apply)


def global_avg_pool(name: str = "gap") -> Layer:
    def init(key, in_shape):
        h, w, c = in_shape
        return {}, {}, (c,)

    def apply(p, s, x, train):
        return jnp.mean(x, axis=(1, 2)), s

    return Layer(name, init, apply)


def flatten(name: str = "flatten") -> Layer:
    def init(key, in_shape):
        return {}, {}, (int(math.prod(in_shape)),)

    def apply(p, s, x, train):
        return x.reshape(x.shape[0], -1), s

    return Layer(name, init, apply)


def dense(name: str, out_features: int, relu: bool = False, dropout: float = 0.0) -> Layer:
    """Linear layer over flattened features. Dropout is a no-op here (the
    benchmark protocol measures throughput; reference VGG classifiers carry
    Dropout but it does not change shapes/FLOPs materially) — documented
    deviation."""

    def init(key, in_shape):
        cin = int(in_shape[0]) if len(in_shape) == 1 else int(math.prod(in_shape))
        w, b = _linear_init(key, cin, out_features)
        return {"w": w, "b": b}, {}, (out_features,)

    def apply(p, s, x, train):
        x = x.reshape(x.shape[0], -1)
        y = x @ p["w"].astype(x.dtype) + p["b"].astype(x.dtype)
        if relu:
            y = jax.nn.relu(y)
        return y, s

    return Layer(name, init, apply)


# ---------------------------------------------------------------------------
# Residual blocks — each is ONE Layer (pipeline-atomic), so skip connections
# never cross stage boundaries and the reference's stash/pop machinery
# (gpipemodels/resnet/block.py:31-51) has no TPU analog to build.
# ---------------------------------------------------------------------------

def basic_block(name: str, out_ch: int, stride: int = 1) -> Layer:
    """ResNet BasicBlock: 3x3 -> 3x3 with identity/projection shortcut."""

    def init(key, in_shape):
        h, w, c = in_shape
        k1, k2, k3 = jax.random.split(key, 3)
        p = {
            "conv1": _conv_kernel_init(k1, 3, 3, c, out_ch),
            "conv2": _conv_kernel_init(k2, 3, 3, out_ch, out_ch),
        }
        s = {}
        p["bn1"], s["bn1"] = bn_init(out_ch)
        p["bn2"], s["bn2"] = bn_init(out_ch)
        if stride != 1 or c != out_ch:
            p["proj"] = _conv_kernel_init(k3, 1, 1, c, out_ch)
            p["bn_proj"], s["bn_proj"] = bn_init(out_ch)
        oh, ow = _conv_out_hw(h, w, 3, 3, stride, "SAME")
        return p, s, (oh, ow, out_ch)

    def apply(p, s, x, train):
        ns = {}
        y = conv2d(x, p["conv1"], stride)
        y, ns["bn1"] = batchnorm(p["bn1"], s["bn1"], y, train)
        y = jax.nn.relu(y)
        y = conv2d(y, p["conv2"], 1)
        y, ns["bn2"] = batchnorm(p["bn2"], s["bn2"], y, train)
        if "proj" in p:
            sc = conv2d(x, p["proj"], stride)
            sc, ns["bn_proj"] = batchnorm(p["bn_proj"], s["bn_proj"], sc, train)
        else:
            sc = x
        return jax.nn.relu(y + sc), ns

    return Layer(name, init, apply)


def bottleneck_block(name: str, mid_ch: int, stride: int = 1, expansion: int = 4) -> Layer:
    """ResNet Bottleneck: 1x1 -> 3x3 -> 1x1(x4) with projection shortcut."""
    out_ch = mid_ch * expansion

    def init(key, in_shape):
        h, w, c = in_shape
        k1, k2, k3, k4 = jax.random.split(key, 4)
        p = {
            "conv1": _conv_kernel_init(k1, 1, 1, c, mid_ch),
            "conv2": _conv_kernel_init(k2, 3, 3, mid_ch, mid_ch),
            "conv3": _conv_kernel_init(k3, 1, 1, mid_ch, out_ch),
        }
        s = {}
        p["bn1"], s["bn1"] = bn_init(mid_ch)
        p["bn2"], s["bn2"] = bn_init(mid_ch)
        p["bn3"], s["bn3"] = bn_init(out_ch)
        if stride != 1 or c != out_ch:
            p["proj"] = _conv_kernel_init(k4, 1, 1, c, out_ch)
            p["bn_proj"], s["bn_proj"] = bn_init(out_ch)
        oh, ow = _conv_out_hw(h, w, 3, 3, stride, "SAME")
        return p, s, (oh, ow, out_ch)

    def apply(p, s, x, train):
        ns = {}
        y = conv2d(x, p["conv1"], 1)
        y, ns["bn1"] = batchnorm(p["bn1"], s["bn1"], y, train)
        y = jax.nn.relu(y)
        y = conv2d(y, p["conv2"], stride)
        y, ns["bn2"] = batchnorm(p["bn2"], s["bn2"], y, train)
        y = jax.nn.relu(y)
        y = conv2d(y, p["conv3"], 1)
        y, ns["bn3"] = batchnorm(p["bn3"], s["bn3"], y, train)
        if "proj" in p:
            sc = conv2d(x, p["proj"], stride)
            sc, ns["bn_proj"] = batchnorm(p["bn_proj"], s["bn_proj"], sc, train)
        else:
            sc = x
        return jax.nn.relu(y + sc), ns

    return Layer(name, init, apply)


def inverted_residual(name: str, out_ch: int, stride: int, expand: int) -> Layer:
    """MobileNetV2 inverted residual: 1x1 expand -> 3x3 depthwise -> 1x1 project,
    residual add when stride==1 and channels match."""

    def init(key, in_shape):
        h, w, c = in_shape
        hidden = c * expand
        k1, k2, k3 = jax.random.split(key, 3)
        p, s = {}, {}
        if expand != 1:
            p["expand"] = _conv_kernel_init(k1, 1, 1, c, hidden)
            p["bn_e"], s["bn_e"] = bn_init(hidden)
        # depthwise: HWIO with I=1, groups=hidden
        p["dw"] = _conv_kernel_init(k2, 3, 3, 1, hidden)
        p["bn_d"], s["bn_d"] = bn_init(hidden)
        p["project"] = _conv_kernel_init(k3, 1, 1, hidden, out_ch)
        p["bn_p"], s["bn_p"] = bn_init(out_ch)
        oh, ow = _conv_out_hw(h, w, 3, 3, stride, "SAME")
        return p, s, (oh, ow, out_ch)

    def apply(p, s, x, train):
        ns = {}
        y = x
        hidden_groups = p["dw"].shape[-1]
        if "expand" in p:
            y = conv2d(y, p["expand"], 1)
            y, ns["bn_e"] = batchnorm(p["bn_e"], s["bn_e"], y, train)
            y = jax.nn.relu6(y)
        y = conv2d(y, p["dw"], stride, groups=hidden_groups)
        y, ns["bn_d"] = batchnorm(p["bn_d"], s["bn_d"], y, train)
        y = jax.nn.relu6(y)
        y = conv2d(y, p["project"], 1)
        y, ns["bn_p"] = batchnorm(p["bn_p"], s["bn_p"], y, train)
        if stride == 1 and x.shape[-1] == y.shape[-1]:
            y = y + x
        return y, ns

    return Layer(name, init, apply)


def param_count(params) -> int:
    return sum(int(jnp.size(l)) for l in jax.tree.leaves(params))


def param_bytes(params) -> int:
    return sum(int(jnp.size(l)) * l.dtype.itemsize for l in jax.tree.leaves(params))
