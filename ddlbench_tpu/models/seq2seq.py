"""Seq2seq (translation) workload — the reference's GNMT, re-designed TPU-first.

The reference's translation workload (SURVEY.md §2 C13;
pipedream-fork/{runtime,profiler}/translation) is a GNMT LSTM encoder-decoder
with Bahdanau attention, varlen packing CUDA kernels (D2), label smoothing, and
beam-search inference. None of that machinery survives a TPU-first redesign:

* LSTM recurrence serializes over time — the one thing the MXU cannot hide.
  The TPU-native seq2seq is a transformer with a **prefix-LM attention
  pattern**: source and target ride ONE [B, S+T] token stream; source
  positions attend bidirectionally within the source (the "encoder"), target
  positions attend causally to targets and fully to the source (the
  "decoder" + cross-attention), all in the same block. One activation stream
  means the model is a flat layer chain like every other model here, so it
  runs unchanged under single/dp/tp/fsdp/gpipe/pipedream AND sequence
  parallelism (ring attention applies the prefix rule on absolute key
  positions, so the source may span shards; ep stays causal-LM-only since
  MoE archs are LMs) — where the reference needed a separate model family
  and runtime driver (runtime/translation/main_with_runtime.py) for GNMT.
* The blocks ARE models/transformer.py's blocks: transformer_block takes a
  ``prefix_len`` that generalizes the causal mask, so seq2seq adds only the
  segment-aware embedding and the decode entry points below.
* Varlen packing (pack_utils CUDA, D2) disappears: batches are fixed-shape
  [B, S+T] streams with loss masking (label -1) on source positions — XLA
  gets static shapes, the masked positions cost FLOPs but keep the MXU busy,
  and the data pipeline needs no scatter kernels.
* Label smoothing (GNMT trains with 0.1) is in the shared loss
  (parallel/common.py cross_entropy_loss), applied via
  RunConfig.resolved_label_smoothing().
* Inference parity: greedy_decode and beam_search_decode below, both fully
  jitted with static shapes (lax.fori_loop over positions), replacing GNMT's
  Python beam-search generator.

The prefix split point (src_len) is static per dataset spec ("synthmt":
128 source + 128 target), so the attention mask is a compile-time constant.

Variants: seq2seq_s (8 x d512, ~GNMT-scale), seq2seq_m (12 x d768).
"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp
from jax import lax

from ddlbench_tpu.models.layers import Layer, LayerModel
from ddlbench_tpu.models.transformer import (
    _dense_init,
    lm_head,
    shard_positions,
    transformer_block,
)

_VARIANTS = {
    "seq2seq_s": dict(d_model=512, n_layers=8, n_heads=8),
    "seq2seq_m": dict(d_model=768, n_layers=12, n_heads=12),
}


def seq2seq_embed(name: str, vocab: int, d_model: int, max_len: int,
                  src_len: int) -> Layer:
    """Token + learned position + segment (source=0 / target=1) embedding."""

    def init(key, in_shape):
        (T,) = in_shape
        k1, k2, k3 = jax.random.split(key, 3)
        p = {
            "tok": _dense_init(k1, vocab, d_model),
            "pos": _dense_init(k2, max_len, d_model),
            "seg": _dense_init(k3, 2, d_model),
        }
        return p, {}, (T, d_model)

    def apply(p, s, x, train):
        # x: [B, T] int32 (T = local shard length under sequence parallelism;
        # position/segment embeddings use absolute positions either way)
        pos_emb, abs_pos = shard_positions(p["pos"], x.shape[1])
        seg_ids = (abs_pos >= src_len).astype(jnp.int32)
        y = (jnp.take(p["tok"], x, axis=0)
             + pos_emb
             + jnp.take(p["seg"], seg_ids, axis=0))
        return y, s

    def decode(p, s, cache, x, pos):
        # x: [B, 1] at dynamic absolute position pos
        pe = lax.dynamic_slice_in_dim(p["pos"], pos, 1, axis=0)
        seg_id = (jnp.asarray(pos, jnp.int32) >= src_len).astype(jnp.int32)
        seg = jnp.take(p["seg"], seg_id[None], axis=0)
        return jnp.take(p["tok"], x, axis=0) + pe + seg, cache

    return Layer(name, init, apply, decode=decode)


def build_seq2seq(arch: str, in_shape, vocab: int, src_len: int) -> LayerModel:
    cfgv = _VARIANTS[arch]
    T = in_shape[0]
    if not 0 < src_len < T:
        raise ValueError(f"src_len {src_len} must be inside the stream (T={T})")
    layers: List[Layer] = [
        seq2seq_embed("embed", vocab, cfgv["d_model"], T, src_len)
    ]
    for i in range(cfgv["n_layers"]):
        layers.append(
            transformer_block(f"block{i + 1}", cfgv["d_model"],
                              cfgv["n_heads"], prefix_len=src_len)
        )
    layers.append(lm_head("lm_head", vocab))
    return LayerModel(arch, layers, tuple(in_shape), vocab,
                      input_kind="tokens", src_len=src_len)


# ---------------------------------------------------------------------------
# Inference (GNMT beam-search parity, reference
# runtime/translation seq2seq inference modules). Both decoders re-run the
# full forward per emitted token — O(T^2) per sequence but fully static-shaped
# and jittable. By default both delegate to the KV-cached incremental
# implementation (models/decode.py, O(T) per token); the full-forward loops
# below are the reference semantics the cached path is tested against.
# ---------------------------------------------------------------------------


def _check_src(model: LayerModel, src, total_len: int) -> None:
    if model.src_len is None:
        raise ValueError(f"{model.name} is not a seq2seq model")
    if src.ndim != 2 or src.shape[1] != model.src_len:
        raise ValueError(
            f"src must be [B, {model.src_len}] (the src_len baked into "
            f"{model.name}'s attention masks), got {tuple(src.shape)}"
        )
    T = model.in_shape[0]
    if not model.src_len < total_len <= T:
        raise ValueError(
            f"total_len must be in ({model.src_len}, {T}] (past the source, "
            f"within {model.name}'s trained context), got {total_len}"
        )


def _forward_logits(model: LayerModel, params, state, tokens):
    from ddlbench_tpu.models.layers import apply_model

    logits, _ = apply_model(model, params, state, tokens, False)
    return logits


def greedy_decode(model: LayerModel, params, state, src, total_len: int,
                  use_cache: bool = True):
    """Greedy continuation of `src` [B, src_len] to length `total_len`.

    Returns [B, total_len] where positions >= src_len are argmax
    continuations. ``use_cache=True`` (default) takes the KV-cached
    incremental path (models/decode.py, O(T) per token); ``use_cache=False``
    is the full-forward reference implementation the cached path is tested
    against.
    """
    _check_src(model, src, total_len)
    if use_cache:
        from ddlbench_tpu.models.decode import greedy_decode as cached

        return cached(model, params, state, src, total_len)
    B, S = src.shape
    x0 = jnp.zeros((B, total_len), jnp.int32).at[:, :S].set(src)

    def body(t, x):
        logits = _forward_logits(model, params, state, x)
        nxt = jnp.argmax(logits[:, t - 1], axis=-1).astype(jnp.int32)
        return x.at[:, t].set(nxt)

    return lax.fori_loop(S, total_len, body, x0)


def beam_search_decode(model: LayerModel, params, state, src, total_len: int,
                       beam: int = 4, length_penalty: float = 0.6,
                       use_cache: bool = True):
    """Beam-search continuation of `src` [B, src_len] to length `total_len`.

    Standard length-normalized beam search (GNMT inference semantics:
    score = logprob_sum / ((5+len)/6)^alpha) over a static position loop.
    ``use_cache=True`` (default) keeps per-hypothesis KV caches and regathers
    them along the parent beam (models/decode.py); ``use_cache=False``
    re-runs the full forward per step (the reference implementation).
    Hypotheses all have the same (full) length so no finished-hypothesis
    bookkeeping is needed. Returns (tokens [B, total_len], score [B]) for
    the best beam.
    """
    _check_src(model, src, total_len)
    if use_cache:
        from ddlbench_tpu.models.decode import beam_search_decode as cached

        return cached(model, params, state, src, total_len, beam,
                      length_penalty)
    B, S = src.shape
    V = model.num_classes
    # [B*beam, total_len] hypothesis buffer; beams identical at start.
    x0 = jnp.zeros((B, total_len), jnp.int32).at[:, :S].set(src)
    x0 = jnp.repeat(x0, beam, axis=0)
    # First expansion must come from ONE beam per batch item (all beams are
    # identical); mask others with -inf.
    score0 = jnp.where(
        jnp.arange(B * beam) % beam == 0, 0.0, -jnp.inf
    ).astype(jnp.float32)

    def body(t, carry):
        x, score = carry
        logits = _forward_logits(model, params, state, x)  # [B*beam, T, V]
        logp = jax.nn.log_softmax(logits[:, t - 1].astype(jnp.float32), -1)
        # candidate scores: [B, beam*V]
        cand = (score[:, None] + logp).reshape(B, beam * V)
        top_score, top_idx = lax.top_k(cand, beam)  # [B, beam]
        beam_src = top_idx // V  # which parent beam
        token = (top_idx % V).astype(jnp.int32)
        flat_src = (jnp.arange(B)[:, None] * beam + beam_src).reshape(-1)
        x = x[flat_src].at[:, t].set(token.reshape(-1))
        return x, top_score.reshape(-1)

    x, score = lax.fori_loop(S, total_len, body, (x0, score0))
    # length-normalized best beam per batch item
    norm = ((5.0 + (total_len - S)) / 6.0) ** length_penalty
    score = (score / norm).reshape(B, beam)
    best = jnp.argmax(score, axis=-1)
    x = x.reshape(B, beam, total_len)[jnp.arange(B), best]
    return x, score[jnp.arange(B), best]
