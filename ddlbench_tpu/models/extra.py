"""Extended CNN families from the reference profiler's model directory.

The reference's PipeDream profiler tree carries torchvision-style models
beyond the benchmarked trio — alexnet, lenet, squeezenet, resnext, densenet
(pipedream-fork/profiler/image_classification/models/, SURVEY.md §2 B7
"+ unused ...") — kept so any of them can be profiled and partitioned. This
module provides the same family as flat layer chains: every block is one
pipeline-atomic Layer, so each model runs under every strategy and profiles
into the partitioner like the core zoo. (inception and nasnet live in
models/branchy.py instead: their cell graphs ARE the new capability —
declared DAGs profiled as real branchy graphs, series-parallel and
non-series-parallel respectively.)

Builders follow the torchvision architectures; small inputs (MNIST/CIFAR)
get resolution-preserving stems like models/resnet.py.
"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp

from ddlbench_tpu.models.layers import (
    Layer, LayerModel, _conv_kernel_init, _conv_out_hw, bn_init, batchnorm,
    conv2d, conv_bn, dense, flatten, global_avg_pool, max_pool)


def _conv_relu(name: str, out_ch: int, kernel: int, stride: int = 1,
               padding: str = "SAME", relu: bool = True) -> Layer:
    """Plain conv (+bias) without BatchNorm — LeNet/AlexNet/SqueezeNet
    fidelity (those architectures predate BN)."""

    def init(key, in_shape):
        h, w, c = in_shape
        k = _conv_kernel_init(key, kernel, kernel, c, out_ch)
        b = jnp.zeros((out_ch,), jnp.float32)
        oh, ow = _conv_out_hw(h, w, kernel, kernel, stride, padding)
        return {"kernel": k, "b": b}, {}, (oh, ow, out_ch)

    def apply(p, s, x, train):
        y = conv2d(x, p["kernel"], stride, padding) + p["b"].astype(x.dtype)
        if relu:
            y = jax.nn.relu(y)
        return y, s

    return Layer(name, init, apply)


# ---------------------------------------------------------------------------
# LeNet-5 / AlexNet
# ---------------------------------------------------------------------------

def build_lenet(in_shape, num_classes: int) -> LayerModel:
    layers = [
        _conv_relu("conv1", 6, kernel=5),
        max_pool("pool1", window=2),
        _conv_relu("conv2", 16, kernel=5),
        max_pool("pool2", window=2),
        flatten(),
        dense("fc1", 120, relu=True),
        dense("fc2", 84, relu=True),
        dense("fc3", num_classes),
    ]
    return LayerModel("lenet", layers, tuple(in_shape), num_classes)


def build_alexnet(in_shape, num_classes: int) -> LayerModel:
    small = in_shape[0] <= 64
    layers: List[Layer] = [
        _conv_relu("conv1", 64, kernel=11 if not small else 3,
                   stride=4 if not small else 1),
        max_pool("pool1", window=3, stride=2, padding="SAME" if small else "VALID"),
        _conv_relu("conv2", 192, kernel=5),
        max_pool("pool2", window=3, stride=2, padding="SAME" if small else "VALID"),
        _conv_relu("conv3", 384, kernel=3),
        _conv_relu("conv4", 256, kernel=3),
        _conv_relu("conv5", 256, kernel=3),
        max_pool("pool5", window=3, stride=2, padding="SAME" if small else "VALID"),
        flatten(),
        dense("fc1", 4096, relu=True, dropout=0.5),
        dense("fc2", 4096, relu=True, dropout=0.5),
        dense("fc3", num_classes),
    ]
    return LayerModel("alexnet", layers, tuple(in_shape), num_classes)


# ---------------------------------------------------------------------------
# SqueezeNet (fire modules)
# ---------------------------------------------------------------------------

def _fire(name: str, squeeze: int, expand: int) -> Layer:
    """Fire module: 1x1 squeeze -> concat(1x1 expand, 3x3 expand)."""

    def init(key, in_shape):
        h, w, c = in_shape
        k1, k2, k3 = jax.random.split(key, 3)
        p = {
            "sq": _conv_kernel_init(k1, 1, 1, c, squeeze),
            "e1": _conv_kernel_init(k2, 1, 1, squeeze, expand),
            "e3": _conv_kernel_init(k3, 3, 3, squeeze, expand),
        }
        return p, {}, (h, w, 2 * expand)

    def apply(p, s, x, train):
        sq = jax.nn.relu(conv2d(x, p["sq"], 1, "SAME"))
        e1 = jax.nn.relu(conv2d(sq, p["e1"], 1, "SAME"))
        e3 = jax.nn.relu(conv2d(sq, p["e3"], 1, "SAME"))
        return jnp.concatenate([e1, e3], axis=-1), s

    return Layer(name, init, apply)


def build_squeezenet(in_shape, num_classes: int) -> LayerModel:
    small = in_shape[0] <= 64
    layers: List[Layer] = [
        _conv_relu("conv1", 64, kernel=3, stride=1 if small else 2),
        max_pool("pool1", window=3, stride=2, padding="SAME"),
        _fire("fire2", 16, 64),
        _fire("fire3", 16, 64),
        max_pool("pool3", window=3, stride=2, padding="SAME"),
        _fire("fire4", 32, 128),
        _fire("fire5", 32, 128),
        max_pool("pool5", window=3, stride=2, padding="SAME"),
        _fire("fire6", 48, 192),
        _fire("fire7", 48, 192),
        _fire("fire8", 64, 256),
        _fire("fire9", 64, 256),
        _conv_relu("conv10", num_classes, kernel=1),
        global_avg_pool(),
    ]
    return LayerModel("squeezenet", layers, tuple(in_shape), num_classes)


# ---------------------------------------------------------------------------
# ResNeXt-50 32x4d (grouped bottlenecks)
# ---------------------------------------------------------------------------

def _resnext_block(name: str, width: int, stride: int, groups: int = 32,
                   expansion: int = 2) -> Layer:
    """Grouped bottleneck: 1x1 -> grouped 3x3 -> 1x1, residual add."""

    def init(key, in_shape):
        h, w, c = in_shape
        out_ch = width * expansion
        ks = jax.random.split(key, 4)
        p = {
            "c1": _conv_kernel_init(ks[0], 1, 1, c, width),
            "c2": _conv_kernel_init(ks[1], 3, 3, width // groups, width),
            "c3": _conv_kernel_init(ks[2], 1, 1, width, out_ch),
        }
        s = {}
        p["bn1"], s["bn1"] = bn_init(width)
        p["bn2"], s["bn2"] = bn_init(width)
        p["bn3"], s["bn3"] = bn_init(out_ch)
        if stride != 1 or c != out_ch:
            p["proj"] = _conv_kernel_init(ks[3], 1, 1, c, out_ch)
            p["bnp"], s["bnp"] = bn_init(out_ch)
        oh, ow = (h + stride - 1) // stride, (w + stride - 1) // stride
        return p, s, (oh, ow, out_ch)

    def apply(p, s, x, train):
        y = conv2d(x, p["c1"], 1, "SAME")
        y, bn1 = batchnorm(p["bn1"], s["bn1"], y, train)
        y = jax.nn.relu(y)
        y = conv2d(y, p["c2"], stride, "SAME", groups=groups)
        y, bn2 = batchnorm(p["bn2"], s["bn2"], y, train)
        y = jax.nn.relu(y)
        y = conv2d(y, p["c3"], 1, "SAME")
        y, bn3 = batchnorm(p["bn3"], s["bn3"], y, train)
        ns = {"bn1": bn1, "bn2": bn2, "bn3": bn3}
        if "proj" in p:
            sc = conv2d(x, p["proj"], stride, "SAME")
            sc, bnp = batchnorm(p["bnp"], s["bnp"], sc, train)
            ns["bnp"] = bnp
        else:
            sc = x
        return jax.nn.relu(y + sc), ns

    return Layer(name, init, apply)


def build_resnext50(in_shape, num_classes: int) -> LayerModel:
    small = in_shape[0] <= 64
    layers: List[Layer] = []
    if small:
        layers.append(conv_bn("stem", 64, kernel=3, stride=1))
    else:
        layers.append(conv_bn("stem", 64, kernel=7, stride=2))
        layers.append(max_pool("stem_pool", window=3, stride=2,
                               padding="SAME"))
    counts = [3, 4, 6, 3]
    widths = [128, 256, 512, 1024]  # 32 groups x 4d base
    for g, (width, n) in enumerate(zip(widths, counts)):
        for b in range(n):
            stride = 2 if (b == 0 and g > 0) else 1
            layers.append(_resnext_block(f"group{g + 1}_block{b + 1}",
                                         width, stride))
    layers.append(global_avg_pool())
    layers.append(dense("fc", num_classes))
    return LayerModel("resnext50", layers, tuple(in_shape), num_classes)


# ---------------------------------------------------------------------------
# DenseNet-121 (dense blocks + transitions; each dense block is one Layer)
# ---------------------------------------------------------------------------

def _dense_block(name: str, n_layers: int, growth: int = 32,
                 bn_size: int = 4) -> Layer:
    """DenseNet block: n_layers of BN-ReLU-1x1 -> BN-ReLU-3x3, each
    concatenating its growth-channel output onto the running feature map."""

    def init(key, in_shape):
        h, w, c = in_shape
        p, s = {}, {}
        ch = c
        for i in range(n_layers):
            k1, k2, key = jax.random.split(key, 3)
            p[f"l{i}_bn1"], s[f"l{i}_bn1"] = bn_init(ch)
            p[f"l{i}_c1"] = _conv_kernel_init(k1, 1, 1, ch, bn_size * growth)
            p[f"l{i}_bn2"], s[f"l{i}_bn2"] = bn_init(bn_size * growth)
            p[f"l{i}_c2"] = _conv_kernel_init(k2, 3, 3, bn_size * growth,
                                              growth)
            ch += growth
        return p, s, (h, w, ch)

    def apply(p, s, x, train):
        ns = {}
        feats = x
        for i in range(n_layers):
            y, ns[f"l{i}_bn1"] = batchnorm(p[f"l{i}_bn1"], s[f"l{i}_bn1"],
                                           feats, train)
            y = conv2d(jax.nn.relu(y), p[f"l{i}_c1"], 1, "SAME")
            y, ns[f"l{i}_bn2"] = batchnorm(p[f"l{i}_bn2"], s[f"l{i}_bn2"],
                                           y, train)
            y = conv2d(jax.nn.relu(y), p[f"l{i}_c2"], 1, "SAME")
            feats = jnp.concatenate([feats, y.astype(feats.dtype)], axis=-1)
        return feats, ns

    return Layer(name, init, apply)


def _bn_relu(name: str) -> Layer:
    """Final features BatchNorm + ReLU (torchvision DenseNet's norm5)."""

    def init(key, in_shape):
        h, w, c = in_shape
        p, s = {}, {}
        p["bn"], s["bn"] = bn_init(c)
        return p, s, (h, w, c)

    def apply(p, s, x, train):
        y, bn = batchnorm(p["bn"], s["bn"], x, train)
        return jax.nn.relu(y), {"bn": bn}

    return Layer(name, init, apply)


def _transition(name: str, out_ch: int) -> Layer:
    def init(key, in_shape):
        h, w, c = in_shape
        p = {"conv": _conv_kernel_init(key, 1, 1, c, out_ch)}
        s = {}
        p["bn"], s["bn"] = bn_init(c)
        return p, s, (h // 2, w // 2, out_ch)

    def apply(p, s, x, train):
        y, bn = batchnorm(p["bn"], s["bn"], x, train)
        y = conv2d(jax.nn.relu(y), p["conv"], 1, "SAME")
        # torch AvgPool2d(2, 2): floor output, no padding, true mean
        y = jax.lax.reduce_window(
            y, 0.0, jax.lax.add, (1, 2, 2, 1), (1, 2, 2, 1), "VALID") / 4.0
        return y, {"bn": bn}

    return Layer(name, init, apply)


def build_densenet121(in_shape, num_classes: int) -> LayerModel:
    small = in_shape[0] <= 64
    growth = 32
    layers: List[Layer] = []
    if small:
        layers.append(conv_bn("stem", 2 * growth, kernel=3, stride=1))
    else:
        layers.append(conv_bn("stem", 2 * growth, kernel=7, stride=2))
        layers.append(max_pool("stem_pool", window=3, stride=2,
                               padding="SAME"))
    ch = 2 * growth
    for i, n in enumerate([6, 12, 24, 16]):
        layers.append(_dense_block(f"dense{i + 1}", n, growth))
        ch += n * growth
        if i < 3:
            ch = ch // 2
            layers.append(_transition(f"trans{i + 1}", ch))
    layers.append(_bn_relu("norm5"))  # torchvision's final features norm
    layers.append(global_avg_pool())
    layers.append(dense("fc", num_classes))
    return LayerModel("densenet121", layers, tuple(in_shape), num_classes)


BUILDERS = {
    "lenet": build_lenet,
    "alexnet": build_alexnet,
    "squeezenet": build_squeezenet,
    "resnext50": build_resnext50,
    "densenet121": build_densenet121,
}
