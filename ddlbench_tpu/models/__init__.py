from ddlbench_tpu.models.layers import (
    Layer,
    LayerModel,
    init_model,
    apply_model,
    apply_slice,
)
from ddlbench_tpu.models.zoo import get_model, MODEL_NAMES

__all__ = [
    "Layer",
    "LayerModel",
    "init_model",
    "apply_model",
    "apply_slice",
    "get_model",
    "MODEL_NAMES",
]
