"""VGG-11/16 (batch-norm variants) as flat layer lists.

Parity with the reference's VGG families (benchmark/mnist/models/mnistvgg.py,
benchmark/cifar10/pytorchcifargitmodels/vgg.py, torchvision VGG for imagenet,
plus the GPipe nn.Sequential builds under benchmark/*/gpipemodels/vgg/).
Small-input variants classify straight from the 512-channel feature map (the
pytorch-cifar convention); large-input variants keep the 4096-wide two-layer
classifier head so FLOP/parameter footprints match torchvision's.
"""

from __future__ import annotations

from typing import List

from ddlbench_tpu.models.layers import (
    Layer,
    LayerModel,
    conv_bn,
    dense,
    flatten,
    global_avg_pool,
    max_pool,
)

_CFG = {
    # torchvision cfgs A (vgg11) and D (vgg16); 'M' = 2x2 maxpool.
    "vgg11": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "vgg16": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
              512, 512, 512, "M", 512, 512, 512, "M"],
}


def build_vgg(arch: str, in_shape, num_classes: int) -> LayerModel:
    small_input = in_shape[0] <= 64
    layers: List[Layer] = []
    conv_i = 0
    pool_i = 0
    for item in _CFG[arch]:
        if item == "M":
            pool_i += 1
            layers.append(max_pool(f"pool{pool_i}", window=2, stride=2))
        else:
            conv_i += 1
            layers.append(conv_bn(f"conv{conv_i}", int(item), kernel=3, stride=1))

    if small_input:
        layers.append(global_avg_pool())
        layers.append(dense("fc", num_classes))
    else:
        layers.append(flatten())
        layers.append(dense("fc1", 4096, relu=True))
        layers.append(dense("fc2", 4096, relu=True))
        layers.append(dense("fc3", num_classes))
    return LayerModel(name=arch, layers=layers, in_shape=tuple(in_shape), num_classes=num_classes)
