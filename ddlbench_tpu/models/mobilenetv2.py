"""MobileNetV2 as a flat layer list.

Parity with the reference's three MobileNetV2 variants
(benchmark/mnist/models/mnistmobilenetv2.py,
benchmark/cifar10/pytorchcifargitmodels/mobilenetv2.py, torchvision for
imagenet; GPipe skip-wrapped build at
benchmark/*/gpipemodels/mobilenetv2/mobilenetv2.py:15-39). Small-input variants
use a stride-1 stem (the pytorch-cifar convention) so 28/32-px inputs are not
downsampled to nothing.
"""

from __future__ import annotations

from typing import List

from ddlbench_tpu.models.layers import (
    Layer,
    LayerModel,
    conv_bn,
    dense,
    global_avg_pool,
    inverted_residual,
)

# (expansion t, output channels c, repeats n, first-block stride s) — the
# standard MobileNetV2 table.
_CFG = [
    (1, 16, 1, 1),
    (6, 24, 2, 2),
    (6, 32, 3, 2),
    (6, 64, 4, 2),
    (6, 96, 3, 1),
    (6, 160, 3, 2),
    (6, 320, 1, 1),
]


def build_mobilenetv2(arch: str, in_shape, num_classes: int) -> LayerModel:
    small_input = in_shape[0] <= 64
    layers: List[Layer] = []
    layers.append(conv_bn("stem", 32, kernel=3, stride=1 if small_input else 2))
    block_i = 0
    for t, c, n, s in _CFG:
        for i in range(n):
            stride = s if i == 0 else 1
            if small_input and block_i < 2:
                # keep early resolution on 28/32-px inputs
                stride = 1
            block_i += 1
            layers.append(inverted_residual(f"block{block_i}", c, stride, t))
    layers.append(conv_bn("head_conv", 1280, kernel=1, stride=1))
    layers.append(global_avg_pool())
    layers.append(dense("fc", num_classes))
    return LayerModel(name="mobilenetv2", layers=layers, in_shape=tuple(in_shape),
                      num_classes=num_classes)
