"""Decoder-only transformer LM as a flat layer chain.

This is the framework's sequence workload — the modern analog of the
reference's GNMT translation workload (pipedream-fork/{runtime,profiler}/
translation, SURVEY.md §2 C13), re-designed rather than translated: a causal
transformer whose blocks are pipeline-atomic layers, so the SAME model runs
under single/dp/gpipe/pipedream, and whose attention has a sequence-parallel
ring implementation (parallel/sp.py) for long-context training — the
capability the reference approximates spatially with its "highres" dataset
(SURVEY.md §5.7).

Arch variants: transformer_s (8 x d512), transformer_m (12 x d768).
Pre-LN blocks, learned positions, GELU MLP (4x), untied LM head.
"""

from __future__ import annotations

import math
from typing import List

import jax
import jax.numpy as jnp
from jax import lax

from ddlbench_tpu.models.layers import Layer, LayerModel, axis_context

LN_EPS = 1e-5

_VARIANTS = {
    # _t is the test/smoke size: big enough to exercise every code path
    # (attention, MLP, fused head), small enough for 1-core CPU compiles.
    "transformer_t": dict(d_model=32, n_layers=2, n_heads=4),
    "transformer_s": dict(d_model=512, n_layers=8, n_heads=8),
    "transformer_m": dict(d_model=768, n_layers=12, n_heads=12),
}

class sequence_parallel(axis_context):
    """Context manager: trace model applies in sequence-parallel mode. When
    active (parallel/sp.py enters it inside its shard_map), embed offsets
    positions by the shard index and attention runs the ring algorithm over
    the named mesh axis. One model definition serves both modes."""

    _stack: list = []


def _seq_axis():
    return sequence_parallel.current()


class tensor_parallel(axis_context):
    """Context manager: trace model applies in Megatron-style tensor-parallel
    mode over ``num_shards`` shards of a named mesh axis (parallel/tpp.py
    enters it inside its shard_map). When active, each shard holds the
    slices produced by :func:`tp_split_layer_params` — attention runs its
    local contiguous head group (wqkv column-slice, wo row-slice) and the
    MLP its local hidden columns (w1/b1 column-slice, w2 row-slice) — and
    the two row-parallel projections psum over the axis. Activations stay
    replicated across shards, so LN/bias/embedding leaves are shared
    (their gradients all-reduce via the strategy's replicated param path).
    """

    _stack: list = []

    def __init__(self, axis: str, num_shards: int):
        self.axis = (axis, int(num_shards))  # pushed by axis_context


def _tp_ctx():
    return tensor_parallel.current()


# Transformer-block leaves sliced per TP shard; everything else (LN scales,
# the output bias b2, embeddings, heads) is replicated across shards.
TP_SLICED_KEYS = ("wqkv", "wo", "w1", "b1", "w2")


def tp_split_layer_params(p, n: int):
    """Split one layer's params for n-way tensor parallelism.

    Returns ``(shards, repl)``: ``shards[s]`` is shard s's dict of sliced
    leaves and ``repl`` the shared remainder; a layer that is not a dense
    transformer block (no wqkv/wo/w1/w2 — embeddings, heads, MoE blocks
    whose FFN is expert-routed) is fully replicated (``shards[s] == {}``).
    Head alignment: the contiguous d/n column group of wqkv covers whole
    heads iff n divides n_heads — asserted at trace time in
    attention_sublayer, where the head count is known.
    """
    if not (isinstance(p, dict) and {"wqkv", "wo", "w1", "w2"} <= set(p)):
        return [{} for _ in range(n)], p
    d = p["wo"].shape[1]
    f = p["w1"].shape[1]
    if d % n or f % n:
        raise ValueError(
            f"tensor parallelism: d_model={d} / mlp width={f} not divisible "
            f"by tp_size={n}")
    dl, fl = d // n, f // n
    shards = [{
        # wqkv columns are q|k|v blocks of d each; slice the SAME head
        # group out of each block and re-concatenate so the apply-side
        # jnp.split(qkv, 3) still lands on q/k/v
        "wqkv": p["wqkv"].reshape(d, 3, d)[:, :, s * dl:(s + 1) * dl]
                .reshape(d, 3 * dl),
        "wo": p["wo"][s * dl:(s + 1) * dl, :],
        "w1": p["w1"][:, s * fl:(s + 1) * fl],
        "b1": p["b1"][s * fl:(s + 1) * fl],
        "w2": p["w2"][s * fl:(s + 1) * fl, :],
    } for s in range(n)]
    repl = {k: v for k, v in p.items() if k not in TP_SLICED_KEYS}
    return shards, repl


def layer_norm(p, x):
    """f32-accumulated LayerNorm over the feature axis, compute-dtype out."""
    mean = jnp.mean(x, axis=-1, keepdims=True, dtype=jnp.float32)
    mean2 = jnp.mean(lax.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    inv = lax.rsqrt(jnp.maximum(mean2 - lax.square(mean), 0.0) + LN_EPS)
    y = (x.astype(jnp.float32) - mean) * inv
    return (y.astype(x.dtype) * p["scale"].astype(x.dtype)
            + p["bias"].astype(x.dtype))


def _ln_init(d):
    return {"scale": jnp.ones((d,), jnp.float32), "bias": jnp.zeros((d,), jnp.float32)}


def _dense_init(key, din, dout, std=0.02):
    return jax.random.normal(key, (din, dout), jnp.float32) * std


def shard_positions(pos_table: jax.Array, T: int):
    """(position embeddings [T, d], absolute positions [T]) for the local
    sequence shard: rows [0, T) outside sequence parallelism, this shard's
    contiguous slice (axis_index * T offset) inside it. Single home for the
    shard layout, shared by every embedding (transformer + seq2seq)."""
    axis = _seq_axis()
    if axis is None:
        return pos_table[:T], jnp.arange(T)
    offset = lax.axis_index(axis) * T
    return (lax.dynamic_slice_in_dim(pos_table, offset, T, axis=0),
            offset + jnp.arange(T))


def embed(name: str, vocab: int, d_model: int, max_len: int) -> Layer:
    def init(key, in_shape):
        (T,) = in_shape
        k1, k2 = jax.random.split(key)
        p = {
            "tok": _dense_init(k1, vocab, d_model),
            "pos": _dense_init(k2, max_len, d_model),
        }
        return p, {}, (T, d_model)

    def apply(p, s, x, train):
        # x: [B, T] int32 (T = local shard length under sequence parallelism)
        pos, _ = shard_positions(p["pos"], x.shape[1])
        y = jnp.take(p["tok"], x, axis=0) + pos
        return y, s

    def decode(p, s, cache, x, pos):
        # x: [B, 1] int32 at dynamic absolute position `pos`
        pe = lax.dynamic_slice_in_dim(p["pos"], pos, 1, axis=0)
        return jnp.take(p["tok"], x, axis=0) + pe, cache

    def serve_prefill(p, s, pool, table, x, start, npl, page):
        # x: [R, C] chunk at positions [start, start + C); padded positions
        # past the position table are clipped (their outputs are discarded)
        C = x.shape[1]
        pe = jnp.take(p["pos"], start + jnp.arange(C), axis=0)
        return jnp.take(p["tok"], x, axis=0) + pe, pool

    def serve_decode(p, s, pool, table, x, pos, npl, page):
        # x: [B, 1] at PER-ROW positions pos [B] (each row its own request)
        pe = jnp.take(p["pos"], pos, axis=0)[:, None]
        return jnp.take(p["tok"], x, axis=0) + pe, pool

    def serve_verify(p, s, pool, table, x, pos0, npl, page):
        # x: [B, W] draft spans at per-row positions [pos0, pos0 + W);
        # pad positions past the table clip (their outputs are discarded)
        W = x.shape[1]
        pe = jnp.take(p["pos"], pos0[:, None] + jnp.arange(W), axis=0)
        return jnp.take(p["tok"], x, axis=0) + pe, pool

    from ddlbench_tpu.models.layers import ServeOps

    return Layer(name, init, apply, decode=decode,
                 serve=ServeOps(None, serve_prefill, serve_decode,
                                serve_verify))


# Attention backend: "auto" uses the Pallas flash kernel on TPU and the jnp
# path elsewhere; "flash"/"xla" force one (flash off-TPU runs the kernel in
# interpret mode — tests only, it is slow).
_ATTENTION_BACKEND = ["auto"]

# "auto" takes the flash kernel only past this (local) sequence length.
# Measured on v5e (bf16, H=8, dh=64, fwd+bwd, 50-step avg): XLA's fused
# attention wins short sequences — flash/XLA ratio 0.64x at B=64 T=256
# (prefix-LM), 0.94-0.97x at T=128-512 — and flash wins past the crossover:
# 1.24x at T=768, 1.55x at T=1024, 2.06x at T=2048, 3.4x end-to-end at
# T=8192 (where un-remat'd XLA attention cannot fit one chip at all). At
# short T the kernel's grid/stream overhead exceeds its HBM savings; the
# quadratic score tensor is small enough for XLA to keep in registers/VMEM
# through its own fusions. (perf_runs + PERF.md "auto dispatch", round 3.)
FLASH_AUTO_MIN_SEQ = 640  # base threshold; see flash_pays_off for the table


def flash_pays_off(seq_len: int, batch: int, prefix_len: int) -> bool:
    """Shape-aware flash-vs-XLA decision table (the "auto" backend policy).

    Round 3 used the single FLASH_AUTO_MIN_SEQ threshold, picked from a
    noisy single-shot sweep (VERDICT r3 weak #2). The table below encodes
    the REPRODUCIBLE signals of perf_runs/attn_crossover.json and PERF.md's
    auto-dispatch section, and is refreshed from the round-4 median-of-5
    sweeps (scripts/tpu_round4.sh attnsweep_* tasks; reader:
    tools/attnpolicy.py):

    * T >= 768: flash wins monotonically (1.24x @ 768 -> 2.06x @ 2048,
      B=16 causal) — flash.
    * T < 640: XLA's fused attention wins (0.82-0.96x) — xla.
    * [640, 768) is the noise band (sub-2ms cells swing with tunnel
      latency); flash only for the plain causal shape that measured above
      1.0 there (prefix == 0, B <= 32).
    * Prefix-LM at large batch is the strongest XLA signal (0.61x at
      B=64, T=256 — the synthmt shape): with prefix > 0 and B >= 64,
      require T >= 1024 until the b64pfx sweep shows the crossover.
    """
    if seq_len >= 1024:
        return True
    if prefix_len > 0 and batch >= 64:
        return False
    if seq_len >= 768:
        return True
    if seq_len >= FLASH_AUTO_MIN_SEQ:
        return prefix_len == 0 and batch <= 32
    return False


def set_attention_backend(backend: str) -> None:
    from ddlbench_tpu.config import ATTENTION_BACKENDS

    if backend not in ATTENTION_BACKENDS:
        raise ValueError(f"unknown attention backend {backend!r}")
    _ATTENTION_BACKEND[0] = backend


def _flash_dispatch(*operands, prefix_len: int = 0):
    """Return (use_flash, interpret) for the current backend setting.

    "auto" picks the Pallas kernel only where it partitions correctly:
    pallas_call has no GSPMD partitioning rule, so under a multi-device jit
    with sharded operands XLA would gather them to every device (ADVICE r1).
    Inside shard_map (nonempty varying-manual-axes type on an operand) and on
    a single device the kernel shapes are already local — flash is safe."""
    from ddlbench_tpu.distributed import is_tpu_backend

    mode = _ATTENTION_BACKEND[0]
    if mode == "xla":
        return False, False
    on_tpu = is_tpu_backend()
    if mode == "flash":
        return True, not on_tpu
    if not on_tpu:
        return False, False
    from ddlbench_tpu.ops.util import pallas_partitions_safely

    # compiled kernels need 8-aligned sequence blocks (flash_attention.py
    # _pick_block); odd sequence lengths take the XLA einsum path
    if any(o.ndim >= 3 and o.shape[2] % 8 for o in operands):
        return False, False
    # shape-aware crossover (flash_pays_off table): local sequence length,
    # batch, and the prefix-LM flag all shift the flash/XLA winner; ring
    # attention applies the same rule to its per-shard block length
    T = max(o.shape[2] for o in operands if o.ndim >= 3)
    B = max(o.shape[0] for o in operands if o.ndim >= 3)
    if not flash_pays_off(T, B, prefix_len):
        return False, False
    return pallas_partitions_safely(*operands), False


def causal_attention(q, k, v, q_offset: int = 0, k_offset: int = 0,
                     prefix_len: int = 0):
    """Masked attention for blocks of a causal (or prefix-LM) sequence.

    q: [B, H, Tq, Dh]; k/v: [B, H, Tk, Dh]. Offsets give each block's absolute
    position so the same primitive serves full attention (offsets 0) and ring
    attention over sequence shards (parallel/sp.py). ``prefix_len`` > 0 adds
    the prefix-LM rule: key positions < prefix_len are visible to every query
    (the seq2seq source segment, models/seq2seq.py). On TPU this dispatches
    to the fused Pallas flash-attention kernel (ops/flash_attention.py) —
    which implements the same prefix rule with block-level skipping — unless
    set_attention_backend("xla") was called.
    """
    use_flash, interpret = _flash_dispatch(q, k, v, prefix_len=prefix_len)
    if use_flash:
        from ddlbench_tpu.ops.flash_attention import flash_attention

        return flash_attention(q, k, v, q_offset, k_offset, prefix_len,
                               interpret=interpret)
    dh = q.shape[-1]
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(dh)
    q_pos = q_offset + jnp.arange(q.shape[2])[:, None]
    k_pos = k_offset + jnp.arange(k.shape[2])[None, :]
    ok = q_pos >= k_pos
    if prefix_len:
        ok = ok | (k_pos < prefix_len)
    scores = jnp.where(ok, scores, -jnp.inf)
    # numerically safe softmax that tolerates fully-masked rows
    m = jnp.max(scores, axis=-1, keepdims=True)
    m = jnp.where(jnp.isfinite(m), m, 0.0)
    e = jnp.exp(scores - m)
    z = jnp.sum(e, axis=-1, keepdims=True)
    return jnp.einsum("bhqk,bhkd->bhqd", e / jnp.maximum(z, 1e-20), v)


def ring_attention(q, k, v, axis: str, prefix_len: int = 0):
    """Causal (or prefix-LM) attention over a sequence sharded on mesh axis
    `axis`.

    Each device holds the Q/K/V block for its sequence shard; K/V blocks rotate
    around the ring with `lax.ppermute` while a streaming (online-softmax)
    accumulator — running max m, normalizer l, weighted sum acc — combines the
    partial attention of the local queries against each visiting block. This is
    blockwise/ring attention: peak memory is O(T_local^2) instead of O(T^2),
    and the ring transfers ride ICI neighbor links. ``prefix_len`` > 0 adds
    the prefix-LM rule on ABSOLUTE key positions (the seq2seq source segment
    is globally visible), so sequence-parallel translation works even when
    the source spans multiple shards.

    On TPU the causal (prefix_len == 0) path runs each visiting block through
    the fused Pallas kernel (_ring_attention_flash) instead of the einsum
    below; the prefix-LM path keeps the einsum (its visible-key count per
    block is data-dependent on the shard index, which the kernel's static
    offsets can't express).
    """
    use_flash, interpret = _flash_dispatch(q, k, v, prefix_len=prefix_len)
    if use_flash and prefix_len == 0:
        return _ring_attention_flash(q, k, v, axis, interpret)
    n = lax.psum(1, axis)
    idx = lax.axis_index(axis)
    B, H, Tl, dh = q.shape
    qf = q.astype(jnp.float32)
    q_pos = idx * Tl + jnp.arange(Tl)[:, None]  # absolute query positions

    def step(carry, i):
        k_blk, v_blk, m, l, acc = carry
        src = (idx - i) % n  # which shard's K/V we hold this round
        k_pos = src * Tl + jnp.arange(Tl)[None, :]
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, k_blk.astype(jnp.float32))
        s = s / math.sqrt(dh)
        ok = q_pos >= k_pos
        if prefix_len:
            ok = ok | (k_pos < prefix_len)
        s = jnp.where(ok, s, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - safe_m)
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + jnp.einsum("bhqk,bhkd->bhqd", p, v_blk.astype(jnp.float32))
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_blk = lax.ppermute(k_blk, axis, perm)
        v_blk = lax.ppermute(v_blk, axis, perm)
        return (k_blk, v_blk, m_new, l, acc), None

    from ddlbench_tpu.parallel.common import vary

    m0 = vary(jnp.full((B, H, Tl, 1), -jnp.inf, jnp.float32), (axis,))
    l0 = vary(jnp.zeros((B, H, Tl, 1), jnp.float32), (axis,))
    acc0 = vary(jnp.zeros((B, H, Tl, dh), jnp.float32), (axis,))
    (k, v, m, l, acc), _ = lax.scan(step, (k, v, m0, l0, acc0), jnp.arange(n))
    return (acc / jnp.maximum(l, 1e-20)).astype(q.dtype)


def _ring_attention_flash(q, k, v, axis: str, interpret: bool):
    """Ring attention with the fused kernel per visiting block.

    Each ring step classifies the visiting K/V block against the local shard
    index — fully visible (src < idx), causal-diagonal (src == idx), or
    invisible (src > idx) — so the kernel's STATIC offsets suffice: the
    "full" case fakes q_offset=Tl to open the whole block. Partial results
    combine exactly through their logsumexps:
        lse' = logaddexp(lse, lse_i);  o' = e^{lse-lse'} o + e^{lse_i-lse'} o_i
    (the associative flash combination), and the kernel's custom VJP carries
    gradients through both o_i and lse_i, so jax.grad of the scan yields the
    reverse ring schedule.
    """
    from ddlbench_tpu.ops.flash_attention import NEG_INF, flash_attention_lse
    from ddlbench_tpu.parallel.common import vary

    n = lax.psum(1, axis)
    idx = lax.axis_index(axis)
    B, H, Tl, dh = q.shape

    def full_blk(q, kb, vb):
        return flash_attention_lse(q, kb, vb, Tl, 0, 0, interpret=interpret)

    def diag_blk(q, kb, vb):
        return flash_attention_lse(q, kb, vb, 0, 0, 0, interpret=interpret)

    def skip_blk(q, kb, vb):
        return (vary(jnp.zeros_like(q), (axis,)),
                vary(jnp.full((B, H, Tl), NEG_INF, jnp.float32), (axis,)))

    def step(carry, i):
        k_blk, v_blk, o, lse = carry
        src = (idx - i) % n  # which shard's K/V we hold this round
        case = jnp.where(src < idx, 0, jnp.where(src == idx, 1, 2))
        o_i, lse_i = lax.switch(case, [full_blk, diag_blk, skip_blk],
                                q, k_blk, v_blk)
        new_lse = jnp.logaddexp(lse, lse_i)
        safe = jnp.maximum(new_lse, NEG_INF)
        o = (o * jnp.exp(lse - safe)[..., None]
             + o_i.astype(jnp.float32) * jnp.exp(lse_i - safe)[..., None])
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_blk = lax.ppermute(k_blk, axis, perm)
        v_blk = lax.ppermute(v_blk, axis, perm)
        return (k_blk, v_blk, o, new_lse), None

    o0 = vary(jnp.zeros((B, H, Tl, dh), jnp.float32), (axis,))
    lse0 = vary(jnp.full((B, H, Tl), NEG_INF, jnp.float32), (axis,))
    (k, v, o, lse), _ = lax.scan(step, (k, v, o0, lse0), jnp.arange(n))
    return o.astype(q.dtype)


def attention_sublayer(p, x, n_heads: int, prefix_len: int = 0):
    """Pre-LN self-attention sublayer with residual: reads p["ln1"],
    p["wqkv"], p["wo"]. Dispatches to ring attention over the active
    sequence_parallel axis, so every block (dense and MoE) gets the
    sequence-parallel path from one implementation; under an active
    tensor_parallel context the shard computes its local head group and the
    output projection psums over the TP axis. ``prefix_len`` selects the
    prefix-LM mask (seq2seq) on both paths."""
    B, T, d = x.shape
    dh = d // n_heads
    # Sliced-vs-replicated is decided by the PARAMS the shard actually
    # holds, not by the context alone: under tp a layer the splitter left
    # replicated (e.g. an MoE block — tp_split_layer_params) carries the
    # full-width wqkv, computes the full attention identically on every
    # shard, and must NOT psum (that would multiply by tp).
    tp = _tp_ctx()
    sliced = tp is not None and p["wqkv"].shape[1] < 3 * d
    n_local = n_heads
    if sliced:
        assert n_heads % tp[1] == 0, (
            f"tensor parallelism: n_heads={n_heads} not divisible by "
            f"tp_size={tp[1]}")
        n_local = n_heads // tp[1]
    h = layer_norm(p["ln1"], x)
    qkv = h @ p["wqkv"].astype(x.dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)

    def heads(t):
        return t.reshape(B, T, n_local, dh).transpose(0, 2, 1, 3)

    axis = _seq_axis()
    if axis is None:
        o = causal_attention(heads(q), heads(k), heads(v),
                             prefix_len=prefix_len)
    else:
        assert tp is None, (
            "ring (sequence-parallel) attention composed with tensor "
            "parallelism is not supported")
        o = ring_attention(heads(q), heads(k), heads(v), axis,
                           prefix_len=prefix_len)
    o = o.transpose(0, 2, 1, 3).reshape(B, T, n_local * dh)
    proj = o @ p["wo"].astype(x.dtype)
    if sliced:
        proj = lax.psum(proj, tp[0])
    return x + proj


def transformer_block(name: str, d_model: int, n_heads: int, mlp_ratio: int = 4,
                      prefix_len: int = 0) -> Layer:
    """Pre-LN block; ``prefix_len`` > 0 switches the attention to the
    prefix-LM mask (the seq2seq workload, models/seq2seq.py)."""
    dh = d_model // n_heads

    def init(key, in_shape):
        T, d = in_shape
        assert d == d_model
        ks = jax.random.split(key, 6)
        p = {
            "ln1": _ln_init(d),
            "wqkv": _dense_init(ks[0], d, 3 * d),
            "wo": _dense_init(ks[1], d, d),
            "ln2": _ln_init(d),
            "w1": _dense_init(ks[2], d, mlp_ratio * d),
            "b1": jnp.zeros((mlp_ratio * d,), jnp.float32),
            "w2": _dense_init(ks[3], mlp_ratio * d, d),
            "b2": jnp.zeros((d,), jnp.float32),
        }
        return p, {}, (T, d)

    def apply(p, s, x, train):
        x = attention_sublayer(p, x, n_heads, prefix_len)
        return mlp(p, x), s

    def mlp(p, x):
        h = layer_norm(p["ln2"], x)
        h = jax.nn.gelu(h @ p["w1"].astype(x.dtype) + p["b1"].astype(x.dtype))
        proj = h @ p["w2"].astype(x.dtype)
        tp = _tp_ctx()
        # row-parallel psum ONLY when this shard holds a column slice (see
        # attention_sublayer — replicated layers compute the full MLP)
        if tp is not None and p["w1"].shape[1] < mlp_ratio * d_model:
            proj = lax.psum(proj, tp[0])
        return x + proj + p["b2"].astype(x.dtype)

    def prefill(p, s, cache, x, start):
        x, cache = attn_prefill_op(p, x, cache, n_heads, prefix_len, start)
        return mlp(p, x), cache

    def decode(p, s, cache, x, pos):
        x, cache = attn_decode_op(p, x, cache, n_heads, pos)
        return mlp(p, x), cache

    def paged_prefill(p, s, cache, x, start):
        x, cache = attn_paged_prefill_op(p, x, cache, n_heads, prefix_len,
                                         start)
        return mlp(p, x), cache

    def paged_decode(p, s, cache, x, pos):
        x, cache = attn_paged_decode_op(p, x, cache, n_heads, pos)
        return mlp(p, x), cache

    def serve_prefill(p, s, pool, table, x, start, npl, page):
        x, pool = attn_serve_prefill_op(p, x, pool, table, n_heads, start,
                                        npl, page)
        return mlp(p, x), pool

    def serve_decode(p, s, pool, table, x, pos, npl, page):
        x, pool = attn_serve_decode_op(p, x, pool, table, n_heads, pos,
                                       npl, page)
        return mlp(p, x), pool

    def serve_verify(p, s, pool, table, x, pos0, npl, page):
        x, pool = attn_serve_verify_op(p, x, pool, table, n_heads, pos0,
                                       npl, page)
        return mlp(p, x), pool

    from ddlbench_tpu.models.layers import PagedOps, ServeOps

    # serving is causal-LM only: the prefix-LM mask (seq2seq) would need the
    # per-request source length threaded through every chunk's mask
    serve = (None if prefix_len else
             ServeOps(attn_serve_pool_init(n_heads, dh),
                      serve_prefill, serve_decode, serve_verify))
    return Layer(name, init, apply, init_cache=attn_cache_init(n_heads, dh),
                 prefill=prefill, decode=decode,
                 paged=PagedOps(attn_paged_cache_init(n_heads, dh),
                                paged_prefill, paged_decode,
                                attn_paged_reorder),
                 serve=serve)


# ---------------------------------------------------------------------------
# Shared attention-sublayer cache ops (models/decode.py protocol), used by the
# dense transformer block above and the MoE block (models/moe.py).
# ---------------------------------------------------------------------------


def attn_cache_init(n_heads: int, dh: int):
    def init_cache(p, batch, max_len, dtype):
        shape = (batch, n_heads, max_len, dh)
        return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}

    return init_cache


def _qkv_heads(p, x, n_heads: int):
    B, T, d = x.shape
    dh = d // n_heads
    h = layer_norm(p["ln1"], x)
    qkv = h @ p["wqkv"].astype(x.dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    # the LOCAL head count comes from the params actually held: a TP
    # shard's wqkv is the [d, 3 * (d/tp)] column slice
    # (tp_split_layer_params), so its q/k/v carry n_heads/tp heads.
    # Unsliced params give n_local == n_heads — bitwise the old path.
    n_local = q.shape[-1] // dh
    return [t.reshape(B, T, n_local, dh).transpose(0, 2, 1, 3)
            for t in (q, k, v)]


def attn_prefill_op(p, x, cache, n_heads: int, prefix_len: int, start: int):
    """Attention sublayer (incl. residual) over a whole prompt, recording K/V.

    Attention runs only within the segment, so the prompt must start the
    stream (chunked prefill against existing cache entries is future work).
    """
    assert start == 0, "chunked prefill (start > 0) is not implemented"
    B, T, d = x.shape
    q, k, v = _qkv_heads(p, x, n_heads)
    cache = {
        "k": lax.dynamic_update_slice_in_dim(
            cache["k"], k.astype(cache["k"].dtype), start, axis=2),
        "v": lax.dynamic_update_slice_in_dim(
            cache["v"], v.astype(cache["v"].dtype), start, axis=2),
    }
    o = causal_attention(q, k, v, start, start, prefix_len=prefix_len)
    x = x + o.transpose(0, 2, 1, 3).reshape(B, T, d) @ p["wo"].astype(x.dtype)
    return x, cache


def attn_paged_cache_init(n_heads: int, dh: int):
    def init_cache(p, batch, max_len, dtype):
        from ddlbench_tpu.ops.paged_decode import paged_cache_init

        return paged_cache_init(batch, max_len, n_heads, dh, dtype)

    return init_cache


def attn_paged_prefill_op(p, x, cache, n_heads: int, prefix_len: int,
                          start: int):
    """attn_prefill_op with the K/V recorded into pages ([rows, T, H, dh]
    page layout; ops/paged_decode.py)."""
    from ddlbench_tpu.ops.paged_decode import paged_prefill_write

    assert start == 0, "chunked prefill (start > 0) is not implemented"
    B, T, d = x.shape
    q, k, v = _qkv_heads(p, x, n_heads)
    cache = paged_prefill_write(cache, k.transpose(0, 2, 1, 3),
                                v.transpose(0, 2, 1, 3))
    o = causal_attention(q, k, v, start, start, prefix_len=prefix_len)
    x = x + o.transpose(0, 2, 1, 3).reshape(B, T, d) @ p["wo"].astype(x.dtype)
    return x, cache


def attn_paged_decode_op(p, x, cache, n_heads: int, pos):
    """attn_decode_op against the paged cache: write one position into the
    row's own page slot, then single-query attention over only the LIVE
    pages (flash-decode kernel on TPU). Must be traced inside a
    ``live_pages`` segment (models/decode.py paged loops)."""
    from ddlbench_tpu.ops.paged_decode import (live_pages, paged_attention,
                                               paged_decode_write)

    B, _, d = x.shape
    q, k, v = _qkv_heads(p, x, n_heads)  # [B, H, 1, dh]
    cache = paged_decode_write(cache, k.transpose(0, 2, 1, 3),
                               v.transpose(0, 2, 1, 3), pos)
    o = paged_attention(q[:, :, 0].astype(x.dtype), cache, pos,
                        live_pages.current())  # [B, H, dh]
    x = x + o.reshape(B, 1, d) @ p["wo"].astype(x.dtype)
    return x, cache


def attn_paged_reorder(cache, parent, pos):
    from ddlbench_tpu.ops.paged_decode import paged_reorder

    return paged_reorder(cache, parent, pos)


def attn_serve_pool_init(n_heads: int, dh: int):
    def pool_init(p, n_pages, page, dtype):
        from ddlbench_tpu.ops.paged_decode import serve_pool_init

        # pool shape follows the params it serves: a TP shard's wqkv
        # column slice produces n_heads/tp heads of K/V per position, so
        # its pool slice holds exactly those. Full params keep the full
        # head count — the single-chip layout, bitwise.
        n_local = p["wqkv"].shape[1] // (3 * dh)
        return serve_pool_init(n_pages, page, n_local, dh, dtype)

    return pool_init


def _serve_proj(p, o2, x):
    """Output projection + residual shared by the serve attention ops:
    ``o2`` is the [B, T, n_local * dh] attention output. Row-parallel
    under an active tensor_parallel context when this shard holds a wo
    row slice (the attention_sublayer discipline — a replicated layer
    computes the full projection on every shard and must NOT psum)."""
    d = x.shape[-1]
    proj = o2 @ p["wo"].astype(x.dtype)
    tp = _tp_ctx()
    if tp is not None and p["wqkv"].shape[1] < 3 * d:
        proj = lax.psum(proj, tp[0])
    return x + proj


def _serve_pool_out(cache):
    """The pool dict back out of a write's cache (everything but the
    table — quantized pools carry scale sidecars + the layer's kv_seed
    alongside pool_k/pool_v, and all of it must round-trip through the
    engine's donated pool pytree)."""
    return {k: v for k, v in cache.items() if k != "table"}


def attn_serve_prefill_op(p, x, pool, table, n_heads: int, start, npl: int,
                          page: int):
    """Chunked-prefill attention sublayer for the serving engine: write the
    page-aligned chunk's K/V through the shared table, then attend the
    chunk queries against the live pages (which the table already exposes
    for positions < start). ``start`` is dynamic — the same compiled chunk
    serves every request at the same page depth."""
    from ddlbench_tpu.ops.paged_decode import (paged_chunk_attention,
                                               paged_table_chunk_write)

    B, C, d = x.shape
    q, k, v = _qkv_heads(p, x, n_heads)  # [B, H, C, dh]
    cache = {**pool, "table": table}
    cache = paged_table_chunk_write(cache, k.transpose(0, 2, 1, 3),
                                    v.transpose(0, 2, 1, 3), start, page)
    o = paged_chunk_attention(q, cache, start, npl, page)  # [B, H, C, dh]
    x = _serve_proj(p, o.transpose(0, 2, 1, 3).reshape(B, C, -1), x)
    return x, _serve_pool_out(cache)


def attn_serve_decode_op(p, x, pool, table, n_heads: int, pos, npl: int,
                         page: int):
    """attn_paged_decode_op for the serving engine: per-ROW positions and
    table-indirected writes into the shared pool (rows borrow free-list
    slots instead of owning a stripe). Inactive rows are routed to the
    scratch slot by the table the engine passes in."""
    from ddlbench_tpu.ops.paged_decode import (paged_attention,
                                               paged_table_write)

    B, _, d = x.shape
    q, k, v = _qkv_heads(p, x, n_heads)  # [B, H, 1, dh]
    cache = {**pool, "table": table}
    cache = paged_table_write(cache, k.transpose(0, 2, 1, 3),
                              v.transpose(0, 2, 1, 3), pos, page)
    o = paged_attention(q[:, :, 0].astype(x.dtype), cache, pos, npl,
                        page)  # [B, H, dh]
    x = _serve_proj(p, o.reshape(B, 1, -1), x)
    return x, _serve_pool_out(cache)


def attn_serve_verify_op(p, x, pool, table, n_heads: int, pos0, npl: int,
                         page: int):
    """Speculative-decoding verify pass: write a W-token span's K/V at
    page-UNALIGNED per-row positions [pos0, pos0 + W) through the table
    (ops/paged_decode.paged_table_span_write), then attend all W queries
    causally at their absolute positions — the multi-query chunk
    attention with per-row starts, which the chunk-prefill path already
    compiles. One call scores the pending token plus every draft; the
    engine accepts the longest prefix whose drafts match greedy argmax."""
    from ddlbench_tpu.ops.paged_decode import (paged_chunk_attention,
                                               paged_table_span_write)

    B, W, d = x.shape
    q, k, v = _qkv_heads(p, x, n_heads)  # [B, H, W, dh]
    cache = {**pool, "table": table}
    cache = paged_table_span_write(cache, k.transpose(0, 2, 1, 3),
                                   v.transpose(0, 2, 1, 3), pos0, page)
    o = paged_chunk_attention(q, cache, pos0, npl, page)  # [B, H, W, dh]
    x = _serve_proj(p, o.transpose(0, 2, 1, 3).reshape(B, W, -1), x)
    return x, _serve_pool_out(cache)


def attn_decode_op(p, x, cache, n_heads: int, pos):
    """Attention sublayer for ONE token at dynamic position pos against the
    populated cache. Every cached position <= pos, so the prefix rule needs
    no extra term: the mask is just k_pos <= pos."""
    B, _, d = x.shape
    dh = d // n_heads
    q, k, v = _qkv_heads(p, x, n_heads)  # [B, H, 1, dh]
    cache = {
        "k": lax.dynamic_update_slice_in_dim(
            cache["k"], k.astype(cache["k"].dtype), pos, axis=2),
        "v": lax.dynamic_update_slice_in_dim(
            cache["v"], v.astype(cache["v"].dtype), pos, axis=2),
    }
    kc, vc = cache["k"].astype(x.dtype), cache["v"].astype(x.dtype)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, kc) / math.sqrt(dh)
    k_pos = jnp.arange(kc.shape[2])[None, None, None, :]
    scores = jnp.where(k_pos <= pos, scores, -jnp.inf)
    o = jnp.einsum("bhqk,bhkd->bhqd",
                   jax.nn.softmax(scores.astype(jnp.float32), -1).astype(x.dtype),
                   vc)
    x = x + o.transpose(0, 2, 1, 3).reshape(B, 1, d) @ p["wo"].astype(x.dtype)
    return x, cache


def lm_head(name: str, vocab: int) -> Layer:
    def init(key, in_shape):
        T, d = in_shape
        p = {"ln_f": _ln_init(d), "head": _dense_init(key, d, vocab)}
        return p, {}, (T, vocab)

    def apply(p, s, x, train):
        h = layer_norm(p["ln_f"], x)
        return h @ p["head"].astype(x.dtype), s

    def fused_loss(p, x, labels, smoothing):
        # Projection + CE fused per row chunk: the [B*T, vocab] logits never
        # hit HBM (ops/fused_xent.py) — at vocab 32k this is the largest
        # tensor a token workload would otherwise materialize.
        from ddlbench_tpu.ops.fused_xent import fused_linear_xent

        d = x.shape[-1]
        h = layer_norm(p["ln_f"], x).reshape(-1, d)
        return fused_linear_xent(h, p["head"].astype(x.dtype),
                                 labels.reshape(-1), smoothing)

    def fused_eval(p, x, labels):
        from ddlbench_tpu.ops.fused_xent import fused_linear_xent_eval

        d = x.shape[-1]
        h = layer_norm(p["ln_f"], x).reshape(-1, d)
        return fused_linear_xent_eval(h, p["head"].astype(x.dtype),
                                      labels.reshape(-1))

    return Layer(name, init, apply, pointwise=True, fused_loss=fused_loss,
                 fused_eval=fused_eval)


def build_transformer(arch: str, in_shape, vocab: int) -> LayerModel:
    cfgv = _VARIANTS[arch]
    T = in_shape[0]
    layers: List[Layer] = [embed("embed", vocab, cfgv["d_model"], T)]
    for i in range(cfgv["n_layers"]):
        layers.append(
            transformer_block(f"block{i + 1}", cfgv["d_model"], cfgv["n_heads"])
        )
    layers.append(lm_head("lm_head", vocab))
    return LayerModel(arch, layers, tuple(in_shape), vocab, input_kind="tokens")
