"""ResNet-18/50/152 as flat layer lists, with per-dataset stems.

Capability parity with the reference's three ResNet families:
* MNIST variant — 1-channel 3x3 stride-1 stem, no maxpool, 4-window avgpool
  (benchmark/mnist/models/mnistresnet.py:68-76),
* CIFAR variant — 3x3 stride-1 stem (benchmark/cifar10/pytorchcifargitmodels/resnet.py),
* ImageNet/highres variant — torchvision-style 7x7 stride-2 stem + 3x3 maxpool
  (benchmark/imagenet/imagenet_pytorch.py:19-30 uses torchvision.models).

One builder serves all strategies; each residual block is one pipeline-atomic
Layer (see models/layers.py).
"""

from __future__ import annotations

from typing import List

from ddlbench_tpu.models.layers import (
    Layer,
    LayerModel,
    basic_block,
    bottleneck_block,
    conv_bn,
    dense,
    global_avg_pool,
    max_pool,
)

# (block_kind, per-group block counts)
_DEPTHS = {
    "resnet18": ("basic", [2, 2, 2, 2]),
    "resnet50": ("bottleneck", [3, 4, 6, 3]),
    "resnet152": ("bottleneck", [3, 8, 36, 3]),
}
_WIDTHS = [64, 128, 256, 512]


def build_resnet(arch: str, in_shape, num_classes: int) -> LayerModel:
    kind, counts = _DEPTHS[arch]
    small_input = in_shape[0] <= 64  # mnist/cifar stems keep resolution

    layers: List[Layer] = []
    if small_input:
        layers.append(conv_bn("stem", 64, kernel=3, stride=1))
    else:
        layers.append(conv_bn("stem", 64, kernel=7, stride=2))
        layers.append(max_pool("stem_pool", window=3, stride=2, padding="SAME"))

    for group, (width, n_blocks) in enumerate(zip(_WIDTHS, counts)):
        for b in range(n_blocks):
            stride = 2 if (b == 0 and group > 0) else 1
            name = f"group{group + 1}_block{b + 1}"
            if kind == "basic":
                layers.append(basic_block(name, width, stride))
            else:
                layers.append(bottleneck_block(name, width, stride))

    layers.append(global_avg_pool())
    layers.append(dense("fc", num_classes))
    return LayerModel(name=arch, layers=layers, in_shape=tuple(in_shape), num_classes=num_classes)
