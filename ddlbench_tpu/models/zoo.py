"""Model registry: (arch, dataset) -> LayerModel.

Replaces the reference's three parallel model families and per-dataset
directories (SURVEY.md §2 B5-B7) with one registry; the dataset spec chooses
the stem/classifier variant.
"""

from __future__ import annotations

from ddlbench_tpu.config import DATASETS, DatasetSpec
from ddlbench_tpu.models.layers import LayerModel
from ddlbench_tpu.models.mobilenetv2 import build_mobilenetv2
from ddlbench_tpu.models.resnet import build_resnet
from ddlbench_tpu.models.vgg import build_vgg

MODEL_NAMES = ("resnet18", "resnet50", "resnet152", "vgg11", "vgg16",
               "mobilenetv2", "lenet", "alexnet", "squeezenet", "resnext50",
               "densenet121", "inception", "nasnet", "transformer_t",
               "transformer_s",
               "transformer_m", "transformer_moe_s", "seq2seq_s", "seq2seq_m",
               "seq2seq_lstm_s")


def get_model(arch: str, dataset: str | DatasetSpec,
              moe_capacity_factor: float = 1.25) -> LayerModel:
    spec = dataset if isinstance(dataset, DatasetSpec) else DATASETS[dataset]
    if arch.startswith("seq2seq"):
        if spec.kind != "seq2seq":
            raise ValueError(f"{arch} requires a seq2seq dataset, got {spec.name}")
        if "lstm" in arch:
            # recurrent (GNMT-class) variant, scan-based (models/lstm.py)
            from ddlbench_tpu.models.lstm import build_lstm_seq2seq

            return build_lstm_seq2seq(arch, spec.image_size,
                                      spec.num_classes, spec.src_len)
        from ddlbench_tpu.models.seq2seq import build_seq2seq

        return build_seq2seq(arch, spec.image_size, spec.num_classes,
                             spec.src_len)
    if arch.startswith("transformer"):
        if spec.kind != "tokens":
            raise ValueError(f"{arch} requires a token dataset, got {spec.name}")
        if "moe" in arch:
            from ddlbench_tpu.models.moe import build_transformer_moe

            return build_transformer_moe(
                arch, spec.image_size, spec.num_classes,
                capacity_factor=moe_capacity_factor,
            )
        from ddlbench_tpu.models.transformer import build_transformer

        return build_transformer(arch, spec.image_size, spec.num_classes)
    if spec.kind != "image":
        raise ValueError(f"{arch} requires an image dataset, got {spec.name}")
    if arch.startswith(("inception", "nasnet")):
        # branchy DAG archs: strategies run the articulation-block chain
        # form; the auto-partition path profiles the real DAG
        # (models/branchy.py). nasnet's two-input cells make its DAG
        # non-series-parallel, unlike inception's SP modules.
        from ddlbench_tpu.models.branchy import get_dag, to_chain

        dag = get_dag(arch, spec.image_size, spec.num_classes)
        if dag is None:
            raise ValueError(f"unknown branchy arch {arch!r}")
        return to_chain(dag)
    if arch.startswith("resnet"):
        return build_resnet(arch, spec.image_size, spec.num_classes)
    if arch.startswith("vgg"):
        return build_vgg(arch, spec.image_size, spec.num_classes)
    if arch == "mobilenetv2":
        return build_mobilenetv2(arch, spec.image_size, spec.num_classes)
    from ddlbench_tpu.models.extra import BUILDERS as _EXTRA

    if arch in _EXTRA:
        return _EXTRA[arch](spec.image_size, spec.num_classes)
    raise ValueError(f"unknown arch {arch!r}; known: {MODEL_NAMES}")
