"""KV-cached incremental decoding over the flat layer chain.

The full-forward decoders in models/seq2seq.py re-run the entire model per
emitted token (O(T) forwards of length T). This module is the TPU-native
fast path: one **prefill** pass processes the prompt and populates per-block
K/V caches, then each generated token runs a single-position **decode** pass
against the caches — O(T) attention reads instead of a full forward. The
protocol is three optional fields on ``Layer`` (models/layers.py): attention
blocks provide ``init_cache``/``prefill``/``decode``; position-embedding
layers provide ``decode``; position-independent layers (``pointwise=True``,
e.g. the LM head) are decoded through their ordinary ``apply``.

Reference context: GNMT's beam-search inference (SURVEY.md §2 C13) keeps
LSTM hidden state between steps — the KV cache is the transformer analog of
that recurrent state. For dense models both decoders below produce
token-identical streams to their full-forward counterparts
(tests/test_decode.py).

MoE blocks implement the protocol too (models/moe.py): decode runs each
token's top-1 expert without a capacity limit (standard MoE inference),
while prefill keeps the training-style capacity over the prompt tokens.
This equals the full-forward path whenever routing capacity drops nothing
(always true with a generous capacity_factor); with tight capacity the two
paths can legitimately differ — the full-forward loop also pads the stream,
which itself perturbs MoE routing. ``supports_cache`` reports whether a
model can take the cached path.
"""

from __future__ import annotations

from typing import Any, List, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ddlbench_tpu.models.layers import LayerModel


def supports_cache(model: LayerModel) -> bool:
    """True if every layer can participate in cached decoding."""
    return all(
        l.decode is not None or l.pointwise for l in model.layers
    )


def _require_cache_support(model: LayerModel) -> None:
    if not supports_cache(model):
        missing = [l.name for l in model.layers
                   if l.decode is None and not l.pointwise]
        raise NotImplementedError(
            f"{model.name} has layers without cached-decode support: "
            f"{missing}; use the full-forward decoders instead"
        )


def init_caches(model: LayerModel, params, batch: int, max_len: int,
                dtype) -> List[Any]:
    return [
        l.init_cache(p, batch, max_len, dtype) if l.init_cache else None
        for l, p in zip(model.layers, params)
    ]


def prefill(model: LayerModel, params, state, caches, tokens):
    """Run the prompt [B, S] through the chain, populating caches from 0.

    Returns (logits [B, S, V], caches).
    """
    h = tokens
    out = []
    for layer, p, s, c in zip(model.layers, params, state, caches):
        if layer.prefill is not None:
            h, c = layer.prefill(p, s, c, h, 0)
        else:
            h, _ = layer.apply(p, s, h, False)
        out.append(c)
    return h, out


def decode_one(model: LayerModel, params, state, caches, tok, pos):
    """Run ONE token [B, 1] at dynamic position pos. Returns (logits, caches)."""
    h = tok
    out = []
    for layer, p, s, c in zip(model.layers, params, state, caches):
        if layer.decode is not None:
            h, c = layer.decode(p, s, c, h, pos)
        else:
            h, _ = layer.apply(p, s, h, False)
        out.append(c)
    return h, out


# ---------------------------------------------------------------------------
# Paged-cache variants (ops/paged_decode.py): copy-on-write beam reorder +
# live-page-only attention. The decode loops run in one-page SEGMENTS so the
# page count each attention kernel walks is static (live_pages context).
# ---------------------------------------------------------------------------


def supports_paged(model: LayerModel) -> bool:
    """True if every cache-allocating layer provides the paged protocol."""
    return supports_cache(model) and all(
        l.paged is not None for l in model.layers if l.init_cache is not None
    )


def _require_paged_support(model: LayerModel) -> None:
    if not supports_paged(model):
        missing = [l.name for l in model.layers
                   if l.init_cache is not None and l.paged is None]
        raise NotImplementedError(
            f"{model.name} has cached layers without paged-decode support: "
            f"{missing or 'cached path unsupported'}; use paged=False"
        )


def init_paged_caches(model: LayerModel, params, batch: int, max_len: int,
                      dtype):
    return [
        l.paged.init_cache(p, batch, max_len, dtype) if l.paged else None
        for l, p in zip(model.layers, params)
    ]


def paged_prefill(model: LayerModel, params, state, caches, tokens):
    h = tokens
    out = []
    for layer, p, s, c in zip(model.layers, params, state, caches):
        if layer.paged is not None:
            h, c = layer.paged.prefill(p, s, c, h, 0)
        elif layer.prefill is not None:
            h, c = layer.prefill(p, s, c, h, 0)
        else:
            h, _ = layer.apply(p, s, h, False)
        out.append(c)
    return h, out


def paged_decode_one(model: LayerModel, params, state, caches, tok, pos):
    h = tok
    out = []
    for layer, p, s, c in zip(model.layers, params, state, caches):
        if layer.paged is not None:
            h, c = layer.paged.decode(p, s, c, h, pos)
        elif layer.decode is not None:
            h, c = layer.decode(p, s, c, h, pos)
        else:
            h, _ = layer.apply(p, s, h, False)
        out.append(c)
    return h, out


def paged_reorder_caches(model: LayerModel, caches, parent, pos):
    # in paged mode every layer either has PagedOps (_require_paged_support)
    # or carries no cache at all (init_paged_caches gives it None)
    return [
        l.paged.reorder(c, parent, pos) if l.paged is not None else None
        for l, c in zip(model.layers, caches)
    ]


def _segmented_fori(start: int, stop: int, body, carry):
    """fori_loop over [start, stop) split at page boundaries, each segment
    traced under live_pages(p + 1) so paged attention sees a static page
    count. Equivalent to lax.fori_loop(start, stop, body, carry).

    Each segment wraps ``body`` in a FRESH function object: fori_loop caches
    the traced body by function identity + avals, and the live-page count is
    a trace-time constant invisible to that cache — reusing ``body`` would
    silently run every segment with the first segment's page count
    (measured: tokens past the first boundary attended only the stale page
    range)."""
    from jax import lax

    from ddlbench_tpu.ops.paged_decode import PAGE, live_pages

    for p in range(start // PAGE, (stop - 1) // PAGE + 1):
        lo, hi = max(start, p * PAGE), min(stop, (p + 1) * PAGE)
        if lo >= hi:
            continue

        def seg_body(t, c, _npl=p + 1):
            with live_pages(_npl):
                return body(t, c)

        carry = lax.fori_loop(lo, hi, seg_body, carry)
    return carry


def _start_len(model: LayerModel, src) -> int:
    if model.src_len is not None and src.shape[1] != model.src_len:
        raise ValueError(
            f"src must be [B, {model.src_len}] for {model.name}, "
            f"got {tuple(src.shape)}"
        )
    return src.shape[1]


def greedy_decode(model: LayerModel, params, state, src, total_len: int,
                  dtype=jnp.float32, paged: bool = False):
    """KV-cached greedy continuation of `src` [B, S] to length `total_len`.

    Token-identical to models/seq2seq.greedy_decode's full-forward loop for
    dense models (MoE caveat: see module docstring). ``paged=True`` uses the
    paged cache (attention reads only the live pages — ops/paged_decode.py);
    greedy never reorders, so the win is the read traffic alone.
    """
    if paged:
        _require_paged_support(model)
    else:
        _require_cache_support(model)
    S = _start_len(model, src)
    T = model.in_shape[0]
    if not S < total_len <= T:
        raise ValueError(f"total_len must be in ({S}, {T}], got {total_len}")
    B = src.shape[0]

    if paged:
        caches = init_paged_caches(model, params, B, total_len, dtype)
        logits, caches = paged_prefill(model, params, state, caches, src)
    else:
        caches = init_caches(model, params, B, total_len, dtype)
        logits, caches = prefill(model, params, state, caches, src)
    first = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
    x0 = (jnp.zeros((B, total_len), jnp.int32)
          .at[:, :S].set(src).at[:, S].set(first))
    step = paged_decode_one if paged else decode_one

    def body(t, carry):
        x, caches = carry
        tok = lax.dynamic_slice_in_dim(x, t, 1, axis=1)
        logits, caches = step(model, params, state, caches, tok, t)
        nxt = jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)
        return lax.dynamic_update_slice_in_dim(
            x, nxt[:, None], t + 1, axis=1), caches

    loop = _segmented_fori if paged else lax.fori_loop
    x, _ = loop(S, total_len - 1, body, (x0, caches))
    return x


def beam_search_decode(model: LayerModel, params, state, src, total_len: int,
                       beam: int = 4, length_penalty: float = 0.6,
                       dtype=jnp.float32, paged: bool = False):
    """KV-cached beam search; same semantics/scores as
    models/seq2seq.beam_search_decode (length-normalized, GNMT-style).

    Caches are kept per hypothesis ([B*beam, ...]) and follow the parent
    beam at every expansion — the transformer analog of reordering GNMT's
    recurrent decoder state. Default: a physical gather of every cache.
    ``paged=True``: copy-on-write page tables (ops/paged_decode.py) — the
    reorder moves pointers plus one partial page instead of the full cache,
    and attention reads only the live pages. Token-identical to the dense
    path in f32.
    """
    if paged:
        _require_paged_support(model)
    else:
        _require_cache_support(model)
    S = _start_len(model, src)
    T = model.in_shape[0]
    if not S < total_len <= T:
        raise ValueError(f"total_len must be in ({S}, {T}], got {total_len}")
    B = src.shape[0]
    V = model.num_classes

    src_rep = jnp.repeat(src, beam, axis=0)
    if paged:
        caches = init_paged_caches(model, params, B * beam, total_len, dtype)
        logits, caches = paged_prefill(model, params, state, caches, src_rep)
    else:
        caches = init_caches(model, params, B * beam, total_len, dtype)
        logits, caches = prefill(model, params, state, caches, src_rep)
    logits_prev = logits[:, -1]  # [B*beam, V]

    x0 = jnp.zeros((B * beam, total_len), jnp.int32).at[:, :S].set(src_rep)
    score0 = jnp.where(
        jnp.arange(B * beam) % beam == 0, 0.0, -jnp.inf
    ).astype(jnp.float32)

    def gather_caches(caches, idx):
        return jax.tree.map(lambda a: a[idx], caches)

    def expand(x, score, logits_prev, t):
        """One beam expansion at position t; returns (x, score, flat_src)."""
        logp = jax.nn.log_softmax(logits_prev.astype(jnp.float32), -1)
        cand = (score[:, None] + logp).reshape(B, beam * V)
        top_score, top_idx = lax.top_k(cand, beam)
        beam_src = top_idx // V
        token = (top_idx % V).astype(jnp.int32)
        flat_src = (jnp.arange(B)[:, None] * beam + beam_src).reshape(-1)
        x = lax.dynamic_update_slice_in_dim(
            x[flat_src], token.reshape(-1)[:, None], t, axis=1)
        return x, top_score.reshape(-1), flat_src

    step = paged_decode_one if paged else decode_one

    def body(t, carry):
        x, score, caches, logits_prev = carry
        x, score, flat_src = expand(x, score, logits_prev, t)
        if paged:
            caches = paged_reorder_caches(model, caches, flat_src, t)
        else:
            caches = gather_caches(caches, flat_src)
        tok = lax.dynamic_slice_in_dim(x, t, 1, axis=1)
        logits, caches = step(model, params, state, caches, tok, t)
        return x, score, caches, logits[:, 0]

    # The last position needs only the expansion — no decode_one afterwards
    # (its logits would be discarded), so the loop stops one early.
    loop = _segmented_fori if paged else lax.fori_loop
    x, score, _, logits_prev = loop(
        S, total_len - 1, body, (x0, score0, caches, logits_prev))
    x, score, _ = expand(x, score, logits_prev, total_len - 1)
    norm = ((5.0 + (total_len - S)) / 6.0) ** length_penalty
    score = (score / norm).reshape(B, beam)
    best = jnp.argmax(score, axis=-1)
    x = x.reshape(B, beam, total_len)[jnp.arange(B), best]
    return x, score[jnp.arange(B), best]
