"""DAG-of-layers models: native multi-branch profiling and execution.

The reference's tracer produces real DAGs from arbitrary models —
TensorWrapper threads dataflow through overloaded ops
(pipedream-fork/profiler/torchmodules/torchgraph/graph_creator.py:55-195) —
which is how branchy profiles like resnext50_generated.txt exist, and its
inception family (profiler/image_classification/models/inception.py:1) is
the canonical branchy workload. Here the dataflow is DECLARED, not traced:
a ``DagModel`` lists each layer's predecessor indices and join rule, the
profiler (profiler/profile.profile_dag) emits the real branchy Graph from
it, and the graph machinery (is_series_parallel, compress_branches,
antichain partitioning) runs on native profiles instead of only imported
fixtures.

Execution stays engine-compatible: ``to_chain`` cuts the DAG at its
articulation positions (cuts crossed by exactly ONE tensor) and wraps each
span into a composite Layer — the pipeline engines see a flat chain whose
boundaries are single activations, so every strategy (single/dp/gpipe/
pipedream/hetero) runs branchy models unchanged, and partition bounds over
the coarse block chain map 1:1 onto the chain model's layers.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ddlbench_tpu.models.layers import (
    Layer, LayerModel, Shape, avg_pool, conv_bn, dense, flatten,
    global_avg_pool, max_pool, sep_conv_bn)


@dataclasses.dataclass(frozen=True)
class DagModel:
    """A model as a DAG of layers in topological (list) order.

    ``inputs[i]`` are the predecessor layer indices feeding layer i (-1 is
    the model input); multi-input nodes combine predecessor outputs with
    ``combine[i]`` ("concat" over channels, or "add") before apply.
    """

    name: str
    layers: List[Layer]
    inputs: List[Tuple[int, ...]]
    combine: List[str]
    in_shape: Shape
    num_classes: int
    input_kind: str = "float"

    def __post_init__(self):
        for i, preds in enumerate(self.inputs):
            assert all(p < i for p in preds), (
                f"node {i} has a non-topological input {preds}")
            assert len(preds) == 1 or self.combine[i] in ("concat", "add")


def _combined_shape(shapes: Sequence[Shape], how: str) -> Shape:
    if len(shapes) == 1:
        return shapes[0]
    if how == "concat":
        base = shapes[0][:-1]
        assert all(s[:-1] == base for s in shapes), shapes
        return (*base, sum(s[-1] for s in shapes))
    assert all(s == shapes[0] for s in shapes), shapes
    return shapes[0]


def _combine(vals, how: str):
    if len(vals) == 1:
        return vals[0]
    if how == "concat":
        return jnp.concatenate(vals, axis=-1)
    total = vals[0]
    for v in vals[1:]:
        total = total + v
    return total


def init_dag(model: DagModel, key: jax.Array):
    """Initialize every node. Returns (params_list, state_list, out_shapes)
    where out_shapes[i] is node i's per-example output shape."""
    params_list, state_list, out_shapes = [], [], []
    for i, layer in enumerate(model.layers):
        in_sh = _combined_shape(
            [model.in_shape if p < 0 else out_shapes[p]
             for p in model.inputs[i]], model.combine[i])
        key, sub = jax.random.split(key)
        p, s, out_sh = layer.init(sub, in_sh)
        params_list.append(p)
        state_list.append(s)
        out_shapes.append(out_sh)
    return params_list, state_list, out_shapes


def apply_dag(model: DagModel, params, states, x, train: bool):
    """Topological fold; returns (last node's output, new_states)."""
    outs, new_states = [], []
    for i, layer in enumerate(model.layers):
        xin = _combine([x if p < 0 else outs[p] for p in model.inputs[i]],
                       model.combine[i])
        y, ns = layer.apply(params[i], states[i], xin, train)
        outs.append(y)
        new_states.append(ns)
    return outs[-1], new_states


def cut_positions(model: DagModel) -> List[int]:
    """Positions p (0 < p < n) where the DAG can be cut into [0,p) | [p,n)
    with exactly ONE tensor crossing — i.e. all edges from {<p} (or the
    model input) into {>=p} share a single source. These are the boundaries
    every chain pipeline engine can host."""
    n = len(model.layers)
    cuts = []
    for p in range(1, n):
        sources = set()
        for d in range(p, n):
            for s in model.inputs[d]:
                if s < p:
                    sources.add(s)
        if len(sources) == 1:
            cuts.append(p)
    return cuts


def block_spans(model: DagModel) -> List[Tuple[int, int]]:
    """Contiguous node spans between consecutive articulation cuts — the
    atomic pipeline blocks of the DAG."""
    bounds = [0] + cut_positions(model) + [len(model.layers)]
    return list(zip(bounds[:-1], bounds[1:]))


def _composite_layer(model: DagModel, start: int, end: int) -> Layer:
    """Wrap DAG span [start, end) into one chain Layer. Valid only when the
    span's external inputs all come from one source (guaranteed when start
    is an articulation cut): that source's tensor IS the layer input."""
    span = list(range(start, end))
    name = f"{model.layers[start].name}..{model.layers[end - 1].name}" \
        if end - start > 1 else model.layers[start].name

    def init(key, in_shape):
        params, states, shapes = [], [], {}

        def shape_of(p):
            return in_shape if p < start else shapes[p]

        for i in span:
            in_sh = _combined_shape([shape_of(p) for p in model.inputs[i]],
                                    model.combine[i])
            key, sub = jax.random.split(key)
            pp, ss, out_sh = model.layers[i].init(sub, in_sh)
            params.append(pp)
            states.append(ss)
            shapes[i] = out_sh
        return params, states, shapes[end - 1]

    def apply(params, states, x, train):
        outs, new_states = {}, []
        for k, i in enumerate(span):
            xin = _combine([x if p < start else outs[p]
                            for p in model.inputs[i]], model.combine[i])
            y, ns = model.layers[i].apply(params[k], states[k], xin, train)
            outs[i] = y
            new_states.append(ns)
        return outs[end - 1], new_states

    return Layer(name, init, apply)


def to_chain(model: DagModel) -> LayerModel:
    """DAG -> flat LayerModel of composite block layers (one per span
    between articulation cuts) — runnable by every strategy unchanged.
    Chain layer k corresponds exactly to block k of the profiled coarse
    chain (partition bounds transfer 1:1)."""
    layers = [_composite_layer(model, a, b) for a, b in block_spans(model)]
    return LayerModel(model.name, layers, model.in_shape, model.num_classes,
                      input_kind=model.input_kind)


# ---- inception family ------------------------------------------------------


def _identity(name: str) -> Layer:
    def init(key, in_shape):
        return {}, {}, in_shape

    def apply(params, state, x, train):
        return x, state

    return Layer(name, init, apply)


def _append(layers, inputs, combine, layer, preds, how="") -> int:
    """Add one DAG node; returns its index."""
    layers.append(layer)
    inputs.append(tuple(preds))
    combine.append(how)
    return len(layers) - 1


def _add_inception_block(layers, inputs, combine, pred: int, name: str,
                         ch1: int, ch3r: int, ch3: int, ch5r: int, ch5: int,
                         pool_proj: int) -> int:
    """Append one GoogLeNet inception module (4 parallel branches joined by
    channel concat — reference inception.py's InceptionModule) reading from
    node ``pred``. Returns the join node's index."""

    def add(layer, preds, how=""):
        return _append(layers, inputs, combine, layer, preds, how)

    b1 = add(conv_bn(f"{name}_1x1", ch1, kernel=1), [pred])
    b3a = add(conv_bn(f"{name}_3x3r", ch3r, kernel=1), [pred])
    b3 = add(conv_bn(f"{name}_3x3", ch3, kernel=3), [b3a])
    b5a = add(conv_bn(f"{name}_5x5r", ch5r, kernel=1), [pred])
    b5 = add(conv_bn(f"{name}_5x5", ch5, kernel=5), [b5a])
    bp = add(max_pool(f"{name}_pool", window=3, stride=1, padding="SAME"),
             [pred])
    bpp = add(conv_bn(f"{name}_poolproj", pool_proj, kernel=1), [bp])
    return add(_identity(f"{name}_concat"), [b1, b3, b5, bpp], "concat")


_INCEPTION_BLOCKS = {
    # (ch1, ch3r, ch3, ch5r, ch5, pool_proj) — GoogLeNet table 1 widths
    "inception": [(64, 96, 128, 16, 32, 32), (128, 128, 192, 32, 96, 64),
                  (192, 96, 208, 16, 48, 64), (160, 112, 224, 24, 64, 64)],
    # tiny test variant
    "inception_t": [(8, 8, 8, 4, 4, 4), (8, 8, 8, 4, 4, 4)],
}


def build_inception(arch: str, in_shape, num_classes: int) -> DagModel:
    """Mini GoogLeNet as a declared DAG (stem -> inception modules with a
    mid maxpool -> gap/fc). Branch widths follow the reference's inception
    family (profiler/image_classification/models/inception.py:1); depth is
    reduced to 4 modules (documented mini — the benchmark exercises branchy
    structure, not ILSVRC accuracy)."""
    layers: List[Layer] = []
    inputs: List[Tuple[int, ...]] = []
    combine: List[str] = []

    def add(layer, preds, how=""):
        return _append(layers, inputs, combine, layer, preds, how)

    small = in_shape[0] <= 64
    stem_ch = 16 if arch == "inception_t" else 64
    cur = add(conv_bn("stem", stem_ch, kernel=3 if small else 7,
                      stride=1 if small else 2), [-1])
    if not small:
        cur = add(max_pool("stem_pool", window=3, stride=2, padding="SAME"),
                  [cur])
    blocks = _INCEPTION_BLOCKS[arch]
    for i, spec in enumerate(blocks):
        cur = _add_inception_block(layers, inputs, combine, cur,
                                   f"inc{i}", *spec)
        if i == len(blocks) // 2 - 1:
            cur = add(max_pool(f"mid_pool{i}", window=3, stride=2,
                               padding="SAME"), [cur])
    cur = add(global_avg_pool(), [cur])
    cur = add(flatten(), [cur])
    add(dense("fc", num_classes), [cur])
    return DagModel(arch, layers, inputs, combine, tuple(in_shape),
                    num_classes)


# ---- packed chain form: multi-tensor pipeline boundaries -------------------


def crossing_ids(model: DagModel, p: int) -> List[int]:
    """Ids whose output crosses the cut before node ``p`` (consumed by some
    node >= p); -1 is the model input. Sorted ascending."""
    n = len(model.layers)
    return sorted({pid for j in range(p, n) for pid in model.inputs[j]
                   if pid < p})


def _flat_size(shape: Shape) -> int:
    out = 1
    for d in shape:
        out *= int(d)
    return out


def to_packed_chain(model: DagModel, cuts: Sequence[int],
                    out_shapes: Optional[Sequence[Shape]] = None
                    ) -> LayerModel:
    """Chain form with ARBITRARY cut positions: every tensor crossing a cut
    is flattened and concatenated into ONE [B, N] boundary buffer, which the
    next span unpacks. This is the TPU-native answer to the reference
    runtime's multi-tensor stage edges (StageRuntime sends each crossing
    tensor separately, runtime.py:193-223): the engines' single-activation
    pipeline machinery (buffers, ppermute, conveyor) runs unchanged, and a
    cut no longer needs to be an articulation position — nasnet's cell
    stack, where two tensors cross every cell boundary, partitions at cell
    (or any) granularity instead of packing into one block (to_chain).

    ``cuts`` are node positions strictly inside (0, n); the result has
    len(cuts)+1 composite layers, one per span, with stage_bounds
    [0, 1, ..., len(cuts)+1] mapping spans to stages 1:1. ``out_shapes``
    (per-node output shapes) skips the shape-inference init when the
    caller already has them (profile_dag(return_shapes=True)).
    """
    n = len(model.layers)
    cuts = sorted(set(int(c) for c in cuts))
    assert all(0 < c < n for c in cuts), f"cuts {cuts} outside (0, {n})"
    assert model.input_kind == "float", (
        "packed boundaries concatenate in the compute dtype; token inputs "
        "(int ids) would need a cast-free side channel")
    if out_shapes is None:
        # one shape-inference pass; shapes are key-independent
        _, _, out_shapes = init_dag(model, jax.random.key(0))

    def shape_of(pid: int) -> Shape:
        return model.in_shape if pid < 0 else tuple(out_shapes[pid])

    bounds = [0, *cuts, n]
    span_layers: List[Layer] = []
    for k in range(len(bounds) - 1):
        a, b = bounds[k], bounds[k + 1]
        in_ids = crossing_ids(model, a) if a > 0 else [-1]
        out_ids = crossing_ids(model, b) if b < n else None
        span_layers.append(
            _packed_span(model, a, b, in_ids, out_ids, shape_of))
    return LayerModel(f"{model.name}_packed", span_layers, model.in_shape,
                      model.num_classes, input_kind=model.input_kind)


def _packed_span(model: DagModel, a: int, b: int, in_ids: List[int],
                 out_ids, shape_of) -> Layer:
    """Composite Layer for DAG span [a, b): unpack crossing inputs, run the
    span's nodes, pack crossing outputs (final span returns raw output)."""
    in_shapes = [shape_of(i) for i in in_ids]
    in_sizes = [_flat_size(s) for s in in_shapes]

    def init(key, in_shape):
        if a > 0:
            assert tuple(in_shape) == (sum(in_sizes),), (in_shape, in_sizes)
        ps, ss = [], []
        for i in range(a, b):
            key, sub = jax.random.split(key)
            node_in = _combined_shape(
                [shape_of(p) for p in model.inputs[i]], model.combine[i])
            p_, s_, o_ = model.layers[i].init(sub, node_in)
            assert tuple(o_) == shape_of(i), (i, o_, shape_of(i))
            ps.append(p_)
            ss.append(s_)
        if out_ids is None:
            out_sh = shape_of(b - 1)
        else:
            out_sh = (sum(_flat_size(shape_of(i)) for i in out_ids),)
        return ps, ss, out_sh

    def apply(params, states, x, train):
        B = x.shape[0]
        env = {}
        if a == 0:
            env[-1] = x
        else:
            off = 0
            for pid, sh, sz in zip(in_ids, in_shapes, in_sizes):
                env[pid] = x[:, off:off + sz].reshape(B, *sh)
                off += sz
        new_states = []
        for idx, i in enumerate(range(a, b)):
            xin = _combine([env[p] for p in model.inputs[i]],
                           model.combine[i])
            y, ns = model.layers[i].apply(params[idx], states[idx], xin,
                                          train)
            env[i] = y
            new_states.append(ns)
        if out_ids is None:
            return env[b - 1], new_states
        packed = jnp.concatenate(
            [env[i].reshape(B, -1) for i in out_ids], axis=1)
        return packed, new_states

    # the span's flat packed boundary hides the compute geometry from the
    # analytic FLOP heuristic (spatial would read as 1); advertise the
    # span's true spatial scale. Single-node spans (the manual pipeline
    # path) keep the scalar form — exact. Multi-node spans carry the full
    # per-node tuple so layer_flop_costs can sum exact per-node costs; a
    # max over a span mixing large-spatial convs with dense nodes would
    # over-weight it (ADVICE r3).
    per_node = tuple(
        _flat_size(shape_of(i)[:-1]) if len(shape_of(i)) > 1 else 1
        for i in range(a, b))
    spatial = per_node[0] if len(per_node) == 1 else per_node
    return Layer(f"{model.name}_span{a}_{b}", init, apply,
                 cost_spatial=spatial)


# ---- nasnet family ---------------------------------------------------------
#
# NASNet-A-style cells (reference family: profiler/image_classification/
# models/nasnet.py:1). The structural property that matters for the
# partitioner is that every cell reads the previous TWO cell outputs — the
# skip-over-a-cell edges make the graph NOT series-parallel (inception's
# fan-out/fan-in modules are SP), so antichain partitioning and
# is_series_parallel get a genuinely harder native workload. Depth/width are
# reduced (documented mini, like build_inception); block wiring follows the
# NASNet-A normal/reduction cells with the paired sep-conv applied once.


def _add_nasnet_normal(layers, inputs, combine, prev: int, cur: int,
                       name: str, ch: int, adj_stride: int = 1) -> int:
    """One normal cell reading (h_{i-2}=prev, h_{i-1}=cur); returns the
    5-block concat node (5*ch channels). ``adj_stride=2`` folds the
    factorized reduction of a lagging prev into its 1x1 adjust."""

    def add(layer, preds, how=""):
        return _append(layers, inputs, combine, layer, preds, how)

    def pair(tag, left, right):
        return add(_identity(f"{name}_{tag}"), [left, right], "add")

    p = add(conv_bn(f"{name}_adjP", ch, kernel=1, stride=adj_stride), [prev])
    c = add(conv_bn(f"{name}_adjC", ch, kernel=1), [cur])
    b1 = pair("b1", add(sep_conv_bn(f"{name}_b1_sep3", ch, 3), [c]), c)
    b2 = pair("b2", add(sep_conv_bn(f"{name}_b2_sep3", ch, 3), [p]),
              add(sep_conv_bn(f"{name}_b2_sep5", ch, 5), [c]))
    b3 = pair("b3", add(avg_pool(f"{name}_b3_avg"), [c]), p)
    b4 = pair("b4", add(avg_pool(f"{name}_b4_avgA"), [p]),
              add(avg_pool(f"{name}_b4_avgB"), [p]))
    b5 = pair("b5", add(sep_conv_bn(f"{name}_b5_sep5", ch, 5), [p]),
              add(sep_conv_bn(f"{name}_b5_sep3", ch, 3), [p]))
    return add(_identity(f"{name}_concat"), [b1, b2, b3, b4, b5], "concat")


def _add_nasnet_reduction(layers, inputs, combine, prev: int, cur: int,
                          name: str, ch: int, adj_stride: int = 1) -> int:
    """One reduction cell (spatial /2); returns the 4-block concat node
    (4*ch channels). ``adj_stride`` as in _add_nasnet_normal."""

    def add(layer, preds, how=""):
        return _append(layers, inputs, combine, layer, preds, how)

    def pair(tag, left, right):
        return add(_identity(f"{name}_{tag}"), [left, right], "add")

    p = add(conv_bn(f"{name}_adjP", ch, kernel=1, stride=adj_stride), [prev])
    c = add(conv_bn(f"{name}_adjC", ch, kernel=1), [cur])
    b1 = pair("b1", add(sep_conv_bn(f"{name}_b1_sep5", ch, 5, 2), [c]),
              add(sep_conv_bn(f"{name}_b1_sep7", ch, 7, 2), [p]))
    b2 = pair("b2", add(max_pool(f"{name}_b2_max", 3, 2, "SAME"), [c]),
              add(sep_conv_bn(f"{name}_b2_sep7", ch, 7, 2), [p]))
    b3 = pair("b3", add(avg_pool(f"{name}_b3_avg", 3, 2), [c]),
              add(sep_conv_bn(f"{name}_b3_sep5", ch, 5, 2), [p]))
    b4 = pair("b4", add(max_pool(f"{name}_b4_max", 3, 2, "SAME"), [c]),
              add(sep_conv_bn(f"{name}_b4_sep3", ch, 3), [b1]))
    return add(_identity(f"{name}_concat"), [b1, b2, b3, b4], "concat")


_NASNET_SPECS = {
    # (stem channels, cell filter count, cell sequence: N=normal, R=reduce;
    # filters double at each reduction — NASNet-A scheme, reduced depth)
    "nasnet": (32, 44, "NNRNNRNN"),
    # tiny test variant
    "nasnet_t": (8, 8, "NRN"),
}


def build_nasnet(arch: str, in_shape, num_classes: int) -> DagModel:
    """NASNet-A-style mini as a declared DAG: stem, then cells over the
    previous two cell outputs; prev is spatially adjusted with a strided
    1x1 after each reduction (the paper's factorized reduction,
    simplified)."""
    stem_ch, ch, cells = _NASNET_SPECS[arch]
    layers: List[Layer] = []
    inputs: List[Tuple[int, ...]] = []
    combine: List[str] = []

    def add(layer, preds, how=""):
        return _append(layers, inputs, combine, layer, preds, how)

    small = in_shape[0] <= 64
    stem = add(conv_bn("stem", stem_ch, kernel=3, stride=1 if small else 2),
               [-1])
    prev = cur = stem
    prev_lags = False  # prev has 2x the spatial extent of cur
    for i, kind in enumerate(cells):
        # a lagging prev (last cell was a reduction) is spatially adjusted
        # by striding its own 1x1 adjust — the paper's factorized
        # reduction, folded into the cell
        adj = 2 if prev_lags else 1
        if kind == "R":
            ch *= 2
            out = _add_nasnet_reduction(layers, inputs, combine, prev, cur,
                                        f"cell{i}", ch, adj_stride=adj)
        else:
            out = _add_nasnet_normal(layers, inputs, combine, prev, cur,
                                     f"cell{i}", ch, adj_stride=adj)
        prev_lags = kind == "R"
        prev, cur = cur, out
    # after a final reduction the classifier only reads `cur`; a lagging
    # prev needs no adjustment
    cur = add(global_avg_pool(), [cur])
    cur = add(flatten(), [cur])
    add(dense("fc", num_classes), [cur])
    return DagModel(arch, layers, inputs, combine, tuple(in_shape),
                    num_classes)


def get_dag(arch: str, in_shape, num_classes: int):
    """The DAG form of a branchy zoo arch (None for chain archs) — used by
    the auto-partition path to profile the real dataflow graph."""
    if arch in _INCEPTION_BLOCKS:
        return build_inception(arch, in_shape, num_classes)
    if arch in _NASNET_SPECS:
        return build_nasnet(arch, in_shape, num_classes)
    return None
