"""Recurrent (LSTM) seq2seq — the reference's GNMT workload CLASS, scan-based.

The flagship TPU seq2seq stays the prefix-LM transformer (models/seq2seq.py,
accepted round 1), but the reference's translation workload is a multi-layer
residual LSTM encoder/decoder with attention
(pipedream-fork/runtime/translation/seq2seq/models/encoder.py:25-33,
decoder.py, attention.py) and round 2 left NO recurrence anywhere in the
repo. This module supplies the class, idiomatically: the recurrence is a
``lax.scan`` over time (the carry is the [B, H] hidden/cell pair — XLA
compiles one step and iterates; static trip count, no Python loop), batched
matmuls [B, D]x[D, 4H] keep the MXU busy within each step, and the model
rides the SAME [B, S+T] prefix token stream as the transformer seq2seq:

* a unidirectional LSTM over the joint stream makes the encoder's final
  hidden state flow into the first target step BY CONSTRUCTION — GNMT's
  encoder->decoder hidden handoff without a separate decoder module;
* cross-attention lets target positions attend over the source segment
  (GNMT's decoder attention, dot-product form); source positions pass
  through untouched;
* the head is the shared lm_head, so the fused projection+loss
  (ops/fused_xent.py) applies to the LSTM variant unchanged.

Layers map [B, T, *] -> [B, T, *], so the model is a flat chain and runs
under single/dp/gpipe/pipedream/tp/fsdp like every other model. Sequence
parallelism is the one exclusion: a recurrence cannot shard its time axis
(documented in PARITY.md — the transformer seq2seq is the sp-capable one).
Incremental decode entry points are likewise transformer-only.
"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp
from jax import lax

from ddlbench_tpu.models.layers import Layer, LayerModel
from ddlbench_tpu.models.transformer import _dense_init, lm_head

_VARIANTS = {
    # n_layers counts LSTM layers; GNMT uses 4 enc + 4 dec of d1024 — the
    # joint-stream design halves that (one stack serves both segments)
    "seq2seq_lstm_s": dict(d_model=512, n_layers=4),
    "seq2seq_lstm_t": dict(d_model=32, n_layers=2),  # test variant
}


def lstm_layer(name: str, hidden: int, residual: bool = True) -> Layer:
    """One LSTM layer over the time axis: [B, T, D] -> [B, T, H] via
    lax.scan. Gate order (i, f, g, o); forget-gate bias starts at 1.0 (the
    GNMT/standard initialization that keeps early gradients flowing).
    Residual connection when shapes allow (GNMT stacks residual LSTM layers,
    encoder.py:25-33)."""

    def init(key, in_shape):
        T, d = in_shape
        kx, kh = jax.random.split(key)
        p = {
            "wx": _dense_init(kx, d, 4 * hidden),
            "wh": _dense_init(kh, hidden, 4 * hidden),
            "b": jnp.zeros((4 * hidden,), jnp.float32)
            .at[hidden:2 * hidden].set(1.0),
        }
        return p, {}, (T, hidden)

    def apply(p, s, x, train):
        B, T, d = x.shape
        H = p["wh"].shape[0]
        # precompute the input projections for ALL steps in one [B*T, 4H]
        # matmul (MXU-friendly); the scan then only does the [B, H]x[H, 4H]
        # recurrent matmul per step
        xw = (x.reshape(B * T, d) @ p["wx"].astype(x.dtype)).reshape(B, T, -1)
        xw = xw + p["b"].astype(x.dtype)

        def step(carry, xw_t):
            h, c = carry
            gates = xw_t + h @ p["wh"].astype(h.dtype)
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            c = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
            h = jax.nn.sigmoid(o) * jnp.tanh(c)
            return (h, c), h

        # zeros_like (not zeros): under shard_map the carry must share the
        # input's varying-axes type or the scan rejects the fresh constant
        h0 = jnp.zeros_like(xw[:, 0, :H])
        _, hs = lax.scan(step, (h0, h0), jnp.swapaxes(xw, 0, 1))
        y = jnp.swapaxes(hs, 0, 1)  # [B, T, H]
        if residual and d == H:
            y = y + x
        return y, s

    return Layer(name, init, apply)


def cross_attention(name: str, d_model: int, src_len: int) -> Layer:
    """GNMT decoder attention, dot-product form: target positions attend
    over the source segment's states (keys/values = positions < src_len);
    source positions pass through unchanged (reference attention.py computes
    context only in the decoder)."""

    def init(key, in_shape):
        T, d = in_shape
        kq, kk, kv, ko = jax.random.split(key, 4)
        p = {"q": _dense_init(kq, d, d), "k": _dense_init(kk, d, d),
             "v": _dense_init(kv, d, d), "o": _dense_init(ko, d, d)}
        return p, {}, (T, d)

    def apply(p, s, x, train):
        B, T, d = x.shape
        q = x @ p["q"].astype(x.dtype)
        k = x[:, :src_len] @ p["k"].astype(x.dtype)
        v = x[:, :src_len] @ p["v"].astype(x.dtype)
        scores = jnp.einsum("btd,bsd->bts", q, k) / jnp.sqrt(
            jnp.asarray(d, x.dtype))
        ctx = jnp.einsum("bts,bsd->btd",
                         jax.nn.softmax(scores.astype(jnp.float32),
                                        axis=-1).astype(x.dtype), v)
        out = ctx @ p["o"].astype(x.dtype)
        # only target positions receive context; the source segment is the
        # "encoder" and must not see it
        is_tgt = (jnp.arange(T) >= src_len)[None, :, None]
        return x + jnp.where(is_tgt, out, jnp.zeros_like(out)), s

    return Layer(name, init, apply)


def lstm_embed(name: str, vocab: int, d_model: int, src_len: int) -> Layer:
    """Token + segment embedding (no positions — the recurrence provides
    order, as in GNMT)."""

    def init(key, in_shape):
        (T,) = in_shape
        k1, k2 = jax.random.split(key)
        p = {"tok": _dense_init(k1, vocab, d_model),
             "seg": _dense_init(k2, 2, d_model)}
        return p, {}, (T, d_model)

    def apply(p, s, x, train):
        T = x.shape[1]
        seg = (jnp.arange(T) >= src_len).astype(jnp.int32)
        return (jnp.take(p["tok"], x, axis=0)
                + jnp.take(p["seg"], seg, axis=0)[None]), s

    return Layer(name, init, apply)


def build_lstm_seq2seq(arch: str, in_shape, vocab: int,
                       src_len: int) -> LayerModel:
    cfgv = _VARIANTS[arch]
    T = in_shape[0]
    if not 0 < src_len < T:
        raise ValueError(f"src_len {src_len} must be inside the stream (T={T})")
    d = cfgv["d_model"]
    layers: List[Layer] = [lstm_embed("embed", vocab, d, src_len)]
    n = cfgv["n_layers"]
    for i in range(n):
        layers.append(lstm_layer(f"lstm{i + 1}", d, residual=i > 0))
        if i == n // 2 - 1 or n == 1:
            # attention mid-stack: the lower layers encode, the upper layers
            # consume source context (GNMT attends from the first decoder
            # layer; here "decoder depth" is the upper half of the stack)
            layers.append(cross_attention(f"attn{i + 1}", d, src_len))
    layers.append(lm_head("lm_head", vocab))
    return LayerModel(arch, layers, tuple(in_shape), vocab,
                      input_kind="tokens", src_len=src_len)
