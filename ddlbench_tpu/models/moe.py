"""Mixture-of-experts transformer LM — the expert-parallel (EP) workload.

The reference has no MoE models anywhere (SURVEY.md §2E marks EP absent), so
this is a new first-class capability, designed TPU-first rather than ported:

* Switch-style top-1 routing with a static capacity per expert, expressed as
  dense one-hot dispatch/combine einsums — fixed shapes, no gather/scatter, so
  XLA tiles the whole layer onto the MXU.
* Expert FFNs are a single batched einsum over a stacked ``[E, ...]`` weight
  axis; under expert parallelism that axis is sharded over an ``expert`` mesh
  axis and token blocks move with two ``lax.all_to_all`` collectives
  (dispatch there, combine back) riding ICI.
* The router's load-balance auxiliary loss (Switch eq. 4) is published through
  a trace-time collector so strategies can add it to the objective without
  threading it through every Layer signature.

One model definition serves dense (single/dp/sp/tp/fsdp) and expert-parallel
(ep) execution: parallel/ep.py enters :class:`expert_parallel` inside its
shard_map, exactly the pattern models/transformer.py uses for sequence
parallelism. EVERY strategy adds the collected aux loss to its training
objective (weight cfg.moe_aux_weight): single/dp/tp/fsdp through
loss_with_moe_aux, sp/ep with a psum over their shard axis, gpipe by
accumulating per-stage aux through its scan, and pipedream by adding each
stage's aux term to the per-microbatch objective in its recompute-based
backward.
"""

from __future__ import annotations

import contextlib
import math
from typing import List

import jax
import jax.numpy as jnp
from jax import lax

from ddlbench_tpu.models.layers import Layer, LayerModel, axis_context
from ddlbench_tpu.models.transformer import (
    _dense_init,
    _ln_init,
    attention_sublayer,
    attn_cache_init,
    attn_decode_op,
    attn_prefill_op,
    embed,
    layer_norm,
    lm_head,
)

_VARIANTS = {
    # every other block is MoE (Switch/GShard convention)
    "transformer_moe_s": dict(d_model=512, n_layers=8, n_heads=8, n_experts=8),
}

class expert_parallel(axis_context):
    """Context manager: trace MoE applies in expert-parallel mode. When active
    (parallel/ep.py enters it inside its shard_map), the stacked expert
    weights seen by apply are the LOCAL shard and token blocks are exchanged
    with all_to_all over the named axis."""

    _stack: list = []


def _expert_axis():
    return expert_parallel.current()


# Trace-time sink for router auxiliary losses (one scalar per MoE layer).
_AUX_SINK: list = []


@contextlib.contextmanager
def collect_aux_losses(out: list):
    """Collect each MoE layer's load-balance loss traced inside the block."""
    _AUX_SINK.append(out)
    try:
        yield out
    finally:
        _AUX_SINK.pop()


def _record_aux(v):
    if _AUX_SINK:
        _AUX_SINK[-1].append(v)


def _top1_gate(gate_logits: jax.Array):
    """Shared top-1 routing core: (probs f32, one-hot choice, chosen-expert
    probability). Used by training routing (switch_route) AND the cached
    decode path so the two can never diverge."""
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    idx = jnp.argmax(probs, axis=-1)
    onehot = jax.nn.one_hot(idx, probs.shape[-1], dtype=jnp.float32)
    gate = jnp.sum(probs * onehot, axis=-1)
    return probs, onehot, gate


def switch_route(gate_logits: jax.Array, capacity: int):
    """Top-1 switch routing over [S, E] router logits.

    Returns (dispatch [S, E, C] 0/1, combine [S, E, C] gate-weighted, aux).
    Tokens beyond an expert's capacity C are dropped (their dispatch row is
    all-zero, so they pass through the residual unchanged) — the standard
    Switch semantics, static shapes throughout.
    """
    S, E = gate_logits.shape
    probs, onehot, gate = _top1_gate(gate_logits)
    # load-balance aux (Switch eq. 4): E * sum_e fraction_e * mean_prob_e
    aux = E * jnp.sum(jnp.mean(onehot, axis=0) * jnp.mean(probs, axis=0))
    _record_aux(aux)
    # 1-based position of each token within its expert's queue
    pos1 = jnp.cumsum(onehot, axis=0) * onehot
    within = (pos1 <= capacity).astype(jnp.float32)
    # one_hot of -1 (token not routed to e) is all-zero
    dispatch = jax.nn.one_hot(
        (pos1 - 1.0).astype(jnp.int32), capacity, dtype=jnp.float32
    ) * within[..., None]
    combine = dispatch * gate[:, None, None]
    return dispatch, combine, aux


def _expert_ffn(pe, x):
    """Batched expert MLP: x [E_local, C', d] -> [E_local, C', d]."""
    h = jnp.einsum("ecd,edf->ecf", x, pe["w1"].astype(x.dtype))
    h = jax.nn.gelu(h + pe["b1"][:, None, :].astype(x.dtype))
    y = jnp.einsum("ecf,efd->ecd", h, pe["w2"].astype(x.dtype))
    return y + pe["b2"][:, None, :].astype(x.dtype)


def moe_mlp(p, x, capacity_factor: float):
    """Switch MoE feed-forward over x [B, T, d]; returns [B, T, d].

    Dense mode: all E experts are local. Expert-parallel mode (inside
    :class:`expert_parallel`): ``p["experts"]`` holds this device's E/n
    experts; dispatched token blocks are exchanged with ``lax.all_to_all``
    (split the expert axis, concatenate the capacity axis), the local experts
    run one batched einsum over tokens from every device, and a second
    all_to_all brings results home for the combine.
    """
    B, T, d = x.shape
    S = B * T
    xf = x.reshape(S, d)
    E = p["gate"].shape[1]
    E_local = p["experts"]["w1"].shape[0]
    capacity = max(1, math.ceil(capacity_factor * S / E))

    gate_logits = xf.astype(jnp.float32) @ p["gate"]
    dispatch, combine, _ = switch_route(gate_logits, capacity)
    dispatch = dispatch.astype(x.dtype)
    expert_in = jnp.einsum("sec,sd->ecd", dispatch, xf)  # [E, C, d]

    axis = _expert_axis()
    if axis is None:
        if E_local != E:
            raise ValueError(
                f"{E_local}/{E} experts present outside expert_parallel context"
            )
        expert_out = _expert_ffn(p["experts"], expert_in)
    else:
        # [E, C, d] -> [E/n, n*C, d]: each device keeps its experts' blocks
        # from every peer.
        expert_in = lax.all_to_all(
            expert_in, axis, split_axis=0, concat_axis=1, tiled=True
        )
        expert_out = _expert_ffn(p["experts"], expert_in)
        # [E/n, n*C, d] -> [E, C, d]: blocks return to their source device.
        expert_out = lax.all_to_all(
            expert_out, axis, split_axis=1, concat_axis=0, tiled=True
        )
    y = jnp.einsum("sec,ecd->sd", combine.astype(x.dtype), expert_out)
    return y.reshape(B, T, d)


def moe_block(name: str, d_model: int, n_heads: int, n_experts: int,
              mlp_ratio: int = 4, capacity_factor: float = 1.25) -> Layer:
    """Pre-LN transformer block whose MLP is a switch-routed expert bank."""
    d_ff = mlp_ratio * d_model

    def init(key, in_shape):
        T, dm = in_shape
        assert dm == d_model
        ks = jax.random.split(key, 5)
        p = {
            "ln1": _ln_init(dm),
            "wqkv": _dense_init(ks[0], dm, 3 * dm),
            "wo": _dense_init(ks[1], dm, dm),
            "ln2": _ln_init(dm),
            "gate": _dense_init(ks[2], dm, n_experts),
            "experts": {
                "w1": jax.vmap(lambda k: _dense_init(k, dm, d_ff))(
                    jax.random.split(ks[3], n_experts)
                ),
                "b1": jnp.zeros((n_experts, d_ff), jnp.float32),
                "w2": jax.vmap(lambda k: _dense_init(k, d_ff, dm))(
                    jax.random.split(ks[4], n_experts)
                ),
                "b2": jnp.zeros((n_experts, dm), jnp.float32),
            },
        }
        return p, {}, (T, dm)

    def apply(p, s, x, train):
        x = attention_sublayer(p, x, n_heads)
        h = layer_norm(p["ln2"], x)
        x = x + moe_mlp(
            {"gate": p["gate"], "experts": p["experts"]}, h, capacity_factor
        )
        return x, s

    # ---- KV-cached incremental decoding (models/decode.py protocol) ----

    def _moe_ffn(p, x):
        h = layer_norm(p["ln2"], x)
        return x + moe_mlp(
            {"gate": p["gate"], "experts": p["experts"]}, h, capacity_factor
        )

    def _reject_ep():
        if _expert_axis() is not None:
            raise NotImplementedError(
                "cached decoding under expert_parallel is not supported; "
                "decode outside the ep shard_map")

    def prefill(p, s, cache, x, start):
        _reject_ep()
        x, cache = attn_prefill_op(p, x, cache, n_heads, 0, start)
        return _moe_ffn(p, x), cache

    def _moe_ffn_token(p, x):
        """Per-token top-1 expert FFN for one decoded position [B, 1, d].
        Decode routing has no capacity limit (each token simply runs its
        chosen expert — standard MoE inference); this matches the training
        semantics exactly whenever apply's capacity didn't drop the
        token."""
        h = layer_norm(p["ln2"], x)  # [B, 1, d]
        hf = h[:, 0]
        _, onehot, gate = _top1_gate(hf.astype(jnp.float32) @ p["gate"])
        pe = p["experts"]
        # all-expert compute for the single position (E small, B small at
        # decode time), then gate-weighted top-1 combine
        eh = jnp.einsum("bd,edf->bef", hf, pe["w1"].astype(hf.dtype))
        eh = jax.nn.gelu(eh + pe["b1"][None].astype(hf.dtype))
        ey = jnp.einsum("bef,efd->bed", eh, pe["w2"].astype(hf.dtype))
        ey = ey + pe["b2"][None].astype(hf.dtype)
        w = (onehot * gate[:, None]).astype(hf.dtype)
        y = jnp.einsum("be,bed->bd", w, ey)
        return x + y[:, None, :]

    def decode(p, s, cache, x, pos):
        _reject_ep()
        x, cache = attn_decode_op(p, x, cache, n_heads, pos)
        return _moe_ffn_token(p, x), cache

    dh = d_model // n_heads

    # paged-cache protocol: same attention sublayer ops as the dense
    # transformer block (models/transformer.py), same MoE FFN as decode
    from ddlbench_tpu.models.layers import PagedOps
    from ddlbench_tpu.models.transformer import (attn_paged_cache_init,
                                                 attn_paged_decode_op,
                                                 attn_paged_prefill_op,
                                                 attn_paged_reorder)

    def paged_prefill(p, s, cache, x, start):
        _reject_ep()
        x, cache = attn_paged_prefill_op(p, x, cache, n_heads, 0, start)
        return _moe_ffn(p, x), cache

    def paged_decode(p, s, cache, x, pos):
        _reject_ep()
        x, cache = attn_paged_decode_op(p, x, cache, n_heads, pos)
        return _moe_ffn_token(p, x), cache

    return Layer(name, init, apply, init_cache=attn_cache_init(n_heads, dh),
                 prefill=prefill, decode=decode,
                 paged=PagedOps(attn_paged_cache_init(n_heads, dh),
                                paged_prefill, paged_decode,
                                attn_paged_reorder))


def build_transformer_moe(arch: str, in_shape, vocab: int,
                          capacity_factor: float = 1.25) -> LayerModel:
    """MoE variant of the transformer LM: dense and MoE blocks alternate."""
    from ddlbench_tpu.models.transformer import transformer_block

    cfgv = _VARIANTS[arch]
    T = in_shape[0]
    layers: List[Layer] = [embed("embed", vocab, cfgv["d_model"], T)]
    for i in range(cfgv["n_layers"]):
        if i % 2 == 1:
            layers.append(moe_block(
                f"moe_block{i + 1}", cfgv["d_model"], cfgv["n_heads"],
                cfgv["n_experts"], capacity_factor=capacity_factor,
            ))
        else:
            layers.append(
                transformer_block(f"block{i + 1}", cfgv["d_model"], cfgv["n_heads"])
            )
    layers.append(lm_head("lm_head", vocab))
    return LayerModel(arch, layers, tuple(in_shape), vocab, input_kind="tokens")
