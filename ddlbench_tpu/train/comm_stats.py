"""Per-step communication-volume accounting.

Parity with the reference's RuntimeStats, which counts send/recv bytes per
minibatch inside the receive/send helpers (pipedream-fork/runtime/
runtime_utilities.py:4-27, incremented at runtime.py:423-425,444-446,462-464).

Under XLA the collectives are compiled into the program, so instead of runtime
counters we compute the exact analytic volume per train step from the strategy
topology — same numbers, no instrumentation overhead:

* dp: ring all-reduce of all gradients, 2 (r-1)/r * param_bytes per step.
  With the explicit sharded weight update (--dp-shard-update, ZeRO-1) the
  pattern decomposes into its two halves and is reported as such:
  reduce-scatter of the gradients ((r-1)/r * grad_wire_bytes, where the
  wire dtype follows --allreduce-dtype) plus all-gather of the updated
  params ((r-1)/r * param_bytes, always f32 — the master weights). The
  physical_* twins price the PADDED packed flat vector the engine actually
  ships (the pad aligns the per-device shard; logical payload excludes it).
  A bf16 --allreduce-dtype without the sharded update is an explicit bf16
  ring all-reduce: half the gradient wire bytes, same pattern.
* gpipe: every microbatch crosses every interior stage boundary twice
  (activation forward, gradient backward) + one per-step gradient all-reduce
  across each stage's 'data' replicas.
* pipedream: same boundary traffic, but the intra-stage replica all-reduce
  happens once per microbatch (per-microbatch updates).
"""

from __future__ import annotations

import math
from typing import Dict


def _ring_allreduce_bytes(payload: float, r: int) -> float:
    return 2.0 * (r - 1) / r * payload if r > 1 else 0.0


def comm_stats(strategy) -> Dict[str, float]:
    """Analytic communication bytes per train step for a built strategy."""
    from ddlbench_tpu.models.layers import param_bytes as pb

    name = type(strategy).__name__
    out: Dict[str, float] = {
        "boundary_bytes": 0.0,
        "allreduce_bytes": 0.0,
        "reduce_scatter_bytes": 0.0,
        "all_gather_bytes": 0.0,
    }
    if name == "SingleStrategy":
        pass
    elif name == "DPStrategy":
        import numpy as np

        params, _, _ = _model_params(strategy)
        r = strategy.world_size
        pbytes = float(pb(params))
        wire_dtype = np.dtype(getattr(strategy, "wire_dtype", "float32"))
        wire_itemsize = wire_dtype.itemsize
        # gradient elements ride the wire in the (possibly narrowed)
        # --allreduce-dtype (int8 = quarter f32 bytes); params are f32
        # (pb already prices them)
        grad_wire = pbytes / 4.0 * wire_itemsize
        meta = getattr(strategy, "_flat_meta", None)
        if meta is not None:
            # bucketed collectives (--comm-buckets) change neither the
            # logical nor the physical totals — the buckets partition the
            # same padded vector (per-bucket pads are already in
            # meta.padded) — only WHEN the bytes move; the per-bucket
            # split is reported for the span/overlap tooling.
            out["comm_buckets"] = float(meta.num_buckets)
            out["wire_dtype"] = str(wire_dtype)
        if getattr(strategy, "shard_update", False):
            out["reduce_scatter_bytes"] = (r - 1) / r * grad_wire
            out["all_gather_bytes"] = (r - 1) / r * pbytes
            # physical: the engine ships the PADDED packed flat vector
            out["physical_reduce_scatter_bytes"] = (
                (r - 1) / r * meta.padded * wire_itemsize)
            out["physical_all_gather_bytes"] = (r - 1) / r * meta.padded * 4.0
            if wire_dtype == np.dtype(np.int8):
                # int8 adds one psum'd f32 scale per bucket (the shared
                # absmax) — priced so the accounting stays EXACT
                out["scale_bytes"] = _ring_allreduce_bytes(
                    4.0 * meta.num_buckets, r)
        else:
            out["allreduce_bytes"] = _ring_allreduce_bytes(grad_wire, r)
            if meta is not None:  # explicit wire engine, replicated update
                out["physical_allreduce_bytes"] = _ring_allreduce_bytes(
                    float(meta.padded * wire_itemsize), r)
                if wire_dtype == np.dtype(np.int8):
                    out["scale_bytes"] = _ring_allreduce_bytes(
                        4.0 * meta.num_buckets, r)
    elif name in ("HeteroGPipeStrategy", "HeteroPipeDreamStrategy"):
        # Uneven hybrid PPxDP (parallel/hetero.py). boundary/allreduce are
        # LOGICAL payload bytes (reference RuntimeStats parity,
        # runtime_utilities.py:4-27): each activation crosses its boundary
        # once fwd + once bwd, each replica group reduces its gradient once
        # per sync. The flat-axis implementation's WIRE traffic is a large
        # multiple — the conveyor ships a full max-interior-activation
        # buffer over every chain link for R rounds per tick, and the async
        # engine runs the gradient ring every tick with masked payloads —
        # reported separately as physical_* (ADVICE r2).
        itemsize = strategy.compute_dtype.itemsize
        M, mb = strategy.num_microbatches, strategy.mb
        bounds, shapes = strategy.bounds, strategy.shapes
        S = strategy.num_stages
        boundary = 0.0
        for s in range(1, S):
            act = mb * math.prod(shapes[bounds[s]]) * itemsize
            boundary += 2.0 * M * act
        out["boundary_bytes"] = boundary
        per_sync = sum(
            _ring_allreduce_bytes(4.0 * strategy._p_lens[s], r)
            for s, r in enumerate(strategy.repl))
        asynch = name == "HeteroPipeDreamStrategy"
        out["allreduce_bytes"] = per_sync * (M if asynch else 1)
        # physical wire estimate: links x rounds x ticks x buffer size
        N, R = strategy.N, strategy._R
        links = N - 1
        buf = float(strategy._act_size) * itemsize
        Lmax = 4.0 * max(strategy._p_lens)  # packed f32 param row
        Rg = max(strategy.repl) - 1
        # singleton stages' ring edges are self-permutes (local copy, no
        # wire): only devices in groups of >1 replicas transmit
        n_ring = sum(r for r in strategy.repl if r > 1)
        if asynch:
            ticks = 2 * M + 2 * S - 2
            conveyors = 2.0  # fwd chain + bwd chain every tick
            ring_ticks = ticks
        else:
            ticks = M + S - 1
            conveyors = 2.0  # jax.grad transposes the fwd conveyor
            ring_ticks = 1
        out["physical_conveyor_bytes"] = conveyors * ticks * R * links * buf
        out["physical_allreduce_bytes"] = float(Rg * ring_ticks * n_ring) * Lmax
    else:  # pipeline strategies (gpipe / pipedream)
        itemsize = strategy.compute_dtype.itemsize
        M, mb, dp = strategy.num_microbatches, strategy.mb, strategy.dp
        bounds, shapes = strategy.bounds, strategy.shapes
        S = strategy.num_stages
        boundary = 0.0
        for s in range(1, S):
            act = mb * math.prod(shapes[bounds[s]]) * itemsize
            boundary += 2.0 * M * act  # activation fwd + gradient bwd
        out["boundary_bytes"] = boundary * dp  # per replica column
        if dp > 1:
            grad_bytes = sum(
                4.0 * strategy._p_lens[c]
                for c in range(len(strategy._p_lens))
            )  # f32 packed grads (all chunks)
            if getattr(strategy, "pipe_shard", False):
                # hybrid PP x ZeRO-1 (--dp-shard-update on gpipe): the
                # per-step gradient pmean decomposes into its RS half —
                # gradient wire HALVES vs the replicated ring allreduce —
                # plus the params' just-in-time per-bucket all-gather at
                # the next forward (f32 master weights). physical_* twins
                # price the PADDED device-major rows actually shipped.
                meta = strategy._row_meta
                C = strategy.num_chunks
                out["reduce_scatter_bytes"] = (dp - 1) / dp * grad_bytes
                out["all_gather_bytes"] = (dp - 1) / dp * grad_bytes
                out["physical_reduce_scatter_bytes"] = (
                    (dp - 1) / dp * C * meta.padded * 4.0)
                out["physical_all_gather_bytes"] = (
                    (dp - 1) / dp * C * meta.padded * 4.0)
                out["comm_buckets"] = float(meta.num_buckets)
            else:
                per_sync = _ring_allreduce_bytes(grad_bytes, dp)
                syncs = M if name == "PipeDreamStrategy" else 1
                out["allreduce_bytes"] = per_sync * syncs
    out["total_bytes"] = (out["boundary_bytes"] + out["allreduce_bytes"]
                          + out["reduce_scatter_bytes"]
                          + out["all_gather_bytes"])
    return out


def _model_params(strategy):
    import jax

    from ddlbench_tpu.models.layers import init_model

    return init_model(strategy.model, jax.random.key(0))
