"""Per-step communication-volume accounting.

Parity with the reference's RuntimeStats, which counts send/recv bytes per
minibatch inside the receive/send helpers (pipedream-fork/runtime/
runtime_utilities.py:4-27, incremented at runtime.py:423-425,444-446,462-464).

Under XLA the collectives are compiled into the program, so instead of runtime
counters we compute the exact analytic volume per train step from the strategy
topology — same numbers, no instrumentation overhead:

* dp: ring all-reduce of all gradients, 2 (r-1)/r * param_bytes per step.
  With the explicit sharded weight update (--dp-shard-update, ZeRO-1) the
  pattern decomposes into its two halves and is reported as such:
  reduce-scatter of the gradients ((r-1)/r * grad_wire_bytes, where the
  wire dtype follows --allreduce-dtype) plus all-gather of the updated
  params ((r-1)/r * param_bytes, always f32 — the master weights). The
  physical_* twins price the PADDED packed flat vector the engine actually
  ships (the pad aligns the per-device shard; logical payload excludes it).
  A bf16 --allreduce-dtype without the sharded update is an explicit bf16
  ring all-reduce: half the gradient wire bytes, same pattern.
* gpipe: every microbatch crosses every interior stage boundary twice
  (activation forward, gradient backward) + one per-step gradient all-reduce
  across each stage's 'data' replicas. The physical_* twins price what the
  compiled scan actually ships: the conveyor ppermutes the full packed
  activation buffer over every (stage, replica) link on every one of the
  T = M*V + S - 1 ticks (forward + its autodiff transpose), and the
  replicated gradient sync rings the PADDED packed stage rows.
* pipedream: same boundary traffic, but the intra-stage replica all-reduce
  happens once per microbatch (per-microbatch updates).
* tpp (TPGPipeStrategy): the Megatron activation psums inside every stage
  are priced PER COLLECTIVE (``tp_psum_payload_bytes`` — the audit plane
  ties every 'model'-axis all-reduce in the optimized HLO to one of the
  analytic payload classes reported here), plus the conveyor boundary and
  the packed-row gradient/state syncs over 'data' and 'data x model'.

Every byte figure here is cross-checked against the per-collective ledger
the audit plane (telemetry/audit.py) walks out of the compiled HLO — the
exact tie-outs are pinned in tests/test_audit.py.
"""

from __future__ import annotations

import math
from typing import Dict


def _ring_allreduce_bytes(payload: float, r: int) -> float:
    return 2.0 * (r - 1) / r * payload if r > 1 else 0.0


def comm_stats(strategy) -> Dict[str, float]:
    """Analytic communication bytes per train step for a built strategy."""
    from ddlbench_tpu.models.layers import param_bytes as pb

    name = type(strategy).__name__
    out: Dict[str, float] = {
        "boundary_bytes": 0.0,
        "allreduce_bytes": 0.0,
        "reduce_scatter_bytes": 0.0,
        "all_gather_bytes": 0.0,
    }
    if name == "SingleStrategy":
        pass
    elif name == "DPStrategy":
        import numpy as np

        params, _, _ = _model_params(strategy)
        r = strategy.world_size
        pbytes = float(pb(params))
        wire_dtype = np.dtype(getattr(strategy, "wire_dtype", "float32"))
        wire_itemsize = wire_dtype.itemsize
        # gradient elements ride the wire in the (possibly narrowed)
        # --allreduce-dtype (int8 = quarter f32 bytes); params are f32
        # (pb already prices them)
        grad_wire = pbytes / 4.0 * wire_itemsize
        meta = getattr(strategy, "_flat_meta", None)
        if meta is not None:
            # bucketed collectives (--comm-buckets) change neither the
            # logical nor the physical totals — the buckets partition the
            # same padded vector (per-bucket pads are already in
            # meta.padded) — only WHEN the bytes move; the per-bucket
            # split is reported for the span/overlap tooling.
            out["comm_buckets"] = float(meta.num_buckets)
            out["wire_dtype"] = str(wire_dtype)
        if getattr(strategy, "shard_update", False):
            out["reduce_scatter_bytes"] = (r - 1) / r * grad_wire
            out["all_gather_bytes"] = (r - 1) / r * pbytes
            # physical: the engine ships the PADDED packed flat vector
            out["physical_reduce_scatter_bytes"] = (
                (r - 1) / r * meta.padded * wire_itemsize)
            out["physical_all_gather_bytes"] = (r - 1) / r * meta.padded * 4.0
            if wire_dtype == np.dtype(np.int8):
                # int8 adds one psum'd f32 scale per bucket (the shared
                # absmax) — priced so the accounting stays EXACT
                out["scale_bytes"] = _ring_allreduce_bytes(
                    4.0 * meta.num_buckets, r)
        else:
            out["allreduce_bytes"] = _ring_allreduce_bytes(grad_wire, r)
            if meta is not None:  # explicit wire engine, replicated update
                out["physical_allreduce_bytes"] = _ring_allreduce_bytes(
                    float(meta.padded * wire_itemsize), r)
                if wire_dtype == np.dtype(np.int8):
                    out["scale_bytes"] = _ring_allreduce_bytes(
                        4.0 * meta.num_buckets, r)
    elif name in ("HeteroGPipeStrategy", "HeteroPipeDreamStrategy"):
        # Uneven hybrid PPxDP (parallel/hetero.py). boundary/allreduce are
        # LOGICAL payload bytes (reference RuntimeStats parity,
        # runtime_utilities.py:4-27): each activation crosses its boundary
        # once fwd + once bwd, each replica group reduces its gradient once
        # per sync. The flat-axis implementation's WIRE traffic is a large
        # multiple — the conveyor ships a full max-interior-activation
        # buffer over every chain link for R rounds per tick, and the async
        # engine runs the gradient ring every tick with masked payloads —
        # reported separately as physical_* (ADVICE r2).
        itemsize = strategy.compute_dtype.itemsize
        M, mb = strategy.num_microbatches, strategy.mb
        bounds, shapes = strategy.bounds, strategy.shapes
        S = strategy.num_stages
        boundary = 0.0
        for s in range(1, S):
            act = mb * math.prod(shapes[bounds[s]]) * itemsize
            boundary += 2.0 * M * act
        out["boundary_bytes"] = boundary
        per_sync = sum(
            _ring_allreduce_bytes(4.0 * strategy._p_lens[s], r)
            for s, r in enumerate(strategy.repl))
        asynch = name == "HeteroPipeDreamStrategy"
        out["allreduce_bytes"] = per_sync * (M if asynch else 1)
        # physical wire estimate: links x rounds x ticks x buffer size
        N, R = strategy.N, strategy._R
        links = N - 1
        buf = float(strategy._act_size) * itemsize
        Lmax = 4.0 * max(strategy._p_lens)  # packed f32 param row
        Rg = max(strategy.repl) - 1
        # singleton stages' ring edges are self-permutes (local copy, no
        # wire): only devices in groups of >1 replicas transmit
        n_ring = sum(r for r in strategy.repl if r > 1)
        if asynch:
            ticks = 2 * M + 2 * S - 2
            conveyors = 2.0  # fwd chain + bwd chain every tick
            ring_ticks = ticks
        else:
            ticks = M + S - 1
            conveyors = 2.0  # jax.grad transposes the fwd conveyor
            ring_ticks = 1
        out["physical_conveyor_bytes"] = conveyors * ticks * R * links * buf
        out["physical_allreduce_bytes"] = float(Rg * ring_ticks * n_ring) * Lmax
    elif name == "TPGPipeStrategy":
        # Megatron-in-stage pipeline (parallel/tpp.py). boundary/allreduce
        # stay LOGICAL (reference RuntimeStats parity); every physical
        # payload class the compiled program ships is priced separately so
        # the audit plane can classify each HLO collective exactly:
        #   * 'model'-axis activation psums: one [mb, seq, d_model] block
        #     output per row-parallel projection (count is XLA's business —
        #     CSE merges some — so we pin the PAYLOAD, not the count)
        #   * 'data'-axis grad sync of the padded sliced rows (S*tp groups)
        #   * 'data x model' grad sync of the padded replicated rows (S)
        #   * state rows pmean'd over 'data' then 'model'
        #   * the stage conveyor: 2 ppermutes x T ticks x (S-1)*dp*tp pairs
        itemsize = strategy.compute_dtype.itemsize
        M, mb = strategy.num_microbatches, strategy.mb
        dp, tp, S = strategy.dp, strategy.tp, strategy.num_stages
        bounds, shapes = strategy.bounds, strategy.shapes
        boundary = 0.0
        for s in range(1, S):
            act = mb * math.prod(shapes[bounds[s]]) * itemsize
            boundary += 2.0 * M * act
        out["boundary_bytes"] = boundary * dp
        out["allreduce_bytes"] = sum(
            tp * _ring_allreduce_bytes(4.0 * strategy._sl_lens[c], dp)
            + _ring_allreduce_bytes(4.0 * strategy._rp_lens[c], dp * tp)
            for c in range(S))
        L_sl = max(max(strategy._sl_lens), 1)
        L_rp = max(max(strategy._rp_lens), 1)
        L_st = max(max(strategy._st_lens), 1)
        out["tp_psum_payload_bytes"] = (
            float(mb) * math.prod(shapes[1]) * itemsize)
        out["tp_grad_sliced_row_bytes"] = 4.0 * L_sl
        out["tp_grad_repl_row_bytes"] = 4.0 * L_rp
        out["tp_state_row_bytes"] = 4.0 * L_st
        out["physical_allreduce_bytes"] = (
            S * tp * _ring_allreduce_bytes(4.0 * L_sl, dp)
            + S * _ring_allreduce_bytes(4.0 * L_rp, dp * tp)
            + S * tp * _ring_allreduce_bytes(4.0 * L_st, dp)
            + S * dp * _ring_allreduce_bytes(4.0 * L_st, tp))
        T = M + S - 1
        out["physical_boundary_bytes"] = (
            2.0 * T * (S - 1) * dp * tp * strategy._act_size * itemsize)
    else:  # pipeline strategies (gpipe / pipedream)
        itemsize = strategy.compute_dtype.itemsize
        M, mb, dp = strategy.num_microbatches, strategy.mb, strategy.dp
        bounds, shapes = strategy.bounds, strategy.shapes
        S = strategy.num_stages
        boundary = 0.0
        for s in range(1, S):
            act = mb * math.prod(shapes[bounds[s]]) * itemsize
            boundary += 2.0 * M * act  # activation fwd + gradient bwd
        out["boundary_bytes"] = boundary * dp  # per replica column
        if name == "GPipeStrategy":
            # physical conveyor: the compiled scan ppermutes the full
            # packed activation buffer (fwd + the autodiff transpose) over
            # every interior link of every replica column on every one of
            # the T = M*V + S - 1 ticks
            V = strategy.num_chunks // S
            T = M * V + S - 1
            out["physical_boundary_bytes"] = (
                2.0 * T * (S - 1) * dp * strategy._act_size * itemsize)
        if dp > 1:
            grad_bytes = sum(
                4.0 * strategy._p_lens[c]
                for c in range(len(strategy._p_lens))
            )  # f32 packed grads (all chunks)
            if getattr(strategy, "pipe_shard", False):
                # hybrid PP x ZeRO-1 (--dp-shard-update on gpipe): the
                # per-step gradient pmean decomposes into its RS half —
                # gradient wire HALVES vs the replicated ring allreduce —
                # plus the params' just-in-time per-bucket all-gather at
                # the next forward (f32 master weights). physical_* twins
                # price the PADDED device-major rows actually shipped.
                meta = strategy._row_meta
                C = strategy.num_chunks
                out["reduce_scatter_bytes"] = (dp - 1) / dp * grad_bytes
                out["all_gather_bytes"] = (dp - 1) / dp * grad_bytes
                out["physical_reduce_scatter_bytes"] = (
                    (dp - 1) / dp * C * meta.padded * 4.0)
                out["physical_all_gather_bytes"] = (
                    (dp - 1) / dp * C * meta.padded * 4.0)
                out["comm_buckets"] = float(meta.num_buckets)
            else:
                per_sync = _ring_allreduce_bytes(grad_bytes, dp)
                syncs = M if name == "PipeDreamStrategy" else 1
                out["allreduce_bytes"] = per_sync * syncs
                if name == "GPipeStrategy":
                    # physical grad/state sync: one ring per stage group
                    # over the PADDED [V, Lmax] device rows
                    V = strategy.num_chunks // S
                    Lp = max(max(strategy._p_lens), 1)
                    Ls = max(max(strategy._s_lens), 1)
                    out["gp_grad_row_bytes"] = 4.0 * V * Lp
                    out["gp_state_row_bytes"] = 4.0 * V * Ls
                    out["physical_allreduce_bytes"] = S * (
                        _ring_allreduce_bytes(4.0 * V * Lp, dp)
                        + _ring_allreduce_bytes(4.0 * V * Ls, dp))
    out["total_bytes"] = (out["boundary_bytes"] + out["allreduce_bytes"]
                          + out["reduce_scatter_bytes"]
                          + out["all_gather_bytes"])
    return out


def _model_params(strategy):
    import jax

    from ddlbench_tpu.models.layers import init_model

    return init_model(strategy.model, jax.random.key(0))
