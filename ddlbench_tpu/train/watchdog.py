"""Failure detection: hang watchdog + non-finite-loss policy.

The reference has essentially no failure detection (SURVEY.md §5.3): a
120-minute process-group timeout so hangs eventually die (pipedream-fork/
runtime/communication.py:43), a pkill-over-ssh cleanup script
(runtime/scripts/terminate_runtime.py:29-30), and nothing that notices a
diverged loss. This module is the TPU-native superset:

* :class:`HangWatchdog` — a monitor thread armed with a deadline; while it is
  armed the train loop syncs (and kicks) EVERY step, so the timeout really is
  per-step — a hang dies in seconds-to-minutes instead of hours — at a small
  pipelining cost paid only when the feature is enabled. On expiry it dumps
  every Python thread's stack (so a stuck collective or host-transfer is
  diagnosable — the reference's hang just times out silently after 2 hours)
  and terminates the process. The loop starts the watchdog only after warmup,
  so the first deadline excludes XLA compile time.
* :func:`check_finite` — NaN/Inf loss policy (abort | warn | ignore). A
  diverged run aborts with :class:`TrainingFailure` instead of burning the
  rest of its allocation; combined with --checkpoint-dir/--resume the run can
  be restarted from the last good epoch.

Nothing here touches device code: detection lives entirely at the host sync
points the benchmark loop already has (loss transfers), so it costs nothing
on the hot path.
"""

from __future__ import annotations

import faulthandler
import math
import os
import sys
import threading
import time
from typing import Callable, Optional

NAN_POLICIES = ("abort", "warn", "ignore")


class TrainingFailure(RuntimeError):
    """Raised when the configured failure policy aborts the run."""


def check_finite(loss: float, epoch: int, step: int, policy: str = "abort",
                 where: Optional[str] = None) -> bool:
    """Apply the non-finite-loss policy; returns True if the loss is finite.

    ``where`` overrides the default "epoch E step S" location — callers that
    detect non-finiteness away from the offending step (e.g. the eval loop's
    one epoch-end transfer) must not claim a specific step."""
    if math.isfinite(loss):
        return True
    where = where or f"at epoch {epoch} step {step}"
    if policy == "abort":
        raise TrainingFailure(f"non-finite loss {loss!r} {where}")
    if policy == "warn":
        print(
            f"WARNING: non-finite loss {loss!r} {where}",
            file=sys.stderr,
            flush=True,
        )
    return False


class ProgressMonitor:
    """Clock-agnostic no-progress detector: the deadline logic of
    :class:`HangWatchdog` with the wall clock factored OUT. ``kick(now)``
    records progress on whatever monotone timeline the caller runs —
    ``time.monotonic()``, a global step counter, or the serving engine's
    virtual model-pass clock — and ``expired(now)`` is True once more
    than ``window`` of that timeline has passed without a kick.

    This is what lets the serving fleet reuse the training watchdog's
    detection rule (SURVEY.md §5.3's answer) in VIRTUAL time, where a
    thread + ``time.monotonic()`` would be meaningless: ReplicatedServer
    kicks a replica's monitor every step it schedules work, and a replica
    that holds requests while its monitor expires is a straggler to drain
    (serve/engine.py). Pure host arithmetic — no threads, deterministic,
    jax-free like the rest of this module.
    """

    def __init__(self, window: float, now: float = 0.0):
        if window <= 0:
            raise ValueError("window must be positive")
        self.window = window
        self._last = now

    def kick(self, now: float) -> None:
        """Record progress at ``now``; postpones expiry by ``window``."""
        self._last = now

    def expired(self, now: float) -> bool:
        return now - self._last > self.window

    @property
    def last_progress(self) -> float:
        return self._last

    def stalled_for(self, now: float) -> float:
        return now - self._last


def _default_on_timeout(timeout_s: float) -> None:
    print(
        f"HANG: no progress for {timeout_s:.0f}s — dumping stacks and aborting",
        file=sys.stderr,
        flush=True,
    )
    faulthandler.dump_traceback(file=sys.stderr)
    # os._exit, not sys.exit: the hung thread holds the GIL-visible state we
    # just dumped; exiting hard is the point (terminate_runtime.py parity).
    os._exit(124)


class HangWatchdog:
    """Deadline monitor: ``kick()`` at every sync point or ``on_timeout`` fires.

    Usable as a context manager; the monitor is a daemon thread so it can
    never keep a finished process alive.
    """

    def __init__(self, timeout_s: float,
                 on_timeout: Optional[Callable[[], None]] = None):
        if timeout_s <= 0:
            raise ValueError("timeout_s must be positive")
        self.timeout_s = timeout_s
        self._on_timeout = on_timeout or (
            lambda: _default_on_timeout(self.timeout_s)
        )
        self._last = time.monotonic()
        self._stop = threading.Event()
        self._fired = False
        self._thread = threading.Thread(
            target=self._run, name="ddlbench-hang-watchdog", daemon=True
        )

    def start(self) -> "HangWatchdog":
        """Idempotent: the guard's rewind path re-enters the run loop with
        the same watchdog, and threading.Thread.start() raises on reuse."""
        if not self._thread.is_alive() and not self._stop.is_set():
            try:
                self._thread.start()
            except RuntimeError:  # already started and since finished
                pass
        return self

    def kick(self) -> None:
        """Record progress; postpones the deadline by ``timeout_s``."""
        self._last = time.monotonic()

    def stop(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=5)

    @property
    def fired(self) -> bool:
        return self._fired

    def _run(self) -> None:
        poll = min(1.0, self.timeout_s / 4)
        while not self._stop.wait(poll):
            if time.monotonic() - self._last > self.timeout_s:
                self._fired = True
                self._on_timeout()
                return

    def __enter__(self) -> "HangWatchdog":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False
