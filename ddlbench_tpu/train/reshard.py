"""Topology-portable checkpoints: world-size detection + the reshard pass.

Crash consistency (train/checkpoint.py) assumed the replacement pod has
the SAME shape as the one that died; real fleets hand back fewer or more
chips, and an N-chip ZeRO-1 checkpoint restored on M chips used to die
deep inside orbax with a cryptic shape assert (the packed flat vectors are
world-padded: ``padded_N != padded_M``). This module makes the mismatch a
first-class event:

* every checkpoint now carries LOGICAL (unsharded, world-agnostic)
  metadata — leaf shapes/dtypes, the flat-meta bucket layout, and the
  world/dp/stage shape it was saved under (``logical_meta``, written as
  ``logical.json`` inside the commit and covered by the manifest);
* at resume, :func:`compare` detects the mismatch. Without
  ``--elastic-resume`` it raises the named :class:`CheckpointShapeError`
  (both shapes in the message, warn-once pointer at the flag); with it,
  :func:`elastic_restore` restores the checkpoint at its SAVED shapes and
  converts the flat state between world sizes.

The conversion is a pure PERMUTATION, never a gather: the
weight-update-sharding layout (PAPERS.md 2004.13336) keeps every logical
element's value independent of the world size — world padding only moves
zeros between buckets, and the device-major relayout is an index
permutation (``parallel/common.py to_device_major``/``device_major_perm``).
So for f32 state the round trip save@N -> reshard -> M is bitwise: strip
each bucket's pad, re-pad for the new world, re-permute. Covered layouts:

* the dp ZeRO-1 engine's packed flat optimizer state (``--dp-shard-update``,
  sgd momentum and adam m/v, any ``--comm-buckets K`` on either side) and
  the overlapped engine's flat device-major parameter vector;
* the PR 8 pipe-mesh ``row_flat_meta`` stage rows (params + optimizer
  state sharded over the pipe mesh's 'data' axis), for a changed dp
  replica count at the SAME stage split. A changed stage count is a
  re-planning problem, not a permutation — the auto-partition path
  (``--auto-partition``) owns the stage split, so S/V changes raise
  :class:`CheckpointShapeError` directing the run there.

Exact data/RNG fast-forward needs nothing new: batches are (epoch, step)-
addressed at the GLOBAL batch and per-step RNG streams are pure
(seed, epoch, step) fold-ins, so the bitwise-resume machinery carries over
unchanged — provided the global batch is preserved across the reshape
(checked here, loud warning on mismatch). Trajectory bitwiseness across
world sizes additionally needs the world-invariant reduction order of
``--elastic-slices`` (parallel/dp.py elastic engine); the lr world-scaling
factor is pinned to the LAUNCH world recorded in the metadata so shrinking
a fleet never silently changes the learning rate.
"""

from __future__ import annotations

import sys
from typing import Any, Dict, List, Optional

import numpy as np

LOGICAL_SCHEMA = 1

_warned_flag = False  # warn-once pointer at --elastic-resume


class CheckpointShapeError(RuntimeError):
    """A checkpoint's recorded world shape mismatches the current mesh and
    the elastic reshard path is not enabled (or cannot cover the change)."""


def _leaf_meta(ts) -> List[Dict[str, Any]]:
    import jax

    return [{"shape": list(getattr(l, "shape", ())),
             "dtype": str(np.dtype(getattr(l, "dtype", np.float32)))}
            for l in jax.tree.leaves(ts)]


def logical_meta(strategy, cfg, ts, lr_world: int) -> Dict[str, Any]:
    """World-agnostic description of ``ts``'s sharded layout, written next
    to every checkpoint (``logical.json``). ``lr_world`` is the world size
    the run's lr scaling was computed with (the LAUNCH world — carried
    through elastic resumes so a reshape never changes the lr)."""
    meta: Dict[str, Any] = {
        "schema": LOGICAL_SCHEMA,
        "strategy": cfg.strategy,
        "world": int(getattr(strategy, "world_size", cfg.num_devices)),
        "global_batch": int(cfg.global_batch()),
        "lr_world": int(lr_world),
        "elastic_slices": cfg.elastic_slices,
        "kind": "replicated",
        "leaves": _leaf_meta(ts),
    }
    if getattr(strategy, "pipe_shard", False):
        rm = strategy._row_meta
        meta.update(
            kind="pipe_shard", dp=int(strategy.dp),
            stages=int(strategy.num_stages), vstages=int(strategy.vstages),
            buckets=int(max(1, cfg.comm_buckets)),
            length=int(rm.length), padded=int(rm.padded),
            bucket_padded=[int(b) for b in rm.bucket_padded])
    elif getattr(strategy, "shard_update", False) and \
            getattr(strategy, "_flat_meta", None) is not None:
        fm = strategy._flat_meta
        meta.update(
            kind="dp_shard", buckets=int(max(1, cfg.comm_buckets)),
            overlap=bool(getattr(strategy, "_overlap", False)),
            length=int(fm.length), padded=int(fm.padded),
            bucket_padded=[int(b) for b in fm.bucket_padded])
    return meta


def compare(saved: Optional[Dict[str, Any]], cur: Dict[str, Any],
            elastic: bool) -> Optional[str]:
    """None = shapes agree (plain restore); "reshard" = world-size mismatch
    the permutation pass covers. Raises :class:`CheckpointShapeError` when
    the mismatch is not covered, or is covered but ``elastic`` is False
    (with a warn-once pointer at --elastic-resume)."""
    global _warned_flag
    if saved is None:
        # pre-elastic checkpoint: no recorded shape to compare — restore as
        # before (a genuine mismatch still fails inside orbax, as it always
        # did for un-annotated checkpoints)
        return None
    schema = saved.get("schema")
    if schema != LOGICAL_SCHEMA:
        # a NEWER schema must fail loudly, not silently skip the shape
        # check and die in the orbax assert this module exists to remove
        raise CheckpointShapeError(
            f"checkpoint logical metadata has schema {schema!r}; this "
            f"build understands schema {LOGICAL_SCHEMA} — resume with a "
            f"build at least as new as the one that wrote the checkpoint")
    if saved.get("strategy") != cur["strategy"]:
        raise CheckpointShapeError(
            f"checkpoint was saved by the {saved.get('strategy')!r} strategy "
            f"but this run uses {cur['strategy']!r}; resharding converts "
            f"world sizes, not engines")
    if saved.get("kind") != cur["kind"]:
        raise CheckpointShapeError(
            f"checkpoint engine layout {saved.get('kind')!r} != current "
            f"{cur['kind']!r} (e.g. --dp-shard-update toggled between save "
            f"and resume); rerun with the saving run's engine flags")
    kind = cur["kind"]
    if kind == "pipe_shard" and (saved["stages"] != cur["stages"]
                                 or saved["vstages"] != cur["vstages"]):
        raise CheckpointShapeError(
            f"checkpoint stage split S={saved['stages']} V={saved['vstages']}"
            f" != current S={cur['stages']} V={cur['vstages']}: a changed "
            f"stage count is a re-planning problem, not a permutation — "
            f"with --plan auto the resume re-plans automatically (the "
            f"planner pins the stage count to the checkpoint's and "
            f"re-solves dp for the new world, partition/planner.py); "
            f"otherwise re-plan via --auto-partition at the new topology "
            f"and restart (elastic resume covers the 'data'-axis world "
            f"only)")
    if kind != "replicated" and saved.get("length") != cur.get("length"):
        raise CheckpointShapeError(
            f"checkpoint packed length {saved.get('length')} != current "
            f"{cur.get('length')}: the MODEL differs, not just the world")
    same = (saved.get("world") == cur["world"]
            and saved.get("padded") == cur.get("padded")
            and saved.get("bucket_padded") == cur.get("bucket_padded")
            and saved.get("dp", saved.get("world")) ==
            cur.get("dp", cur["world"])
            and bool(saved.get("overlap")) == bool(cur.get("overlap")))
    if same:
        return None
    if kind == "replicated":
        if saved.get("leaves") == cur.get("leaves"):
            # every leaf really is world-agnostic (the recorded shapes
            # equal the live strategy's): a changed world restores
            # cleanly — worth a note, not an error
            print(f"elastic resume: world changed {saved.get('world')} -> "
                  f"{cur['world']} (state shapes world-agnostic; no "
                  f"reshard needed)", flush=True)
            return None
        # "replicated" is the catch-all kind, and some engines under it
        # DO shape their state by the topology (hetero's [N, L] packed
        # rows, stage-packed matrices at a different split): claiming the
        # restore is safe would just move the crash into orbax
        raise CheckpointShapeError(
            f"checkpoint state shapes (saved at world {saved.get('world')})"
            f" differ from the live strategy's (world {cur['world']}) and "
            f"the {cur['strategy']!r} engine's layout has no reshard path "
            f"— elastic resume covers the dp ZeRO-1 and pipe-mesh hybrid "
            f"flat layouts; restart at the saved topology (or re-plan)")
    shapes = (f"saved world {saved.get('world')} "
              f"(dp {saved.get('dp', saved.get('world'))}, "
              f"buckets {saved.get('buckets')}, padded {saved.get('padded')})"
              f" vs current world {cur['world']} "
              f"(dp {cur.get('dp', cur['world'])}, buckets "
              f"{cur.get('buckets')}, padded {cur.get('padded')})")
    if not elastic:
        if not _warned_flag:
            print("WARNING: checkpoint world shape mismatches the current "
                  "mesh; pass --elastic-resume to reshard the ZeRO-1 flat "
                  "state through the topology-portable permutation path",
                  file=sys.stderr, flush=True)
            _warned_flag = True
        raise CheckpointShapeError(
            f"checkpoint/mesh world-shape mismatch: {shapes}; enable "
            f"--elastic-resume to reshard instead of crashing in orbax")
    return "reshard"


# ---- the permutation itself (pure numpy, f32-bitwise) ----------------------


def _content_lengths(meta):
    from ddlbench_tpu.parallel.common import bucket_content_lengths

    return bucket_content_lengths(meta)


def to_logical(flat: np.ndarray, meta) -> np.ndarray:
    """Padded bucket-layout vector -> the [length] logical vector (pads
    stripped). Inverse of :func:`from_logical`."""
    lens = _content_lengths(meta)
    parts = [flat[off:off + bl]
             for off, bl in zip(meta.bucket_offsets, lens)]
    return (np.concatenate(parts) if parts
            else flat[:0])


def from_logical(vec: np.ndarray, meta) -> np.ndarray:
    """[length] logical vector -> the padded bucket layout of ``meta``."""
    lens = _content_lengths(meta)
    parts: List[np.ndarray] = []
    c = 0
    for bp, bl in zip(meta.bucket_padded, lens):
        parts.append(vec[c:c + bl])
        c += bl
        if bp > bl:
            parts.append(np.zeros((bp - bl,), vec.dtype))
    return np.concatenate(parts) if parts else vec[:0]


def reshard_flat(vec: np.ndarray, meta_src, world_src: int, meta_dst,
                 world_dst: int, dm_src: bool = False,
                 dm_dst: bool = False) -> np.ndarray:
    """Convert one packed flat vector between world layouts along its LAST
    axis: (optional) undo the source device-major permutation, strip each
    source bucket's world padding, re-pad for the destination buckets, and
    (optionally) apply the destination device-major permutation. A pure
    index permutation plus zero pads — bitwise for any dtype."""
    from ddlbench_tpu.parallel.common import device_major_perm

    lead = vec.shape[:-1]
    flat = vec.reshape(-1, vec.shape[-1])
    if dm_src:
        _, inv = device_major_perm(meta_src, world_src)
        flat = flat[:, inv]
    out = np.stack([from_logical(to_logical(row, meta_src), meta_dst)
                    for row in flat])
    if dm_dst:
        perm, _ = device_major_perm(meta_dst, world_dst)
        out = out[:, perm]
    return out.reshape(*lead, meta_dst.padded)


# ---- the end-to-end elastic restore ---------------------------------------


def _abstract_saved(ts, saved: Dict[str, Any], strategy, mesh):
    """Abstract target mirroring ``ts``'s structure at the SAVED shapes
    (flat leaves resized to the saved padded length), replicated over the
    current mesh so orbax can restore it on any world size."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    rep = NamedSharding(mesh, P())
    padded_n = saved["padded"]

    def remap(leaf, flat: bool, axis_last: bool = False):
        shape = tuple(leaf.shape)
        if flat:
            shape = (shape[:-1] + (padded_n,)) if axis_last else (padded_n,)
        return jax.ShapeDtypeStruct(shape, leaf.dtype, sharding=rep)

    kind = saved["kind"]
    params, model_state, opt = ts.params, ts.model_state, ts.opt
    if kind == "dp_shard":
        overlap = bool(saved.get("overlap"))
        if overlap:
            # saved params = the flat device-major [padded_N] vector
            abs_params = jax.ShapeDtypeStruct((saved["padded"],),
                                              np.float32, sharding=rep)
        else:
            # saved params = the per-layer pytree. When the CURRENT engine
            # is overlapped, ts.params is flat — rebuild the pytree
            # structure from the (model-identical) flat meta instead.
            fm = strategy._flat_meta
            if getattr(strategy, "_overlap", False):
                leaves = [jax.ShapeDtypeStruct(s, d, sharding=rep)
                          for s, d in zip(fm.shapes, fm.dtypes)]
                abs_params = jax.tree.unflatten(fm.treedef, leaves)
            else:
                abs_params = jax.tree.map(lambda l: remap(l, False), params)
        abs_opt = {k: (remap(v, True) if k in ("m", "v")
                       else jax.tree.map(lambda l: remap(l, False), v))
                   for k, v in opt.items()}
    else:  # pipe_shard: flat row axis is the LAST axis of every row leaf
        abs_params = remap(params, True, axis_last=True)
        abs_opt = {k: (remap(v, True, axis_last=True) if k in ("m", "v")
                       else jax.tree.map(lambda l: remap(l, False), v))
                   for k, v in opt.items()}
    abs_state = jax.tree.map(lambda l: remap(l, False), model_state)
    return type(ts)(abs_params, abs_state, abs_opt)


def _dp_metas(strategy, saved: Dict[str, Any]):
    meta_src = strategy.flat_meta_for_world(saved["world"], saved["buckets"])
    if list(meta_src.bucket_padded) != list(saved["bucket_padded"]) or \
            meta_src.padded != saved["padded"]:
        raise CheckpointShapeError(
            f"reconstructed flat layout for world {saved['world']} x "
            f"{saved['buckets']} buckets (padded {meta_src.padded}, "
            f"{list(meta_src.bucket_padded)}) disagrees with the recorded "
            f"one (padded {saved['padded']}, {saved['bucket_padded']}): "
            f"the model or packing changed since the save")
    return meta_src, strategy._flat_meta


def _pipe_metas(strategy, saved: Dict[str, Any]):
    from ddlbench_tpu.parallel.common import row_flat_meta

    meta_src = row_flat_meta(saved["length"], saved["dp"], saved["buckets"])
    if list(meta_src.bucket_padded) != list(saved["bucket_padded"]) or \
            meta_src.padded != saved["padded"]:
        raise CheckpointShapeError(
            f"reconstructed row layout for dp {saved['dp']} x "
            f"{saved['buckets']} buckets disagrees with the recorded one: "
            f"the stage packing changed since the save")
    return meta_src, strategy._row_meta


def elastic_restore(info, ts, saved: Dict[str, Any], strategy, cfg):
    """Restore ``info``'s checkpoint (written at the saved world shape)
    into the CURRENT strategy's layout: orbax-restore at the saved shapes,
    permute every flat leaf between world layouts on the host, and
    device_put the result with the live target's shardings."""
    import jax

    from ddlbench_tpu.train.checkpoint import restore_info

    kind = saved["kind"]
    mesh = strategy.mesh
    abs_target = _abstract_saved(ts, saved, strategy, mesh)
    restored = restore_info(info, abs_target)

    if kind == "dp_shard":
        meta_src, meta_dst = _dp_metas(strategy, saved)
        world_src, world_dst = saved["world"], strategy.world_size
        overlap_src = bool(saved.get("overlap"))
        overlap_dst = bool(getattr(strategy, "_overlap", False))

        def conv(v, dm_s, dm_d):
            return reshard_flat(np.asarray(v), meta_src, world_src,
                                meta_dst, world_dst, dm_src=dm_s,
                                dm_dst=dm_d)

        params = restored.params
        if overlap_src and overlap_dst:
            params = conv(params, True, True)
        elif overlap_src and not overlap_dst:
            # flat device-major -> per-layer pytree (the saved run ran the
            # overlapped engine, this one does not)
            logical = to_logical(
                _undo_dm(np.asarray(restored.params), meta_src, world_src),
                meta_src)
            params = _unpack_logical(logical, meta_dst)
        elif not overlap_src and overlap_dst:
            logical = _pack_logical(restored.params)
            flat = from_logical(logical, meta_dst)
            params = flat[_dm_perm(meta_dst, world_dst)]
        # m/v live in the layout the per-device shard concatenation
        # produces — device-major (identity at one bucket, since the
        # shard engine only runs multi-bucket in overlap mode)
        opt = dict(restored.opt)
        for k in ("m", "v"):
            if k in opt:
                opt[k] = conv(opt[k], True, True)
        out = type(ts)(params, restored.model_state, opt)
    else:  # pipe_shard: every row leaf converts along its last axis,
        #       device-major on both sides (the rows live permuted)
        meta_src, meta_dst = _pipe_metas(strategy, saved)
        world_src, world_dst = saved["dp"], strategy.dp

        def conv(v):
            return reshard_flat(np.asarray(v), meta_src, world_src,
                                meta_dst, world_dst, dm_src=True,
                                dm_dst=True)

        opt = dict(restored.opt)
        for k in ("m", "v"):
            if k in opt:
                opt[k] = conv(opt[k])
        out = type(ts)(conv(restored.params), restored.model_state, opt)

    # land every leaf on the LIVE target's shardings (the converted values
    # are plain host arrays at this point)
    return jax.tree.map(
        lambda v, t: jax.device_put(np.asarray(v), t.sharding), out, ts)


def _dm_perm(meta, world):
    from ddlbench_tpu.parallel.common import device_major_perm

    return device_major_perm(meta, world)[0]


def _undo_dm(vec: np.ndarray, meta, world) -> np.ndarray:
    from ddlbench_tpu.parallel.common import device_major_perm

    _, inv = device_major_perm(meta, world)
    return vec[inv]


def _pack_logical(params) -> np.ndarray:
    """Per-layer params pytree -> the [length] logical f32 vector (the
    concatenated raveled leaves — pack_flat without the pads)."""
    import jax

    leaves = jax.tree.leaves(params)
    if not leaves:
        return np.zeros((0,), np.float32)
    return np.concatenate([np.asarray(l).astype(np.float32).ravel()
                           for l in leaves])


def _unpack_logical(vec: np.ndarray, meta):
    """[length] logical vector -> the per-layer pytree of ``meta``."""
    import jax

    out = []
    off = 0
    for size, shape, dtype in zip(meta.sizes, meta.shapes, meta.dtypes):
        out.append(vec[off:off + size].reshape(shape).astype(dtype))
        off += size
    return jax.tree.unflatten(meta.treedef, out)
