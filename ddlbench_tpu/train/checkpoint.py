"""Crash-consistent checkpoint/resume for sharded train states.

Reference behavior (the only checkpointing in DDLBench lives in the PipeDream
runtime): per-stage files ``checkpoint.{stage}.pth.tar`` holding
epoch/arch/state_dict/optimizer, written by rank 0 of each stage per epoch and
restored before resuming (main_with_runtime.py:393-403,580-584,:241-262) —
plain ``torch.save`` with no commit protocol: a crash mid-write leaves a
truncated file that the restore happily loads or dies on.

TPU-native equivalent: one orbax checkpoint of the whole (sharded) train-state
pytree, wrapped in an explicit **atomic commit protocol**:

1. orbax writes the state under ``<name>.tmp/state``;
2. ``resume.json`` (epoch, interior step, global step, metric-logger
   counters, seed) is written next to it, and — when the caller supplies
   it — ``logical.json``, the topology-portable metadata (leaf
   shapes/dtypes, flat-meta bucket layout, the world/dp/stage shape the
   state was saved under; see train/reshard.py) that lets an N-chip
   checkpoint resume on M chips;
3. a ``COMMIT.json`` marker — carrying a manifest of every file's size and
   SHA-256, metadata files included — is written + fsynced *last*;
4. the ``.tmp`` directory is atomically renamed to its final name and the
   parent directory fsynced.

A crash at any point leaves either a ``.tmp`` directory without a marker
(ignored and GC'd) or a fully committed checkpoint. ``latest_valid`` walks
checkpoints newest-first, verifies each against its manifest (catching
truncation AND bit flips, e.g. the ``ckpt-corrupt`` fault), logs what it
skips, and falls back to the previous good one. ``--keep-checkpoints N``
bounds retention.

Checkpoints come in two granularities: per-epoch (``epoch_N``, resume
restarts at epoch N+1 — the historical behavior) and per-step
(``epoch_N_step_S``, written every ``--checkpoint-every-steps K`` steps).
Step checkpoints carry the *full* resume state — the interior data-iterator
position is just the step index (every data source is (epoch, step)
addressed, and the per-epoch RNG streams are pure fold-ins of
``(seed, epoch, step)``), so a mid-epoch resume replays the identical
trajectory bit-for-bit (pinned by tests/test_faults.py).

The pipeline strategies' packed ``[S, L]`` stage matrices are sharded over
the 'stage' mesh axis, so orbax's OCDBT layout naturally writes per-stage
shards — the same on-disk decomposition as the reference's per-stage files,
without per-rank coordination code.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import re
import shutil
from typing import Any, Dict, List, Optional, Tuple

import jax

from ddlbench_tpu import faults

COMMIT_MARKER = "COMMIT.json"
RESUME_META = "resume.json"
# topology-portable logical metadata (train/reshard.py): leaf shapes,
# flat-meta bucket layout, and the world/dp/stage shape the state was saved
# under — what lets an N-chip checkpoint resume on M chips
LOGICAL_META = "logical.json"
_STATE_SUBDIR = "state"
_NAME_RE = re.compile(r"^epoch_(\d+)(?:_step_(\d+))?$")


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def checkpoint_name(epoch: int, step: Optional[int] = None) -> str:
    return f"epoch_{epoch}" if step is None else f"epoch_{epoch}_step_{step}"


def _parse_name(name: str) -> Optional[Tuple[int, Optional[int]]]:
    m = _NAME_RE.match(name)
    if not m:
        return None
    return int(m.group(1)), (int(m.group(2)) if m.group(2) else None)


def _order_key(epoch: int, step: Optional[int]) -> Tuple[int, float]:
    # within an epoch, the epoch-end checkpoint outranks any interior step
    return (epoch, float("inf") if step is None else float(step))


def _fsync_path(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _manifest(root: str, skip: Tuple[str, ...] = (COMMIT_MARKER,)) -> Dict[str, Dict[str, Any]]:
    out: Dict[str, Dict[str, Any]] = {}
    for dirpath, _, files in os.walk(root):
        for name in files:
            p = os.path.join(dirpath, name)
            rel = os.path.relpath(p, root)
            if rel in skip:
                continue
            # fsync every payload file while building the manifest: the
            # COMMIT marker's durability claim (marker present => every
            # other byte durable) needs the orbax-written data flushed too,
            # not just our own metadata files — a directory fsync does not
            # flush file CONTENTS
            _fsync_path(p)
            out[rel] = {"size": os.path.getsize(p), "sha256": _sha256(p)}
    return out


@dataclasses.dataclass(frozen=True)
class CheckpointInfo:
    """One committed checkpoint: coordinates + on-disk path + resume meta."""

    epoch: int
    step: Optional[int]  # interior step index of the LAST COMPLETED step
    path: str
    meta: Dict[str, Any]

    @property
    def mid_epoch(self) -> bool:
        return self.step is not None


def save_checkpoint(ckpt_dir: str, epoch: int, train_state: Any,
                    step: Optional[int] = None,
                    global_step: Optional[int] = None,
                    logger_state: Optional[Dict[str, Any]] = None,
                    seed: Optional[int] = None,
                    keep: Optional[int] = None,
                    pin: Optional[str] = None,
                    logical: Optional[Dict[str, Any]] = None) -> str:
    """Atomically commit ``train_state`` under ``<ckpt_dir>/<name>``.

    ``step`` (interior, 0-based index of the last completed step) selects the
    step-granular name; None is the per-epoch checkpoint. Returns the
    committed path. ``keep`` applies the retention policy after the commit
    (see :func:`gc_checkpoints`); ``pin`` names a checkpoint retention must
    never drop — the loop pins its current rewind/resume target so a stale
    but marker-bearing (possibly corrupt) newer checkpoint cannot crowd the
    only verified-restorable state out of the window.
    """
    ckpt_dir = os.path.abspath(ckpt_dir)
    os.makedirs(ckpt_dir, exist_ok=True)
    name = checkpoint_name(epoch, step)
    final = os.path.join(ckpt_dir, name)
    tmp = final + ".tmp"
    if os.path.isdir(tmp):  # stale tmp from a crashed save: never trusted
        shutil.rmtree(tmp)

    ckptr = _checkpointer()
    ckptr.save(os.path.join(tmp, _STATE_SUBDIR), train_state, force=True)
    ckptr.wait_until_finished()

    meta = {
        "epoch": epoch,
        "step": step,
        "global_step": global_step,
        "seed": seed,
        "logger": logger_state,
    }
    meta_path = os.path.join(tmp, RESUME_META)
    with open(meta_path, "w") as f:
        json.dump(meta, f)
        f.flush()
        os.fsync(f.fileno())

    if logical is not None:
        # topology-portable metadata (train/reshard.logical_meta) — written
        # INSIDE the tmp dir before the marker, so the manifest below
        # covers it exactly like resume.json and the orbax payload: a torn
        # metadata file fails verification and latest_valid falls back
        with open(os.path.join(tmp, LOGICAL_META), "w") as f:
            json.dump(logical, f)
            f.flush()
            os.fsync(f.fileno())

    # COMMIT marker last: its presence asserts every other byte is durable
    # and its manifest (size + sha256 per file) is what latest_valid verifies
    marker = {"epoch": epoch, "step": step, "files": _manifest(tmp)}
    marker_path = os.path.join(tmp, COMMIT_MARKER)
    with open(marker_path, "w") as f:
        json.dump(marker, f)
        f.flush()
        os.fsync(f.fileno())
    _fsync_path(tmp)
    # force-overwrite semantics (orbax parity) — deferred until the tmp is
    # fully durable, so a same-name re-save that dies mid-write can only
    # lose the old copy in this rmtree->rename gap, not during the whole
    # (slow) orbax save above
    if os.path.isdir(final):
        shutil.rmtree(final)
    os.rename(tmp, final)
    _fsync_path(ckpt_dir)
    # fault hook: ckpt-corrupt damages the just-committed checkpoint
    faults.checkpoint_saved(final, epoch, step)
    if keep is not None:
        gc_checkpoints(ckpt_dir, keep, pin=pin)
    return final


def list_checkpoints(ckpt_dir: str) -> List[Tuple[int, Optional[int], str]]:
    """All checkpoint-named entries (committed or not), oldest first."""
    if not os.path.isdir(ckpt_dir):
        return []
    found = []
    for name in os.listdir(ckpt_dir):
        parsed = _parse_name(name)
        if parsed is not None:
            found.append((*parsed, os.path.join(ckpt_dir, name)))
    found.sort(key=lambda t: _order_key(t[0], t[1]))
    return found


def is_legacy_checkpoint(path: str) -> bool:
    """True for a pre-commit-protocol checkpoint: no COMMIT marker AND the
    legacy on-disk layout (orbax files directly under ``epoch_N``, no
    ``state`` subdir). Under the new protocol a marker-less FINAL-named
    directory cannot be a crash remnant — saves build under ``.tmp`` and
    publish by atomic rename only after the marker — so this shape can only
    be a checkpoint written before the protocol existed. It is restorable
    (``_restore_path`` handles the layout) but unverifiable."""
    return (os.path.isdir(path)
            and not os.path.exists(os.path.join(path, COMMIT_MARKER))
            and not os.path.isdir(os.path.join(path, _STATE_SUBDIR))
            and bool(os.listdir(path)))


def verify_checkpoint(path: str) -> Optional[str]:
    """None if ``path`` is a committed, manifest-clean checkpoint; else the
    human-readable reason it is invalid."""
    marker_path = os.path.join(path, COMMIT_MARKER)
    if not os.path.exists(marker_path):
        return "no COMMIT marker (crashed mid-save?)"
    try:
        with open(marker_path) as f:
            marker = json.load(f)
        files = marker["files"]
    except (OSError, ValueError, KeyError) as e:
        return f"unreadable COMMIT marker ({e})"
    for rel, want in files.items():
        p = os.path.join(path, rel)
        if not os.path.exists(p):
            return f"missing file {rel}"
        size = os.path.getsize(p)
        if size != want["size"]:
            return f"size mismatch on {rel} ({size} != {want['size']})"
        if _sha256(p) != want["sha256"]:
            return f"checksum mismatch on {rel} (corrupt?)"
    return None


def latest_valid(ckpt_dir: str) -> Optional[CheckpointInfo]:
    """Newest committed + verified checkpoint, falling back past invalid ones.

    Walks newest-first; anything uncommitted (no marker — e.g. a crash
    mid-save left only ``.tmp``, or a crash between orbax and the marker),
    truncated, or bit-flipped is skipped WITH A LOG LINE, and the previous
    good checkpoint wins. Returns None when nothing valid exists.
    """
    for epoch, step, path in reversed(list_checkpoints(ckpt_dir)):
        if is_legacy_checkpoint(path):
            # pre-protocol checkpoint: restorable but carries no manifest.
            # Accepting it (with a log) beats silently restarting a user's
            # run from scratch; anything torn in it fails loudly at restore.
            print(f"checkpoint: {os.path.basename(path)} predates the "
                  f"commit protocol (no manifest); restoring unverified",
                  flush=True)
            return CheckpointInfo(epoch, step, path,
                                  {"epoch": epoch, "step": step})
        reason = verify_checkpoint(path)
        if reason is not None:
            print(f"checkpoint: skipping {os.path.basename(path)}: {reason}",
                  flush=True)
            continue
        meta: Dict[str, Any] = {}
        try:
            with open(os.path.join(path, RESUME_META)) as f:
                meta = json.load(f)
        except (OSError, ValueError):
            meta = {"epoch": epoch, "step": step}
        return CheckpointInfo(epoch, step, path, meta)
    return None


def load_logical(path: str) -> Optional[Dict[str, Any]]:
    """The checkpoint's logical (topology-portable) metadata, or None for
    pre-elastic checkpoints. ``latest_valid`` has already verified the
    file against the commit manifest by the time a resume reads it, so an
    unreadable file here is a programming error, not media corruption."""
    p = os.path.join(path, LOGICAL_META)
    if not os.path.exists(p):
        return None
    with open(p) as f:
        return json.load(f)


def gc_checkpoints(ckpt_dir: str, keep: int,
                   pin: Optional[str] = None) -> List[str]:
    """Retention policy: keep the newest ``keep`` restorable checkpoints
    (committed ones AND pre-protocol legacy ones — legacy dirs are real
    user data, never remnants), delete everything older, plus stale
    ``.tmp`` directories and marker-less NEW-layout directories (those are
    unreachable states under the protocol: tampered or hand-copied, never
    restorable). Restorability here is a marker/layout check, not a full
    manifest verification — GC runs after every save and must not re-hash
    the whole retention window.

    ``pin`` (a path) is exempt from the age-out: the train loop pins its
    current rewind/resume target, so a NEWER but post-commit-corrupted
    checkpoint (marker present, manifest broken — undetectable without the
    re-hash GC must not pay) can never crowd the one checkpoint the run is
    known to be able to restore out of the window. Returns deleted paths.
    """
    if keep < 1:
        raise ValueError("keep-checkpoints must be >= 1")
    deleted = []
    if not os.path.isdir(ckpt_dir):
        return deleted
    for name in os.listdir(ckpt_dir):
        if name.endswith(".tmp") and _parse_name(name[:-4]) is not None:
            shutil.rmtree(os.path.join(ckpt_dir, name), ignore_errors=True)
            deleted.append(os.path.join(ckpt_dir, name))

    def _restorable(p: str) -> bool:
        return (os.path.exists(os.path.join(p, COMMIT_MARKER))
                or is_legacy_checkpoint(p))

    pin_real = os.path.realpath(pin) if pin else None
    entries = list_checkpoints(ckpt_dir)
    keepers = [t for t in entries if _restorable(t[2])]
    drop = keepers[:-keep] if len(keepers) > keep else []
    drop = [t for t in drop if os.path.realpath(t[2]) != pin_real]
    remnants = [t for t in entries if not _restorable(t[2])]
    for _, _, path in drop + remnants:
        shutil.rmtree(path, ignore_errors=True)
        deleted.append(path)
        print(f"checkpoint: retention dropped {os.path.basename(path)}",
              flush=True)
    return deleted


def latest_epoch(ckpt_dir: str) -> Optional[int]:
    """Newest epoch number present by NAME (committed or not) — the legacy
    existence probe. Resume paths should use :func:`latest_valid`."""
    epochs = [e for e, s, _ in list_checkpoints(ckpt_dir) if s is None]
    return max(epochs) if epochs else None


def _abstract_like(target: Any):
    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        if isinstance(x, jax.Array) else x,
        target,
    )


def _restore_path(path: str, target: Any) -> Any:
    state_path = os.path.join(path, _STATE_SUBDIR)
    if not os.path.isdir(state_path):
        state_path = path  # legacy layout: orbax state directly at <name>/
    return _checkpointer().restore(state_path, _abstract_like(target))


def restore_checkpoint(ckpt_dir: str, target: Any,
                       epoch: Optional[int] = None) -> Tuple[int, Any]:
    """Restore the given (or latest valid) EPOCH checkpoint into target's
    structure/shardings.

    ``target`` is a live train state (e.g. freshly init'd) supplying pytree
    structure, dtypes, and shardings. Returns (epoch, restored_state).
    """
    if epoch is None:
        info = latest_valid(ckpt_dir)
        if info is None:
            raise FileNotFoundError(
                f"no valid checkpoints under {ckpt_dir!r}")
        return info.epoch, _restore_path(info.path, target)
    path = os.path.join(os.path.abspath(ckpt_dir), checkpoint_name(epoch))
    if not os.path.isdir(path):
        raise FileNotFoundError(f"no checkpoint {path!r}")
    return epoch, _restore_path(path, target)


def restore_info(info: CheckpointInfo, target: Any) -> Any:
    """Restore the state of an already-validated :class:`CheckpointInfo`."""
    return _restore_path(info.path, target)
