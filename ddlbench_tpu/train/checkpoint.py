"""Checkpoint/resume for sharded train states.

Reference behavior (the only checkpointing in DDLBench lives in the PipeDream
runtime): per-stage files ``checkpoint.{stage}.pth.tar`` holding
epoch/arch/state_dict/optimizer, written by rank 0 of each stage per epoch and
restored before resuming (main_with_runtime.py:393-403,580-584,:241-262).

TPU-native equivalent: one orbax checkpoint of the whole (sharded) train-state
pytree per epoch. The pipeline strategies' packed ``[S, L]`` stage matrices are
sharded over the 'stage' mesh axis, so orbax's OCDBT layout naturally writes
per-stage shards — the same on-disk decomposition as the reference's per-stage
files, without per-rank coordination code.
"""

from __future__ import annotations

import os
from typing import Any, Optional, Tuple

import jax


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def save_checkpoint(ckpt_dir: str, epoch: int, train_state: Any) -> str:
    """Write train_state under <ckpt_dir>/epoch_<n>; returns the path."""
    path = os.path.join(os.path.abspath(ckpt_dir), f"epoch_{epoch}")
    ckptr = _checkpointer()
    ckptr.save(path, train_state, force=True)
    ckptr.wait_until_finished()
    return path


def latest_epoch(ckpt_dir: str) -> Optional[int]:
    if not os.path.isdir(ckpt_dir):
        return None
    epochs = []
    for name in os.listdir(ckpt_dir):
        if name.startswith("epoch_"):
            try:
                epochs.append(int(name.split("_", 1)[1]))
            except ValueError:
                continue
    return max(epochs) if epochs else None


def restore_checkpoint(ckpt_dir: str, target: Any,
                       epoch: Optional[int] = None) -> Tuple[int, Any]:
    """Restore the given (or latest) epoch into target's structure/shardings.

    ``target`` is a live train state (e.g. freshly init'd) supplying pytree
    structure, dtypes, and shardings. Returns (epoch, restored_state).
    """
    epoch = epoch if epoch is not None else latest_epoch(ckpt_dir)
    if epoch is None:
        raise FileNotFoundError(f"no checkpoints under {ckpt_dir!r}")
    path = os.path.join(os.path.abspath(ckpt_dir), f"epoch_{epoch}")
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        if isinstance(x, jax.Array) else x,
        target,
    )
    restored = _checkpointer().restore(path, abstract)
    return epoch, restored
