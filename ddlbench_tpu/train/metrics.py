"""Metrics accumulation and the log-line schema.

The reference's observability is print-based and machine-scraped; the exact line
formats are its public metric interface (SURVEY.md §5.5):

* per-interval train line: ``train | <e>/<E> epoch (<p>%) | <X> samples/sec | ...``
  with peak memory (benchmark/mnist/mnist_pytorch.py:79-97),
* final summary: ``valid accuracy: <A> | <X> samples/sec, <S> sec/epoch (average)``
  (benchmark/mnist/mnist_pytorch.py:225-226),
* ``AverageMeter`` val/avg accumulators
  (pipedream-fork/runtime/image_classification/main_with_runtime.py:587-602).

We keep the same schema so the reference's log scrapers
(pipedream-fork/runtime/scripts/process_output.py) would parse our output, and
substitute TPU HBM stats (jax ``memory_stats``) for ``torch.cuda.memory_stats``.
"""

from __future__ import annotations

import json
import sys
import time
from typing import Any, Dict, Optional

import jax


class AverageMeter:
    """Running value/average/sum/count accumulator.

    Reference-parity API (PipeDream's AverageMeter,
    main_with_runtime.py:587-602 — SURVEY.md §5.5), kept exported for
    external consumers even though the benchmark loop itself now
    accumulates metrics on device (train/loop.py) rather than through
    host-side meters."""

    def __init__(self, name: str = ""):
        self.name = name
        self.reset()

    def reset(self) -> None:
        self.val = 0.0
        self.avg = 0.0
        self.sum = 0.0
        self.count = 0

    def update(self, val: float, n: int = 1) -> None:
        self.val = float(val)
        self.sum += float(val) * n
        self.count += n
        self.avg = self.sum / max(1, self.count)


def device_memory_gb(device: Optional[Any] = None) -> Dict[str, float]:
    """Peak/in-use device memory in GB (TPU analog of torch.cuda.memory_stats)."""
    try:
        dev = device or jax.local_devices()[0]
        stats = dev.memory_stats() or {}
    except Exception:
        stats = {}
    gb = 1024.0**3
    return {
        "in_use": stats.get("bytes_in_use", 0) / gb,
        "peak": stats.get("peak_bytes_in_use", stats.get("bytes_in_use", 0)) / gb,
        "limit": stats.get("bytes_limit", 0) / gb,
    }


class MetricLogger:
    """Produces the reference-schema log lines plus a structured JSONL stream."""

    def __init__(self, total_epochs: int, log_interval: int = 25, jsonl_path: Optional[str] = None, rank: int = 0):
        self.total_epochs = total_epochs
        self.log_interval = log_interval
        self.rank = rank
        self._jsonl = open(jsonl_path, "a") if jsonl_path else None
        self.epoch_throughputs: list[float] = []
        self.epoch_times: list[float] = []
        # per-epoch input-stall time (data/prefetch.py): how long the train
        # loop sat blocked waiting for input — the signal that separates
        # input-bound from compute-bound regimes in the throughput curves
        self.epoch_stall_ms: list[float] = []
        # per-epoch validation curve (reference protocol: one validation
        # accuracy per train epoch, mnist_pytorch.py:102-133); surfaced in
        # summary() so accuracy-parity artifacts carry the full curve
        self.valid_history: list[Dict[str, float]] = []

    def _emit(self, line: str, record: Dict[str, Any]) -> None:
        if self.rank == 0:
            print(line, flush=True)
            if self._jsonl:
                self._jsonl.write(json.dumps(record) + "\n")
                self._jsonl.flush()

    def train_interval(self, epoch: int, progress_pct: float, samples_per_sec: float, loss: float) -> None:
        mem = device_memory_gb()
        line = (
            f"train | {epoch}/{self.total_epochs} epoch ({progress_pct:.0f}%) | "
            f"{samples_per_sec:.2f} samples/sec | loss {loss:.4f} | "
            f"mem {mem['in_use']:.2f} GB in use, {mem['peak']:.2f} GB peak"
        )
        self._emit(
            line,
            {
                "kind": "train_interval",
                "epoch": epoch,
                "progress_pct": progress_pct,
                "samples_per_sec": samples_per_sec,
                "loss": loss,
                **{f"mem_{k}_gb": v for k, v in mem.items()},
            },
        )

    def epoch_done(self, epoch: int, samples_per_sec: float, epoch_seconds: float,
                   input_stall_ms: Optional[float] = None,
                   step_ms: Optional[Dict[str, float]] = None) -> None:
        self.epoch_throughputs.append(samples_per_sec)
        self.epoch_times.append(epoch_seconds)
        line = (
            f"epoch {epoch}/{self.total_epochs} done | {samples_per_sec:.2f} samples/sec | "
            f"{epoch_seconds:.2f} sec"
        )
        record = {
            "kind": "epoch",
            "epoch": epoch,
            "samples_per_sec": samples_per_sec,
            "epoch_seconds": epoch_seconds,
        }
        if input_stall_ms is not None:
            # appended so the reference-schema prefix keeps matching existing
            # scrapers (same convention as the valid line's top5 suffix)
            self.epoch_stall_ms.append(input_stall_ms)
            line += f" | input stall {input_stall_ms:.1f} ms"
            record["input_stall_ms"] = input_stall_ms
        if step_ms:
            # step-latency percentiles (telemetry/stats.py) — appended after
            # the stall field, same suffix convention
            line += (f" | step p50 {step_ms['p50_ms']:.2f} ms, "
                     f"p95 {step_ms['p95_ms']:.2f} ms")
            record["step_time_p50_ms"] = step_ms["p50_ms"]
            record["step_time_p95_ms"] = step_ms["p95_ms"]
            record["step_time_p99_ms"] = step_ms["p99_ms"]
            record["step_time_max_ms"] = step_ms["max_ms"]
        self._emit(line, record)

    def valid_epoch(self, epoch: int, loss: float, accuracy: float,
                    top5: Optional[float] = None) -> None:
        line = (f"valid | {epoch}/{self.total_epochs} epoch | "
                f"loss {loss:.4f} | accuracy {accuracy:.4f}")
        record = {"kind": "valid", "epoch": epoch, "loss": loss,
                  "accuracy": accuracy}
        hist = {"epoch": epoch, "loss": loss, "accuracy": accuracy}
        if top5 is not None:
            # prec@5 (PipeDream parity); appended so top-1-only scrapers
            # keep matching the line prefix
            line += f" | top5 {top5:.4f}"
            record["top5"] = top5
            hist["top5"] = top5
        # keyed by epoch: a post-resume re-validation of an epoch restored
        # from a checkpoint (train/loop.py) replaces the restored entry
        # instead of duplicating it in the summary's curve
        self.valid_history = [h for h in self.valid_history
                              if h["epoch"] != epoch] + [hist]
        self._emit(line, record)

    def summary(self, valid_accuracy: float,
                step_time: Optional[Dict[str, float]] = None) -> Dict[str, float]:
        """Final line matching mnist_pytorch.py:225-226's schema.

        ``step_time`` is the run-level step-latency aggregate
        (telemetry/stats.py ``StepLatencyStats.run_summary``): percentiles
        over all recorded steps plus the warmup/compile accounting. The
        printed line keeps the reference schema; the JSONL record and the
        returned dict carry the percentiles.
        """
        avg_tp = sum(self.epoch_throughputs) / max(1, len(self.epoch_throughputs))
        avg_t = sum(self.epoch_times) / max(1, len(self.epoch_times))
        record = {
            "kind": "summary",
            "valid_accuracy": valid_accuracy,
            "samples_per_sec": avg_tp,
            "sec_per_epoch": avg_t,
        }
        result = {
            "valid_accuracy": valid_accuracy,
            "samples_per_sec": avg_tp,
            "sec_per_epoch": avg_t,
            # full per-epoch curve (printed lines keep the reference
            # schema; the dict is the structured superset)
            "valid_history": list(self.valid_history),
        }
        if step_time:
            extras = {
                "step_time_p50_ms": step_time["p50_ms"],
                "step_time_p95_ms": step_time["p95_ms"],
                "step_time_p99_ms": step_time["p99_ms"],
                "step_time_max_ms": step_time["max_ms"],
            }
            if "warmup_compile_s" in step_time:
                extras["warmup_compile_s"] = step_time["warmup_compile_s"]
            record.update(extras)
            result.update(extras)
        if self.epoch_stall_ms:
            result["input_stall_ms_per_epoch"] = (
                sum(self.epoch_stall_ms) / len(self.epoch_stall_ms))
        self._emit(
            f"valid accuracy: {valid_accuracy:.4f} | "
            f"{avg_tp:.2f} samples/sec, {avg_t:.2f} sec/epoch (average)",
            record,
        )
        return result

    def state_dict(self) -> Dict[str, Any]:
        """Resumable counters (checkpointed by train/loop.py so a restarted
        run's summary covers the WHOLE trajectory, not just the tail after
        the last crash)."""
        return {
            "epoch_throughputs": list(self.epoch_throughputs),
            "epoch_times": list(self.epoch_times),
            "epoch_stall_ms": list(self.epoch_stall_ms),
            "valid_history": [dict(h) for h in self.valid_history],
        }

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.epoch_throughputs = list(state.get("epoch_throughputs", []))
        self.epoch_times = list(state.get("epoch_times", []))
        self.epoch_stall_ms = list(state.get("epoch_stall_ms", []))
        self.valid_history = [dict(h)
                              for h in state.get("valid_history", [])]

    def close(self) -> None:
        if self._jsonl:
            self._jsonl.close()
            self._jsonl = None


class Stopwatch:
    def __init__(self) -> None:
        self.t0 = time.perf_counter()

    def lap(self) -> float:
        now = time.perf_counter()
        dt = now - self.t0
        self.t0 = now
        return dt
