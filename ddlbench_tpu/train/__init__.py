"""Train-loop package. The metric re-exports are lazy (PEP 562): metrics
imports jax, but jax-free consumers of sibling submodules (the chaosbench
supervisor reaching train.watchdog, tools parsing args) run this package
init on the way in and must not pay the multi-second jax import for it."""

__all__ = ["AverageMeter", "MetricLogger"]


def __getattr__(name):
    if name in __all__:
        from ddlbench_tpu.train import metrics

        return getattr(metrics, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
