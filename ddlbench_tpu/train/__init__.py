from ddlbench_tpu.train.metrics import AverageMeter, MetricLogger

__all__ = ["AverageMeter", "MetricLogger"]
