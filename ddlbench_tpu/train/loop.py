"""The benchmark train/eval loop — shared by all strategies.

Parity with the reference's per-driver loops (benchmark/mnist/mnist_pytorch.py:
train_epoch :52-99, test_epoch :102-133, summary :222-226): `epochs` training
epochs, per-LOGINTER throughput/memory lines, one validation epoch per training
epoch, and a final averaged summary. The loop is strategy-agnostic; all
device-side work lives in the strategy's jitted steps.
"""

from __future__ import annotations

import contextlib
import os
import sys
import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from ddlbench_tpu import faults
from ddlbench_tpu.config import RunConfig
from ddlbench_tpu.data.prefetch import Prefetcher
from ddlbench_tpu.data.synthetic import make_synthetic
from ddlbench_tpu.guard import (GracefulPreemption, GuardRewind,
                                PreemptionHandler, StabilityGuard)
from ddlbench_tpu.parallel.api import make_strategy
from ddlbench_tpu.telemetry import (StepLatencyStats, Tracer,
                                    export_chrome_trace, get_tracer,
                                    set_tracer)
from ddlbench_tpu.train.metrics import MetricLogger
from ddlbench_tpu.train.watchdog import (HangWatchdog, TrainingFailure,
                                         check_finite)
from ddlbench_tpu.parallel.common import step_decay_lr

_NULL_CTX = contextlib.nullcontext()


class _XlaWindow:
    """Windowed jax.profiler capture: ``--xla-trace-steps A:B`` profiles
    global train steps [A, B) into ``trace_dir`` (device timelines stay
    small enough to open; the host trace covers the whole run). With no
    window configured every call is a no-op."""

    def __init__(self, cfg: RunConfig):
        self.window = cfg.xla_trace_steps
        self.trace_dir = cfg.trace_dir
        self.active = False
        self.done = False

    def step(self, gstep: int, sync) -> None:
        """Called before dispatching global step ``gstep``; ``sync()`` must
        block until the device drained (used to close the window)."""
        if self.window is None or self.done:
            return
        start, stop = self.window
        if not self.active and start <= gstep < stop:
            jax.profiler.start_trace(self.trace_dir)
            self.active = True
        elif self.active and gstep >= stop:
            sync()
            self.close()

    def close(self) -> None:
        if self.active:
            jax.profiler.stop_trace()
            self.active = False
            self.done = True
            print(f"xla profile (steps {self.window[0]}:{self.window[1]}) "
                  f"written to {self.trace_dir}", flush=True)


def _write_audit(cfg: RunConfig, strategy) -> None:
    """--audit PATH: AOT-lower the train step at the run's exact shapes,
    extract the compiled-program manifest (flops, HBM components, the
    per-collective ledger out of the optimized HLO), tie it to comm_stats,
    and write the ledger next to the run's JSON. Lowering with a synthetic
    shape-double never executes and never consumes the real data stream;
    with `--plan auto` + a persisted plan the planner's per-stage HBM
    error also lands in partition.json (plan_auto.hbm_audit)."""
    from ddlbench_tpu.telemetry.audit import (lower_manifest,
                                              planner_stage_hbm_audit,
                                              reconcile_train,
                                              record_hbm_audit,
                                              write_manifests)
    from ddlbench_tpu.distributed import record_provenance

    prov = record_provenance(None, "train --audit")
    probe = make_synthetic(cfg.dataset(), cfg.global_batch(),
                           steps_per_epoch=1)
    ts0 = strategy.init(jax.random.key(cfg.seed))
    x0, y0 = probe.batch(0, 0)
    jit_step = getattr(strategy, "_jit_train_step", None) \
        or strategy.train_step
    man = lower_manifest(
        jit_step, (ts0, *strategy.shard_batch(x0, y0),
                   jnp.float32(cfg.resolved_lr())),
        name=f"train/{cfg.strategy}/{cfg.arch}@{cfg.num_devices}",
        mesh=getattr(strategy, "mesh", None))
    man["reconcile"] = reconcile_train(strategy, man)
    if cfg.plan == "auto" and cfg.checkpoint_dir:
        # the resolved plan is persisted; grade its HBM model against
        # memory_analysis() and record the signed per-stage error there
        import json as _json

        from ddlbench_tpu.parallel.api import _plan_path

        path = _plan_path(cfg)
        if path and os.path.exists(path):
            with open(path) as f:
                winner = _json.load(f).get("plan_auto", {}).get("winner")
            if winner:
                hbm = planner_stage_hbm_audit(winner, man,
                                              cfg.num_devices)
                man["hbm_audit"] = hbm
                if hbm is not None:
                    record_hbm_audit(cfg, hbm)
    write_manifests(cfg.audit, [man],
                    header={**prov, "tool": "train"})
    rec = man["reconcile"]
    print(f"audit: manifest -> {cfg.audit} "
          f"(tieable={rec['tieable']} ok={rec.get('ok')})", flush=True)


def run_benchmark(cfg: RunConfig, strategy=None, logger: Optional[MetricLogger] = None,
                  warmup_steps: int = 1) -> Dict[str, Any]:
    """Run the full 3-epoch benchmark protocol; returns the summary dict."""
    cfg.validate()
    if cfg.plan == "auto" and strategy is None:
        # --plan auto resolves BEFORE anything reads the config: the
        # rewritten strategy shapes the data stream's global batch, the
        # lr world-scaling, and the checkpoint metadata exactly as the
        # explicitly-flagged equivalent run would (the bitwise contract).
        from ddlbench_tpu.partition.planner import resolve_auto_plan

        def _probe_input_ms(cfg=cfg):
            # real data: price the host loader into the solve exactly as
            # --auto-partition prices it into stage 0 (fold_input_node).
            # A throwaway probe stream keeps the real one unconsumed; the
            # pre-plan global batch equals the post-plan one (the rewrite
            # preserves it), so the per-microbatch scaling is exact. Only
            # evaluated on a plan-cache MISS (resolve_auto_plan).
            from ddlbench_tpu.profiler.profile import measure_input_ms

            probe = _make_data(cfg)
            try:
                global_ms = measure_input_ms(probe)
            finally:
                getattr(probe, "close", lambda: None)()
            mb_pre, _ = cfg.resolved_batches()
            ms = global_ms * mb_pre / cfg.global_batch()
            print(f"plan auto: measured input cost "
                  f"{global_ms:.2f} ms/global-batch "
                  f"({ms:.3f} ms/microbatch)", flush=True)
            return ms

        cfg = resolve_auto_plan(
            cfg, input_time_ms=0.0 if cfg.synthetic else _probe_input_ms)
    data = _make_data(cfg)
    if strategy is None:
        input_ms = 0.0
        if (cfg.auto_partition and not cfg.synthetic
                and cfg.strategy in ("gpipe", "pipedream")):
            # Input-node cost for the partitioner (reference parity:
            # profiler main.py:388-407): measure the on-disk loader's fetch
            # cost so --auto-partition prices host-side data loading into
            # stage 0. A throwaway loader instance keeps the real training
            # stream unconsumed, and the per-GLOBAL-batch measurement is
            # scaled to the per-MICROBATCH units of the profile graph.
            from ddlbench_tpu.profiler.profile import measure_input_ms

            # sequential streams (the native on-disk loader) need a
            # throwaway instance so the training stream stays unconsumed;
            # random-access sources (translation corpus) are probed directly
            if getattr(data, "stateful_stream", False):
                probe = _make_data(cfg)
                try:
                    global_ms = measure_input_ms(probe)
                finally:
                    probe.close()
            else:
                global_ms = measure_input_ms(data)
            mb_, _ = cfg.resolved_batches()
            input_ms = global_ms * mb_ / cfg.global_batch()
            print(f"auto-partition: measured input cost "
                  f"{global_ms:.2f} ms/global-batch "
                  f"({input_ms:.3f} ms/microbatch)", flush=True)
        strategy = make_strategy(cfg, input_time_ms=input_ms)
    if cfg.audit:
        _write_audit(cfg, strategy)
    logger = logger or MetricLogger(cfg.epochs, cfg.log_interval)

    # Step-level telemetry (ddlbench_tpu/telemetry/): a fresh bounded
    # tracer per run when --trace is set, exported (Perfetto-loadable) in
    # the finally so a run that dies mid-epoch still leaves its trace.
    # With tracing off the global tracer stays disabled and every span
    # site below is a no-op check.
    tracer, prev_tracer = None, None
    if cfg.trace:
        # fail fast on an unwritable path — the export happens at run END,
        # and discovering a bad --trace there would waste the whole run
        with open(cfg.trace, "a"):
            pass
        prev_tracer = get_tracer()
        tracer = set_tracer(Tracer(cfg.trace_capacity)).enable()

    # Failure detection (SURVEY.md §5.3): the watchdog is kicked at every
    # host sync point below; non-finite losses go through cfg.nan_policy.
    # Started only after warmup so the first deadline excludes XLA compile
    # (tens of seconds); with warmup_steps=0 the first step's compile counts.
    wd = HangWatchdog(cfg.hang_timeout_s) if cfg.hang_timeout_s else None
    xla_window = _XlaWindow(cfg)
    # Stability guard (ddlbench_tpu/guard/): the ONE policy surface for
    # every anomaly — on-device (finite, grad_norm) flags from the guarded
    # engines, non-finite losses at the legacy check sites, EWMA grad-norm
    # spikes — plus graceful preemption. With neither --anomaly-policy nor
    # --loss-scale set, the guard only mirrors the legacy nan_policy checks.
    guard = StabilityGuard(cfg)
    preempt = None
    if cfg.checkpoint_dir:
        # SIGTERM/SIGINT -> flag -> step-boundary checkpoint -> distinct
        # exit code. Only armed when there is somewhere to commit to.
        preempt = PreemptionHandler().install()
    # Deterministic fault injection (ddlbench_tpu/faults/): armed for the
    # run, disarmed in the finally. With cfg.inject empty this arms nothing
    # and every hook below is a single falsy check.
    faults.arm(cfg.inject)
    if any(s.kind == "grad-spike" for s in faults.armed_specs()):
        from ddlbench_tpu.guard.policy import GUARD_UNWIRED_STRATEGIES

        # grad-spike is consumed by the guard's device-metric window; with
        # the guard disarmed — or a strategy whose engine carries no guard
        # wiring and so emits no device metrics — the spec would silently
        # never fire. Surface it instead of breaking the deterministic-
        # firing contract quietly.
        if not guard.device_armed:
            print("WARNING: --inject grad-spike has no effect without "
                  "--anomaly-policy/--loss-scale (the guard's grad-norm "
                  "detector is what consumes it)", file=sys.stderr,
                  flush=True)
        elif cfg.strategy in GUARD_UNWIRED_STRATEGIES:
            print(f"WARNING: --inject grad-spike has no effect with "
                  f"-f {cfg.strategy} (its engine has no device-guard "
                  f"wiring, so no grad-norm stream feeds the detector)",
                  file=sys.stderr, flush=True)
    if preempt is None and \
            any(s.kind in ("preempt", "shrink", "grow")
                for s in faults.armed_specs()):
        # the graceful path needs somewhere to commit; without it the
        # injected SIGTERM is just an uncheckpointed death (rc -15) —
        # and for shrink/grow there is then no checkpoint to reshape from
        print("WARNING: --inject preempt/shrink/grow without "
              "--checkpoint-dir kills the run uncheckpointed (the graceful "
              "path needs a commit target)", file=sys.stderr, flush=True)
    try:
        while True:
            try:
                return _run_benchmark(cfg, strategy, data, logger,
                                      warmup_steps, wd, xla_window, guard,
                                      preempt)
            except GuardRewind as rw:
                # --anomaly-policy rewind: restore the last committed
                # checkpoint through the existing latest_valid resume path;
                # the (epoch, step)-addressed data stream fast-forwards
                # deterministically, so the replay is bitwise. The guard
                # bounds repeated rewinds for the same step by the budget.
                from ddlbench_tpu.train.checkpoint import latest_valid

                if latest_valid(cfg.checkpoint_dir) is None:
                    # no committed checkpoint yet: re-entering would fall
                    # through the empty-dir resume path and silently restart
                    # with FRESH params (not a rewind) while the logger keeps
                    # the abandoned attempt's records — escalate instead
                    raise TrainingFailure(
                        f"guard: rewind requested but no committed "
                        f"checkpoint exists in {cfg.checkpoint_dir} ({rw}); "
                        f"use --checkpoint-every-steps to bound the window "
                        f"before the first epoch-end commit") from rw
                print(f"guard: rewinding to the last valid checkpoint "
                      f"({rw})", flush=True)
                get_tracer().complete("guard_rewind",
                                      time.perf_counter_ns(),
                                      time.perf_counter_ns())
                guard.reset_window()  # drop the abandoned interval's flags
                cfg = cfg.replace(resume=True)
    finally:
        faults.disarm()
        if preempt is not None:
            preempt.uninstall()
        if wd:
            wd.stop()
        # an exception mid-window must still stop + flush the device
        # profile (and leave jax.profiler usable for the next run)
        xla_window.close()
        if tracer is not None:
            tracer.disable()
            set_tracer(prev_tracer)  # drop the ring; untraced runs follow
            try:
                # mesh shape + run identity: joins this trace to the
                # run's audit manifest (same fields in the ledger header)
                mesh = getattr(strategy, "mesh", None)
                n = export_chrome_trace(tracer, cfg.trace, extra_metadata={
                    "train": {"strategy": cfg.strategy, "arch": cfg.arch,
                              "num_devices": cfg.num_devices,
                              "mesh_shape": (dict(mesh.shape)
                                             if mesh is not None else None)}})
            except OSError as e:  # never mask the run's own exception
                print(f"telemetry: trace export to {cfg.trace} failed: {e}",
                      flush=True)
            else:
                print(f"telemetry: {n} trace events written to {cfg.trace}"
                      + (f" ({tracer.dropped_events} dropped: ring full)"
                         if tracer.dropped_events else ""), flush=True)


def _make_data(cfg: RunConfig):
    global_batch = cfg.global_batch()
    spec = cfg.dataset()
    if cfg.synthetic:
        return make_synthetic(
            spec, global_batch, seed=cfg.seed, steps_per_epoch=cfg.steps_per_epoch
        )
    if spec.kind == "seq2seq" and cfg.data_dir:
        # Real translation corpus (train.src/train.tgt parallel line files):
        # BPE-tokenized fixed-shape prefix-LM streams with padding-efficiency
        # accounting (data/translation.py).
        from ddlbench_tpu.data.translation import (
            TranslationData, find_parallel_corpus)

        if find_parallel_corpus(cfg.data_dir, "train"):
            data = TranslationData(cfg.data_dir, spec, global_batch,
                                   seed=cfg.seed,
                                   steps_per_epoch=cfg.steps_per_epoch)
            rep = data.bucketing_report()
            print(
                f"translation data: vocab {data.tokenizer.vocab_size}, "
                f"padding efficiency {rep['fixed_efficiency']:.3f} fixed vs "
                f"{rep['bucketed_efficiency']:.3f} bucketed "
                f"({rep['num_compiles_bucketed']} bucket compiles)",
                flush=True,
            )
            return data
    if spec.kind == "tokens" and cfg.data_dir:
        # Real text corpus (train.txt): BPE-tokenized document-packed causal
        # LM windows (data/textcorpus.py) — the raw-bytes placeholder below
        # stays synthetic-only.
        from ddlbench_tpu.data.textcorpus import (
            TextCorpusData, find_text_corpus)

        if find_text_corpus(cfg.data_dir, "train"):
            data = TextCorpusData(cfg.data_dir, spec, global_batch,
                                  seed=cfg.seed,
                                  steps_per_epoch=cfg.steps_per_epoch)
            print(
                f"text corpus: {data.num_tokens} tokens, vocab "
                f"{data.tokenizer.vocab_size}, "
                f"{data.steps_per_epoch()} steps/epoch", flush=True)
            return data
    from ddlbench_tpu.data.ondisk import OnDiskData

    train_count = (cfg.steps_per_epoch or 0) * global_batch or None
    test_count = max(global_batch, (train_count or 0) // 5) if train_count else None
    return OnDiskData(
        cfg.data_dir or "./data", spec, global_batch, seed=cfg.seed,
        train_count=train_count, test_count=test_count,
        augment=cfg.augment, prefetch_depth=cfg.prefetch_depth,
    )


def _run_benchmark(cfg: RunConfig, strategy, data, logger: MetricLogger,
                   warmup_steps: int, wd: Optional[HangWatchdog],
                   xla_window: Optional[_XlaWindow] = None,
                   guard: Optional[StabilityGuard] = None,
                   preempt: Optional[PreemptionHandler] = None
                   ) -> Dict[str, Any]:

    guard = guard or StabilityGuard(cfg)
    mb, chunks = cfg.resolved_batches()
    global_batch = cfg.global_batch()

    def _scaled_lr(lr_world: int):
        lr = cfg.resolved_lr()
        # The gradual warmup ramps away exactly the world-scaling factor
        # (imagenet_horovod.py:258-275), so it only does something where
        # that scaling is applied — warmup_world stays 1 elsewhere and
        # gradual_warmup_lr is then the identity.
        w = 1
        if (cfg.strategy == "dp" and cfg.scale_lr_by_world
                and cfg.resolved_optimizer() == "sgd"):
            # Horovod parity: lr scaled by world size (mnist_horovod.py:226)
            # and by the accumulation count (lr * batches_per_allreduce *
            # hvd.size(), imagenet_horovod.py:131). SGD only — linear
            # scaling is the SGD heuristic; the reference never scales its
            # Adam (translation) lr by replica count. ``lr_world`` is
            # normally the mesh world, but an ELASTIC resume pins it to
            # the LAUNCH world recorded in the checkpoint — shrinking a
            # fleet must never silently change the learning rate.
            lr = lr * lr_world * cfg.grad_accum_steps
            w = lr_world
        return lr, w

    lr_world = getattr(strategy, "world_size", cfg.num_devices)
    base_lr, warmup_world = _scaled_lr(lr_world)

    # Step-latency accounting (telemetry/stats.py): every loop iteration's
    # wall time is recorded (two monotonic clock reads — stays on even with
    # tracing off) and aggregated to p50/p95/p99/max per epoch for the
    # epoch lines / JSONL / summary. The tracer is only consulted through
    # its `enabled` flag on the hot path.
    stats = StepLatencyStats()
    tracer = get_tracer()

    # Warmup: trigger compilation outside the timed region (first XLA compile is
    # tens of seconds; the reference's closest analog is cudnn.benchmark=True,
    # imagenet_pytorch.py:58-66). Runs on a throwaway state so the measured run
    # starts from pristine params/momentum/BN stats. The wall time is kept
    # as the run's explicit warmup/compile accounting — never mixed into
    # the step-latency distribution.
    if warmup_steps > 0:
        t_warm = time.perf_counter_ns()
        ts_warm = strategy.init(jax.random.key(cfg.seed))
        batch = strategy.shard_batch(*data.batch(epoch=0, step=0))
        for _ in range(warmup_steps):
            ts_warm, m = strategy.train_step(ts_warm, *batch,
                                             jnp.float32(base_lr))
        float(m["loss"])  # device transfer = real sync (axon block_until_ready is lazy)
        if wd:
            # also compile eval_step now, so the watchdog deadline (armed
            # below) never spans a first-eval XLA compile
            float(strategy.eval_step(ts_warm, *batch)["loss"])
        del ts_warm
        t_warm_end = time.perf_counter_ns()
        stats.set_warmup((t_warm_end - t_warm) / 1e9)
        tracer.complete("warmup_compile", t_warm, t_warm_end)

    ts = strategy.init(jax.random.key(cfg.seed))

    # Comm-volume accounting (RuntimeStats parity, SURVEY.md §5.5).
    try:
        from ddlbench_tpu.train.comm_stats import comm_stats

        cs = comm_stats(strategy)
        parts = [f"boundaries {cs['boundary_bytes'] / 1e6:.2f} MB",
                 f"allreduce {cs['allreduce_bytes'] / 1e6:.2f} MB"]
        if cs.get("reduce_scatter_bytes") or cs.get("all_gather_bytes"):
            # explicit sharded weight update: the allreduce decomposes
            parts.append(f"reduce-scatter "
                         f"{cs['reduce_scatter_bytes'] / 1e6:.2f} MB")
            parts.append(f"all-gather {cs['all_gather_bytes'] / 1e6:.2f} MB")
        print(f"comm volume/step: {cs['total_bytes'] / 1e6:.2f} MB "
              f"({', '.join(parts)})", flush=True)
    except Exception:
        pass

    # Asynchronous input pipeline (data/prefetch.py): batch production AND
    # shard_batch/device_put run a bounded prefetch_depth ahead of the
    # consuming loop on a producer thread, so step N's H2D transfer overlaps
    # step N-1's compute. depth 0 (--no-prefetch) is the synchronous
    # fallback through the same interface; both paths feed the loop the same
    # (epoch, step)-addressed batches, so losses are bitwise identical.
    prefetch = Prefetcher(data, strategy.shard_batch,
                          depth=cfg.prefetch_depth, watchdog=wd)

    # Retention pin: the path of the checkpoint the run would currently
    # rewind/resume to — gc never drops it (train/checkpoint.py), so a
    # newer post-commit-corrupted checkpoint cannot crowd the only known-
    # restorable state out of a tight --keep-checkpoints window. Updated to
    # every newly committed checkpoint (which then IS the rewind target).
    ckpt_pin: Optional[str] = None
    start_epoch, resume_step, global_step = 1, 0, 0
    if cfg.checkpoint_dir and cfg.resume:
        from ddlbench_tpu.train import reshard
        from ddlbench_tpu.train.checkpoint import (latest_valid,
                                                   load_logical,
                                                   restore_info)

        info = latest_valid(cfg.checkpoint_dir)
        if wd:
            # on a rewind re-entry the watchdog thread is already running;
            # the restore below gets a full deadline
            wd.kick()
        if info is None:
            # A restarted-from-scratch supervisor loop (tools/chaosbench.py)
            # passes --resume unconditionally; an empty/missing checkpoint
            # dir must start fresh, not crash.
            print(f"resume: no valid checkpoint under {cfg.checkpoint_dir}; "
                  f"starting fresh", flush=True)
        else:
            # Topology check BEFORE touching orbax: a world-shape mismatch
            # either routes through the reshard pass (--elastic-resume) or
            # raises the named CheckpointShapeError instead of dying on a
            # cryptic orbax shape assert (train/reshard.py).
            saved_logical = load_logical(info.path)
            cur_logical = reshard.logical_meta(strategy, cfg, ts, lr_world)
            decision = reshard.compare(saved_logical, cur_logical,
                                       cfg.elastic_resume)
            with tracer.span("checkpoint_restore",
                             reshard=decision == "reshard"):
                if decision == "reshard":
                    print(f"elastic resume: resharding checkpoint from "
                          f"world {saved_logical['world']} to "
                          f"{cur_logical['world']} "
                          f"(buckets {saved_logical.get('buckets')} -> "
                          f"{cur_logical.get('buckets')})", flush=True)
                    ts = reshard.elastic_restore(info, ts, saved_logical,
                                                 strategy, cfg)
                else:
                    ts = restore_info(info, ts)
            if saved_logical is not None:
                if saved_logical.get("global_batch") != cfg.global_batch():
                    print(f"resume: WARNING checkpoint was written at "
                          f"global batch {saved_logical.get('global_batch')}"
                          f", run uses {cfg.global_batch()} — the "
                          f"(epoch, step)-addressed data streams will not "
                          f"match the original trajectory", flush=True)
                saved_lr_world = saved_logical.get("lr_world")
                if saved_lr_world and saved_lr_world != lr_world:
                    # pin the lr world-scaling to the LAUNCH world: the
                    # run's hyperparameters were fixed at launch, and a
                    # reshaped fleet must replay the same schedule
                    lr_world = saved_lr_world
                    base_lr, warmup_world = _scaled_lr(lr_world)
                    print(f"elastic resume: lr world-scaling pinned to the "
                          f"launch world ({lr_world})", flush=True)
                if saved_logical.get("elastic_slices") != \
                        cfg.elastic_slices:
                    print(f"resume: WARNING checkpoint recorded "
                          f"--elastic-slices "
                          f"{saved_logical.get('elastic_slices')}, run "
                          f"uses {cfg.elastic_slices} — reduction orders "
                          f"differ, the trajectory will not be bitwise",
                          flush=True)
            ckpt_pin = info.path
            meta = info.meta
            if meta.get("seed") is not None and meta["seed"] != cfg.seed:
                print(f"resume: WARNING checkpoint was written with seed "
                      f"{meta['seed']}, run uses seed {cfg.seed} — the "
                      f"(epoch, step)-addressed data/RNG streams will not "
                      f"match the original trajectory", flush=True)
            if meta.get("logger"):
                logger.load_state_dict(meta["logger"])
            steps_ = data.steps_per_epoch(train=True)
            if info.mid_epoch:
                # step-granular checkpoint: resume INSIDE the epoch at the
                # next step — the data iterator position IS the step index
                # (every source is (epoch, step)-addressed) and per-step
                # RNG streams are pure (seed, epoch, step) fold-ins, so the
                # replayed trajectory is bitwise
                start_epoch, resume_step = info.epoch, info.step + 1
                if resume_step >= steps_:  # epoch actually completed
                    start_epoch, resume_step = info.epoch + 1, 0
                print(f"resumed from {cfg.checkpoint_dir} epoch "
                      f"{info.epoch} step {info.step} (mid-epoch)",
                      flush=True)
            else:
                start_epoch = info.epoch + 1
                print(f"resumed from {cfg.checkpoint_dir} epoch "
                      f"{info.epoch}", flush=True)
            global_step = (meta.get("global_step")
                           if meta.get("global_step") is not None
                           else (start_epoch - 1) * steps_ + resume_step)
            if not info.mid_epoch:
                # post-resume validation BEFORE training continues
                # (reference semantics: main_with_runtime.py:374-376 re-runs
                # validate() right after restoring) — confirms the restored
                # state is the one that was saved, not merely loadable.
                # Mid-epoch resumes skip it: the epoch is not finished, and
                # its epoch-end validation will run at the normal point.
                ev = evaluate(cfg, strategy, ts, data, info.epoch, wd,
                              prefetcher=prefetch, guard=guard)
                logger.valid_epoch(info.epoch, ev["loss"], ev["accuracy"],
                                   top5=ev.get("top5"))

    # Topology-portable metadata written beside every commit from here on:
    # the recorded shape is what lets the NEXT resume detect a world-size
    # mismatch and reshard instead of crashing (train/reshard.py).
    ckpt_logical = None
    if cfg.checkpoint_dir:
        from ddlbench_tpu.train import reshard as _reshard

        ckpt_logical = _reshard.logical_meta(strategy, cfg, ts, lr_world)

    # Activation/gradient deep-dive logging (torchlogger analog, §5.5).
    # Works on the flat per-layer param structure; pipeline strategies pack
    # params per stage, so those log from the model definition is not wired —
    # documented in profiler/actlog.py.
    actlog = None
    if cfg.activation_log_dir:
        from ddlbench_tpu.profiler.actlog import ActivationLogger

        # Structure check once here: the logger needs the flat per-layer param
        # list; pipeline strategies pack params per stage (ts structure is
        # fixed by strategy.init, so this cannot change mid-run).
        model = getattr(strategy, "model", None)
        params = getattr(ts, "params", None)
        if (model is not None and isinstance(params, list)
                and len(params) == len(model.layers)):
            actlog = ActivationLogger(
                cfg.activation_log_dir, model, jnp.dtype(cfg.compute_dtype),
                cfg.activation_log_freq, cfg.activation_log_steps,
                moe_aux_weight=cfg.moe_aux_weight,
                label_smoothing=cfg.resolved_label_smoothing(),
            )
        else:
            print("activation logging unsupported for this strategy "
                  "(packed or absent per-layer params); skipped", flush=True)

    if wd:
        wd.kick()
        wd.start()

    # Host/device trace alignment: when a jax.profiler capture is on (whole
    # run via cli.py's --trace-dir, or the [A, B) window below), every step
    # dispatch is wrapped in a StepTraceAnnotation carrying the global step
    # number, so device timelines line up with the host spans' step args.
    annotate_steps = cfg.trace_dir is not None
    if xla_window is None:
        xla_window = _XlaWindow(cfg)

    summary_acc = (logger.valid_history[-1]["accuracy"]
                   if logger.valid_history else 0.0)
    for epoch in range(start_epoch, cfg.epochs + 1):
        # mid-epoch resume: only the first epoch starts at an interior step
        ep_start = resume_step if epoch == start_epoch else 0
        lr = step_decay_lr(base_lr, epoch - 1, cfg.lr_step_epochs, cfg.lr_step_gamma)
        steps = data.steps_per_epoch(train=True)
        tick = time.perf_counter()
        interval_tick, interval_samples = tick, 0
        # On-device metric accumulation: step losses are summed as lazy
        # jax.Arrays and transferred ONCE per log interval (the logged loss
        # is the interval mean), so the host never blocks the dispatch queue
        # between intervals. The watchdog path below keeps its opt-in
        # per-step sync — and since every loss already lands on the host
        # there, it accumulates the plain floats instead of paying a
        # second device-side sum and interval transfer.
        loss_sum, host_loss_sum, interval_steps = None, 0.0, 0
        metrics = None
        stream = prefetch.stream(epoch, train=True,
                                 keep_raw=actlog is not None,
                                 start_step=ep_start)
        try:
            for step, fetched in enumerate(stream, start=ep_start):
                if actlog is not None and actlog.should_log(epoch, step):
                    bx, by = fetched.raw
                    try:
                        # overlapped dp keeps params as a flat sharded
                        # vector between steps; ask the strategy for the
                        # per-layer pytree instead of touching ts.params
                        p_log = (strategy.materialize_params(ts)
                                 if hasattr(strategy, "materialize_params")
                                 else ts.params)
                        path = actlog.log(epoch, step, p_log,
                                          ts.model_state, bx, by)
                    except RuntimeError as e:  # e.g. non-addressable sharded params
                        print(f"activation logging failed ({e}); disabled",
                              flush=True)
                        actlog, path = None, None
                    if path:
                        print(f"activations logged: {path}", flush=True)
                step_lr = lr
                if cfg.warmup_epochs and epoch - 1 < cfg.warmup_epochs:
                    from ddlbench_tpu.parallel.common import gradual_warmup_lr

                    step_lr = gradual_warmup_lr(
                        lr, warmup_world, epoch - 1, step, steps,
                        cfg.warmup_epochs)
                # Step wall time = this loop body (dispatch + any sync the
                # body performs); the ring wait on input is accounted
                # separately as stall (data/prefetch.py), so the two
                # decompose the epoch instead of double-counting it.
                t_step = time.perf_counter_ns()
                # fault hook: `kill` SIGKILLs / `preempt` SIGTERMs at this
                # step boundary — before the dispatch, so the last committed
                # checkpoint is what a resume must recover from
                faults.step_boundary(epoch, step)
                if preempt is not None and preempt.requested:
                    # graceful preemption: commit the state as of the LAST
                    # COMPLETED step through the atomic protocol, then exit
                    # with the distinct code (cli.py). The guard flushes
                    # first so an anomalous pending step cannot be the
                    # state that gets committed.
                    guard.flush(epoch, step)
                    _commit_preemption(cfg, ts, epoch, step, global_step,
                                       logger, tracer, wd, ckpt_pin,
                                       ckpt_logical)
                if faults.poison_grad(epoch, step):
                    # `nan-grad`: a NaN lr rides into the backward through
                    # the guard-armed engines' objective multiplier
                    # (lr*0+1), poisoning the device-side gradients — the
                    # on-device detection/skip path is what gets exercised.
                    # Disarmed engines have no multiplier: the NaN scales
                    # the update directly and params stay NaN, which is
                    # exactly what a real NaN gradient does without a guard
                    # (nan_policy then sees it at the next loss sync)
                    step_lr = float("nan")
                xla_window.step(global_step, lambda: (
                    float(metrics["loss"]) if metrics is not None else None))
                ann = (jax.profiler.StepTraceAnnotation(
                    "train", step_num=global_step)
                    if annotate_steps else _NULL_CTX)
                with ann:
                    ts, metrics = strategy.train_step(ts, *fetched.batch,
                                                      jnp.float32(step_lr))
                if faults.poison_loss(epoch, step):
                    # `nan-loss`: poison this step's HOST-side loss (device
                    # state untouched) — drives the --nan-policy path
                    metrics = dict(metrics)
                    metrics["loss"] = jnp.float32(float("nan"))
                global_step += 1
                interval_samples += global_batch
                interval_steps += 1
                # With the watchdog armed, sync every step so the deadline
                # really is per-step (a small pipelining cost, only when
                # opted in); otherwise the loop transfers one accumulated
                # scalar per log interval.
                log_step = (step + 1) % cfg.log_interval == 0 or step == steps - 1
                if wd:
                    with tracer.span("step_sync"):
                        step_loss = float(metrics["loss"])  # transfer = sync
                    # per-step health first: a dropped/rewound update is the
                    # step's primary event, the loss value its symptom
                    guard.step_health(epoch, step + 1, metrics)
                    guard.check_loss(step_loss, epoch, step + 1)
                    wd.kick()
                    host_loss_sum += step_loss
                else:
                    loss_sum = (metrics["loss"] if loss_sum is None
                                else loss_sum + metrics["loss"])
                    # guard: chain (finite, grad_norm) lazily on device —
                    # synced with the same interval transfer below
                    guard.accumulate(metrics)
                if log_step:
                    if wd:
                        # per-step syncs already landed (and checked) every
                        # loss; the interval mean is free host math
                        loss = host_loss_sum / interval_steps
                    else:
                        # one transfer = sync; the sum chains every step in
                        # the interval, so non-finite losses propagate into
                        # it (the interval mean cannot pin the offending
                        # step — only the watchdog's per-step sync can)
                        with tracer.span("interval_sync"):
                            loss = float(loss_sum) / interval_steps
                        guard.check_loss(loss, epoch, step + 1,
                                         where=f"in epoch {epoch} interval "
                                               f"ending step {step + 1}")
                        guard.flush(epoch, step + 1)
                    loss_sum, host_loss_sum, interval_steps = None, 0.0, 0
                    now = time.perf_counter()
                    logger.train_interval(
                        epoch,
                        100.0 * (step + 1) / steps,
                        interval_samples / max(1e-9, now - interval_tick),
                        loss,
                    )
                    interval_tick, interval_samples = now, 0
                t_step_end = time.perf_counter_ns()
                stats.record_step(epoch, (t_step_end - t_step) / 1e9)
                if tracer.enabled:
                    tracer.complete("train_step", t_step, t_step_end,
                                    {"epoch": epoch, "step": step,
                                     "global_step": global_step - 1})
                    if step == ep_start and \
                            getattr(strategy, "timetable", None) is not None:
                        # pipeline runtimes: project the schedule timetable
                        # onto this step's window as per-stage pipe_tick
                        # marker spans — telemetry/bubble.py's food. Once
                        # per epoch: the projection is identical every step
                        # (the schedule is static), so more would only fill
                        # the ring.
                        from ddlbench_tpu.telemetry.bubble import (
                            emit_tick_spans)

                        emit_tick_spans(tracer, strategy.timetable, t_step,
                                        t_step_end, step=global_step - 1)
                if (cfg.checkpoint_every_steps
                        and (step + 1) % cfg.checkpoint_every_steps == 0
                        and step != steps - 1):  # epoch-end save covers last
                    from ddlbench_tpu.train.checkpoint import save_checkpoint

                    # a pending anomaly must apply its policy BEFORE the
                    # commit — under rewind/abort the live state may be
                    # poisoned, and a poisoned commit would become the
                    # rewind target itself
                    guard.flush(epoch, step + 1)
                    if wd:
                        wd.kick()  # the save gets a full deadline
                    with tracer.span("checkpoint_save", epoch=epoch,
                                     step=step):
                        ckpt_pin = save_checkpoint(
                            cfg.checkpoint_dir, epoch, ts, step=step,
                            global_step=global_step,
                            logger_state=logger.state_dict(), seed=cfg.seed,
                            keep=cfg.keep_checkpoints, pin=ckpt_pin,
                            logical=ckpt_logical)
                    if wd:
                        wd.kick()
        finally:
            stream.close()
        # the final step is always a log_step, so the loop already synced on
        # the full ts chain before the clock stops here
        epoch_time = time.perf_counter() - tick
        logger.epoch_done(epoch,
                          (steps - ep_start) * global_batch / epoch_time,
                          epoch_time,
                          input_stall_ms=stream.stall_ms,
                          step_ms=stats.epoch_summary(epoch))

        # Validation epoch (test_epoch parity, mnist_pytorch.py:102-133).
        with tracer.span("eval_epoch", epoch=epoch):
            val = evaluate(cfg, strategy, ts, data, epoch, wd,
                           prefetcher=prefetch, guard=guard)
        logger.valid_epoch(epoch, val["loss"], val["accuracy"],
                           top5=val.get("top5"))
        summary_acc = val["accuracy"]

        if cfg.checkpoint_dir:
            from ddlbench_tpu.train.checkpoint import save_checkpoint

            if wd:
                wd.kick()  # the save itself gets a full deadline
            with tracer.span("checkpoint_save", epoch=epoch):
                ckpt_pin = save_checkpoint(
                    cfg.checkpoint_dir, epoch, ts,
                    global_step=global_step,
                    logger_state=logger.state_dict(),
                    seed=cfg.seed, keep=cfg.keep_checkpoints, pin=ckpt_pin,
                    logical=ckpt_logical)
            if wd:
                wd.kick()

    xla_window.close()  # a window that outlived the run still gets flushed
    result = logger.summary(summary_acc, step_time=stats.run_summary())
    if guard.active:
        # anomalies absorbed / skipped / rewound / backed off — the
        # robustness half of the benchmark result (chaosbench aggregates
        # the per-event "guard:" lines across attempts too)
        result["guard"] = guard.summary()
    result["train_state"] = ts
    return result


def _commit_preemption(cfg: RunConfig, ts, epoch: int, step: int,
                       global_step: int, logger: MetricLogger, tracer, wd,
                       pin: Optional[str],
                       logical: Optional[Dict[str, Any]] = None) -> None:
    """Graceful preemption at the (epoch, step) boundary: commit the state
    as of the last COMPLETED step through the atomic protocol, then raise
    :class:`GracefulPreemption` (cli.py maps it to PREEMPT_EXIT_CODE)."""
    from ddlbench_tpu.train.checkpoint import checkpoint_name, save_checkpoint

    # state at this boundary = end of step-1 (or the previous epoch's end
    # when preempted before the epoch's first dispatch)
    ck_epoch, ck_step = (epoch, step - 1) if step > 0 else (epoch - 1, None)
    if pin and os.path.basename(pin) == checkpoint_name(ck_epoch, ck_step) \
            and os.path.isdir(pin):
        # zero steps completed since the pinned commit (preempted right
        # after a periodic save, or at the first boundary after a resume):
        # re-saving would rmtree-and-rewrite the only restorable state —
        # a second signal mid-save would destroy it for nothing
        where = (f"epoch {ck_epoch} step {ck_step}" if ck_step is not None
                 else f"epoch {ck_epoch}")
        # prefix must stay "preempt: checkpoint committed" — the chaosbench
        # supervisor matches it to classify the exit as graceful
        print(f"preempt: checkpoint committed at {where} (reusing the "
              f"existing commit)", flush=True)
        raise GracefulPreemption(
            f"preemption checkpoint committed at {where}",
            checkpoint_path=pin)
    if wd:
        wd.kick()  # the save gets a full deadline
    span_args = {"epoch": ck_epoch}
    if ck_step is not None:
        span_args["step"] = ck_step
    with tracer.span("checkpoint_save", **span_args):
        path = save_checkpoint(
            cfg.checkpoint_dir, ck_epoch, ts, step=ck_step,
            global_step=global_step, logger_state=logger.state_dict(),
            seed=cfg.seed, keep=cfg.keep_checkpoints, pin=pin,
            logical=logical)
    where = (f"epoch {ck_epoch} step {ck_step}" if ck_step is not None
             else f"epoch {ck_epoch}")
    print(f"preempt: checkpoint committed at {where}", flush=True)
    raise GracefulPreemption(
        f"preemption checkpoint committed at {where}", checkpoint_path=path)


def evaluate(cfg: RunConfig, strategy, ts, data, epoch: int,
             wd: Optional[HangWatchdog] = None,
             prefetcher: Optional[Prefetcher] = None,
             guard: Optional[StabilityGuard] = None) -> Dict[str, float]:
    """One validation epoch with on-device metric accumulation.

    loss*count / correct / correct5 / count are summed as lazy jax.Arrays —
    ONE device->host transfer per epoch instead of a blocking ``float()``
    per step, so eval steps pipeline like train steps. With a watchdog
    ARMED, eval keeps the per-step sync (train-path parity,
    train/watchdog.py semantics: the deadline must bound DEVICE progress,
    which the prefetcher heartbeat — host input progress — cannot prove);
    the heartbeat additionally covers gaps where slow input production is
    the bottleneck."""
    pf = prefetcher or Prefetcher(data, strategy.shard_batch,
                                  depth=cfg.prefetch_depth, watchdog=wd)
    loss_sum = correct_sum = correct5_sum = count_sum = None
    saw_correct5 = True
    steps = 0

    def acc(total, v):
        return v if total is None else total + v

    tracer = get_tracer()
    stream = pf.stream(epoch, train=False)
    try:
        for fetched in stream:
            with tracer.span("eval_step"):
                m = strategy.eval_step(ts, *fetched.batch)
            steps += 1
            if wd is not None:
                # armed watchdog: per-step transfer = sync, so a device hang
                # mid-eval dies within one deadline (and a non-finite eval
                # loss is attributed to its actual step)
                step_loss = float(m["loss"])
                if guard is not None:  # unified policy surface
                    guard.check_loss(step_loss, epoch, steps, train=False)
                else:
                    check_finite(step_loss, epoch, steps, cfg.nan_policy)
                wd.kick()
            loss_sum = acc(loss_sum, m["loss"] * m["count"])
            correct_sum = acc(correct_sum, m["correct"])
            count_sum = acc(count_sum, m["count"])
            if "correct5" in m:
                correct5_sum = acc(correct5_sum, m["correct5"])
            else:  # strategy without prec@5 support: report None, never 0.0
                saw_correct5 = False
    finally:
        stream.close()
    if steps:  # ONE device->host transfer for all accumulators = epoch sync
        with tracer.span("eval_epoch_sync"):
            loss_sum, correct_sum, correct5_sum, count_sum = jax.device_get(
                (loss_sum, correct_sum,
                 correct5_sum if saw_correct5 else 0, count_sum))
    total_count = int(count_sum) if steps else 0
    loss = float(loss_sum) / max(1, total_count) if steps else 0.0
    # detection happens at the one epoch-end transfer, so no specific step
    # can honestly be blamed. The guard is the one policy surface for this
    # site too (skip/rewind degrade to warn: eval has no update to drop).
    if guard is not None:
        guard.check_loss(loss, epoch, steps, train=False,
                         where=f"in validation epoch {epoch} "
                               f"(epoch-end check)")
    else:
        check_finite(loss, epoch, steps, cfg.nan_policy,
                     where=f"in validation epoch {epoch} (epoch-end check)")
    if wd:
        wd.kick()  # the epoch-end transfer above proved device progress
    return {
        "loss": loss,
        "accuracy": int(correct_sum) / max(1, total_count) if steps else 0.0,
        # prec@5 (PipeDream eval parity, main_with_runtime.py:639-653);
        # None when unsupported by the strategy or when no eval step ran
        "top5": (int(correct5_sum) / total_count
                 if saw_correct5 and steps and total_count else None),
    }
