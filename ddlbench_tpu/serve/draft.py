"""Self-drafting n-gram proposer for speculative decoding.

Speculative decoding (Leviathan et al. 2022, "Fast Inference from
Transformers via Speculative Decoding" — cited directly, like the Kwon et
al. PagedAttention lineage of PR 10; not among the training papers in
PAPERS.md) splits token generation into a cheap DRAFT and an exact
VERIFY: a proposer guesses the next K tokens, the target model scores all
K+1 positions in ONE pass, and the longest prefix of drafts matching the
model's own (greedy) choices is accepted — every accepted draft turns a
would-be decode pass into a free token, and a rejected draft costs
nothing the plain pass would not have spent (the verify pass still emits
its one guaranteed token).

This drafter is the SELF-drafting variant (prompt-lookup style): instead
of a second model it proposes from the request's OWN token stream — find
the most recent earlier occurrence of the last N emitted/prompt tokens
and propose the continuation that followed it. Natural-language and code
traffic repeat themselves (boilerplate, copied spans, templated phrasing);
an N-gram that recurred once tends to continue the same way. The proposer
is pure host arithmetic over the tokens the engine already holds:

* deterministic — same context, same proposal (no RNG at all), which is
  what lets eviction/recompute replay identical speculative schedules;
* bounded — it reads ONLY the request's prompt + emitted tokens (the
  engine drafts only for rows whose prefill is complete, so the context
  never reaches past ``prefill_done``), and proposes at most ``k`` tokens;
* cheap — one backwards scan per decode row per step, O(len(context) * n)
  worst case on token counts that are at most ``max_len``.

The engine (serve/engine.py) owns acceptance: drafts are scored by the
K+1-wide verify program and accepted while they match greedy argmax, so
the emitted stream is BITWISE the non-speculative stream regardless of
what this module proposes — a bad proposal costs acceptance rate, never
correctness (pinned, tests/test_serve_spec.py).
"""

from __future__ import annotations

from typing import List, Sequence


class NgramDrafter:
    """Propose up to ``k`` continuation tokens by matching the context's
    trailing ``n``-gram against its own history."""

    def __init__(self, n: int, k: int):
        if n < 1 or k < 1:
            raise ValueError(f"ngram drafter needs n >= 1 and k >= 1, "
                             f"got n={n} k={k}")
        self.n = int(n)
        self.k = int(k)

    def propose(self, context: Sequence[int], k_max: int | None = None
                ) -> List[int]:
        """Drafts for the token stream ``context`` (prompt + emitted
        tokens, most recent last): the continuation that followed the most
        recent PRIOR occurrence of the trailing n-gram, truncated to
        ``min(k, k_max)`` tokens and to what the history actually
        contains. Empty when the n-gram never recurred or the context is
        shorter than n + 1."""
        k = self.k if k_max is None else min(self.k, int(k_max))
        n = self.n
        L = len(context)
        if k < 1 or L < n + 1:
            return []
        tail = list(context[L - n:])
        # j is the index AFTER a match (the first proposed token), scanned
        # right-to-left: the most recent occurrence that can supply all k
        # tokens wins (freshest full-width continuation); when every match
        # sits too close to the end — the periodic-stream case, where
        # matches overlap the tail itself — fall back to the earliest
        # match, whose continuation is the longest available
        fallback = None
        for j in range(L - 1, n - 1, -1):
            if list(context[j - n:j]) == tail:
                if L - j >= k:
                    return [int(t) for t in context[j:j + k]]
                fallback = j
        if fallback is not None:
            return [int(t) for t in context[fallback:fallback + k]]
        return []
