"""Silent-data-corruption defense for the serving data plane: a host-side
per-page checksum ledger over the shared KV pool.

A flipped bit in a pool page, scale sidecar, or in-flight handoff payload
is invisible to every existing guard — the device happily attends over the
poisoned bytes and the stream diverges silently, including prefix-cache
full-hits that serve the corrupted context to *future* sessions. This
module is the serving analog of the train loop's guard/ + chaosbench
treatment: detect at trust boundaries, quarantine the bad page, and
recover through the machinery that already exists.

The ledger
----------
One crc32 word per (layer, slot), chained over the slot's rows of every
per-slot pool array in sorted key order (``pool_checksum_keys`` in
ops/paged_decode.py: payload ``pool_k``/``pool_v`` plus the int8
``scale_k``/``scale_v`` sidecars — the exact domain the three table-write
primitives scatter). Entries carry a WRITE GENERATION so a re-stamp after
a legitimate overwrite (decode filling a page, COW, rollback re-derive)
is distinguishable from a stale expectation; ``verify`` only ever
compares against the latest generation.

The checksum is crc32c when the hardware-accelerated wheel is importable
and stdlib ``zlib.crc32`` otherwise — both are 4-byte words with the same
error-detection class, and the choice never leaks into pinned artifacts
(checksums are host-side state, not part of any row schema or stream).

Trust boundaries (serve/engine.py + serve/handoff.py wire the calls):

* pool writes (decode/prefill-chunk/COW) STAMP the written slots;
* handoff ``export_request`` verifies fetched bytes against the ledger
  and attaches per-(layer, page) checksums to the ship;
  ``import_request`` verifies the ship before any pool write and stamps
  the destination slots from the ship's checksums (all-or-nothing: a
  corrupt ship writes nothing and rides the parked-ship retry);
* prefix-hit binds (full and partial) verify the hit slots before a
  request attaches to them;
* COW verifies the SOURCE page before copying (a corrupted shared page
  must not propagate through the copy);
* a budgeted background scrubber (cfg.scrub pages/step) walks stamped
  slots round-robin, catching latent corruption on cold pages before a
  full-hit serves them.

Detection -> quarantine -> recovery: the allocator marks the slot
quarantined (never handed out again), the prefix index drops its entry,
and every request referencing the slot takes the existing
eviction-recompute path — re-prefill regenerates int8 pages
byte-identically (counter-based rounding seeds), and a recovered
request's FULL stream is regenerated from scratch, so any detection
before completion yields bitwise-identical final streams vs an unfaulted
control. That is the headline gate tools/servechaos.py pins.
"""

from __future__ import annotations

import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

try:  # hardware crc32c when the wheel is present; stdlib crc32 otherwise
    from crc32c import crc32c as _crc32c  # type: ignore
except ImportError:  # pragma: no cover - container ships without crc32c
    _crc32c = None

# one checksum word per (layer, page) on the handoff wire — the constant
# the ship_checksum_bytes accounting and the serve_pool_audit tie share
CHECKSUM_BYTES = 4


def checksum(data: bytes, crc: int = 0) -> int:
    """4-byte checksum of ``data`` chained onto ``crc`` (crc32c if
    available, zlib.crc32 otherwise), masked to an unsigned word."""
    if _crc32c is not None:
        return _crc32c(data, crc) & 0xFFFFFFFF
    return zlib.crc32(data, crc) & 0xFFFFFFFF


def page_checksum(rows: Dict[str, np.ndarray]) -> int:
    """CRC of one pool slot's fetched rows, chained over sorted key order
    so payload and sidecar corruption are both visible in the one word."""
    crc = 0
    for key in sorted(rows):
        crc = checksum(np.ascontiguousarray(rows[key]).tobytes(), crc)
    return crc


def ship_checksums(pages: List[Optional[Dict[str, np.ndarray]]],
                   page_axis: int = 0) -> List[Optional[List[int]]]:
    """Per-(layer, page) checksums of a handoff ship's fetched rows —
    exactly the values a local per-slot fetch would ledger, so import can
    stamp destination slots straight from the ship."""
    out: List[Optional[List[int]]] = []
    for per_layer in pages:
        if per_layer is None:  # layers with no pool ship nothing
            out.append(None)
            continue
        keys = sorted(per_layer)
        n = per_layer[keys[0]].shape[page_axis]
        out.append([
            page_checksum({k: (per_layer[k][p] if page_axis == 0
                               else per_layer[k][:, p]) for k in keys})
            for p in range(n)])
    return out


class PageLedger:
    """Host-side (layer, slot) -> (write-generation, crc) ledger."""

    def __init__(self) -> None:
        self._crc: Dict[Tuple[int, int], Tuple[int, int]] = {}
        self.stamps = 0
        self.verifies = 0
        self.mismatches = 0

    def __len__(self) -> int:
        return len(self._crc)

    def stamp(self, layer: int, slot: int, crc: int) -> int:
        """Record ``crc`` as the latest contents of (layer, slot); bumps
        the write generation. Returns the new generation."""
        gen = self._crc.get((layer, slot), (0, 0))[0] + 1
        self._crc[(layer, slot)] = (gen, crc)
        self.stamps += 1
        return gen

    def expected(self, layer: int, slot: int) -> Optional[int]:
        ent = self._crc.get((layer, slot))
        return None if ent is None else ent[1]

    def generation(self, layer: int, slot: int) -> int:
        return self._crc.get((layer, slot), (0, 0))[0]

    def verify(self, layer: int, slot: int, crc: int) -> Optional[bool]:
        """Compare ``crc`` against the latest stamp. True = intact,
        False = MISMATCH (counted), None = the slot was never stamped
        (unwritten/partial pages carry no expectation)."""
        exp = self.expected(layer, slot)
        if exp is None:
            return None
        self.verifies += 1
        if crc != exp:
            self.mismatches += 1
            return False
        return True

    def drop_slot(self, slot: int) -> int:
        """Forget every layer's entry for ``slot`` (the slot returned to
        the free list or was quarantined — its next tenant re-stamps).
        Returns how many entries dropped."""
        dead = [k for k in self._crc if k[1] == slot]
        for k in dead:
            del self._crc[k]
        return len(dead)

    def stamped_slots(self) -> List[int]:
        """Distinct slots with at least one stamped layer, sorted — the
        scrubber's deterministic round-robin domain."""
        return sorted({s for (_, s) in self._crc})


# ---------------------------------------------------------------------------
# Fault injection (tools/servechaos.py + tests). The flip is REAL: the
# device buffer (or the in-flight host ship) holds different bytes
# afterward, and only checksum verification can tell.


def pool_layers(engine) -> List[int]:
    """Model-layer indices that own a KV pool (attention layers) — the
    valid ``layer`` domain for ``flip_pool_bit`` and the servechaos
    ``--corrupt`` @L suffix."""
    return [li for li, pool in enumerate(engine.pools) if pool is not None]


def stable_stamped_slots(engine) -> List[int]:
    """Stamped slots that are NOT any active row's current write frontier,
    sorted — the deterministic injection domain chaos tooling targets.

    A flip into the page a row is about to append to races the next
    write's re-stamp, which checksums the whole page — corrupted residue
    included — and blesses the corruption. That is the honest TOCTOU
    window of any write-boundary ledger (a flip landing mid-write is
    indistinguishable from the write); targeting settled pages is what
    makes an injection experiment measure DETECTION, not the race."""
    if engine.integrity is None:
        return []
    hot = set()
    for a in engine._active():
        if a.state == "decode":
            p0 = a.decode_pos // engine.page
            pages = range(p0, min(a.n_pages, p0 + 2))
        else:  # prefill frontier page (partially written, not yet stamped)
            pages = range(a.prefill_done // engine.page,
                          min(a.n_pages, a.prefill_done // engine.page + 1))
        for idx in pages:
            hot.add(int(engine.table[a.row, idx]))
    return [s for s in engine.integrity.stamped_slots() if s not in hot]


def flip_pool_bit(engine, layer: int, slot: int,
                  key: Optional[str] = None, index: int = 0,
                  bit: int = 0) -> Dict[str, int]:
    """Flip ONE bit of pool array ``key`` inside ``slot``'s rows of layer
    ``layer`` on the DEVICE (functional update via device_put, so no
    recompile — the buffer is replaced, not re-traced). ``key`` None
    picks the first checksum-domain key (payload); pass ``"scale_k"`` to
    corrupt the int8 sidecar. Returns a record of what flipped."""
    import jax  # deferred: the ledger half of this module stays jax-free

    pool = engine.pools[layer]
    if pool is None:
        raise ValueError(
            f"layer {layer} owns no KV pool (valid: {pool_layers(engine)})")
    if key is None:
        key = sorted(k for k, v in pool.items()
                     if getattr(v, "ndim", 0))[0]
    arr = pool[key]
    host = np.array(np.asarray(arr), copy=True)
    rows = host[slot] if engine._page_axis == 0 else host[:, slot]
    sub = np.array(rows, copy=True)
    flat = sub.reshape(-1).view(np.uint8)
    byte = int(index) % flat.size
    flat[byte] ^= np.uint8(1 << (bit % 8))
    if engine._page_axis == 0:
        host[slot] = sub
    else:
        host[:, slot] = sub
    npool = dict(pool)
    npool[key] = jax.device_put(host, arr.sharding)
    engine.pools[layer] = npool
    return {"layer": int(layer), "slot": int(slot), "key": key,
            "byte": byte, "bit": bit % 8}


def flip_ship_bit(ship: dict, layer: int = 0, key: Optional[str] = None,
                  index: int = 0, bit: int = 0) -> Dict[str, int]:
    """Flip one bit of an in-flight handoff ship's page rows (host-side
    numpy — the wire-transit fault model). The original byte is stashed
    in ``ship["_wire_fault"]`` so the handoff retry can model
    retransmission from the exporter's intact source buffer."""
    pages = ship["pages"][layer]
    if key is None:
        key = sorted(pages)[0]
    arr = np.array(pages[key], copy=True)  # fetched rows may be read-only
    flat = arr.reshape(-1).view(np.uint8)
    byte = int(index) % flat.size
    orig = int(flat[byte])
    flat[byte] = orig ^ (1 << (bit % 8))
    pages[key] = arr
    ship["_wire_fault"] = {"layer": int(layer), "key": key, "byte": byte,
                           "orig": orig}
    return {"layer": int(layer), "key": key, "byte": byte, "bit": bit % 8}


def repair_ship(ship: dict) -> bool:
    """Undo a stashed wire fault — the model of the exporter
    retransmitting from its intact host buffer after the importer
    rejected the corrupt ship. Returns True if a fault was repaired."""
    fault = ship.pop("_wire_fault", None)
    if fault is None:
        return False
    arr = np.array(ship["pages"][fault["layer"]][fault["key"]], copy=True)
    arr.reshape(-1).view(np.uint8)[fault["byte"]] = fault["orig"]
    ship["pages"][fault["layer"]][fault["key"]] = arr
    return True
