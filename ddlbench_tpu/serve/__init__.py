"""Continuous-batching serving over the paged decoder.

The serving-side counterpart of the training benchmark: a scheduler that
packs chunked prefill next to in-flight decode under a token budget
(:mod:`serve.engine`), a refcounted free-list page allocator over the
shared KV pool (:mod:`serve.allocator`), a cross-request prefix cache over
page-aligned prompt blocks (:mod:`serve.prefix`), deterministic
open/closed-loop traffic incl. shared-prefix groups
(:mod:`serve.workload`), and — through ``tools/servebench.py`` — TTFT /
inter-token-latency percentiles and goodput-under-SLO reporting.

Import discipline: :mod:`serve.allocator`, :mod:`serve.draft`,
:mod:`serve.prefix` and :mod:`serve.workload` are jax-free (numpy +
stdlib), so workload synthesis, drafting, and allocation logic are
importable from jax-free hosts; the engine (which traces models) is
imported lazily via PEP 562 — the same laziness train/__init__ applies
for the chaosbench supervisor.
"""

from ddlbench_tpu.serve.allocator import PageAllocator  # noqa: F401
from ddlbench_tpu.serve.draft import NgramDrafter  # noqa: F401
from ddlbench_tpu.serve.prefix import PrefixIndex  # noqa: F401
from ddlbench_tpu.serve.workload import (  # noqa: F401
    ServeRequest,
    make_workload,
)

_ENGINE_NAMES = ("ReplicatedServer", "ServeEngine", "StepReport",
                 "make_server", "supports_serve", "fleet_stats")
_HANDOFF_NAMES = ("DisaggregatedServer", "export_request",
                  "make_disaggregated")


def __getattr__(name):  # PEP 562: engine (and with it jax) loads on demand
    if name in _ENGINE_NAMES:
        from ddlbench_tpu.serve import engine

        return getattr(engine, name)
    if name in _HANDOFF_NAMES:
        from ddlbench_tpu.serve import handoff

        return getattr(handoff, name)
    raise AttributeError(name)
