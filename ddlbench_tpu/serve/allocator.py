"""Free-list page allocator for the shared serving KV pool.

The beam-search paged cache (ops/paged_decode.py) statically stripes the
pool: row r owns slots ``r * n_pages + [0, n_pages)`` forever. A serving
engine cannot afford that — a request's KV history lives exactly as long as
the request, and "pool exhausted" must mean *the chip's cache memory is
genuinely full*, not *some row's private stripe ran out*. This allocator is
the host-side free list that turns the pool into per-request page-granular
memory: requests allocate pages as their streams grow, free them all on
completion or eviction, and admission backpressure falls out of
``alloc`` returning ``None``.

All decisions are plain Python on the host (the device only ever sees the
resulting page TABLE as an int32 input), so allocation order — and with it
every downstream scheduling decision — is deterministic: slots are handed
out lowest-first and freed slots are reused LIFO.

Slot 0 is reserved as the SCRATCH page (ops/paged_decode.SCRATCH_SLOT):
inactive rows' table entries point at it so their masked writes land
somewhere harmless. It is never handed out and never counted as capacity.
"""

from __future__ import annotations

from typing import Dict, List, Optional

# ops/paged_decode.SCRATCH_SLOT, duplicated so this module stays jax-free
# (the supervisor-side import discipline of train/__init__)
SCRATCH_SLOT = 0


class PageAllocator:
    """All-or-nothing page allocation with exact occupancy accounting."""

    def __init__(self, n_pages: int):
        if n_pages < 2:
            raise ValueError(
                f"pool needs >= 2 pages (1 scratch + 1 usable), got {n_pages}")
        self.n_pages = int(n_pages)
        # descending so .pop() hands out the lowest slot first; freed slots
        # are appended (LIFO reuse) — both choices only matter for
        # determinism, which they guarantee
        self._free: List[int] = [s for s in range(self.n_pages - 1, 0, -1)]
        self._owned: Dict[int, List[int]] = {}  # rid -> slots, alloc order
        self.allocs = 0
        self.frees = 0
        self.peak_in_use = 0

    @property
    def capacity(self) -> int:
        """Usable pages (the scratch slot is not capacity)."""
        return self.n_pages - 1

    @property
    def in_use(self) -> int:
        return self.capacity - len(self._free)

    def occupancy(self) -> float:
        return self.in_use / self.capacity

    def owned(self, rid: int) -> List[int]:
        return list(self._owned.get(rid, ()))

    def alloc(self, rid: int, n: int = 1) -> Optional[List[int]]:
        """Allocate ``n`` pages for request ``rid``; all-or-nothing.

        Returns the slot list, or None when the pool cannot supply ``n``
        pages (admission/step backpressure — nothing is allocated).
        """
        if n <= 0:
            raise ValueError(f"alloc n must be positive, got {n}")
        if n > len(self._free):
            return None
        slots = [self._free.pop() for _ in range(n)]
        assert SCRATCH_SLOT not in slots
        self._owned.setdefault(rid, []).extend(slots)
        self.allocs += n
        self.peak_in_use = max(self.peak_in_use, self.in_use)
        return slots

    def free_request(self, rid: int) -> int:
        """Free every page owned by ``rid`` (completion or eviction).

        Freeing a request that owns nothing is a double-free — the engine
        frees exactly once per retirement — and raises.
        """
        slots = self._owned.pop(rid, None)
        if slots is None:
            raise ValueError(f"double free: request {rid} owns no pages")
        self._free.extend(slots)
        self.frees += len(slots)
        return len(slots)
