"""Refcounted free-list page allocator for the shared serving KV pool.

The beam-search paged cache (ops/paged_decode.py) statically stripes the
pool: row r owns slots ``r * n_pages + [0, n_pages)`` forever. A serving
engine cannot afford that — a request's KV history lives exactly as long as
the request, and "pool exhausted" must mean *the chip's cache memory is
genuinely full*, not *some row's private stripe ran out*. This allocator is
the host-side free list that turns the pool into per-request page-granular
memory: requests allocate pages as their streams grow, free them all on
completion or eviction, and admission backpressure falls out of
``alloc`` returning ``None``.

Cross-request PREFIX CACHING (serve/prefix.py) adds shared ownership: one
pool slot may hold the KV of a prompt prefix that several requests (and the
prefix index itself) reference at once. Ownership is therefore a REFCOUNT
per slot:

* ``alloc`` hands out fresh slots at refcount 1 (private to the request);
* ``bind`` lets a request take a reference on already-resident slots (the
  prefix-cache hit path) — shared slots are immutable by the engine's
  write discipline (ops/paged_decode.py shared-pool contract);
* ``free_request``/``decref`` drop references; the slot returns to the
  free list only when the LAST reference drops, so freeing a request whose
  prefix is shared never yanks pages out from under its siblings;
* the prefix index holds its own reference (``incref``) on every page it
  caches, which is what keeps a completed request's prompt pages resident
  for future hits — reclaiming the cache (eviction under pool pressure)
  only ever takes pages whose sole remaining reference IS the cache, i.e.
  pages no live request holds.

All decisions are plain Python on the host (the device only ever sees the
resulting page TABLE as an int32 input), so allocation order — and with it
every downstream scheduling decision — is deterministic: slots are handed
out lowest-first and freed slots are reused LIFO.

Slot 0 is reserved as the SCRATCH page (ops/paged_decode.SCRATCH_SLOT):
inactive rows' table entries point at it so their masked writes land
somewhere harmless. It is never handed out and never counted as capacity.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

# ops/paged_decode.SCRATCH_SLOT, duplicated so this module stays jax-free
# (the supervisor-side import discipline of train/__init__)
SCRATCH_SLOT = 0


class PageAllocator:
    """All-or-nothing page allocation with per-slot refcounts and exact
    occupancy accounting."""

    def __init__(self, n_pages: int):
        if n_pages < 2:
            raise ValueError(
                f"pool needs >= 2 pages (1 scratch + 1 usable), got {n_pages}")
        self.n_pages = int(n_pages)
        # descending so .pop() hands out the lowest slot first; freed slots
        # are appended (LIFO reuse) — both choices only matter for
        # determinism, which they guarantee
        self._free: List[int] = [s for s in range(self.n_pages - 1, 0, -1)]
        self._owned: Dict[int, List[int]] = {}  # rid -> slots, alloc order
        self._ref: Dict[int, int] = {}  # slot -> refcount (live slots only)
        # slots pulled from circulation by SDC quarantine: when the last
        # reference drops they do NOT return to the free list, so a
        # corrupted page is never handed to another request. Quarantined
        # capacity stays counted as in_use — the pool genuinely shrank.
        self._quarantined: set = set()
        self.allocs = 0
        self.frees = 0
        self.peak_in_use = 0
        # optional (name, **args) sink for pool lifecycle instants — the
        # engine wires it to the virtual-time tracer when cfg.trace is on
        # (this module stays jax- and telemetry-free; the hook is how the
        # allocator shows up on the trace without knowing virtual time)
        self.on_event: Optional[Callable[..., None]] = None
        # optional hook fired with the slot id whenever a slot PHYSICALLY
        # returns to the free list (never for quarantined retires) — the
        # engine wires it to the SDC ledger's drop_slot so stale checksum
        # expectations die with the tenancy (serve/integrity.py)
        self.on_slot_free: Optional[Callable[[int], None]] = None

    @property
    def capacity(self) -> int:
        """Usable pages (the scratch slot is not capacity)."""
        return self.n_pages - 1

    @property
    def in_use(self) -> int:
        return self.capacity - len(self._free)

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def quarantined(self) -> int:
        """Slots pulled from circulation by SDC quarantine (live refs may
        still be draining; the count never shrinks within a run)."""
        return len(self._quarantined)

    @property
    def shared_pages(self) -> int:
        """Slots referenced more than once right now (cross-request prefix
        sharing; the cache's own reference counts, so a cached page bound
        by one live request shows as shared)."""
        return sum(1 for c in self._ref.values() if c >= 2)

    def occupancy(self) -> float:
        return self.in_use / self.capacity

    def owned(self, rid: int) -> List[int]:
        return list(self._owned.get(rid, ()))

    def refcount(self, slot: int) -> int:
        return self._ref.get(slot, 0)

    def alloc(self, rid: int, n: int = 1) -> Optional[List[int]]:
        """Allocate ``n`` fresh pages for request ``rid``; all-or-nothing.

        Returns the slot list (each at refcount 1), or None when the pool
        cannot supply ``n`` pages (admission/step backpressure — nothing
        is allocated).
        """
        if n <= 0:
            raise ValueError(f"alloc n must be positive, got {n}")
        if n > len(self._free):
            return None
        slots = [self._free.pop() for _ in range(n)]
        assert SCRATCH_SLOT not in slots
        self._owned.setdefault(rid, []).extend(slots)
        for s in slots:
            self._ref[s] = 1
        self.allocs += n
        self.peak_in_use = max(self.peak_in_use, self.in_use)
        if self.on_event is not None:
            self.on_event("pool_alloc", rid=rid, pages=n,
                          free=len(self._free))
        return slots

    def bind(self, rid: int, slots: List[int]) -> None:
        """Take a reference on already-resident ``slots`` for request
        ``rid`` (the prefix-cache hit path). Binding a dead slot is a
        bookkeeping bug and raises."""
        for s in slots:
            if self._ref.get(s, 0) < 1:
                raise ValueError(f"bind of dead slot {s} for request {rid}")
        self._owned.setdefault(rid, []).extend(slots)
        for s in slots:
            self._ref[s] += 1

    def incref(self, slot: int) -> None:
        """Extra reference on a live slot (the prefix index pinning a page
        it caches — request-side references go through ``bind``)."""
        if self._ref.get(slot, 0) < 1:
            raise ValueError(f"incref of dead slot {slot}")
        self._ref[slot] += 1

    def holders(self, slot: int) -> List[int]:
        """Request ids currently holding a reference on ``slot``, in rid
        order — the quarantine walk of a corrupted SHARED page (every
        holder read poisoned bytes and must take the recompute path)."""
        return sorted(r for r, slots in self._owned.items() if slot in slots)

    def quarantine(self, slot: int) -> None:
        """Pull ``slot`` out of circulation (SDC detection): if it is on
        the free list it leaves immediately; if references are still live
        it leaves when the last one drops (see ``decref``). Either way it
        is never allocated again this run. Idempotent; the scratch slot
        cannot be quarantined (it holds no real data)."""
        if slot == SCRATCH_SLOT:
            raise ValueError("cannot quarantine the scratch slot")
        if slot in self._quarantined:
            return
        self._quarantined.add(slot)
        if slot in self._free:
            self._free.remove(slot)
        if self.on_event is not None:
            self.on_event("pool_quarantine", slot=slot,
                          free=len(self._free))

    def decref(self, slot: int) -> bool:
        """Drop one reference; returns True when the slot actually
        returned to the free list (last reference dropped). A quarantined
        slot never returns — its last decref retires it for good (counted
        as freed: the holder genuinely let go). Dropping a reference a
        holder does not have is a double-free and raises."""
        c = self._ref.get(slot, 0)
        if c < 1:
            raise ValueError(f"double free: slot {slot} has no references")
        if c == 1:
            del self._ref[slot]
            self.frees += 1
            if slot not in self._quarantined:
                self._free.append(slot)
                if self.on_slot_free is not None:
                    self.on_slot_free(slot)
            return True
        self._ref[slot] = c - 1
        return False

    def release(self, rid: int, slots: List[int]) -> int:
        """Drop ``rid``'s reference on a SUBSET of its pages — the
        speculative-decoding rollback: pages allocated ahead for draft
        writes whose drafts were rejected return to the pool without
        retiring the request (eviction's partial sibling). Releasing a
        slot the request does not hold is a double-free and raises; the
        all-or-nothing alloc discipline is unaffected (these pages were
        granted normally). Returns how many pages physically freed."""
        owned = self._owned.get(rid)
        freed = 0
        for s in slots:
            if owned is None or s not in owned:
                raise ValueError(
                    f"double free: request {rid} does not hold slot {s}")
            owned.remove(s)
            freed += self.decref(s)
        # a fully-released rid keeps its (empty) ownership entry: the
        # request is still live and its eventual free_request must not
        # read as a double-free
        if slots and self.on_event is not None:
            self.on_event("pool_rollback", rid=rid, held=len(slots),
                          freed=freed, free=len(self._free))
        return freed

    def free_request(self, rid: int) -> int:
        """Drop ``rid``'s reference on every page it holds (completion or
        eviction). Returns how many pages physically returned to the free
        list — shared pages survive until their last holder lets go.

        Freeing a request that owns nothing is a double-free — the engine
        frees exactly once per retirement — and raises.
        """
        slots = self._owned.pop(rid, None)
        if slots is None:
            raise ValueError(f"double free: request {rid} owns no pages")
        freed = sum(1 for s in slots if self.decref(s))
        if self.on_event is not None:
            self.on_event("pool_release", rid=rid, held=len(slots),
                          freed=freed, free=len(self._free))
        return freed
