"""Continuous-batching serving engine over the paged decoder.

DDLBench measures training; this engine is the serving half of the north
star ("serve heavy traffic from millions of users"). It is the same
"keep the device busy" move the training side made with prefetch (PR 1)
and comm overlap (PR 6), applied to inference: instead of decoding a fixed
batch to completion and idling drained rows, the engine runs ITERATION-
level scheduling (Orca/vLLM lineage) — every step packs, under a token
budget, chunked-prefill segments of newly admitted requests next to
single-token decode for the requests already in flight, so a finishing
request's row is refilled on the very next step.

Structure (host schedules, device computes):

* The host owns the admission queue, the per-request bookkeeping, ONE page
  table ``[max_batch, npg_max] int32`` shared by every layer, and the
  free-list :class:`~ddlbench_tpu.serve.allocator.PageAllocator` over the
  shared K/V pool (ops/paged_decode.py serve primitives; slot 0 scratch).
  Every scheduling decision is plain deterministic Python; the device only
  ever sees the table as an int32 input.
* Two jitted programs cover all traffic, shape-stable by construction:
  a ``[max_batch, 1]`` decode step at per-row positions (inactive rows are
  masked by routing their table row to the scratch slot) and a
  ``[1, prefill_chunk]`` page-aligned prefill chunk. Each compiles per
  live-page count ``npl`` — the one-page-segment static-shape idiom of
  models/decode.py — so the jit cache is bounded by ``max_len / page``
  variants regardless of traffic.
* Cross-request PREFIX CACHING (``cfg.prefix_cache``; serve/prefix.py):
  fully-prefilled prompt pages are registered in a host-side prefix index
  as they complete, and a newly admitted request BINDS the already-
  resident pages of its longest cached prefix into its table row
  (allocator refcounts) instead of re-prefilling them — only the uncached
  tail is chunk-prefilled. A full page-aligned hit skips prefill entirely:
  the last cached page is copy-on-write copied into a private slot
  (ops/paged_decode.serve_page_copy — shared pages are immutable) and the
  request enters decode directly, re-deriving the last prompt position's
  K/V and first-token logits through the decode program.
* Sampling (``cfg.temperature``): greedy argmax stays the default and its
  compiled programs are bitwise-untouched; with temperature > 0 the
  programs return logits and the host samples with counter-based
  per-request seeds (fold of sample_seed + request id + token index — no
  wall-clock nondeterminism), so streams are bitwise-reproducible per
  seed and eviction/recompute regenerates identical tokens.
* Quantized KV pages (``cfg.kv_dtype``): the shared pool stores K/V in
  f32, bf16, or int8 — int8 quantizes at the page-WRITE boundary
  (per-page scale sidecar, unbiased stochastic rounding with counter-
  based seeds: ops/paged_decode.py) and dequantizes inside the attention
  kernels/references, so pool bytes per token drop 4x vs f32 (2x vs
  bf16) and concurrent capacity at equal HBM doubles. Output quality is
  pinned by a digits gate; scales travel with pages through COW and
  prefix binds, so caching composes for free.
* Self-drafting SPECULATIVE DECODING (``cfg.speculative = ngram:N:K``;
  Leviathan et al. 2022): a host-side n-gram drafter proposes up to K
  tokens per decode row from the row's own emitted prefix, and ONE
  verify pass — a [max_batch, K+1] span program built from the existing
  per-row-start chunk attention — scores all K+1 positions at the price
  of one model pass. The longest draft prefix matching greedy argmax is
  accepted, so spec-on streams equal the spec-off streams token for
  token — pinned BITWISE on the CPU fixtures (the correctness pin; exact
  equality also needs the verify program's argmax to agree with the
  decode program's, whose reduction orders differ in the last ulp — the
  on-chip round-16 A/B re-checks agreement), accepted K/V is already in
  place from the span
  write, and pages past the accepted frontier roll back to the pool like
  eviction's frees. Speculation never evicts anyone: a page shortfall
  truncates drafts instead.
* Eviction closes the loop on pool exhaustion: when a growing request
  needs a page and the free list is empty, the engine first RECLAIMS
  prefix-cache pages no live request references (newest-registered
  first), then evicts the NEWEST-admitted request (its refs dropped —
  shared pages survive for their other holders — request re-queued at
  the front for recomputation, which the seeded sampling/greedy streams
  regenerate identically), so the oldest requests always make progress
  and livelock is impossible.
* ``policy="static"`` is the built-in A/B baseline: requests are admitted
  only when every row is free (whole-batch fill), with full worst-case
  page reservation, and the batch drains to completion before the next is
  admitted — classic static batching on identical numerics, so servebench
  measures pure scheduling effect.
* FLEET FAULT TOLERANCE (ISSUE 15): :meth:`ReplicatedServer.fail` hard-
  kills a replica (pool lost, finished records salvaged, held requests
  resubmitted least-loaded onto survivors where recompute regenerates
  BITWISE-identical streams — prompt + emitted tokens are host state),
  :meth:`ReplicatedServer.stall` injects a straggler that holds its
  requests without progressing, and a serve-side heartbeat
  (``cfg.heartbeat``, train/watchdog.ProgressMonitor on the virtual
  clock) drains a no-progress replica like a scale-down. Per-request
  DEADLINES add admission control: a request whose projected completion
  already misses its deadline is SHED at submit (named rejection, driver
  retries with bounded backoff), and one that expires in place cancels
  into the named ``timeout`` terminal state with every page freed. SLO
  TIERS (ROADMAP 2c): ``ServeRequest.tier`` — interactive admits ahead
  of batch, batch is the preemptible lane (evicted first under pool
  pressure, riding eviction+recompute). All of it is inert for plain
  traffic: no deadlines, one tier, no injections = the pre-chaos
  scheduler, bitwise (pinned).

Virtual time: one unit = one model pass (a decode step over max_batch rows
or one prefill chunk), the cost model under which batch parallelism is
free and wasted passes are what continuous batching eliminates. All
latency/goodput metrics are in these units — fully deterministic, which is
what makes servebench's JSON bitwise-reproducible under a fixed seed.

Observability (``cfg.trace``, PR 11): the engine emits request-lifecycle
events into the process-global telemetry tracer, stamped in VIRTUAL time —
``submit``/``queue_wait``/``admit``/``prefill_chunk``/``first_token``/
``decode``/``evict``/``recompute``/``finish`` on one Chrome-trace track
per request per replica, pool/prefix instants on a pool track, and
per-step counter tracks (occupancy, free pages, decode-batch utilization,
token-budget fill, prefix hits, shared pages, queue depth).
``telemetry/serveview.py`` reduces the trace to TTFT/ITL component
decompositions and the windowed SLO/goodput time series. Tracing is
metrics-neutral on AND off — it only records decisions already made, so
virtual-time JSON and token streams are bitwise identical (pinned). A
bounded flight recorder (``cfg.flight_recorder`` recent per-step states)
plus ``snapshot()`` expose live state without any tracer at all.

Multi-replica serving (:class:`ReplicatedServer`) runs N independent
engines — the serving analog of the mesh's 'data' axis: replicas share
nothing, and a least-loaded dispatcher routes each arrival. Replicas step
in lockstep; a global step costs the maximum over replica step costs.
"""

from __future__ import annotations

import dataclasses
import random
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ddlbench_tpu.config import ServeConfig
from ddlbench_tpu.models.layers import LayerModel
from ddlbench_tpu.serve.allocator import PageAllocator
from ddlbench_tpu.serve.draft import NgramDrafter
from ddlbench_tpu.serve.integrity import (
    PageLedger,
    page_checksum,
    ship_checksums,
)
from ddlbench_tpu.serve.prefix import PrefixIndex
from ddlbench_tpu.serve.workload import TIERS, ServeRequest
from ddlbench_tpu.telemetry.stats import request_slo_ok
from ddlbench_tpu.telemetry.tracer import get_tracer
from ddlbench_tpu.train.watchdog import ProgressMonitor


def _vns(t: float) -> int:
    """Virtual time -> trace 'nanoseconds': one model pass scales to 1000
    ns so the exporter's /1e3 renders one virtual unit as exactly 1 µs,
    and every timestamp is an exact integer — serveview's TTFT/ITL
    decomposition tiles these intervals with no float drift."""
    return int(round(t * 1000.0))


def sample_token(logits: np.ndarray, temperature: float, top_k: int,
                 sample_seed: int, rid: int, token_index: int) -> int:
    """Temperature/top-k sampling with a counter-based seed: one uniform
    from ``random.Random(f"{sample_seed}:{rid}:{token_index}")`` (CPython
    seeds strings through SHA-512 — stable by language guarantee),
    inverse-transformed over the f64 softmax CDF. Keyed by TOKEN INDEX,
    not engine step, so eviction/recompute re-draws the identical stream.
    Pure host arithmetic — deterministic given the logits bytes."""
    scaled = logits.astype(np.float64) / temperature
    if top_k:
        # ties broken by vocab index (stable sort) — deterministic
        order = np.argsort(-scaled, kind="stable")
        mask = np.full_like(scaled, -np.inf)
        keep = order[:top_k]
        mask[keep] = scaled[keep]
        scaled = mask
    scaled -= scaled.max()
    probs = np.exp(scaled)
    probs /= probs.sum()
    u = random.Random(f"{sample_seed}:{rid}:{token_index}").random()
    idx = int(np.searchsorted(np.cumsum(probs), u, side="right"))
    return min(idx, len(probs) - 1)


def supports_serve(model: LayerModel) -> bool:
    """True if every layer is servable (ServeOps or pointwise)."""
    return all(l.serve is not None or l.pointwise for l in model.layers)


def _require_serve_support(model: LayerModel) -> None:
    if not supports_serve(model):
        missing = [l.name for l in model.layers
                   if l.serve is None and not l.pointwise]
        raise NotImplementedError(
            f"{model.name} has layers without serving support: {missing}; "
            "the serving engine is wired for causal-LM transformer stacks")


@dataclasses.dataclass
class _Active:
    """Host-side bookkeeping for one in-flight request on one engine row."""

    req: ServeRequest
    row: int
    admit_seq: int  # admission order; eviction victims are newest-first
    state: str = "prefill"  # "prefill" -> "decode"
    prefill_done: int = 0  # prompt positions already processed
    n_pages: int = 0  # table[row, :n_pages] hold this request's slots
    # prompt blocks already in the prefix index (bound blocks at admission,
    # then private blocks registered as their prefill completes)
    registered_blocks: int = 0
    pending_tok: int = -1  # next decode input token (= last emitted)
    first_token_t: Optional[float] = None
    out: List[int] = dataclasses.field(default_factory=list)
    token_times: List[float] = dataclasses.field(default_factory=list)

    @property
    def decode_pos(self) -> int:
        """Stream position of the pending decode input token."""
        return self.req.prompt_len + len(self.out) - 1


@dataclasses.dataclass
class StepReport:
    """What one engine step did (host-observable; drives the load gen)."""

    cost: int = 0  # virtual time units = model passes this step
    prefill_calls: int = 0
    decode_rows: int = 0
    admitted: int = 0
    evicted: int = 0
    backpressure: int = 0
    prefix_hits: int = 0  # admissions that bound >= 1 cached prefix page
    completed: List[int] = dataclasses.field(default_factory=list)
    # rids cancelled into the `timeout` terminal state this step — a
    # terminal event like completion (the closed-loop driver releases the
    # next request on either, or it would wait forever on a dead rid)
    timed_out: List[int] = dataclasses.field(default_factory=list)

    def merge(self, other: "StepReport") -> None:
        self.cost = max(self.cost, other.cost)
        self.prefill_calls += other.prefill_calls
        self.decode_rows += other.decode_rows
        self.admitted += other.admitted
        self.evicted += other.evicted
        self.backpressure += other.backpressure
        self.prefix_hits += other.prefix_hits
        self.completed.extend(other.completed)
        self.timed_out.extend(other.timed_out)


class ServeEngine:
    """One serving replica: scheduler + allocator + the two jitted steps."""

    def __init__(self, model: LayerModel, params, state, cfg: ServeConfig,
                 dtype=None, device=None, shared_fns=None, replica: int = 0):
        import jax
        import jax.numpy as jnp

        _require_serve_support(model)
        cfg.validate()
        if cfg.max_len > model.in_shape[0]:
            raise ValueError(
                f"max_len {cfg.max_len} exceeds the model's stream length "
                f"{model.in_shape[0]}")
        self.model = model
        self.cfg = cfg
        self.page = cfg.page
        self.npg_max = cfg.npg_max()
        # pool storage dtype: cfg.kv_dtype unless the caller overrides —
        # int8 builds the quantized pool layout (payload + per-page scale
        # sidecar; ops/paged_decode.serve_pool_init)
        kv_map = {"float32": jnp.float32, "bfloat16": jnp.bfloat16,
                  "int8": jnp.int8}
        self.dtype = dtype if dtype is not None else kv_map[cfg.kv_dtype]
        self._put = (lambda t: jax.device_put(t, device)) if device \
            else (lambda t: t)
        # page axis of the pool leaves: tp=1 pools are [n_pages, ...] (the
        # single-chip layout, bitwise-unchanged); tp>1 stacks per-shard
        # pool slices on a LEADING [tp] axis laid over the mesh 'model'
        # axis, so the page axis moves to 1
        self._page_axis = 0 if cfg.tp == 1 else 1
        if cfg.tp == 1:
            self.params = self._put(params)
            self.state = self._put(state)
            pools = []
            self.bytes_per_page = 0  # K/V payload bytes per slot, summed
            for li, (l, p) in enumerate(zip(model.layers, params)):
                if l.serve is None or l.serve.pool_init is None:
                    pools.append(None)
                    continue
                pool = l.serve.pool_init(p, cfg.pool_pages, cfg.page,
                                         self.dtype)
                if "scale_k" in pool:
                    # per-layer counter seed for the write-boundary
                    # stochastic rounding: quantized bytes become a pure
                    # function of (values, layer, k/v tag, stream
                    # position) — recompute and prefix re-derivations
                    # replay bitwise
                    pool["kv_seed"] = jnp.int32(li)
                from ddlbench_tpu.ops.paged_decode import pool_page_bytes

                self.bytes_per_page += pool_page_bytes(pool)
                pools.append(pool)
            self.pools = self._put(pools)
        else:
            self._init_tp(model, params, state, cfg)
        # self-drafting speculative decoding (cfg.speculative: ngram:N:K)
        self._spec = cfg.spec_params()
        self._drafter = NgramDrafter(*self._spec) if self._spec else None
        if self._spec is not None:
            missing = [l.name for l in model.layers
                       if l.serve is not None and l.serve.verify is None]
            if missing:
                raise NotImplementedError(
                    f"{model.name}: speculative decoding needs a "
                    f"ServeOps.verify on every serving layer; missing: "
                    f"{missing}")
        self.table = np.zeros((cfg.max_batch, self.npg_max), np.int32)
        self.allocator = PageAllocator(cfg.pool_pages)
        self.prefix: Optional[PrefixIndex] = (
            PrefixIndex(self.allocator, self.page)
            if cfg.prefix_cache else None)
        self._sampling = cfg.temperature > 0.0
        self.queue: deque = deque()
        self.rows: List[Optional[_Active]] = [None] * cfg.max_batch
        self.finished: List[Dict[str, Any]] = []
        self._admit_seq = 0
        self._filling = False  # static policy: whole-batch fill phase
        # -- observability state (tentpole, PR 11). All of it is host-side
        # bookkeeping the scheduler never reads: with cfg.trace off OR on,
        # scheduling decisions and token streams are bitwise identical.
        self.replica = replica
        self._trk = f"r{replica}"  # per-replica trace-track prefix
        self._now = 0.0  # current step's start (for mid-schedule instants)
        self._last_t = 0.0  # last step's end — snapshot()'s clock
        # when each queued request entered the queue (arrival, or the
        # eviction instant on recompute) — the queue_wait span's left edge
        # and the queued-request age in snapshot()
        self._queued_at: Dict[int, float] = {}
        # rids evicted and not yet re-admitted (the `recompute` instant)
        self._evicted_rids: set = set()
        self._flight: Optional[deque] = (
            deque(maxlen=cfg.flight_recorder) if cfg.flight_recorder
            else None)
        if cfg.trace:
            # pool/prefix lifecycle instants ride the same virtual clock
            self.allocator.on_event = self._pool_event
            if self.prefix is not None:
                self.prefix.on_event = self._pool_event
        # prompt tokens served from the cache per request, accumulated
        # across re-admissions (eviction/recompute) — attached to the
        # finished record for telemetry/stats.serve_summary
        self._cached_tokens: Dict[int, int] = {}
        # -- chaos/robustness state (ISSUE 15). All defaults inert: with
        # no deadlines submitted, no tiers in the traffic, and no
        # stall()/fail() injected, scheduling is bitwise the pre-chaos
        # engine.
        # deadline bookkeeping: the expiry scan only runs once a
        # deadlined request has ever been accepted
        self._has_deadlines = False
        # `timeout` terminal records (rid/t/deadline/state/out_tokens/
        # tier) and `shed` admission rejections (rid/t/deadline/tier)
        self.timed_out: List[Dict[str, Any]] = []
        self.shed: List[Dict[str, Any]] = []
        # every eviction (rid/t/tier) — the tier-preemption-order ledger
        self.evicted_log: List[Dict[str, Any]] = []
        # straggler injection: ReplicatedServer.stall sets this; while
        # positive the server skips this replica's steps (it holds its
        # requests but makes no progress) and decrements per global step
        self._stall_ticks = 0
        # serve-side heartbeat (cfg.heartbeat > 0): the server kicks this
        # monitor every step it schedules the replica; an expired monitor
        # on a replica that still holds work is the straggler verdict
        self.monitor: Optional[ProgressMonitor] = (
            ProgressMonitor(cfg.heartbeat) if cfg.heartbeat > 0 else None)
        # -- SDC defense state (ISSUE 20; serve/integrity.py). With
        # cfg.integrity off there is NO ledger, no stamps, no verifies —
        # scheduling and streams are bitwise the pre-SDC engine.
        self.integrity: Optional[PageLedger] = (
            PageLedger() if cfg.integrity else None)
        # detection/quarantine ledger (t/slot/where/displaced rids) —
        # servechaos derives MTTD and quarantine-MTTR from these
        self.sdc_events: List[Dict[str, Any]] = []
        self._scrub_cursor = 0
        # eviction-recompute re-derivation expectations: rid -> {(layer,
        # page_idx): crc} of the FULLY-written prompt pages at eviction —
        # the replayed prefill must regenerate the same bytes (the
        # byte-identical re-prefill invariant, now checked, not assumed)
        self._recompute_expect: Dict[int, Dict[Tuple[int, int], int]] = {}
        if self.integrity is not None:
            # a slot physically returning to the free list invalidates
            # its ledger entries (the next tenant re-stamps at its own
            # write) — without this, the scrubber would flag every
            # legitimate reuse as corruption
            self.allocator.on_slot_free = self.integrity.drop_slot
        self.stats: Dict[str, float] = {
            "steps": 0, "model_calls": 0, "prefill_calls": 0,
            "decode_calls": 0, "decode_row_slots": 0, "admitted": 0,
            "completed": 0, "evicted": 0, "backpressure": 0,
            # deadline counters (always present — deadline-free runs
            # report 0; servebench gates them out of plain rows so the
            # pinned schema is unchanged)
            "shed": 0, "timeouts": 0,
            "peak_occupancy": 0.0, "frag_sum": 0.0, "frag_samples": 0,
            # prefix-cache counters (always present — cache-off and the
            # static baseline report 0, keeping the JSON schema stable)
            "prefix_hits": 0, "prefix_tokens_saved": 0, "cow_copies": 0,
            "shared_pages": 0, "prefill_tokens": 0,
            # speculative-decoding counters (always present — spec-off
            # reports 0, keeping the schema stable like the prefix set).
            # decode_tokens = tokens emitted by decode/verify passes (the
            # tokens-per-pass numerator; prefill first tokens excluded)
            "spec_passes": 0, "spec_drafted": 0, "spec_accepted": 0,
            "decode_tokens": 0,
            # SDC-defense counters (always present — integrity-off runs
            # report 0; servebench gates them out of plain rows via
            # _SDC_FIELDS, the _CHAOS_FIELDS pattern)
            "sdc_injected": 0, "sdc_detected": 0, "sdc_quarantined": 0,
            "sdc_recovered": 0, "sdc_scrubbed": 0,
            "sdc_recompute_checks": 0,
        }
        if shared_fns is not None:
            # replicas of one server share the jitted callables (same model
            # and shapes), so same-device replicas share the compile cache
            # instead of re-tracing every npl variant per engine
            (self._decode_jit, self._prefill_jit, self._cow_jit,
             self._verify_jit) = shared_fns
        elif cfg.tp == 1:
            self._make_fns()
        else:
            self._make_tp_fns()

    def jit_fns(self):
        """The (decode, prefill, cow, verify) jitted callables, shareable
        with sibling replicas built from the same model/config."""
        return (self._decode_jit, self._prefill_jit, self._cow_jit,
                self._verify_jit)

    def audit_programs(self):
        """``(name, jitfn, example_args)`` for the audit plane
        (telemetry/audit.py): representative zero-token instantiations of
        the serve programs at ``npl=1``, the same shapes the scheduler
        calls with. AOT lowering never executes, so the donated pool
        arguments are safe to keep using afterwards."""
        import jax.numpy as jnp

        cfg = self.cfg
        B, G = cfg.max_batch, self.npg_max
        table = jnp.zeros((B, G), jnp.int32)
        toks = jnp.zeros((B, 1), jnp.int32)
        pos = jnp.zeros((B,), jnp.int32)
        chunk = jnp.zeros((1, cfg.resolved_prefill_chunk()), jnp.int32)
        progs = [
            ("decode", self._decode_jit,
             (self.params, self.state, self.pools, table, toks, pos, 1)),
            ("prefill", self._prefill_jit,
             (self.params, self.state, self.pools, table[:1], chunk,
              jnp.int32(0), jnp.int32(0), 1)),
            ("cow", self._cow_jit,
             (self.pools, jnp.int32(0), jnp.int32(1))),
        ]
        if self._spec is not None:
            W = self._spec[1] + 1
            progs.append(
                ("verify", self._verify_jit,
                 (self.params, self.state, self.pools, table,
                  jnp.zeros((B, W), jnp.int32), pos, 1)))
        return progs

    # -- request-lifecycle tracing (virtual-time, metrics-neutral) ---------

    def _tr(self):
        """The live tracer, or None. Both gates — ``cfg.trace`` off and a
        disabled process tracer — collapse every emission site to one
        attribute check, the same disabled-path contract the train loop
        holds (telemetry/tracer.py)."""
        if not self.cfg.trace:
            return None
        tr = get_tracer()
        return tr if tr.enabled else None

    def _req_track(self, rid: int) -> str:
        """One Chrome-trace track per request per replica."""
        return f"{self._trk}/req{rid}"

    def _pool_event(self, name: str, **args: Any) -> None:
        """Allocator/prefix hook target: pool lifecycle instants on the
        replica's pool track, stamped at the current step's start."""
        tr = self._tr()
        if tr is not None:
            tr.emit("i", name, _vns(self._now), track=f"{self._trk}/pool",
                    args=args)

    def _trace_admit(self, a: "_Active", cached: int) -> None:
        """Close the request's queue_wait span and mark the admission
        (plus the recompute marker when this is a re-admission after
        eviction). Also runs the queue bookkeeping the snapshot ages use,
        so it is called on EVERY admission, traced or not."""
        rid = a.req.rid
        q0 = self._queued_at.pop(rid, self._now)
        recompute = rid in self._evicted_rids
        self._evicted_rids.discard(rid)
        tr = self._tr()
        if tr is None:
            return
        trk = self._req_track(rid)
        t_ns = _vns(self._now)
        tr.emit("X", "queue_wait", _vns(q0), t_ns - _vns(q0), track=trk,
                args={"rid": rid,
                      "reason": "recompute" if recompute else "arrival"})
        if recompute:
            tr.emit("i", "recompute", t_ns, track=trk, args={"rid": rid})
        tr.emit("i", "admit", t_ns, track=trk,
                args={"rid": rid, "row": a.row, "seq": a.admit_seq,
                      "cached_tokens": cached})

    # -- jitted model programs ---------------------------------------------

    def _make_fns(self) -> None:
        import jax
        import jax.numpy as jnp

        layers = self.model.layers
        page = self.page

        def walk(params, states, pools, table, h, op_name, *op_args):
            out_pools = []
            for layer, p, s, pool in zip(layers, params, states, pools):
                if layer.serve is not None:
                    op = getattr(layer.serve, op_name)
                    h, pool = op(p, s, pool, table, h, *op_args)
                else:  # pointwise (the LM head)
                    h, _ = layer.apply(p, s, h, False)
                out_pools.append(pool)
            return h, out_pools

        sampling = self._sampling

        def decode_fn(params, states, pools, table, toks, pos, npl):
            logits, pools = walk(params, states, pools, table, toks,
                                 "decode", pos, npl, page)
            if sampling:  # host samples; greedy keeps the on-device argmax
                return logits[:, 0, :].astype(jnp.float32), pools
            nxt = jnp.argmax(logits[:, 0, :], axis=-1).astype(jnp.int32)
            return nxt, pools

        # trailing pointwise layers (the LM head) need only the ONE chunk
        # position whose next token the scheduler wants — applying them to
        # all C positions would spend C head matmuls per chunk for 1 (or,
        # on non-last chunks, 0) useful rows
        n_body = len(layers)
        while n_body and layers[n_body - 1].serve is None \
                and layers[n_body - 1].pointwise:
            n_body -= 1

        def prefill_fn(params, states, pools, table, chunk, start, want, npl):
            from jax import lax

            h, out_pools = walk(params[:n_body], states[:n_body],
                                pools[:n_body], table, chunk,
                                "prefill", start, npl, page)
            h = lax.dynamic_slice_in_dim(h, want, 1, axis=1)  # [1, 1, d]
            for layer, p, s in zip(layers[n_body:], params[n_body:],
                                   states[n_body:]):
                h, _ = layer.apply(p, s, h, False)
            if sampling:
                return h[0, 0, :].astype(jnp.float32), \
                    out_pools + list(pools[n_body:])
            nxt = jnp.argmax(h[0, 0, :], axis=-1).astype(jnp.int32)
            return nxt, out_pools + list(pools[n_body:])

        def cow_fn(pools, src, dst):
            # prefix-cache copy-on-write: clone pool slot src into the
            # request's private slot dst in every layer's pool (one traced
            # program — src/dst are dynamic scalars)
            from ddlbench_tpu.ops.paged_decode import serve_page_copy

            return [serve_page_copy(pool, src, dst)
                    if pool is not None else None for pool in pools]

        def verify_fn(params, states, pools, table, toks, pos0, npl):
            # speculative verify: ONE [max_batch, W] pass scores every
            # row's pending token + drafts at per-row span positions
            # [pos0, pos0 + W) — the K-wide chunk variant (the span write
            # + the chunk-prefill attention program with per-row starts).
            # Greedy only: the host accepts drafts against these argmaxes
            logits, pools = walk(params, states, pools, table, toks,
                                 "verify", pos0, npl, page)
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), pools

        self._decode_jit = jax.jit(decode_fn, static_argnums=(6,),
                                   donate_argnums=(2,))
        self._prefill_jit = jax.jit(prefill_fn, static_argnums=(7,),
                                    donate_argnums=(2,))
        self._cow_jit = jax.jit(cow_fn, donate_argnums=(0,))
        self._verify_jit = jax.jit(verify_fn, static_argnums=(6,),
                                   donate_argnums=(2,))

    # -- tensor-parallel decode (cfg.tp > 1) -------------------------------

    def _init_tp(self, model, params, state, cfg: ServeConfig) -> None:
        """tp>1 layout: stack each layer's Megatron shard slices
        (models/transformer.tp_split_layer_params — the SAME splitter the
        training tp engine uses) on a leading [tp] axis laid over a mesh
        'model' axis, and size each shard's KV-pool slice from the params
        it actually holds (n_heads/tp head groups). The page table,
        allocator, and every scheduler decision stay host-side and
        per-ENGINE: a tp group is ONE replica — all tp shards hold their
        head slice of the same page, addressed by the same table row."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from ddlbench_tpu.distributed import make_mesh
        from ddlbench_tpu.models.transformer import tp_split_layer_params

        tp = cfg.tp
        if len(jax.devices()) < tp:
            raise ValueError(
                f"ServeConfig.tp={tp} needs {tp} devices; have "
                f"{len(jax.devices())}")
        self._mesh = make_mesh([("model", tp)])

        def put(tree, spec_tree):
            return jax.tree.map(
                lambda a, s: jax.device_put(
                    a, NamedSharding(self._mesh, s)), tree, spec_tree)

        stacked_params = []
        # per-layer frozenset of the stacked (shard-sliced) param keys —
        # _make_tp_fns squeezes exactly these back to shard-local leaves
        self._stacked: List[frozenset] = []
        self._p_specs = []
        for p in params:
            shards, repl = tp_split_layer_params(p, tp)
            if shards[0]:
                merged = dict(repl)
                merged.update({k: jnp.stack([sh[k] for sh in shards])
                               for k in shards[0]})
                sk = frozenset(shards[0])
                # replicated leaves may be nested subtrees (ln dicts) —
                # mirror their structure with P() per leaf
                spec = {k: (P("model") if k in sk
                            else jax.tree.map(lambda _: P(), merged[k]))
                        for k in merged}
            else:
                merged, sk = p, frozenset()
                spec = jax.tree.map(lambda _: P(), p)
            stacked_params.append(merged)
            self._stacked.append(sk)
            self._p_specs.append(spec)
        self.params = put(stacked_params, self._p_specs)
        self.state = jax.tree.map(
            lambda a: jax.device_put(
                a, NamedSharding(self._mesh, P())), state)
        pools = []
        self._pool_specs = []
        self.bytes_per_page = 0  # full-width payload bytes per slot
        for li, (l, p) in enumerate(zip(model.layers, params)):
            if l.serve is None or l.serve.pool_init is None:
                pools.append(None)
                self._pool_specs.append(None)
                continue
            shards, repl = tp_split_layer_params(p, tp)
            views = ([{**repl, **sh} for sh in shards] if shards[0]
                     else [p] * tp)
            per = [l.serve.pool_init(v, cfg.pool_pages, cfg.page,
                                     self.dtype) for v in views]
            pool = {k: jnp.stack([sp[k] for sp in per]) for k in per[0]}
            spec = {k: P("model") for k in pool}
            if "scale_k" in pool:
                # same per-layer counter seed on every shard: each shard
                # stochastically rounds ITS head slice with the same
                # position-keyed stream, so quantized bytes stay a pure
                # function of (values, layer, k/v tag, position) — the
                # handoff-reship bitwise argument holds shard-wise
                pool["kv_seed"] = jnp.int32(li)
                spec["kv_seed"] = P()
            from ddlbench_tpu.ops.paged_decode import pool_page_bytes

            # [tp, n_pages, page, H/tp, dh]: per-page bytes sum over
            # shards to exactly the single-chip full-width page
            self.bytes_per_page += pool_page_bytes(pool, page_axis=1)
            pools.append(put(pool, spec))
            self._pool_specs.append(spec)
        self.pools = pools

    def _make_tp_fns(self) -> None:
        """The four serve programs sharded over the mesh 'model' axis:
        one shard_map per program whose body squeezes each shard's
        stacked param/pool slices, enters the ``tensor_parallel`` trace
        context, and runs the SAME layer walk as the single-chip
        programs. The attention/MLP row-parallel projections psum over
        'model' (models/transformer.py), so activations, logits, and
        tokens come out replicated (out_specs P()) while the pool slices
        stay shard-resident (out_specs P('model')). tp=1 never enters
        this path — ``_make_fns`` is byte-identical to the pre-tp
        programs."""
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import PartitionSpec as P

        from ddlbench_tpu.compat import shard_map as _shard_map
        from ddlbench_tpu.models.transformer import tensor_parallel

        layers = self.model.layers
        page = self.page
        tp = self.cfg.tp
        mesh = self._mesh
        stacked = self._stacked
        sampling = self._sampling
        p_specs = self._p_specs
        pool_specs = self._pool_specs
        s_specs = jax.tree.map(lambda _: P(), self.state)

        def local_params(params):
            # a sliced leaf arrives as this shard's [1, ...] stack block
            return [{k: (v[0] if k in sk else v) for k, v in p.items()}
                    if sk else p for p, sk in zip(params, stacked)]

        def local_pools(pools):
            # array leaves are per-shard [1, ...] blocks; scalars
            # (kv_seed) ride replicated
            return [None if pool is None else
                    {k: (v[0] if getattr(v, "ndim", 0) else v)
                     for k, v in pool.items()} for pool in pools]

        def restack(pools):
            return [None if pool is None else
                    {k: (v[None] if getattr(v, "ndim", 0) else v)
                     for k, v in pool.items()} for pool in pools]

        def walk(params, states, pools, table, h, op_name, *op_args):
            out_pools = []
            for layer, p, s, pool in zip(layers, params, states, pools):
                if layer.serve is not None:
                    op = getattr(layer.serve, op_name)
                    h, pool = op(p, s, pool, table, h, *op_args)
                else:  # pointwise (the LM head) — replicated compute
                    h, _ = layer.apply(p, s, h, False)
                out_pools.append(pool)
            return h, out_pools

        def decode_fn(params, states, pools, table, toks, pos, npl):
            def inner(params, states, pools, table, toks, pos):
                with tensor_parallel("model", tp):
                    logits, out_pools = walk(
                        local_params(params), states, local_pools(pools),
                        table, toks, "decode", pos, npl, page)
                out = (logits[:, 0, :].astype(jnp.float32) if sampling
                       else jnp.argmax(logits[:, 0, :], axis=-1)
                       .astype(jnp.int32))
                return out, restack(out_pools)

            return _shard_map(
                inner, mesh=mesh,
                in_specs=(p_specs, s_specs, pool_specs, P(), P(), P()),
                out_specs=(P(), pool_specs))(
                    params, states, pools, table, toks, pos)

        n_body = len(layers)
        while n_body and layers[n_body - 1].serve is None \
                and layers[n_body - 1].pointwise:
            n_body -= 1

        def prefill_fn(params, states, pools, table, chunk, start, want,
                       npl):
            def inner(params, states, pools, table, chunk, start, want):
                params_l = local_params(params)
                pools_l = local_pools(pools)
                with tensor_parallel("model", tp):
                    h, out_pools = walk(
                        params_l[:n_body], states[:n_body],
                        pools_l[:n_body], table, chunk, "prefill",
                        start, npl, page)
                h = lax.dynamic_slice_in_dim(h, want, 1, axis=1)
                for layer, p, s in zip(layers[n_body:],
                                       params_l[n_body:],
                                       states[n_body:]):
                    h, _ = layer.apply(p, s, h, False)
                out = (h[0, 0, :].astype(jnp.float32) if sampling
                       else jnp.argmax(h[0, 0, :], axis=-1)
                       .astype(jnp.int32))
                return out, restack(out_pools + pools_l[n_body:])

            return _shard_map(
                inner, mesh=mesh,
                in_specs=(p_specs, s_specs, pool_specs, P(), P(), P(),
                          P()),
                out_specs=(P(), pool_specs))(
                    params, states, pools, table, chunk, start, want)

        def cow_fn(pools, src, dst):
            from ddlbench_tpu.ops.paged_decode import serve_page_copy

            def inner(pools, src, dst):
                return restack([serve_page_copy(pool, src, dst)
                                if pool is not None else None
                                for pool in local_pools(pools)])

            return _shard_map(
                inner, mesh=mesh, in_specs=(pool_specs, P(), P()),
                out_specs=pool_specs)(pools, src, dst)

        def verify_fn(params, states, pools, table, toks, pos0, npl):
            def inner(params, states, pools, table, toks, pos0):
                with tensor_parallel("model", tp):
                    logits, out_pools = walk(
                        local_params(params), states, local_pools(pools),
                        table, toks, "verify", pos0, npl, page)
                return (jnp.argmax(logits, axis=-1).astype(jnp.int32),
                        restack(out_pools))

            return _shard_map(
                inner, mesh=mesh,
                in_specs=(p_specs, s_specs, pool_specs, P(), P(), P()),
                out_specs=(P(), pool_specs))(
                    params, states, pools, table, toks, pos0)

        self._decode_jit = jax.jit(decode_fn, static_argnums=(6,),
                                   donate_argnums=(2,))
        self._prefill_jit = jax.jit(prefill_fn, static_argnums=(7,),
                                    donate_argnums=(2,))
        self._cow_jit = jax.jit(cow_fn, donate_argnums=(0,))
        self._verify_jit = jax.jit(verify_fn, static_argnums=(6,),
                                   donate_argnums=(2,))

    def _emit_token(self, raw, rid: int, token_index: int) -> int:
        """One emitted token from a program output: the argmax'd int32 in
        greedy mode, a host-sampled draw from the logits otherwise."""
        if self._sampling:
            return sample_token(np.asarray(raw), self.cfg.temperature,
                                self.cfg.top_k, self.cfg.sample_seed,
                                rid, token_index)
        return int(raw)

    # -- SDC defense: stamp / verify / quarantine (serve/integrity.py) -----

    def _slot_crc(self, li: int, slot: int) -> int:
        """Checksum of (layer, slot)'s current device bytes: payload +
        sidecar rows fetched to host, chained in sorted key order (the
        ops/paged_decode.pool_checksum_keys domain)."""
        pool = self.pools[li]
        rows = {k: np.asarray(v[slot] if self._page_axis == 0
                              else v[:, slot])
                for k, v in pool.items() if getattr(v, "ndim", 0)}
        return page_checksum(rows)

    def _stamp_slot(self, slot: int) -> None:
        """Stamp every serving layer's ledger entry for ``slot`` from the
        bytes just written — the pool-write-boundary hook."""
        for li, pool in enumerate(self.pools):
            if pool is None:
                continue
            self.integrity.stamp(li, slot, self._slot_crc(li, slot))

    def _verify_slot(self, slot: int, where: str,
                     rep: Optional[StepReport] = None) -> bool:
        """Trust-boundary check of ``slot`` against the ledger. True =
        intact (or never stamped — unwritten pages carry no expectation);
        on any layer mismatch the slot is quarantined, every holder is
        recovered, and False returns — the caller must not serve it."""
        for li, pool in enumerate(self.pools):
            if pool is None:
                continue
            if self.integrity.verify(li, slot,
                                     self._slot_crc(li, slot)) is False:
                self._quarantine_slot(slot, where, rep)
                return False
        return True

    def _quarantine_slot(self, slot: int, where: str,
                         rep: Optional[StepReport] = None) -> None:
        """Detection -> quarantine -> recovery: pull the slot out of
        circulation for good, purge its prefix-index entry, and EVICT
        every request referencing it (a corrupted SHARED page walks its
        refcounts) onto the existing recompute path — re-prefill
        regenerates pages byte-identically and a recovered request's
        FULL stream regenerates from scratch, so final token streams
        stay bitwise vs an unfaulted control."""
        if rep is None:
            rep = StepReport()  # detections outside step() (export path)
        holders = self.allocator.holders(slot)
        self.allocator.quarantine(slot)
        if self.prefix is not None:
            self.prefix.drop_slot(slot)
        displaced: List[int] = []
        for rid in holders:
            victim = next((x for x in self._active()
                           if x.req.rid == rid), None)
            if victim is not None and self.rows[victim.row] is victim:
                self._evict(victim, rep)
                displaced.append(rid)
        self.integrity.drop_slot(slot)
        self.stats["sdc_detected"] += 1
        self.stats["sdc_quarantined"] += 1
        self.stats["sdc_recovered"] += len(displaced)
        self.sdc_events.append({"t": self._now, "slot": int(slot),
                                "where": where, "displaced": displaced})
        self._sdc_trace("detect", slot=int(slot), where=where)
        self._sdc_trace("quarantine", slot=int(slot),
                        displaced=len(displaced))

    def _sdc_trace(self, kind: str, **args: Any) -> None:
        """``sdc:*`` instants on the replica's sdc track — the
        telemetry/export.py ``sdc_events`` reducer collects them."""
        tr = self._tr()
        if tr is not None:
            tr.emit("i", f"sdc:{kind}", _vns(self._now),
                    track=f"{self._trk}/sdc", args=args)

    def _capture_recompute_expect(self, victim: "_Active") -> None:
        """At eviction, snapshot the ledger CRCs of the victim's FULLY
        prefilled prompt pages: the recompute replay's chunk writes must
        regenerate exactly these bytes (checked in
        :meth:`_stamp_prefill_pages` — the byte-identical re-prefill
        invariant, verified instead of assumed)."""
        exp: Dict[Tuple[int, int], int] = {}
        full = min(victim.prefill_done, victim.req.prompt_len) // self.page
        for idx in range(full):
            slot = int(self.table[victim.row, idx])
            if not slot:
                continue
            for li, pool in enumerate(self.pools):
                if pool is None:
                    continue
                crc = self.integrity.expected(li, slot)
                if crc is not None:
                    exp[(li, idx)] = crc
        if exp:
            self._recompute_expect[victim.req.rid] = exp

    def _stamp_prefill_pages(self, a: "_Active", start: int,
                             end_real: int) -> None:
        """Stamp the pages a prefill chunk wrote ([start, end_real) plus
        the padded tail inside the last allocated page) and check every
        FULLY rewritten page against any eviction-recompute
        expectation."""
        exp = self._recompute_expect.get(a.req.rid)
        full_end = end_real // self.page
        for idx in range(start // self.page, self._pages_for(end_real)):
            slot = int(self.table[a.row, idx])
            if not slot:
                continue
            for li, pool in enumerate(self.pools):
                if pool is None:
                    continue
                crc = self._slot_crc(li, slot)
                self.integrity.stamp(li, slot, crc)
                if exp is None or idx >= full_end:
                    continue
                want = exp.pop((li, idx), None)
                if want is None:
                    continue
                self.stats["sdc_recompute_checks"] += 1
                if crc != want:
                    # the replay did NOT regenerate the original bytes —
                    # either the original write was already corrupt or
                    # re-derivation determinism broke. Recorded as a
                    # detection, not quarantined: the fresh bytes are the
                    # re-derived truth.
                    self.stats["sdc_detected"] += 1
                    self.sdc_events.append({
                        "t": self._now, "slot": slot,
                        "where": "recompute", "displaced": []})
                    self._sdc_trace("recompute_mismatch", slot=slot,
                                    layer=li, page=idx)

    def _scrub(self, rep: StepReport) -> None:
        """Budgeted background scrubber: verify up to ``cfg.scrub``
        stamped slots per step, round-robin over the sorted stamped-slot
        list — latent corruption on cold prefix pages is caught before a
        full-hit (or a ship) can serve it."""
        for _ in range(self.cfg.scrub):
            slots = self.integrity.stamped_slots()
            if not slots:
                return
            slot = slots[self._scrub_cursor % len(slots)]
            self._scrub_cursor += 1
            self.stats["sdc_scrubbed"] += 1
            self._verify_slot(slot, "scrub", rep)

    # -- allocation under pool pressure ------------------------------------

    def _alloc(self, rid: int, n: int) -> Optional[List[int]]:
        """``allocator.alloc`` preceded, on exhaustion, by reclaiming
        prefix-cache pages no live request references (newest-registered
        first) — cached-but-unbound pages are free capacity, and spending
        them beats evicting a live request."""
        slots = self.allocator.alloc(rid, n)
        if slots is None and self.prefix is not None:
            self.prefix.reclaim(n - self.allocator.free_pages)
            slots = self.allocator.alloc(rid, n)
        return slots

    # -- request lifecycle -------------------------------------------------

    def _pages_for(self, n_positions: int) -> int:
        """Pages that hold stream positions [0, n_positions)."""
        return (n_positions - 1) // self.page + 1 if n_positions else 0

    def _written_positions(self, req: ServeRequest) -> int:
        # prompt S + decode writes (max_new - 1): the final emitted token
        # is never fed back, so its K/V is never written
        return req.prompt_len + req.max_new - 1

    def min_service_passes(self, req: ServeRequest) -> int:
        """Lower bound on the model passes ``req`` needs end to end on an
        IDLE engine: one prefill call per chunk of the UNCACHED prompt
        tail (the first token rides the last chunk) plus one decode pass
        per remaining token. With the prefix cache on, the currently
        cached prefix is consulted — a full page-aligned hit admits with
        zero prefill calls (decode-only: ``max_new`` passes), a partial
        hit prefills only the tail — so a cached request is never shed
        for prompt work it would not do (cache state can shift before
        admission; the bound is exact as of the submission instant)."""
        C = self.cfg.resolved_prefill_chunk()
        S = req.prompt_len
        if self.prefix is not None:
            hit = self.prefix.match(req.prompt)
            if hit and len(hit) * self.page >= S:
                return req.max_new  # full hit: straight to decode
            cached = min(len(hit), (S - 1) // self.page) * self.page
            S -= cached
        return -(-S // C) + req.max_new - 1

    def projected_finish(self, req: ServeRequest, now: float) -> float:
        """Deterministic completion projection for admission control:
        ``now + max(congestion_delay, own_min_passes)``.

        ``own_min_passes`` (:meth:`min_service_passes`) is an EXACT lower
        bound, so the one hard guarantee is: a request that cannot meet
        its deadline even alone on an idle engine is always shed, and a
        request submitted to an idle engine is never shed unless truly
        hopeless. ``congestion_delay`` — tokens that compete for budget
        ahead of this request (remaining in-flight work, plus queued
        requests that admit ahead of it: an interactive submission
        outranks every queued batch request via
        :meth:`_next_admission_index`, and a queued request already past
        its own deadline will be cancelled before consuming budget, so
        neither counts) over the per-step token budget — is a HEURISTIC:
        continuous batching drains in-flight work concurrently with the
        new request, so under contention the projection can over-shed a
        request that would just have made it. That is a deterministic,
        REPORTED policy choice (shed_rate; the driver's bounded retry is
        the recourse), not a correctness claim — taking the max rather
        than the sum of the two terms keeps the estimate as tight as a
        one-pass host scan can be."""
        ahead = 0
        for a in self.rows:
            if a is not None:
                ahead += (a.req.prompt_len - a.prefill_done) \
                    + (a.req.max_new - len(a.out))
        for r in self.queue:
            if r.deadline is not None and now >= r.deadline:
                continue  # expires before it could consume budget
            if req.tier != "batch" and r.tier == "batch":
                continue  # this submission admits ahead of queued batch
            ahead += r.prompt_len + r.max_new
        congestion = ahead // self.cfg.resolved_token_budget()
        return now + max(congestion, self.min_service_passes(req))

    def submit(self, req: ServeRequest, now: Optional[float] = None) -> bool:
        """Enqueue ``req``; returns True when accepted. A request with a
        DEADLINE is subject to admission control: when its projected
        completion (:meth:`projected_finish`) already exceeds the
        deadline, the engine SHEDS it — the named ``shed`` rejection,
        returned as False so the driver's bounded retry-with-backoff
        policy (tools/servebench.py) owns what happens next. Deadline-free
        requests are always accepted (the pre-deadline contract)."""
        if req.prompt_len < 1 or req.max_new < 1:
            raise ValueError("request needs a non-empty prompt and "
                             "max_new >= 1")
        if req.tier not in TIERS:
            # a typo'd tier would silently schedule as interactive while
            # vanishing from both per-tier summary buckets
            raise ValueError(
                f"request {req.rid}: tier must be one of {TIERS}, got "
                f"{req.tier!r}")
        if req.prompt_len + req.max_new > self.cfg.max_len:
            raise ValueError(
                f"request {req.rid}: prompt {req.prompt_len} + max_new "
                f"{req.max_new} exceeds max_len {self.cfg.max_len}")
        if self._pages_for(self._written_positions(req)) > \
                self.allocator.capacity:
            raise ValueError(
                f"request {req.rid} can never fit the pool "
                f"({self.allocator.capacity} usable pages)")
        t0 = req.arrival if req.arrival is not None else 0.0
        if req.deadline is not None:
            t_sub = now if now is not None else t0
            if self.projected_finish(req, t_sub) > req.deadline:
                self.stats["shed"] += 1
                self.shed.append({"rid": req.rid, "t": t_sub,
                                  "deadline": req.deadline,
                                  "tier": req.tier})
                tr = self._tr()
                if tr is not None:
                    tr.emit("i", "shed", _vns(t_sub),
                            track=self._req_track(req.rid),
                            args={"rid": req.rid, "deadline": req.deadline,
                                  "tier": req.tier})
                return False
            self._has_deadlines = True
        self.queue.append(req)
        self._queued_at[req.rid] = t0
        tr = self._tr()
        if tr is not None:
            tr.emit("i", "submit", _vns(t0), track=self._req_track(req.rid),
                    args={"rid": req.rid, "prompt_len": req.prompt_len,
                          "max_new": req.max_new})
        return True

    def has_work(self) -> bool:
        return bool(self.queue) or any(a is not None for a in self.rows)

    def load(self) -> int:
        """Remaining token work (queued + in flight) — the least-loaded
        dispatch key."""
        tot = sum(r.prompt_len + r.max_new for r in self.queue)
        for a in self.rows:
            if a is not None:
                tot += (a.req.prompt_len - a.prefill_done) \
                    + (a.req.max_new - len(a.out))
        return tot

    def _free_row(self) -> Optional[int]:
        for i, a in enumerate(self.rows):
            if a is None:
                return i
        return None

    def _active(self) -> List[_Active]:
        return [a for a in self.rows if a is not None]

    def _evict(self, victim: _Active, rep: StepReport) -> None:
        """Free the victim's pages and re-queue it (front) for
        recomputation — greedy decode regenerates the same tokens."""
        if self.integrity is not None:
            # snapshot BEFORE the frees drop the ledger entries: the
            # recompute replay is checked against these CRCs
            self._capture_recompute_expect(victim)
        self.allocator.free_request(victim.req.rid)
        self.table[victim.row, :] = 0
        self.rows[victim.row] = None
        self.queue.appendleft(victim.req)
        rep.evicted += 1
        self.stats["evicted"] += 1
        rid = victim.req.rid
        # batch_active = co-resident batch-tier actives the victim hunt
        # passed over: > 0 with an interactive victim would break the
        # tier preemption order (the assertable invariant; 0 by
        # construction of _evict_newest, regression-pinned)
        self.evicted_log.append({
            "rid": rid, "t": self._now, "tier": victim.req.tier,
            "batch_active": sum(1 for a in self._active()
                                if a is not victim
                                and a.req.tier == "batch")})
        self._queued_at[rid] = self._now  # requeued: the wait restarts now
        self._evicted_rids.add(rid)
        tr = self._tr()
        if tr is not None:
            tr.emit("i", "evict", _vns(self._now), track=self._req_track(rid),
                    args={"rid": rid, "prefill_done": victim.prefill_done,
                          "out_tokens": len(victim.out)})

    def _evict_newest(self, rep: StepReport) -> Optional[_Active]:
        """Preemption order (ROADMAP 2c): BATCH-tier actives are evicted
        first — newest-first within the tier — and only when no batch
        request is in flight does an interactive one go (newest-first,
        the pre-tier rule, which all-interactive traffic reduces to
        bitwise). Batch is the preemptible background lane riding the
        existing eviction+recompute machinery."""
        active = self._active()
        if not active:
            return None
        batch = [a for a in active if a.req.tier == "batch"]
        victim = max(batch or active, key=lambda a: a.admit_seq)
        self._evict(victim, rep)
        return victim

    def _complete(self, a: _Active, t: float, rep: StepReport) -> None:
        self.allocator.free_request(a.req.rid)
        self.table[a.row, :] = 0
        self.rows[a.row] = None
        # static policy: a completion ends the fill phase — otherwise a
        # short-output workload whose completions keep freeing rows while
        # the queue is nonempty would leave the phase open forever and the
        # "static" baseline would degenerate into budget-paced continuous
        # admission (no drain barrier, biasing the A/B)
        self._filling = False
        self.finished.append({
            "rid": a.req.rid,
            "arrival": a.req.arrival,
            "prompt_len": a.req.prompt_len,
            "tokens": list(a.out),
            "n_tokens": len(a.out),
            "first_token_t": a.first_token_t,
            "token_times": list(a.token_times),
            "completed_t": t,
            # prompt tokens served from the prefix cache (all admissions
            # of this request — telemetry/stats.serve_summary aggregates)
            "cached_tokens": self._cached_tokens.pop(a.req.rid, 0),
            # SLO tier — serve_summary's per-tier split keys on it
            "tier": a.req.tier,
        })
        rep.completed.append(a.req.rid)
        self.stats["completed"] += 1
        self._recompute_expect.pop(a.req.rid, None)
        tr = self._tr()
        if tr is not None:
            f = self.finished[-1]
            tr.emit("i", "finish", _vns(t), track=self._req_track(a.req.rid),
                    args={"rid": a.req.rid, "n_tokens": f["n_tokens"],
                          "arrival": f["arrival"],
                          "first_token_t": f["first_token_t"],
                          "cached_tokens": f["cached_tokens"]})

    # -- deadlines: expiry cancellation (the `timeout` terminal state) -----

    def _record_timeout(self, rid: int, now: float, deadline: float,
                        state: str, out_tokens: int, tier: str,
                        rep: StepReport) -> None:
        self.timed_out.append({"rid": rid, "t": now, "deadline": deadline,
                               "state": state, "out_tokens": out_tokens,
                               "tier": tier})
        self.stats["timeouts"] += 1
        rep.timed_out.append(rid)
        self._queued_at.pop(rid, None)
        self._evicted_rids.discard(rid)
        self._cached_tokens.pop(rid, None)
        self._recompute_expect.pop(rid, None)
        tr = self._tr()
        if tr is not None:
            tr.emit("i", "timeout", _vns(now), track=self._req_track(rid),
                    args={"rid": rid, "deadline": deadline, "state": state,
                          "out_tokens": out_tokens})

    def _cancel_expired(self, now: float, rep: StepReport) -> None:
        """Deadline enforcement, observed at step boundaries: a request
        whose deadline has passed can no longer complete in time (every
        emission this step stamps at ``now + cost > deadline``), so it
        cancels into the named ``timeout`` terminal state — queued
        entries just leave the queue, in-flight ones free every page
        (prefix-registered pages survive on the index's own refs, like
        eviction). A request that completed LATE in an earlier step
        stays completed — the SLO machinery judges it, the deadline only
        governs work still pending when the expiry is observed."""
        expired = [r for r in self.queue
                   if r.deadline is not None and now >= r.deadline]
        if expired:
            dead = {id(r) for r in expired}  # identity, never dataclass ==
            kept = [r for r in self.queue if id(r) not in dead]
            self.queue.clear()
            self.queue.extend(kept)
            for r in expired:
                self._record_timeout(r.rid, now, r.deadline, "queued", 0,
                                     r.tier, rep)
        for a in [a for a in self._active()
                  if a.req.deadline is not None and now >= a.req.deadline]:
            self.allocator.free_request(a.req.rid)
            self.table[a.row, :] = 0
            self.rows[a.row] = None
            # static policy: a freed row ends the fill phase like a
            # completion does (same drain-barrier reasoning)
            self._filling = False
            self._record_timeout(a.req.rid, now, a.req.deadline, a.state,
                                 len(a.out), a.req.tier, rep)

    # -- the step: ensure pages -> pack -> prefill/decode -> retire --------

    def _ensure_decode_pages(self, rep: StepReport) -> List[_Active]:
        """Give every decode row the page its next write needs, evicting
        newest-first when the pool is exhausted. Returns the surviving
        decode set."""
        out = []
        for a in [x for x in self.rows
                  if x is not None and x.state == "decode"]:
            if self.rows[a.row] is not a:  # evicted by an earlier victim hunt
                continue
            pgi = a.decode_pos // self.page
            alive = True
            while pgi >= a.n_pages:
                slots = self._alloc(a.req.rid, 1)
                if slots is not None:
                    self.table[a.row, a.n_pages] = slots[0]
                    a.n_pages += 1
                    continue
                victim = self._evict_newest(rep)
                assert victim is not None
                if victim is a:
                    alive = False
                    break
            if alive:
                out.append(a)
        # a victim can sit at a LOWER row index than its evictor (rows are
        # reused, so admission order and row order diverge): a row already
        # appended here may be evicted by a later iteration's victim hunt.
        # Running it anyway would decode against a zeroed table row and —
        # at its final token — double-free its already-freed pages.
        return [a for a in out if self.rows[a.row] is a]

    def _ensure_prefill_pages(self, a: _Active, end_real: int,
                              rep: StepReport, can_evict: bool) -> bool:
        need = self._pages_for(end_real) - a.n_pages
        while True:
            if need <= 0:
                return True
            slots = self._alloc(a.req.rid, need)
            if slots is not None:
                self.table[a.row, a.n_pages:a.n_pages + need] = slots
                a.n_pages += need
                return True
            if not can_evict:
                rep.backpressure += 1
                self.stats["backpressure"] += 1
                return False
            victim = self._evict_newest(rep)
            if victim is a:
                return False  # evicted ourselves; the queue will retry

    def _admit_full_hit(self, req: ServeRequest, hit: List[int],
                        rep: StepReport, qi: int = 0) -> Optional[_Active]:
        """Admit a request whose WHOLE (page-aligned) prompt is cached:
        bind every cached page, copy-on-write the last one into a private
        slot — the decode program is about to re-derive position S-1's K/V
        into it, and writing into a shared page would couple the sibling
        streams through last-ulp drift between the chunked and
        single-token computations — and enter decode directly with the
        last prompt token pending. Zero prefill calls; the first output
        token costs one decode pass."""
        S = req.prompt_len
        nblk = S // self.page
        # trust boundary: a full hit serves these pages WITHOUT any
        # recompute — verify before binding (a stale corrupted cold page
        # is exactly what the scrubber and this check exist for). On a
        # mismatch the slot quarantines (its index entry purged, holders
        # recovered) and the admission bails: the next step's match
        # misses the purged block and takes the prefill path.
        if self.integrity is not None:
            for s in hit[:nblk]:
                if not self._verify_slot(int(s), "prefix_hit", rep):
                    return None
        # pin every matched page (including the COW source) before
        # allocating: _alloc's cache reclaim frees index-only pages, and
        # the hit slots are exactly that once their owner completed — see
        # the partial-hit pin in step() (regression-pinned)
        for s in hit[:nblk]:
            self.allocator.incref(s)
        priv = self._alloc(req.rid, 1)
        if priv is None:
            for s in hit[:nblk]:
                self.allocator.decref(s)
            rep.backpressure += 1
            self.stats["backpressure"] += 1
            return None
        self.allocator.bind(req.rid, hit[:nblk - 1])
        del self.queue[qi]
        row = self._free_row()
        a = _Active(req=req, row=row, admit_seq=self._admit_seq)
        self._admit_seq += 1
        self.table[row, :] = 0
        self.table[row, :nblk - 1] = hit[:nblk - 1]
        self.table[row, nblk - 1] = priv[0]
        a.n_pages = nblk
        a.prefill_done = S
        a.registered_blocks = nblk  # every block is already in the index
        a.state = "decode"
        a.pending_tok = int(req.prompt[S - 1])
        self.rows[row] = a
        # device-side COW: the source page is pinned above, so the alloc's
        # reclaim cannot have freed it between match and this copy
        self.pools = self._cow_jit(self.pools, np.int32(hit[nblk - 1]),
                                   np.int32(priv[0]))
        if self.integrity is not None:
            # serve_page_copy moves device bytes verbatim: the COW
            # destination inherits the (just-verified) source's ledger
            # CRCs without another host fetch
            src = int(hit[nblk - 1])
            for li, pool in enumerate(self.pools):
                if pool is None:
                    continue
                crc = self.integrity.expected(li, src)
                if crc is not None:
                    self.integrity.stamp(li, priv[0], crc)
        # release the admission pins (the bind above keeps its own refs on
        # the shared blocks; the COW source drops back to its cache ref)
        for s in hit[:nblk]:
            self.allocator.decref(s)
        rep.admitted += 1
        rep.prefix_hits += 1
        self.stats["admitted"] += 1
        self.stats["prefix_hits"] += 1
        self.stats["cow_copies"] += 1
        # S - 1 prompt positions never recomputed (the last one re-runs
        # through the decode program to produce the first-token logits)
        self.stats["prefix_tokens_saved"] += S - 1
        self._cached_tokens[req.rid] = \
            self._cached_tokens.get(req.rid, 0) + S - 1
        self._trace_admit(a, S - 1)
        return a

    def _next_admission_index(self) -> int:
        """Queue position of the next request to admit: INTERACTIVE
        admits ahead of batch (FIFO within a tier — ROADMAP 2c's
        priority lane); with no interactive request waiting, the head
        batch request goes. All-interactive traffic always returns 0 —
        the pre-tier FIFO order, bitwise."""
        for i, r in enumerate(self.queue):
            if r.tier != "batch":
                return i
        return 0

    def _admission_open(self) -> bool:
        if self.cfg.policy == "continuous":
            return True
        # static: admit only during a whole-batch fill phase
        if not self._filling and not self._active():
            self._filling = True
        return self._filling

    def step(self, now: float = 0.0) -> StepReport:
        """One engine step. Returns what ran; emission/completion times are
        stamped at ``now + cost`` (the step's end in virtual time)."""
        rep = StepReport()
        self._now = now  # mid-schedule instants (evict, pool, admit)
        # deadline expiry first: freed pages/rows are capacity this very
        # step (the scan arms only after a deadlined request ever arrived)
        if self._has_deadlines:
            self._cancel_expired(now, rep)
        # budgeted background scrub, BEFORE any program reads pool pages
        # this step: a latent flip on a settled page must be caught ahead
        # of the decode/prefill pass that would attend over it (detection
        # evicts the holders onto the recompute path before the poisoned
        # read, keeping recovered streams bitwise). Running it at the
        # step's end instead loses the race when a victim completes — and
        # frees its pages — in the same step the flip landed.
        # (cfg.scrub pages/step; a host-side ledger walk — the virtual
        # cost model is unchanged, the real overhead is the device->host
        # fetches, measured on-chip in PERF.md round 23)
        if self.integrity is not None and self.cfg.scrub:
            self._scrub(rep)
        C = self.cfg.resolved_prefill_chunk()

        # 1) decode set: every decode row gets its next page (evictions may
        #    shrink the set — or free rows the packer then refills)
        decode_set = self._ensure_decode_pages(rep)
        # 1b) speculative drafts, planned BEFORE the budget so the packer
        #     charges a verify pass at its true token width (1 + drafts
        #     per row); nothing later in a step with live decode rows can
        #     evict, so the plan cannot go stale
        draft_plan = (self._plan_drafts(decode_set)
                      if self._spec is not None and decode_set else None)
        spec_tokens = (sum(len(d) for _, d, _ in draft_plan)
                       if draft_plan else 0)
        budget = (self.cfg.resolved_token_budget() - len(decode_set)
                  - spec_tokens)

        # 2) continue in-flight prefills, admission order
        prefill_calls: List[_Active] = []
        for a in sorted((x for x in self.rows
                         if x is not None and x.state == "prefill"),
                        key=lambda x: x.admit_seq):
            if self.rows[a.row] is not a:
                continue  # evicted by an earlier iteration's victim hunt
            if budget < C:
                break
            end_real = min(a.prefill_done + C, a.req.prompt_len)
            # waiting only helps if running requests will free pages;
            # with no decode rows in flight, evict to guarantee progress
            if self._ensure_prefill_pages(a, end_real, rep,
                                          can_evict=not decode_set):
                prefill_calls.append(a)
                budget -= C
            # (prefill eviction only runs when decode_set is empty, so it
            # can never remove a decode row scheduled this step)

        # 3) admit new requests while the packer has budget. With the
        #    prefix cache on, an admission binds the pages of its longest
        #    cached prefix and prefills only the tail; a FULL page-aligned
        #    hit skips prefill entirely (COW the last cached page, enter
        #    decode directly — budget 1, the bookkeeping slot).
        while (self.queue and self._free_row() is not None
               and self._admission_open()):
            qi = self._next_admission_index()
            req = self.queue[qi]
            hit = self.prefix.match(req.prompt) if self.prefix else []
            S = req.prompt_len
            full_hit = bool(hit) and len(hit) * self.page >= S
            if budget < (1 if full_hit else C):
                break
            if full_hit:
                a = self._admit_full_hit(req, hit, rep, qi)
                if a is None:
                    break  # backpressure — even one COW page unavailable
                budget -= 1
                continue
            # partial hit: never bind the page holding position S-1 — the
            # first-token logits need at least the last prompt position to
            # run through a (page-aligned) prefill chunk anyway
            nbind = min(len(hit), (S - 1) // self.page)
            # trust boundary: verify the hit pages before binding (the
            # full-hit sibling check). A mismatch quarantines the slot —
            # possibly evicting holders onto the queue front, which
            # shifts qi — so the admission just stops for this step; the
            # next match misses the purged block.
            if nbind and self.integrity is not None and not all(
                    self._verify_slot(int(s), "prefix_hit", rep)
                    for s in hit[:nbind]):
                break
            cached = nbind * self.page
            end0 = min(cached + C, S)  # first tail chunk's frontier
            if self.cfg.policy == "static":
                # static baseline reserves the full worst case up front
                # (prefix_cache is continuous-only, so nbind == 0 here)
                need = self._pages_for(self._written_positions(req))
            else:
                need = self._pages_for(end0) - nbind
            # pin the matched pages BEFORE allocating the tail: _alloc's
            # cache reclaim frees exactly the index-only (refcount-1)
            # pages, which the not-yet-bound hit slots ARE once their
            # original owner completed — unpinned, reclaim could free a
            # hit page and alloc recycle it as this request's own tail
            # slot, aliasing an "immutable cached block" with a writable
            # page (silent KV corruption; regression-pinned)
            for s in hit[:nbind]:
                self.allocator.incref(s)
            slots = self._alloc(req.rid, need) if need else []
            for s in hit[:nbind]:
                self.allocator.decref(s)
            if slots is None:
                rep.backpressure += 1
                self.stats["backpressure"] += 1
                self._filling = False  # static: close the fill phase
                break
            if nbind:
                self.allocator.bind(req.rid, hit[:nbind])
            del self.queue[qi]
            row = self._free_row()
            a = _Active(req=req, row=row, admit_seq=self._admit_seq)
            self._admit_seq += 1
            self.table[row, :] = 0
            self.table[row, :nbind] = hit[:nbind]
            self.table[row, nbind:nbind + need] = slots
            a.n_pages = nbind + need
            a.prefill_done = cached
            a.registered_blocks = nbind
            self.rows[row] = a
            if nbind:
                rep.prefix_hits += 1
                self.stats["prefix_hits"] += 1
                self.stats["prefix_tokens_saved"] += cached
                self._cached_tokens[req.rid] = \
                    self._cached_tokens.get(req.rid, 0) + cached
            prefill_calls.append(a)
            budget -= C
            rep.admitted += 1
            self.stats["admitted"] += 1
            self._trace_admit(a, cached if nbind else 0)
        if self.cfg.policy == "static" and (
                self._free_row() is None or not self.queue):
            self._filling = False

        # 4) price the step, then run it. A verify pass is ONE model pass
        #    (the same price as the decode step it replaces — the honest
        #    virtual-cost accounting the goodput A/B rides on)
        if self.integrity is not None:
            # an admission-time integrity check may have quarantined a
            # shared page and evicted a holder already scheduled this
            # step — never run a dead row
            prefill_calls = [a for a in prefill_calls
                             if self.rows[a.row] is a]
            decode_set = [a for a in decode_set if self.rows[a.row] is a]
            if draft_plan is not None:
                draft_plan = [p for p in draft_plan
                              if self.rows[p[0].row] is p[0]]
        cost = len(prefill_calls) + (1 if decode_set else 0)
        t_end = now + cost
        for a in prefill_calls:
            self._run_prefill_chunk(a, C, t_end, rep)
        if decode_set:
            if draft_plan is not None and any(d for _, d, _ in draft_plan):
                self._run_verify(draft_plan, t_end, rep)
            else:
                self._run_decode(decode_set, t_end, rep)

        # 5) occupancy / fragmentation accounting
        self.stats["steps"] += 1
        self.stats["model_calls"] += cost
        self.stats["peak_occupancy"] = max(self.stats["peak_occupancy"],
                                           self.allocator.occupancy())
        self.stats["shared_pages"] = max(self.stats["shared_pages"],
                                         self.allocator.shared_pages)
        live = cap = 0
        for a in self._active():
            live += a.prefill_done + max(0, len(a.out) - 1)
            cap += a.n_pages * self.page
        if cap:
            self.stats["frag_sum"] += 1.0 - live / cap
            self.stats["frag_samples"] += 1
        rep.cost = cost

        # 6) flight recorder + counter tracks (host-only observability —
        #    nothing below feeds back into scheduling)
        self._last_t = t_end
        occ = self.allocator.occupancy()
        if self._flight is not None:
            self._flight.append({
                "step": int(self.stats["steps"]), "t": t_end, "cost": cost,
                "occupancy": occ, "free_pages": self.allocator.free_pages,
                "queue_depth": len(self.queue),
                "active": sum(1 for x in self.rows if x is not None),
                "decode_rows": len(decode_set),
                "prefill_calls": len(prefill_calls),
                "admitted": rep.admitted, "evicted": rep.evicted,
                "backpressure": rep.backpressure,
            })
        tr = self._tr()
        if tr is not None:
            t_ns = _vns(t_end)
            trk = f"{self._trk}/engine"
            B = self.cfg.resolved_token_budget()
            used = B - budget  # decode rows + admitted/continued chunks
            for cname, v in (
                    ("pool_occupancy", occ),
                    ("free_pages", float(self.allocator.free_pages)),
                    ("decode_batch_util",
                     len(decode_set) / self.cfg.max_batch),
                    ("token_budget_fill", min(1.0, max(0.0, used / B))),
                    ("prefix_hits", float(self.stats["prefix_hits"])),
                    ("shared_pages", float(self.allocator.shared_pages)),
                    ("queue_depth", float(len(self.queue))),
            ):
                tr.emit("C", f"{cname}[{self._trk}]", t_ns, track=trk,
                        args={"value": v})
        return rep

    def _plan_drafts(self, decode_set: List[_Active]):
        """Per decode row: self-draft up to K tokens from the row's own
        stream (the n-gram drafter reads prompt + emitted tokens only —
        decode rows are fully prefilled, so it never reads past
        ``prefill_done``) and opportunistically pre-allocate the pages the
        span write needs. Speculation NEVER evicts — and never reclaims
        prefix-cache pages either: draft headroom comes straight off the
        free list (``allocator.alloc``, not ``_alloc``), since spending a
        hot shared-prefix page on K/V that is likely rolled back the same
        step would erode the cache the run is measuring. A shortfall
        truncates the drafts to what the row's pages can hold — a bad
        pool day degrades acceptance, not residency. Plan entries are
        ``(active, drafts, pre_pages)``; ``pre_pages`` (the row's page
        count BEFORE planning) bounds the rollback so it only ever
        returns pages this planner added — the static policy's up-front
        worst-case reservation must survive a verify pass untouched."""
        plan = []
        for a in decode_set:
            pre_pages = a.n_pages
            # never draft past the request's own max_new: the verify pass
            # emits at most 1 + len(drafts) tokens, and the final token's
            # K/V is never written — the page math stays inside the
            # non-speculative worst case
            k_max = a.req.max_new - len(a.out) - 1
            drafts: List[int] = []
            if k_max > 0:
                ctx = list(a.req.prompt.tolist()) + a.out
                drafts = self._drafter.propose(ctx, k_max)
            if drafts:
                need = self._pages_for(
                    a.decode_pos + len(drafts) + 1) - a.n_pages
                while need > 0:
                    slots = self.allocator.alloc(a.req.rid, need)
                    if slots is not None:
                        self.table[a.row,
                                   a.n_pages:a.n_pages + need] = slots
                        a.n_pages += need
                        break
                    need -= 1
                # positions [decode_pos, n_pages * page) are writable
                fit = a.n_pages * self.page - 1 - a.decode_pos
                drafts = drafts[:max(0, fit)]
            if drafts:
                self.stats["spec_drafted"] += len(drafts)
                tr = self._tr()
                if tr is not None:
                    tr.emit("i", "draft", _vns(self._now),
                            track=self._req_track(a.req.rid),
                            args={"rid": a.req.rid,
                                  "proposed": len(drafts),
                                  "tok": len(a.out)})
            plan.append((a, drafts, pre_pages))
        return plan

    def _run_verify(self, plan, t_end: float, rep: StepReport) -> None:
        """One speculative verify pass over the decode set: score every
        row's pending token + drafts at span positions
        [decode_pos, decode_pos + W) in ONE [max_batch, W] program call,
        accept the longest draft prefix matching greedy argmax (so the
        emitted stream is BITWISE the non-speculative stream), write
        accepted K/V in place (the span write already put it there), and
        roll back pages past the accepted frontier like eviction does."""
        import jax.numpy as jnp

        assert all(self.rows[a.row] is a for a, _, _ in plan), \
            "scheduled a dead (evicted) row"
        W = self._spec[1] + 1
        B = self.cfg.max_batch
        toks = np.zeros((B, W), np.int32)
        pos0 = np.zeros((B,), np.int32)
        mask = np.zeros((B,), bool)
        for a, drafts, _ in plan:
            toks[a.row, 0] = a.pending_tok
            if drafts:
                toks[a.row, 1:1 + len(drafts)] = drafts
            pos0[a.row] = a.decode_pos
            mask[a.row] = True
        # inactive rows route to scratch exactly like the decode pass;
        # a row's own padded draft tail lands in its page headroom (never
        # attended: key pos > every live query pos) or on scratch
        ver_table = np.where(mask[:, None], self.table, 0)
        npl = max((int(a.decode_pos) + len(d)) // self.page + 1
                  for a, d, _ in plan)
        nxt, self.pools = self._verify_jit(
            self.params, self.state, self.pools, jnp.asarray(ver_table),
            jnp.asarray(toks), jnp.asarray(pos0), npl)
        nxt = np.asarray(nxt)
        rep.decode_rows = len(plan)
        self.stats["spec_passes"] += 1
        self.stats["decode_row_slots"] += len(plan)
        tr = self._tr()
        d0, d1 = _vns(self._now), _vns(t_end)
        for a, drafts, pre_pages in plan:
            y = nxt[a.row]  # y[j] = greedy token after span slot j
            emitted = [int(y[0])]  # slot 0 (the pending token) is exact
            for j in range(1, len(drafts) + 1):
                # draft j-1 occupies slot j; it was the RIGHT input iff it
                # equals the token the model emitted after slot j-1
                if int(drafts[j - 1]) != emitted[j - 1]:
                    break
                emitted.append(int(y[j]))
            accepted = len(emitted) - 1
            self.stats["spec_accepted"] += accepted
            self.stats["decode_tokens"] += len(emitted)
            if self.integrity is not None:
                # the span write touched every allocated page under
                # [pos0, pos0 + W) — stamp them (rejected-tail bytes
                # included: they are real device state) before the
                # completion/rollback below can free any of them
                p0 = int(pos0[a.row]) // self.page
                p1 = min(a.n_pages,
                         (int(pos0[a.row]) + W - 1) // self.page + 1)
                for idx in range(p0, p1):
                    slot = int(self.table[a.row, idx])
                    if slot:
                        self._stamp_slot(slot)
            if tr is not None:
                trk = self._req_track(a.req.rid)
                tr.emit("X", "verify", d0, d1 - d0, track=trk,
                        args={"rid": a.req.rid, "tok": len(a.out),
                              "pos": int(a.decode_pos),
                              "drafted": len(drafts),
                              "emitted": len(emitted),
                              "step": int(self.stats["steps"])})
                tr.emit("i", "accept", d1, track=trk,
                        args={"rid": a.req.rid, "accepted": accepted,
                              "drafted": len(drafts)})
            first = a.first_token_t is None
            for tok in emitted:
                a.out.append(tok)
                a.token_times.append(t_end)
            if first:
                # full-hit admissions reach their first token through a
                # decode/verify pass, exactly like _run_decode
                a.first_token_t = t_end
                if tr is not None:
                    tr.emit("i", "first_token", d1,
                            track=self._req_track(a.req.rid),
                            args={"rid": a.req.rid, "t": t_end})
            if len(a.out) >= a.req.max_new:
                self._complete(a, t_end, rep)
            else:
                a.pending_tok = emitted[-1]
                # rollback: pages past the new frontier (rejected-draft
                # territory) return to the pool — the partial sibling of
                # eviction's free_request; their stale K/V is never
                # attended (mask) and re-writes overwrite it. Bounded
                # below by pre_pages: only pages _plan_drafts added are
                # ever released, so a policy that reserves ahead (static's
                # worst-case admission grant) keeps its reservation
                keep = max(self._pages_for(a.decode_pos + 1), pre_pages)
                if a.n_pages > keep:
                    extra = [int(s)
                             for s in self.table[a.row, keep:a.n_pages]]
                    self.allocator.release(a.req.rid, extra)
                    self.table[a.row, keep:a.n_pages] = 0
                    a.n_pages = keep

    def _run_prefill_chunk(self, a: _Active, C: int, t_end: float,
                           rep: StepReport) -> None:
        import jax.numpy as jnp

        assert self.rows[a.row] is a, "scheduled a dead (evicted) row"
        S = a.req.prompt_len
        start = a.prefill_done
        end_real = min(start + C, S)
        chunk = np.zeros((1, C), np.int32)
        chunk[0, :end_real - start] = a.req.prompt[start:end_real]
        last = end_real == S
        want = (S - 1 - start) if last else 0
        npl = self._pages_for(end_real)
        nxt, self.pools = self._prefill_jit(
            self.params, self.state, self.pools,
            jnp.asarray(self.table[a.row:a.row + 1]), jnp.asarray(chunk),
            np.int32(start), np.int32(want), npl)
        a.prefill_done = end_real
        if self.integrity is not None:
            self._stamp_prefill_pages(a, start, end_real)
        rep.prefill_calls += 1
        self.stats["prefill_calls"] += 1
        self.stats["prefill_tokens"] += end_real - start
        tr = self._tr()
        if tr is not None:
            # the span covers the WHOLE step window [now, t_end): in the
            # virtual cost model the request is "being prefilled" for the
            # step it is packed into — serveview's TTFT decomposition
            # counts that full window as prefill time
            tr.emit("X", "prefill_chunk", _vns(self._now),
                    _vns(t_end) - _vns(self._now),
                    track=self._req_track(a.req.rid),
                    args={"rid": a.req.rid, "chunk": start // max(C, 1),
                          "start": start, "tokens": end_real - start,
                          "cached_tokens":
                              self._cached_tokens.get(a.req.rid, 0),
                          "step": int(self.stats["steps"])})
        if self.prefix is not None:
            # register newly completed prompt pages (every byte prompt
            # content — positions the request will never write again)
            for b in range(a.registered_blocks, end_real // self.page):
                self.prefix.register(a.req.prompt, b,
                                     int(self.table[a.row, b]))
            a.registered_blocks = max(a.registered_blocks,
                                      end_real // self.page)
        if last:
            tok = self._emit_token(nxt, a.req.rid, len(a.out))
            a.out.append(tok)
            a.token_times.append(t_end)
            a.first_token_t = t_end
            if tr is not None:
                tr.emit("i", "first_token", _vns(t_end),
                        track=self._req_track(a.req.rid),
                        args={"rid": a.req.rid, "t": t_end})
            if len(a.out) >= a.req.max_new:
                self._complete(a, t_end, rep)
            else:
                a.state = "decode"
                a.pending_tok = tok

    def _run_decode(self, decode_set: List[_Active], t_end: float,
                    rep: StepReport) -> None:
        import jax.numpy as jnp

        assert all(self.rows[a.row] is a for a in decode_set), \
            "scheduled a dead (evicted) row"
        tr = self._tr()
        if tr is not None:
            # one span per participating request, covering the step window
            # — `tok` is the index of the token this pass emits, so
            # serveview can reconstruct per-token times (last emission
            # wins across eviction/recompute replays)
            d0, d1 = _vns(self._now), _vns(t_end)
            for a in decode_set:
                tr.emit("X", "decode", d0, d1 - d0,
                        track=self._req_track(a.req.rid),
                        args={"rid": a.req.rid, "tok": len(a.out),
                              "pos": int(a.decode_pos),
                              "step": int(self.stats["steps"])})
        B = self.cfg.max_batch
        toks = np.zeros((B, 1), np.int32)
        pos = np.zeros((B,), np.int32)
        mask = np.zeros((B,), bool)
        for a in decode_set:
            toks[a.row, 0] = a.pending_tok
            pos[a.row] = a.decode_pos
            mask[a.row] = True
        # inactive rows (free, or mid-prefill) are routed to the scratch
        # slot so their masked writes cannot touch a live page
        dec_table = np.where(mask[:, None], self.table, 0)
        npl = max(int(a.decode_pos) // self.page + 1 for a in decode_set)
        nxt, self.pools = self._decode_jit(
            self.params, self.state, self.pools, jnp.asarray(dec_table),
            jnp.asarray(toks), jnp.asarray(pos), npl)
        nxt = np.asarray(nxt)
        if self.integrity is not None:
            # stamp each row's written page from the SAVED pos array,
            # BEFORE the emission loop can complete (and free) a request
            for a in decode_set:
                slot = int(self.table[a.row, pos[a.row] // self.page])
                if slot:
                    self._stamp_slot(slot)
        rep.decode_rows = len(decode_set)
        self.stats["decode_calls"] += 1
        self.stats["decode_row_slots"] += len(decode_set)
        self.stats["decode_tokens"] += len(decode_set)
        for a in decode_set:
            tok = self._emit_token(nxt[a.row], a.req.rid, len(a.out))
            a.out.append(tok)
            a.token_times.append(t_end)
            if a.first_token_t is None:
                # full-hit admissions skip prefill entirely — their first
                # token comes from this decode pass
                a.first_token_t = t_end
                if tr is not None:
                    tr.emit("i", "first_token", _vns(t_end),
                            track=self._req_track(a.req.rid),
                            args={"rid": a.req.rid, "t": t_end})
            if len(a.out) >= a.req.max_new:
                self._complete(a, t_end, rep)
            else:
                a.pending_tok = tok

    def drain(self, now: float = 0.0):
        """Retire this replica under live load (ReplicatedServer.resize
        scale-down): every in-flight request is EVICTED onto the existing
        recompute path (pages freed — shared prefix pages survive for the
        index until the pools are dropped with the engine — tokens
        regenerate identically on whichever replica re-admits it, greedy
        and seeded sampling both being pure functions of (params, prompt,
        rid, token index)), and the whole queue is handed back for
        least-loaded redistribution. Finished records stay on the engine;
        the server keeps draining engines in its retired list so nothing
        drops out of ``finished``/``stats_summary``. Returns (requests,
        evicted_count, handoff) — ``handoff[rid] = (queued_at, evicted)``
        lets the receiving engine keep the queue-wait baseline and the
        recompute marker, so resized requests trace like engine-local
        evictions instead of resetting to their original arrival."""
        self._now = now
        rep = StepReport()
        for a in sorted(self._active(), key=lambda x: x.admit_seq):
            if self.rows[a.row] is a:
                self._evict(a, rep)
        reqs = list(self.queue)
        self.queue.clear()
        handoff = {r.rid: (self._queued_at.get(r.rid, now),
                           r.rid in self._evicted_rids) for r in reqs}
        self._queued_at.clear()
        return reqs, rep.evicted, handoff

    # -- cross-engine page shipping (serve/handoff.py) ---------------------

    def fetch_pages(self, slots: List[int]) -> List[Optional[Dict[str,
                                                                  Any]]]:
        """Device->host copy of the given pool slots: payload + scale-
        sidecar rows, one dict per serving layer (None for layers with no
        pool). tp>1 pools fetch the [tp, ...] stacked slices, so the full
        head width ships regardless of the shard layout. The per-layer
        ``kv_seed`` never ships — it is layer-intrinsic and identical on
        every engine built from the same model, which is exactly what
        makes re-quantization after a decode-fleet failover bitwise."""
        idx = np.asarray(slots, np.int64)
        out: List[Optional[Dict[str, Any]]] = []
        for pool in self.pools:
            if pool is None:
                out.append(None)
                continue
            out.append({k: np.asarray(v[idx] if self._page_axis == 0
                                      else v[:, idx])
                        for k, v in pool.items()
                        if getattr(v, "ndim", 0)})
        return out

    def write_pages(self, slots: List[int], pages) -> None:
        """Host->device import of ``fetch_pages`` rows into this engine's
        pool at ``slots`` (the importer's own allocator grants). Bytes are
        written verbatim — int8 payload and f32 scale sidecars land
        bit-identical to the exporter's, so subsequent decode reads (and
        the position-keyed stochastic-rounding re-writes of any future
        positions) match the aggregated engine exactly."""
        idx = np.asarray(slots, np.int64)
        new_pools = []
        for pool, rows in zip(self.pools, pages):
            if pool is None:
                new_pools.append(None)
                continue
            pool = dict(pool)
            for k, v in rows.items():
                arr = pool[k]
                pool[k] = (arr.at[idx].set(v) if self._page_axis == 0
                           else arr.at[:, idx].set(v))
            new_pools.append(pool)
        self.pools = new_pools

    def extract_request(self, rid: int) -> Optional[Dict[str, Any]]:
        """Pop an in-flight DECODE-state request off this engine for
        cross-engine shipping: copy its table-row pages to host
        (:meth:`fetch_pages`), then free the row and its page refs —
        prefix-registered blocks survive on the index's own refs, exactly
        like eviction. Returns the ship dict :meth:`import_request`
        accepts. Extraction is not a terminal state: nothing lands in
        ``finished``/``evicted`` — the request continues elsewhere.

        With integrity on, export is a trust boundary: every page is
        verified against the ledger BEFORE it can ship. A mismatch
        quarantines the slot — which evicts this very request onto the
        local recompute path — and returns None: corrupt bytes never
        leave the engine, and the request re-prefills and re-ships clean
        ones. Clean ships carry per-(layer, page) ``checksums`` the
        importer re-verifies and stamps from."""
        a = next((x for x in self._active() if x.req.rid == rid), None)
        if a is None or a.state != "decode":
            raise ValueError(
                f"extract_request: rid {rid} is not an in-flight decode "
                "request")
        slots = [int(s) for s in self.table[a.row, :a.n_pages]]
        if self.integrity is not None:
            for s in slots:
                if not self._verify_slot(s, "export"):
                    return None  # quarantined + evicted: nothing ships
        ship = {
            "rid": rid, "req": a.req, "out": list(a.out),
            "token_times": list(a.token_times),
            "first_token_t": a.first_token_t,
            "pending_tok": a.pending_tok,
            "prefill_done": a.prefill_done,
            "n_pages": a.n_pages,
            "cached_tokens": self._cached_tokens.pop(rid, 0),
            "pages": self.fetch_pages(slots),
        }
        if self.integrity is not None:
            # wire checksums straight from the (just-verified) ledger —
            # one word per (layer, page); None for poolless layers and
            # for not-yet-stamped partial tail pages
            ship["checksums"] = [
                None if pool is None else
                [self.integrity.expected(li, s) for s in slots]
                for li, pool in enumerate(self.pools)]
        self.allocator.free_request(rid)
        self.table[a.row, :] = 0
        self.rows[a.row] = None
        self._queued_at.pop(rid, None)
        self._evicted_rids.discard(rid)
        return ship

    def import_request(self, ship: Dict[str, Any], now: float) -> bool:
        """Bind a shipped request's pages into this engine and resume it
        mid-stream in decode state. All-or-nothing: returns False (engine
        unchanged) when no free row or not enough free pages — the caller
        parks the ship and retries next step. The imported request joins
        the admission order at the tail, like any admission."""
        row = self._free_row()
        if row is None:
            return False
        req: ServeRequest = ship["req"]
        if self.integrity is not None and \
                ship.get("checksums") is not None:
            # trust boundary: re-checksum the ship's host bytes against
            # the exporter's words BEFORE any allocation or pool write —
            # a corrupt ship is rejected all-or-nothing (engine
            # untouched) and rides the parked-ship retry, where the
            # handoff wire repair retransmits intact bytes
            self._now = now
            calc = ship_checksums(ship["pages"], self._page_axis)
            for li, want in enumerate(ship["checksums"]):
                if want is None:
                    continue
                for p, w in enumerate(want):
                    if w is not None and w != calc[li][p]:
                        self.stats["sdc_detected"] += 1
                        self._sdc_trace("ship_reject", rid=req.rid,
                                        layer=li, page=p)
                        return False
        slots = self._alloc(req.rid, ship["n_pages"])
        if slots is None:
            return False
        self._now = now
        self.write_pages(slots, ship["pages"])
        if self.integrity is not None and \
                ship.get("checksums") is not None:
            # the scatter is verbatim: destination slots inherit the
            # ship's verified checksums without a fresh device fetch
            for li, want in enumerate(ship["checksums"]):
                if want is None:
                    continue
                for p, w in enumerate(want):
                    if w is not None:
                        self.integrity.stamp(li, slots[p], w)
        a = _Active(req=req, row=row, admit_seq=self._admit_seq)
        self._admit_seq += 1
        a.state = "decode"
        a.prefill_done = ship["prefill_done"]
        a.n_pages = ship["n_pages"]
        a.pending_tok = ship["pending_tok"]
        a.out = list(ship["out"])
        a.token_times = list(ship["token_times"])
        a.first_token_t = ship["first_token_t"]
        self.table[row, :] = 0
        self.table[row, :a.n_pages] = slots
        self.rows[row] = a
        if ship["cached_tokens"]:
            self._cached_tokens[req.rid] = ship["cached_tokens"]
        if req.deadline is not None:
            self._has_deadlines = True
        self.stats["admitted"] += 1
        self._trace_admit(a, ship["cached_tokens"])
        return True

    def stats_summary(self) -> Dict[str, float]:
        s = dict(self.stats)
        calls = s.pop("decode_calls")
        slots = s.pop("decode_row_slots")
        frag_sum, frag_n = s.pop("frag_sum"), s.pop("frag_samples")
        s["decode_calls"] = calls
        # verify passes fill batch rows exactly like decode passes — the
        # utilization denominator counts both
        passes = calls + s["spec_passes"]
        s["decode_batch_util"] = (
            slots / (passes * self.cfg.max_batch) if passes else 0.0)
        s["mean_page_fragmentation"] = frag_sum / frag_n if frag_n else 0.0
        # HBM accounting: peak_occupancy * pool_bytes = peak cache bytes.
        # bytes_per_page is K/V PAYLOAD per slot summed over layers (the
        # int8 scale sidecar — 8 B/position/layer — is excluded so the
        # dtype capacity ratios are exact; documented in ARCHITECTURE.md)
        s["bytes_per_page"] = self.bytes_per_page
        s["pool_bytes"] = self.bytes_per_page * self.cfg.pool_pages
        # speculative-decoding headline rates (0-guarded; spec-off runs
        # report accept_rate 0 and tokens_per_pass exactly 1.0).
        # tokens_per_pass is PER ROW-pass — tokens a request gains per
        # decode/verify slot it occupies — so it isolates the speculative
        # multiplier from batch-width effects (1 + mean accepted drafts)
        s["spec_accept_rate"] = (
            s["spec_accepted"] / s["spec_drafted"]
            if s["spec_drafted"] else 0.0)
        s["tokens_per_pass"] = (
            s["decode_tokens"] / slots if slots else 0.0)
        return s

    def snapshot(self) -> Dict[str, Any]:
        """Live state of this replica, O(rows + queue + finished) host
        work and zero device traffic — the flight-recorder window an
        operator (or the ROADMAP-2c autoscaler) polls mid-run: occupancy,
        queue depth, per-request ages at the engine's current virtual
        clock, SLO attainment so far (``cfg.slo_ttft``/``slo_itl``; 0 =
        no SLO, always-attained), and the ring of recent per-step states
        (``cfg.flight_recorder`` entries)."""
        now = self._last_t
        reqs: List[Dict[str, Any]] = []
        for a in sorted(self._active(), key=lambda x: x.admit_seq):
            reqs.append({
                "rid": a.req.rid, "state": a.state,
                "age": now - (a.req.arrival if a.req.arrival is not None
                              else 0.0),
                "prefill_done": a.prefill_done,
                "out_tokens": len(a.out), "pages": a.n_pages,
            })
        for r in self.queue:
            # queued age = time since (re)enqueue, from _queued_at — for a
            # never-evicted request that IS the arrival; for a requeued
            # victim it is the current wait, matching the queue_wait span
            q0 = self._queued_at.get(
                r.rid, r.arrival if r.arrival is not None else 0.0)
            reqs.append({
                "rid": r.rid, "state": "queued", "age": now - q0,
                "prefill_done": 0, "out_tokens": 0, "pages": 0,
            })
        slo_t = self.cfg.slo_ttft or None
        slo_i = self.cfg.slo_itl or None
        ok = sum(1 for f in self.finished
                 if request_slo_ok(f, slo_t, slo_i))
        return {
            "t": now, "replica": self.replica,
            "occupancy": self.allocator.occupancy(),
            "free_pages": self.allocator.free_pages,
            "shared_pages": self.allocator.shared_pages,
            "queue_depth": len(self.queue),
            "active": len(self._active()),
            "completed": len(self.finished),
            "evicted": int(self.stats["evicted"]),
            "slo_attainment": ok / len(self.finished)
            if self.finished else 0.0,
            "requests": reqs,
            "recent_steps": (list(self._flight)
                             if self._flight is not None else []),
        }


class ReplicatedServer:
    """N independent replicas over the serving mesh's 'data' axis with a
    least-loaded dispatcher. Replicas step in lockstep; a global step
    costs the max over replica costs (they run in parallel).

    LIVE RESIZE (:meth:`resize`, ISSUE 12): the serving half of the
    elastic world-size story. Scale-down drains the highest-index
    replicas — in-flight requests are evicted onto the existing recompute
    path and the drained queues redistribute least-loaded over the
    survivors — so no request is ever lost, and token streams stay
    bitwise (greedy and seeded sampling are pure functions of (params,
    prompt, rid, token index), the same invariant eviction/recompute
    already relies on; pinned vs an un-resized control by
    tests/test_elastic.py). Scale-up spawns fresh replicas through the
    ``engine_factory`` make_server installs, SHARING the jitted callables
    — a new replica costs zero compiles. Drained engines are retired, not
    discarded: their finished records and counters stay in ``finished``
    and ``stats_summary``.
    """

    def __init__(self, engines: List[ServeEngine], engine_factory=None):
        if not engines:
            raise ValueError("need at least one engine")
        self.engines = list(engines)
        self._factory = engine_factory
        self._retired: List[ServeEngine] = []
        self._next_replica = len(engines)
        # (t, from, to, evicted, redistributed, shed) — servebench embeds
        # these
        self.resize_events: List[Dict[str, Any]] = []
        # chaos ledgers (ISSUE 15): hard kills, injected stalls, and
        # heartbeat straggler drains — servechaos embeds all three
        self.fail_events: List[Dict[str, Any]] = []
        self.stall_events: List[Dict[str, Any]] = []
        self.heartbeat_events: List[Dict[str, Any]] = []

    def _least_loaded(self) -> ServeEngine:
        return min(enumerate(self.engines), key=lambda ie: (ie[1].load(),
                                                            ie[0]))[1]

    def _dispatch(self, req: ServeRequest,
                  now: Optional[float] = None) -> Optional[ServeEngine]:
        """Fleet dispatch returning the ACCEPTING engine (None = shed).
        For a DEADLINED request the fleet sheds only when NO replica
        projects the deadline as makeable — with tiers, a higher-load
        replica whose queue is all batch can beat the least-loaded one's
        projection for an interactive submission, so replicas are probed
        in (load, index) order and the first whose projection fits takes
        the request; if none fits, the least-loaded replica records the
        ONE shed. Deadline-free requests go straight to the least-loaded
        replica (the pre-chaos dispatch, bitwise). Every fleet-side
        submission — driver traffic AND failover resubmission
        (fail/heartbeat-drain/resize) — routes through here, so a
        displaced request is never shed by a survivor when a sibling
        could still meet its deadline."""
        if req.deadline is not None:
            order = sorted(enumerate(self.engines),
                           key=lambda ie: (ie[1].load(), ie[0]))
            t_sub = now if now is not None else (
                req.arrival if req.arrival is not None else 0.0)
            for _, e in order:
                if e.projected_finish(req, t_sub) <= req.deadline:
                    return e if e.submit(req, now=now) else None
            order[0][1].submit(req, now=now)  # records the one shed
            return None
        e = self._least_loaded()
        return e if e.submit(req, now=now) else None

    def submit(self, req: ServeRequest, now: Optional[float] = None) -> bool:
        """Least-loaded dispatch with the fleet-wide deadline probe
        (:meth:`_dispatch`): False means the request was SHED."""
        return self._dispatch(req, now=now) is not None

    def has_work(self) -> bool:
        return any(e.has_work() for e in self.engines)

    def step(self, now: float = 0.0) -> StepReport:
        rep = StepReport()
        stalled_work = False
        progressed: List[ServeEngine] = []
        for e in self.engines:
            if e._stall_ticks > 0:
                # straggler injection: the replica holds its requests but
                # schedules nothing this global step — and its progress
                # monitor is deliberately NOT kicked
                e._stall_ticks -= 1
                stalled_work = stalled_work or e.has_work()
                continue
            if e.has_work():
                rep.merge(e.step(now))
            progressed.append(e)
        if rep.cost == 0 and stalled_work:
            # every replica holding work is stalled: the fleet still burns
            # a virtual time unit doing nothing, or the straggler would be
            # free (clock frozen => heartbeat could never fire)
            rep.cost = 1
        t_end = now + rep.cost
        for e in progressed:
            if e.monitor is not None:
                # scheduled (or idle — an empty replica is healthy, not
                # stuck) counts as progress AS OF THE STEP'S END: kicking
                # at `now` instead would falsely expire every working
                # replica on any global step whose cost exceeds the
                # window (expiry below is evaluated at t_end)
                e.monitor.kick(t_end)
        hb = self.engines[0].cfg.heartbeat
        if hb > 0:
            for e in [x for x in self.engines
                      if x.monitor is not None and x.has_work()
                      and x.monitor.expired(t_end)]:
                if len(self.engines) == 1:
                    break  # no survivor to redistribute onto
                self._drain_straggler(e, t_end)
        return rep

    # -- serving-fleet chaos: hard kill, straggler stall, heartbeat --------

    def fail(self, replica: int, now: float = 0.0,
             dispatch=None) -> Dict[str, Any]:
        """HARD-KILL the replica at fleet index ``replica``: the engine is
        discarded — its device pool (all resident KV, prefix cache
        included) is lost — and only host-side state survives: finished
        records are SALVAGED (the killed engine retires into the summary,
        so ``finished``/``stats_summary`` never lose them), and every
        request it still held (in-flight and queued — prompt + emitted
        tokens are host-side dispatcher state) is RESUBMITTED least-loaded
        onto the survivors, where the eviction/recompute path regenerates
        the token streams from scratch, bitwise identical (greedy and
        seeded sampling are pure functions of (params, prompt, rid, token
        index) — the PR 12 resize argument, now under UNCOORDINATED loss).
        Resubmission is a re-admission: the survivors count ``admitted``
        again and trace a ``recompute`` marker, mirroring eviction; the
        ``completed`` counters and finished records stay exactly-once.
        In-flight requests resubmit oldest-first (the oldest work gets the
        least-loaded pick first), then the waiting queue in order. A
        resubmission can still be SHED by deadline admission control on
        the survivor — counted in the event's ``shed_on_failover`` (those
        requests surface in servechaos's ``requests_lost``).

        ``dispatch`` overrides where displaced requests resubmit: the
        disaggregated server routes a killed DECODE replica's requests
        back through the PREFILL fleet's dispatcher (the pages died with
        the replica — they must re-prefill and re-ship)."""
        if not 0 <= replica < len(self.engines):
            raise IndexError(
                f"fail: no replica at fleet index {replica} "
                f"(fleet size {len(self.engines)})")
        if len(self.engines) == 1 and dispatch is None:
            raise ValueError(
                "cannot fail the last replica — no survivor to fail over "
                "to (the fleet analog of losing the whole pod)")
        eng = self.engines.pop(replica)
        inflight = sorted(eng._active(), key=lambda a: a.admit_seq)
        queued = list(eng.queue)
        # queued requests' wait baselines + recompute markers are HOST
        # state and survive the kill (the drain()/resize() handoff
        # convention): a request evicted earlier and still queued at the
        # kill keeps its restarted wait, not its original arrival
        handoff = {r.rid: (eng._queued_at.get(r.rid, now),
                           r.rid in eng._evicted_rids) for r in queued}
        # the engine is dead: clear its live bookkeeping (the allocator/
        # pool state is garbage with it) but keep finished + counters
        eng.queue.clear()
        for a in inflight:
            eng.rows[a.row] = None
        eng._queued_at.clear()
        eng._evicted_rids.clear()
        eng._cached_tokens.clear()
        eng._stall_ticks = 0
        self._retired.append(eng)
        resubmitted = shed_n = 0
        dispatch = dispatch if dispatch is not None else self._dispatch
        moves = [(a.req, True) for a in inflight] \
            + [(r, False) for r in queued]
        for r, was_active in moves:
            tgt = dispatch(r, now=now)
            if tgt is not None:
                resubmitted += 1
                if was_active:
                    # the failover is the eviction analog: the wait
                    # restarts at the kill instant and the re-admission
                    # traces as a recompute
                    tgt._queued_at[r.rid] = now
                    tgt._evicted_rids.add(r.rid)
                else:
                    q0, was_evicted = handoff[r.rid]
                    tgt._queued_at[r.rid] = q0
                    if was_evicted:
                        tgt._evicted_rids.add(r.rid)
            else:
                shed_n += 1
        ev = {"t": now, "replica_id": eng.replica, "fleet_index": replica,
              "salvaged": len(eng.finished),
              "displaced_inflight": [a.req.rid for a in inflight],
              "displaced_queued": len(queued),
              "resubmitted": resubmitted, "shed_on_failover": shed_n}
        self.fail_events.append(ev)
        return ev

    def stall(self, replica: int, ticks: int, now: float = 0.0) -> None:
        """Inject a STRAGGLER: the replica at fleet index ``replica``
        stops progressing for ``ticks`` global steps while holding its
        requests (the grey-failure sibling of :meth:`fail` — nothing
        died, it is just not answering). With ``cfg.heartbeat > 0`` the
        no-progress detector drains it within the detection window; with
        no heartbeat the stall simply delays its requests until the
        replica recovers."""
        if not 0 <= replica < len(self.engines):
            raise IndexError(
                f"stall: no replica at fleet index {replica} "
                f"(fleet size {len(self.engines)})")
        if ticks < 1:
            raise ValueError(f"stall needs ticks >= 1, got {ticks}")
        eng = self.engines[replica]
        eng._stall_ticks = ticks
        self.stall_events.append({"t": now, "replica_id": eng.replica,
                                  "fleet_index": replica, "ticks": ticks})

    def _drain_straggler(self, eng: ServeEngine, now: float) -> None:
        """Heartbeat verdict: drain a no-progress replica exactly like a
        scale-down — in-flight requests evict onto the recompute path,
        the queue redistributes least-loaded, the engine retires with its
        records (unlike :meth:`fail`, the replica's host state is intact,
        so pages free cleanly)."""
        idx = self.engines.index(eng)
        self.engines.remove(eng)
        reqs, evicted, handoff = eng.drain(now)
        self._retired.append(eng)
        shed_n = 0
        for r in reqs:
            tgt = self._dispatch(r, now=now)
            if tgt is not None:
                q0, was_evicted = handoff[r.rid]
                tgt._queued_at[r.rid] = q0
                if was_evicted:
                    tgt._evicted_rids.add(r.rid)
            else:
                shed_n += 1
        self.heartbeat_events.append({
            "t": now, "replica_id": eng.replica, "fleet_index": idx,
            "stalled_for": eng.monitor.stalled_for(now),
            "evicted": evicted, "redistributed": len(reqs) - shed_n,
            "shed": shed_n})

    def resize(self, n: int, now: float = 0.0) -> Dict[str, Any]:
        """Scale the live replica fleet to ``n`` under load. Scale-down
        drains the highest-index replicas first (lowest replica indices —
        the oldest trace tracks — are the stable ones) and resubmits every
        displaced request least-loaded; scale-up appends factory-built
        replicas sharing the compiled programs. Returns a report dict."""
        if n < 1:
            raise ValueError(f"resize needs >= 1 replica, got {n}")
        before = len(self.engines)
        drained: List[ServeEngine] = []
        while len(self.engines) > n:
            drained.append(self.engines.pop())
        reqs: List[ServeRequest] = []
        evicted = 0
        handoff: Dict[int, Any] = {}
        # drain in ascending replica order for a deterministic resubmit
        # sequence; within one engine: evicted actives NEWEST-first (the
        # eviction requeue stacks them at the queue's front), then the
        # waiting queue in arrival order
        for eng in reversed(drained):
            r, ev, h = eng.drain(now)
            reqs.extend(r)
            evicted += ev
            handoff.update(h)
        self._retired.extend(reversed(drained))
        shed_n = 0
        for r in reqs:
            eng = self._dispatch(r, now=now)
            if eng is None:
                shed_n += 1  # deadline admission control shed the move
                continue
            # keep the queue-wait baseline + recompute marker across the
            # replica move: a request evicted by the drain must trace as
            # a recompute whose wait restarts at the resize instant, not
            # as a fresh arrival waiting since t=0
            q0, was_evicted = handoff[r.rid]
            eng._queued_at[r.rid] = q0
            if was_evicted:
                eng._evicted_rids.add(r.rid)
        while len(self.engines) < n:
            if self._factory is None:
                raise RuntimeError(
                    "resize: scale-up needs the engine factory make_server "
                    "installs (this server was built from bare engines)")
            # replica id is monotonic (unique trace tracks); the device
            # SLOT is the fleet position, so a re-grown fleet reuses the
            # devices its drained predecessors vacated
            eng = self._factory(self._next_replica, n, len(self.engines))
            if eng.monitor is not None:
                # the heartbeat baseline starts at the GROW instant — a
                # fresh monitor's default 0.0 would read as `now` units
                # of no progress and drain a brand-new replica on its
                # first stalled (or merely unlucky) step
                eng.monitor.kick(now)
            self.engines.append(eng)
            self._next_replica += 1
        # shed moves are NOT redistributed — same accounting convention
        # as fail()'s resubmitted/shed_on_failover and the heartbeat
        # drain's redistributed/shed split
        report = {"t": now, "from": before, "to": n, "evicted": evicted,
                  "redistributed": len(reqs) - shed_n, "shed": shed_n}
        self.resize_events.append(report)
        return report

    @property
    def finished(self) -> List[Dict[str, Any]]:
        out = []
        for e in self.engines + self._retired:
            out.extend(e.finished)
        return out

    @property
    def timed_out(self) -> List[Dict[str, Any]]:
        """Every ``timeout`` terminal record across the fleet (retired —
        drained, failed, resized-away — replicas included)."""
        out = []
        for e in self.engines + self._retired:
            out.extend(e.timed_out)
        return out

    @property
    def shed_records(self) -> List[Dict[str, Any]]:
        """Every ``shed`` admission rejection across the fleet."""
        out = []
        for e in self.engines + self._retired:
            out.extend(e.shed)
        return out

    @property
    def sdc_events(self) -> List[Dict[str, Any]]:
        """Every SDC detection/quarantine record across the fleet
        (retired replicas included), time-ordered — servechaos derives
        MTTD and quarantine-recovery MTTR from these."""
        out = []
        for e in self.engines + self._retired:
            out.extend(e.sdc_events)
        return sorted(out, key=lambda ev: ev["t"])

    def snapshot(self) -> Dict[str, Any]:
        """Fleet snapshot: per-replica snapshots plus the aggregates a
        dispatcher/autoscaler reads — total queue depth and active count,
        the WORST replica's occupancy (saturation is a max signal, same
        reasoning as stats_summary's peak), and fleet-wide SLO attainment
        so far."""
        snaps = [e.snapshot() for e in self.engines]
        fin = self.finished
        slo_t = self.engines[0].cfg.slo_ttft or None
        slo_i = self.engines[0].cfg.slo_itl or None
        ok = sum(1 for f in fin if request_slo_ok(f, slo_t, slo_i))
        return {
            "t": max(s["t"] for s in snaps),
            "replicas": snaps,
            "queue_depth": sum(s["queue_depth"] for s in snaps),
            "active": sum(s["active"] for s in snaps),
            "completed": len(fin),
            "occupancy": max(s["occupancy"] for s in snaps),
            "slo_attainment": ok / len(fin) if fin else 0.0,
        }

    def stats_summary(self) -> Dict[str, float]:
        return fleet_stats(self.engines, self._retired)


def fleet_stats(live: List[ServeEngine],
                retired: List[ServeEngine]) -> Dict[str, float]:
    """Fleet-wide summary over live + retired engines — shared by
    ReplicatedServer and the disaggregated server (serve/handoff.py),
    whose fleet is the union of its prefill and decode engines."""
    sums: Dict[str, float] = {}
    fleet = live + retired  # resize/failure never loses counters
    for e in fleet:
        for k, v in e.stats_summary().items():
            sums[k] = sums.get(k, 0) + v
    for k in ("decode_batch_util", "mean_page_fragmentation"):
        sums[k] /= len(fleet)
    # peak occupancy is a saturation signal: averaging would hide one
    # evicting, pool-bound replica behind its idle siblings — the
    # shared-page peak is the same kind of signal
    sums["peak_occupancy"] = max(
        e.stats["peak_occupancy"] for e in fleet)
    sums["shared_pages"] = max(
        e.stats["shared_pages"] for e in fleet)
    # per-slot layout is identical across the fleet (one model/config);
    # pool_bytes is the LIVE fleet's total cache HBM — a drained
    # (retired) engine's pool is released with it, so summing the
    # whole fleet would over-report capacity after every scale-down
    sums["bytes_per_page"] = fleet[0].bytes_per_page
    sums["pool_bytes"] = sum(
        e.bytes_per_page * e.cfg.pool_pages for e in live)
    # rates re-derive from the summed counters (a mean of per-replica
    # ratios would weight an idle replica like a loaded one)
    row_passes = sum(e.stats["decode_row_slots"] for e in fleet)
    sums["spec_accept_rate"] = (
        sums["spec_accepted"] / sums["spec_drafted"]
        if sums["spec_drafted"] else 0.0)
    sums["tokens_per_pass"] = (
        sums["decode_tokens"] / row_passes if row_passes else 0.0)
    return sums


def make_server(model: LayerModel, params, state, cfg: ServeConfig,
                dtype=None, devices=None,
                shared_fns=None) -> ReplicatedServer:
    """Build a (possibly multi-replica) server. ``devices=None`` places
    replica i on ``jax.devices()[i]`` when there are enough devices — the
    serving analog of laying replicas along the mesh's 'data' axis — and
    shares the default device otherwise. ``shared_fns`` (a prior server's
    ``engines[0].jit_fns()``) seeds the jitted callables: servers built
    from the same model and shapes — e.g. servebench's per-policy rows —
    reuse one compile instead of re-tracing every npl variant.

    The returned server carries an ENGINE FACTORY so ``resize`` can scale
    the fleet up under live load: a new replica shares the first engine's
    jitted callables (zero compiles) and follows the same device-placement
    rule at its new fleet size."""
    import jax

    n = cfg.replicas
    if devices is None:
        devs = jax.devices()
        # a tp>1 replica is placed by its mesh sharding (_init_tp), not a
        # single device — per-replica device pinning applies to tp=1 only
        devices = [devs[i] if n > 1 and cfg.tp == 1 and i < len(devs)
                   else None for i in range(n)]
    rep_cfg = cfg.replace(replicas=1)
    engines = []
    for d in devices:
        engines.append(ServeEngine(
            model, params, state, rep_cfg, dtype=dtype, device=d,
            shared_fns=engines[0].jit_fns() if engines else shared_fns,
            replica=len(engines)))
    fns = engines[0].jit_fns()

    def factory(replica: int, fleet_size: int, slot: int) -> ServeEngine:
        # placement by fleet SLOT, not replica id: replica ids grow
        # monotonically across resizes (unique trace tracks), while slots
        # are fleet positions — a grow after a shrink reuses the devices
        # the drained replicas vacated instead of stacking new replicas
        # on the default device
        devs = jax.devices()
        device = (devs[slot] if fleet_size > 1 and rep_cfg.tp == 1
                  and slot < len(devs) else None)
        return ServeEngine(model, params, state, rep_cfg, dtype=dtype,
                           device=device, shared_fns=fns, replica=replica)

    return ReplicatedServer(engines, engine_factory=factory)
