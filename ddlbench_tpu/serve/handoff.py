"""Disaggregated serving: a prefill fleet feeding a decode fleet by
KV-page shipping.

Prefill and decode have opposite hardware appetites — prefill is one
compute-bound pass over the whole prompt, decode is hundreds of
memory-bound single-token passes — so serving both phases on every
replica makes each replica bad at one of them (the chunked-prefill
token budget is exactly the knob that rations their interference). The
disaggregated layout gives each phase its own fleet and moves a request
ONCE, at the phase boundary:

    prefill fleet                          decode fleet
    admit -> chunk-prefill -> first token
            export_request(rid)  ----->  import_request(ship)
            (pages + scale sidecars       (bind into own allocator,
             device->host, refs freed)     resume mid-stream in decode)

The transfer primitive is the page pool itself: a request's KV state is
its table-row page slots, so export is a device->host gather of those
pool rows (payload + int8 scale sidecars), and import is an allocator
grant plus a verbatim scatter on the receiving engine — the same
refcount/free-list machinery the prefix cache's bind/COW path already
exercises. int8 pools ship exactly f32/4 payload bytes; the f32 scale
sidecar (8 B/position/layer) is accounted separately, mirroring the
``bytes_per_page`` convention.

Determinism: token streams are pure functions of (params, prompt, rid,
token index) — greedy argmax and seeded sampling alike — and quantized
page bytes are pure functions of (values, layer seed, k/v tag, stream
position). So the disaggregated server's streams pin bitwise against
the aggregated fleet, a prefill-replica kill mid-handoff loses nothing
(displaced requests re-prefill on survivors, regenerating identical
pages), and a decode-replica kill re-routes its requests through the
PREFILL fleet's dispatcher (the pages died with the replica), where
re-prefill re-quantizes byte-identical pages before re-shipping.
"""

from typing import Any, Dict, List, Optional

from ddlbench_tpu.config import ServeConfig
from ddlbench_tpu.serve.engine import (
    ReplicatedServer,
    ServeEngine,
    StepReport,
    fleet_stats,
    make_server,
)
from ddlbench_tpu.serve.integrity import (
    CHECKSUM_BYTES,
    repair_ship,
    ship_checksums,
)
from ddlbench_tpu.serve.workload import ServeRequest

PAYLOAD_KEYS = ("pool_k", "pool_v")
SIDECAR_KEYS = ("scale_k", "scale_v")


def ship_payload_bytes(ship: Dict[str, Any]) -> int:
    """K/V payload bytes in one ship — for an int8 pool exactly 1/4 of
    the f32 pool's bytes for the same pages (the EQuARX-style halving
    argument, applied to the handoff wire)."""
    return sum(rows[k].nbytes for rows in ship["pages"]
               if rows is not None for k in PAYLOAD_KEYS)


def ship_sidecar_bytes(ship: Dict[str, Any]) -> int:
    """f32 scale-sidecar bytes in one ship (0 for unquantized pools)."""
    return sum(rows[k].nbytes for rows in ship["pages"]
               if rows is not None for k in SIDECAR_KEYS if k in rows)


def ship_checksum_bytes(ship: Dict[str, Any]) -> int:
    """Integrity-word bytes riding the wire with one ship: CHECKSUM_BYTES
    per attached (layer, page) checksum word (0 when the exporter runs
    without integrity — the wire overhead is strictly flag-gated)."""
    return CHECKSUM_BYTES * sum(
        sum(1 for w in per_layer if w is not None)
        for per_layer in ship.get("checksums") or [] if per_layer is not None)


def export_request(engine: ServeEngine, rid: int) -> Optional[Dict[str, Any]]:
    """Pop ``rid`` off ``engine`` (ServeEngine.extract_request) and stamp
    the ship with its wire-byte accounting. Returns None when export-time
    integrity verification caught a corrupt page — the request was
    quarantine-evicted onto the engine's local recompute path and nothing
    ships (it re-exports clean bytes after re-prefill)."""
    ship = engine.extract_request(rid)
    if ship is None:
        return None
    ship["payload_bytes"] = ship_payload_bytes(ship)
    ship["sidecar_bytes"] = ship_sidecar_bytes(ship)
    ship["checksum_bytes"] = ship_checksum_bytes(ship)
    return ship


class DisaggregatedServer:
    """A prefill ReplicatedServer feeding a decode ReplicatedServer.

    Driver-compatible with ReplicatedServer (submit/has_work/step plus
    the record/event surfaces servebench and servechaos read), so the
    open/closed-loop generators drive both layouts unchanged. Traffic
    enters the PREFILL fleet; after every global step, each prefill
    engine's decode-state actives — requests whose prefill just finished
    (their first token rode the last chunk) — are exported and imported
    least-loaded into the decode fleet. A ship that finds no decode
    capacity parks host-side and retries every step (``backpressure`` is
    the decode fleet's admission story, not the prefill fleet's).
    """

    def __init__(self, prefill: ReplicatedServer,
                 decode: ReplicatedServer):
        self.prefill = prefill
        self.decode = decode
        self._pending: List[Dict[str, Any]] = []  # ships parked host-side
        self.shipped: Dict[str, int] = {
            "shipped_requests": 0, "shipped_pages": 0,
            "shipped_payload_bytes": 0, "shipped_sidecar_bytes": 0,
            "shipped_checksum_bytes": 0}
        # wire-transit SDC: ships whose host bytes failed their attached
        # checksums at the handoff pre-import check (detected once here,
        # not once per decode engine tried), and how many were repaired
        # by modelled retransmission from the exporter's intact buffer
        self.wire_sdc: Dict[str, int] = {
            "sdc_wire_detected": 0, "sdc_wire_repaired": 0}
        self.wire_events: List[Dict[str, Any]] = []
        # optional fault hook fired on every pending ship between export
        # and import — the only window that models wire-transit
        # corruption (a ship normally exports and imports within one
        # ``_ship`` tick, so nothing outside this hook can touch it
        # in flight). servechaos --corrupt ...:ship arms it one-shot.
        self.wire_fault_hook: Optional[Any] = None

    # -- ReplicatedServer-compatible driver surface ------------------------

    def submit(self, req: ServeRequest,
               now: Optional[float] = None) -> bool:
        return self.prefill.submit(req, now=now)

    def has_work(self) -> bool:
        return (bool(self._pending) or self.prefill.has_work()
                or self.decode.has_work())

    def step(self, now: float = 0.0) -> StepReport:
        rep = StepReport()
        if self.prefill.has_work():
            rep.merge(self.prefill.step(now))
        if self.decode.has_work():
            rep.merge(self.decode.step(now))
        if rep.cost == 0 and self.has_work():
            rep.cost = 1  # parked ships alone still burn a time unit
        self._ship(now + rep.cost)
        return rep

    def _ship(self, now: float) -> None:
        """The handoff tick: export every prefill-side request whose
        prefill completed this step, then bind pending ships into the
        decode fleet in (load, index) order — all-or-nothing per ship,
        parking what finds no room. Runs at the step's END, so a request
        always takes its first decode pass on the decode fleet (at step
        start the prefill fleet never holds a decode-state active)."""
        for eng in self.prefill.engines:
            ready = sorted((a for a in eng._active()
                            if a.state == "decode"),
                           key=lambda a: a.admit_seq)
            for a in ready:
                ship = export_request(eng, a.req.rid)
                if ship is None:
                    # export verify caught corruption: the request was
                    # quarantine-evicted locally and re-ships after its
                    # recompute — corrupt bytes never reach the wire
                    continue
                self.shipped["shipped_requests"] += 1
                self.shipped["shipped_pages"] += ship["n_pages"]
                self.shipped["shipped_payload_bytes"] += \
                    ship["payload_bytes"]
                self.shipped["shipped_sidecar_bytes"] += \
                    ship["sidecar_bytes"]
                self.shipped["shipped_checksum_bytes"] += \
                    ship["checksum_bytes"]
                self._pending.append(ship)
        for ship in self._pending:
            if self.wire_fault_hook is None:
                break  # one-shot hooks disarm themselves mid-iteration
            self.wire_fault_hook(ship)
        parked = []
        for ship in self._pending:
            verdict = self._wire_corrupt(ship, now)
            if verdict == "park":
                parked.append(ship)  # repaired; retransmission costs a step
                continue
            if verdict == "drop":
                continue  # unrepairable: re-routed through prefill
            order = sorted(enumerate(self.decode.engines),
                           key=lambda ie: (ie[1].load(), ie[0]))
            if not any(e.import_request(ship, now) for _, e in order):
                parked.append(ship)
        self._pending = parked

    def _wire_corrupt(self, ship: Dict[str, Any],
                      now: float) -> Optional[str]:
        """Pre-import wire check: re-checksum a pending ship's host bytes
        against the exporter's attached words. On mismatch, count the
        detection ONCE (the importer's own all-or-nothing check would
        fire per decode engine tried) and repair from the stashed
        original byte — the model of the exporter retransmitting from its
        intact source buffer — parking the ship one step for the
        retransmit ("park"). If nothing intact remains to retransmit the
        ship is dropped and the request re-routes through the PREFILL
        dispatcher, the decode-kill recovery path: re-prefill regenerates
        the pages byte-identically and the handoff re-ships ("drop").
        Ships without checksums (integrity off) pass untouched (None)."""
        want = ship.get("checksums")
        if want is None:
            return None
        axis = (self.prefill.engines or self.decode.engines)[0]._page_axis
        calc = ship_checksums(ship["pages"], axis)
        for li, per_layer in enumerate(want):
            if per_layer is None:
                continue
            for p, w in enumerate(per_layer):
                if w is not None and w != calc[li][p]:
                    self.wire_sdc["sdc_wire_detected"] += 1
                    repaired = repair_ship(ship)
                    if repaired:
                        self.wire_sdc["sdc_wire_repaired"] += 1
                    else:
                        self.prefill._dispatch(ship["req"], now)
                    self.wire_events.append({
                        "t": now, "slot": -1, "where": "wire",
                        "rid": ship["rid"], "layer": li, "page": p,
                        "repaired": repaired, "displaced": []})
                    return "park" if repaired else "drop"
        return None

    # -- chaos: per-fleet hard kills ---------------------------------------

    def fail_prefill(self, index: int, now: float = 0.0) -> Dict[str, Any]:
        """Kill the prefill replica at fleet index ``index``: displaced
        requests (mid-prefill or queued — any already-exported ship is
        host-side and unaffected) resubmit onto the surviving prefill
        replicas and re-prefill from scratch, regenerating identical
        pages."""
        ev = self.prefill.fail(index, now)
        ev["fleet"] = "prefill"
        return ev

    def fail_decode(self, index: int, now: float = 0.0) -> Dict[str, Any]:
        """Kill the decode replica at fleet index ``index``: its imported
        pages die with it, so displaced requests route back through the
        PREFILL fleet's dispatcher — re-prefill re-quantizes the pages
        byte-identically (position-keyed stochastic rounding) and the
        handoff re-ships them."""
        ev = self.decode.fail(index, now,
                              dispatch=self.prefill._dispatch)
        ev["fleet"] = "decode"
        return ev

    # -- autoscale (serve/autoscaler.py attaches per fleet) ----------------

    def controllers(self, policy, start: float = 0.0):
        """Per-fleet autoscale controllers: prefill and decode have
        opposite hardware appetites, so they scale INDEPENDENTLY — each
        fleet gets its own FleetController reading its own signals,
        clamped to the same [lo, hi] band. (A decode-side kill repairs on
        the decode fleet even though its displaced requests re-enter via
        the prefill dispatcher: the dead capacity was decode capacity.)"""
        from ddlbench_tpu.serve.autoscaler import FleetController

        return [FleetController(self.prefill, policy, name="prefill",
                                start=start),
                FleetController(self.decode, policy, name="decode",
                                start=start)]

    # -- record/event surfaces (servebench/servechaos read these) ----------

    @property
    def engines(self) -> List[ServeEngine]:
        return self.prefill.engines + self.decode.engines

    @property
    def finished(self) -> List[Dict[str, Any]]:
        return self.prefill.finished + self.decode.finished

    @property
    def timed_out(self) -> List[Dict[str, Any]]:
        return self.prefill.timed_out + self.decode.timed_out

    @property
    def shed_records(self) -> List[Dict[str, Any]]:
        return self.prefill.shed_records + self.decode.shed_records

    @property
    def fail_events(self) -> List[Dict[str, Any]]:
        return self.prefill.fail_events + self.decode.fail_events

    @property
    def stall_events(self) -> List[Dict[str, Any]]:
        return self.prefill.stall_events + self.decode.stall_events

    @property
    def heartbeat_events(self) -> List[Dict[str, Any]]:
        return self.prefill.heartbeat_events + self.decode.heartbeat_events

    @property
    def resize_events(self) -> List[Dict[str, Any]]:
        return self.prefill.resize_events + self.decode.resize_events

    @property
    def sdc_events(self) -> List[Dict[str, Any]]:
        """Pool detections from both fleets plus wire-transit detections
        from the handoff pre-import check, time-ordered."""
        return sorted(self.prefill.sdc_events + self.decode.sdc_events
                      + self.wire_events, key=lambda ev: ev["t"])

    def snapshot(self) -> Dict[str, Any]:
        return {"prefill": self.prefill.snapshot(),
                "decode": self.decode.snapshot(),
                "pending_ships": len(self._pending), **self.shipped}

    def stats_summary(self) -> Dict[str, float]:
        s = fleet_stats(self.prefill.engines + self.decode.engines,
                        self.prefill._retired + self.decode._retired)
        s.update(self.shipped)
        s.update(self.wire_sdc)
        return s


def make_disaggregated(model, params, state, cfg: ServeConfig,
                       prefill_replicas: int, decode_replicas: int,
                       dtype=None, shared_fns=None) -> DisaggregatedServer:
    """Build a P:D disaggregated server over one model/config. Both
    fleets run the SAME jitted programs (disaggregation is a scheduling
    split, not a program split), so they share one compiled-callable
    cache; tp=1 fleets lay out on devices [0, P) and [P, P+D) when
    enough exist (a tp>1 replica is mesh-placed instead)."""
    import jax

    if prefill_replicas < 1 or decode_replicas < 1:
        raise ValueError(
            f"disaggregation needs >= 1 replica per fleet, got "
            f"{prefill_replicas}:{decode_replicas}")
    devs = jax.devices()
    total = prefill_replicas + decode_replicas
    pre_devs = dec_devs = None
    if cfg.tp == 1 and total > 1 and total <= len(devs):
        pre_devs = list(devs[:prefill_replicas])
        dec_devs = list(devs[prefill_replicas:total])
    pre = make_server(model, params, state,
                      cfg.replace(replicas=prefill_replicas), dtype=dtype,
                      devices=pre_devs, shared_fns=shared_fns)
    dec = make_server(model, params, state,
                      cfg.replace(replicas=decode_replicas), dtype=dtype,
                      devices=dec_devs,
                      shared_fns=pre.engines[0].jit_fns())
    return DisaggregatedServer(pre, dec)
