"""Seeded serving workloads: arrival processes + heavy-tail length mixtures.

"Millions of users" traffic is not a fixed batch of equal-length prompts;
it is open-loop arrivals (users do not wait for each other) with bursts,
and request sizes with a heavy tail (most prompts short, a few very long —
the mix that makes static batching bleed: one long request pins the whole
batch while its neighbors' rows sit drained). This module synthesizes that
shape deterministically.

Determinism discipline (the same bitwise-repro bar every other tool meets):
every draw comes from ``random.Random(seed)`` — CPython's Mersenne Twister,
whose ``random()`` stream is stable across platforms and Python versions by
language guarantee — and all distributions are hand-rolled inverse
transforms over those uniforms (exponential arrivals, bounded-Pareto
lengths). Identical seed => identical arrival times, prompt tokens, and
output lengths, byte for byte.

Arrival processes:

* ``closed``  — no arrival times; the driver keeps a fixed number of
  requests in flight and submits the next on each completion (classic
  closed-loop load: measures capacity, hides queueing).
* ``poisson`` — open loop, exponential inter-arrivals at ``rate`` requests
  per time unit (the time unit is the engine's virtual step cost — one
  model pass; see serve/engine.py).
* ``bursty``  — square-wave-modulated Poisson: requests arrive in groups of
  ``burst_size`` at ``rate * burst_factor``, with the gaps between groups
  at ``rate / burst_factor`` (open loop with queue-building bursts).

Traffic SHAPES (``shape=`` on top of ``poisson``, for the autoscaler A/B):
``diurnal`` (raised-cosine rate curve — trough/peak/trough, the daily load
cycle), ``ramp`` (linear ramp from trough to peak), ``spike`` (flat
baseline with a short high-multiplier flash crowd mid-run). The shaped
arrival uniforms come from a SEPARATE seeded stream
(``Random(f"{seed}:shape")``, the tier-mix pattern), so the main stream
never sees them: prompts and output lengths are bitwise-identical across
every ``shape=`` value at a fixed seed — an autoscale-vs-static A/B
differs only in WHEN requests arrive, never in WHAT they ask.
"""

from __future__ import annotations

import dataclasses
import math
import random
from typing import List, Optional

import numpy as np

ARRIVALS = ("closed", "poisson", "bursty")

# rate-curve shapes layered on the poisson process (serve/autoscaler.py's
# traffic fixtures); see _shape_factor for the exact curves
SHAPES = ("diurnal", "ramp", "spike")


TIERS = ("interactive", "batch")


@dataclasses.dataclass
class ServeRequest:
    """One serving request: a prompt to continue by ``max_new`` tokens."""

    rid: int
    prompt: np.ndarray  # [S] int32 token ids
    max_new: int
    # virtual arrival time; None for closed-loop (the driver stamps the
    # submission time when it releases the request)
    arrival: Optional[float] = None
    # absolute virtual-time completion deadline. None (the default) means
    # no deadline: the engine never sheds or times the request out, so
    # plain traffic behaves exactly as before deadlines existed. With a
    # deadline, admission control may SHED the request up front (projected
    # completion already past the deadline) and the engine cancels it into
    # the named ``timeout`` terminal state once the deadline passes.
    deadline: Optional[float] = None
    # SLO tier (ROADMAP 2c): "interactive" admits ahead of "batch", and
    # batch requests are the preferred eviction victims under pool
    # pressure (preemptible background lane riding eviction+recompute).
    # All-interactive traffic reduces to the pre-tier scheduler, bitwise.
    tier: str = "interactive"

    @property
    def prompt_len(self) -> int:
        return int(self.prompt.shape[0])


def _bounded_pareto(u: float, lo: int, hi: int, alpha: float) -> int:
    """Inverse-transform bounded Pareto draw on [lo, hi] from one uniform."""
    x = lo * (1.0 - u * (1.0 - (lo / hi) ** alpha)) ** (-1.0 / alpha)
    return max(lo, min(hi, int(x)))


def heavy_tail_length(rng: random.Random, lo: int, typical: int, hi: int,
                      tail_frac: float = 0.25, alpha: float = 1.2) -> int:
    """Mixture length: uniform [lo, typical] body, bounded-Pareto tail.

    With probability ``tail_frac`` the length is a Pareto(alpha) draw
    anchored at ``typical`` and clipped to ``hi`` — the few very long
    requests that dominate pool occupancy; otherwise uniform in the short
    body. lo <= result <= hi always.
    """
    if rng.random() < tail_frac and hi > typical:
        return _bounded_pareto(rng.random(), typical, hi, alpha)
    return lo + int(rng.random() * (typical - lo + 1))


def _shape_factor(shape: str, i: int, n: int) -> float:
    """Arrival-rate multiplier for request ``i`` of ``n`` under a traffic
    shape. Peak multiplier is 1.0 (so ``rate`` stays the peak rate and a
    shaped run never arrives faster than the plain poisson run at the
    same ``rate``); troughs bottom out at 0.15 to keep inter-arrivals
    finite. ``spike`` is the adversarial fixture: a 6.67x flash crowd
    over 15% of the run — steeper than one controller cooldown can
    track, which is exactly where the autoscaler loses (PERF.md)."""
    x = i / max(1, n - 1)
    if shape == "diurnal":
        # raised cosine: trough at both ends, peak mid-run
        return 0.15 + 0.85 * 0.5 * (1.0 - math.cos(2.0 * math.pi * x))
    if shape == "ramp":
        return 0.15 + 0.85 * x
    if shape == "spike":
        return 1.0 if 0.45 <= x < 0.60 else 0.15
    raise ValueError(f"shape must be one of {SHAPES}, got {shape!r}")


def make_workload(*, seed: int, n_requests: int, vocab: int,
                  arrival: str = "poisson", rate: float = 0.5,
                  shape: Optional[str] = None,
                  burst_size: int = 8, burst_factor: float = 4.0,
                  prompt_lo: int = 4, prompt_typical: int = 16,
                  prompt_hi: int = 64, out_lo: int = 2, out_typical: int = 16,
                  out_hi: int = 64, tail_frac: float = 0.25,
                  prefix_groups: int = 0, prefix_len: int = 0,
                  max_len: Optional[int] = None,
                  deadline_slack: Optional[float] = None,
                  batch_frac: float = 0.0) -> List[ServeRequest]:
    """Synthesize a deterministic request list for one benchmark run.

    ``max_len`` (the engine's stream capacity) caps prompt + output: the
    prompt is clipped to ``max_len - out_lo`` and the output to the
    remaining room, so every generated request is admissible.

    SHARED-PREFIX traffic (``prefix_groups > 0``): the "hundreds of users
    behind N system prompts" shape that prefix caching exists for. The
    generator draws ``prefix_groups`` fixed prefixes of ``prefix_len``
    tokens up front; each request then picks a group uniformly and its
    prompt is that group's prefix followed by a per-request unique tail
    whose length comes from the SAME bounded-Pareto mixture as plain
    traffic (the heavy tail rides on top of the shared head). Orthogonal
    to the arrival process — any of closed/poisson/bursty composes.

    DEADLINES (``deadline_slack``): every open-loop request gets
    ``deadline = arrival + deadline_slack`` (a flat virtual-time budget —
    long requests really are harder to meet, which is the shed-vs-timeout
    tradeoff the chaos harness measures). Closed-loop requests have no
    arrival until the driver releases them, so the driver stamps
    ``deadline = release + slack`` itself (servebench/servechaos do).

    SLO TIERS (``batch_frac``): each request is drawn "batch" with this
    probability from a SEPARATE seeded stream (``Random(f"{seed}:tier")``
    — string seeding is SHA-512, platform-stable), so the tier mix bolts
    onto the SAME prompts/arrivals as the untiered workload, bitwise: the
    tiered-vs-plain A/B differs only in the labels. Interactive traffic
    admits ahead of batch and batch is the preemptible lane
    (serve/engine.py).

    TRAFFIC SHAPES (``shape``, poisson only): the inter-arrival draw moves
    to its own ``Random(f"{seed}:shape")`` stream and is scaled by the
    shape's rate curve (``_shape_factor``). Because the main stream stops
    drawing arrivals entirely, prompts/lengths are bitwise-identical
    across all three shape values at a fixed seed — the property the
    autoscaler A/B pins ride on.
    """
    if arrival not in ARRIVALS:
        raise ValueError(f"arrival must be one of {ARRIVALS}, got {arrival!r}")
    if shape is not None:
        if shape not in SHAPES:
            raise ValueError(
                f"shape must be one of {SHAPES}, got {shape!r}")
        if arrival != "poisson":
            raise ValueError(
                "traffic shapes modulate the poisson process; "
                f"pass arrival='poisson' (got {arrival!r})")
    if prefix_groups < 0 or prefix_len < 0:
        raise ValueError("prefix_groups and prefix_len must be >= 0")
    if deadline_slack is not None and deadline_slack <= 0:
        raise ValueError(
            f"deadline_slack must be > 0 time units, got {deadline_slack}")
    if not 0.0 <= batch_frac <= 1.0:
        raise ValueError(
            f"batch_frac is a probability in [0, 1], got {batch_frac}")
    if bool(prefix_groups) != bool(prefix_len):
        raise ValueError("shared-prefix traffic needs BOTH prefix_groups "
                         "and prefix_len (> 0)")
    if max_len is not None and prefix_len > max_len - out_lo - 1:
        raise ValueError(
            f"prefix_len {prefix_len} leaves no room for a tail + output "
            f"within max_len {max_len}")
    rng = random.Random(seed)
    # tiers ride their own stream so a tier-mix A/B keeps the exact same
    # prompts/arrivals (and batch_frac=0 consumes nothing anywhere)
    trng = random.Random(f"{seed}:tier")
    # shaped arrivals likewise ride their own stream (shape=None consumes
    # nothing from it), so the prompt/length draws below are untouched
    srng = random.Random(f"{seed}:shape")
    prefixes = [
        np.array([rng.randrange(vocab) for _ in range(prefix_len)], np.int32)
        for _ in range(prefix_groups)
    ]
    reqs: List[ServeRequest] = []
    t = 0.0
    for i in range(n_requests):
        s = heavy_tail_length(rng, prompt_lo, prompt_typical, prompt_hi,
                              tail_frac)
        m = heavy_tail_length(rng, out_lo, out_typical, out_hi, tail_frac)
        if prefix_groups:
            # the drawn length becomes the TAIL length (>= 1 so every
            # prompt diverges from its siblings after the shared head)
            group = rng.randrange(prefix_groups)
            s = max(1, s)
            if max_len is not None:
                s = max(1, min(s, max_len - out_lo - prefix_len))
            tail = np.array([rng.randrange(vocab) for _ in range(s)],
                            np.int32)
            prompt = np.concatenate([prefixes[group], tail])
            s = int(prompt.shape[0])
            if max_len is not None:
                m = min(m, max_len - s)
        else:
            if max_len is not None:
                s = min(s, max_len - out_lo)
                m = min(m, max_len - s)
            prompt = np.array(
                [rng.randrange(vocab) for _ in range(s)], np.int32)
        when: Optional[float] = None
        if arrival == "poisson":
            if shape is not None:
                r = rate * _shape_factor(shape, i, n_requests)
                t += -math.log(1.0 - srng.random()) / r
            else:
                t += -math.log(1.0 - rng.random()) / rate
            when = t
        elif arrival == "bursty":
            in_burst = (i // burst_size) % 2 == 0
            r = rate * burst_factor if in_burst else rate / burst_factor
            t += -math.log(1.0 - rng.random()) / r
            when = t
        tier = "interactive"
        if batch_frac and trng.random() < batch_frac:
            tier = "batch"
        deadline = (when + deadline_slack
                    if deadline_slack is not None and when is not None
                    else None)
        reqs.append(ServeRequest(rid=i, prompt=prompt, max_new=m,
                                 arrival=when, deadline=deadline, tier=tier))
    return reqs
