"""Self-healing SLO autoscaler: the closed control loop over the serving
fleet (ROADMAP item 2, dynamic half).

PRs 11-16 built every sensor (windowed SLO attainment, shed/timeout
counters, heartbeat expiries, flight-recorder ledgers) and every actuator
(``ReplicatedServer.resize()``, fail/stall/drain, disaggregated fleets),
but an operator still had to watch the timeline and call ``resize()`` by
hand, and a chaos-killed replica stayed dead until a test script said
otherwise. :class:`FleetController` closes the loop:

    sense   — an incremental online form of ``telemetry/serveview.py``'s
              windowed attainment/goodput reducer (:class:`OnlineTimeline`
              — same tumbling buckets, same ``request_slo_ok`` predicate,
              fed one finished record at a time instead of reducing a
              trace post-hoc), plus live fleet state (queue depth, worst
              occupancy) and the shed/timeout counter deltas per window.
    decide  — a PURE function of (window signal, policy): hysteresis
              bands suppress flapping, per-direction cooldowns block
              back-to-back actuations, min/max clamps bound the fleet,
              and a bounded actuation budget degrades gracefully — the
              named ``budget_exhausted`` ledger event fires once and the
              fleet keeps serving at its current size.
    actuate — the EXISTING surfaces only: ``resize(n +/- 1)`` for
              scale-up/down, and AUTO-REPAIR — a dead (``fail_events``)
              or heartbeat-drained (``heartbeat_events``) replica is
              replaced through the same engine-factory spawn resize grow
              uses (shared jitted callables, zero new compiles), so MTTR
              becomes a controller property instead of a test-script
              property. Repair is NOT a scale decision: it consumes
              budget but neither consults nor arms the scale cooldowns
              (capacity the policy already chose is being restored, not
              changed).

Everything runs inside the drivers' virtual clock (1 unit = 1 model
pass): ``advance(now)`` is called by servebench's open/closed-loop
drivers after every global step and idle jump, so every decision lands
at a deterministic virtual instant and the whole trajectory — sizes,
events, token streams — is bitwise-reproducible per seed, the same repro
discipline as every other tool. Each actuation also emits an
``autoscale:*`` trace instant carrying the triggering signal snapshot
(``telemetry/export.autoscale_decisions`` reads them back), so every
resize in a trace answers "why".

Repair exactly-once: the controller consumes the fail/heartbeat ledgers
by index — an expiry that spans two observation windows is still ONE
ledger entry, so it can never double-spawn.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, List, Optional

from ddlbench_tpu.telemetry.stats import request_slo_ok
from ddlbench_tpu.telemetry.tracer import get_tracer


def _vns(t: float) -> int:
    """Virtual model-pass time -> integer trace-ns (the serve engine's
    1-pass = 1000-trace-ns stamping convention, kept import-cycle-free)."""
    return int(round(t * 1000.0))


@dataclasses.dataclass(frozen=True)
class AutoscalePolicy:
    """The controller's policy config — with the window signal, the ONLY
    inputs to :func:`decide` (pure function => pinnable trajectories).

    The hysteresis band is ``[attain_lo, attain_hi]``: windows whose
    attainment falls inside it (with no shed/timeout/queue pressure and
    no idle-fleet slack) actuate NOTHING, so an oscillating signal that
    stays in the band cannot flap the fleet.
    """

    lo: int                      # min replicas (clamp floor)
    hi: int                      # max replicas (clamp ceiling)
    window: float = 32.0         # observation window (virtual units)
    cooldown_up: float = 64.0    # min time between scale-UPS
    cooldown_down: float = 64.0  # min time between scale-DOWNS
    attain_lo: float = 0.9       # window attainment below this = pressure
    attain_hi: float = 0.98      # at/above this (idle fleet) = slack
    queue_hi: float = 1.0        # queued reqs per replica that alone = pressure
    occ_lo: float = 0.5          # worst-replica occupancy under this = idle
    budget: int = 16             # total actuations (scales + repairs)

    def __post_init__(self):
        if self.lo < 1 or self.hi < self.lo:
            raise ValueError(
                f"autoscale clamps need 1 <= lo <= hi, got {self.lo}:{self.hi}")
        if self.window <= 0:
            raise ValueError(f"window must be > 0, got {self.window}")
        if self.cooldown_up < 0 or self.cooldown_down < 0:
            raise ValueError("cooldowns must be >= 0")
        if not 0.0 <= self.attain_lo <= self.attain_hi <= 1.0:
            raise ValueError(
                f"hysteresis band needs 0 <= attain_lo <= attain_hi <= 1, "
                f"got [{self.attain_lo}, {self.attain_hi}]")
        if self.budget < 1:
            raise ValueError(f"budget must be >= 1, got {self.budget}")


@dataclasses.dataclass(frozen=True)
class WindowSignal:
    """One closed observation window, as the decide() input: the online
    timeline bucket (attainment/goodput — serveview's definitions) plus
    the live pressure signals the post-hoc reducer cannot see."""

    t0: float
    t1: float
    completed: int
    slo_ok: int
    attainment: float
    tokens: int
    good_tokens: int
    goodput_tokens_per_unit: float
    shed: int           # shed-counter DELTA inside this window
    timeouts: int       # timeout-counter delta inside this window
    queue_depth: int    # live, at window close
    active: int         # live in-flight, at window close
    occupancy: float    # live worst-replica pool occupancy, at window close
    replicas: int       # fleet size at window close


def decide(sig: WindowSignal, policy: AutoscalePolicy) -> Optional[str]:
    """The pure decision: ``"up"`` / ``"down"`` / ``None`` from ONE window
    signal and the policy — no controller state, no clocks (cooldowns and
    budget are the controller's job, so this stays a pinnable function).

    Pressure (any of): attainment below the band on a window that
    completed work, a shed or timeout inside the window, or queue depth
    above ``queue_hi`` per replica. Slack (all of): empty queue, worst
    occupancy under ``occ_lo``, and attainment at/above the band (an
    all-idle window — nothing completed, nothing queued — is slack too:
    that is the diurnal trough). In between: the hysteresis dead band.
    """
    if sig.replicas < policy.lo:
        return "up"      # below the floor (initial size, over-shrunk fleet)
    if sig.replicas > policy.hi:
        return "down"
    pressure = ((sig.completed > 0 and sig.attainment < policy.attain_lo)
                or sig.shed > 0 or sig.timeouts > 0
                or sig.queue_depth > policy.queue_hi * sig.replicas)
    if pressure:
        return "up" if sig.replicas < policy.hi else None  # clamped at hi
    slack = (sig.queue_depth == 0 and sig.occupancy < policy.occ_lo
             and (sig.completed == 0
                  or sig.attainment >= policy.attain_hi))
    if slack and sig.replicas > policy.lo:                 # clamped at lo
        return "down"
    return None


class OnlineTimeline:
    """``telemetry/serveview.timeline`` hoisted into an incremental
    online form: the same tumbling ``[k*W, (k+1)*W)`` buckets with the
    same attainment/goodput definitions and the same
    ``telemetry/stats.request_slo_ok`` predicate — but fed one finished
    record at a time (``add``) and closed at exact window boundaries
    (``close``), so a controller inside the run reads the signal the
    post-hoc reducer would have computed, without a trace. The one field
    the online form drops is ``submitted`` (a driver-side event the
    fleet's finished records cannot carry); the controller reads live
    queue depth instead, which is the stronger leading signal anyway."""

    def __init__(self, window: float, slo_ttft: Optional[float] = None,
                 slo_itl: Optional[float] = None):
        if window <= 0:
            raise ValueError(f"window must be > 0, got {window}")
        self.window = float(window)
        self.slo_ttft = slo_ttft
        self.slo_itl = slo_itl
        self.closed: List[Dict[str, Any]] = []
        self.completed_total = 0
        self.slo_ok_total = 0
        self._open: Dict[int, Dict[str, Any]] = {}  # bucket index -> partial

    def _fresh(self, k: int) -> Dict[str, Any]:
        return {"t0": k * self.window, "t1": (k + 1) * self.window,
                "completed": 0, "slo_ok": 0, "attainment": 0.0,
                "tokens": 0, "good_tokens": 0,
                "goodput_tokens_per_unit": 0.0}

    def add(self, rec: Dict[str, Any]) -> None:
        """Ingest one engine finished record (arrival / first_token_t /
        token_times / n_tokens / completed_t — serve/engine.py's shape)."""
        k = int(rec["completed_t"] // self.window)
        b = self._open.setdefault(k, self._fresh(k))
        n_tok = int(rec["n_tokens"])
        b["completed"] += 1
        b["tokens"] += n_tok
        self.completed_total += 1
        if request_slo_ok(rec, self.slo_ttft, self.slo_itl):
            b["slo_ok"] += 1
            b["good_tokens"] += n_tok
            self.slo_ok_total += 1

    def close(self, k: int) -> Dict[str, Any]:
        """Finalize bucket ``k`` (attainment + goodput, serveview's
        formulas; an untouched bucket closes as the all-zero row, keeping
        the series continuous through idle troughs)."""
        b = self._open.pop(k, None) or self._fresh(k)
        b["attainment"] = (b["slo_ok"] / b["completed"]
                           if b["completed"] else 0.0)
        b["goodput_tokens_per_unit"] = b["good_tokens"] / self.window
        self.closed.append(b)
        return b

    @property
    def attainment(self) -> float:
        """Overall online attainment across every ingested record — the
        controller's ``autoscale_attainment`` row figure."""
        return (self.slo_ok_total / self.completed_total
                if self.completed_total else 0.0)


class FleetController:
    """The closed loop over ONE ReplicatedServer (the disaggregated
    server runs one per fleet — ``DisaggregatedServer.controllers``).

    Drivers call :meth:`advance` with the virtual clock after every
    global step and idle jump; the controller integrates replica-hours,
    ingests newly-finished records into the online timeline, repairs any
    newly-ledgered replica death/drain, and — at each window boundary
    crossed — closes the window and runs :func:`decide` under the
    cooldown/budget gates. Pure function of (signal stream, policy):
    identical traffic + policy => identical event ledger, bitwise.
    """

    def __init__(self, server, policy: AutoscalePolicy, *,
                 name: str = "fleet", start: float = 0.0):
        self.server = server
        self.policy = policy
        self.name = name
        cfg = server.engines[0].cfg
        self.timeline = OnlineTimeline(policy.window,
                                       slo_ttft=cfg.slo_ttft or None,
                                       slo_itl=cfg.slo_itl or None)
        self.events: List[Dict[str, Any]] = []  # the decision ledger
        self.replica_hours = 0.0  # integral of fleet size over virtual time
        self.scale_ups = 0
        self.scale_downs = 0
        self.repairs = 0
        self.suppressed = 0       # decisions blocked by cooldown/exhaustion
        self._t = float(start)
        self._start = float(start)
        self._windows_closed = 0
        self._last_up: Optional[float] = None
        self._last_down: Optional[float] = None
        self._budget_left = policy.budget
        self._exhausted = False
        self._seen_rids: set = set()
        self._seen_fail = 0
        self._seen_drain = 0
        self._prev_shed = 0
        self._prev_timeouts = 0

    # -- the driver hook ---------------------------------------------------

    def advance(self, now: float) -> None:
        """Advance the controller's clock to ``now`` (monotone): integrate
        replica-hours at the size that did the work, ingest completions,
        repair ledgered deaths, and fire every window boundary crossed."""
        if now > self._t:
            self.replica_hours += len(self.server.engines) * (now - self._t)
            self._t = now
        self._ingest()
        self._check_repairs(now)
        while self._next_boundary() <= now:
            t1 = self._next_boundary()
            self._decide_window(t1)
            self._windows_closed += 1

    def _next_boundary(self) -> float:
        # multiplication, not accumulation: boundary k is EXACTLY
        # start + (k+1)*window, so float drift can never skew the grid
        return self._start + (self._windows_closed + 1) * self.policy.window

    # -- sense -------------------------------------------------------------

    def _ingest(self) -> None:
        for rec in self.server.finished:
            if rec["rid"] in self._seen_rids:
                continue
            self._seen_rids.add(rec["rid"])
            self.timeline.add(rec)

    def _signal(self, t1: float) -> WindowSignal:
        b = self.timeline.close(self._windows_closed)
        s = self.server.stats_summary()
        shed, timeouts = int(s.get("shed", 0)), int(s.get("timeouts", 0))
        d_shed, d_to = shed - self._prev_shed, timeouts - self._prev_timeouts
        self._prev_shed, self._prev_timeouts = shed, timeouts
        snap = self.server.snapshot()
        return WindowSignal(
            t0=b["t0"], t1=b["t1"], completed=b["completed"],
            slo_ok=b["slo_ok"], attainment=b["attainment"],
            tokens=b["tokens"], good_tokens=b["good_tokens"],
            goodput_tokens_per_unit=b["goodput_tokens_per_unit"],
            shed=d_shed, timeouts=d_to,
            queue_depth=int(snap["queue_depth"]),
            active=int(snap["active"]),
            occupancy=float(snap["occupancy"]),
            replicas=len(self.server.engines))

    # -- actuate -----------------------------------------------------------

    def _record(self, ev: Dict[str, Any]) -> None:
        self.events.append(ev)
        tr = get_tracer()
        if tr.enabled:
            # the decision instant, on its own synthetic track, with the
            # triggering signal attached — every actuation answers "why"
            tr.emit("i", f"autoscale:{ev['event']}", _vns(ev["t"]),
                    track=f"autoscale/{self.name}", args=dict(ev))

    def _spend(self, t: float, wanted: str) -> bool:
        """Take one actuation from the budget; on exhaustion emit the
        named ``budget_exhausted`` ledger event ONCE and refuse — the
        fleet keeps serving at its current size (graceful degradation,
        never an exception mid-run)."""
        if self._budget_left > 0:
            self._budget_left -= 1
            return True
        if not self._exhausted:
            self._exhausted = True
            self._record({"t": t, "event": "budget_exhausted",
                          "fleet": self.name, "wanted": wanted,
                          "replicas": len(self.server.engines)})
        else:
            self.suppressed += 1
        return False

    def _check_repairs(self, now: float) -> None:
        """AUTO-REPAIR: every not-yet-consumed fail/heartbeat ledger entry
        is one dead/stalled replica to replace through the factory spawn
        ``resize`` grow uses. Ledger entries are consumed BY INDEX, so a
        death observed across two windows still repairs exactly once."""
        fails = self.server.fail_events
        drains = self.server.heartbeat_events
        pending = ([("fail", ev) for ev in fails[self._seen_fail:]]
                   + [("heartbeat", ev) for ev in drains[self._seen_drain:]])
        self._seen_fail = len(fails)
        self._seen_drain = len(drains)
        for trigger, ev in pending:
            n0 = len(self.server.engines)
            target = min(self.policy.hi, n0 + 1)
            if target == n0:
                continue  # already at the ceiling: the policy's capacity
            if not self._spend(now, "repair"):
                continue
            self.server.resize(target, now)
            self.repairs += 1
            self._record({"t": now, "event": "repair", "fleet": self.name,
                          "trigger": trigger,
                          "replica_id": ev["replica_id"],
                          "from": n0, "to": target,
                          "budget_left": self._budget_left})

    def _decide_window(self, t1: float) -> None:
        sig = self._signal(t1)
        action = decide(sig, self.policy)
        if action == "up" and self._last_up is not None \
                and t1 - self._last_up < self.policy.cooldown_up:
            self.suppressed += 1
            return
        if action == "down" and self._last_down is not None \
                and t1 - self._last_down < self.policy.cooldown_down:
            self.suppressed += 1
            return
        if action is None:
            return
        if not self._spend(t1, f"scale_{action}"):
            return
        n0 = len(self.server.engines)
        target = n0 + 1 if action == "up" else n0 - 1
        self.server.resize(target, t1)
        if action == "up":
            self.scale_ups += 1
            self._last_up = t1
        else:
            self.scale_downs += 1
            self._last_down = t1
        self._record({"t": t1, "event": f"scale_{action}",
                      "fleet": self.name, "from": n0, "to": target,
                      "budget_left": self._budget_left,
                      "signal": dataclasses.asdict(sig)})

    # -- row figures -------------------------------------------------------

    @property
    def scale_events(self) -> int:
        return self.scale_ups + self.scale_downs

    @property
    def attainment(self) -> float:
        return self.timeline.attainment


def make_controllers(server, policy: AutoscalePolicy,
                     start: float = 0.0) -> List[FleetController]:
    """Controllers for any driver-compatible server: one for an
    aggregated ReplicatedServer, one PER FLEET for a disaggregated
    server (``DisaggregatedServer.controllers`` — prefill and decode
    scale independently, each clamped to the same [lo, hi] band)."""
    if hasattr(server, "controllers"):
        return server.controllers(policy, start=start)
    return [FleetController(server, policy, start=start)]


def combined_attainment(controllers: List[FleetController]) -> float:
    """Overall online attainment across a controller set (for the
    disaggregated layout, completions land on the decode fleet's
    controller; the totals union is the fleet-wide figure)."""
    ok = sum(c.timeline.slo_ok_total for c in controllers)
    done = sum(c.timeline.completed_total for c in controllers)
    return ok / done if done else 0.0


def replica_hours(controllers: List[FleetController]) -> float:
    """Total replica-hours (virtual units x replicas) across fleets —
    the headline economics figure: the static-max baseline pays
    ``replicas * duration``; the autoscaler's integral is what it
    actually used. ``math.fsum`` keeps the sum order-independent."""
    return math.fsum(c.replica_hours for c in controllers)
