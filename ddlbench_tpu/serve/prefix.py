"""Cross-request prefix cache: a host-side index over page-aligned prompt
blocks (the PagedAttention/COW lineage of Kwon et al., SOSP'23, applied to
the admission hot path).

Hundreds of requests sharing a system-prompt prefix each re-prefill the
same tokens through the same weights — pure redundant FLOPs on the path
that determines TTFT. This index lets a newly admitted request CLAIM the
already-resident immutable KV pages of its longest cached prefix instead:
the engine binds those pool slots straight into the request's table row
(allocator refcounts make the sharing safe) and chunk-prefills only the
uncached tail.

Structure: one entry per fully-prefilled PAGE of a prompt, keyed by the
exact bytes of the prompt up to and including that page —
``prompt[: (b + 1) * page].tobytes()`` — so a key identifies both the
block's content AND its whole left context (a hash-chain with zero
collision risk; prompts at benchmark scale make the O(prefix) key cost
irrelevant). ``match`` walks keys block by block and stops at the first
miss, which is exactly the longest-cached-prefix semantics a trie would
give.

Residency: the index holds its own allocator reference (``incref``) on
every page it caches, so a completed request's prompt pages survive the
request. Under pool pressure the engine reclaims the cache before evicting
live requests — ``reclaim`` drops entries newest-registered-first (the
same newest-first rule as request eviction) and only ever frees pages
whose sole remaining reference is the cache itself, i.e. refcount-0 from
any live request's point of view; pages bound by in-flight requests are
skipped (dropping their entry would lose the cache hit without freeing a
byte). Children (longer prefixes) are always registered after their
parents, so newest-first reclaim can never strand an unreachable chain
suffix.

Immutability: only pages every byte of which is prompt content get
registered — a page that will still receive decode writes (the partial
tail page of an unaligned prompt) never enters the index, and the engine
copy-on-writes before its one write into a bound page (the full-hit fast
path). See the shared-pool contract in ops/paged_decode.py.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np

from ddlbench_tpu.serve.allocator import PageAllocator


def _block_key(prompt: np.ndarray, block: int, page: int) -> bytes:
    """Key of prompt block ``block``: the exact token bytes of the whole
    prefix through that block (content + left context in one key)."""
    return np.ascontiguousarray(
        prompt[: (block + 1) * page], dtype=np.int32).tobytes()


class PrefixIndex:
    """Host-side prefix index over one engine's shared pool."""

    def __init__(self, allocator: PageAllocator, page: int):
        self.allocator = allocator
        self.page = int(page)
        # block key -> pool slot; dict insertion order IS registration
        # order (children always register after their parents), which is
        # all reclaim's newest-first walk needs
        self._slots: Dict[bytes, int] = {}
        self.lookups = 0
        self.hit_blocks = 0
        self.reclaimed = 0
        # optional (name, **args) sink for hit/reclaim instants — wired by
        # the engine to the virtual-time tracer when cfg.trace is on (same
        # hook discipline as PageAllocator.on_event)
        self.on_event: Optional[Callable[..., None]] = None

    def __len__(self) -> int:
        return len(self._slots)

    def match(self, prompt: np.ndarray) -> List[int]:
        """Pool slots of the longest cached prefix of ``prompt`` (leading
        full pages only), in block order. Empty list = miss."""
        self.lookups += 1
        slots: List[int] = []
        for b in range(len(prompt) // self.page):
            slot = self._slots.get(_block_key(prompt, b, self.page))
            if slot is None:
                break
            slots.append(slot)
        self.hit_blocks += len(slots)
        if slots and self.on_event is not None:
            self.on_event("prefix_hit", blocks=len(slots),
                          tokens=len(slots) * self.page)
        return slots

    def register(self, prompt: np.ndarray, block: int, slot: int) -> bool:
        """Index ``slot`` as holding block ``block`` of ``prompt``; the
        index takes its own reference so the page outlives the request.
        Returns False (and takes nothing) if the key is already cached —
        two requests racing the same prefix keep the first copy, and the
        second's page stays private to it."""
        key = _block_key(prompt, block, self.page)
        if key in self._slots:
            return False
        self.allocator.incref(slot)
        self._slots[key] = slot
        return True

    def reclaim(self, n_pages: int) -> int:
        """Free up to ``n_pages`` pool pages by dropping cache entries,
        newest-registered-first, skipping entries some live request still
        has bound (their pages would not free anyway and the hit would be
        lost for nothing). Returns how many pages were actually freed."""
        freed = 0
        for key in list(reversed(self._slots)):
            if freed >= n_pages:
                break
            slot = self._slots[key]
            if self.allocator.refcount(slot) != 1:
                continue  # a live request still holds this page
            del self._slots[key]
            self.allocator.decref(slot)
            self.reclaimed += 1
            freed += 1
        if self.on_event is not None:
            self.on_event("prefix_reclaim", asked=n_pages, freed=freed,
                          entries=len(self._slots))
        return freed

    def drop_slot(self, slot: int) -> int:
        """Purge the entry (at most one — a slot appears in the index at
        most once) mapping to pool ``slot`` and drop the index's
        reference, regardless of other holders: the SDC quarantine path,
        where the page's CONTENT is bad and must never be hit again.
        Returns how many entries were purged (0 or 1)."""
        dead = [k for k, s in self._slots.items() if s == slot]
        for key in dead:
            del self._slots[key]
            self.allocator.decref(slot)
        if dead and self.on_event is not None:
            self.on_event("prefix_drop", slot=slot, entries=len(self._slots))
        return len(dead)

    def drop_all(self) -> int:
        """Release every entry the cache can release (shutdown/tests)."""
        return self.reclaim(len(self._slots))
