"""Profile-graph IR: a text-serializable weighted DAG of model layers.

Capability parity with the reference's graph IR
(pipedream-fork/graph/graph.py): nodes carry per-layer forward/backward compute
times, activation and parameter sizes, and an optional stage_id; the graph
supports topological sort, predecessor/successor queries, antichain-DAG
construction (the partitioner's state space, graph.py:350-449), partitioning by
stage_id (:117-137), and a text round-trip (:451-480) kept line-compatible with
the reference's ``graph.txt`` format so its downstream tooling could parse our
profiles:

    node{id} -- {desc} -- forward_compute_time={f}, backward_compute_time={b},
        activation_size={a}, parameter_size={p}[ -- stage_id={s}]
    \\tnode{src} -- node{dst}

(one line per node, one tab-prefixed line per edge).

In this framework models are flat layer chains by construction
(models/layers.py), so profile graphs are chains and every maximal antichain is
a singleton; the general-DAG algorithms are kept because the IR is also the
import path for externally produced graphs (e.g. the reference's own fixtures).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple


@dataclasses.dataclass
class Node:
    node_id: str
    node_desc: str
    forward_compute_time: float = 0.0  # ms
    backward_compute_time: float = 0.0  # ms
    activation_size: float = 0.0  # bytes (output activation)
    parameter_size: float = 0.0  # bytes
    stage_id: Optional[int] = None
    # longest-path annotations, filled by populate_depths/populate_heights
    depth: Optional[int] = None
    height: Optional[int] = None

    def __str__(self) -> str:
        s = (
            f"node{self.node_id} -- {self.node_desc} -- "
            f"forward_compute_time={self.forward_compute_time:.3f}, "
            f"backward_compute_time={self.backward_compute_time:.3f}, "
            f"activation_size={self.activation_size:.3f}, "
            f"parameter_size={self.parameter_size:.3f}"
        )
        if self.stage_id is not None:
            s += f" -- stage_id={self.stage_id}"
        return s

    # the reference's small test fixtures (graph/test_graphs/test*.txt) omit
    # the "node" id prefix; accept both spellings
    _LINE_RE = re.compile(
        r"(?:node)?(?P<id>\S+) -- (?P<desc>.*) -- "
        r"forward_compute_time=(?P<f>[-\d.e]+), "
        r"backward_compute_time=(?P<b>[-\d.e]+), "
        r"activation_size=(?P<a>[-\d.e+]+), "
        r"parameter_size=(?P<p>[-\d.e+]+?)"
        r"(?: -- stage_id=(?P<stage>\d+))?$"
    )

    @classmethod
    def from_str(cls, line: str) -> "Node":
        m = cls._LINE_RE.match(line.strip())
        if not m:
            raise ValueError(f"unparseable node line: {line!r}")
        return cls(
            node_id=m.group("id"),
            node_desc=m.group("desc"),
            forward_compute_time=float(m.group("f")),
            backward_compute_time=float(m.group("b")),
            activation_size=float(m.group("a")),
            parameter_size=float(m.group("p")),
            stage_id=int(m.group("stage")) if m.group("stage") else None,
        )


class Graph:
    def __init__(self):
        self.nodes: Dict[str, Node] = {}
        self.edges: Dict[str, List[str]] = {}  # node_id -> successor ids
        self.in_edges: Dict[str, List[str]] = {}  # node_id -> predecessor ids

    # -- construction ------------------------------------------------------

    def add_node(self, node: Node) -> None:
        self.nodes[node.node_id] = node
        self.edges.setdefault(node.node_id, [])
        self.in_edges.setdefault(node.node_id, [])

    def add_edge(self, src: str, dst: str) -> None:
        self.edges.setdefault(src, []).append(dst)
        self.in_edges.setdefault(dst, []).append(src)

    @classmethod
    def chain(cls, nodes: Sequence[Node]) -> "Graph":
        g = cls()
        for n in nodes:
            g.add_node(n)
        for a, b in zip(nodes, nodes[1:]):
            g.add_edge(a.node_id, b.node_id)
        return g

    # -- queries -----------------------------------------------------------

    def sources(self) -> List[Node]:
        return [self.nodes[i] for i in self.nodes if not self.in_edges.get(i)]

    def sinks(self) -> List[Node]:
        return [self.nodes[i] for i in self.nodes if not self.edges.get(i)]

    def topological_sort(self) -> List[Node]:
        indeg = {i: len(self.in_edges.get(i, [])) for i in self.nodes}
        # stable: seed with insertion order
        ready = [i for i in self.nodes if indeg[i] == 0]
        order: List[Node] = []
        while ready:
            i = ready.pop(0)
            order.append(self.nodes[i])
            for j in self.edges.get(i, []):
                indeg[j] -= 1
                if indeg[j] == 0:
                    ready.append(j)
        if len(order) != len(self.nodes):
            raise ValueError("graph has a cycle")
        return order

    def predecessors(self, node_id: str) -> Set[str]:
        """All transitive predecessors."""
        seen: Set[str] = set()
        stack = list(self.in_edges.get(node_id, []))
        while stack:
            i = stack.pop()
            if i not in seen:
                seen.add(i)
                stack.extend(self.in_edges.get(i, []))
        return seen

    def successors(self, node_id: str) -> Set[str]:
        seen: Set[str] = set()
        stack = list(self.edges.get(node_id, []))
        while stack:
            i = stack.pop()
            if i not in seen:
                seen.add(i)
                stack.extend(self.edges.get(i, []))
        return seen

    def is_chain(self) -> bool:
        return all(len(v) <= 1 for v in self.edges.values()) and all(
            len(v) <= 1 for v in self.in_edges.values()
        )

    # -- structure annotations / analyses ----------------------------------
    # Parity: reference graph.py populate_depths/populate_heights (:87-115),
    # is_series_parallel (:229-243), check_isomorphism (:275-289) — all
    # exercised by the reference's own graph/test.py:58-91. Re-derived here
    # over the topological order (one linear pass each) instead of the
    # reference's worklist propagation.

    def populate_depths(self) -> None:
        """node.depth = longest path length (in nodes) from a source; 1 at
        sources."""
        for n in self.topological_sort():
            preds = self.in_edges.get(n.node_id, [])
            n.depth = 1 + max(
                (self.nodes[p].depth for p in preds), default=0)

    def populate_heights(self) -> None:
        """node.height = longest path length (in nodes) to a sink; 1 at
        sinks."""
        for n in reversed(self.topological_sort()):
            succs = self.edges.get(n.node_id, [])
            n.height = 1 + max(
                (self.nodes[s].height for s in succs), default=0)

    def is_series_parallel(self) -> bool:
        """True iff the DAG reduces to a single source->sink edge under
        series-parallel reduction: repeatedly contract interior nodes with
        in-degree 1 and out-degree 1 (series step), merging the parallel
        edges that contraction creates (parallel step). Two-terminal SP
        graphs — and therefore any chain-of-blocks model profile — reduce to
        exactly 2 nodes; branchy non-SP graphs (e.g. NASNet cells) get stuck
        earlier."""
        out = {i: set(v) for i, v in self.edges.items()}
        inn = {i: set(v) for i, v in self.in_edges.items()}
        alive = set(self.nodes)
        changed = True
        while changed:
            changed = False
            for i in list(alive):
                if len(out.get(i, ())) == 1 and len(inn.get(i, ())) == 1:
                    (p,), (s,) = inn[i], out[i]
                    if p == s:  # would be a cycle; never true in a DAG
                        continue
                    alive.discard(i)
                    out[p].discard(i)
                    inn[s].discard(i)
                    out[p].add(s)  # set => duplicate edges merge
                    inn[s].add(p)
                    del out[i], inn[i]
                    changed = True
        if len(alive) != 2:
            return False
        a, b = alive
        return b in out.get(a, ()) or a in out.get(b, ())

    def _canonical_order(self) -> List[Node]:
        """Deterministic topological order keyed on (node_desc, height,
        degrees) — the alignment used by check_isomorphism. Ties among
        structurally identical nodes are harmless: any alignment of them
        satisfies the checked invariants."""
        self.populate_heights()
        import heapq

        indeg = {i: len(self.in_edges.get(i, [])) for i in self.nodes}
        key = {
            i: (n.node_desc, -(n.height or 0),
                len(self.edges.get(i, [])), len(self.in_edges.get(i, [])))
            for i, n in self.nodes.items()
        }
        heap = [(key[i], i) for i in self.nodes if indeg[i] == 0]
        heapq.heapify(heap)
        order: List[Node] = []
        while heap:
            _, i = heapq.heappop(heap)
            order.append(self.nodes[i])
            for j in self.edges.get(i, []):
                indeg[j] -= 1
                if indeg[j] == 0:
                    heapq.heappush(heap, (key[j], j))
        return order

    def check_isomorphism(self, other: "Graph") -> None:
        """Raise ValueError unless ``other`` aligns with this graph under the
        canonical order: same node count, and pairwise identical node_desc,
        out-degree and in-degree. Like the reference's check this is a
        canonical-ordering approximation (sound for profile graphs whose
        descs/heights discriminate), not a general isomorphism decision."""
        a = self._canonical_order()
        b = other._canonical_order()
        if len(a) != len(b):
            raise ValueError(
                f"node counts differ: {len(a)} vs {len(b)}")
        for na, nb in zip(a, b):
            if na.node_desc != nb.node_desc:
                raise ValueError(
                    f"desc mismatch: {na.node_id}:{na.node_desc!r} vs "
                    f"{nb.node_id}:{nb.node_desc!r}")
            da = (len(self.edges.get(na.node_id, [])),
                  len(self.in_edges.get(na.node_id, [])))
            db = (len(other.edges.get(nb.node_id, [])),
                  len(other.in_edges.get(nb.node_id, [])))
            if da != db:
                raise ValueError(
                    f"degree mismatch at {na.node_id} vs {nb.node_id}: "
                    f"{da} vs {db}")

    # -- antichain DAG (partitioner state space) ---------------------------

    def antichain_dag(self) -> Tuple[List[frozenset], Dict[frozenset, List[frozenset]]]:
        """States of the partitioning DP: each state is an antichain (a set of
        mutually incomparable nodes) representing a cut frontier; an edge moves
        the frontier forward past one node. Returns (states in topological
        order, adjacency). For chain graphs this is the chain of singletons.

        Functional analog of reference graph.py:399-449 (next_antichains /
        antichain_dag), computed as reachable frontier sets.
        """
        # An antichain A denotes the done-set D = A ∪ predecessors(A); moving to
        # the next state admits one node n ∉ D whose predecessors are all in D,
        # giving antichain {n} ∪ {a ∈ A : a ∉ predecessors(n)} (the maximal
        # elements of D ∪ {n}).
        pred_cache = {i: self.predecessors(i) for i in self.nodes}
        starts = [n.node_id for n in self.sources()]
        states: List[frozenset] = []
        adj: Dict[frozenset, List[frozenset]] = {}
        seen: Set[frozenset] = set()
        queue: List[frozenset] = []
        for s0 in starts:
            st = frozenset({s0})
            if st not in seen:
                seen.add(st)
                queue.append(st)
        while queue:
            st = queue.pop(0)
            states.append(st)
            adj[st] = []
            done = set(st)
            for i in st:
                done |= pred_cache[i]
            for n in sorted(self.nodes):
                if n in done:
                    continue
                if all(p in done for p in self.in_edges.get(n, [])):
                    nxt = frozenset({n} | {a for a in st if a not in pred_cache[n]})
                    adj[st].append(nxt)
                    if nxt not in seen:
                        seen.add(nxt)
                        queue.append(nxt)
        return states, adj

    # -- partitioning ------------------------------------------------------

    def partition(self) -> List["Graph"]:
        """Split into per-stage subgraphs by stage_id (reference graph.py:117-137)."""
        stage_ids = sorted(
            {n.stage_id for n in self.nodes.values() if n.stage_id is not None}
        )
        out = []
        for sid in stage_ids:
            sub = Graph()
            members = {i for i, n in self.nodes.items() if n.stage_id == sid}
            for i in members:
                sub.add_node(self.nodes[i])
            for i in members:
                for j in self.edges.get(i, []):
                    if j in members:
                        sub.add_edge(i, j)
            out.append(sub)
        return out

    # -- branch compression (reference graph.py:139-228 + aggregate/fidelity
    # :255-275; used by optimizer/scripts/compress_graph_branches.py to shrink
    # the antichain state space of branchy graphs before the partitioning DP).

    def aggregate(self, sum_activations: bool = False) -> List[float]:
        """[fwd_time, bwd_time, parameter_size, activation_size] totals.

        activation_size counts only source nodes unless ``sum_activations``
        (reference semantics: interior activations are transfer sizes, not
        resident memory).
        """
        f = sum(n.forward_compute_time for n in self.nodes.values())
        b = sum(n.backward_compute_time for n in self.nodes.values())
        p = sum(n.parameter_size for n in self.nodes.values())
        if sum_activations:
            a = sum(n.activation_size for n in self.nodes.values())
        else:
            a = sum(n.activation_size for n in self.sources())
        return [f, b, p, a]

    def check_fidelity(self, other: "Graph", tol: float = 1e-4) -> None:
        """Assert aggregate totals match ``other`` within ``tol`` (the
        compression-preserves-cost invariant)."""
        for mine, theirs in zip(self.aggregate(), other.aggregate()):
            if mine == theirs:
                continue
            assert theirs and abs(mine / theirs - 1.0) <= tol, (
                f"aggregate mismatch: {self.aggregate()} vs {other.aggregate()}"
            )

    def compress_branches(self) -> "Graph":
        """Merge each linear branch body hanging off a fork node into one
        aggregate node (summed compute times and parameter sizes; the last
        member's activation_size), shrinking the antichain-DAG state space of
        branchy graphs while preserving aggregate cost (check_fidelity).
        Join nodes (in-degree > 1) and pure-chain graphs come back unchanged.
        """
        new = Graph()
        mapping: Dict[str, str] = {}  # old id -> new (possibly merged) id
        counter = [0]

        def ensure(nid: str) -> str:
            if nid not in mapping:
                new.add_node(dataclasses.replace(self.nodes[nid]))
                mapping[nid] = nid
            return mapping[nid]

        def compress_from(nid: str):
            """Merge the maximal run starting at nid (1-in/1-out interior; a
            trailing sink/fork is folded in; a join ends the run before it).
            Returns (merged_new_id or None, last old id of the run)."""
            if len(self.in_edges.get(nid, [])) > 1:
                return None, nid  # join node: never merged
            run = []
            cur = nid
            while True:
                run.append(cur)
                outs = self.edges.get(cur, [])
                if len(outs) != 1:
                    break  # sink or fork terminates the run (folded in)
                if len(self.in_edges.get(outs[0], [])) > 1:
                    break  # next node is a join: run ends before it
                cur = outs[0]
            if len(run) == 1:
                return None, nid
            merged = Node(f"compressed_node{counter[0]}",
                          node_desc=f"Branch {counter[0]}")
            counter[0] += 1
            for rid in run:
                n = self.nodes[rid]
                merged.forward_compute_time += n.forward_compute_time
                merged.backward_compute_time += n.backward_compute_time
                merged.parameter_size += n.parameter_size
                merged.activation_size = n.activation_size
            if len(run) == 2:
                merged.node_desc = self.nodes[run[-1]].node_desc
            new.add_node(merged)
            for rid in run:
                mapping[rid] = merged.node_id
            return merged.node_id, run[-1]

        seen: Set[str] = set()
        queue = [n.node_id for n in self.sources()]
        while queue:
            nid = queue.pop(0)
            if nid in seen:
                continue
            seen.add(nid)
            outs = list(self.edges.get(nid, []))
            if len(outs) > 1:
                src = ensure(nid)
                for o in outs:
                    cid, last = compress_from(o)
                    if cid is None:
                        new.add_edge(src, ensure(o))
                        queue.append(o)
                    else:
                        new.add_edge(src, cid)
                        queue.append(last)
            else:
                src = ensure(nid)
                for o in outs:
                    dst = ensure(o)
                    if dst != src:
                        new.add_edge(src, dst)
                    queue.append(o)
        return new

    @classmethod
    def from_profile_csv(cls, path: str) -> "Graph":
        """Build a chain graph from a per-layer profile CSV (the import path
        of optimizer/scripts/convert_profiles_to_graphs.py + utils.py
        parse_profile_file_to_graph).

        Expected columns: "Layer Type", "Total time" (summed over the N
        minibatches named by a "Forward pass time (N)" column), "Output Size"
        and "Parameter Size (floats)" (floats, 4 bytes each). The upstream
        script passes a ``compute_time`` kwarg its own Node no longer accepts
        (py2-era bit rot); here the per-layer time lands as a 1/3 : 2/3
        forward/backward split (the standard train-step ratio), documented
        deviation.
        """
        import csv as _csv

        g = cls()
        prev: Optional[str] = None
        with open(path) as f:
            rows = list(_csv.reader(f))
        if not rows:
            raise ValueError(f"{path}: empty profile CSV (expected a header "
                             "row with 'Total time' etc.)")
        header = rows[0]
        num_minibatches = 1
        for cell in header:
            if "Forward pass time" in cell:
                if "(" not in cell:
                    raise ValueError(
                        f"{path}: 'Forward pass time' header cell must name "
                        f"the minibatch count, e.g. 'Forward pass time (100)';"
                        f" got {cell!r}")
                num_minibatches = int(cell.split("(")[1].rstrip(")"))
        def col(row, name, default=0.0):
            for i, cell in enumerate(header):
                if name in cell:
                    return float(row[i].replace(",", "")) if row[i] else default
            return default
        for k, row in enumerate(rows[1:]):
            if not row:
                continue
            total_ms = col(row, "Total time") / num_minibatches * 1000.0
            node = Node(
                node_id=str(k),
                node_desc=row[header.index("Layer Type")]
                if "Layer Type" in header else f"layer{k}",
                forward_compute_time=total_ms / 3.0,
                backward_compute_time=total_ms * 2.0 / 3.0,
                activation_size=col(row, "Output Size") * 4.0,
                parameter_size=col(row, "Parameter Size (floats)") * 4.0,
            )
            g.add_node(node)
            if prev is not None:
                g.add_edge(prev, node.node_id)
            prev = node.node_id
        return g

    # -- serialization -----------------------------------------------------

    def __str__(self) -> str:
        lines = [str(n) for n in self.topological_sort()]
        for i in self.nodes:
            for j in self.edges.get(i, []):
                lines.append(f"\tnode{i} -- node{j}")
        return "\n".join(lines)

    @classmethod
    def from_str(cls, text: str) -> "Graph":
        g = cls()
        edge_re = re.compile(r"\s+(?:node)?(\S+) -- (?:node)?(\S+)")
        for line in text.splitlines():
            if not line.strip():
                continue
            if line.startswith("\t"):
                m = edge_re.match(line)
                if not m:
                    raise ValueError(f"unparseable edge line: {line!r}")
                g.add_edge(m.group(1), m.group(2))
            else:
                g.add_node(Node.from_str(line))
        return g

    # -- visualization -----------------------------------------------------
    # Parity: reference graph.py:482-499 (to_dot via the graphviz package) and
    # :501-615 (matplotlib CDF + bar plots). DOT source is emitted directly so
    # no graphviz runtime is required; plots gate on matplotlib import.

    def to_dot(self, path: Optional[str] = None) -> str:
        """Render as Graphviz DOT source; node labels carry the profile fields.

        Returns the DOT text; if ``path`` is given, also writes it there.
        """

        def esc(s: str) -> str:
            return s.replace("\\", "\\\\").replace('"', '\\"')

        lines = ["digraph {"]
        for n in self.topological_sort():
            label = (
                f"{esc(n.node_desc)}\\n"
                f"fwd={n.forward_compute_time:.3f}ms bwd={n.backward_compute_time:.3f}ms\\n"
                f"act={n.activation_size / 1e6:.2f}MB params={n.parameter_size / 1e6:.2f}MB"
            )
            if n.stage_id is not None:
                label += f"\\nstage={n.stage_id}"
            lines.append(f'  "node{esc(n.node_id)}" [label="{label}"];')
        for i in self.nodes:
            for j in self.edges.get(i, []):
                lines.append(f'  "node{esc(i)}" -> "node{esc(j)}";')
        lines.append("}")
        text = "\n".join(lines) + "\n"
        if path:
            with open(path, "w") as f:
                f.write(text)
        return text

    def plot_cdfs(self, path: str) -> None:
        """CDFs of per-node compute time, activation size, and parameter size
        (reference graph.py:501-557 render_bar_graphs_and_cdfs)."""
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        nodes = self.topological_sort()
        series = [
            ("compute time (ms)",
             sorted(n.forward_compute_time + n.backward_compute_time for n in nodes)),
            ("activation size (bytes)", sorted(n.activation_size for n in nodes)),
            ("parameter size (bytes)", sorted(n.parameter_size for n in nodes)),
        ]
        fig, axes = plt.subplots(1, 3, figsize=(15, 4))
        for ax, (label, xs) in zip(axes, series):
            total = sum(xs) or 1.0
            cum, ys = 0.0, []
            for v in xs:
                cum += v
                ys.append(100.0 * cum / total)
            ax.plot(range(len(xs)), ys)
            ax.set_xlabel("node index (sorted)")
            ax.set_ylabel("cumulative % of total")
            ax.set_title(label)
        fig.tight_layout()
        fig.savefig(path)
        plt.close(fig)

    def plot_bars(self, path: str) -> None:
        """Per-node bar charts in topological order (reference graph.py:559-615)."""
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        nodes = self.topological_sort()
        idx = range(len(nodes))
        fields = [
            ("fwd+bwd time (ms)",
             [n.forward_compute_time + n.backward_compute_time for n in nodes]),
            ("activation size (MB)", [n.activation_size / 1e6 for n in nodes]),
            ("parameter size (MB)", [n.parameter_size / 1e6 for n in nodes]),
        ]
        fig, axes = plt.subplots(3, 1, figsize=(max(8, len(nodes) * 0.25), 9))
        for ax, (label, ys) in zip(axes, fields):
            ax.bar(idx, ys)
            ax.set_ylabel(label)
        axes[-1].set_xlabel("node (topological order)")
        fig.tight_layout()
        fig.savefig(path)
        plt.close(fig)

    # -- aggregates --------------------------------------------------------

    def total_compute(self) -> float:
        return sum(
            n.forward_compute_time + n.backward_compute_time
            for n in self.nodes.values()
        )

    def total_parameter_bytes(self) -> float:
        return sum(n.parameter_size for n in self.nodes.values())
