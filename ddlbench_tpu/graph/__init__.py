from ddlbench_tpu.graph.graph import Graph, Node

__all__ = ["Graph", "Node"]
