"""Pipeline-schedule math: timetables as DATA, bubble fractions, advice.

The schedule-programmable pipeline runtime (parallel/pipeline_rt.py)
consumes a :class:`Timetable` — a dense ``(half_tick, device) -> {fwd,
bwd_input, bwd_weight, idle}`` description — rather than baking a schedule
into engine code (Piper's "schedules are descriptions" design, PAPERS.md).
This module is where the four shipped schedules live:

* ``fill-drain``   — GPipe: all forwards flush through, then the combined
  backward drains in reverse (the autodiff schedule of parallel/gpipe.py).
* ``1f1b``         — synchronous 1F1B: warmup of ``S-1-s`` forwards per
  stage, then one-forward-one-backward steady state; same weights for every
  microbatch (no stashing, unlike pipedream's ASYNC 1F1B).
* ``interleaved``  — interleaved 1F1B over ``C = S*V`` model chunks
  (generalizing ``cfg.virtual_stages`` beyond the fill-drain schedule).
* ``zero-bubble``  — ZB-H1-style: the backward is split into an input-grad
  event (B, produces the upstream cotangent) and a weight-grad event (W,
  consumes the stashed input + cotangent), and W is deferred to fill the
  fill/drain bubbles.

Event cost model (the half-tick grid): one F, one B (input grad) or one W
(weight grad) each occupy ONE half-tick, one event per device per half-tick
— the F = B = W unit-cost model of the zero-bubble literature. A legacy
combined backward is B immediately followed by W (2 half-ticks). Activation
handoffs take one half-tick (ring ppermute), so F(c+1, m) and B(c, m) run
at least one half-tick after their producers.

Analytic bubble fractions under this model, at equal (S, M), V = 1::

    fill-drain:   3(S-1) / (3M + 3(S-1))  =  (S-1)/(M+S-1)
    1f1b:         2(S-1) / (3M + 2(S-1))          (< fill-drain: the split
                  W lets stage s-1's B start under stage s's W in the drain)
    interleaved:  == 1f1b at V=1; fill/drain cost shrinks toward /V as the
                  per-device chunks interleave (measured from the table)
    zero-bubble:   (S-1) / (3M + 1(S-1))          (deferred W fills the
                  drain; only the F fill bubble remains)

so ``zero-bubble < 1f1b <= interleaved < fill-drain`` — the ordering the
schedule-parity suite pins. ``1f1b``/``zero-bubble`` formulas are verified
against the table-derived fractions in tests/test_pipeline_rt.py.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, List, Tuple

import numpy as np

# Event codes (Timetable.events values). IDLE must stay 0 (zeros padding).
EVENT_IDLE, EVENT_FWD, EVENT_BWD_IN, EVENT_BWD_W = 0, 1, 2, 3
EVENT_NAMES = ("idle", "F", "B", "W")

PIPE_SCHEDULES = ("fill-drain", "1f1b", "interleaved", "zero-bubble")


@dataclasses.dataclass(frozen=True)
class Timetable:
    """One pipeline schedule as data, on the global half-tick grid.

    ``events[h, s]`` is the event device ``s`` executes at half-tick ``h``
    (EVENT_* code), ``mbs[h, s]`` the microbatch index (-1 when idle) and
    ``chunks[h, s]`` the model-chunk index ``c = v*S + s`` it applies to
    (-1 when idle; always the device's own chunk row, i.e. c % S == s).
    """

    name: str
    num_stages: int
    virtual_stages: int
    num_microbatches: int
    events: np.ndarray  # [H, S] int8
    mbs: np.ndarray  # [H, S] int32
    chunks: np.ndarray  # [H, S] int32

    @property
    def num_chunks(self) -> int:
        return self.num_stages * self.virtual_stages

    @property
    def half_ticks(self) -> int:
        return int(self.events.shape[0])

    # -- derived figures ---------------------------------------------------

    def bubble_fraction(self) -> float:
        """Idle fraction of the device-time grid: idle half-ticks over
        S * H. This is THE schedule's analytic bubble — the runtime executes
        the table verbatim, and telemetry/bubble.py measures the same
        quantity from emitted tick spans."""
        total = self.events.size
        busy = int(np.count_nonzero(self.events))
        return (total - busy) / total if total else 0.0

    def event_times(self, kind: int) -> Dict[Tuple[int, int], int]:
        """{(chunk, microbatch): half_tick} for one event kind."""
        out: Dict[Tuple[int, int], int] = {}
        hs, ss = np.nonzero(self.events == kind)
        for h, s in zip(hs.tolist(), ss.tolist()):
            out[(int(self.chunks[h, s]), int(self.mbs[h, s]))] = int(h)
        return out

    def validate(self) -> None:
        """Dependency-correctness: every (chunk, mb) runs F once, B once,
        W once, in an order that respects the one-half-tick handoffs.
        Raises AssertionError with the violated relation."""
        S, V, M, C = (self.num_stages, self.virtual_stages,
                      self.num_microbatches, self.num_chunks)
        F = self.event_times(EVENT_FWD)
        B = self.event_times(EVENT_BWD_IN)
        W = self.event_times(EVENT_BWD_W)
        for table, nm in ((F, "F"), (B, "B"), (W, "W")):
            assert len(table) == C * M, (
                f"{self.name}: {nm} covers {len(table)} of {C * M} "
                f"(chunk, microbatch) events")
        for c in range(C):
            for m in range(M):
                f, b, w = F[(c, m)], B[(c, m)], W[(c, m)]
                if c > 0:
                    assert f >= F[(c - 1, m)] + 1, (
                        f"{self.name}: F({c},{m})@{f} before its input "
                        f"arrives (producer F({c - 1},{m})@{F[(c - 1, m)]})")
                if c < C - 1:
                    assert b >= B[(c + 1, m)] + 1, (
                        f"{self.name}: B({c},{m})@{b} before its cotangent "
                        f"arrives (producer B({c + 1},{m})@{B[(c + 1, m)]})")
                else:
                    assert b >= f + 1, (
                        f"{self.name}: last-chunk B({c},{m})@{b} not after "
                        f"its F@{f}")
                assert w >= b + 1, (
                    f"{self.name}: W({c},{m})@{w} not after B@{b}")
                assert b > f, f"{self.name}: B({c},{m})@{b} not after F@{f}"
        # one event per device per half-tick is structural ([H, S] grid);
        # chunk-locality: every event's chunk lives on its device
        hs, ss = np.nonzero(self.events)
        assert all(int(self.chunks[h, s]) % S == s
                   for h, s in zip(hs.tolist(), ss.tolist())), (
            f"{self.name}: an event landed on a foreign device")

    def forward_tick_arrays(self) -> Tuple[np.ndarray, np.ndarray,
                                           np.ndarray]:
        """The F events of the leading forward phase as per-tick arrays
        ``(v, m, valid)``, each ``[T, S]`` with ``T = M*V + S - 1`` — what
        the autodiff (fill-drain) runtime scans over; the backward half of
        the table is realized by jax.grad reversing that scan. Only
        meaningful for fill-drain (whose forward phase IS its first T
        half-ticks); asserts that shape."""
        S, V, M = self.num_stages, self.virtual_stages, self.num_microbatches
        T = M * V + S - 1
        fwd = self.events[:T] == EVENT_FWD
        assert int(np.count_nonzero(fwd)) == S * V * M, (
            f"{self.name}: forward phase is not the leading {T} half-ticks")
        v = np.where(fwd, self.chunks[:T] // S, 0).astype(np.int32)
        m = np.where(fwd, self.mbs[:T], 0).astype(np.int32)
        return v, m, fwd.astype(np.bool_)

    def max_inflight(self) -> int:
        """Max microbatches any chunk holds stashed at once (F done, W not)
        — the activation-memory high-water mark the schedule implies."""
        F = self.event_times(EVENT_FWD)
        W = self.event_times(EVENT_BWD_W)
        worst = 0
        for c in range(self.num_chunks):
            spans = [(F[(c, m)], W[(c, m)])
                     for m in range(self.num_microbatches)]
            for h in range(self.half_ticks):
                worst = max(worst, sum(1 for a, b in spans if a <= h < b))
        return worst

    def engine_arrays(self) -> Dict[str, np.ndarray]:
        """Everything the event-mode runtime (parallel/pipeline_rt.py)
        needs to EXECUTE this table, precomputed on the host:

        * ``ev/vrow/mb [H, S]`` — the event grid (vrow = chunk row v on the
          device; -1s clipped to 0, ev==IDLE masks them);
        * forward-arrival routing ``fa_valid/fa_row/fa_m [H, S]`` — at
          half-tick h, device s's ring buffer holds the activation chunk
          ``vrow*S + s`` sent by its left neighbor's F at h-1 (V>1 wrap
          transfers are baked into the row index);
        * backward-arrival routing ``ba_* [H, S]`` — same for cotangents
          from the right neighbor's B events;
        * ring sizes ``nq_f/nq_b`` (arrival->use queues, slot = m % n) and
          ``ns_x/ns_g`` (F->W input stash, B->W cotangent stash).
        """
        S, V, M, C, H = (self.num_stages, self.virtual_stages,
                         self.num_microbatches, self.num_chunks,
                         self.half_ticks)
        F = self.event_times(EVENT_FWD)
        B = self.event_times(EVENT_BWD_IN)
        W = self.event_times(EVENT_BWD_W)
        fa_valid = np.zeros((H, S), np.bool_)
        fa_row = np.zeros((H, S), np.int32)
        fa_m = np.zeros((H, S), np.int32)
        ba_valid = np.zeros((H, S), np.bool_)
        ba_row = np.zeros((H, S), np.int32)
        ba_m = np.zeros((H, S), np.int32)
        for (c, m), h in F.items():
            if c < C - 1:  # last chunk's output is the loss, never shipped
                dev = (c + 1) % S
                fa_valid[h + 1, dev] = True
                fa_row[h + 1, dev] = (c + 1) // S
                fa_m[h + 1, dev] = m
        for (c, m), h in B.items():
            if c > 0:  # chunk 0's input grad has no consumer
                dev = (c - 1) % S
                ba_valid[h + 1, dev] = True
                ba_row[h + 1, dev] = (c - 1) // S
                ba_m[h + 1, dev] = m
        interior = {(c, m): t for (c, m), t in F.items() if c > 0}
        return {
            "ev": self.events.astype(np.int32),
            "vrow": np.maximum(self.chunks // S, 0).astype(np.int32),
            "mb": np.maximum(self.mbs, 0).astype(np.int32),
            "fa_valid": fa_valid, "fa_row": fa_row, "fa_m": fa_m,
            "ba_valid": ba_valid, "ba_row": ba_row, "ba_m": ba_m,
            "nq_f": ring_slots(
                {k: F[(k[0] - 1, k[1])] + 1 for k in interior},
                interior, C, M),
            "nq_b": ring_slots(
                {(c, m): B[(c + 1, m)] + 1 for (c, m) in B if c < C - 1},
                {k: B[k] for k in B if k[0] < C - 1}, C, M),
            "ns_x": ring_slots(interior,
                               {k: W[k] for k in interior}, C, M),
            "ns_g": ring_slots({k: B[k] for k in B if k[0] < C - 1},
                               {k: W[k] for k in W if k[0] < C - 1}, C, M),
        }


def ring_slots(writes: Dict[Tuple[int, int], int],
               reads: Dict[Tuple[int, int], int],
               num_chunks: int, num_microbatches: int) -> int:
    """Smallest ring size ``n`` such that slot ``m % n`` never holds two
    live values at once (live = [write half-tick, read half-tick]). The
    runtime sizes its stash/queue rings with this, per table, on the host.
    """
    for n in range(1, num_microbatches + 1):
        ok = True
        for c in range(num_chunks):
            spans = [(writes[(c, m)], reads[(c, m)], m)
                     for m in range(num_microbatches) if (c, m) in writes]
            for i, (a0, b0, m0) in enumerate(spans):
                for a1, b1, m1 in spans[i + 1:]:
                    if m0 % n == m1 % n and a0 <= b1 and a1 <= b0:
                        ok = False
                        break
                if not ok:
                    break
            if not ok:
                break
        if ok:
            return n
    return num_microbatches


# -- generators ------------------------------------------------------------


def _empty(H: int, S: int):
    return (np.zeros((H, S), np.int8), np.full((H, S), -1, np.int32),
            np.full((H, S), -1, np.int32))


def fill_drain_timetable(S: int, M: int, V: int = 1) -> Timetable:
    """GPipe: the forward scan's timetable (chunk c = v*S + s runs
    microbatch m = g*S + r at tick t = g*S*V + v*S + s + r — the same
    closed form parallel/gpipe.py compiles), followed by the reversed
    combined backward: forward tick t replays as B then W at half-ticks
    T + 2*(T-1-t) and T + 2*(T-1-t) + 1 (jax.grad reverses the scan)."""
    T = M * V + S - 1
    H = 3 * T
    events, mbs, chunks = _empty(H, S)
    for t in range(T):
        for s in range(S):
            u = t - s
            if not 0 <= u < M * V:
                continue
            g, rem = divmod(u, S * V)
            v, r = divmod(rem, S)
            m = g * S + r
            if m >= M:
                continue
            c = v * S + s
            events[t, s] = EVENT_FWD
            mbs[t, s], chunks[t, s] = m, c
            tb = T + 2 * (T - 1 - t)
            events[tb, s], events[tb + 1, s] = EVENT_BWD_IN, EVENT_BWD_W
            mbs[tb, s] = mbs[tb + 1, s] = m
            chunks[tb, s] = chunks[tb + 1, s] = c
    return Timetable("fill-drain", S, V, M, events, mbs, chunks)


@functools.lru_cache(maxsize=64)
def _greedy_timetable(name: str, S: int, M: int, V: int,
                      defer_weight_grads: bool) -> Timetable:
    """Event-driven greedy generator for the synchronous 1F1B family.

    Closed-form rule set (this IS the schedule description; the dense table
    is its materialization):

    * chunk c runs a warmup of ``C - 1 - c`` forwards, i.e. at most
      ``C - c`` microbatches may be in flight (F done, B not) — the classic
      1F1B in-flight cap over C = S*V chunks;
    * readiness: F(c, m) one half-tick after F(c-1, m); B(c, m) one after
      B(c+1, m) (one after F(c, m) on the last chunk); W(c, m) any time
      after B(c, m);
    * per half-tick each device runs its highest-priority ready event:
      B first (drain the pipe), then — 1f1b — W (the legacy combined
      backward, W glued behind B) or — zero-bubble — F (ZB-H1: W is
      deferred into half-ticks where nothing else is ready, filling the
      bubbles). Ties go to the earliest microbatch, then the deepest chunk.
    """
    C = S * V
    F: Dict[Tuple[int, int], int] = {}
    B: Dict[Tuple[int, int], int] = {}
    W: Dict[Tuple[int, int], int] = {}
    rows: List[Tuple[int, int, int, int]] = []  # (h, s, event, c, m)

    def ready_f(c, m, h):
        if (c, m) in F or m >= M:
            return False
        if c > 0 and F.get((c - 1, m), h) >= h:
            return False
        inflight = sum(1 for mm in range(M)
                       if (c, mm) in F and (c, mm) not in B)
        return inflight < C - c

    def ready_b(c, m, h):
        if (c, m) in B or (c, m) not in F:
            return False
        if c == C - 1:
            return F[(c, m)] < h
        return B.get((c + 1, m), h) < h

    def ready_w(c, m, h):
        return (c, m) in B and (c, m) not in W and B[(c, m)] < h

    h = 0
    total = 3 * C * M
    done = 0
    while done < total:
        for s in range(S):
            # candidate (priority, m, -c, event, c) rows; lowest wins
            cand = []
            for v in range(V):
                c = v * S + s
                for m in range(M):
                    if ready_b(c, m, h):
                        cand.append((0, m, -c, EVENT_BWD_IN, c))
                    if ready_w(c, m, h):
                        cand.append((2 if defer_weight_grads else 1,
                                     m, -c, EVENT_BWD_W, c))
                    if ready_f(c, m, h):
                        cand.append((1 if defer_weight_grads else 2,
                                     m, -c, EVENT_FWD, c))
            if not cand:
                continue
            _, m, _, ev, c = min(cand)
            {EVENT_FWD: F, EVENT_BWD_IN: B, EVENT_BWD_W: W}[ev][(c, m)] = h
            rows.append((h, s, ev, c, m))
            done += 1
        h += 1
        assert h <= 6 * C * M + 6 * C + 16, (
            f"{name}: greedy schedule did not converge (S={S}, V={V}, "
            f"M={M})")
    events, mbs, chunks = _empty(h, S)
    for hh, s, ev, c, m in rows:
        events[hh, s], mbs[hh, s], chunks[hh, s] = ev, m, c
    tt = Timetable(name, S, V, M, events, mbs, chunks)
    tt.validate()
    return tt


def sync_1f1b_timetable(S: int, M: int, V: int = 1) -> Timetable:
    """Synchronous 1F1B (V=1) / interleaved 1F1B (V>1): same step-start
    weights for every microbatch, grads accumulated, ONE optimizer update
    per step — unlike parallel/pipedream.py's async engine."""
    return _greedy_timetable("1f1b" if V == 1 else "interleaved",
                             S, M, V, defer_weight_grads=False)


def zero_bubble_timetable(S: int, M: int) -> Timetable:
    """ZB-H1-style: weight-grad events deferred to fill the drain bubble
    (same in-flight cap as 1F1B, so activation memory is 1F1B-equal)."""
    return _greedy_timetable("zero-bubble", S, M, 1,
                             defer_weight_grads=True)


def make_timetable(schedule: str, S: int, M: int, V: int = 1) -> Timetable:
    """Factory keyed by the ``--pipe-schedule`` flag value."""
    if schedule == "fill-drain":
        return fill_drain_timetable(S, M, V)
    if schedule == "1f1b":
        if V != 1:
            raise ValueError("1f1b is the V=1 schedule; use "
                             "--pipe-schedule interleaved with "
                             "--virtual-stages for V > 1")
        return sync_1f1b_timetable(S, M, 1)
    if schedule == "interleaved":
        return sync_1f1b_timetable(S, M, V)
    if schedule == "zero-bubble":
        if V != 1:
            raise ValueError("zero-bubble (ZB-H1) is scoped to V = 1; "
                             "combine interleaving and W-deferral in a "
                             "future schedule")
        return zero_bubble_timetable(S, M)
    raise ValueError(f"unknown pipe schedule {schedule!r} "
                     f"(choose from {', '.join(PIPE_SCHEDULES)})")


# -- analytic bubble fractions (module docstring's closed forms) -----------


def pipeline_bubble_fraction(num_stages: int, num_microbatches: int,
                             virtual_stages: int = 1) -> float:
    """Idle fraction of the synchronous fill-drain schedule — the classic
    (S-1)/(M*V + S-1). Identical on the half-tick grid: both the forward
    tick and the 2-half-tick combined backward idle S-1 units per device."""
    S, M, V = num_stages, num_microbatches, virtual_stages
    if S <= 1:
        return 0.0
    return (S - 1) / (M * V + S - 1)


def schedule_bubble_fraction(schedule: str, num_stages: int,
                             num_microbatches: int,
                             virtual_stages: int = 1) -> float:
    """Analytic bubble fraction for one shipped schedule at (S, M, V).

    fill-drain / 1f1b / zero-bubble use the closed forms (module
    docstring); interleaved is measured from its table (its fill/drain
    compression depends on how the greedy packer interleaves chunk rows).
    Closed forms are pinned against table-derived fractions by the
    ``pipesched`` suite.
    """
    S, M, V = num_stages, num_microbatches, virtual_stages
    if S <= 1:
        return 0.0
    if schedule == "fill-drain":
        return pipeline_bubble_fraction(S, M, V)
    if schedule == "1f1b" or (schedule == "interleaved" and V == 1):
        return 2 * (S - 1) / (3 * M + 2 * (S - 1))
    if schedule == "zero-bubble":
        return (S - 1) / (3 * M + (S - 1))
    if schedule == "interleaved":
        if bubble_is_estimate(schedule, S, M, V):
            # advisory-scale guard: the greedy generator is pure Python
            # (O(H*S*V*M^2) worst case) — beyond a few thousand events,
            # report the ideal-packing LOWER BOUND (fill/drain shrunk by
            # V) instead of materializing the table for a printed hint;
            # the runtime still builds (and caches) the exact table when
            # the schedule actually executes
            return 2 * (S - 1) / (3 * M * V + 2 * (S - 1))
        return make_timetable("interleaved", S, M, V).bubble_fraction()
    raise ValueError(f"unknown pipe schedule {schedule!r}")


def bubble_is_estimate(schedule: str, num_stages: int,
                       num_microbatches: int,
                       virtual_stages: int = 1) -> bool:
    """True when :func:`schedule_bubble_fraction` returns the
    ideal-packing LOWER BOUND instead of the exact table-derived value
    (large interleaved shapes) — callers reporting the figure (scalebench
    ``bubble_analytic``) tag it so measured-vs-analytic comparisons don't
    read an optimistic bound as the schedule's true prediction."""
    return (schedule == "interleaved" and virtual_stages > 1
            and num_stages * virtual_stages * num_microbatches > 2048)


def recommend_schedule(num_stages: int, num_microbatches: int,
                       virtual_stages: int = 1) -> List[dict]:
    """Feasible schedules at (S, M, V) with their analytic bubbles, best
    first — what --auto-partition's advisor now reports alongside the best
    V. zero-bubble/1f1b rows appear only where their constraints hold."""
    S, M, V = num_stages, num_microbatches, virtual_stages
    rows = []
    for name in PIPE_SCHEDULES:
        if name in ("1f1b", "zero-bubble") and V != 1:
            continue
        if name == "interleaved" and V > 1 and M % S:
            continue  # interleaved groups microbatches in rounds of S
        rows.append({
            "schedule": name,
            "bubble": round(schedule_bubble_fraction(name, S, M, V), 4),
            "virtual_stages": V if name in ("fill-drain", "interleaved")
            else 1,
        })
    rows.sort(key=lambda r: (r["bubble"], r["schedule"]))
    return rows


def recommend_virtual_stages(num_stages: int, num_microbatches: int,
                             num_layers: int,
                             candidates: Tuple[int, ...] = (1, 2, 3, 4, 6, 8),
                             ) -> List[dict]:
    """Feasible interleaving factors with their bubble fractions, best first.

    Feasibility: V=1 always; V>1 needs num_microbatches % num_stages == 0
    (the interleaved timetable groups microbatches in rounds of S) and
    enough layers for S*V chunks. Rows carry the transfer count per
    microbatch so callers can weigh bubble savings against rotation cost
    (the bubble always shrinks with V; communication always grows), plus
    the best schedule at that V (recommend_schedule) now that schedules
    are data.
    """
    S, M = num_stages, num_microbatches
    rows = []
    for v in candidates:
        if v > 1 and (M % S or S * v > num_layers or S <= 1):
            continue
        if v == 1 and S * v > num_layers:
            continue
        best = recommend_schedule(S, M, v)[0]
        rows.append({
            "virtual_stages": v,
            "bubble": round(pipeline_bubble_fraction(S, M, v), 4),
            "transfers_per_microbatch": max(0, S * v - 1),
            "best_schedule": best["schedule"],
            "best_schedule_bubble": best["bubble"],
        })
    rows.sort(key=lambda r: (r["bubble"], r["virtual_stages"]))
    return rows
