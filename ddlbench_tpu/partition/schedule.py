"""Pipeline-schedule math: bubble fractions and virtual-stage advice.

The synchronous pipeline (parallel/gpipe.py) runs T = M*V + S - 1 chunk-ticks
per device for M*V useful ones, so the idle (bubble) fraction is
(S-1)/(M*V + S-1); interleaving (V chunks per device, cfg.virtual_stages)
divides the fill/drain cost by V at the price of (S*V - 1) ring rotations per
microbatch instead of S - 1. These helpers quantify that tradeoff so
--auto-partition can report it alongside the stage bounds.
"""

from __future__ import annotations

from typing import List, Optional, Tuple


def pipeline_bubble_fraction(num_stages: int, num_microbatches: int,
                             virtual_stages: int = 1) -> float:
    """Idle fraction of the synchronous (fill-drain) schedule."""
    S, M, V = num_stages, num_microbatches, virtual_stages
    if S <= 1:
        return 0.0
    return (S - 1) / (M * V + S - 1)


def recommend_virtual_stages(num_stages: int, num_microbatches: int,
                             num_layers: int,
                             candidates: Tuple[int, ...] = (1, 2, 3, 4, 6, 8),
                             ) -> List[dict]:
    """Feasible interleaving factors with their bubble fractions, best first.

    Feasibility: V=1 always; V>1 needs num_microbatches % num_stages == 0
    (the interleaved timetable groups microbatches in rounds of S) and
    enough layers for S*V chunks. Rows carry the transfer count per
    microbatch so callers can weigh bubble savings against rotation cost
    (the bubble always shrinks with V; communication always grows).
    """
    S, M = num_stages, num_microbatches
    rows = []
    for v in candidates:
        if v > 1 and (M % S or S * v > num_layers or S <= 1):
            continue
        if v == 1 and S * v > num_layers:
            continue
        rows.append({
            "virtual_stages": v,
            "bubble": round(pipeline_bubble_fraction(S, M, v), 4),
            "transfers_per_microbatch": max(0, S * v - 1),
        })
    rows.sort(key=lambda r: (r["bubble"], r["virtual_stages"]))
    return rows
