"""Pipeline-schedule math: timetables as DATA, bubble fractions, advice.

The schedule-programmable pipeline runtime (parallel/pipeline_rt.py)
consumes a :class:`Timetable` — a dense ``(half_tick, device) -> {fwd,
bwd_input, bwd_weight, idle}`` description — rather than baking a schedule
into engine code (Piper's "schedules are descriptions" design, PAPERS.md).
This module is where the shipped schedule FAMILY lives:

* ``fill-drain``   — GPipe: all forwards flush through, then the combined
  backward drains in reverse (the autodiff schedule of parallel/gpipe.py).
* ``1f1b``         — synchronous 1F1B: warmup of ``S-1-s`` forwards per
  stage, then one-forward-one-backward steady state; same weights for every
  microbatch (no stashing, unlike pipedream's ASYNC 1F1B). At V > 1 it IS
  the interleaved table (the composed schedule, not an error).
* ``interleaved``  — interleaved 1F1B over ``C = S*V`` model chunks
  (generalizing ``cfg.virtual_stages`` beyond the fill-drain schedule).
* ``zero-bubble``  — ZB-H1-style: the backward is split into an input-grad
  event (B, produces the upstream cotangent) and a weight-grad event (W,
  consumes the stashed input + cotangent), and W is deferred to fill the
  fill/drain bubbles. At V > 1 the same W-deferral composes with the
  interleaved chunk rows (``defer_weight_grads`` over C = S*V chunks).
* ``zero-bubble-h2`` — ZB-H2-style: the 1F1B in-flight cap is lifted by a
  configurable extra activation stash (``stash`` microbatches per chunk)
  and up to ``stash`` trailing W events per chunk are DEFERRED PAST THE
  STEP BOUNDARY into the next step's warmup idle. Execution stays linear
  (the deferred W events still run at the step's tail, before the
  optimizer update, so per-step math is unchanged and trajectories stay
  pinned); the deferral is the STEADY-STATE accounting —
  :meth:`Timetable.bubble_fraction` prices the wrapped period
  :meth:`Timetable.steady_period` instead of the linear makespan. The
  extra stash is priced into the planner's memory term, so a tight
  ``--hbm-gb`` cap can reject H2 for exactly that memory.
* ``searched``     — partition/schedule_search.py: deterministic budgeted
  local search (per-device swap/shift moves on the weighted event grid,
  seeded by BOTH heuristics of every 1F1B-memory family) that never packs
  worse than the min-of-two-heuristics table and strictly beats it on
  genuinely uneven profiled costs.

Event cost model (the half-tick grid): one F, one B (input grad) or one W
(weight grad) each occupy ONE half-tick, one event per device per half-tick
— the F = B = W unit-cost model of the zero-bubble literature. A legacy
combined backward is B immediately followed by W (2 half-ticks). Activation
handoffs take one half-tick (ring ppermute), so F(c+1, m) and B(c, m) run
at least one half-tick after their producers.

Analytic bubble fractions under this model, at equal (S, M), V = 1::

    fill-drain:   3(S-1) / (3M + 3(S-1))  =  (S-1)/(M+S-1)
    1f1b:         2(S-1) / (3M + 2(S-1))          (< fill-drain: the split
                  W lets stage s-1's B start under stage s's W in the drain)
    interleaved:  == 1f1b at V=1; fill/drain cost shrinks toward /V as the
                  per-device chunks interleave (measured from the table)
    zero-bubble:   (S-1) / (3M + 1(S-1))          (deferred W fills the
                  drain; only the F fill bubble remains)

so ``zero-bubble < 1f1b <= interleaved < fill-drain`` — the ordering the
schedule-parity suite pins. ``1f1b``/``zero-bubble`` formulas are verified
against the table-derived fractions in tests/test_pipeline_rt.py.

Cost-aware timetables (ISSUE 8): every generator also accepts per-chunk
``costs = (f, b, w)`` — three length-C tuples of positive ints pricing each
chunk's F/B/W event in half-ticks — so the auto-partitioner's deliberately
UNEVEN stage splits get timetables packed for their true costs instead of
the F=B=W unit fiction. An event occupies ``cost`` consecutive grid cells;
``event_times`` reports START half-ticks, handoffs remain one half-tick
after the producer's END, and ``validate``/``ring_slots``/
``bubble_fraction`` generalize (a weighted cell grid's idle fraction IS the
weighted bubble). Unit costs reproduce the PR 7 tables bitwise (pinned by
tests/test_schedule_costs.py); :func:`quantize_cost_vectors` maps profiled
per-chunk milliseconds onto the integer grid, and
:func:`reprice_timetable` re-simulates a unit-cost table's event ORDER
under true costs — the baseline a cost-aware table must beat.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, List, Optional, Tuple

import numpy as np

# Event codes (Timetable.events values). IDLE must stay 0 (zeros padding).
EVENT_IDLE, EVENT_FWD, EVENT_BWD_IN, EVENT_BWD_W = 0, 1, 2, 3
EVENT_NAMES = ("idle", "F", "B", "W")

PIPE_SCHEDULES = ("fill-drain", "1f1b", "interleaved", "zero-bubble",
                  "zero-bubble-h2", "searched")

# the 1F1B-memory event family the searched packer draws its seeds from
# (fill-drain is the autodiff scan; zero-bubble-h2 trades memory for its
# bubble, so a searched table must not silently inherit its lifted cap)
SEARCH_SEED_SCHEDULES = ("1f1b", "zero-bubble")

# costs = (f, b, w): three length-C tuples of positive ints, half-ticks per
# chunk event. None = the F=B=W unit-cost model.
CostVectors = Tuple[Tuple[int, ...], Tuple[int, ...], Tuple[int, ...]]


def normalize_costs(costs, num_chunks: int) -> Optional[CostVectors]:
    """Canonical cost vectors: three length-``num_chunks`` int tuples, all
    >= 1; all-unit vectors normalize to None (the closed-form unit paths
    are then taken, which is what makes "unit costs reproduce the legacy
    tables bitwise" true by routing as well as by construction)."""
    if costs is None:
        return None
    if len(costs) != 3:
        raise ValueError(f"costs must be (f, b, w) vectors; got {costs!r}")
    out = []
    for vec in costs:
        vec = tuple(int(v) for v in vec)
        if len(vec) != num_chunks:
            raise ValueError(
                f"cost vector length {len(vec)} != num_chunks {num_chunks}")
        if any(v < 1 for v in vec):
            raise ValueError(f"event costs must be >= 1 half-tick; got {vec}")
        out.append(vec)
    f, b, w = out
    if all(v == 1 for v in f + b + w):
        return None
    return (f, b, w)


@dataclasses.dataclass(frozen=True)
class Timetable:
    """One pipeline schedule as data, on the global half-tick grid.

    ``events[h, s]`` is the event device ``s`` executes at half-tick ``h``
    (EVENT_* code), ``mbs[h, s]`` the microbatch index (-1 when idle) and
    ``chunks[h, s]`` the model-chunk index ``c = v*S + s`` it applies to
    (-1 when idle; always the device's own chunk row, i.e. c % S == s).
    """

    name: str
    num_stages: int
    virtual_stages: int
    num_microbatches: int
    events: np.ndarray  # [H, S] int8
    mbs: np.ndarray  # [H, S] int32
    chunks: np.ndarray  # [H, S] int32
    # per-chunk (f, b, w) half-tick costs; None = unit-cost model. A
    # weighted event occupies ``cost`` consecutive grid cells starting at
    # its event_times() half-tick.
    costs: Optional[CostVectors] = None
    # (chunk, microbatch) W events the STEADY-STATE model defers past the
    # step boundary (ZB-H2): they are still painted (and executed) at the
    # step's tail — per-step math unchanged — but bubble_fraction prices
    # the wrapped steady_period instead of the linear makespan, because in
    # back-to-back steps those cells overlap the next step's warmup idle.
    deferred_w: Optional[Tuple[Tuple[int, int], ...]] = None

    @property
    def num_chunks(self) -> int:
        return self.num_stages * self.virtual_stages

    @property
    def half_ticks(self) -> int:
        return int(self.events.shape[0])

    def cost_of(self, kind: int, chunk: int) -> int:
        """Half-ticks event ``kind`` occupies on ``chunk`` (1 when unit)."""
        if self.costs is None:
            return 1
        return self.costs[kind - EVENT_FWD][chunk]

    # -- derived figures ---------------------------------------------------

    def bubble_fraction(self) -> float:
        """Idle fraction of the device-time grid: idle half-ticks over
        S * H. This is THE schedule's analytic bubble — the runtime executes
        the table verbatim, and telemetry/bubble.py measures the same
        quantity from emitted tick spans.

        With ``deferred_w`` set (ZB-H2) the fraction is priced over the
        STEADY-STATE period instead: idle cells over
        ``S * steady_period()``. A single linear step still measures the
        grid fraction (``bubble_is_estimate`` flags exactly this gap for
        telemetry consumers)."""
        total = self.events.size
        busy = int(np.count_nonzero(self.events))
        if not total:
            return 0.0
        if self.deferred_w:
            P = self.steady_period()
            return (self.num_stages * P - busy) / (self.num_stages * P)
        return (total - busy) / total

    def steady_period(self) -> int:
        """Half-ticks per step in the back-to-back steady state.

        Without deferral this is the linear makespan (the grid height H).
        With ``deferred_w``, each stage's deferred tail-W cells wrap into
        the NEXT step's idle, so the per-stage period is
        ``max(end of last non-deferred event, total busy cells)`` — the
        first term keeps the in-step critical path, the second is work
        conservation (wrapped cells must fit in that stage's idle). The
        step period is the max over stages."""
        if not self.deferred_w:
            return self.half_ticks
        deferred = set(self.deferred_w)
        S = self.num_stages
        busy = [0] * S
        e_nondef = [0] * S
        for kind in (EVENT_FWD, EVENT_BWD_IN, EVENT_BWD_W):
            for (c, m), h in self.event_times(kind).items():
                s = c % S
                cost = self.cost_of(kind, c)
                busy[s] += cost
                if not (kind == EVENT_BWD_W and (c, m) in deferred):
                    e_nondef[s] = max(e_nondef[s], h + cost)
        return max(max(e_nondef[s], busy[s]) for s in range(S))

    def event_times(self, kind: int) -> Dict[Tuple[int, int], int]:
        """{(chunk, microbatch): START half_tick} for one event kind.
        Weighted events fill ``cost`` consecutive cells; np.nonzero walks
        h-ascending, so the first cell seen is the start."""
        out: Dict[Tuple[int, int], int] = {}
        hs, ss = np.nonzero(self.events == kind)
        for h, s in zip(hs.tolist(), ss.tolist()):
            out.setdefault(
                (int(self.chunks[h, s]), int(self.mbs[h, s])), int(h))
        return out

    def validate(self) -> None:
        """Dependency-correctness: every (chunk, mb) runs F once, B once,
        W once, in an order that respects the one-half-tick handoffs —
        generalized to weighted events (a consumer may start no earlier
        than its producer's END, i.e. start + cost). Raises AssertionError
        with the violated relation."""
        S, V, M, C = (self.num_stages, self.virtual_stages,
                      self.num_microbatches, self.num_chunks)
        F = self.event_times(EVENT_FWD)
        B = self.event_times(EVENT_BWD_IN)
        W = self.event_times(EVENT_BWD_W)
        fc = lambda c: self.cost_of(EVENT_FWD, c)
        bc = lambda c: self.cost_of(EVENT_BWD_IN, c)
        wc = lambda c: self.cost_of(EVENT_BWD_W, c)
        for table, nm in ((F, "F"), (B, "B"), (W, "W")):
            assert len(table) == C * M, (
                f"{self.name}: {nm} covers {len(table)} of {C * M} "
                f"(chunk, microbatch) events")
        for c in range(C):
            for m in range(M):
                f, b, w = F[(c, m)], B[(c, m)], W[(c, m)]
                if c > 0:
                    assert f >= F[(c - 1, m)] + fc(c - 1), (
                        f"{self.name}: F({c},{m})@{f} before its input "
                        f"arrives (producer F({c - 1},{m})@{F[(c - 1, m)]}"
                        f"+{fc(c - 1)})")
                if c < C - 1:
                    assert b >= B[(c + 1, m)] + bc(c + 1), (
                        f"{self.name}: B({c},{m})@{b} before its cotangent "
                        f"arrives (producer B({c + 1},{m})@{B[(c + 1, m)]}"
                        f"+{bc(c + 1)})")
                assert b >= f + fc(c), (
                    f"{self.name}: B({c},{m})@{b} not after its F@{f}"
                    f"+{fc(c)}")
                assert w >= b + bc(c), (
                    f"{self.name}: W({c},{m})@{w} not after B@{b}+{bc(c)}")
        # one event per device per half-tick is structural ([H, S] grid)
        # PROVIDED no generator overwrote a cell: the busy-cell count must
        # equal the summed event costs (catches overlapping placements)
        busy = int(np.count_nonzero(self.events))
        expect = M * sum(fc(c) + bc(c) + wc(c) for c in range(C))
        assert busy == expect, (
            f"{self.name}: {busy} busy cells != {expect} summed event "
            f"costs (overlapping weighted events?)")
        # chunk-locality: every event's chunk lives on its device
        hs, ss = np.nonzero(self.events)
        assert all(int(self.chunks[h, s]) % S == s
                   for h, s in zip(hs.tolist(), ss.tolist())), (
            f"{self.name}: an event landed on a foreign device")
        if self.deferred_w:
            # ZB-H2 accounting soundness: a deferred W must be a real W
            # event forming its stage's TAIL (it starts at/after every
            # non-deferred event on that stage ends), so wrapping it into
            # the next period cannot collide with in-step work
            deferred = set(self.deferred_w)
            for (c, m) in deferred:
                assert (c, m) in W, (
                    f"{self.name}: deferred_w ({c},{m}) is not a W event")
            e_nondef = [0] * S
            for table, kind in ((F, EVENT_FWD), (B, EVENT_BWD_IN),
                                (W, EVENT_BWD_W)):
                for (c, m), h in table.items():
                    if kind == EVENT_BWD_W and (c, m) in deferred:
                        continue
                    s = c % S
                    e_nondef[s] = max(e_nondef[s],
                                      h + self.cost_of(kind, c))
            for (c, m) in deferred:
                assert W[(c, m)] >= e_nondef[c % S], (
                    f"{self.name}: deferred W({c},{m})@{W[(c, m)]} is not "
                    f"its stage's tail (non-deferred work ends at "
                    f"{e_nondef[c % S]})")

    def forward_tick_arrays(self) -> Tuple[np.ndarray, np.ndarray,
                                           np.ndarray]:
        """The F events of the leading forward phase as per-tick arrays
        ``(v, m, valid)``, each ``[T, S]`` with ``T = M*V + S - 1`` — what
        the autodiff (fill-drain) runtime scans over; the backward half of
        the table is realized by jax.grad reversing that scan. Only
        meaningful for fill-drain (whose forward phase IS its first T
        half-ticks); asserts that shape."""
        assert self.costs is None, (
            f"{self.name}: the autodiff (fill-drain) runtime executes the "
            f"unit-cost schedule only; weighted tables are event-mode/"
            f"analysis data")
        S, V, M = self.num_stages, self.virtual_stages, self.num_microbatches
        T = M * V + S - 1
        fwd = self.events[:T] == EVENT_FWD
        assert int(np.count_nonzero(fwd)) == S * V * M, (
            f"{self.name}: forward phase is not the leading {T} half-ticks")
        v = np.where(fwd, self.chunks[:T] // S, 0).astype(np.int32)
        m = np.where(fwd, self.mbs[:T], 0).astype(np.int32)
        return v, m, fwd.astype(np.bool_)

    def max_inflight(self) -> int:
        """Max microbatches any chunk holds stashed at once (F done, W not)
        — the activation-memory high-water mark the schedule implies."""
        F = self.event_times(EVENT_FWD)
        W = self.event_times(EVENT_BWD_W)
        worst = 0
        for c in range(self.num_chunks):
            spans = [(F[(c, m)], W[(c, m)])
                     for m in range(self.num_microbatches)]
            for h in range(self.half_ticks):
                worst = max(worst, sum(1 for a, b in spans if a <= h < b))
        return worst

    def engine_arrays(self) -> Dict[str, np.ndarray]:
        """Everything the event-mode runtime (parallel/pipeline_rt.py)
        needs to EXECUTE this table, precomputed on the host:

        * ``ev/vrow/mb [He, S]`` — the EXECUTION grid over the He ticks on
          which at least one device dispatches an event (for unit-cost
          tables every busy half-tick; for weighted tables the event START
          ticks — the in-between cells only model predicted duration, and
          compressing them out keeps the compiled scan length equal to the
          event count instead of the weighted makespan). -1s are clipped
          to 0, ev==IDLE masks them;
        * forward-arrival routing ``fa_valid/fa_row/fa_m [He, S]`` — at
          execution tick i, device s's ring buffer holds the activation
          chunk ``vrow*S + s`` sent by its left neighbor's F dispatched at
          tick i-1 (V>1 wrap transfers are baked into the row index);
        * backward-arrival routing ``ba_* [He, S]`` — same for cotangents
          from the right neighbor's B events;
        * ring sizes ``nq_f/nq_b`` (arrival->use queues, slot = m % n) and
          ``ns_x/ns_g`` (F->W input stash, B->W cotangent stash).
        """
        S, V, M, C = (self.num_stages, self.virtual_stages,
                      self.num_microbatches, self.num_chunks)
        F = self.event_times(EVENT_FWD)
        B = self.event_times(EVENT_BWD_IN)
        W = self.event_times(EVENT_BWD_W)
        # execution ticks: every half-tick where some device STARTS an
        # event. Dependency-correct by construction: a consumer's start is
        # a later execution tick than its producer's, and physical ring
        # arrivals land one EXECUTION tick after the producer's dispatch
        # (the engine ships at the dispatch tick regardless of the
        # modelled duration).
        starts = sorted({h for d in (F, B, W) for h in d.values()})
        idx = {h: i for i, h in enumerate(starts)}
        He = len(starts)
        ev = np.zeros((He, S), np.int32)
        vrow = np.zeros((He, S), np.int32)
        mb = np.zeros((He, S), np.int32)
        fa_valid = np.zeros((He, S), np.bool_)
        fa_row = np.zeros((He, S), np.int32)
        fa_m = np.zeros((He, S), np.int32)
        ba_valid = np.zeros((He, S), np.bool_)
        ba_row = np.zeros((He, S), np.int32)
        ba_m = np.zeros((He, S), np.int32)
        for table, kind in ((F, EVENT_FWD), (B, EVENT_BWD_IN),
                            (W, EVENT_BWD_W)):
            for (c, m), h in table.items():
                i = idx[h]
                ev[i, c % S] = kind
                vrow[i, c % S] = c // S
                mb[i, c % S] = m
        for (c, m), h in F.items():
            if c < C - 1:  # last chunk's output is the loss, never shipped
                dev = (c + 1) % S
                fa_valid[idx[h] + 1, dev] = True
                fa_row[idx[h] + 1, dev] = (c + 1) // S
                fa_m[idx[h] + 1, dev] = m
        for (c, m), h in B.items():
            if c > 0:  # chunk 0's input grad has no consumer
                dev = (c - 1) % S
                ba_valid[idx[h] + 1, dev] = True
                ba_row[idx[h] + 1, dev] = (c - 1) // S
                ba_m[idx[h] + 1, dev] = m
        # ring live-ranges in EXECUTION ticks (write = arrival, one tick
        # after the producer's dispatch; read = the consumer's dispatch)
        Fi = {k: idx[h] for k, h in F.items()}
        Bi = {k: idx[h] for k, h in B.items()}
        Wi = {k: idx[h] for k, h in W.items()}
        interior = {(c, m): t for (c, m), t in Fi.items() if c > 0}
        return {
            "ev": ev,
            "vrow": vrow,
            "mb": mb,
            "fa_valid": fa_valid, "fa_row": fa_row, "fa_m": fa_m,
            "ba_valid": ba_valid, "ba_row": ba_row, "ba_m": ba_m,
            "nq_f": ring_slots(
                {k: Fi[(k[0] - 1, k[1])] + 1 for k in interior},
                interior, C, M),
            "nq_b": ring_slots(
                {(c, m): Bi[(c + 1, m)] + 1 for (c, m) in Bi if c < C - 1},
                {k: Bi[k] for k in Bi if k[0] < C - 1}, C, M),
            "ns_x": ring_slots(interior,
                               {k: Wi[k] for k in interior}, C, M),
            "ns_g": ring_slots({k: Bi[k] for k in Bi if k[0] < C - 1},
                               {k: Wi[k] for k in Wi if k[0] < C - 1}, C, M),
        }


def ring_slots(writes: Dict[Tuple[int, int], int],
               reads: Dict[Tuple[int, int], int],
               num_chunks: int, num_microbatches: int) -> int:
    """Smallest ring size ``n`` such that slot ``m % n`` never holds two
    live values at once (live = [write half-tick, read half-tick]). The
    runtime sizes its stash/queue rings with this, per table, on the host.
    """
    for n in range(1, num_microbatches + 1):
        ok = True
        for c in range(num_chunks):
            spans = [(writes[(c, m)], reads[(c, m)], m)
                     for m in range(num_microbatches) if (c, m) in writes]
            for i, (a0, b0, m0) in enumerate(spans):
                for a1, b1, m1 in spans[i + 1:]:
                    if m0 % n == m1 % n and a0 <= b1 and a1 <= b0:
                        ok = False
                        break
                if not ok:
                    break
            if not ok:
                break
        if ok:
            return n
    return num_microbatches


# -- generators ------------------------------------------------------------


def _empty(H: int, S: int):
    return (np.zeros((H, S), np.int8), np.full((H, S), -1, np.int32),
            np.full((H, S), -1, np.int32))


def _paint(events, mbs, chunks, h: int, s: int, kind: int, c: int, m: int,
           cost: int) -> None:
    """Write one weighted event's ``cost`` consecutive cells."""
    events[h:h + cost, s] = kind
    mbs[h:h + cost, s] = m
    chunks[h:h + cost, s] = c


def fill_drain_timetable(S: int, M: int, V: int = 1,
                         costs: Optional[CostVectors] = None) -> Timetable:
    """GPipe: the forward scan's timetable (chunk c = v*S + s runs
    microbatch m = g*S + r at tick t = g*S*V + v*S + s + r — the same
    closed form parallel/gpipe.py compiles), followed by the reversed
    combined backward: forward tick t replays as B then W at half-ticks
    T + 2*(T-1-t) and T + 2*(T-1-t) + 1 (jax.grad reverses the scan).

    With ``costs``, the same STRUCTURE priced by per-chunk weights: every
    device runs its forwards in the identical (g, v, r) order, each
    starting at max(device free, input arrival = producer start + cost);
    the backward replays the per-device forward order REVERSED after the
    global forward flush, items glued B+W, cotangent arrival = the
    producer's whole reversed-scan item (B+W) completing — the weighted
    generalization of jax.grad's tick-reversed schedule. Unit costs
    reproduce the closed form bitwise (tests/test_schedule_costs.py)."""
    costs = normalize_costs(costs, S * V)
    if costs is None:
        T = M * V + S - 1
        H = 3 * T
        events, mbs, chunks = _empty(H, S)
        for t in range(T):
            for s in range(S):
                u = t - s
                if not 0 <= u < M * V:
                    continue
                g, rem = divmod(u, S * V)
                v, r = divmod(rem, S)
                m = g * S + r
                if m >= M:
                    continue
                c = v * S + s
                events[t, s] = EVENT_FWD
                mbs[t, s], chunks[t, s] = m, c
                tb = T + 2 * (T - 1 - t)
                events[tb, s], events[tb + 1, s] = EVENT_BWD_IN, EVENT_BWD_W
                mbs[tb, s] = mbs[tb + 1, s] = m
                chunks[tb, s] = chunks[tb + 1, s] = c
        return Timetable("fill-drain", S, V, M, events, mbs, chunks)

    fc, bc, wc = costs
    assert M % S == 0 or V == 1, "V > 1 needs M % S == 0"
    F: Dict[Tuple[int, int], int] = {}
    order: Dict[int, List[Tuple[int, int]]] = {s: [] for s in range(S)}
    free = [0] * S
    # forward: per device, (g, v, r) ascending — the closed form's order
    for g in range(-(-M // S)):
        for v in range(V):
            for r in range(S):
                m = g * S + r
                if m >= M:
                    continue
                for s in range(S):
                    c = v * S + s
                    arrival = (0 if c == 0
                               else F[(c - 1, m)] + fc[c - 1])
                    h = max(free[s], arrival)
                    F[(c, m)] = h
                    free[s] = h + fc[c]
                    order[s].append((c, m))
    flush = max(free)  # the synchronous flush: no B before every F ends
    B: Dict[Tuple[int, int], int] = {}
    W: Dict[Tuple[int, int], int] = {}
    free = [flush] * S
    # backward: per device, the forward order reversed, B+W glued; the
    # cotangent arrives when the producer's whole reversed-scan item
    # (its B and its glued W) has completed
    done = [0] * S  # per-device position in the reversed order
    pending = sum(len(order[s]) for s in range(S))
    while pending:
        progressed = False
        for s in range(S):
            while done[s] < len(order[s]):
                c, m = order[s][len(order[s]) - 1 - done[s]]
                if c == S * V - 1:
                    arrival = F[(c, m)] + fc[c]
                elif (c + 1, m) not in B:
                    break  # producer not placed yet; try other devices
                else:
                    arrival = B[(c + 1, m)] + bc[c + 1] + wc[c + 1]
                h = max(free[s], arrival)
                B[(c, m)] = h
                W[(c, m)] = h + bc[c]
                free[s] = h + bc[c] + wc[c]
                done[s] += 1
                pending -= 1
                progressed = True
        assert progressed, "fill-drain backward deadlocked (internal bug)"
    H = max(free)
    events, mbs, chunks = _empty(H, S)
    for (c, m), h in F.items():
        _paint(events, mbs, chunks, h, c % S, EVENT_FWD, c, m, fc[c])
    for (c, m), h in B.items():
        _paint(events, mbs, chunks, h, c % S, EVENT_BWD_IN, c, m, bc[c])
    for (c, m), h in W.items():
        _paint(events, mbs, chunks, h, c % S, EVENT_BWD_W, c, m, wc[c])
    tt = Timetable("fill-drain", S, V, M, events, mbs, chunks, costs)
    tt.validate()
    return tt


@functools.lru_cache(maxsize=64)
def _greedy_timetable(name: str, S: int, M: int, V: int,
                      defer_weight_grads: bool,
                      costs: Optional[CostVectors] = None,
                      extra_inflight: int = 0) -> Timetable:
    """Event-driven greedy generator for the synchronous 1F1B family.

    Closed-form rule set (this IS the schedule description; the dense table
    is its materialization):

    * chunk c runs a warmup of ``C - 1 - c`` forwards, i.e. at most
      ``C - c`` microbatches may be in flight (F done, B not) — the classic
      1F1B in-flight cap over C = S*V chunks. ``extra_inflight`` (ZB-H2)
      LIFTS the cap to ``min(M, C - c + extra_inflight)``: deeper warmup,
      more stashed activations, fewer forced idles;
    * readiness: F(c, m) one half-tick after F(c-1, m) ENDS; B(c, m) one
      after B(c+1, m) ends (after F(c, m) ends on the last chunk); W(c, m)
      any time after B(c, m) ends;
    * per half-tick each FREE device (weighted events keep it busy for
      their whole cost) runs its highest-priority ready event: B first
      (drain the pipe), then — 1f1b — W (the legacy combined backward, W
      glued behind B) or — zero-bubble — F (ZB-H1: W is deferred into
      half-ticks where nothing else is ready, filling the bubbles). Ties
      go to the earliest microbatch, then the deepest chunk.

    With unit costs (``costs is None``) every end is start + 1 and the
    busy-until bookkeeping is a no-op, so the emitted grid is bitwise the
    PR 7 table.
    """
    C = S * V
    fc, bc, wc = costs if costs is not None else ((1,) * C,) * 3
    F: Dict[Tuple[int, int], int] = {}
    B: Dict[Tuple[int, int], int] = {}
    W: Dict[Tuple[int, int], int] = {}
    rows: List[Tuple[int, int, int, int, int, int]] = []
    # per-chunk microbatches in flight (F done, B not), maintained
    # incrementally — the O(M) scan per readiness probe made large-M
    # advisory builds (recommend_virtual_stages) a visible startup stall
    inflight = [0] * C

    def ready_f(c, m, h):
        if (c, m) in F or m >= M:
            return False
        if c > 0 and F.get((c - 1, m), h) + fc[c - 1] > h:
            return False
        return inflight[c] < min(M, C - c + extra_inflight)

    def ready_b(c, m, h):
        if (c, m) in B or (c, m) not in F:
            return False
        if c == C - 1:
            return F[(c, m)] + fc[c] <= h
        return B.get((c + 1, m), h) + bc[c + 1] <= h

    def ready_w(c, m, h):
        return ((c, m) in B and (c, m) not in W
                and B[(c, m)] + bc[c] <= h)

    h = 0
    total = 3 * C * M
    done = 0
    busy = [0] * S  # device s is mid-event until half-tick busy[s]
    max_cost = max(fc + bc + wc)
    while done < total:
        for s in range(S):
            if busy[s] > h:
                continue
            # candidate (priority, m, -c, event, c) rows; lowest wins
            cand = []
            for v in range(V):
                c = v * S + s
                for m in range(M):
                    if ready_b(c, m, h):
                        cand.append((0, m, -c, EVENT_BWD_IN, c))
                    if ready_w(c, m, h):
                        cand.append((2 if defer_weight_grads else 1,
                                     m, -c, EVENT_BWD_W, c))
                    if ready_f(c, m, h):
                        cand.append((1 if defer_weight_grads else 2,
                                     m, -c, EVENT_FWD, c))
            if not cand:
                continue
            _, m, _, ev, c = min(cand)
            {EVENT_FWD: F, EVENT_BWD_IN: B, EVENT_BWD_W: W}[ev][(c, m)] = h
            if ev == EVENT_FWD:
                inflight[c] += 1
            elif ev == EVENT_BWD_IN:
                inflight[c] -= 1
            cost = {EVENT_FWD: fc, EVENT_BWD_IN: bc, EVENT_BWD_W: wc}[ev][c]
            busy[s] = h + cost
            rows.append((h, s, ev, c, m, cost))
            done += 1
        h += 1
        assert h <= (6 * C * M + 6 * C + 16) * max_cost, (
            f"{name}: greedy schedule did not converge (S={S}, V={V}, "
            f"M={M})")
    events, mbs, chunks = _empty(max(busy), S)
    for hh, s, ev, c, m, cost in rows:
        _paint(events, mbs, chunks, hh, s, ev, c, m, cost)
    tt = Timetable(name, S, V, M, events, mbs, chunks, costs)
    tt.validate()
    return tt


def sync_1f1b_timetable(S: int, M: int, V: int = 1,
                        costs: Optional[CostVectors] = None) -> Timetable:
    """Synchronous 1F1B (V=1) / interleaved 1F1B (V>1): same step-start
    weights for every microbatch, grads accumulated, ONE optimizer update
    per step — unlike parallel/pipedream.py's async engine."""
    return _greedy_timetable("1f1b" if V == 1 else "interleaved",
                             S, M, V, defer_weight_grads=False,
                             costs=normalize_costs(costs, S * V))


def zero_bubble_timetable(S: int, M: int, V: int = 1,
                          costs: Optional[CostVectors] = None) -> Timetable:
    """ZB-H1-style: weight-grad events deferred to fill the drain bubble
    (same in-flight cap as 1F1B, so activation memory is 1F1B-equal).
    V > 1 composes the same W-deferral with the interleaved chunk rows —
    the ``defer_weight_grads`` priority over C = S*V chunks."""
    return _greedy_timetable("zero-bubble", S, M, V,
                             defer_weight_grads=True,
                             costs=normalize_costs(costs, S * V))


def _defer_tail_w(tt: Timetable, stash: int) -> Timetable:
    """Mark up to ``stash`` trailing W events per chunk as deferred past
    the step boundary (the ZB-H2 steady-state accounting). Only a stage's
    TAIL is eligible — a contiguous run of W events after every other
    event on that stage — so the wrapped cells provably land in the next
    period's idle (Timetable.validate pins the invariant). Execution is
    untouched: the events stay painted where they are."""
    if stash <= 0:
        return tt
    S = tt.num_stages
    # per-stage events sorted by start
    per_stage: Dict[int, List[Tuple[int, int, int, int]]] = {
        s: [] for s in range(S)}
    for kind in (EVENT_FWD, EVENT_BWD_IN, EVENT_BWD_W):
        for (c, m), h in tt.event_times(kind).items():
            per_stage[c % S].append((h, kind, c, m))
    deferred: List[Tuple[int, int]] = []
    for s in range(S):
        taken: Dict[int, int] = {}  # chunk -> deferred count
        for h, kind, c, m in sorted(per_stage[s], reverse=True):
            if kind != EVENT_BWD_W or taken.get(c, 0) >= stash:
                break  # the tail run ended (or this chunk's stash is full)
            taken[c] = taken.get(c, 0) + 1
            deferred.append((c, m))
    if not deferred:
        return tt
    return dataclasses.replace(tt, deferred_w=tuple(sorted(deferred)))


@functools.lru_cache(maxsize=64)
def zero_bubble_h2_timetable(S: int, M: int, V: int = 1,
                             costs: Optional[CostVectors] = None,
                             stash: int = 1) -> Timetable:
    """ZB-H2-style: the greedy W-deferring packer with the 1F1B in-flight
    cap LIFTED by ``stash`` extra microbatches per chunk, then up to
    ``stash`` trailing W events per chunk marked deferred past the step
    boundary. The linear event order still executes within the step (so
    trajectories pin against 1f1b exactly like zero-bubble); the payoff is
    the steady-state period — bubble_fraction prices the wrapped schedule,
    which the lifted warmup + boundary deferral drive toward zero at the
    price of ``stash`` extra stashed activations per chunk (the planner's
    stage_mem term; a tight --hbm-gb cap rejects exactly this)."""
    tt = _greedy_timetable("zero-bubble-h2", S, M, V,
                           defer_weight_grads=True,
                           costs=normalize_costs(costs, S * V),
                           extra_inflight=stash)
    out = _defer_tail_w(tt, stash)
    out.validate()
    return out


def timetable_from_times(name: str, S: int, V: int, M: int,
                         F: Dict[Tuple[int, int], int],
                         B: Dict[Tuple[int, int], int],
                         W: Dict[Tuple[int, int], int],
                         costs: Optional[CostVectors]) -> Timetable:
    """Materialize a dense validated grid from start-time tables — the
    shared tail of :func:`reprice_timetable` and the searched packer's
    list scheduler (partition/schedule_search.py)."""
    fc, bc, wc = costs if costs is not None else ((1,) * (S * V),) * 3
    H = max(max(h + wc[c] for (c, _), h in W.items()),
            max(h + bc[c] for (c, _), h in B.items()),
            max(h + fc[c] for (c, _), h in F.items()))
    events, mbs, chunks = _empty(H, S)
    for table, kind, cv in ((F, EVENT_FWD, fc), (B, EVENT_BWD_IN, bc),
                            (W, EVENT_BWD_W, wc)):
        for (c, m), h in table.items():
            _paint(events, mbs, chunks, h, c % S, kind, c, m, cv[c])
    out = Timetable(name, S, V, M, events, mbs, chunks, costs)
    out.validate()
    return out


def make_timetable(schedule: str, S: int, M: int, V: int = 1,
                   costs: Optional[CostVectors] = None, *,
                   stash: int = 1, search_budget: int = 256,
                   search_seed: int = 0) -> Timetable:
    """Factory keyed by the ``--pipe-schedule`` flag value. ``costs`` are
    per-chunk (f, b, w) half-tick vectors (None / all-unit = the PR 7
    unit-cost tables, reproduced bitwise).

    For weighted EVENT schedules the factory builds two candidates — the
    cost-aware greedy table and the unit-cost table's event order
    repriced under the true costs (:func:`reprice_timetable`) — and
    returns the lower-bubble one: the greedy is a heuristic that can
    commit early where the unit order happens to interleave better, so
    taking the min guarantees a weighted timetable never packs WORSE
    than executing the classic schedule on the same uneven chunks.

    ``1f1b``/``zero-bubble`` at V > 1 return the COMPOSED schedules (the
    interleaved table; the W-deferring interleaved table) instead of the
    pre-PR-18 ValueError. ``stash`` sizes zero-bubble-h2's extra in-flight
    stash; ``search_budget``/``search_seed`` parameterize the searched
    packer (deterministic: same budget + seed reproduce the table
    bitwise)."""
    costs = normalize_costs(costs, S * V)
    if schedule == "fill-drain":
        return fill_drain_timetable(S, M, V, costs)
    if schedule == "searched":
        from ddlbench_tpu.partition.schedule_search import searched_timetable

        return searched_timetable(S, M, V, costs, budget=search_budget,
                                  seed=search_seed)
    if schedule in ("1f1b", "interleaved"):
        # 1f1b at V > 1 IS the interleaved table (the composed schedule)
        gen = lambda c: sync_1f1b_timetable(S, M, V, c)
    elif schedule == "zero-bubble":
        gen = lambda c: zero_bubble_timetable(S, M, V, c)
    elif schedule == "zero-bubble-h2":
        gen = lambda c: zero_bubble_h2_timetable(S, M, V, c, stash=stash)
    else:
        raise ValueError(f"unknown pipe schedule {schedule!r} "
                         f"(choose from {', '.join(PIPE_SCHEDULES)})")
    if costs is None:
        return gen(None)
    aware = gen(costs)
    repriced = reprice_timetable(gen(None), costs)
    if schedule == "zero-bubble-h2":
        # compare on the steady-state accounting both candidates use:
        # repricing rebuilds the grid, so re-mark its deferred tail
        repriced = _defer_tail_w(repriced, stash)
        repriced.validate()
    return (aware if aware.bubble_fraction() <= repriced.bubble_fraction()
            else repriced)


def reprice_timetable(tt: Timetable, costs: CostVectors) -> Timetable:
    """Re-simulate ``tt``'s event ORDER under per-chunk ``costs``: each
    device runs its events in the original start order, each starting at
    max(device free, producer end) — what executing a unit-cost schedule
    on genuinely uneven stages would actually cost. The cost-aware
    generator's table must beat (or match) this table's bubble; the
    uneven-cost acceptance fixture pins strictly-lower for 1f1b."""
    costs = normalize_costs(costs, tt.num_chunks)
    if costs is None:
        return tt
    fc, bc, wc = costs
    C = tt.num_chunks
    F0 = tt.event_times(EVENT_FWD)
    B0 = tt.event_times(EVENT_BWD_IN)
    W0 = tt.event_times(EVENT_BWD_W)
    # global original start order; producers always precede consumers
    seq = sorted(
        [(h, c % tt.num_stages, EVENT_FWD, c, m) for (c, m), h in F0.items()]
        + [(h, c % tt.num_stages, EVENT_BWD_IN, c, m)
           for (c, m), h in B0.items()]
        + [(h, c % tt.num_stages, EVENT_BWD_W, c, m)
           for (c, m), h in W0.items()])
    F: Dict[Tuple[int, int], int] = {}
    B: Dict[Tuple[int, int], int] = {}
    W: Dict[Tuple[int, int], int] = {}
    free = [0] * tt.num_stages
    for _h0, s, kind, c, m in seq:
        if kind == EVENT_FWD:
            arrival = 0 if c == 0 else F[(c - 1, m)] + fc[c - 1]
            start = max(free[s], arrival)
            F[(c, m)] = start
            free[s] = start + fc[c]
        elif kind == EVENT_BWD_IN:
            arrival = (F[(c, m)] + fc[c] if c == C - 1
                       else B[(c + 1, m)] + bc[c + 1])
            start = max(free[s], arrival, F[(c, m)] + fc[c])
            B[(c, m)] = start
            free[s] = start + bc[c]
        else:
            start = max(free[s], B[(c, m)] + bc[c])
            W[(c, m)] = start
            free[s] = start + wc[c]
    return timetable_from_times(tt.name, tt.num_stages, tt.virtual_stages,
                                tt.num_microbatches, F, B, W, costs)


def quantize_cost_vectors_clipped(
        f_ms, b_ms, w_ms=None,
        max_units: int = 8) -> Tuple[CostVectors, int]:
    """Per-chunk profiled milliseconds -> integer half-tick cost vectors,
    plus HOW MANY events the ``max_units`` cap clipped (the no-silent-caps
    rule: a clipped vector flattens genuinely uneven profiles, and the
    caller should say so — parallel/api.py logs it, and the search path
    raises the cap so the packer sees the real unevenness).

    The cheapest event maps to one half-tick; everything else scales
    relative to it, rounded, capped at ``max_units`` (bounding the
    weighted grid's height). ``w_ms=None`` splits the combined backward
    evenly into B and W — the profiler measures fwd and fwd+bwd only, and
    dL/dx vs dL/dw each cost about one forward (the same 2x heuristic
    profiler/profile.py's flops mode uses)."""
    f_ms = [float(v) for v in f_ms]
    if w_ms is None:
        b_ms = [float(v) / 2.0 for v in b_ms]
        w_ms = list(b_ms)
    else:
        b_ms = [float(v) for v in b_ms]
        w_ms = [float(v) for v in w_ms]
    lo = min(v for v in f_ms + b_ms + w_ms if v > 0) if any(
        v > 0 for v in f_ms + b_ms + w_ms) else 1.0
    clipped = sum(1 for v in f_ms + b_ms + w_ms
                  if int(round(v / lo)) > max_units)
    q = lambda v: max(1, min(max_units, int(round(v / lo))))
    return (tuple(q(v) for v in f_ms), tuple(q(v) for v in b_ms),
            tuple(q(v) for v in w_ms)), clipped


def quantize_cost_vectors(f_ms, b_ms, w_ms=None,
                          max_units: int = 8) -> CostVectors:
    """:func:`quantize_cost_vectors_clipped` without the clip count — for
    callers that handle/report clipping elsewhere (or don't care)."""
    return quantize_cost_vectors_clipped(f_ms, b_ms, w_ms, max_units)[0]


# -- analytic bubble fractions (module docstring's closed forms) -----------


def pipeline_bubble_fraction(num_stages: int, num_microbatches: int,
                             virtual_stages: int = 1) -> float:
    """Idle fraction of the synchronous fill-drain schedule — the classic
    (S-1)/(M*V + S-1). Identical on the half-tick grid: both the forward
    tick and the 2-half-tick combined backward idle S-1 units per device."""
    S, M, V = num_stages, num_microbatches, virtual_stages
    if S <= 1:
        return 0.0
    return (S - 1) / (M * V + S - 1)


def schedule_bubble_fraction(schedule: str, num_stages: int,
                             num_microbatches: int,
                             virtual_stages: int = 1,
                             costs: Optional[CostVectors] = None,
                             stash: int = 1) -> float:
    """Analytic bubble fraction for one shipped schedule at (S, M, V).

    fill-drain / 1f1b / zero-bubble use the closed forms (module
    docstring); interleaved / zero-bubble-h2 / searched are measured from
    their tables at runtime-plausible shapes (their packing depends on how
    the generator interleaves / defers / searches) and fall back to
    lower-bound closed forms at advisory scale. Closed forms are pinned
    against table-derived fractions by the ``pipesched`` suite. With
    ``costs`` the WEIGHTED bubble is measured from the cost-aware table
    (no closed forms exist for uneven chunks). ``stash`` is
    zero-bubble-h2's extra in-flight stash."""
    S, M, V = num_stages, num_microbatches, virtual_stages
    if S <= 1:
        return 0.0
    costs = normalize_costs(costs, S * V)
    if costs is not None:
        return make_timetable(schedule, S, M, V, costs,
                              stash=stash).bubble_fraction()
    if schedule == "fill-drain":
        return pipeline_bubble_fraction(S, M, V)
    if schedule == "1f1b" and V == 1 or schedule == "interleaved" and V == 1:
        return 2 * (S - 1) / (3 * M + 2 * (S - 1))
    if schedule == "zero-bubble" and V == 1:
        return (S - 1) / (3 * M + (S - 1))
    if bubble_is_estimate(schedule, S, M, V):
        # advisory-scale guard: the generators are pure Python (the greedy
        # O(H*S*V*M^2) worst case; the searched packer budget * O(events)
        # on top) — beyond a few thousand events, report the ideal-packing
        # LOWER BOUND instead of materializing the table for a printed
        # hint; the runtime still builds (and caches) the exact table when
        # the schedule actually executes
        if schedule in ("1f1b", "interleaved"):
            return 2 * (S - 1) / (3 * M * V + 2 * (S - 1))
        if schedule == "zero-bubble":
            return (S - 1) / (3 * M * V + (S - 1))
        if schedule == "zero-bubble-h2":
            # the zero-bubble form with the fill shrunk by the stash —
            # each extra in-flight microbatch hides one warmup idle
            d = max(0, S - 1 - stash)
            return d / (3 * M * V + d) if d else 0.0
        if schedule == "searched":
            # searched seeds include zero-bubble, so its form bounds below
            return (S - 1) / (3 * M * V + (S - 1))
    if schedule not in PIPE_SCHEDULES:
        raise ValueError(f"unknown pipe schedule {schedule!r}")
    return make_timetable(schedule, S, M, V, stash=stash).bubble_fraction()


def bubble_is_estimate(schedule: str, num_stages: int,
                       num_microbatches: int,
                       virtual_stages: int = 1) -> bool:
    """True when :func:`schedule_bubble_fraction` returns a value a
    single-step measured trace will NOT reproduce — either an
    ideal-packing LOWER BOUND (large table-derived shapes, where the pure-
    Python generators are too slow for a printed hint), or zero-bubble-h2
    ALWAYS (its analytic figure prices the wrapped steady-state period;
    one linear step measures the strictly-higher grid fraction). Callers
    reporting the figure (scalebench ``bubble_analytic``) tag it so
    measured-vs-analytic comparisons don't read an optimistic bound as
    the schedule's true prediction."""
    S, V, M = num_stages, virtual_stages, num_microbatches
    if schedule == "zero-bubble-h2":
        return True
    if schedule == "searched":
        return S * V * M > 512
    return (schedule in ("1f1b", "interleaved", "zero-bubble")
            and V > 1 and S * V * M > 2048)


def recommend_schedule(num_stages: int, num_microbatches: int,
                       virtual_stages: int = 1,
                       costs: Optional[CostVectors] = None,
                       measured: Optional[Dict[str, float]] = None,
                       ) -> List[dict]:
    """Feasible schedules at (S, M, V) with their analytic bubbles, best
    first — what --auto-partition's advisor now reports alongside the best
    V. Ranks the FULL grown family (fill-drain, 1f1b, interleaved,
    zero-bubble, zero-bubble-h2, searched); the 1f1b row is skipped at
    V > 1 where it aliases the interleaved table.

    ``costs``: per-chunk (f, b, w) half-tick vectors — rows then carry the
    WEIGHTED analytic bubble of each schedule's cost-aware table.
    ``measured``: {schedule: bubble} fractions reduced from a real trace
    (telemetry/bubble.py) — a schedule with a measured figure ranks by it
    (reality outranks the model; ROADMAP item 2c), keeping the analytic
    value alongside as ``bubble``.
    """
    S, M, V = num_stages, num_microbatches, virtual_stages
    rows = []
    for name in PIPE_SCHEDULES:
        if name == "1f1b" and V != 1:
            continue  # at V > 1 the 1f1b row IS the interleaved row
        if name != "fill-drain" and V > 1 and M % S:
            continue  # event schedules group microbatches in rounds of S
        row = {
            "schedule": name,
            "bubble": round(
                schedule_bubble_fraction(name, S, M, V, costs), 4),
            "virtual_stages": V,
        }
        if bubble_is_estimate(name, S, M, V):
            row["bubble_is_estimate"] = True
        if measured and name in measured:
            row["bubble_measured"] = round(float(measured[name]), 4)
        rows.append(row)
    rows.sort(key=lambda r: (r.get("bubble_measured", r["bubble"]),
                             r["schedule"]))
    return rows


def recommend_virtual_stages(num_stages: int, num_microbatches: int,
                             num_layers: int,
                             candidates: Tuple[int, ...] = (1, 2, 3, 4, 6, 8),
                             ) -> List[dict]:
    """Feasible interleaving factors with their bubble fractions, best first.

    Feasibility: V=1 always; V>1 needs num_microbatches % num_stages == 0
    (the interleaved timetable groups microbatches in rounds of S) and
    enough layers for S*V chunks. Rows carry the transfer count per
    microbatch so callers can weigh bubble savings against rotation cost
    (the bubble always shrinks with V; communication always grows), plus
    the best schedule at that V (recommend_schedule) now that schedules
    are data.
    """
    S, M = num_stages, num_microbatches
    rows = []
    for v in candidates:
        if v > 1 and (M % S or S * v > num_layers or S <= 1):
            continue
        if v == 1 and S * v > num_layers:
            continue
        best = recommend_schedule(S, M, v)[0]
        rows.append({
            "virtual_stages": v,
            "bubble": round(pipeline_bubble_fraction(S, M, v), 4),
            "transfers_per_microbatch": max(0, S * v - 1),
            "best_schedule": best["schedule"],
            "best_schedule_bubble": best["bubble"],
        })
    rows.sort(key=lambda r: (r["bubble"], r["virtual_stages"]))
    return rows
