"""ctypes binding for the native (C++) partitioner DP core.

Loads native/libpartitioner.so, building it with `make -C native` on first use
if the toolchain is available; ddlbench_tpu.partition.optimizer falls back to
the pure-Python DP when neither works, so the native core is an accelerator,
not a dependency.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Dict, Optional, Tuple

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libpartitioner.so")

_lib = None
_lib_failed = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    try:
        # Always run make (an incremental no-op when current): a stale .so
        # from before an ABI change would otherwise be dlopen'd and called
        # with the wrong argument layout.
        subprocess.run(
            ["make", "-C", _NATIVE_DIR, "-s"],
            check=True,
            capture_output=True,
            timeout=120,
        )
        lib = ctypes.CDLL(_LIB_PATH)
        lib.solve_level.argtypes = [
            ctypes.c_int, ctypes.c_int,
            np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
            ctypes.c_double, ctypes.c_double, ctypes.c_int, ctypes.c_int,
            ctypes.c_int,  # sync_grads (0 = forward-only partitioning)
            ctypes.c_void_p,  # base_time or NULL
            np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        ]
        lib.solve_level.restype = None
        _lib = lib
    except Exception:
        _lib_failed = True
    return _lib


def available() -> bool:
    return _load() is not None


def solve_level_native(
    times: np.ndarray,
    params: np.ndarray,
    acts: np.ndarray,
    max_units: int,
    bandwidth: float,
    hbm_bytes: float,
    versions_bound: int,
    memory_check: bool,
    sync_grads: bool = True,
    base_time: Optional[np.ndarray] = None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Run one DP level natively. Returns (A, choice_k, choice_m) with shapes
    [(n+1), (n+1), (max_units+1)]."""
    lib = _load()
    assert lib is not None
    n = len(times)
    shape = (n + 1, n + 1, max_units + 1)
    A = np.full(shape, np.inf, np.float64)
    ck = np.full(shape, -1, np.int32)
    cm = np.full(shape, -1, np.int32)
    bt_ptr = None
    if base_time is not None:
        base_time = np.ascontiguousarray(base_time, np.float64)
        bt_ptr = base_time.ctypes.data_as(ctypes.c_void_p)
    lib.solve_level(
        n, max_units,
        np.ascontiguousarray(times, np.float64),
        np.ascontiguousarray(params, np.float64),
        np.ascontiguousarray(acts, np.float64),
        float(bandwidth), float(hbm_bytes), int(versions_bound),
        int(bool(memory_check)), int(bool(sync_grads)), bt_ptr, A, ck, cm,
    )
    return A, ck, cm


def backtrack(A: np.ndarray, ck: np.ndarray, cm: np.ndarray,
              i: int, j: int, m: int):
    """[(start, end, units)] from native choice tables."""
    k, ml = int(ck[i, j, m]), int(cm[i, j, m])
    if k < 0:
        return [(i, j, m)]
    return backtrack(A, ck, cm, i, k, m - ml) + [(k, j, ml)]
