"""Hierarchical pipeline-partitioning optimizer with a TPU cost model.

Re-implements the *capability* of the reference's partitioning optimizer
(pipedream-fork/optimizer/optimizer_graph_hierarchical.py): a dynamic program
that, given per-layer profiled compute times and sizes, chooses contiguous
pipeline stages and per-stage data-parallel replication minimizing the
steady-state pipeline bottleneck — solved per interconnect level (reference:
PCIe then Ethernet, :282-297; here: ICI within a host/slice, then DCN across
hosts), the lower level's solutions becoming the upper level's compute times.
The algorithm here is written from the published PipeDream formulation with a
TPU cost model (ring-allreduce over ICI/DCN, HBM limit), not translated from
the reference source.

Cost model:
* stage compute: sum of layer fwd+bwd times / replication r
* intra-stage DP sync: ring allreduce, 2 (r-1)/r * param_bytes / bandwidth
* inter-stage edge: boundary activation bytes / bandwidth (both per minibatch)
* memory: (1 + versions) * param_bytes / r  <=  hbm_bytes, versions bounded by
  the machine count at the level (weight stashing keeps <= num_stages
  versions; conservative, reference analog optimizer_graph_hierarchical.py:38-41)

Models here are chains by construction, so the DP runs over the topological
node order directly (the chain is its own antichain linearization; for general
DAGs Graph.antichain_dag() supplies the order).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple

from ddlbench_tpu.config import HardwareModel
from ddlbench_tpu.graph.graph import Graph, Node

INF = float("inf")


@dataclasses.dataclass(frozen=True)
class StagePlan:
    start: int  # node index span [start, end)
    end: int
    replication: int  # chips running this stage data-parallel

    @property
    def num_chips(self) -> int:
        return self.replication


@dataclasses.dataclass
class PartitionResult:
    stages: List[StagePlan]
    pipeline_time_ms: float  # bottleneck (steady-state time per minibatch)
    num_chips_used: int

    def stage_bounds(self) -> List[int]:
        return [self.stages[0].start] + [s.end for s in self.stages]

    def replication_map(self) -> Dict[int, int]:
        return {i: s.replication for i, s in enumerate(self.stages)}


def _ms(bytes_: float, bandwidth: float) -> float:
    return 1000.0 * bytes_ / bandwidth if bandwidth > 0 else 0.0


def _allreduce_ms(param_bytes: float, r: int, bandwidth: float) -> float:
    if r <= 1:
        return 0.0
    return _ms(2.0 * (r - 1) / r * param_bytes, bandwidth)


class _LevelDP:
    """One level of the hierarchical DP over a chain of n nodes and m units."""

    def __init__(self, n: int, max_units: int):
        self.n = n
        self.max_units = max_units
        # A[(i, j, m)] = (time, choice); choice is None for a single
        # (replicated) stage or (k, m_last) for a split.
        self.A: Dict[Tuple[int, int, int], Tuple[float, Optional[Tuple[int, int]]]] = {}

    def solve(self, stage_cost, edge_cost):
        n, M = self.n, self.max_units
        for j in range(1, n + 1):
            for i in range(j - 1, -1, -1):
                for m in range(1, M + 1):
                    best = (stage_cost(i, j, m), None)
                    for m_last in range(1, m):
                        last = None
                        for k in range(i + 1, j):
                            t_last = stage_cost(k, j, m_last)
                            t_rest = self.A[(i, k, m - m_last)][0]
                            t = max(t_rest, edge_cost(k), t_last)
                            if t < best[0]:
                                best = (t, (k, m_last))
                    self.A[(i, j, m)] = best
        return self.A

    def backtrack(self, i: int, j: int, m: int) -> List[Tuple[int, int, int]]:
        """Return [(start, end, units)] stage spans for span (i, j] on m units."""
        time, choice = self.A[(i, j, m)]
        if choice is None:
            return [(i, j, m)]
        k, m_last = choice
        return self.backtrack(i, k, m - m_last) + [(k, j, m_last)]


def partition_hierarchical(
    graph: Graph,
    num_chips: int,
    hw: Optional[HardwareModel] = None,
    num_hosts: int = 1,
    memory_check: bool = True,
    use_native: bool = True,
    forward_only: bool = False,
) -> PartitionResult:
    """Partition a (chain) profile graph over num_chips, optionally across hosts.

    Level 0: chips within a host/slice over ICI; level 1 (if num_hosts > 1):
    hosts over DCN. With use_native (default), the DP levels run in the C++
    core (native/partitioner.cpp via ctypes) when it is buildable, falling
    back to this module's pure-Python DP otherwise; both implement the same
    recurrence and cost model.

    ``forward_only=True`` is the inference variant (reference
    optimizer/inference_optimizer_graph.py, SURVEY.md §2 C6): only forward
    compute times count, replication pays no gradient allreduce, and memory
    holds one weight copy (no stashing versions).
    """
    hw = hw or HardwareModel()
    if use_native:
        from ddlbench_tpu.partition import native

        if native.available():
            return _partition_native(graph, num_chips, hw, num_hosts,
                                     memory_check, forward_only)
    order = graph.topological_sort()
    n = len(order)
    times = [
        nd.forward_compute_time
        + (0.0 if forward_only else nd.backward_compute_time)
        for nd in order
    ]
    params = [nd.parameter_size for nd in order]
    acts = [nd.activation_size for nd in order]
    pre_t = [0.0]
    pre_p = [0.0]
    for t, p in zip(times, params):
        pre_t.append(pre_t[-1] + t)
        pre_p.append(pre_p[-1] + p)

    if num_hosts > 1:
        if num_chips % num_hosts:
            raise ValueError("num_chips must divide evenly across hosts")
        chips_per_host = num_chips // num_hosts
    else:
        chips_per_host = num_chips

    def span_time(i, j):
        return pre_t[j] - pre_t[i]

    def span_params(i, j):
        return pre_p[j] - pre_p[i]

    def mem_ok(i, j, r, versions_bound):
        if not memory_check:
            return True
        # DP replication copies the full stage parameters onto every replica
        # (it shards the batch, not the weights), so r does not divide memory.
        need = (1 + versions_bound) * span_params(i, j)
        return need <= hw.hbm_bytes

    versions0 = 0 if forward_only else chips_per_host

    # ---- level 0: chips over ICI ----
    def stage_cost0(i, j, r):
        if not mem_ok(i, j, r, versions_bound=versions0):
            return INF
        t = span_time(i, j) / r
        if forward_only:
            return t
        return t + _allreduce_ms(span_params(i, j), r, hw.ici_bandwidth)

    def edge_cost0(k):
        return _ms(acts[k - 1], hw.ici_bandwidth)

    dp0 = _LevelDP(n, chips_per_host)
    dp0.solve(stage_cost0, edge_cost0)

    if num_hosts == 1:
        spans = dp0.backtrack(0, n, chips_per_host)
        stages = [StagePlan(i, j, r) for i, j, r in spans]
        time = dp0.A[(0, n, chips_per_host)][0]
        return PartitionResult(stages, time, sum(s.replication for s in stages))

    # ---- level 1: hosts over DCN; a "unit" is one full host ----
    def stage_cost1(i, j, r):
        base = dp0.A[(i, j, chips_per_host)][0]
        if base == INF:
            return INF
        t = base / r
        if forward_only:
            return t
        return t + _allreduce_ms(span_params(i, j), r, hw.dcn_bandwidth)

    def edge_cost1(k):
        return _ms(acts[k - 1], hw.dcn_bandwidth)

    dp1 = _LevelDP(n, num_hosts)
    dp1.solve(stage_cost1, edge_cost1)

    stages: List[StagePlan] = []
    for (i, j, r_hosts) in dp1.backtrack(0, n, num_hosts):
        # expand each host-level stage into its chip-level sub-pipeline
        for (a, b, r_chips) in dp0.backtrack(i, j, chips_per_host):
            stages.append(StagePlan(a, b, r_chips * r_hosts))
    time = dp1.A[(0, n, num_hosts)][0]
    return PartitionResult(stages, time, sum(s.replication for s in stages))


def _partition_native(graph: Graph, num_chips: int, hw: HardwareModel,
                      num_hosts: int, memory_check: bool,
                      forward_only: bool = False) -> PartitionResult:
    import numpy as np

    from ddlbench_tpu.partition import native

    order = graph.topological_sort()
    n = len(order)
    times = np.array([
        nd.forward_compute_time
        + (0.0 if forward_only else nd.backward_compute_time)
        for nd in order
    ])
    params = np.array([nd.parameter_size for nd in order])
    acts = np.array([nd.activation_size for nd in order])
    if num_hosts > 1:
        if num_chips % num_hosts:
            raise ValueError("num_chips must divide evenly across hosts")
        chips_per_host = num_chips // num_hosts
    else:
        chips_per_host = num_chips

    A0, ck0, cm0 = native.solve_level_native(
        times, params, acts, chips_per_host, hw.ici_bandwidth, hw.hbm_bytes,
        versions_bound=0 if forward_only else chips_per_host,
        memory_check=memory_check, sync_grads=not forward_only,
    )
    if num_hosts == 1:
        spans = native.backtrack(A0, ck0, cm0, 0, n, chips_per_host)
        stages = [StagePlan(i, j, r) for i, j, r in spans]
        return PartitionResult(
            stages, float(A0[0, n, chips_per_host]),
            sum(s.replication for s in stages),
        )

    base = A0[:, :, chips_per_host].copy()
    A1, ck1, cm1 = native.solve_level_native(
        times, params, acts, num_hosts, hw.dcn_bandwidth, hw.hbm_bytes,
        versions_bound=num_hosts, memory_check=False,
        sync_grads=not forward_only, base_time=base,
    )
    stages: List[StagePlan] = []
    for (i, j, r_hosts) in native.backtrack(A1, ck1, cm1, 0, n, num_hosts):
        for (a, b, r_chips) in native.backtrack(A0, ck0, cm0, i, j, chips_per_host):
            stages.append(StagePlan(a, b, r_chips * r_hosts))
    return PartitionResult(
        stages, float(A1[0, n, num_hosts]), sum(s.replication for s in stages)
    )


@dataclasses.dataclass
class InterleavedPlan:
    """An interleaved (virtual-stage) plan — always executable by the grid
    runtime: C = num_stages * virtual_stages balanced chunks, device-stage s
    owning chunks {s, s+S, ...}, with UNIFORM replication.

    The flat-axis conveyor engine has no interleaved timetable, so for V > 1
    the search space is restricted to what the 2-D ('data','stage') mesh can
    run — the reference's bar is that the optimizer's output always executes
    (run_template.sh:436-498), which this guarantees by construction instead
    of by downgrade.
    """

    bounds: List[int]  # C+1 chunk bounds
    num_stages: int
    replication: int
    virtual_stages: int
    pipeline_time_ms: float


def partition_interleaved(
    graph: Graph,
    num_chips: int,
    virtual_stages: int,
    hw: Optional[HardwareModel] = None,
    num_hosts: int = 1,
    memory_check: bool = True,
    num_microbatches: Optional[int] = None,
    micro_batch: Optional[int] = None,
) -> InterleavedPlan:
    """Best executable interleaved plan: search uniform replication factors
    r | num_chips (S = num_chips/r device stages, C = S*V chunks), score each
    with the same cost model as partition_hierarchical (bottleneck of
    per-stage compute + DP allreduce vs chunk-boundary transfer), return the
    minimum. Chunk bounds are the balanced min-max split of profiled times.
    ``num_microbatches`` (when known) filters out stage counts the
    interleaved timetable cannot schedule (it groups microbatches by S);
    ``micro_batch`` filters out replication factors that cannot split the
    microbatch's rows evenly (replication = intra-microbatch row splitting,
    keeping the caller's global batch unchanged — the same convention as the
    uniform-plan rewrite in parallel/api.py).
    """
    hw = hw or HardwareModel()
    from ddlbench_tpu.parallel.packing import balanced_stage_bounds

    order = graph.topological_sort()
    n = len(order)
    times = [nd.forward_compute_time + nd.backward_compute_time
             for nd in order]
    params = [nd.parameter_size for nd in order]
    acts = [nd.activation_size for nd in order]
    if num_hosts > 1 and num_chips % num_hosts:
        raise ValueError("num_chips must divide evenly across hosts")
    chips_per_host = (num_chips // num_hosts if num_hosts > 1 else num_chips)

    best: Optional[InterleavedPlan] = None
    for r in range(1, num_chips + 1):
        if num_chips % r:
            continue
        S = num_chips // r
        C = S * virtual_stages
        if C > n:
            continue
        if num_microbatches is not None and num_microbatches % S:
            continue
        if micro_batch is not None and micro_batch % r:
            continue
        bounds = balanced_stage_bounds(times, C)
        # replicas within a host sync over ICI; wider groups pay DCN. When
        # replication spans whole hosts (r >= chips/host) every pipeline
        # fits inside one host and boundaries ride ICI; otherwise the
        # pipeline itself crosses hosts and boundary transfers pay DCN
        # (partition_hierarchical's edge_cost1, conservatively applied to
        # every boundary)
        bw = hw.ici_bandwidth if r <= chips_per_host else hw.dcn_bandwidth
        edge_bw = (hw.ici_bandwidth
                   if num_hosts == 1 or r >= chips_per_host
                   else hw.dcn_bandwidth)
        stage_ok = True
        bottleneck = 0.0
        for s in range(S):
            t = p = 0.0
            for c in range(s, C, S):
                t += sum(times[bounds[c]:bounds[c + 1]])
                p += sum(params[bounds[c]:bounds[c + 1]])
            if memory_check and (1 + S) * p > hw.hbm_bytes:
                stage_ok = False
                break
            bottleneck = max(bottleneck, t / r + _allreduce_ms(p, r, bw))
        if not stage_ok:
            continue
        for c in range(C - 1):
            bottleneck = max(bottleneck, _ms(acts[bounds[c + 1] - 1],
                                             edge_bw))
        plan = InterleavedPlan(bounds, S, r, virtual_stages, bottleneck)
        if best is None or plan.pipeline_time_ms < best.pipeline_time_ms:
            best = plan
    if best is None:
        raise ValueError(
            f"no executable interleaved plan: {num_chips} chips x "
            f"{virtual_stages} virtual stages needs some S*V <= {n} layers")
    return best


def capped_balanced_split(n: int, num_stages: int, span_cost, edge_cost,
                          span_ok) -> Optional[List[int]]:
    """Contiguous split of nodes [0, n) into EXACTLY ``num_stages`` spans
    minimizing the bottleneck ``max(span costs, cut-edge costs)`` subject to
    a per-span feasibility predicate (the memory cap).

    This is the fixed-replication specialization of :class:`_LevelDP`'s
    recurrence — replication is decided OUTSIDE (the --plan auto solver
    enumerates uniform (pp, dp, tp) factorizations, so every stage runs the
    same unit count) which collapses the unit dimension and leaves the
    classic O(n^2 * stages) min-max chain partition:

        A[j][k] = min over i of max(A[i][k-1], edge_cost(i), span_cost(i, j))
                  where span_ok(i, j)

    ``span_cost(i, j)``/``span_ok(i, j)`` see the half-open node span
    [i, j); ``edge_cost(i)`` prices the cut before node i. Returns the
    ``num_stages + 1`` bounds, or None when no feasible split exists (some
    span every split must contain violates ``span_ok``)."""
    if num_stages < 1 or num_stages > n:
        return None
    A = [[INF] * (num_stages + 1) for _ in range(n + 1)]
    choice = [[-1] * (num_stages + 1) for _ in range(n + 1)]
    A[0][0] = 0.0
    for k in range(1, num_stages + 1):
        for j in range(k, n - (num_stages - k) + 1):
            best, arg = INF, -1
            for i in range(k - 1, j):
                prev = A[i][k - 1]
                if prev == INF or not span_ok(i, j):
                    continue
                t = max(prev, span_cost(i, j),
                        edge_cost(i) if i > 0 else 0.0)
                if t < best:
                    best, arg = t, i
            A[j][k], choice[j][k] = best, arg
    if A[n][num_stages] == INF:
        return None
    bounds = [n]
    j, k = n, num_stages
    while k > 0:
        j = choice[j][k]
        bounds.append(j)
        k -= 1
    return bounds[::-1]


def stage_bounds_from_graph(graph: Graph, num_stages: int) -> List[int]:
    """Uniform-mesh helper: contiguous min-max split of measured per-node
    times into num_stages (the profiled replacement for torchgpipe's
    balance_by_time). Use partition_hierarchical for replicated plans."""
    from ddlbench_tpu.parallel.packing import balanced_stage_bounds

    order = graph.topological_sort()
    times = [nd.forward_compute_time + nd.backward_compute_time for nd in order]
    return balanced_stage_bounds(times, num_stages)


def stamp_stage_ids(graph: Graph, result: PartitionResult) -> None:
    """Write stage_id onto graph nodes (gpus=N.txt parity,
    optimizer_graph_hierarchical.py:334-346)."""
    order = graph.topological_sort()
    for sid, plan in enumerate(result.stages):
        for idx in range(plan.start, plan.end):
            order[idx].stage_id = sid
