from ddlbench_tpu.partition.optimizer import (
    PartitionResult,
    StagePlan,
    partition_hierarchical,
    stage_bounds_from_graph,
)

__all__ = [
    "PartitionResult",
    "StagePlan",
    "partition_hierarchical",
    "stage_bounds_from_graph",
]
