from ddlbench_tpu.partition.optimizer import (
    PartitionResult,
    StagePlan,
    capped_balanced_split,
    partition_hierarchical,
    stage_bounds_from_graph,
)

__all__ = [
    "PartitionResult",
    "StagePlan",
    "capped_balanced_split",
    "partition_hierarchical",
    "stage_bounds_from_graph",
]
