"""`--plan auto`: the joint dp/pp/tp solver closing profile -> graph -> plan.

The PipeDream lineage (PAPERS.md 1806.03377) chose a stage split per
topology; Piper (PAPERS.md 2606.11169) extends the search to the full
data/pipeline/tensor mix under per-chip memory caps. This module is that
missing optimizer pass for this framework: given a profiled layer graph
(profiler/profile.py node times + activation/param bytes) and the live
topology (:class:`HardwareModel` chip count / HBM cap / ICI bandwidth), it

1. enumerates every (pp, dp, tp) factorization of the world (tp gated to
   token/seq2seq workloads — transformer blocks are what gets
   Megatron-sliced) and every executable schedule at that pp,
2. solves a memory-capped compute-balanced contiguous stage split per pp
   (:func:`optimizer.capped_balanced_split`, the fixed-replication
   specialization of ``partition_hierarchical``'s ``_LevelDP``),
3. prices each candidate with the cost-aware timetable machinery
   (``make_timetable(costs=...)`` event orders repriced under the true
   float costs where small enough, the analytic
   ``schedule_bubble_fraction`` closed forms beyond)
   plus the ring-collective wire terms ``comm_stats`` prices at runtime,
4. emits the argmin as a :class:`PlanResult` and rewrites the RunConfig
   onto the existing engines: pure-dp winners run the dp ZeRO-1 engine
   (``--dp-shard-update``), pipelined winners run gpipe/pipeline_rt (with
   the hybrid PP x ZeRO-1 shard when dp > 1), tensor-sliced winners run
   tp / the tpp composition. The chosen stage bounds travel as
   ``cfg.plan_bounds`` so the engine executes exactly the split the
   solver priced.

The full decision — every candidate with its predicted step time and peak
bytes/chip, and the reason the winner won — persists in ``partition.json``
under the ``_plan_key`` cache (parallel/api.py), keyed by (model, topology,
batch grammar, plan mode) and cross-checked against the profile mode and
hardware constants, so a plan solved for one (model, topology, schedule,
cost-model) is never silently reused by another.

Cost model (single-interconnect-level: ICI; the multi-host DCN level of
``partition_hierarchical`` is a deliberate deferral — the planner targets
the in-slice mixes the PR 7/8 runtime executes):

* per-microbatch per-chip stage time: ``(f_s + b_s) / (dp * tp)`` — dp
  replicas split each microbatch's rows (the uniform-plan convention of
  parallel/api.py), tp shards the matmuls — plus the Megatron activation
  allreduces when tp > 1 (``~2 rings each way of the stage's activation
  bytes``);
* pipeline makespan: the weighted timetable's event order repriced under
  the true float costs when ``pp * M`` is small enough to materialize,
  else ``ideal / (1 - analytic bubble)``; a steady-state boundary-transfer
  bottleneck term mirrors ``partition_hierarchical``'s edge cost;
* dp sync: ring RS+AG of the bottleneck stage's parameter bytes
  (``2 (dp-1)/dp * P_s / tp`` — ZeRO-1 moves the same total wire bytes as
  the replicated ring, train/comm_stats.py);
* memory/chip: ``(weights + grads + opt) * P_s / tp`` with the optimizer
  slots divided by dp under ZeRO-1, plus the schedule's in-flight
  activation stash (all M microbatches for fill-drain, <= pp for the 1F1B
  family INCLUDING searched tables — the packer enforces the 1F1B cap —
  and <= pp + stash for zero-bubble-h2, whose extra in-flight
  microbatches are exactly what a tight ``--hbm-gb`` cap rejects; remat
  keeps one boundary activation per in-flight microbatch plus one
  layer's working set) — candidates whose peak exceeds ``hw.hbm_bytes``
  are infeasible, which is how a tight cap provably flips the chosen mix
  toward pp > 1 (or away from ZB-H2's stash).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

from ddlbench_tpu.config import HardwareModel, RunConfig
from ddlbench_tpu.graph.graph import Graph
from ddlbench_tpu.models.zoo import get_model
from ddlbench_tpu.partition.optimizer import INF, capped_balanced_split

PLAN_MODES = ("manual", "auto")

# exact weighted-makespan pricing is used while the greedy generator's
# pure-Python table stays below this many (chunk, microbatch) events;
# larger shapes fall back to the analytic closed forms (same bound family
# as schedule.bubble_is_estimate)
_EXACT_TABLE_EVENTS = 512


@dataclasses.dataclass(frozen=True)
class Candidate:
    """One (pp, dp, tp, schedule) point of the search space, priced."""

    pp: int
    dp: int
    tp: int
    schedule: str
    bounds: Optional[Tuple[int, ...]]  # pp+1 graph-node stage bounds
    step_time_ms: float  # predicted; inf when infeasible
    peak_bytes_per_chip: float
    feasible: bool
    reason: str = ""  # why infeasible / pricing notes
    # per-stage predicted resident bytes/chip (peak is their max) — the
    # audit plane (telemetry/audit.py planner_stage_hbm_audit) prices the
    # HBM model's signed per-stage error against memory_analysis() with
    # these, recorded under plan_auto["hbm_audit"] in partition.json
    stage_mem: Optional[Tuple[float, ...]] = None
    # EXTRA activation bytes/chip the schedule's lifted in-flight cap
    # stashes beyond the 1F1B family's (zero-bubble-h2 only; 0 elsewhere)
    # — recorded so partition.json says what memory the bubble was bought
    # with
    stash_bytes: float = 0.0

    def mix(self) -> str:
        return f"pp={self.pp} dp={self.dp} tp={self.tp} @{self.schedule}"

    def as_record(self) -> dict:
        return {
            "pp": self.pp, "dp": self.dp, "tp": self.tp,
            "schedule": self.schedule,
            "bounds": list(self.bounds) if self.bounds else None,
            "step_time_ms": (None if self.step_time_ms == INF
                             else round(self.step_time_ms, 4)),
            "peak_bytes_per_chip": round(self.peak_bytes_per_chip, 1),
            "feasible": self.feasible,
            "reason": self.reason,
            "stage_mem": ([round(m, 1) for m in self.stage_mem]
                          if self.stage_mem else None),
            "stash_bytes": round(self.stash_bytes, 1),
        }


@dataclasses.dataclass
class PlanResult:
    winner: Candidate
    candidates: List[Candidate]
    reason: str  # why the winner won (vs the runner-up)


def _ring_ms(bytes_: float, r: int, bw: float) -> float:
    """Ring allreduce wire time in ms — the SAME byte formula comm_stats
    reports for the executed run, so predictions and runtime accounting
    cannot silently diverge."""
    from ddlbench_tpu.train.comm_stats import _ring_allreduce_bytes

    if bw <= 0:
        return 0.0
    return 1000.0 * _ring_allreduce_bytes(bytes_, r) / bw


def _reprice_float(tt, F: Sequence[float], B: Sequence[float]) -> float:
    """Execute the timetable's event ORDER under the true float ms costs.

    ``quantize_cost_vectors`` rounds every event to half-tick units capped
    at 8, so ``half_ticks * cheapest_event`` would under-price uneven
    splits severalfold (a stage 90x the cheapest event bills 8 ticks).
    Instead, walk the events in start-half-tick order — a valid
    topological order of the dependency DAG, since a consumer starts
    strictly after its producer on the grid — and start each at
    max(device ready, producers done) with its REAL cost: the honest
    makespan of the order the runtime would execute."""
    from ddlbench_tpu.partition.schedule import (EVENT_BWD_IN, EVENT_BWD_W,
                                                 EVENT_FWD)

    S, C = tt.num_stages, tt.num_chunks
    cost = {EVENT_FWD: lambda c: F[c],
            EVENT_BWD_IN: lambda c: B[c] / 2.0,  # the quantizer's B/W split
            EVENT_BWD_W: lambda c: B[c] / 2.0}
    evs = []
    for kind in (EVENT_FWD, EVENT_BWD_IN, EVENT_BWD_W):
        for (chunk, mb), h in tt.event_times(kind).items():
            evs.append((h, chunk % S, kind, chunk, mb))
    evs.sort()
    ready = [0.0] * S
    done: Dict[Tuple[int, int, int], float] = {}
    t_end = 0.0
    for h, s, kind, chunk, mb in evs:
        if kind == EVENT_FWD:
            deps = [(EVENT_FWD, chunk - 1, mb)] if chunk > 0 else []
        elif kind == EVENT_BWD_IN:
            deps = [(EVENT_FWD, chunk, mb)]
            if chunk < C - 1:
                deps.append((EVENT_BWD_IN, chunk + 1, mb))
        else:
            deps = [(EVENT_BWD_IN, chunk, mb)]
        t0 = max([ready[s]] + [done[d] for d in deps if d in done])
        t1 = t0 + cost[kind](chunk)
        ready[s] = t1
        done[(kind, chunk, mb)] = t1
        t_end = max(t_end, t1)
    return t_end


def _pipe_ms(schedule: str, pp: int, M: int,
             F: Sequence[float], B: Sequence[float], *,
             h2_stash: int = 1, search_budget: int = 256,
             search_seed: int = 0) -> float:
    """Predicted pipeline portion of one step in ms: per-chunk forward /
    backward costs F/B (already per-chip), M microbatches, one of the
    V=1 schedules. Where the table is small enough, build the weighted
    timetable and reprice its event order under the true float costs
    (:func:`_reprice_float`); analytic bubble closed forms beyond.

    The search path quantizes at max_units=64 instead of 8 — the packer
    needs to SEE the real unevenness to place events around it, and a
    clipped vector would hand it the same flattened profile the
    heuristics already pack. zero-bubble-h2 is priced at its steady-state
    period (the per-step cost of back-to-back steps; the deferred tail-W
    overlaps the next step's warmup)."""
    if pp == 1:
        return M * (F[0] + B[0])
    from ddlbench_tpu.partition.schedule import (make_timetable,
                                                 quantize_cost_vectors,
                                                 schedule_bubble_fraction)

    if pp * M <= _EXACT_TABLE_EVENTS:
        max_units = 64 if schedule == "searched" else 8
        costs = quantize_cost_vectors(F, B, max_units=max_units)
        tt = make_timetable(schedule, pp, M, 1, costs, stash=h2_stash,
                            search_budget=search_budget,
                            search_seed=search_seed)
        ms = _reprice_float(tt, F, B)
        if schedule == "zero-bubble-h2":
            ms *= tt.steady_period() / tt.half_ticks
        return ms
    ideal = M * max(F[s] + B[s] for s in range(pp))
    frac = schedule_bubble_fraction(schedule, pp, M, stash=h2_stash)
    return ideal / max(1e-9, 1.0 - frac)


def solve_plan(graph: Graph, world: int, micro_batch: int,
               num_microbatches: int, hw: Optional[HardwareModel] = None,
               *, optimizer: str = "sgd", token_model: bool = False,
               tp_candidates: Optional[Sequence[int]] = None,
               remat: bool = True, pin_pp: Optional[int] = None,
               pin_bounds: Optional[Sequence[int]] = None,
               zero1: bool = True, h2_stash: int = 1,
               search_budget: int = 256,
               search_seed: int = 0) -> PlanResult:
    """Solve the dp/pp/tp mix + stage split + schedule for one profile
    graph on ``world`` chips. Pure host math — no devices touched.

    ``pin_pp`` constrains the stage count and ``pin_bounds`` the exact
    layer split (the elastic-resume cross-link: a checkpointed run's
    recorded split must be kept VERBATIM — same count, same cuts — so the
    per-stage packed rows line up and the dp-axis reshard stays a
    permutation); tp candidates are then excluded (the recorded ZeRO-1
    flat layouts have no tp axis). ``zero1=False`` prices the replicated
    optimizer state (MoE archs, where the explicit dp collective engine
    is unavailable). ``h2_stash`` sizes zero-bubble-h2's extra in-flight
    stash (both its memory term and its steady-state pricing);
    ``search_budget``/``search_seed`` parameterize the searched packer so
    the priced table is exactly the one the runtime will execute."""
    hw = hw or HardwareModel()
    order = graph.topological_sort()
    n = len(order)
    if n == 0:
        raise ValueError("empty profile graph")
    f = [nd.forward_compute_time for nd in order]
    b = [nd.backward_compute_time for nd in order]
    p = [nd.parameter_size for nd in order]
    a = [nd.activation_size for nd in order]
    pre_f = [0.0]
    pre_b = [0.0]
    pre_p = [0.0]
    pre_a = [0.0]
    for i in range(n):
        pre_f.append(pre_f[-1] + f[i])
        pre_b.append(pre_b[-1] + b[i])
        pre_p.append(pre_p[-1] + p[i])
        pre_a.append(pre_a[-1] + a[i])
    # sparse table over a[] for O(1) range max — stage_mem runs inside
    # capped_balanced_split's O(n^2 * pp) inner loop, so an O(n) slice
    # there would make each candidate O(n^3 * pp) in pure Python
    log2 = [0] * (n + 1)
    for i in range(2, n + 1):
        log2[i] = log2[i >> 1] + 1
    sp_a = [list(a)]
    k = 1
    while (1 << k) <= n:
        prev = sp_a[-1]
        half = 1 << (k - 1)
        sp_a.append([max(prev[i], prev[i + half])
                     for i in range(n - (1 << k) + 1)])
        k += 1

    def max_a(i, j):
        """max(a[i:j]), 0.0 when empty."""
        if i >= j:
            return 0.0
        k = log2[j - i]
        return max(sp_a[k][i], sp_a[k][j - (1 << k)])
    M = num_microbatches
    opt_slots = 2.0 if optimizer == "adam" else 1.0
    if tp_candidates is None:
        tp_candidates = [t for t in (2, 4, 8) if world % t == 0] \
            if token_model else []
    if pin_pp is not None:
        tp_candidates = []
    if pin_bounds is not None:
        pb = tuple(int(x) for x in pin_bounds)
        if pin_pp is None or len(pb) != pin_pp + 1 or pb[0] != 0 or \
                pb[-1] != n or any(x >= y for x, y in zip(pb, pb[1:])):
            raise ValueError(
                f"pin_bounds {pin_bounds} must be pin_pp+1 strictly "
                f"increasing cuts from 0 to the graph's {n} nodes")
        pin_bounds = pb

    def span_f(i, j):
        return pre_f[j] - pre_f[i]

    def span_b(i, j):
        return pre_b[j] - pre_b[i]

    def span_p(i, j):
        return pre_p[j] - pre_p[i]

    def span_a(i, j):
        return pre_a[j] - pre_a[i]

    candidates: List[Candidate] = []

    def consider(pp: int, dp: int, tp: int, schedule: str) -> None:
        denom = dp * tp
        shard = zero1 and tp == 1  # the engines the mapping selects
        pmult = 2.0 + opt_slots / (dp if shard else 1)

        def _inflight():
            if schedule == "fill-drain":
                return M
            extra = h2_stash if schedule == "zero-bubble-h2" else 0
            return min(M, pp + extra)

        def stage_mem(i, j):
            """Predicted resident bytes/chip for span [i, j)."""
            weights = pmult * span_p(i, j) / tp
            if pp == 1:
                # one-apply engines: the whole per-device batch's
                # activations live through the backward (M microbatches'
                # rows land in one forward)
                acts = span_a(i, j) * M / denom
            else:
                # searched tables keep the strict 1F1B cap (the packer
                # rejects cap-busting orders); zero-bubble-h2 stashes
                # h2_stash extra in-flight microbatches per chunk
                inflight = _inflight()
                # remat stashes one boundary activation per in-flight
                # microbatch (+ one layer's working set during recompute);
                # without it the whole span's interiors stay live
                boundary = a[i - 1] if i > 0 else a[0]
                stash = (boundary if remat else span_a(i, j))
                acts = (inflight * stash + max_a(i, j)) / denom
            return weights + acts

        def stage_stash_extra(i, j):
            """Bytes/chip the schedule stashes BEYOND the 1F1B cap."""
            if pp == 1 or schedule in ("fill-drain",):
                return 0.0
            extra = _inflight() - min(M, pp)
            if extra <= 0:
                return 0.0
            boundary = a[i - 1] if i > 0 else a[0]
            return extra * (boundary if remat else span_a(i, j)) / denom

        def stage_ms_f(i, j):
            t = span_f(i, j) / denom
            if tp > 1:
                # Megatron block allreduces: ~2 rings over the span's
                # activation bytes each direction (rows already /dp)
                t += _ring_ms(2.0 * span_a(i, j) / dp, tp,
                              hw.ici_bandwidth)
            return t

        def stage_ms_b(i, j):
            t = span_b(i, j) / denom
            if tp > 1:
                t += _ring_ms(2.0 * span_a(i, j) / dp, tp,
                              hw.ici_bandwidth)
            return t

        def edge_ms(i):  # cut before node i: boundary activation transfer
            return 1000.0 * (a[i - 1] / dp) / hw.ici_bandwidth

        # feasibility gates before the split DP
        if pp > n:
            candidates.append(Candidate(
                pp, dp, tp, schedule, None, INF, 0.0, False,
                f"{pp} stages need {pp} layers; graph has {n}"))
            return
        if pp > 1 or tp > 1:
            if micro_batch % dp:
                candidates.append(Candidate(
                    pp, dp, tp, schedule, None, INF, 0.0, False,
                    f"micro-batch {micro_batch} not divisible by dp={dp}"))
                return
        elif (micro_batch * M) % dp:
            candidates.append(Candidate(
                pp, dp, tp, schedule, None, INF, 0.0, False,
                f"global batch {micro_batch * M} not divisible by "
                f"dp={dp}"))
            return

        if pin_bounds is not None:
            # elastic resume: the checkpoint's exact recorded cuts, priced
            # (and memory-gated) at the new world rather than re-chosen —
            # per-stage packed rows must line up for the dp reshard
            bounds = list(pin_bounds)
            peak0 = max(stage_mem(bounds[s], bounds[s + 1])
                        for s in range(pp))
            if peak0 > hw.hbm_bytes:
                candidates.append(Candidate(
                    pp, dp, tp, schedule, tuple(bounds), INF, peak0, False,
                    f"checkpoint-pinned split needs {peak0 / 2**30:.2f} "
                    f"GiB/chip of {hw.hbm_bytes / 2**30:.2f} GiB at the "
                    f"new world"))
                return
        else:
            bounds = capped_balanced_split(
                n, pp, lambda i, j: stage_ms_f(i, j) + stage_ms_b(i, j),
                edge_ms, lambda i, j: stage_mem(i, j) <= hw.hbm_bytes)
        if bounds is None:
            # report the memory the best UNCAPPED split would need, so the
            # record says why the cap killed the candidate
            free = capped_balanced_split(
                n, pp, lambda i, j: stage_ms_f(i, j) + stage_ms_b(i, j),
                edge_ms, lambda i, j: True)
            need = max(stage_mem(free[s], free[s + 1]) for s in range(pp)) \
                if free else 0.0
            candidates.append(Candidate(
                pp, dp, tp, schedule, None, INF, need, False,
                f"exceeds HBM cap: best split needs "
                f"{need / 2**30:.2f} GiB/chip of "
                f"{hw.hbm_bytes / 2**30:.2f} GiB"))
            return
        F = [stage_ms_f(bounds[s], bounds[s + 1]) for s in range(pp)]
        B = [stage_ms_b(bounds[s], bounds[s + 1]) for s in range(pp)]
        pipe = _pipe_ms(schedule, pp, M, F, B, h2_stash=h2_stash,
                        search_budget=search_budget,
                        search_seed=search_seed)
        # steady-state boundary bottleneck (activation fwd + gradient bwd
        # per microbatch per interior cut), partition_hierarchical-style
        if pp > 1:
            worst_edge = max(edge_ms(bounds[s]) for s in range(1, pp))
            pipe = max(pipe, M * 2.0 * worst_edge)
        sync = max(_ring_ms(span_p(bounds[s], bounds[s + 1]) / tp, dp,
                            hw.ici_bandwidth)
                   for s in range(pp))
        mems = tuple(stage_mem(bounds[s], bounds[s + 1])
                     for s in range(pp))
        candidates.append(Candidate(
            pp, dp, tp, schedule, tuple(bounds), pipe + sync, max(mems),
            True, stage_mem=mems,
            stash_bytes=max(stage_stash_extra(bounds[s], bounds[s + 1])
                            for s in range(pp))))

    pps = [d for d in range(1, world + 1) if world % d == 0]
    if pin_pp is not None:
        pps = [pin_pp] if world % pin_pp == 0 else []
        if not pps:
            raise ValueError(
                f"checkpoint-pinned stage count {pin_pp} does not divide "
                f"the new world {world}; restart at the saved topology")
    for pp in pps:
        rest = world // pp
        for dp in [d for d in range(1, rest + 1) if rest % d == 0]:
            tp = rest // dp
            if tp > 1 and tp not in tp_candidates:
                # still RECORDED, so partition.json shows why every
                # factorization of the world was ruled out
                if pin_pp is not None:
                    reason = ("elastic pin: the checkpoint's recorded "
                              "ZeRO-1 flat layouts have no tp axis")
                elif not token_model:
                    reason = ("tensor parallelism needs a token/seq2seq "
                              "benchmark (transformer blocks get sliced)")
                else:
                    reason = (f"tp={tp} outside the supported widths "
                              f"{sorted(tp_candidates)}")
                candidates.append(Candidate(
                    pp, dp, tp, "fill-drain", None, INF, 0.0, False,
                    reason))
                continue
            if pp == 1:
                consider(pp, dp, tp, "fill-drain")
            elif tp > 1:
                # the tpp composition executes the fill-drain scan only
                consider(pp, dp, tp, "fill-drain")
            else:
                for schedule in ("fill-drain", "1f1b", "zero-bubble",
                                 "zero-bubble-h2", "searched"):
                    consider(pp, dp, tp, schedule)

    feasible = [c for c in candidates if c.feasible]
    if not feasible:
        detail = "; ".join(f"{c.mix()}: {c.reason}" for c in candidates[:6])
        raise ValueError(
            f"--plan auto: no feasible (pp, dp, tp) mix for world={world} "
            f"under the {hw.hbm_bytes / 2**30:.2f} GiB/chip cap ({detail})")
    ranked = sorted(feasible,
                    key=lambda c: (c.step_time_ms, c.pp, -c.dp, c.tp,
                                   c.schedule))
    winner = ranked[0]
    if len(ranked) > 1:
        ru = ranked[1]
        reason = (f"{winner.mix()} predicts {winner.step_time_ms:.3f} "
                  f"ms/step vs {ru.step_time_ms:.3f} for next-best "
                  f"{ru.mix()}; peak {winner.peak_bytes_per_chip / 2**30:.3f}"
                  f" GiB/chip of {hw.hbm_bytes / 2**30:.2f} GiB cap")
    else:
        reason = (f"{winner.mix()} is the only feasible mix "
                  f"({winner.step_time_ms:.3f} ms/step predicted)")
    if pin_pp is not None:
        reason += f" [stage count pinned to checkpoint pp={pin_pp}]"
    return PlanResult(winner, candidates, reason)


# ---- config-level resolution: profile -> solve -> rewrite -> cache --------


def _rewrite_fields(cfg: RunConfig, winner: Candidate, micro_batch: int,
                    num_microbatches: int,
                    force_shard: bool = False) -> Dict[str, object]:
    """The ``cfg.replace`` kwargs that map the winning mix onto the
    existing engines. The rewrite PRESERVES the global batch
    (micro_batch * num_microbatches under the pre-plan gpipe accounting)
    and returns a plan='manual' config — by construction equal to the same
    mix passed explicitly, which is what the bitwise end-to-end pin holds
    the planner to."""
    world = cfg.num_devices
    base: Dict[str, object] = dict(
        plan="manual", auto_partition=False, plan_bounds=None,
        num_stages=None, dp_replicas=1, tp_size=1, dp_shard_update=False,
        batch_size=None, micro_batch_size=None, num_microbatches=None,
        pipe_schedule="fill-drain")
    global_batch = micro_batch * num_microbatches
    if world == 1:
        if force_shard:
            # elastic resume of a dp ZeRO-1 checkpoint onto one device:
            # the recorded flat layout needs the dp engine (a 'single'
            # rewrite would hit reshard's engine-mismatch error)
            base.update(strategy="dp", batch_size=global_batch,
                        dp_shard_update=True)
        else:
            base.update(strategy="single", batch_size=global_batch)
        return base
    pp, dp, tp = winner.pp, winner.dp, winner.tp
    if pp == 1 and tp == 1:
        # pure data parallelism: the dp ZeRO-1 engine (explicit sharded
        # weight update) — except MoE archs, whose router statistics need
        # the replicated engine (config.validate).
        base.update(strategy="dp", batch_size=global_batch // dp,
                    dp_shard_update="moe" not in cfg.arch or force_shard)
        return base
    if pp == 1 and dp == 1:
        # pure tensor parallelism: the standalone Megatron-sharded engine
        base.update(strategy="tp", batch_size=global_batch)
        return base
    base.update(
        strategy="gpipe", num_stages=pp, dp_replicas=dp, tp_size=tp,
        micro_batch_size=micro_batch // dp,
        num_microbatches=num_microbatches,
        pipe_schedule=winner.schedule,
        # hybrid PP x ZeRO-1 shard axis (the tpp composition keeps the
        # replicated update; validate scopes the shard to tp_size == 1)
        dp_shard_update=(dp > 1 or force_shard) and tp == 1,
        plan_bounds=tuple(winner.bounds) if winner.pp > 1 else None)
    return base


def _recorded_bounds(cfg: RunConfig, stages: int
                     ) -> Optional[Tuple[int, ...]]:
    """The stage cuts the original --plan auto run recorded in
    partition.json (the winner's graph-node bounds), regardless of the
    key — on an elastic resume the key's num_devices changed, but the
    SPLIT is exactly what must survive the world change."""
    from ddlbench_tpu.parallel.api import _plan_path

    path = _plan_path(cfg)
    if not (path and os.path.exists(path)):
        return None
    try:
        with open(path) as f:
            doc = json.load(f)
    except (json.JSONDecodeError, OSError):
        return None
    w = (doc.get("plan_auto") or {}).get("winner") or {}
    b = w.get("bounds")
    if w.get("pp") == stages and isinstance(b, list) and \
            len(b) == stages + 1:
        return tuple(int(x) for x in b)
    return None


def _elastic_pin(cfg: RunConfig
                 ) -> Tuple[Optional[int], Optional[Tuple[int, ...]],
                            bool, str]:
    """(pin_pp, pin_bounds, force_shard, note): the elastic-resume
    cross-link. A run resuming onto a new world with ``--elastic-resume``
    must keep the checkpoint's recorded stage split VERBATIM — the
    dp-axis reshard is a pure permutation; a changed stage count OR a
    moved cut re-shapes the per-stage packed rows (train/reshard.py) —
    so the planner re-solves CONSTRAINED to the recorded split instead
    of the restore raising CheckpointShapeError at a freely chosen one.
    The cuts come from the prior run's partition.json winner; if that
    file is gone only the stage count is pinned (best effort — the
    balanced re-solve usually reproduces the same cuts, and a mismatch
    still fails loudly at restore)."""
    if not (cfg.resume and cfg.elastic_resume and cfg.checkpoint_dir):
        return None, None, False, ""
    from ddlbench_tpu.train.checkpoint import latest_valid, load_logical

    info = latest_valid(cfg.checkpoint_dir)
    if info is None:
        return None, None, False, ""
    saved = load_logical(info.path)
    if not saved:
        return None, None, False, ""
    kind = saved.get("kind")
    if kind == "pipe_shard":
        stages = int(saved["stages"])
        bounds = _recorded_bounds(cfg, stages)
        return stages, bounds, True, (
            f"elastic resume: stage split pinned to the checkpoint's "
            f"S={stages}"
            + (f" at the recorded cuts {list(bounds)}" if bounds else "")
            + f" (world {saved.get('world')} -> {cfg.num_devices}; "
            f"the dp-axis reshard is a permutation, a new split is not)")
    if kind == "dp_shard":
        return 1, None, True, (
            f"elastic resume: pp=1 pinned to the checkpoint's dp ZeRO-1 "
            f"layout (world {saved.get('world')} -> {cfg.num_devices})")
    return None, None, False, ""


def _model_tp_widths(arch: str, world: int) -> List[int]:
    """The tp widths the Megatron splitter can actually EXECUTE for
    ``arch``: they must divide the world, the head count, d_model, and
    the MLP width (the trace-time asserts in models/transformer.py —
    tp_split_layer_params and attention_sublayer). Archs without sliced
    attention blocks (LSTM seq2seq, unknown variants) get none: the
    planner must never emit a plan the engine cannot run."""
    import ddlbench_tpu.models.moe as moe
    import ddlbench_tpu.models.seq2seq as seq2seq
    import ddlbench_tpu.models.transformer as tr

    v = (tr._VARIANTS.get(arch) or seq2seq._VARIANTS.get(arch)
         or moe._VARIANTS.get(arch))
    if not v or "n_heads" not in v:
        return []
    d, h = v["d_model"], v["n_heads"]
    mlp = 4 * d  # transformer_block's mlp_ratio=4 FFN width
    return [t for t in (2, 4, 8)
            if world % t == 0 and h % t == 0 and d % t == 0
            and mlp % t == 0]


def plan_for_config(cfg: RunConfig, input_time_ms: float = 0.0
                    ) -> Tuple[PlanResult, Dict[str, object], Graph]:
    """Profile ``cfg``'s model and solve the mix (no cache, no persist):
    returns (plan, cfg-replace kwargs, profile graph). The substrate
    tools/planbench.py prices prediction error with."""
    from ddlbench_tpu.profiler.profile import fold_input_node, profile_model

    spec = cfg.dataset()
    from ddlbench_tpu.models.branchy import get_dag

    if get_dag(cfg.arch, spec.image_size, spec.num_classes) is not None:
        raise ValueError(
            f"--plan auto covers chain archs; {cfg.arch!r} is a branchy "
            f"DAG — use --auto-partition (its packed-boundary chainization "
            f"solves the split at a fixed strategy)")
    mb, chunks = cfg.resolved_batches()
    model = get_model(cfg.arch, cfg.benchmark,
                      moe_capacity_factor=cfg.moe_capacity_factor)
    graph = profile_model(model, mb, mode=cfg.profile_mode, hw=cfg.hardware,
                          input_time_ms=input_time_ms)
    graph = fold_input_node(graph)
    pin_pp, pin_bounds, force_shard, note = _elastic_pin(cfg)
    if note:
        print(f"plan auto: {note}", flush=True)
    if pin_bounds is not None and pin_bounds[-1] != len(graph.nodes):
        # the recorded cuts index a different profile graph (the model or
        # the profiler changed): drop the cut pin, keep the count pin —
        # a genuinely moved split still fails loudly at restore
        print(f"plan auto: recorded cuts {list(pin_bounds)} do not span "
              f"this profile's {len(graph.nodes)} nodes; pinning the "
              f"stage count only", flush=True)
        pin_bounds = None
    token_model = spec.kind in ("tokens", "seq2seq")
    plan = solve_plan(
        graph, cfg.num_devices, mb, chunks, cfg.hardware,
        optimizer=cfg.resolved_optimizer(), token_model=token_model,
        tp_candidates=(_model_tp_widths(cfg.arch, cfg.num_devices)
                       if token_model else []),
        remat=cfg.remat_stages, pin_pp=pin_pp, pin_bounds=pin_bounds,
        zero1="moe" not in cfg.arch, h2_stash=cfg.zb_h2_stash,
        search_budget=cfg.sched_search_budget,
        search_seed=cfg.sched_search_seed)
    rewrite = _rewrite_fields(cfg, plan.winner, mb, chunks,
                              force_shard=force_shard)
    return plan, rewrite, graph


# ---- the partition.json cache ---------------------------------------------


def _cache_fingerprint(cfg: RunConfig) -> dict:
    """The cost-model half of the cache identity: the _plan_key covers
    (model, topology, batch grammar, plan mode); a plan additionally
    depends on HOW costs were obtained. One rule for both plan kinds
    (parallel/api._plan_fingerprint)."""
    from ddlbench_tpu.parallel.api import _plan_fingerprint

    return _plan_fingerprint(cfg)


def _load_cached(cfg: RunConfig, key: dict) -> Optional[dict]:
    from ddlbench_tpu.parallel.api import _plan_path

    path = _plan_path(cfg)
    if not (cfg.resume and path and os.path.exists(path)):
        return None
    try:
        with open(path) as f:
            doc = json.load(f)
    except (json.JSONDecodeError, OSError) as e:
        print(f"plan auto: ignoring unreadable plan {path} ({e}); "
              f"re-solving", flush=True)
        return None
    pkey = doc.get("key")
    if isinstance(pkey, dict) and "plan" not in pkey:
        # pre-plan-mode schema: written before the plan field existed —
        # invalidate loudly and re-solve (never KeyError on the missing
        # field, never silently reuse a plan solved under other semantics)
        print(f"plan auto: persisted plan {path} predates the --plan mode "
              f"field; invalidating and re-solving", flush=True)
        return None
    if pkey != key:
        print(f"plan auto: persisted plan {path} was solved for {pkey}, "
              f"run is {key}; re-solving (the existing file is backed up "
              f"on save)", flush=True)
        return None
    rec = doc.get("plan_auto")
    if not isinstance(rec, dict) or "rewrite" not in rec:
        print(f"plan auto: persisted plan {path} carries no plan_auto "
              f"record; re-solving", flush=True)
        return None
    if rec.get("fingerprint") != _cache_fingerprint(cfg):
        print(f"plan auto: persisted plan {path} was priced under a "
              f"different cost model ({rec.get('fingerprint')}); "
              f"re-solving", flush=True)
        return None
    return doc


def _save_cached(cfg: RunConfig, key: dict, plan: PlanResult,
                 rewrite: Dict[str, object]) -> None:
    from ddlbench_tpu.parallel.api import _backup_foreign_plan, _plan_path

    path = _plan_path(cfg)
    if path is None:
        return
    os.makedirs(cfg.checkpoint_dir, exist_ok=True)
    _backup_foreign_plan(path, key)
    payload = {
        "key": key,
        "plan_auto": {
            "fingerprint": _cache_fingerprint(cfg),
            "winner": plan.winner.as_record(),
            "candidates": [c.as_record() for c in plan.candidates],
            "reason": plan.reason,
            "rewrite": {k: (list(v) if isinstance(v, tuple) else v)
                        for k, v in rewrite.items()},
        },
    }
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)


def _apply_rewrite(cfg: RunConfig, rewrite: Dict[str, object]) -> RunConfig:
    kw = dict(rewrite)
    if kw.get("plan_bounds") is not None:
        kw["plan_bounds"] = tuple(int(x) for x in kw["plan_bounds"])
    out = cfg.replace(**kw)
    out.validate()
    return out


def resolve_auto_plan(cfg: RunConfig,
                      input_time_ms=0.0) -> RunConfig:
    """The ``--plan auto`` entry point: returns the config rewritten onto
    the winning mix (a plan='manual' config, equal to the explicit flags),
    solving at most once per (model, topology, batch grammar, cost model)
    — the decision persists in partition.json next to the checkpoints and
    a ``--resume`` reuses it instead of re-profiling. ``input_time_ms``
    may be a zero-arg callable (the real-data loader probe): it is only
    evaluated on a cache MISS, so a resume that reuses the persisted plan
    never pays the probe."""
    if cfg.plan != "auto":
        return cfg
    cfg.validate()
    from ddlbench_tpu.parallel.api import _plan_key

    key = _plan_key(cfg)
    cached = _load_cached(cfg, key)
    if cached is not None:
        pin_pp, _, _, _ = _elastic_pin(cfg)
        w = cached["plan_auto"].get("winner", {})
        # the elastic pin is solved-in, not part of the key: a cached plan
        # whose stage count mismatches the checkpoint's must re-solve.
        # (No bounds comparison here — _recorded_bounds reads the SAME
        # file's winner, so when the pp matches the bounds match by
        # construction.)
        if pin_pp is not None and w.get("pp") != pin_pp:
            print(f"plan auto: persisted plan's stage count "
                  f"{w.get('pp')} mismatches the checkpoint's pinned "
                  f"{pin_pp}; re-solving", flush=True)
            cached = None
    if cached is not None:
        rec = cached["plan_auto"]
        w = rec.get("winner", {})
        print(f"plan auto: reusing persisted plan (pp={w.get('pp')} "
              f"dp={w.get('dp')} tp={w.get('tp')} @{w.get('schedule')}, "
              f"{len(rec.get('candidates', []))} candidates considered)",
              flush=True)
        return _apply_rewrite(cfg, rec["rewrite"])
    if callable(input_time_ms):
        input_time_ms = input_time_ms()
    plan, rewrite, _ = plan_for_config(cfg, input_time_ms=input_time_ms)
    _save_cached(cfg, key, plan, rewrite)
    w = plan.winner
    print(f"plan auto: {plan.reason}", flush=True)
    print(f"plan auto: executing pp={w.pp} dp={w.dp} tp={w.tp} "
          f"@{w.schedule} (bounds={list(w.bounds) if w.bounds else None}, "
          f"predicted {w.step_time_ms:.3f} ms/step, peak "
          f"{w.peak_bytes_per_chip / 2**30:.3f} GiB/chip; "
          f"{len(plan.candidates)} candidates considered)", flush=True)
    return _apply_rewrite(cfg, rewrite)
