"""Searched pipeline timetables: budgeted local search over event orders.

The heuristic factory (partition/schedule.py) picks the better of exactly
two candidates per schedule family — the cost-aware greedy table and the
unit-cost order repriced under true costs. On genuinely uneven profiled
chunks both leave bubble on the table: the greedy commits one device at a
time and the unit order was packed for the F=B=W fiction. This module is
the Piper direction (PAPERS.md 2606.11169: search the schedule space,
don't hand-pick a point) on top of that machinery:

* **Representation.** A schedule is its PER-DEVICE EVENT ORDER — one
  tuple of (kind, chunk, microbatch) per device. Start times are derived
  by list-scheduling (:func:`simulate_orders`): each device runs its
  events in order, each starting at max(device free, producer end). The
  cross-device interleaving of independent events therefore never needs
  to be searched — only the per-device orders do.
* **Seeds.** Both heuristics of every 1F1B-memory family
  (``SEARCH_SEED_SCHEDULES``: 1f1b and zero-bubble; fill-drain is the
  autodiff scan, zero-bubble-h2 trades memory) — so the searched table
  NEVER packs worse than the min-of-two-heuristics the factory shipped
  before this module existed.
* **Moves.** Deterministic first-improvement ADJACENT-SWAP sweeps per
  device, then seeded random SHIFT moves (pull one event a few slots
  earlier/later) with the remaining budget. Every move is evaluated by
  re-simulation; strictly-better makespan only (busy cells are fixed, so
  minimizing makespan IS minimizing the bubble fraction).
* **Legality.** A move must keep the per-device order schedulable (the
  list scheduler deadlocks otherwise → move rejected) and within the
  1F1B in-flight cap ``min(M, C - c)`` per chunk — a pure ORDER property
  (:func:`caps_ok`), so searched tables inherit 1F1B activation memory
  and the planner prices them with the same ``min(M, pp)`` stash term.
  :func:`check_legal` is the public validator every generated table —
  heuristic or searched — must pass (the pipesched suite pins a
  hand-corrupted table failing it).
* **Determinism.** Fixed move budget + ``np.random.default_rng(seed)``:
  the same (S, M, V, costs, budget, seed) reproduces the table bitwise,
  which is what makes :func:`searched_timetable` ``lru_cache``-able and
  the planner's pricing stable across re-plans.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, List, Optional, Tuple

import numpy as np

from ddlbench_tpu.partition.schedule import (
    EVENT_BWD_IN, EVENT_BWD_W, EVENT_FWD, SEARCH_SEED_SCHEDULES,
    CostVectors, Timetable, _greedy_timetable, normalize_costs,
    reprice_timetable, timetable_from_times)

# (kind, chunk, microbatch) — one entry per event, per device, in order
DeviceOrders = Tuple[Tuple[Tuple[int, int, int], ...], ...]


def orders_of(tt: Timetable) -> DeviceOrders:
    """``tt``'s per-device event order (the search representation)."""
    per_dev: Dict[int, List[Tuple[int, int, int, int]]] = {
        s: [] for s in range(tt.num_stages)}
    for kind in (EVENT_FWD, EVENT_BWD_IN, EVENT_BWD_W):
        for (c, m), h in tt.event_times(kind).items():
            per_dev[c % tt.num_stages].append((h, kind, c, m))
    return tuple(tuple((k, c, m) for _, k, c, m in sorted(per_dev[s]))
                 for s in range(tt.num_stages))


def simulate_orders(orders: DeviceOrders, S: int, V: int, M: int,
                    costs: Optional[CostVectors]):
    """List-schedule per-device orders into start times: every device runs
    its events in order, each starting at max(device free, producer end).
    Returns ``(F, B, W, makespan)`` start-time tables, or None when the
    order deadlocks (a device's head waits on an event stuck behind it) —
    the searched packer's illegal-move signal."""
    C = S * V
    fc, bc, wc = costs if costs is not None else ((1,) * C,) * 3
    F: Dict[Tuple[int, int], int] = {}
    B: Dict[Tuple[int, int], int] = {}
    W: Dict[Tuple[int, int], int] = {}
    free = [0] * S
    ptr = [0] * S
    placed, total = 0, sum(len(o) for o in orders)
    while placed < total:
        progressed = False
        for s in range(S):
            while ptr[s] < len(orders[s]):
                kind, c, m = orders[s][ptr[s]]
                if kind == EVENT_FWD:
                    if c > 0 and (c - 1, m) not in F:
                        break
                    arrival = 0 if c == 0 else F[(c - 1, m)] + fc[c - 1]
                    start = max(free[s], arrival)
                    F[(c, m)] = start
                    free[s] = start + fc[c]
                elif kind == EVENT_BWD_IN:
                    if (c, m) not in F or (c < C - 1 and (c + 1, m) not in B):
                        break
                    arrival = (F[(c, m)] + fc[c] if c == C - 1
                               else B[(c + 1, m)] + bc[c + 1])
                    start = max(free[s], arrival, F[(c, m)] + fc[c])
                    B[(c, m)] = start
                    free[s] = start + bc[c]
                else:
                    if (c, m) not in B:
                        break
                    start = max(free[s], B[(c, m)] + bc[c])
                    W[(c, m)] = start
                    free[s] = start + wc[c]
                ptr[s] += 1
                placed += 1
                progressed = True
        if not progressed:
            return None
    return F, B, W, max(free)


def caps_ok(orders: DeviceOrders, S: int, V: int, M: int,
            extra_inflight: int = 0) -> bool:
    """True when every device order respects the per-chunk in-flight cap
    ``min(M, C - c + extra_inflight)`` (microbatches with F scheduled, B
    not). A pure ORDER property: all of a chunk's F and B events live on
    one device, and any rebuild that preserves per-device order preserves
    their interleaving — so the searched packer can reject cap-busting
    moves without a simulation."""
    C = S * V
    for order in orders:
        inflight: Dict[int, int] = {}
        for kind, c, _m in order:
            if kind == EVENT_FWD:
                inflight[c] = inflight.get(c, 0) + 1
                if inflight[c] > min(M, C - c + extra_inflight):
                    return False
            elif kind == EVENT_BWD_IN:
                inflight[c] = inflight.get(c, 0) - 1
    return True


def chunk_inflight(tt: Timetable) -> Tuple[int, ...]:
    """Per-chunk peak in-flight count (F scheduled, B not) — the
    activation-stash high-water the schedule implies, per chunk."""
    orders = orders_of(tt)
    C = tt.num_chunks
    peak = [0] * C
    for order in orders:
        inflight: Dict[int, int] = {}
        for kind, c, _m in order:
            if kind == EVENT_FWD:
                inflight[c] = inflight.get(c, 0) + 1
                peak[c] = max(peak[c], inflight[c])
            elif kind == EVENT_BWD_IN:
                inflight[c] = inflight.get(c, 0) - 1
    return tuple(peak)


def check_legal(tt: Timetable, extra_inflight: Optional[int] = 0) -> None:
    """The legality validator every generated table — heuristic or
    searched — must pass. Raises AssertionError with the violated
    relation.

    * per-stage serialization + F→B→W microbatch dependencies + event
      coverage + chunk locality: :meth:`Timetable.validate`;
    * in-flight/stash caps: per-chunk peak in-flight (F done, B not) must
      stay within ``min(M, C - c + extra_inflight)``. ``extra_inflight=0``
      is the strict 1F1B cap (1f1b / zero-bubble / searched tables);
      ZB-H2 passes its stash; ``None`` skips the cap check (fill-drain
      legitimately holds all M microbatches in flight).
    """
    tt.validate()
    if extra_inflight is None:
        return
    C, M = tt.num_chunks, tt.num_microbatches
    peaks = chunk_inflight(tt)
    for c in range(C):
        cap = min(M, C - c + extra_inflight)
        assert peaks[c] <= cap, (
            f"{tt.name}: chunk {c} holds {peaks[c]} microbatches in "
            f"flight; cap is {cap} (extra_inflight={extra_inflight})")


def _seed_tables(S: int, M: int, V: int,
                 costs: Optional[CostVectors]) -> List[Timetable]:
    """Both heuristics of every seed family: the cost-aware greedy table
    and the unit-cost order repriced under true costs — exactly the
    candidates the factory's min-of-two picks from, so the searched
    result is ≤ that min by construction."""
    seeds: List[Timetable] = []
    for name in SEARCH_SEED_SCHEDULES:
        defer = name == "zero-bubble"
        unit = _greedy_timetable(name, S, M, V, defer_weight_grads=defer)
        if costs is None:
            seeds.append(unit)
        else:
            seeds.append(_greedy_timetable(name, S, M, V,
                                           defer_weight_grads=defer,
                                           costs=costs))
            seeds.append(reprice_timetable(unit, costs))
    return seeds


@functools.lru_cache(maxsize=32)
def searched_timetable(S: int, M: int, V: int = 1,
                       costs: Optional[CostVectors] = None,
                       budget: int = 256, seed: int = 0) -> Timetable:
    """Budgeted local search over per-device event orders (module
    docstring). ``budget`` counts move EVALUATIONS (simulations) across
    all seeds; ``seed`` drives the shift-move rng. Deterministic and
    cached: the same arguments reproduce the table bitwise."""
    costs = normalize_costs(costs, S * V)
    seeds = _seed_tables(S, M, V, costs)
    # baseline: the best seed TABLE (legal by construction); the search
    # only ever replaces it with a strictly shorter simulated schedule
    best_tt = min(seeds, key=lambda t: (t.half_ticks, t.name))
    best_span = best_tt.half_ticks
    best_times = None  # (F, B, W) when a searched order beat every seed

    rng = np.random.default_rng(seed)
    remaining = max(0, int(budget))

    def evaluate(orders: DeviceOrders):
        nonlocal remaining
        if remaining <= 0:
            return None
        remaining -= 1
        if not caps_ok(orders, S, V, M):
            return None
        return simulate_orders(orders, S, V, M, costs)

    for tt in seeds:
        if remaining <= 0:
            break
        cur = [list(o) for o in orders_of(tt)]
        sim = simulate_orders(tuple(tuple(o) for o in cur), S, V, M, costs)
        assert sim is not None, "seed order must be schedulable"
        cur_span = sim[3]
        if cur_span < best_span:
            best_span, best_times, best_tt = cur_span, sim[:3], tt
        # deterministic first-improvement adjacent-swap sweeps
        improved = True
        while improved and remaining > 0:
            improved = False
            for s in range(S):
                for i in range(len(cur[s]) - 1):
                    if remaining <= 0:
                        break
                    cur[s][i], cur[s][i + 1] = cur[s][i + 1], cur[s][i]
                    sim = evaluate(tuple(tuple(o) for o in cur))
                    if sim is not None and sim[3] < cur_span:
                        cur_span, improved = sim[3], True
                        if cur_span < best_span:
                            best_span, best_times = cur_span, sim[:3]
                            best_tt = tt
                    else:
                        cur[s][i], cur[s][i + 1] = cur[s][i + 1], cur[s][i]
        # seeded random shift moves with this seed's share of the budget
        share = remaining // max(1, len(seeds))
        for _ in range(share):
            if remaining <= 0:
                break
            s = int(rng.integers(S))
            n = len(cur[s])
            if n < 2:
                continue
            i = int(rng.integers(n))
            j = int(rng.integers(max(0, i - 3), min(n, i + 4)))
            if i == j:
                continue
            moved = cur[s][:]
            moved.insert(j, moved.pop(i))
            trial = [o[:] for o in cur]
            trial[s] = moved
            sim = evaluate(tuple(tuple(o) for o in trial))
            if sim is not None and sim[3] < cur_span:
                cur, cur_span = trial, sim[3]
                if cur_span < best_span:
                    best_span, best_times = cur_span, sim[:3]
                    best_tt = tt
    if best_times is None:
        out = dataclasses.replace(best_tt, name="searched")
    else:
        F, B, W = best_times
        out = timetable_from_times("searched", S, V, M, F, B, W, costs)
    check_legal(out, extra_inflight=0)
    return out
