"""ddlbench_tpu — a TPU-native distributed deep-learning training benchmark framework.

Re-creates the capability surface of sara-nl/DDLBench (reference layout documented
in SURVEY.md) on JAX/XLA: one model zoo expressed as flat layer lists, four
parallelization strategies (single, dp, gpipe, pipedream) sharing one train-loop
harness, a layer-graph profiler, and a hierarchical pipeline partitioner with a
TPU (ICI/DCN/HBM) cost model.

Reference parity pointers are cited in docstrings as ``/root/reference/<file>:<lines>``.
"""

__version__ = "0.1.0"

from ddlbench_tpu.config import RunConfig, HardwareModel, DATASETS, DatasetSpec

__all__ = ["RunConfig", "HardwareModel", "DATASETS", "DatasetSpec", "__version__"]
