"""Fused LM-head loss: linear projection + softmax cross-entropy without ever
materializing the full [N, V] logits tensor in HBM.

Why: on the token workloads the vocabulary is 32k (config.DATASETS), so the
unfused path writes logits [B*T, V] (plus an f32 log-softmax copy and an f32
gradient) — gigabytes per step that dwarf every activation in the model. The
reference has no analog (its classifiers top out at 1000 classes — this is
the sequence-workload equivalent of SURVEY.md §2 D2's "hot op gets a custom
kernel" rule). The fusion computes, per row chunk,

    z_c = h_c @ W          (MXU, f32 accumulation)
    lse = logsumexp(z_c);  nll = lse - z_gold;  argmax for top-1

keeping only the per-row ``lse`` (O(N)) as the backward residual; the backward
recomputes z_c blockwise and forms

    dz = go*(p - (1-s)*onehot - s/V) + gce*(p - onehot)      (masked rows: 0)
    dh_c = dz @ W^T;   dW += h_c^T @ dz

so peak memory drops from O(N*V) to O(chunk*V) and the [N, V] round-trips
through HBM disappear. Label smoothing follows parallel/common.py
cross_entropy_loss semantics (GNMT-style: loss = (1-s)*NLL - s*mean_v logp_v);
rows with label < 0 are masked (the seq2seq source segment).

Returned values are SUMS over valid rows — (objective_sum, ce_sum, correct) —
so sequence-parallel callers can psum numerators and denominators separately.
Both obj_sum and ce_sum are differentiable (they coincide when smoothing=0).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from ddlbench_tpu.ops.util import pallas_out_struct as _pl_out


from ddlbench_tpu.compat import pcast_varying as _pcast_to
from ddlbench_tpu.compat import vma_of as _vma


def _pad_rows(h, labels, chunk: int):
    N = h.shape[0]
    rem = N % chunk
    if rem:
        pad = chunk - rem
        h = jnp.concatenate([h, jnp.zeros((pad, h.shape[1]), h.dtype)], 0)
        labels = jnp.concatenate(
            [labels, jnp.full((pad,), -1, labels.dtype)], 0)
    return h, labels, h.shape[0] // chunk


def _row_stats(z, labels, smoothing: float):
    """Per-row (nll, smoothed objective, correct, mask) from f32 logits z."""
    m = jnp.max(z, axis=-1)
    lse = m + jnp.log(jnp.sum(jnp.exp(z - m[:, None]), axis=-1))
    safe = jnp.maximum(labels, 0)
    gold = jnp.take_along_axis(z, safe[:, None], axis=-1)[:, 0]
    mask = labels >= 0
    nll = lse - gold
    if smoothing:
        obj = lse - (1.0 - smoothing) * gold - smoothing * jnp.mean(z, axis=-1)
    else:
        obj = nll
    correct = (jnp.argmax(z, axis=-1) == labels) & mask
    return nll, obj, correct, mask, lse




def _use_pallas(backend: str, *operands) -> bool:
    """Kernel dispatch. "auto" picks the Pallas kernels only where they
    partition correctly: pallas_call has no GSPMD partitioning rule, so under
    a plain multi-device jit with sharded operands XLA would gather/replicate
    them (inverting the fusion's memory win for dp/tp/fsdp). Inside shard_map
    the operands are per-shard (nonempty varying-manual-axes type) and on a
    single device there is nothing to partition — Pallas is safe in both.
    jit-based multi-device strategies get the chunked-XLA scan, which GSPMD
    partitions natively."""
    if backend == "xla":
        return False
    if backend == "pallas":
        return True
    from ddlbench_tpu.distributed import is_tpu_backend

    if not is_tpu_backend():
        return False
    from ddlbench_tpu.ops.util import pallas_partitions_safely

    return pallas_partitions_safely(*operands)


def _pallas_feasible(h, w, backend: str, interpret: bool) -> bool:
    """Mosaic wants lane-dim blocks in multiples of 128 (a vocab with no
    such divisor can't run the compiled kernels), and every kernel's block
    working set must fit scoped VMEM even at the 128-lane floor — a very
    wide D blows the dW accumulator alone (_budget_v_block -> None). The
    budget is evaluated at the row block the kernels will actually use
    (small row counts shrink it, and the dh fixed cost with it). auto falls
    back to chunked-XLA; a forced "pallas" backend gets a clear error
    instead of a Mosaic one."""
    if interpret:
        return True
    D, V = w.shape
    # Price with the wider of the two dtypes: the launch sites size blocks
    # with h.dtype.itemsize (lines 442/555+), so a gate priced only on w
    # could pass while _budget_v_block returns None at launch (ADVICE r3).
    isz = max(h.dtype.itemsize, w.dtype.itemsize)
    br = _row_block(h.shape[0], interpret)
    ok = (
        _budget_v_block(V, D, br, isz, False) is not None  # fwd
        and _budget_v_block(V, D, br, isz, False,
                            **_dh_price(D, br, isz)) is not None
        and _budget_v_block(V, D, br, isz, False,
                            **_dw_price(D, br, isz)) is not None
    )
    if ok:
        return True
    if backend == "pallas":
        raise ValueError(
            f"fused_linear_xent: no feasible Pallas blocking for head "
            f"[D={D}, V={V}] — the vocab needs a 128-multiple block divisor "
            f"and every kernel's block working set must fit scoped VMEM "
            f"({VMEM_HARD >> 20} MiB); pad the vocab or use backend='xla'")
    return False


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def fused_linear_xent(h, w, labels, smoothing: float = 0.0,
                      row_chunk: int = 512, backend: str = "auto",
                      interpret: bool = False):
    """(objective_sum, ce_sum, correct_count) over valid rows.

    h: [N, D] hidden rows (compute dtype); w: [D, V] head weights (compute
    dtype); labels: [N] int (-1 = masked). Objective uses ``smoothing``; ce is
    the unsmoothed CE (the headline metric). Gradients flow to h and w from
    BOTH sums. ``backend``: "auto" = Pallas kernels on TPU, chunked-XLA scan
    elsewhere; "pallas"/"xla" force one (pallas off-TPU needs interpret=True).
    """
    out, _ = _fxent_fwd(h, w, labels, smoothing, row_chunk, backend, interpret)
    return out


def _fxent_fwd(h, w, labels, smoothing: float, row_chunk: int, backend: str,
               interpret: bool):
    if (_use_pallas(backend, h, w, labels)
            and _pallas_feasible(h, w, backend, interpret)):
        return _fxent_fwd_pallas(h, w, labels, smoothing, interpret)
    N = h.shape[0]
    chunk = min(row_chunk, N)
    hp, lp, nc = _pad_rows(h, labels, chunk)
    hcs = hp.reshape(nc, chunk, hp.shape[1])
    lcs = lp.reshape(nc, chunk)

    def body(carry, xs):
        obj_s, ce_s, corr = carry
        h_c, l_c = xs
        z = jnp.dot(h_c, w, preferred_element_type=jnp.float32)
        nll, obj, correct, mask, lse = _row_stats(z, l_c, smoothing)
        obj_s = obj_s + jnp.sum(jnp.where(mask, obj, 0.0))
        ce_s = ce_s + jnp.sum(jnp.where(mask, nll, 0.0))
        corr = corr + jnp.sum(correct.astype(jnp.int32))
        return (obj_s, ce_s, corr), lse

    axes = set(_vma(h)) | set(_vma(w)) | set(_vma(labels))
    init = tuple(
        _pcast_to(z, axes)
        for z in (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32),
                  jnp.zeros((), jnp.int32))
    )
    (obj_s, ce_s, corr), lses = lax.scan(body, init, (hcs, lcs))
    return (obj_s, ce_s, corr), (h, w, labels, lses.reshape(-1)[:N])


def _fxent_bwd(smoothing: float, row_chunk: int, backend: str,
               interpret: bool, res, cots):
    h, w, labels, lses = res
    go, gce, _ = cots  # correct-count cotangent is float0 — ignored
    go = go.astype(jnp.float32)
    gce = gce.astype(jnp.float32)
    if (_use_pallas(backend, h, w, labels)
            and _pallas_feasible(h, w, backend, interpret)):
        dh, dw = _fxent_bwd_pallas(h, w, labels, lses, go, gce, smoothing,
                                   interpret)
    else:
        dh, dw = _fxent_bwd_xla(h, w, labels, lses, go, gce, smoothing,
                                row_chunk)
    # Cotangents must carry their primals' VMA types: when w is invariant
    # over an axis the rows are sharded on (e.g. replicated head weights under
    # sequence parallelism), the true dw is the cross-shard sum.
    extra_w = tuple(a for a in _vma(dw) if a not in _vma(w))
    if extra_w:
        dw = lax.psum(dw, extra_w)
    extra_h = tuple(a for a in _vma(dh) if a not in _vma(h))
    if extra_h:
        dh = lax.psum(dh, extra_h)
    return dh.astype(h.dtype), dw.astype(w.dtype), None


def _fxent_bwd_xla(h, w, labels, lses, go, gce, smoothing: float,
                   row_chunk: int):
    N, D = h.shape
    V = w.shape[1]
    chunk = min(row_chunk, N)
    hp, lp, nc = _pad_rows(h, labels, chunk)
    lsep = jnp.pad(lses, (0, nc * chunk - N))
    hcs = hp.reshape(nc, chunk, D)
    lcs = lp.reshape(nc, chunk)
    lsec = lsep.reshape(nc, chunk)
    s = smoothing

    def body(dw, xs):
        h_c, l_c, lse_c = xs
        z = jnp.dot(h_c, w, preferred_element_type=jnp.float32)
        p = jnp.exp(z - lse_c[:, None])
        mask = (l_c >= 0).astype(jnp.float32)[:, None]
        onehot = jax.nn.one_hot(jnp.maximum(l_c, 0), V, dtype=jnp.float32)
        # d(obj)/dz = p - (1-s)*onehot - s/V ; d(nll)/dz = p - onehot
        dz = (go + gce) * p - (go * (1.0 - s) + gce) * onehot
        if s:
            dz = dz - go * (s / V)
        dz = (dz * mask).astype(h.dtype)
        dh_c = jnp.dot(dz, w.T, preferred_element_type=jnp.float32)
        dw = dw + jnp.dot(h_c.T, dz, preferred_element_type=jnp.float32)
        return dw, dh_c.astype(h.dtype)

    axes = set(_vma(h)) | set(_vma(w)) | set(_vma(labels)) | set(_vma(go))
    dw, dhs = lax.scan(body, _pcast_to(jnp.zeros((D, V), jnp.float32), axes),
                       (hcs, lcs, lsec))
    dh = dhs.reshape(nc * chunk, D)[:N]
    return dh, dw


fused_linear_xent.defvjp(_fxent_fwd, _fxent_bwd)


def fused_linear_xent_eval(h, w, labels, k: int = 5, row_chunk: int = 512):
    """Eval-side fusion: (ce_sum, correct, correct_topk, valid) over valid
    rows, materializing only one [chunk, V] logit block at a time instead of
    the full [N, V] (at longctx shapes the full eval logits would be
    gigabytes).

    Top-k tie handling matches parallel/common.py correct_topk (torch.topk
    order: value descending, index ascending): the label ranks after every
    strictly-greater logit and after equal logits at smaller class indices.
    No gradients (plain function — eval only).
    """
    N, D = h.shape
    V = w.shape[1]
    k = min(k, V)
    chunk = min(row_chunk, N)
    hp, lp, nc = _pad_rows(h, labels, chunk)
    hcs = hp.reshape(nc, chunk, D)
    lcs = lp.reshape(nc, chunk)

    def body(carry, xs):
        ce_s, corr, corrk, cnt = carry
        h_c, l_c = xs
        z = jnp.dot(h_c, w, preferred_element_type=jnp.float32)
        nll, _, correct, mask, _ = _row_stats(z, l_c, 0.0)
        # top-k rank: strictly-greater logits plus equal logits at smaller
        # class indices (torch.topk order)
        safe = jnp.maximum(l_c, 0)
        gold = jnp.take_along_axis(z, safe[:, None], axis=-1)
        idx = jax.lax.broadcasted_iota(jnp.int32, z.shape, 1)
        higher = jnp.sum((z > gold).astype(jnp.int32), axis=-1)
        tie_before = jnp.sum(
            ((z == gold) & (idx < safe[:, None])).astype(jnp.int32), axis=-1)
        ce_s = ce_s + jnp.sum(jnp.where(mask, nll, 0.0))
        corr = corr + jnp.sum(correct.astype(jnp.int32))
        corrk = corrk + jnp.sum(
            ((higher + tie_before < k) & mask).astype(jnp.int32))
        cnt = cnt + jnp.sum(mask.astype(jnp.int32))
        return (ce_s, corr, corrk, cnt), None

    axes = set(_vma(h)) | set(_vma(w)) | set(_vma(labels))
    init = tuple(
        _pcast_to(z, axes)
        for z in (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.int32),
                  jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32))
    )
    (ce_s, corr, corrk, cnt), _ = lax.scan(body, init, (hcs, lcs))
    return ce_s, corr, corrk, cnt


# ---------------------------------------------------------------------------
# Pallas TPU kernels — same math, zero logits traffic to HBM.
#
# Forward: grid (row_blocks, v_blocks), W streamed blockwise through VMEM
# (~16 MB/core, so [D, 32k] never fits whole); online-logsumexp scratch
# carried across the inner v sweep; per-row (lse, gold, zsum, argmax) written
# on the last v block and reduced to the three sums with trivial XLA ops.
# Backward: dh kernel accumulates dz @ W_j^T over the inner v sweep; dW kernel
# flips the grid and accumulates h_i^T @ dz over the inner row sweep — the
# same two-kernel split as ops/flash_attention.py's dq / dkv.
# ---------------------------------------------------------------------------

ROW_BLOCK = 256
V_BLOCK = 2048
# Per-kernel working-set target and hard ceiling. v5e gives ~16 MiB of
# scoped VMEM per core; target well under it so double-buffering + compiler
# temporaries fit (the dW kernel at (br=256, bv=2048, D=512) measures
# 18.2 MiB on-chip and is rejected by Mosaic, hence the budget-aware block
# choice below). A block between target and hard limit is best-effort
# (returned, may still compile); past VMEM_HARD even the 128-lane floor
# cannot fit and the caller must take the chunked-XLA path instead.
VMEM_BUDGET = 12 * 1024 * 1024
VMEM_HARD = 16 * 1024 * 1024


def _pick_block(t: int, preferred: int, unit: int = 1) -> Optional[int]:
    """Tile-aligned block divisor (ops/util.py:pick_block); ``unit`` is 128
    for the lane (vocab) dimension on real TPU."""
    from ddlbench_tpu.ops.util import pick_block

    return pick_block(t, preferred, unit)


def _budget_v_block(V: int, D: int, br: int, in_size: int, interpret: bool,
                    per_bv: int = 0, fixed: int = 0) -> int:
    """Largest 128-multiple vocab-block divisor of ``V`` whose kernel
    working set fits ``VMEM_BUDGET``.

    Shared terms for all three kernels: double-buffered input blocks
    (h [br, D], w [D, bv]) plus the recomputed f32 logit block [br, bv].
    ``per_bv`` prices kernel-specific bytes per vocab lane (dz blocks, the
    dW kernel's f32 [D, bv] scratch + double-buffered f32 out block);
    ``fixed`` prices bv-independent extras (the dh kernel's [br, D] f32
    accumulator and double-buffered out block).

    Returns None when even the smallest lane-aligned block exceeds
    VMEM_HARD (a very wide D — the bv-independent terms alone blow the
    scoped-VMEM limit); the caller falls back to the chunked-XLA path via
    _pallas_feasible. A pick between VMEM_BUDGET and VMEM_HARD is returned
    best-effort."""
    bv = _pick_block(V, V_BLOCK, 1 if interpret else 128)
    if interpret or bv is None:
        return bv

    def footprint(b: int) -> int:
        ins = 2 * (br * D + D * b) * in_size
        return ins + br * b * 4 + per_bv * b + fixed

    while bv > 128 and footprint(bv) > VMEM_BUDGET:
        smaller = _pick_block(V, bv // 2, 128)
        if smaller is None or smaller == bv:
            break
        bv = smaller
    if footprint(bv) > VMEM_HARD:
        return None
    return bv


def _dh_price(D: int, br: int, in_size: int) -> dict:
    """dh-kernel _budget_v_block terms: a dz block [br, bv] in the compute
    dtype per lane, plus the bv-independent f32 [br, D] accumulator and
    double-buffered [br, D] out block. One home for the formulas shared by
    the feasibility gate, the kernel launch, and tests/test_vmem_budget.py."""
    return dict(per_bv=br * in_size, fixed=br * D * (4 + 2 * in_size))


def _dw_price(D: int, br: int, in_size: int) -> dict:
    """dW-kernel terms: the dz block plus an f32 [D, bv] scratch accumulator
    and a double-buffered f32 [D, bv] out block (3 * D * 4 bytes per lane)."""
    return dict(per_bv=br * in_size + 3 * D * 4)


def _row_block(n: int, interpret: bool) -> int:
    """Row (sublane) block: ROW_BLOCK, shrunk for small n but kept a multiple
    of 8 on real TPU (rows are padded up to a block multiple either way)."""
    if n >= ROW_BLOCK:
        return ROW_BLOCK
    return n if interpret else -(-n // 8) * 8


def _fx_fwd_kernel(h_ref, w_ref, lab_ref, lse_ref, gold_ref, zsum_ref,
                   amax_ref, m_sc, l_sc, gold_sc, zsum_sc, av_sc, ai_sc, *,
                   bv: int, nv: int):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_sc[:] = jnp.full(m_sc.shape, NEG_INF, jnp.float32)
        l_sc[:] = jnp.zeros(l_sc.shape, jnp.float32)
        gold_sc[:] = jnp.zeros(gold_sc.shape, jnp.float32)
        zsum_sc[:] = jnp.zeros(zsum_sc.shape, jnp.float32)
        av_sc[:] = jnp.full(av_sc.shape, NEG_INF, jnp.float32)
        ai_sc[:] = jnp.zeros(ai_sc.shape, jnp.int32)

    z = jax.lax.dot_general(
        h_ref[:], w_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [br, bv]
    lab = lab_ref[:]  # [br, 1]
    col = j * bv + jax.lax.broadcasted_iota(jnp.int32, (1, bv), 1)
    match = col == lab
    gold_sc[:] += jnp.sum(jnp.where(match, z, 0.0), axis=1, keepdims=True)
    zsum_sc[:] += jnp.sum(z, axis=1, keepdims=True)
    bm = jnp.max(z, axis=1, keepdims=True)
    bi = j * bv + jnp.argmax(z, axis=1).astype(jnp.int32)[:, None]
    upd = bm > av_sc[:]
    ai_sc[:] = jnp.where(upd, bi, ai_sc[:])
    av_sc[:] = jnp.where(upd, bm, av_sc[:])
    m_prev = m_sc[:]
    m_new = jnp.maximum(m_prev, bm)
    l_sc[:] = (l_sc[:] * jnp.exp(m_prev - m_new)
               + jnp.sum(jnp.exp(z - m_new), axis=1, keepdims=True))
    m_sc[:] = m_new

    @pl.when(j == nv - 1)
    def _fini():
        l_safe = jnp.maximum(l_sc[:], 1e-20)
        lse_ref[:] = m_sc[:] + jnp.log(l_safe)
        gold_ref[:] = gold_sc[:]
        zsum_ref[:] = zsum_sc[:]
        amax_ref[:] = ai_sc[:]


NEG_INF = -1e30


def _fxent_fwd_pallas(h, w, labels, smoothing: float, interpret: bool):
    from jax.experimental.pallas import tpu as pltpu

    N, D = h.shape
    V = w.shape[1]
    br = _row_block(N, interpret)
    # pad rows to a block multiple with masked labels
    hp, lp, _ = _pad_rows(h, labels, br)
    Np = hp.shape[0]
    nr = Np // br
    bv = _budget_v_block(V, D, br,
                         max(h.dtype.itemsize, w.dtype.itemsize), interpret)
    nv = V // bv
    lab2 = lp[:, None].astype(jnp.int32)

    f32 = jnp.float32
    lse, gold, zsum, amax = pl.pallas_call(
        functools.partial(_fx_fwd_kernel, bv=bv, nv=nv),
        grid=(nr, nv),
        in_specs=[
            pl.BlockSpec((br, D), lambda i, j: (i, 0)),
            pl.BlockSpec((D, bv), lambda i, j: (0, j)),
            pl.BlockSpec((br, 1), lambda i, j: (i, 0)),
        ],
        out_specs=[pl.BlockSpec((br, 1), lambda i, j: (i, 0))] * 4,
        out_shape=[
            _pl_out((Np, 1), f32, hp, w, lab2),
            _pl_out((Np, 1), f32, hp, w, lab2),
            _pl_out((Np, 1), f32, hp, w, lab2),
            _pl_out((Np, 1), jnp.int32, hp, w, lab2),
        ],
        scratch_shapes=[pltpu.VMEM((br, 1), f32)] * 5
        + [pltpu.VMEM((br, 1), jnp.int32)],
        interpret=interpret,
    )(hp, w, lab2)

    lse = lse[:N, 0]
    gold = gold[:N, 0]
    zsum = zsum[:N, 0]
    amax = amax[:N, 0]
    mask = labels >= 0
    nll = lse - gold
    if smoothing:
        obj = lse - (1.0 - smoothing) * gold - smoothing * (zsum / V)
    else:
        obj = nll
    obj_s = jnp.sum(jnp.where(mask, obj, 0.0))
    ce_s = jnp.sum(jnp.where(mask, nll, 0.0))
    corr = jnp.sum(((amax == labels) & mask).astype(jnp.int32))
    return (obj_s, ce_s, corr), (h, w, labels, lse)


def _fx_dz(z, lab, lse_col, coef, bv: int, j, dtype):
    """dz block [br, bv] from recomputed logits (shared by dh/dw kernels)."""
    p = jnp.exp(z - lse_col)
    col = j * bv + jax.lax.broadcasted_iota(jnp.int32, (1, bv), 1)
    match = (col == lab).astype(jnp.float32)
    c_p, c_oh, c_sm = coef[0, 0], coef[0, 1], coef[0, 2]
    dz = c_p * p - c_oh * match - c_sm
    maskf = (lab >= 0).astype(jnp.float32)
    return (dz * maskf).astype(dtype)


def _fx_dh_kernel(h_ref, w_ref, lab_ref, lse_ref, coef_ref, dh_ref, acc_sc, *,
                  bv: int, nv: int):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_sc[:] = jnp.zeros(acc_sc.shape, jnp.float32)

    z = jax.lax.dot_general(
        h_ref[:], w_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dz = _fx_dz(z, lab_ref[:], lse_ref[:], coef_ref[:], bv, j, h_ref.dtype)
    acc_sc[:] += jax.lax.dot_general(
        dz, w_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(j == nv - 1)
    def _fini():
        dh_ref[:] = acc_sc[:].astype(dh_ref.dtype)


def _fx_dw_kernel(h_ref, w_ref, lab_ref, lse_ref, coef_ref, dw_ref, acc_sc, *,
                  bv: int, nr: int):
    i = pl.program_id(1)
    j = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        acc_sc[:] = jnp.zeros(acc_sc.shape, jnp.float32)

    z = jax.lax.dot_general(
        h_ref[:], w_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dz = _fx_dz(z, lab_ref[:], lse_ref[:], coef_ref[:], bv, j, h_ref.dtype)
    acc_sc[:] += jax.lax.dot_general(
        h_ref[:], dz, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(i == nr - 1)
    def _fini():
        dw_ref[:] = acc_sc[:].astype(dw_ref.dtype)


def _fxent_bwd_pallas(h, w, labels, lses, go, gce, smoothing: float,
                      interpret: bool):
    from jax.experimental.pallas import tpu as pltpu

    N, D = h.shape
    V = w.shape[1]
    br = _row_block(N, interpret)
    hp, lp, _ = _pad_rows(h, labels, br)
    Np = hp.shape[0]
    nr = Np // br
    # dh's accumulator + double-buffered out block are [br, D]
    # (bv-independent); dW carries an f32 [D, bv] scratch plus a
    # double-buffered f32 [D, bv] out block, so its lane block must shrink
    # when D is wide (VMEM_BUDGET note above; formulas in _dh/_dw_price).
    isz = max(h.dtype.itemsize, w.dtype.itemsize)
    bv = _budget_v_block(V, D, br, isz, interpret, **_dh_price(D, br, isz))
    nv = V // bv
    bv_dw = _budget_v_block(V, D, br, isz, interpret,
                            **_dw_price(D, br, isz))
    nv_dw = V // bv_dw
    lab2 = lp[:, None].astype(jnp.int32)
    # padded rows: lse=0 with z=0 gives p=1 — masked to 0 by the label test
    lse2 = jnp.pad(lses, (0, Np - N))[:, None]
    s = smoothing
    coef = jnp.stack([go + gce, go * (1.0 - s) + gce,
                      go * (s / V), jnp.float32(0.0)])[None, :]

    f32 = jnp.float32
    dh = pl.pallas_call(
        functools.partial(_fx_dh_kernel, bv=bv, nv=nv),
        grid=(nr, nv),
        in_specs=[
            pl.BlockSpec((br, D), lambda i, j: (i, 0)),
            pl.BlockSpec((D, bv), lambda i, j: (0, j)),
            pl.BlockSpec((br, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((br, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((1, 4), lambda i, j: (0, 0)),
        ],
        out_specs=pl.BlockSpec((br, D), lambda i, j: (i, 0)),
        out_shape=_pl_out((Np, D), h.dtype, hp, w, lab2, lse2, coef),
        scratch_shapes=[pltpu.VMEM((br, D), f32)],
        interpret=interpret,
    )(hp, w, lab2, lse2, coef)

    dw = pl.pallas_call(
        functools.partial(_fx_dw_kernel, bv=bv_dw, nr=nr),
        grid=(nv_dw, nr),
        in_specs=[
            pl.BlockSpec((br, D), lambda j, i: (i, 0)),
            pl.BlockSpec((D, bv_dw), lambda j, i: (0, j)),
            pl.BlockSpec((br, 1), lambda j, i: (i, 0)),
            pl.BlockSpec((br, 1), lambda j, i: (i, 0)),
            pl.BlockSpec((1, 4), lambda j, i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((D, bv_dw), lambda j, i: (0, j)),
        out_shape=_pl_out((D, V), f32, hp, w, lab2, lse2, coef),
        scratch_shapes=[pltpu.VMEM((D, bv_dw), f32)],
        interpret=interpret,
    )(hp, w, lab2, lse2, coef)

    return dh[:N], dw
