"""Paged KV cache + single-query flash-decode kernel (beam inference fast path).

Profiling target (VERDICT r3 next #6): KV-cached beam-4 decode measured
3.2k tok/s — the weakest on-chip number. The dominant traffic is structural:
``beam_search_decode`` re-gathers EVERY layer's full [rows, H, max_len, dh]
K/V cache to follow the parent beam at every token (models/decode.py
``gather_caches``), and the attention einsum then reads the full masked
max_len even when only t positions are live. For the decodebench
configuration (seq2seq_s: 8 layers, rows=32, L=256, f32) the permutation
alone moves ~536 MB per token — read AND write — before any compute.

The paged design eliminates that:

* The cache is a POOL of fixed-size pages ([rows * n_pages, page, H, dh])
  plus a tiny int32 page TABLE per row. Every row owns one private slot per
  page index; completed pages are immutable (positions only grow), so a beam
  reorder copies POINTERS for completed pages and physically copies only the
  one partial page per row (``paged_reorder`` — copy-on-write). Per-token
  reorder traffic drops from O(rows * L) to O(rows * page).
* Attention walks only the LIVE pages through the table — the Pallas kernel
  (``paged_attention``) scalar-prefetches the table, DMAs each page block
  directly from the pool (no gathered copy in HBM), and accumulates an
  online softmax across pages, FlashAttention-style with a page-granular
  grid. The jnp reference path (``_paged_attention_ref``) materializes the
  gathered pages and is used on CPU and as the numerics oracle.

The lineage is vLLM's PagedAttention (Kwon et al., SOSP'23 — the serving
engine that introduced page tables for KV caches; not among the training
papers in PAPERS.md): here the copy-on-write table doubles as the
beam-search ancestry structure, which is what removes the reference-style
cache reshuffle (GNMT reorders its recurrent decoder state per expansion —
SURVEY.md §2 C13; the transformer analog is the cache gather this module
deletes). The serving half of that lineage — a SHARED pool whose slots are
free-list-allocated per request instead of statically owned per row — is
the ``serve_*``/``paged_table_*`` primitives below, driven by the
continuous-batching engine in ``serve/engine.py``.

The page count walked per step must be static under jit: callers run the
decode loop in SEGMENTS of one page (models/decode.py paged loops), so each
segment's kernel compiles with ``num_pages = p + 1``.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

NEG_INF = -1e30

# Positions per page; 64 * H * dh blocks DMA efficiently. Module-level so
# tests can shrink it (every entry point resolves the default at CALL time).
PAGE = 64


class live_pages:
    """Trace-time marker for how many pages are live in the current decode
    segment (the static page count the kernel grid needs). The paged decode
    loops (models/decode.py) trace each one-page segment's body under
    ``with live_pages(p + 1):``; attention layers read ``current()`` at
    trace time. Same idiom as models/layers.axis_context."""

    _stack: list = []

    def __init__(self, n: int):
        self.n = int(n)

    def __enter__(self):
        live_pages._stack.append(self.n)
        return self

    def __exit__(self, *exc):
        live_pages._stack.pop()
        return False

    @staticmethod
    def current():
        if not live_pages._stack:
            raise RuntimeError(
                "paged attention decode traced outside a live_pages(...) "
                "segment — use the paged loops in models/decode.py")
        return live_pages._stack[-1]


def num_pages(total_len: int, page: int | None = None) -> int:
    page = page or PAGE
    return -(-total_len // page)


def paged_cache_init(rows: int, total_len: int, n_heads: int, dh: int,
                     dtype, page: int | None = None):
    """Cache dict: pool_k/pool_v [rows*n_pages, page, H, dh] + table.

    ``table[r, q]`` is the pool slot holding row r's K/V for positions
    [q*page, (q+1)*page). Initially every row points at its own private
    slots (slot r*n_pages + q). Invariant maintained by ``paged_reorder``:
    entries for the current and future pages always point at the row's OWN
    slot, so decode writes never collide across rows.
    """
    page = page or PAGE
    npg = num_pages(total_len, page)
    shape = (rows * npg, page, n_heads, dh)
    own = (jnp.arange(rows, dtype=jnp.int32)[:, None] * npg
           + jnp.arange(npg, dtype=jnp.int32)[None, :])
    # NOTE: ``page`` is deliberately NOT in the dict — the cache is a traced
    # pytree in decode-loop carries, and the kernel's BlockSpecs need the
    # page size static. Callers pass it explicitly (layer closures carry it).
    return {
        "pool_k": jnp.zeros(shape, dtype),
        "pool_v": jnp.zeros(shape, dtype),
        "table": own,
    }


def _own_table(rows: int, npg: int) -> jax.Array:
    return (jnp.arange(rows, dtype=jnp.int32)[:, None] * npg
            + jnp.arange(npg, dtype=jnp.int32)[None, :])


def _pool5d(pool, rows: int):
    n, page, H, dh = pool.shape
    return pool.reshape(rows, n // rows, page, H, dh)


def paged_prefill_write(cache, k, v, page: int | None = None, start: int = 0):
    """Write a prompt chunk's K/V [rows, S, H, dh] at positions
    [start, start+S) into each row's own pages.

    ``start`` is static (a Python int): long-context serving chunks the
    prompt, calling this once per chunk. ``start == 0`` (the whole-prompt
    case) takes a dense reshape path; a later chunk — which may begin at a
    page-unaligned position inside a partially-filled page — scatters by
    (page, offset) index so existing positions in that page are preserved.
    """
    page = page or PAGE
    start = int(start)
    rows, S, H, dh = k.shape
    capacity = cache["table"].shape[1] * page
    # .at[...].set scatters with out-of-bounds indices silently dropped /
    # clamped, so a chunk running past the pool would truncate KV history
    # with no error (advisor r5) — reject it at trace time instead.
    assert start + S <= capacity, (
        f"prefill chunk [{start}, {start + S}) exceeds the paged cache "
        f"capacity {capacity} ({cache['table'].shape[1]} pages x {page}); "
        f"allocate the cache for the full prompt before chunked prefill")

    if start == 0:
        npg_s = num_pages(S, page)
        pad = npg_s * page - S

        def write(pool, x):
            p5 = _pool5d(pool, rows)
            xp = jnp.pad(x, ((0, 0), (0, pad), (0, 0), (0, 0)))
            x5 = xp.reshape(rows, npg_s, page, H, dh).astype(pool.dtype)
            return p5.at[:, :npg_s].set(x5).reshape(pool.shape)
    else:
        pos = start + jnp.arange(S, dtype=jnp.int32)
        pg, off = pos // page, pos % page

        def write(pool, x):
            p5 = _pool5d(pool, rows)
            return p5.at[:, pg, off].set(x.astype(pool.dtype)).reshape(
                pool.shape)

    return {**cache, "pool_k": write(cache["pool_k"], k),
            "pool_v": write(cache["pool_v"], v)}


def paged_decode_write(cache, k1, v1, pos, page: int | None = None):
    """Write one token's K/V [rows, 1, H, dh] at dynamic position pos into
    each row's own slot for the current page."""
    page = page or PAGE
    rows = cache["table"].shape[0]
    p, off = pos // page, pos % page

    def write(pool, x):
        p5 = _pool5d(pool, rows)
        blk = x.astype(pool.dtype)[:, None]  # [rows, 1(page), 1(pos), H, dh]
        return lax.dynamic_update_slice(
            p5, blk, (0, p, off, 0, 0)).reshape(pool.shape)

    return {**cache, "pool_k": write(cache["pool_k"], k1),
            "pool_v": write(cache["pool_v"], v1)}


def paged_reorder(cache, parent, pos, page: int | None = None):
    """Copy-on-write beam reorder BEFORE decoding position pos.

    ``parent[r]`` = the row whose history row r continues. Completed pages
    (< pos // page) are pointer-copied through the table; the current page
    is physically copied from the parent's slot into r's own slot iff it is
    partially filled (pos % page > 0). Current-and-future table entries stay
    owned, preserving the write invariant.
    """
    page = page or PAGE
    rows, npg = cache["table"].shape
    p, off = pos // page, pos % page
    own = _own_table(rows, npg)
    page_idx = jnp.arange(npg, dtype=jnp.int32)[None, :]
    table = jnp.where(page_idx >= p, own, cache["table"][parent])

    def copy_partial(pool):
        src_slot = cache["table"][parent, p]  # parent owns its partial page
        blk = pool[src_slot][:, None]  # [rows, 1, page, H, dh]
        p5 = _pool5d(pool, rows)
        return lax.dynamic_update_slice(
            p5, blk, (0, p, 0, 0, 0)).reshape(pool.shape)

    def no_copy(pool):
        return pool

    pool_k, pool_v = lax.cond(
        off > 0,
        lambda: (copy_partial(cache["pool_k"]), copy_partial(cache["pool_v"])),
        lambda: (cache["pool_k"], cache["pool_v"]),
    )
    return {**cache, "pool_k": pool_k, "pool_v": pool_v, "table": table}


# ---------------------------------------------------------------------------
# Attention over the live pages.
# ---------------------------------------------------------------------------


def _gather_dequant(cache, name: str, tbl, dtype):
    """Gather the live pages of ``pool_k``/``pool_v`` through the table
    and return them in ``dtype`` — dequantizing an int8 pool with its
    per-page scale sidecar (q.astype(f32) * scale per position row, the
    SAME per-element math the fused Pallas kernels apply inside the
    online-softmax walk)."""
    pages = cache[name][tbl]  # [rows, np, page, H, dh]
    if pool_quantized(cache):
        scale = cache["scale_" + name[-1]][tbl]  # [rows, np, page]
        return (pages.astype(jnp.float32)
                * scale[..., None, None]).astype(dtype)
    return pages.astype(dtype)


def _paged_attention_ref(q, cache, pos, npages_live: int,
                         page: int | None = None):
    """jnp oracle: gather the live pages, mask, softmax. [rows, H, dh].

    ``pos`` is a scalar (every row at the same position — the beam/greedy
    decode loops) or a per-row [rows] vector (the continuous-batching
    serving engine, where every row is a different request at its own
    stream position).
    """
    page = page or PAGE
    rows, H, dh = q.shape
    tbl = cache["table"][:, :npages_live]  # [rows, np]
    kc = _gather_dequant(cache, "pool_k", tbl, q.dtype)
    vc = _gather_dequant(cache, "pool_v", tbl, q.dtype)
    L = npages_live * page
    kc = kc.reshape(rows, L, H, dh)
    vc = vc.reshape(rows, L, H, dh)
    scores = jnp.einsum("rhd,rkhd->rhk", q, kc) / math.sqrt(dh)
    k_pos = jnp.arange(L)[None, None, :]
    pos = jnp.asarray(pos)
    posb = pos[:, None, None] if pos.ndim == 1 else pos
    scores = jnp.where(k_pos <= posb, scores, -jnp.inf)
    probs = jax.nn.softmax(scores.astype(jnp.float32), -1).astype(q.dtype)
    return jnp.einsum("rhk,rkhd->rhd", probs, vc)


def _attn_page_math(q, k, v, kpos0, t, scale, elementwise: bool):
    """One page's (scores, p_blk, pv) in f32. Two formulations sharing the
    math: batched dot_general ("dots" — MXU-shaped but small batched
    contractions), and a broadcast/multiply/reduce form ("elementwise" —
    only ops Mosaic lowers canonically on any shape; the compile-risk
    hedge, selectable via set_paged_kernel_style)."""
    if elementwise:
        # s[h, p] = sum_d q[h, d] * k[p, h, d]
        s = jnp.sum(q[None, :, :] * k, axis=2).T * scale  # [H, page]
    else:
        s = jax.lax.dot_general(  # contract dh per head (batched over H)
            q, k, (((1,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32,
        ) * scale
    k_pos = kpos0 + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(k_pos <= t, s, NEG_INF)
    return s


def _pv_page_math(p_blk, v, elementwise: bool):
    if elementwise:
        # pv[h, d] = sum_p p[h, p] * v[p, h, d]
        return jnp.sum(p_blk.T[:, :, None] * v, axis=0)  # [H, dh]
    return jax.lax.dot_general(
        p_blk, v, (((1,), (0,)), ((0,), (1,))),
        preferred_element_type=jnp.float32,
    )


# "dots" | "elementwise": which kernel math the compiled paged kernel uses
# (numerics identical; pinned against each other in tests). decodebench's
# watcher tasks queue both so a Mosaic rejection of one cannot waste the
# tunnel window.
_KERNEL_STYLE = ["dots"]


def set_paged_kernel_style(style: str) -> None:
    """Select the default kernel formulation for subsequent TRACES.

    The global is read at trace time only: a decode function that was
    already jit-compiled keeps whichever formulation it was traced with
    (the style is not part of the jit cache key). Call this before the
    first trace — decodebench does — or pass ``kernel_style=`` directly to
    ``paged_attention`` from code that controls its own trace.
    """
    assert style in ("dots", "elementwise"), style
    _KERNEL_STYLE[0] = style


def _paged_attn_kernel(table_ref, t_ref, q_ref, pk_ref, pv_ref, *refs,
                       scale, page, npages, elementwise, quantized=False):
    # quantized pools carry two extra per-page scale blocks; dequant is
    # FUSED here (q.astype(f32) * per-position scale) so the f32 pool is
    # never materialized — the int8 page is what rides the DMA
    if quantized:
        sk_ref, sv_ref, o_ref, m_sc, l_sc, acc_sc = refs
    else:
        o_ref, m_sc, l_sc, acc_sc = refs
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_sc[:] = jnp.full(m_sc.shape, NEG_INF, jnp.float32)
        l_sc[:] = jnp.zeros(l_sc.shape, jnp.float32)
        acc_sc[:] = jnp.zeros(acc_sc.shape, jnp.float32)

    q = q_ref[0, 0].astype(jnp.float32)  # [H, dh]
    k = pk_ref[0].astype(jnp.float32)  # [page, H, dh]
    v = pv_ref[0].astype(jnp.float32)
    if quantized:
        k = k * sk_ref[0][:, None, None]
        v = v * sv_ref[0][:, None, None]
    # t is per-row: the decode loops broadcast one scalar position to every
    # row; the serving engine hands each row its own stream position.
    s = _attn_page_math(q, k, v, j * page, t_ref[pl.program_id(0)], scale,
                        elementwise)

    m_prev, l_prev, acc_prev = m_sc[:], l_sc[:], acc_sc[:]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    p_blk = jnp.exp(s - m_new)  # [H, page]
    l_new = alpha * l_prev + jnp.sum(p_blk, axis=1, keepdims=True)
    pv = _pv_page_math(p_blk, v, elementwise)
    m_sc[:], l_sc[:] = m_new, l_new
    acc_sc[:] = acc_prev * alpha + pv

    @pl.when(j == npages - 1)
    def _fini():
        l_safe = jnp.maximum(l_sc[:], 1e-20)
        o_ref[0, 0] = (acc_sc[:] / l_safe).astype(o_ref.dtype)


def paged_attention(q, cache, pos, npages_live: int, page: int | None = None,
                    interpret: bool = False, use_kernel: bool | None = None,
                    kernel_style: str | None = None):
    """Single-query attention of q [rows, H, dh] against the live pages.

    ``npages_live`` must be static (callers segment the decode loop by
    page); ``pos`` is the dynamic query position (mask: key pos <= pos),
    either a scalar (all rows at one position) or a per-row [rows] vector
    (continuous-batching serving). ``use_kernel=None`` picks the Pallas
    kernel on TPU, the jnp reference elsewhere. ``kernel_style`` ("dots" |
    "elementwise") overrides the module default set by
    ``set_paged_kernel_style``; both are resolved at trace time.
    """
    from ddlbench_tpu.distributed import is_tpu_backend

    assert kernel_style in (None, "dots", "elementwise"), kernel_style
    page = page or PAGE
    if use_kernel is None:
        use_kernel = is_tpu_backend()
    if not (use_kernel or interpret):
        return _paged_attention_ref(q, cache, pos, npages_live, page)

    from jax.experimental.pallas import tpu as pltpu

    rows, H, dh = q.shape
    scale = 1.0 / math.sqrt(dh)
    tbl = cache["table"][:, :npages_live]
    t32 = jnp.broadcast_to(jnp.asarray(pos, jnp.int32).reshape(-1), (rows,))
    quantized = pool_quantized(cache)

    page_spec = pl.BlockSpec((1, page, H, dh),
                             lambda r, j, tab, t: (tab[r, j], 0, 0, 0))
    in_specs = [
        pl.BlockSpec((1, 1, H, dh), lambda r, j, tab, t: (r, 0, 0, 0)),
        page_spec, page_spec,
    ]
    operands = [tbl, t32, q[:, None], cache["pool_k"], cache["pool_v"]]
    if quantized:  # per-page scale sidecar rows ride their page's block
        scale_spec = pl.BlockSpec((1, page),
                                  lambda r, j, tab, t: (tab[r, j], 0))
        in_specs += [scale_spec, scale_spec]
        operands += [cache["scale_k"], cache["scale_v"]]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # table, t
        grid=(rows, npages_live),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, H, dh),
                               lambda r, j, tab, t: (r, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, 1), jnp.float32),
            pltpu.VMEM((H, 1), jnp.float32),
            pltpu.VMEM((H, dh), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _paged_attn_kernel, scale=scale, page=page, npages=npages_live,
            elementwise=(kernel_style or _KERNEL_STYLE[0]) == "elementwise",
            quantized=quantized),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((rows, 1, H, dh), q.dtype),
        interpret=interpret,
    )(*operands)
    return out[:, 0]


# ---------------------------------------------------------------------------
# Shared-pool (serving) primitives. The beam structures above give every row
# a statically OWNED stripe of the pool; a serving engine instead allocates
# pool slots per request from a free list (serve/allocator.py), so rows
# borrow arbitrary slots and every access goes THROUGH the table. The cache
# dict shape is the same ({pool_k, pool_v, table}) — only the pool's leading
# dim is the total page budget rather than rows * n_pages — so
# ``paged_attention`` (and its Pallas kernel) reads a serving cache
# unchanged. Pool slot 0 is reserved as the SCRATCH page by convention:
# inactive rows' table entries point at it, so their masked writes land
# somewhere harmless instead of clobbering a live request's history.
#
# Write/refcount contract under CROSS-REQUEST PREFIX SHARING
# (serve/prefix.py): a slot may appear in MULTIPLE table rows at once —
# refcounted by the host allocator — and a shared slot is IMMUTABLE: the
# engine only ever binds fully-prefilled prompt pages (positions the
# request never writes again, since positions only grow), and any path
# that would write into a bound page (the full-hit fast path re-deriving
# the last prompt position through the decode program) must
# ``serve_page_copy`` it into a private slot first. ``paged_table_write``
# / ``paged_table_chunk_write`` therefore assume the table entries they
# resolve are PRIVATE to (or scratch for) their row; keeping that true is
# the allocator's refcount discipline, not a device-side check.
# ---------------------------------------------------------------------------

SCRATCH_SLOT = 0

# int8 KV pages (EQuARX-lite at the page-write boundary, PAPERS.md
# 2506.17615 — the PR 6 gradient-wire machinery applied to the serving
# pool). A quantized pool stores pool_k/pool_v as int8 plus a SCALE
# SIDECAR ``scale_k``/``scale_v`` [n_pages, page] f32 — one absmax/127
# scale per written position ROW of each page, stored page-structured so
# a page's scales travel with it verbatim through ``serve_page_copy`` and
# the prefix-cache bind path, and so incremental decode writes never
# requantize resident tokens (requant noise would otherwise accumulate
# every step). Rounding is unbiased stochastic
# (parallel/common.stochastic_round_int8 math) with COUNTER-BASED keys —
# fold(kv_seed, k/v tag, stream position) — so the quantized bytes of a
# position are a pure function of its values and its stream position:
# runs replay bitwise, and eviction/recompute regenerates identical
# pages. Dequantization is FUSED into the attention kernels/references
# (scale applied per page row inside the online-softmax walk — an f32
# pool is never materialized). The sidecar costs 8 bytes per position
# per layer (<2% of payload at H*dh >= 256) and is excluded from the
# ``bytes_per_page`` payload accounting (documented in ARCHITECTURE.md).

KV_QMAX = 127.0


def pool_quantized(cache_or_pool) -> bool:
    """True for an int8 serve pool (the scale sidecar is the marker)."""
    return "scale_k" in cache_or_pool


def pool_page_bytes(pool, page_axis: int = 0) -> int:
    """K/V payload bytes per page slot of ``pool`` (scale sidecars and
    the ``kv_seed`` scalar excluded — the ``bytes_per_page``
    convention). ``page_axis=1`` is the tp-stacked [tp, pages, ...]
    layout, whose per-slot bytes sum over shards to exactly the
    single-chip full-width page. An int8 pool reports exactly f32/4 —
    the invariant the handoff wire accounting (serve/handoff.py)
    inherits, since a ship is verbatim rows of this pool."""
    total = 0
    for name in ("pool_k", "pool_v"):
        arr = pool[name]
        total += int(arr.dtype.itemsize * math.prod(arr.shape)
                     // arr.shape[page_axis])
    return total


def pool_checksum_keys(pool) -> tuple:
    """Keys of ``pool`` covered by the SDC checksum ledger
    (serve/integrity.py): every per-slot array the three table-write
    primitives scatter — payload rows plus the quantized scale sidecars
    — in sorted order (the deterministic CRC chain order). The 0-dim
    ``kv_seed`` scalar is excluded: it is not per-slot state and no
    write primitive touches it."""
    return tuple(sorted(
        k for k, v in pool.items() if getattr(v, "ndim", 0)))


def serve_pool_init(n_pages: int, page: int, n_heads: int, dh: int, dtype):
    """A shared K/V pool of ``n_pages`` free-list-managed slots (slot 0 is
    the scratch page — serve/allocator.py never hands it out). ``dtype``
    int8 builds the QUANTIZED layout: int8 payload + the per-page scale
    sidecar (zeros: an unwritten position dequantizes to exactly 0, same
    as the f32 zero init)."""
    shape = (n_pages, page, n_heads, dh)
    pool = {"pool_k": jnp.zeros(shape, dtype),
            "pool_v": jnp.zeros(shape, dtype)}
    if jnp.dtype(dtype) == jnp.int8:
        pool["scale_k"] = jnp.zeros((n_pages, page), jnp.float32)
        pool["scale_v"] = jnp.zeros((n_pages, page), jnp.float32)
    return pool


def _kv_quantize(x, pos, kv_seed, tag: int):
    """Quantize K or V rows ``x`` [..., H, dh] (one leading axis per
    position) to (q int8 same shape, scale f32 [...]).

    Per-position absmax scale (the largest element maps to exactly
    +-127), unbiased stochastic rounding with a counter-based key
    ``fold(fold(PRNGKey(kv_seed), tag), position)`` — ``pos`` carries the
    absolute stream position of every row of x (same leading shape), so
    the quantized bytes depend only on (values, layer seed, k/v tag,
    position): recompute and prefix-cache re-derivations replay bitwise.
    """
    lead = x.shape[:-2]
    absmax = jnp.max(jnp.abs(x).astype(jnp.float32), axis=(-2, -1))
    scale = jnp.where(absmax > 0, absmax / KV_QMAX, jnp.float32(1.0))
    v = x.astype(jnp.float32) / scale[..., None, None]

    base = jax.random.fold_in(jax.random.PRNGKey(kv_seed), tag)

    def u_for(p):
        return jax.random.uniform(jax.random.fold_in(base, p),
                                  x.shape[-2:], jnp.float32)

    u = jax.vmap(u_for)(pos.reshape(-1)).reshape(x.shape)
    lo = jnp.floor(v)
    q = lo + (u < (v - lo)).astype(jnp.float32)
    return jnp.clip(q, -KV_QMAX, KV_QMAX).astype(jnp.int8), scale


def _pool_write(cache, k, v, pos, write_payload, write_scale):
    """Shared quantize-or-passthrough dispatch for the three table-write
    primitives: ``write_payload(pool, x)`` scatters value rows,
    ``write_scale(scales, s)`` scatters the matching scale rows (only
    called on a quantized pool). ``pos`` is the per-row absolute position
    tensor matching x's leading shape."""
    out = dict(cache)
    if pool_quantized(cache):
        seed = cache.get("kv_seed", 0)
        qk, sk = _kv_quantize(k, pos, seed, 0)
        qv, sv = _kv_quantize(v, pos, seed, 1)
        out["pool_k"] = write_payload(cache["pool_k"], qk)
        out["pool_v"] = write_payload(cache["pool_v"], qv)
        out["scale_k"] = write_scale(cache["scale_k"], sk)
        out["scale_v"] = write_scale(cache["scale_v"], sv)
    else:
        out["pool_k"] = write_payload(cache["pool_k"], k)
        out["pool_v"] = write_payload(cache["pool_v"], v)
    return out


def paged_table_write(cache, k1, v1, pos, page: int | None = None):
    """Write one token's K/V [rows, 1, H, dh] at per-row positions ``pos``
    ([rows] int32, or a scalar) through the TABLE: row r's token lands in
    pool slot ``table[r, pos_r // page]`` at offset ``pos_r % page``.
    Rows whose table row points at the scratch slot write garbage there
    harmlessly (the serving engine masks inactive rows this way). On a
    quantized pool the token quantizes at the write boundary and its
    scale lands in the page's sidecar row."""
    page = page or PAGE
    rows = cache["table"].shape[0]
    pos = jnp.broadcast_to(jnp.asarray(pos, jnp.int32).reshape(-1), (rows,))
    slots = jnp.take_along_axis(
        cache["table"], (pos // page)[:, None], axis=1)[:, 0]
    off = pos % page

    def write(pool, x):
        return pool.at[slots, off].set(x[:, 0].astype(pool.dtype))

    def write_scale(scales, s):
        return scales.at[slots, off].set(s[:, 0])

    return _pool_write(cache, k1, v1, pos[:, None], write, write_scale)


def paged_table_chunk_write(cache, k, v, start, page: int | None = None):
    """Write a prefill chunk's K/V [rows, C, H, dh] at positions
    [start, start + C) through the table. ``start`` may be a traced scalar
    but MUST be page-aligned and C a page multiple (the serving engine
    prefills in page-aligned chunks, padding the last one — padded
    positions are either overwritten by decode before any query can attend
    them, or land on un-allocated table entries, i.e. the scratch slot)."""
    page = page or PAGE
    rows, C, H, dh = k.shape
    assert C % page == 0, (
        f"chunk length {C} must be a multiple of the page size {page}")
    npg_c = C // page
    # scratch-extend the table before slicing: a multi-page chunk whose
    # padded tail runs past the last table column would otherwise be
    # CLAMPED by dynamic_slice onto earlier (live) pages of the same row,
    # silently corrupting the request's own KV history — with the pad,
    # overflow pages resolve to the scratch slot and the padded writes
    # land there harmlessly
    tbl = jnp.pad(cache["table"], ((0, 0), (0, npg_c)),
                  constant_values=SCRATCH_SLOT)
    slots = lax.dynamic_slice_in_dim(
        tbl, start // page, npg_c, axis=1)  # [rows, npg_c]

    def write(pool, x):
        x5 = x.reshape(rows, npg_c, page, H, dh).astype(pool.dtype)
        return pool.at[slots].set(x5)

    def write_scale(scales, s):
        return scales.at[slots].set(s.reshape(rows, npg_c, page))

    pos = (jnp.asarray(start, jnp.int32)
           + jnp.arange(C, dtype=jnp.int32))[None, :]  # [1, C] -> broadcast
    return _pool_write(cache, k, v, jnp.broadcast_to(pos, (rows, C)),
                       write, write_scale)


def paged_table_span_write(cache, k, v, pos0, page: int | None = None):
    """Write a SPAN of W tokens' K/V [rows, W, H, dh] at per-row positions
    [pos0_r, pos0_r + W) through the table — page-UNALIGNED, the write
    shape of the speculative-decoding verify pass (the pending token plus
    the drafts start mid-page). Each position scatters independently by
    (page, offset); positions whose page index runs past the table's
    columns resolve to the scratch slot, so a row's padded draft tail
    lands harmlessly exactly like the chunk write's padded tail."""
    page = page or PAGE
    rows, W, H, dh = k.shape
    npg = cache["table"].shape[1]
    pos = (jnp.asarray(pos0, jnp.int32).reshape(-1)[:, None]
           + jnp.arange(W, dtype=jnp.int32)[None, :])  # [rows, W]
    pg, off = pos // page, pos % page
    slots = jnp.take_along_axis(cache["table"],
                                jnp.clip(pg, 0, npg - 1), axis=1)
    slots = jnp.where(pg < npg, slots, SCRATCH_SLOT)

    def write(pool, x):
        return pool.at[slots, off].set(x.astype(pool.dtype))

    def write_scale(scales, s):
        return scales.at[slots, off].set(s)

    return _pool_write(cache, k, v, pos, write, write_scale)


def serve_page_copy(pool, src, dst):
    """Copy-on-write: physically copy pool slot ``src`` into slot ``dst``
    ({pool_k, pool_v} or any same-shaped pool dict; ``src``/``dst`` may be
    traced scalars, so ONE compiled program serves every copy). On a
    quantized pool the page's scale sidecar rows copy verbatim with the
    payload — a copied page dequantizes bit-identically to its source —
    and scalar entries (the layer's ``kv_seed``) pass through untouched.

    This is the serving analog of ``paged_reorder``'s partial-page copy:
    the prefix cache binds immutable shared pages into a new request's
    table row, and before the engine ever writes INTO a shared page (the
    full-hit fast path re-derives the last prompt position's K/V through
    the decode program) it must copy the page into a private slot — the
    two token streams would otherwise couple through last-ulp drift
    between the chunked and single-token K/V computations."""
    return {k: (v.at[dst].set(v[src]) if jnp.ndim(v) else v)
            for k, v in pool.items()}


def _paged_chunk_attention_ref(q, cache, start, npages_live: int,
                               page: int | None = None):
    """jnp/XLA oracle for chunk-prefill attention: gather the live pages,
    mask causally at absolute positions, softmax. [rows, H, C, dh].
    Serving prefill chunks are ordinary dense attention over a gathered
    [rows, L, H, dh] view, which XLA fuses well — this is the CPU path
    and the numerics reference the Pallas kernel is pinned against."""
    page = page or PAGE
    rows, H, C, dh = q.shape
    tbl = cache["table"][:, :npages_live]
    L = npages_live * page
    kc = (_gather_dequant(cache, "pool_k", tbl, q.dtype)
          .reshape(rows, L, H, dh).transpose(0, 2, 1, 3))  # [rows, H, L, dh]
    vc = (_gather_dequant(cache, "pool_v", tbl, q.dtype)
          .reshape(rows, L, H, dh).transpose(0, 2, 1, 3))
    scores = jnp.einsum("rhqd,rhkd->rhqk", q, kc) / math.sqrt(dh)
    start = jnp.asarray(start, jnp.int32).reshape(-1)  # scalar or [rows]
    q_pos = start[:, None] + jnp.arange(C)[None, :]  # [rows or 1, C]
    k_pos = jnp.arange(L)
    ok = k_pos[None, None, None, :] <= q_pos[:, None, :, None]
    scores = jnp.where(ok, scores, -jnp.inf)
    probs = jax.nn.softmax(scores.astype(jnp.float32), -1).astype(q.dtype)
    return jnp.einsum("rhqk,rhkd->rhqd", probs, vc)


def _paged_chunk_attn_kernel(table_ref, s_ref, q_ref, pk_ref, pv_ref, *refs,
                             scale, page, npages, elementwise,
                             quantized=False):
    """Multi-query analog of ``_paged_attn_kernel``: one grid step attends
    ALL C chunk queries of row r against one live page j, accumulating an
    online softmax per (head, query). The causal mask is absolute — query
    c sits at stream position ``start_r + c`` (``s_ref`` is the per-row
    chunk start the scheduler prefetches) — so within-chunk causality and
    full visibility of earlier pages fall out of one comparison. A
    quantized pool's per-page scale blocks dequantize the page in-kernel,
    exactly like the flash-decode variant."""
    if quantized:
        sk_ref, sv_ref, o_ref, m_sc, l_sc, acc_sc = refs
    else:
        o_ref, m_sc, l_sc, acc_sc = refs
    r, j = pl.program_id(0), pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_sc[:] = jnp.full(m_sc.shape, NEG_INF, jnp.float32)
        l_sc[:] = jnp.zeros(l_sc.shape, jnp.float32)
        acc_sc[:] = jnp.zeros(acc_sc.shape, jnp.float32)

    q = q_ref[0].astype(jnp.float32)  # [H, C, dh]
    k = pk_ref[0].astype(jnp.float32)  # [page, H, dh]
    v = pv_ref[0].astype(jnp.float32)
    if quantized:
        k = k * sk_ref[0][:, None, None]
        v = v * sv_ref[0][:, None, None]
    if elementwise:
        # s[h, c, p] = sum_d q[h, c, d] * k[p, h, d]
        s = jnp.sum(q[:, :, None, :] * k.transpose(1, 0, 2)[:, None, :, :],
                    axis=3) * scale  # [H, C, page]
    else:
        s = jax.lax.dot_general(  # contract dh per head (batched over H)
            q, k, (((2,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32,
        ) * scale
    k_pos = j * page + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
    q_pos = s_ref[r] + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(k_pos <= q_pos, s, NEG_INF)

    m_prev, l_prev, acc_prev = m_sc[:], l_sc[:], acc_sc[:]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=2))  # [H, C]
    alpha = jnp.exp(m_prev - m_new)
    p_blk = jnp.exp(s - m_new[:, :, None])  # [H, C, page]
    l_new = alpha * l_prev + jnp.sum(p_blk, axis=2)
    if elementwise:
        # pv[h, c, d] = sum_p p[h, c, p] * v[p, h, d]
        pv = jnp.sum(p_blk[:, :, :, None]
                     * v.transpose(1, 0, 2)[:, None, :, :], axis=2)
    else:
        pv = jax.lax.dot_general(
            p_blk, v, (((2,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32,
        )  # [H, C, dh]
    m_sc[:], l_sc[:] = m_new, l_new
    acc_sc[:] = acc_prev * alpha[:, :, None] + pv

    @pl.when(j == npages - 1)
    def _fini():
        l_safe = jnp.maximum(l_sc[:], 1e-20)
        o_ref[0] = (acc_sc[:] / l_safe[:, :, None]).astype(o_ref.dtype)


def paged_chunk_attention(q, cache, start, npages_live: int,
                          page: int | None = None, interpret: bool = False,
                          use_kernel: bool | None = None,
                          kernel_style: str | None = None):
    """Causal attention of chunk queries q [rows, H, C, dh] at absolute
    positions ``start + [0, C)`` against the live pages (which must already
    contain the chunk's own K/V — write first, then attend, exactly like
    the single-token path). ``start`` is a dynamic scalar or a per-row
    [rows] vector (each serving row is its own request at its own chunk
    start). ``use_kernel=None`` picks the Pallas kernel on TPU — the
    multi-query analog of the flash-decode kernel, replacing the
    gathered-page XLA einsum on the chunk-prefill hot path — and the jnp
    reference elsewhere. ``kernel_style`` as in :func:`paged_attention`."""
    from ddlbench_tpu.distributed import is_tpu_backend

    assert kernel_style in (None, "dots", "elementwise"), kernel_style
    page = page or PAGE
    if use_kernel is None:
        use_kernel = is_tpu_backend()
    if not (use_kernel or interpret):
        return _paged_chunk_attention_ref(q, cache, start, npages_live, page)

    from jax.experimental.pallas import tpu as pltpu

    rows, H, C, dh = q.shape
    scale = 1.0 / math.sqrt(dh)
    tbl = cache["table"][:, :npages_live]
    s32 = jnp.broadcast_to(jnp.asarray(start, jnp.int32).reshape(-1), (rows,))
    quantized = pool_quantized(cache)

    page_spec = pl.BlockSpec((1, page, H, dh),
                             lambda r, j, tab, s: (tab[r, j], 0, 0, 0))
    in_specs = [
        pl.BlockSpec((1, H, C, dh), lambda r, j, tab, s: (r, 0, 0, 0)),
        page_spec, page_spec,
    ]
    operands = [tbl, s32, q, cache["pool_k"], cache["pool_v"]]
    if quantized:
        scale_spec = pl.BlockSpec((1, page),
                                  lambda r, j, tab, s: (tab[r, j], 0))
        in_specs += [scale_spec, scale_spec]
        operands += [cache["scale_k"], cache["scale_v"]]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # table, per-row chunk start
        grid=(rows, npages_live),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, H, C, dh),
                               lambda r, j, tab, s: (r, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, C), jnp.float32),
            pltpu.VMEM((H, C), jnp.float32),
            pltpu.VMEM((H, C, dh), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(
            _paged_chunk_attn_kernel, scale=scale, page=page,
            npages=npages_live,
            elementwise=(kernel_style or _KERNEL_STYLE[0]) == "elementwise",
            quantized=quantized),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((rows, H, C, dh), q.dtype),
        interpret=interpret,
    )(*operands)
