"""Hand-written TPU kernels (Pallas) for the framework's hot ops."""

from ddlbench_tpu.ops.flash_attention import flash_attention  # noqa: F401
