"""Hand-written TPU kernels (Pallas) for the framework's hot ops."""

from ddlbench_tpu.ops.flash_attention import flash_attention  # noqa: F401
from ddlbench_tpu.ops.paged_decode import (  # noqa: F401
    paged_attention, paged_cache_init, paged_decode_write,
    paged_prefill_write, paged_reorder)
