"""Shared Pallas plumbing for the ops kernels."""

from __future__ import annotations

import contextlib

import jax

_IN_SHARDED_JIT = [False]


@contextlib.contextmanager
def sharded_jit_tracing():
    """Mark the enclosed trace as a plain multi-device jit over GSPMD-sharded
    operands (dp/tp/fsdp strategies wrap their step bodies in this). Pallas
    dispatch happens at trace time, so the flag is captured into the traced
    program."""
    _IN_SHARDED_JIT[0] = True
    try:
        yield
    finally:
        _IN_SHARDED_JIT[0] = False


def pallas_partitions_safely(*operands) -> bool:
    """Whether a Pallas kernel over ``operands`` runs where it was placed
    instead of being gathered: pallas_call has no GSPMD partitioning rule, so
    under a plain multi-device jit with sharded operands XLA replicates them
    onto every device (ADVICE r1). Inside shard_map the operands are already
    per-shard (nonempty varying-manual-axes type), and outside a sharded jit
    (single-device programs, whatever the host's chip count) there is nothing
    to partition — both are safe. The shared policy behind the "auto"
    backends of ops/fused_xent.py and the flash-attention dispatch
    (models/transformer.py)."""
    from ddlbench_tpu.compat import vma_of

    if any(vma_of(o) for o in operands):
        return True
    return not _IN_SHARDED_JIT[0]


def pick_block(t: int, preferred: int, unit: int = 1):
    """Largest divisor of ``t`` that is <= preferred and a multiple of
    ``unit`` (block shapes must tile the dimension). Returns None when t is
    not a multiple of unit — on real TPU, Mosaic rejects blocks that are not
    tile-aligned (8 sublanes / 128 lanes), so compiled kernels pass the
    hardware unit and fall back (or error clearly) on a None instead of
    handing Mosaic an arbitrary divisor (ADVICE r1)."""
    if t % unit or preferred < unit:
        # no divisor <= preferred can be a multiple of unit (ADVICE r2:
        # returning unit here would silently exceed the caller's block/VMEM
        # budget)
        return None
    b = max(unit, min(preferred - preferred % unit, t))
    while t % b:
        b -= unit
    return b


def pallas_out_struct(shape, dtype, *operands):
    """ShapeDtypeStruct for a pallas_call output, carrying the union of the
    operands' varying-axes (VMA) types — required when a kernel runs inside a
    shard_map (e.g. per-block calls from ring attention, or any strategy
    whose model apply is shard_mapped)."""
    from ddlbench_tpu.compat import vma_of

    vma = set()
    for a in operands:
        vma |= set(vma_of(a))
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=frozenset(vma))
    return jax.ShapeDtypeStruct(shape, dtype)
