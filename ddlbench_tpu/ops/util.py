"""Shared Pallas plumbing for the ops kernels."""

from __future__ import annotations

import jax


def pallas_out_struct(shape, dtype, *operands):
    """ShapeDtypeStruct for a pallas_call output, carrying the union of the
    operands' varying-axes (VMA) types — required when a kernel runs inside a
    shard_map (e.g. per-block calls from ring attention, or any strategy
    whose model apply is shard_mapped)."""
    vma = set()
    for a in operands:
        vma |= set(getattr(jax.typeof(a), "vma", ()) or ())
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=frozenset(vma))
    return jax.ShapeDtypeStruct(shape, dtype)
