"""Fused causal attention (FlashAttention-2 style) as a Pallas TPU kernel.

This is the framework's hot-op kernel: the reference's only custom kernel is
the GNMT varlen pack_utils CUDA extension (SURVEY.md §2 D2); the modern
sequence workload's equivalent hot op is attention, so that is what gets the
hand-written kernel. The jnp fallback (models/transformer.py
causal_attention) materializes the [B, H, T, T] score matrix in HBM; this
kernel never does — it streams K/V blocks through VMEM with an
online-softmax accumulator, so HBM traffic drops from O(T^2) to O(T * d)
and the block matmuls run on the MXU.

Forward saves only O and the row logsumexp (LSE); backward recomputes the
probabilities blockwise in two more kernels (dQ; dK/dV together), the
standard FlashAttention-2 recipe, wired up with jax.custom_vjp.

Two grid designs share one set of block-step functions (round 3):

* **resident** (the fast path): grid (batch*head, outer block), the whole
  inner sequence lives in VMEM and a fori_loop sweeps it with causal
  bounds. Minimal grid overhead and no re-fetching, but scoped-VMEM use
  grows with T — Mosaic rejects it past ~8-16k (measured: 16.8 MiB at
  T=8192 with 1024-wide blocks vs the 16 MiB v5e limit).
* **streaming**: grid (batch*head, outer block, inner block), the inner
  dimension arrives blockwise via BlockSpec with accumulators in VMEM
  scratch — every block shape is T-independent, so any sequence length
  compiles (T=32k measured on one chip). ~15-30% slower at short T than
  resident (dead causal cells still pay their fetch), hence the hybrid.

_use_streaming picks per kernel: resident while the inner-side operands fit
a conservative budget, streaming beyond (or under oversized block
requests). Block-level causal skipping in both: resident bounds its fori,
streaming skips dead cells' compute under @pl.when.

``q_offset``/``k_offset`` give each block its absolute position — the same
convention as causal_attention — so the kernel also serves blocks of a
distributed sequence (parallel/sp.py ring attention).

Interpret mode (CPU tests) and the compiled TPU path share all code.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ddlbench_tpu.ops.util import pallas_out_struct as _out_struct

NEG_INF = -1e30

# Inner-side resident bytes (both streamed operands, raw) past which the
# streaming design is used. 3 MiB keeps every benchmarked shape on the fast
# resident path (T=8192, dh=64, bf16 -> 2 MiB measured compiling with
# 512-blocks) while dh=128 or f32 at 8k+ stream. Oversized blocks
# (max > 512) also stream once the inner side is nontrivial: the resident
# dkv kernel measured 16.8 MiB scoped VMEM at (bq=256, bk=1024, T=8192).
RESIDENT_MAX_BYTES = 3 * 1024 * 1024


def _use_streaming(t_inner: int, dh: int, itemsize: int, bq: int, bk: int,
                   stream) -> bool:
    if stream is not None:
        return bool(stream)
    resident = 2 * t_inner * dh * itemsize
    return resident > RESIDENT_MAX_BYTES or (
        max(bq, bk) > 512 and resident > 1024 * 1024)


def _grid_params(interpret: bool, streaming: bool):
    """Mosaic grid hints. Streaming: batch*head and the outer block are
    parallel, the inner streamed dimension is "arbitrary" (sequential — it
    carries the scratch accumulator). Resident: both dims parallel. No-op
    under interpret (CPU tests)."""
    if interpret:
        return {}
    from jax.experimental.pallas import tpu as pltpu

    sem = (("parallel", "parallel", "arbitrary") if streaming
           else ("parallel", "parallel"))
    return {"compiler_params": pltpu.CompilerParams(
        dimension_semantics=sem)}


def _pick_block(t: int, preferred: int, interpret: bool = False) -> int:
    """Largest divisor of t <= preferred tiling the sequence dimension; on
    real TPU it must also be a multiple of 8 (Mosaic sublane tile —
    ops/util.py:pick_block). Sequence lengths with no aligned divisor get a
    clear error instead of a raw Mosaic one; the attention dispatch
    (models/transformer.py:_flash_dispatch) avoids flash for such shapes."""
    from ddlbench_tpu.ops.util import pick_block

    b = pick_block(t, preferred, 1 if interpret else 8)
    if b is None:
        raise ValueError(
            f"flash_attention: sequence length {t} has no divisor that is a "
            f"multiple of 8; pad the sequence or use the XLA attention "
            f"backend")
    return b


def _causal_kv_bound(q_hi_pos, k_offset: int, block_k: int, num_k: int,
                     prefix_len: int = 0):
    """Number of leading K blocks any query position <= q_hi_pos can see.

    With a prefix (prefix-LM), K blocks overlapping [0, prefix_len) are
    visible to every query, so the bound is at least the prefix block count.
    """
    visible = q_hi_pos - k_offset + 1  # k positions strictly visible
    if prefix_len:
        visible = jnp.maximum(visible, prefix_len - k_offset)
    nb = (visible + block_k - 1) // block_k
    return jnp.clip(nb, 0, num_k)


# ---------------------------------------------------------------------------
# Block-step math, shared by the resident and streaming kernels.
# ---------------------------------------------------------------------------


def _block_mask(q_pos, k_pos, prefix_len: int):
    mask = q_pos >= k_pos
    if prefix_len:
        mask = mask | (k_pos < prefix_len)
    return mask


def _fwd_block_step(q, k_blk, v_blk, m, l, acc, q_pos, k_pos, scale,
                    prefix_len: int):
    """One online-softmax update of (m, l, acc) against a K/V block."""
    s = jax.lax.dot_general(
        q, k_blk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale
    mask = _block_mask(q_pos, k_pos, prefix_len)
    s = jnp.where(mask, s, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new) * mask.astype(jnp.float32)
    corr = jnp.exp(m - m_new)
    l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
    # p cast to the input dtype so the PV matmul takes the fast MXU path
    acc_new = acc * corr + jax.lax.dot_general(
        p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return m_new, l_new, acc_new


def _dq_block_step(q, do, lse, delta, k_blk, v_blk, q_pos, k_pos, scale,
                   prefix_len: int):
    """This q block's dq contribution from one K/V block."""
    s = jax.lax.dot_general(
        q, k_blk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale
    mask = _block_mask(q_pos, k_pos, prefix_len)
    # where() BEFORE the multiply: fully-masked rows have lse ~ -1e30 and
    # exp(s - lse) overflows to inf; inf * 0 would poison dq with NaN.
    p = jnp.where(mask, jnp.exp(s - lse), 0.0)
    dp = jax.lax.dot_general(
        do, v_blk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    ds = p * (dp - delta) * scale
    return jax.lax.dot_general(
        ds.astype(k_blk.dtype), k_blk, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _dkv_block_step(k, v, q_blk, do_blk, lse_blk, delta_blk, q_pos, k_pos,
                    scale, prefix_len: int):
    """This k block's (dk, dv) contributions from one Q/dO block."""
    s = jax.lax.dot_general(
        q_blk, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale
    mask = _block_mask(q_pos, k_pos, prefix_len)
    # see _dq_block_step: mask inside where() keeps inf out of the matmuls
    p = jnp.where(mask, jnp.exp(s - lse_blk), 0.0)  # [bq, bk]
    dv_add = jax.lax.dot_general(
        p.astype(do_blk.dtype), do_blk, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dp = jax.lax.dot_general(
        do_blk, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    ds = p * (dp - delta_blk) * scale
    dk_add = jax.lax.dot_general(
        ds.astype(q_blk.dtype), q_blk, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return dk_add, dv_add


# ---------------------------------------------------------------------------
# Resident kernels: grid (BH, outer), whole inner sequence in VMEM, fori
# sweep with causal bounds. Fast path for shapes that fit.
# ---------------------------------------------------------------------------


def _fwd_kernel_res(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, block_k,
                    q_offset, k_offset, num_k, prefix_len):
    bq = q_ref.shape[1]
    dh = q_ref.shape[2]
    q = q_ref[0]  # [bq, dh] native dtype; MXU accumulates f32 below
    qi = pl.program_id(1)
    q_pos = q_offset + qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0)
    bound = _causal_kv_bound(q_offset + (qi + 1) * bq - 1, k_offset, block_k,
                             num_k, prefix_len)

    def body(j, carry):
        k_blk = k_ref[0, pl.ds(j * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(j * block_k, block_k), :]
        k_pos = (k_offset + j * block_k
                 + jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1))
        return _fwd_block_step(q, k_blk, v_blk, *carry, q_pos, k_pos, scale,
                               prefix_len)

    m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, dh), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, bound, body, (m0, l0, acc0))
    l_safe = jnp.maximum(l, 1e-20)
    o_ref[0] = (acc / l_safe).astype(o_ref.dtype)
    # LSE of fully-masked rows stays NEG_INF-ish; backward p=exp(s-lse) uses
    # the same masking so those rows contribute nothing either way. Kept as
    # [T, 1] (not [T]) to satisfy TPU block-tiling constraints.
    lse_ref[0] = m + jnp.log(l_safe)


def _dq_kernel_res(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
                   scale, block_k, q_offset, k_offset, num_k, prefix_len):
    bq = q_ref.shape[1]
    q = q_ref[0]
    do = do_ref[0]
    lse = lse_ref[0]      # [bq, 1]
    delta = delta_ref[0]  # [bq, 1]
    qi = pl.program_id(1)
    q_pos = q_offset + qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0)
    bound = _causal_kv_bound(q_offset + (qi + 1) * bq - 1, k_offset, block_k,
                             num_k, prefix_len)

    def body(j, dq):
        k_blk = k_ref[0, pl.ds(j * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(j * block_k, block_k), :]
        k_pos = (k_offset + j * block_k
                 + jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1))
        return dq + _dq_block_step(q, do, lse, delta, k_blk, v_blk, q_pos,
                                   k_pos, scale, prefix_len)

    dq = jax.lax.fori_loop(
        0, bound, body, jnp.zeros((bq, q.shape[1]), jnp.float32)
    )
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _dkv_kernel_res(k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, scale, block_q, q_offset, k_offset,
                    num_q, prefix_len):
    bk = k_ref.shape[1]
    k = k_ref[0]
    v = v_ref[0]
    kj = pl.program_id(1)
    k_pos = (k_offset + kj * bk
             + jax.lax.broadcasted_iota(jnp.int32, (1, bk), 1))
    # first q block whose last position can see this k block's first position;
    # a k block overlapping the prefix is visible to every q block
    k_lo = k_offset + kj * bk
    start = jnp.clip((k_lo - q_offset) // block_q, 0, num_q)
    if prefix_len:
        start = jnp.where(k_lo < prefix_len, 0, start)

    def body(i, carry):
        dk, dv = carry
        q_blk = q_ref[0, pl.ds(i * block_q, block_q), :]
        do_blk = do_ref[0, pl.ds(i * block_q, block_q), :]
        lse_blk = lse_ref[0, pl.ds(i * block_q, block_q), :]      # [bq, 1]
        delta_blk = delta_ref[0, pl.ds(i * block_q, block_q), :]
        q_pos = (q_offset + i * block_q
                 + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0))
        dk_add, dv_add = _dkv_block_step(k, v, q_blk, do_blk, lse_blk,
                                         delta_blk, q_pos, k_pos, scale,
                                         prefix_len)
        return dk + dk_add, dv + dv_add

    dk, dv = jax.lax.fori_loop(
        start, num_q, body,
        (jnp.zeros(k.shape, jnp.float32), jnp.zeros(v.shape, jnp.float32)),
    )
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# Streaming kernels: grid (BH, outer, inner), inner blocks via BlockSpec,
# accumulators in VMEM scratch. Constant VMEM in T; any length compiles.
# ---------------------------------------------------------------------------


def _fwd_kernel_stream(q_ref, k_ref, v_ref, o_ref, lse_ref, m_sc, l_sc,
                       acc_sc, *, scale, block_k, q_offset, k_offset, num_k,
                       prefix_len):
    bq = q_ref.shape[1]
    qi, j = pl.program_id(1), pl.program_id(2)
    q_pos = q_offset + qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0)
    bound = _causal_kv_bound(q_offset + (qi + 1) * bq - 1, k_offset, block_k,
                             num_k, prefix_len)

    @pl.when(j == 0)
    def _init():
        m_sc[:] = jnp.full(m_sc.shape, NEG_INF, jnp.float32)
        l_sc[:] = jnp.zeros(l_sc.shape, jnp.float32)
        acc_sc[:] = jnp.zeros(acc_sc.shape, jnp.float32)

    @pl.when(j < bound)
    def _step():
        k_pos = (k_offset + j * block_k
                 + jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1))
        m, l, acc = _fwd_block_step(
            q_ref[0], k_ref[0], v_ref[0], m_sc[:], l_sc[:], acc_sc[:],
            q_pos, k_pos, scale, prefix_len)
        m_sc[:], l_sc[:], acc_sc[:] = m, l, acc

    @pl.when(j == num_k - 1)
    def _fini():
        l_safe = jnp.maximum(l_sc[:], 1e-20)
        o_ref[0] = (acc_sc[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0] = m_sc[:] + jnp.log(l_safe)


def _dq_kernel_stream(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                      acc_sc, *, scale, block_k, q_offset, k_offset, num_k,
                      prefix_len):
    bq = q_ref.shape[1]
    qi, j = pl.program_id(1), pl.program_id(2)
    q_pos = q_offset + qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0)
    bound = _causal_kv_bound(q_offset + (qi + 1) * bq - 1, k_offset, block_k,
                             num_k, prefix_len)

    @pl.when(j == 0)
    def _init():
        acc_sc[:] = jnp.zeros(acc_sc.shape, jnp.float32)

    @pl.when(j < bound)
    def _step():
        k_pos = (k_offset + j * block_k
                 + jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1))
        acc_sc[:] += _dq_block_step(
            q_ref[0], do_ref[0], lse_ref[0], delta_ref[0], k_ref[0], v_ref[0],
            q_pos, k_pos, scale, prefix_len)

    @pl.when(j == num_k - 1)
    def _fini():
        dq_ref[0] = acc_sc[:].astype(dq_ref.dtype)


def _dkv_kernel_stream(k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref,
                       dk_ref, dv_ref, dk_sc, dv_sc, *, scale, block_q,
                       q_offset, k_offset, num_q, prefix_len):
    bk = k_ref.shape[1]
    kj, i = pl.program_id(1), pl.program_id(2)
    k_pos = (k_offset + kj * bk
             + jax.lax.broadcasted_iota(jnp.int32, (1, bk), 1))
    k_lo = k_offset + kj * bk
    start = jnp.clip((k_lo - q_offset) // block_q, 0, num_q)
    if prefix_len:
        start = jnp.where(k_lo < prefix_len, 0, start)

    @pl.when(i == 0)
    def _init():
        dk_sc[:] = jnp.zeros(dk_sc.shape, jnp.float32)
        dv_sc[:] = jnp.zeros(dv_sc.shape, jnp.float32)

    @pl.when(i >= start)
    def _step():
        q_pos = (q_offset + i * block_q
                 + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0))
        dk_add, dv_add = _dkv_block_step(
            k_ref[0], v_ref[0], q_ref[0], do_ref[0], lse_ref[0], delta_ref[0],
            q_pos, k_pos, scale, prefix_len)
        dk_sc[:] += dk_add
        dv_sc[:] += dv_add

    @pl.when(i == num_q - 1)
    def _fini():
        dk_ref[0] = dk_sc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_sc[:].astype(dv_ref.dtype)


def _bh(x):
    B, H, T, dh = x.shape
    return x.reshape(B * H, T, dh)


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9)
)
def flash_attention(q, k, v, q_offset=0, k_offset=0, prefix_len=0,
                    block_q=512, block_k=512, interpret=False, stream=None):
    """Causal / prefix-LM attention, [B, H, T, dh] -> [B, H, Tq, dh], fused.

    Semantics match models/transformer.py causal_attention (including the
    q_offset/k_offset absolute-position convention and the prefix-LM rule:
    absolute key positions < prefix_len are visible to every query — the
    seq2seq source segment); fully-masked rows return 0. Block sizes shrink
    automatically to divide the sequence. Default 512x512 blocks measured
    fastest on v5e (2.3-2.5x over the XLA attention at T=1024-4096 forward,
    1.2-1.9x forward+backward). ``stream`` forces the streaming (True) or
    resident (False) grid design; None picks per kernel (module docstring).
    """
    o, _ = _flash_fwd_impl(q, k, v, q_offset, k_offset, prefix_len, block_q,
                           block_k, interpret, stream)
    return o


def _flash_fwd_impl(q, k, v, q_offset, k_offset, prefix_len, block_q, block_k,
                    interpret, stream):
    from jax.experimental.pallas import tpu as pltpu

    B, H, Tq, dh = q.shape
    Tk = k.shape[2]
    bq = _pick_block(Tq, block_q, interpret)
    bk = _pick_block(Tk, block_k, interpret)
    num_q, num_k = Tq // bq, Tk // bk
    scale = 1.0 / math.sqrt(dh)
    qr, kr, vr = _bh(q), _bh(k), _bh(v)
    BH = B * H
    streaming = _use_streaming(Tk, dh, q.dtype.itemsize, bq, bk, stream)
    f32 = jnp.float32

    kw = dict(scale=scale, block_k=bk, q_offset=q_offset, k_offset=k_offset,
              num_k=num_k, prefix_len=prefix_len)
    if streaming:
        kern = functools.partial(_fwd_kernel_stream, **kw)
        grid = (BH, num_q, num_k)
        in_specs = [
            pl.BlockSpec((1, bq, dh), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, dh), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, dh), lambda b, i, j: (b, j, 0)),
        ]
        out_specs = [
            pl.BlockSpec((1, bq, dh), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0)),
        ]
        scratch = [pltpu.VMEM((bq, 1), f32), pltpu.VMEM((bq, 1), f32),
                   pltpu.VMEM((bq, dh), f32)]
    else:
        kern = functools.partial(_fwd_kernel_res, **kw)
        grid = (BH, num_q)
        in_specs = [
            pl.BlockSpec((1, bq, dh), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, Tk, dh), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, Tk, dh), lambda b, i: (b, 0, 0)),
        ]
        out_specs = [
            pl.BlockSpec((1, bq, dh), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i: (b, i, 0)),
        ]
        scratch = []

    o, lse = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=[
            _out_struct((BH, Tq, dh), q.dtype, q, k, v),
            _out_struct((BH, Tq, 1), jnp.float32, q, k, v),
        ],
        scratch_shapes=scratch,
        interpret=interpret,
        **_grid_params(interpret, streaming),
    )(qr, kr, vr)
    return o.reshape(B, H, Tq, dh), lse


def _flash_fwd(q, k, v, q_offset, k_offset, prefix_len, block_q, block_k,
               interpret, stream):
    o, lse = _flash_fwd_impl(q, k, v, q_offset, k_offset, prefix_len, block_q,
                             block_k, interpret, stream)
    return o, (q, k, v, o, lse)


def _flash_bwd(q_offset, k_offset, prefix_len, block_q, block_k, interpret,
               stream, res, g):
    return _flash_bwd_core(q_offset, k_offset, prefix_len, block_q, block_k,
                           interpret, stream, res, g, None)


def _flash_bwd_core(q_offset, k_offset, prefix_len, block_q, block_k,
                    interpret, stream, res, g, g_lse):
    from jax.experimental.pallas import tpu as pltpu

    q, k, v, o, lse = res
    B, H, Tq, dh = q.shape
    Tk = k.shape[2]
    bq = _pick_block(Tq, block_q, interpret)
    bk = _pick_block(Tk, block_k, interpret)
    num_q, num_k = Tq // bq, Tk // bk
    scale = 1.0 / math.sqrt(dh)
    BH = B * H
    isz = q.dtype.itemsize

    # delta = rowsum(dO * O) — cheap elementwise+reduce, XLA fuses it. The
    # lse cotangent (flash_attention_lse) enters every ds exactly like -delta
    # (both multiply p rowwise: ds = p∘(dp - delta + lse_bar)), so it is a
    # delta shift and the kernels are shared.
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    if g_lse is not None:
        delta = delta - g_lse.astype(jnp.float32)
    qr, kr, vr, gr = _bh(q), _bh(k), _bh(v), _bh(g)
    delta_r = delta.reshape(BH, Tq, 1)
    f32 = jnp.float32

    dq_kw = dict(scale=scale, block_k=bk, q_offset=q_offset,
                 k_offset=k_offset, num_k=num_k, prefix_len=prefix_len)
    dq_streaming = _use_streaming(Tk, dh, isz, bq, bk, stream)
    if dq_streaming:
        dq_kern = functools.partial(_dq_kernel_stream, **dq_kw)
        dq_grid = (BH, num_q, num_k)
        dq_in = [
            pl.BlockSpec((1, bq, dh), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, dh), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, dh), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bq, dh), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0)),
        ]
        dq_out = pl.BlockSpec((1, bq, dh), lambda b, i, j: (b, i, 0))
        dq_scratch = [pltpu.VMEM((bq, dh), f32)]
    else:
        dq_kern = functools.partial(_dq_kernel_res, **dq_kw)
        dq_grid = (BH, num_q)
        dq_in = [
            pl.BlockSpec((1, bq, dh), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, Tk, dh), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, Tk, dh), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, bq, dh), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i: (b, i, 0)),
        ]
        dq_out = pl.BlockSpec((1, bq, dh), lambda b, i: (b, i, 0))
        dq_scratch = []

    dq = pl.pallas_call(
        dq_kern,
        grid=dq_grid,
        in_specs=dq_in,
        out_specs=dq_out,
        out_shape=_out_struct((BH, Tq, dh), q.dtype, qr, kr, vr, gr),
        scratch_shapes=dq_scratch,
        interpret=interpret,
        **_grid_params(interpret, dq_streaming),
    )(qr, kr, vr, gr, lse, delta_r)

    # the dkv kernel streams Q-side operands: Q, dO, lse, delta
    dkv_kw = dict(scale=scale, block_q=bq, q_offset=q_offset,
                  k_offset=k_offset, num_q=num_q, prefix_len=prefix_len)
    dkv_streaming = _use_streaming(Tq, dh, isz, bq, bk, stream)
    if dkv_streaming:
        dkv_kern = functools.partial(_dkv_kernel_stream, **dkv_kw)
        dkv_grid = (BH, num_k, num_q)
        dkv_in = [
            pl.BlockSpec((1, bk, dh), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, dh), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bq, dh), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bq, dh), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, j, i: (b, i, 0)),
        ]
        dkv_out = [
            pl.BlockSpec((1, bk, dh), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, dh), lambda b, j, i: (b, j, 0)),
        ]
        dkv_scratch = [pltpu.VMEM((bk, dh), f32), pltpu.VMEM((bk, dh), f32)]
    else:
        dkv_kern = functools.partial(_dkv_kernel_res, **dkv_kw)
        dkv_grid = (BH, num_k)
        dkv_in = [
            pl.BlockSpec((1, bk, dh), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, bk, dh), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, Tq, dh), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, Tq, dh), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, Tq, 1), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, Tq, 1), lambda b, j: (b, 0, 0)),
        ]
        dkv_out = [
            pl.BlockSpec((1, bk, dh), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, bk, dh), lambda b, j: (b, j, 0)),
        ]
        dkv_scratch = []

    dk, dv = pl.pallas_call(
        dkv_kern,
        grid=dkv_grid,
        in_specs=dkv_in,
        out_specs=dkv_out,
        out_shape=[
            _out_struct((BH, Tk, dh), k.dtype, qr, kr, vr, gr),
            _out_struct((BH, Tk, dh), v.dtype, qr, kr, vr, gr),
        ],
        scratch_shapes=dkv_scratch,
        interpret=interpret,
        **_grid_params(interpret, dkv_streaming),
    )(kr, vr, qr, gr, lse, delta_r)

    shape4 = lambda x, T: x.reshape(B, H, T, dh)
    return shape4(dq, Tq), shape4(dk, Tk), shape4(dv, Tk)


flash_attention.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def flash_attention_lse(q, k, v, q_offset=0, k_offset=0, prefix_len=0,
                        block_q=512, block_k=512, interpret=False,
                        stream=None):
    """flash_attention that ALSO returns the per-row logsumexp: (o, lse) with
    lse [B, H, Tq] f32.

    This is the building block for blockwise/ring attention over a
    distributed sequence: partial results (o_i, lse_i) against different K/V
    blocks combine exactly as o = Σ_i exp(lse_i - lse_tot) o_i with
    lse_tot = logaddexp_i(lse_i) (models/transformer.py ring_attention).
    Both outputs are differentiable: d lse/d scores = p, which folds into the
    existing backward kernels as a delta shift (ds = p∘(dp - (delta - lse_bar))),
    so the dq/dkv kernels are reused unchanged.
    """
    out, _ = _flash_lse_fwd(q, k, v, q_offset, k_offset, prefix_len, block_q,
                            block_k, interpret, stream)
    return out


def _flash_lse_fwd(q, k, v, q_offset, k_offset, prefix_len, block_q, block_k,
                   interpret, stream):
    o, lse = _flash_fwd_impl(q, k, v, q_offset, k_offset, prefix_len, block_q,
                             block_k, interpret, stream)
    B, H, Tq, _ = q.shape
    return (o, lse.reshape(B, H, Tq)), (q, k, v, o, lse)


def _flash_lse_bwd(q_offset, k_offset, prefix_len, block_q, block_k,
                   interpret, stream, res, cots):
    g_o, g_lse = cots
    return _flash_bwd_core(q_offset, k_offset, prefix_len, block_q, block_k,
                           interpret, stream, res, g_o, g_lse)


flash_attention_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)
