"""jax version-compatibility shims (single home, no copies to drift).

The strategies' shard_map code speaks the VMA (varying-manual-axes) type
system: ``jax.typeof(x).vma`` to read a value's varying axes and
``lax.pcast(..., to="varying")`` to align switch branches / scan carries.
Both arrived well after the oldest jax this repo must run under (the
baked-in toolchain ships 0.4.x, which has neither ``jax.typeof`` nor
``lax.pcast``).

Pre-VMA jax tracks the SAME information inverted: shard_map's check_rep
machinery assigns every value a REPLICATION set (axes the value is known
replicated over; varying = mesh axes minus rep), aligns values with an
explicit ``pbroadcast`` op, and — with ``check_rep=True`` — traces user
code under a RewriteTrace whose tracers expose their rep set through
``get_replication``. :func:`pcast_varying` uses that to emulate ``pcast``
exactly: cast only the axes the value is still replicated over, so the
transpose (a real ``psum``) runs only where mathematically required —
e.g. the cast on gpipe's stage-sharded/data-replicated params transposes
to the DP gradient all-reduce over 'data' alone, and values that are
already fully varying get NO cast (keeping collectives out of
device-divergent ``lax.switch`` branches, which would otherwise deadlock
the mesh in the backward pass).

Three stock 0.4.x rules are patched at import (see ``_install_prevma``):
the pbroadcast check (relaxed to idempotent-cast semantics), the cond
check (stock demands exact rep equality across branches, including grad
residuals where one branch saves a constant and another a computed
value; jax's own rewrite path and-merges instead), and the
pbroadcast/psum2 transposes (Zero-cotangent handling for
multiple-results primitives).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
from jax import lax

_TYPEOF = getattr(jax, "typeof", None)
_HAS_VMA = _TYPEOF is not None and hasattr(lax, "pcast")

try:  # jax >= 0.4.35 exposes shard_map at top level
    from jax import shard_map as _jax_shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _jax_shard_map


def shard_map(f=None, **kw):
    """``jax.shard_map``; every strategy imports this one symbol so any
    future version-specific policy lives in exactly one place."""
    if f is None:
        return lambda g: _jax_shard_map(g, **kw)
    return _jax_shard_map(f, **kw)


def typeof(x):
    """``jax.typeof`` where available, else the abstract value the old way
    (``x.aval`` for tracers/arrays, ``jax.core.get_aval`` for literals)."""
    if _TYPEOF is not None:
        return _TYPEOF(x)
    aval = getattr(x, "aval", None)
    if aval is not None:
        return aval
    return jax.core.get_aval(x)


def vma_of(x) -> Tuple:
    """The value's varying-manual-axes as a tuple; () on pre-VMA jax (whose
    avals have no ``vma`` attribute) and outside shard_map."""
    return tuple(getattr(typeof(x), "vma", ()) or ())


def pcast_varying(v, axes):
    """Mark ``v`` varying over any of ``axes`` it is not already varying
    over (shard_map branches/carries must agree on VMA types).

    On pre-VMA jax, "not already varying over" is read from the check_rep
    RewriteTracer's replication set (``get_replication``); values the
    trace cannot attribute a rep set to (constants created inside the
    traced function) are fully replicated by definition. The cast is the
    old ``pbroadcast`` op over exactly the still-replicated axes — the
    precise analog of ``lax.pcast(..., to="varying")``, including its
    transpose (psum over the same axes).
    """
    if not axes:
        return v
    if _HAS_VMA:
        missing = tuple(a for a in axes if a not in vma_of(v))
        return lax.pcast(v, missing, to="varying") if missing else v
    sm = _prevma_shard_map()
    if sm is None:  # no VMA and no check_rep machinery: nothing to align
        return v
    try:
        sm.get_replication(v)
    except ValueError:
        # Trace constant (no rep attribution): replicated over every mesh
        # axis, and — because const-only subgraphs land in the known/
        # forward jaxpr — its cast is identity end to end, never a
        # collective in the backward.
        return sm.pbroadcast(v, tuple(axes))
    # Tracers keep their own replication accounting: an explicit
    # pbroadcast here would transpose to a REAL psum, which inside a
    # device-divergent lax.switch branch deadlocks the mesh. The lenient
    # cond/scan check rules (installed in _install_prevma) join the
    # resulting rep differences the same way jax's own rewrite pass does.
    return v


_SM_MOD: Optional[object] = None


def _prevma_shard_map():
    """The old shard_map module with our compat rules installed, or None
    when unavailable. Installation happens once, on first use."""
    global _SM_MOD
    if _SM_MOD is None:
        _SM_MOD = _install_prevma()
    return _SM_MOD if _SM_MOD is not False else None


def _install_prevma():
    try:
        from jax.experimental import shard_map as sm

        sm.pbroadcast_p, sm.psum2_p, sm.get_replication  # probe the surface
    except (ImportError, AttributeError):  # pragma: no cover
        return False

    # pbroadcast check, relaxed to idempotent-cast semantics: stock ERRORS
    # when a value is already varying over every broadcast axis; pcast
    # treats that as a no-op. (Belt to get_replication's braces — e.g.
    # values whose rep the eager/vmap paths cannot attribute.)
    def _pbroadcast_check(mesh, *in_rep, axes, axis_index_groups):
        return [(set(mesh.axis_names) if r is None else r) - set(axes)
                for r in in_rep]

    # register_check()/register_norewrite() are setdefault-only, and the
    # norewrite entry froze a reference to the stock check at jax import —
    # replace both registry entries directly.
    sm._check_rules[sm.pbroadcast_p] = _pbroadcast_check
    sm._rewrite_rules[sm.pbroadcast_p] = partial(
        sm._no_rewrite, sm.pbroadcast_p, _pbroadcast_check)

    # standard per-primitive check, relaxed to intersection-join semantics:
    # stock demands every argument's rep set be IDENTICAL, but our lenient
    # cond/scan joins below (and the identity pbroadcast transpose) re-walk
    # rewritten jaxprs under and-merged reps, where a pbroadcast that
    # aligned two args at trace time no longer produces equal sets — e.g.
    # tpp's pad_vec pads a 'model'-replicated activation with a scan-carry
    # zero that the join demoted to fully varying. The sound output rep
    # under mixed inputs is the intersection (an output can only be known
    # replicated over axes EVERY input is), which is exactly what jax's own
    # rewrite pass converges to.
    def _lenient_standard(prim, mesh, *in_rep, **__):
        in_rep_ = [r for r in in_rep if r is not None]
        if not in_rep_:
            return None
        out = set(in_rep_[0])
        for r in in_rep_[1:]:
            out &= r
        return out

    for prim, rule in list(sm._check_rules.items()):
        if getattr(rule, "func", None) is sm._standard_check:
            sm._check_rules[prim] = partial(_lenient_standard, prim)

    # cond check: stock demands EXACT rep equality across branches —
    # including grad residuals, where one branch may save a constant (rep
    # None) and another a computed value (rep set()). jax's own rewrite
    # path (_cond_rewrite) and-merges branch reps; give the check pass the
    # same join semantics.
    cond_p = sm.control_flow.conditionals.cond_p

    def _cond_join(mesh, *in_rep, branches):
        _, *args_rep = in_rep
        out = None
        for br in branches:
            rep = [set(mesh.axis_names) if r is None else r
                   for r in sm._check_rep(mesh, br.jaxpr, args_rep)]
            out = rep if out is None else [a & b for a, b in zip(out, rep)]
        return out

    sm._check_rules[cond_p] = _cond_join

    # scan check: same story for carries — stock demands carry-in rep ==
    # carry-out rep exactly; the rewrite pass (_scan_rewrite) runs an
    # and-merge fixpoint instead. Mirror the fixpoint in the check.
    scan_p = sm.control_flow.loops.scan_p

    def _scan_join(mesh, *in_rep, jaxpr, num_consts, num_carry, **_):
        full = set(mesh.axis_names)
        norm = lambda r: full if r is None else r
        const_rep, carry_in, xs_rep = sm.split_list(
            list(in_rep), [num_consts, num_carry])
        carry_in = [norm(r) for r in carry_in]
        ys_rep = []
        for _i in range(1 + num_carry):
            out_rep = sm._check_rep(
                mesh, jaxpr.jaxpr, [*const_rep, *carry_in, *xs_rep])
            carry_out, ys_rep = sm.split_list(list(out_rep), [num_carry])
            carry_out = [a & norm(b) for a, b in zip(carry_in, carry_out)]
            if carry_out == carry_in:
                break
            carry_in = carry_out
        return [*carry_in, *[norm(r) for r in ys_rep]]

    sm._check_rules[scan_p] = _scan_join

    # pbroadcast transpose: stock binds psum2 on the cotangents — a REAL
    # collective. The check_rep rewrite inserts pbroadcasts inside
    # lax.switch branches (to match branch reps), and cond partial-eval
    # keeps whole switches in the unknown jaxpr, so those transposes land
    # INSIDE device-divergent branches where each device would execute a
    # different collective sequence: guaranteed mesh deadlock. Transpose
    # as identity instead: each device keeps its LOCAL cotangent, which is
    # exactly right for the pipeline strategies' stage-local parameters
    # (only device d executes branch d's compute). What identity cannot
    # express is an implicit cross-replica gradient all-reduce riding a
    # cast's transpose — gpipe's dp_replicas path does that, and is
    # guarded with a clear error on pre-VMA jax (parallel/gpipe.py);
    # hetero's replica all-reduce is an explicit ppermute ring and stays
    # correct.
    Zero = sm.ad_util.Zero
    sm.ad.deflinear2(sm.pbroadcast_p,
                     lambda cts, *_, axes, axis_index_groups: cts)

    # psum2 transpose: keep stock semantics (pbroadcast, identity
    # lowering) but Zero-aware — linear_transpose2's Zero short-circuit
    # tests the whole cotangent against Zero, which for multiple-results
    # primitives is a LIST, so symbolic Zeros inside it reach .bind() and
    # crash.
    def _psum2_transpose(cts, *_, axes, axis_index_groups):
        nz = [c for c in cts if type(c) is not Zero]
        out = iter(sm.pbroadcast_p.bind(
            *nz, axes=axes,
            axis_index_groups=axis_index_groups)) if nz else iter(())
        return [c if type(c) is Zero else next(out) for c in cts]

    sm.ad.deflinear2(sm.psum2_p, _psum2_transpose)

    # Stock _shard_map_transpose mispairs cotangents with in_names: the
    # backward_pass over the partial-eval'd body returns cts ordered
    # [residuals..., undefined-args...], which it zips straight against
    # in_names (ORIGINAL arg order) — wrong whenever the residual list is
    # not exactly the defined args (i.e. whenever the body computes
    # anything worth saving). Strategies that grad INSIDE shard_map
    # (dp/tp/fsdp) never hit this; gpipe/hetero grad THROUGH shard_map and
    # do. This reimplementation keeps only the undef-arg cotangents and
    # scatters them back to arg order before the spec mapping.
    ad, pe, core = sm.ad, sm.pe, sm.core

    def _fixed_shard_map_transpose(out_cts, *args, jaxpr, mesh, in_names,
                                   out_names, check_rep, rewrite, auto):
        mb_div = lambda x, y: x / y if y != 1 else x
        out_cts = [
            ad.Zero(sm._shard_aval(mesh, ns, x.aval)) if type(x) is ad.Zero
            else x if rewrite or sm.dtypes.dtype(x) == sm.dtypes.float0
            else mb_div(x, sm.prod(map(mesh.shape.get,
                                       sm._unmentioned2(mesh, ns, auto))))
            for ns, x in zip(out_names, out_cts)]
        args = [x if type(x) is not ad.UndefinedPrimal else
                ad.UndefinedPrimal(sm._shard_aval(mesh, ns, x.aval))
                for ns, x in zip(in_names, args)]
        all_args, in_tree = sm.tree_flatten((out_cts, args))

        @sm.lu.wrap_init
        def fun_trans(out_cts, args):
            undef = list(map(ad.is_undefined_primal, args))
            res, undefs = sm.partition_list(undef, args)
            jaxpr_known, jaxpr_unknown, _, _ = pe.partial_eval_jaxpr_nounits(
                pe.close_jaxpr(jaxpr), undef, False)
            res_reshaped = core.jaxpr_as_fun(jaxpr_known)(*res)
            out = ad.backward_pass(
                jaxpr_unknown.jaxpr, False, (), (*res_reshaped, *undefs),
                out_cts)
            undef_cts = iter(list(out)[len(res_reshaped):])
            out = [next(undef_cts) if u else ad.Zero(a.aval)
                   for u, a in zip(undef, args)]
            # Unconditional psum over each input's unmentioned axes (stock
            # does this only when rewrite=False): with the identity
            # collective transposes above, every device holds its LOCAL
            # cotangent, and an input replicated over an axis (dp params,
            # stage-replicated activations) is consumed by every member of
            # that axis — its true cotangent is the sum. This is where
            # e.g. gpipe's hybrid-PPxDP gradient all-reduce happens on
            # pre-VMA jax, as one uniform top-level collective.
            out = [
                ad.Zero(sm._unshard_aval(mesh, ns, x.aval))
                if type(x) is ad.Zero
                else jax.lax.psum(x, tuple(sm._unmentioned2(mesh, ns, auto)))
                for ns, x in zip(in_names, out)]
            return out

        fun_trans, nz_arg_cts = ad.nonzero_outputs(fun_trans)
        fun_trans_flat, out_tree = sm.flatten_fun_nokwargs(fun_trans, in_tree)
        new_in_names = \
            [n for n, x in zip(out_names, out_cts)
             if type(x) is not ad.Zero] + \
            [n for n, x in zip(in_names, args)
             if type(x) is not ad.UndefinedPrimal]

        def new_out_names_thunk():
            return tuple(names for names, nz in zip(in_names, nz_arg_cts())
                         if nz)

        out_flat = sm.shard_map_p.bind(
            fun_trans_flat, *all_args, mesh=mesh,
            in_names=tuple(new_in_names),
            out_names_thunk=new_out_names_thunk, check_rep=check_rep,
            rewrite=rewrite, auto=auto)
        return sm.tree_unflatten(out_tree(), out_flat)

    ad.primitive_transposes[sm.shard_map_p] = _fixed_shard_map_transpose
    return sm
