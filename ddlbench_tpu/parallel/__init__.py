from ddlbench_tpu.parallel.api import make_strategy

__all__ = ["make_strategy"]
