"""Data-parallel strategy — the reference's Horovod engine, TPU-native.

Reference mechanism (benchmark/mnist/mnist_horovod.py): hvd.init + one process
per GPU (:162-171), DistributedSampler batch sharding (:207-219), lr scaled by
world size (:226), rank-0 parameter/optimizer broadcast (:230-231),
DistributedOptimizer hooking an NCCL allreduce onto every gradient (:234-236),
and allreduced eval metrics via metric_average (:129-132).

TPU-native design: one jit over a 1-D 'data' mesh. The batch is sharded on the
leading axis; parameters are replicated. XLA's SPMD partitioner inserts the
gradient all-reduce over ICI automatically (the explicit analog of Horovod's
per-gradient NCCL hook), metric means are global by construction (allreduced
eval-metric parity), and the initial `device_put` of replicated params is the
broadcast-init. Helper processes, samplers, and hooks all disappear into the
compiled program.

Deviation (documented): BatchNorm statistics are computed over the *global*
batch (sync-BN) because the batch axis is sharded under one jit; Horovod
computes per-replica statistics. Throughput is unaffected; accuracy parity is
equal or better (SURVEY.md §7 "BatchNorm under pipeline/DP").
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ddlbench_tpu.config import RunConfig
from ddlbench_tpu.models.layers import LayerModel, init_model
from ddlbench_tpu.parallel.common import make_optimizer
from ddlbench_tpu.parallel.single import TrainState


def make_data_mesh(num_devices: int, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    from ddlbench_tpu.distributed import make_mesh

    # DP allreduce tolerates DCN latency; the 'data' axis spans hosts.
    return make_mesh([("data", num_devices)], devices=devices, dcn_axis="data")


class DPStrategy:
    """strategy='dp': batch sharded over the 'data' mesh axis, params replicated."""

    def __init__(self, model: LayerModel, cfg: RunConfig, mesh: Optional[Mesh] = None):
        self.model = model
        self.cfg = cfg
        self.mesh = mesh or make_data_mesh(cfg.num_devices)
        self.compute_dtype = jnp.dtype(cfg.compute_dtype)
        self._opt_init, opt_update = make_optimizer(cfg)
        smooth = cfg.resolved_label_smoothing()

        self._replicated = NamedSharding(self.mesh, P())
        self._batch_sharding = NamedSharding(self.mesh, P("data"))

        def train_step(ts: TrainState, x, y, lr):
            # MoE routing statistics are global-batch (dense semantics: the
            # batch axis is sharded under one jit). With grad_accum_steps > 1
            # this is Horovod backward_passes_per_step parity: K micro-steps,
            # one allreduce on the averaged gradient.
            from ddlbench_tpu.ops.util import sharded_jit_tracing
            from ddlbench_tpu.parallel.common import loss_and_grads

            with sharded_jit_tracing():  # auto-Pallas unsafe under GSPMD
                ce, (correct, valid), new_state, grads = loss_and_grads(
                    model, cfg, ts.params, ts.model_state, x, y,
                    self.compute_dtype, smooth)
            params, opt = opt_update(ts.params, grads, ts.opt, lr)
            metrics = {
                "loss": ce,
                "accuracy": correct.astype(jnp.float32)
                / jnp.maximum(1.0, valid.astype(jnp.float32)),
            }
            return TrainState(params, new_state, opt), metrics

        def eval_step(ts: TrainState, x, y):
            from ddlbench_tpu.ops.util import sharded_jit_tracing
            from ddlbench_tpu.parallel.common import eval_metrics

            with sharded_jit_tracing():
                return eval_metrics(model, cfg, ts.params, ts.model_state,
                                    x, y, self.compute_dtype)

        self.train_step = jax.jit(
            train_step,
            donate_argnums=(0,),
            in_shardings=(None, self._batch_sharding, self._batch_sharding, None),
            out_shardings=None,
        )
        self.eval_step = jax.jit(
            eval_step,
            in_shardings=(None, self._batch_sharding, self._batch_sharding),
        )

    def init(self, key) -> TrainState:
        from ddlbench_tpu.distributed import put_global_tree

        params, state, _ = init_model(self.model, key)
        ts = TrainState(params, state, self._opt_init(params))
        # Broadcast-init parity (mnist_horovod.py:230-231): replicate to the
        # mesh — identical on every host since init is seed-deterministic.
        shardings = TrainState(self._replicated, self._replicated,
                               self._replicated)
        if self.cfg.shard_opt_state:
            # ZeRO-1: optimizer state sharded over 'data' (largest divisible
            # dim per leaf), params replicated. Pure placement — XLA shards
            # the update math and all-gathers only the parameter delta. With
            # adam this drops the optimizer memory 2x*params -> 2x/world.
            from ddlbench_tpu.parallel.sharded import _leaf_spec

            n = self.mesh.devices.size

            def leaf_sh(x):
                return NamedSharding(
                    self.mesh, _leaf_spec(x, "data", n, prefer_last=False))

            shardings = TrainState(
                self._replicated, self._replicated,
                jax.tree.map(leaf_sh, ts.opt))
        return put_global_tree(ts, shardings)

    def shard_batch(self, x, y):
        from ddlbench_tpu.distributed import put_global_batch

        return (
            put_global_batch(x, self._batch_sharding),
            put_global_batch(y, self._batch_sharding),
        )

    @property
    def world_size(self) -> int:
        return self.mesh.devices.size
