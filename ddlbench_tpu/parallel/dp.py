"""Data-parallel strategy — the reference's Horovod engine, TPU-native.

Reference mechanism (benchmark/mnist/mnist_horovod.py): hvd.init + one process
per GPU (:162-171), DistributedSampler batch sharding (:207-219), lr scaled by
world size (:226), rank-0 parameter/optimizer broadcast (:230-231),
DistributedOptimizer hooking an NCCL allreduce onto every gradient (:234-236),
and allreduced eval metrics via metric_average (:129-132).

TPU-native design: one jit over a 1-D 'data' mesh. The batch is sharded on the
leading axis; parameters are replicated. XLA's SPMD partitioner inserts the
gradient all-reduce over ICI automatically (the explicit analog of Horovod's
per-gradient NCCL hook), metric means are global by construction (allreduced
eval-metric parity), and the initial `device_put` of replicated params is the
broadcast-init. Helper processes, samplers, and hooks all disappear into the
compiled program.

Deviation (documented): BatchNorm statistics are computed over the *global*
batch (sync-BN) because the batch axis is sharded under one jit; Horovod
computes per-replica statistics. Throughput is unaffected; accuracy parity is
equal or better (SURVEY.md §7 "BatchNorm under pipeline/DP").

Sharded weight update (``--dp-shard-update``, ZeRO-1): with the flag on, the
train step runs under an explicit shard_map over the 'data' axis instead of
leaving the collective pattern to GSPMD: each device computes its batch
shard's partial gradients, the packed flat gradient vector reduce-scatters
(``lax.psum_scatter``) so every chip receives one contiguous 1/world slice
of the summed gradient, momentum/Adam state and the weight update live on
that slice only (the packed flat-vector optimizer of parallel/common.py
makes the shard a contiguous slice), and the updated parameter shard
all-gathers back to the replicated pytree at the shard_map boundary. Wire
bytes equal the replicated ring allreduce (RS + AG = 2(r-1)/r x P) but
optimizer-state memory and update FLOPs drop ~world x. BatchNorm runs
explicit cross-replica statistics (models/layers.batch_parallel), keeping
replicated dp's sync-BN semantics. ``--allreduce-dtype bf16`` additionally
casts the gradient partials to bfloat16 before the collective (EQuARX-style
compressed allreduce — dtype-narrowed ring collectives without block
rescaling), halving gradient wire bytes; it composes with or without the
sharded update (without, the engine runs an explicit bf16 ``lax.psum`` and
keeps the update replicated). Numerics: the f32 sharded update is pinned
bitwise-identical to replicated dp on the CPU mesh for non-BN models
(tests/test_dp_shard.py); BN models agree to float rounding only, because
GSPMD places the BN-backward cross-replica reductions around linear ops at
its own discretion while the explicit engine fixes them (sync_batch_mean).

Comm/compute overlap (``--comm-buckets K``, ISSUE 6): with K > 1 the packed
flat gradient splits into K contiguous, LAYER-ALIGNED buckets
(common.flat_meta's leaf_groups = leaves per model layer), each riding its
OWN collective. The per-bucket reduce-scatter depends only on that
bucket's layers' gradients, so under XLA's latency-hiding scheduler
(distributed.comm_flags) late buckets' wire time hides under earlier
layers' backward compute — the cross-replica sharded-weight-update
overlap, expressed as dataflow instead of a schedule. Combined with
``--dp-shard-update`` the engine goes fully OVERLAPPED: parameters stay
SHARDED between steps (TrainState.params is the flat device-major f32
vector, one contiguous shard per chip) and the forward all-gathers each
bucket just-in-time — every leaf depends only on its bucket's all-gather,
so the first layers start while late buckets are still in flight
(FSDP-style prefetch left to the scheduler). Bucketing only moves pad
zeros between leaves and never splits or reorders a reduction, so the f32
bucketed path is bitwise-pinned to the monolithic PR 3 engine and
``--comm-buckets 1`` compiles the exact PR 3 program.

Elastic world-invariant numerics (``--elastic-slices E``, ISSUE 12): the
local-sum + psum_scatter reduction above ties the f32 bits of every loss
and gradient to the WORLD SIZE (different batch partitions contract and
reduce in different orders), so an elastic run that shrinks 4 -> 2 chips
could never replay bitwise. With E set, the engine instead computes
gradients in E fixed slices of the GLOBAL batch (contiguous, E/world per
device) and reduces them over a canonical balanced binary tree: a
pairwise fold over each device's contiguous slices composes with a
recursive-doubling butterfly allreduce (log2(world) ppermute+add rounds;
IEEE addition is commutative, so every device lands on the SAME bits)
into one tree whose shape depends on E alone. Save at world N, reshard
(train/reshard.py), resume at world M: per-slice programs, tree, and
elementwise optimizer are all world-independent, so per-step losses and
materialized params are bitwise equal to the uninterrupted N-run
(tests/test_elastic.py). Exact-replay mode, not a fast path: the
butterfly ships log2(world) full vectors vs the ring's (world-1)/world,
and it is scoped to f32 wire, stateless (non-BN) models, and the sharded
update. Eval runs the same canonical reduction so validation losses
match across worlds too.

int8 wire (``--allreduce-dtype int8``, EQuARX-lite): per-bucket GLOBAL
absmax (lax.pmax) -> shared scale absmax/qmax with qmax = 127 // world
(the collective sums IN int8; see common.sum_safe_qmax) -> stochastic
rounding seeded from the run seed + a step counter in the optimizer dict
+ device + bucket indices (bitwise-reproducible runs) -> int8
psum/psum_scatter -> dequantize. Quarter gradient wire bytes vs f32;
accuracy is gated by the digits matrix (tools/accparity.py dp-int8 rows),
not claimed by construction.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ddlbench_tpu.config import RunConfig
from ddlbench_tpu.models.layers import LayerModel, init_model
from ddlbench_tpu.parallel.common import make_optimizer
from ddlbench_tpu.parallel.single import TrainState


def make_data_mesh(num_devices: int, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    from ddlbench_tpu.distributed import make_mesh

    # DP allreduce tolerates DCN latency; the 'data' axis spans hosts.
    return make_mesh([("data", num_devices)], devices=devices, dcn_axis="data")


class DPStrategy:
    """strategy='dp': batch sharded over the 'data' mesh axis, params replicated."""

    def __init__(self, model: LayerModel, cfg: RunConfig, mesh: Optional[Mesh] = None):
        from ddlbench_tpu.guard import device_guard

        self.model = model
        self.cfg = cfg
        self.mesh = mesh or make_data_mesh(cfg.num_devices)
        self.compute_dtype = jnp.dtype(cfg.compute_dtype)
        self._opt_init, opt_update = make_optimizer(cfg)
        self._opt_update = opt_update
        smooth = cfg.resolved_label_smoothing()
        guard = self._guard = device_guard(cfg)  # None = pre-guard program

        self._replicated = NamedSharding(self.mesh, P())
        self._batch_sharding = NamedSharding(self.mesh, P("data"))

        # Explicit collective engine (sharded weight update / compressed
        # allreduce): the train step is built by _build_explicit_engine
        # below instead of the GSPMD path; eval is identical either way.
        self.shard_update = bool(cfg.dp_shard_update)
        self.wire_dtype = jnp.dtype(cfg.resolved_allreduce_dtype())
        self._explicit = cfg.dp_explicit_collectives()
        self._flat_meta = None

        def train_step(ts: TrainState, x, y, lr):
            # MoE routing statistics are global-batch (dense semantics: the
            # batch axis is sharded under one jit). With grad_accum_steps > 1
            # this is Horovod backward_passes_per_step parity: K micro-steps
            # between optimizer updates. (GSPMD reduces each micro-gradient
            # inside the scan — the carry needs a concrete sharding — so the
            # wire cost is K allreduces; the explicit sharded engine below
            # halves that with K reduce-scatters.)
            from ddlbench_tpu.ops.util import sharded_jit_tracing
            from ddlbench_tpu.parallel.common import loss_and_grads

            if guard is None:
                with sharded_jit_tracing():  # auto-Pallas unsafe under GSPMD
                    ce, (correct, valid), new_state, grads = loss_and_grads(
                        model, cfg, ts.params, ts.model_state, x, y,
                        self.compute_dtype, smooth)
                params, opt = opt_update(ts.params, grads, ts.opt, lr)
            else:
                # Stability guard (same shape as the single engine): scaled
                # objective, fused health pair, in-step skip-select. GSPMD
                # shards the norm reduction like any other reduction.
                opt_in, gstate = guard.split_opt(ts.opt)
                smul = guard.smul(gstate, lr)
                with sharded_jit_tracing():
                    ce, (correct, valid), new_state, grads = loss_and_grads(
                        model, cfg, ts.params, ts.model_state, x, y,
                        self.compute_dtype, smooth, obj_scale=smul)
                grads = guard.unscale(grads, smul)
                finite, gnorm = guard.health(ce, grads)
                params, opt = opt_update(ts.params, grads, opt_in, lr)
                params, new_state, opt, gm = guard.commit(
                    finite, gnorm, gstate, (params, new_state, opt),
                    (ts.params, ts.model_state, opt_in))
            metrics = {
                "loss": ce,
                "accuracy": correct.astype(jnp.float32)
                / jnp.maximum(1.0, valid.astype(jnp.float32)),
            }
            if guard is not None:
                metrics.update(gm)
            return TrainState(params, new_state, opt), metrics

        def eval_step(ts: TrainState, x, y):
            from ddlbench_tpu.ops.util import sharded_jit_tracing
            from ddlbench_tpu.parallel.common import eval_metrics

            with sharded_jit_tracing():
                return eval_metrics(model, cfg, self._params_pytree(ts),
                                    ts.model_state, x, y, self.compute_dtype)

        self._overlap = False  # _build_explicit_engine may flip it
        self.eval_step = None  # the elastic engine installs its own
        if self._explicit:
            self._build_explicit_engine(smooth)
        else:
            self.train_step = jax.jit(
                train_step,
                donate_argnums=(0,),
                in_shardings=(None, self._batch_sharding,
                              self._batch_sharding, None),
                out_shardings=None,
            )
        if self.eval_step is None:
            self.eval_step = jax.jit(
                eval_step,
                in_shardings=(None, self._batch_sharding,
                              self._batch_sharding),
            )
        self._materialize = jax.jit(self._params_pytree,
                                    out_shardings=self._replicated)

    def _params_pytree(self, ts: TrainState):
        """ts.params as the per-layer pytree — identity except under the
        overlapped engine, whose between-steps params are the flat
        device-major sharded vector (jit callers let XLA insert the
        gathers; GSPMD slices what each consumer needs)."""
        if not self._overlap:
            return ts.params
        from ddlbench_tpu.parallel.common import from_device_major, unpack_flat

        meta = self._flat_meta
        return unpack_flat(
            from_device_major(ts.params, meta, self.mesh.devices.size), meta)

    def materialize_params(self, ts: TrainState):
        """Replicated per-layer params pytree for host-side consumers
        (activation logging, tools) — the train loop calls this instead of
        touching ts.params so the overlapped engine's flat sharded state
        stays an implementation detail."""
        if not self._overlap:
            return ts.params
        return self._materialize(ts)

    # -- explicit collective engine (ZeRO-1 / compressed allreduce) --------

    def _local_loss_sums(self, params, state, x, y, smooth):
        """Local-shard (obj_sum, ce_sum, correct, valid, norm) mirroring
        loss_with_moe_aux's global computation op for op, so the explicit
        engine's partial gradients and metrics match the GSPMD path's.
        ``norm`` is the LOCAL loss normalizer contribution (float mask sum
        for the unfused CE, int valid count for the fused head — the two
        paths normalize with different dtypes in the replicated step)."""
        from ddlbench_tpu.models.layers import apply_model
        from ddlbench_tpu.parallel.common import (cast_input, cast_params,
                                                  correct_and_count,
                                                  fused_head_loss_sums,
                                                  head_fusable)

        cfg = self.cfg
        p = cast_params(params, self.compute_dtype)
        xc = cast_input(x, self.compute_dtype)
        if cfg.fused_head_loss and head_fusable(self.model):
            obj_sum, ce_sum, correct, valid, new_state = fused_head_loss_sums(
                self.model, p, state, xc, y, smooth)
            return obj_sum, ce_sum, correct, valid, valid, new_state
        logits, new_state = apply_model(self.model, p, state, xc, True)
        lf = logits.astype(jnp.float32)
        logp = jax.nn.log_softmax(lf, axis=-1)
        maskf = (y >= 0).astype(jnp.float32)
        safe = jnp.maximum(y, 0)
        nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
        ce_sum = jnp.sum(nll * maskf)
        if smooth:
            nll_s = (1.0 - smooth) * nll - smooth * jnp.mean(logp, axis=-1)
            obj_sum = jnp.sum(nll_s * maskf)
        else:
            obj_sum = ce_sum
        correct, valid = correct_and_count(logits, y)
        return obj_sum, ce_sum, correct, valid, jnp.sum(maskf), new_state

    def _build_explicit_engine(self, smooth):
        """Build train_step as one jit whose body is an explicit shard_map
        over 'data': per-device partial grads -> packed flat vector ->
        per-bucket psum_scatter (sharded update) or psum (replicated
        update), in self.wire_dtype on the wire -> packed-slice optimizer
        update -> params re-assembled at the sharding boundary (monolithic
        all-gather) or kept SHARDED between steps with per-bucket
        just-in-time all-gathers in the forward (the overlapped engine,
        --comm-buckets > 1 with --dp-shard-update)."""
        from jax import lax

        from ddlbench_tpu.compat import shard_map as _shard_map
        from ddlbench_tpu.models.layers import batch_parallel
        from ddlbench_tpu.parallel.common import (bucket_slice, flat_meta,
                                                  pack_flat, psum_keepgrad,
                                                  quantize_int8,
                                                  shard_bucket_slice,
                                                  sum_safe_qmax,
                                                  unpack_buckets, unpack_flat,
                                                  vary)

        cfg = self.cfg
        model = self.model
        mesh = self.mesh
        n = mesh.devices.size
        K = cfg.grad_accum_steps
        shard_update = self.shard_update
        wire = self.wire_dtype
        opt_update = self._opt_update
        overlap = self._overlap = cfg.dp_overlap_engine()
        int8_wire = wire == jnp.dtype(jnp.int8)

        abs_params, abs_state = jax.eval_shape(
            lambda k: init_model(model, k)[:2], jax.random.key(0))
        # Layer-aligned buckets: abs_params is the per-layer params list, so
        # each layer's leaves form one alignment group and bucket boundaries
        # fall on layer boundaries — the backward finishes a bucket's
        # gradients as one contiguous stretch of layers unwinds.
        leaf_groups = [len(jax.tree.leaves(p)) for p in abs_params]
        meta = flat_meta(abs_params, n, buckets=cfg.comm_buckets,
                         leaf_groups=leaf_groups)
        self._flat_meta = meta
        self._abs_params = abs_params
        self._leaf_groups = leaf_groups
        shard_len = meta.padded // n
        elastic = self._elastic = cfg.elastic_slices
        if elastic and jax.tree.leaves(abs_state):
            raise NotImplementedError(
                "elastic_slices (world-invariant reduction order) supports "
                "stateless (non-BN) models: batch statistics computed over "
                "per-slice sub-batches cannot be made world-invariant "
                f"({model.name} carries model state)")
        qmax = sum_safe_qmax(n) if int8_wire else None
        # int8 stochastic-rounding key root: run seed + a fixed tag keeping
        # the stream disjoint from data/init keys; the step counter
        # (optimizer dict "qstep"), device index, micro-step, and bucket
        # index fold in below — fully deterministic under the run seed.
        int8_key_root = (jax.random.fold_in(jax.random.key(cfg.seed), 0x1A8)
                         if int8_wire else None)

        def reduce_grads(g, qkey=None):
            """Partial gradient pytree -> REDUCED packed flat f32 vector:
            the wire-dtype cast (int8: global-absmax scaling + stochastic
            rounding), then per-bucket psum_scatter (sharded update: each
            device keeps one contiguous 1/world slice of EACH bucket,
            concatenated — the device-major layout) or psum (replicated
            update). Each bucket's collective depends only on its own
            layers' gradients, which is the whole overlap story: the
            latency-hiding scheduler starts late buckets' wire time while
            earlier layers' backward still computes. Within a bucket the
            reduction is the same elementwise cross-device sum as the
            monolithic path, so the f32 result is bitwise-pinned."""
            gf = pack_flat(g, meta)
            if meta.num_buckets == 1 and not int8_wire:
                # the exact PR 3 monolithic program (--comm-buckets 1)
                gw = gf.astype(wire)
                if shard_update:
                    return lax.psum_scatter(gw, "data",
                                            tiled=True).astype(jnp.float32)
                return lax.psum(gw, "data").astype(jnp.float32)
            parts = []
            for b in range(meta.num_buckets):
                gb = bucket_slice(gf, meta, b)
                if int8_wire:
                    # one scale per bucket, shared across devices (pmax of
                    # the local absmaxes) — a per-device scale could not be
                    # summed on the wire
                    absmax = lax.pmax(jnp.max(jnp.abs(gb)), "data")
                    q, scale = quantize_int8(gb, jax.random.fold_in(qkey, b),
                                             qmax=qmax, absmax=absmax)
                    red = (lax.psum_scatter(q, "data", tiled=True)
                           if shard_update else lax.psum(q, "data"))
                    parts.append(red.astype(jnp.float32) * scale)
                else:
                    gw = gb.astype(wire)
                    red = (lax.psum_scatter(gw, "data", tiled=True)
                           if shard_update else lax.psum(gw, "data"))
                    parts.append(red.astype(jnp.float32))
            return parts[0] if len(parts) == 1 else jnp.concatenate(parts)

        guard = self._guard

        def gather_params(pshard):
            """Overlapped forward: one all-gather PER BUCKET, each leaf
            sliced from its bucket's gathered stretch only — the first
            forward layer depends on bucket 0's all-gather alone, so
            compute starts while late buckets are still on the wire."""
            stretches = [
                lax.all_gather(shard_bucket_slice(pshard, meta, n, b),
                               "data", tiled=True)
                for b in range(meta.num_buckets)
            ]
            return unpack_buckets(stretches, meta)

        # -- elastic world-invariant reduction (--elastic-slices E) --------
        # The canonical tree: pairwise fold over each device's E/world
        # contiguous slice partials, then a recursive-doubling butterfly
        # across devices. Both halves compose into ONE balanced binary
        # tree over the E slice partials whose shape depends on E alone —
        # the property that makes f32 trajectories bitwise across world
        # sizes (module docstring; pinned by tests/test_elastic.py).

        def _stack_fold(v):
            """Balanced pairwise fold over the leading (slice) axis of a
            stacked array — the local half of the canonical tree. The
            slice count is a power of two (validate gates E and world)."""
            while v.shape[0] > 1:
                v = v[0::2] + v[1::2]
            return v[0]

        def _butterfly(tree):
            """Recursive-doubling allreduce: after log2(world) XOR-partner
            exchange rounds every device holds the balanced-tree sum —
            with IDENTICAL bits on every device, because a + b and b + a
            round identically (IEEE addition is commutative; only
            associativity fails)."""
            r = 1
            out = tree
            while r < n:
                perm = [(d, d ^ r) for d in range(n)]
                out = jax.tree.map(
                    lambda a: a + lax.ppermute(a, "data", perm), out)
                r <<= 1
            return out

        def _replicate0(x):
            """Force replicated VMA typing on a value the butterfly already
            made device-uniform, without perturbing its bits: psum of
            (x on device 0, zeros elsewhere) — adding zeros is exact in
            any association order."""
            keep = lax.axis_index("data") == 0
            return lax.psum(jnp.where(keep, x, jnp.zeros_like(x)), "data")

        def _own_shard(vec):
            """This device's device-major shard of a full bucket-layout
            vector (the butterfly leaves the FULL reduced vector on every
            device; the optimizer wants its 1/world slice of each
            bucket)."""
            d = lax.axis_index("data")
            parts = [lax.dynamic_slice_in_dim(
                vec, meta.bucket_offsets[b] + d * (meta.bucket_padded[b]
                                                   // n),
                meta.bucket_padded[b] // n)
                for b in range(meta.num_buckets)]
            return jnp.concatenate(parts) if len(parts) > 1 else parts[0]

        def _canonical_denom(y):
            # valid-label counts are small exact integers: their psum is
            # bitwise order-free, so the loss normalizer needs no tree
            return jnp.maximum(1.0, lax.psum(
                jnp.sum((y >= 0).astype(jnp.int32)),
                "data").astype(jnp.float32))

        def elastic_grads(params, state, x, y, smul):
            """(ce, correct, valid, new_state, grad_shard) with every f32
            reduction on the canonical E-leaf tree. Per-slice programs are
            shape-identical across world sizes (each slice is global_B/E
            rows), so save@N -> resume@M replays the same bits. The slices
            run under ONE lax.scan body — program size stays O(1) in E
            instead of unrolling E/world backward passes — and the scan
            only STACKS per-slice partials; the cross-slice reduction is
            the balanced fold below, never the scan's left-to-right carry."""
            k_local = elastic // n
            b = x.shape[0] // k_local
            denom = _canonical_denom(y)
            xs = x.reshape(k_local, b, *x.shape[1:])
            ys = y.reshape(k_local, b, *y.shape[1:])

            def slice_body(st, xy):
                xk, yk = xy

                def f(p):
                    from ddlbench_tpu.ops.util import sharded_jit_tracing

                    with sharded_jit_tracing():
                        obj_sum, ce_sum, correct, valid, _norm, new_st = \
                            self._local_loss_sums(p, st, xk, yk, smooth)
                    obj = obj_sum / denom
                    if smul is not None:  # guard: loss scale / poison
                        obj = obj * smul
                    return obj, (ce_sum, correct, valid, new_st)

                (_, (ce_sum, correct, valid, new_st)), g = \
                    jax.value_and_grad(f, has_aux=True)(params)
                return new_st, (pack_flat(g, meta), ce_sum, correct, valid)

            st, (gstack, ces, corrs, valids) = lax.scan(
                slice_body, state, (xs, ys))
            g_local, ce_local = _stack_fold(gstack), _stack_fold(ces)
            g_full, ce_tot = _butterfly((g_local, ce_local))
            ce = _replicate0(ce_tot) / denom
            # int sums are exact in any order — no tree needed
            return (ce, lax.psum(jnp.sum(corrs), "data"),
                    lax.psum(jnp.sum(valids), "data"), st,
                    _own_shard(g_full))

        def local_grads(params, state, x, y, smul, qkey=None):
            """(ce, correct, valid, new_state, g_reduced): psum'd metrics
            plus the reduced flat gradient (shard or full vector).
            Non-accum partials are pre-seeded by 1/global_count (the GSPMD
            backward's seed) and reduced once. Grad accumulation reduces
            EVERY micro-gradient inside the scan — mirroring the
            replicated step, whose scan carry forces GSPMD to allreduce
            each micro-gradient (one fused multiply-add per step on the
            reduced value; bitwise parity needs the same summation order)
            — and divides the reduced sum by the total weight at the end.
            Wire-wise this still halves replicated accum's cost: K
            reduce-scatters vs K full allreduces."""
            from ddlbench_tpu.ops.util import sharded_jit_tracing

            if K == 1:
                def loss_fn(p):
                    with sharded_jit_tracing():
                        obj_sum, ce_sum, correct, valid, norm, new_state = \
                            self._local_loss_sums(p, state, x, y, smooth)
                    denom = jnp.maximum(
                        1.0, lax.psum(norm, "data").astype(jnp.float32))
                    obj = psum_keepgrad(obj_sum, "data") / denom
                    if smul is not None:  # guard: loss scale / poison
                        obj = obj * smul
                    return obj, (ce_sum, correct, valid, denom, new_state)

                (_, (ce_sum, correct, valid, denom, new_state)), g = \
                    jax.value_and_grad(loss_fn, has_aux=True)(params)
                ce = lax.psum(ce_sum, "data") / denom
                return (ce, lax.psum(correct, "data"),
                        lax.psum(valid, "data"), new_state,
                        reduce_grads(g, qkey))

            B = x.shape[0]
            assert B % K == 0, (
                f"local batch {B} not divisible by grad_accum_steps {K}")
            # Micro-step k takes every K-th local row — the same rows of
            # the global micro-batch that GSPMD keeps on this device
            # (common.accum_loss_and_grads's re-grouping, applied to the
            # local shard).
            xs = x.reshape(B // K, K, *x.shape[1:])
            ys = y.reshape(B // K, K, *y.shape[1:])

            def step(carry, k):
                st, gsum = carry
                xk = lax.dynamic_index_in_dim(xs, k, axis=1, keepdims=False)
                yk = lax.dynamic_index_in_dim(ys, k, axis=1, keepdims=False)

                def f(p):
                    with sharded_jit_tracing():
                        obj_sum, ce_sum, correct, valid, norm, new_st = \
                            self._local_loss_sums(p, st, xk, yk, smooth)
                    denom = jnp.maximum(
                        1.0, lax.psum(norm, "data").astype(jnp.float32))
                    obj = psum_keepgrad(obj_sum, "data") / denom
                    if smul is not None:
                        obj = obj * smul
                    return obj, (ce_sum, correct, valid, denom, new_st)

                (_, (ce_sum, correct, valid, denom, new_st)), g = \
                    jax.value_and_grad(f, has_aux=True)(params)
                ce_k = lax.psum(ce_sum, "data") / denom
                wk = lax.psum(valid, "data").astype(jnp.float32)
                qk = (jax.random.fold_in(qkey, k) if qkey is not None
                      else None)
                gsum = gsum + wk * reduce_grads(g, qk)
                return (new_st, gsum), (ce_k, wk, lax.psum(correct, "data"),
                                        lax.psum(valid, "data"))

            gsum0 = jnp.zeros(
                (shard_len if shard_update else meta.padded,), jnp.float32)
            if shard_update:
                # psum_scatter outputs are device-varying; the scan carry
                # must start with matching varying-axes type
                gsum0 = vary(gsum0, ("data",))
            (new_state, gsum), (ces, wks, corrs, valids) = lax.scan(
                step, (state, gsum0), jnp.arange(K))
            total = jnp.maximum(1.0, jnp.sum(wks))
            ce = jnp.sum(ces * wks) / total
            return (ce, jnp.sum(corrs), jnp.sum(valids), new_state,
                    gsum / total)

        def local_step(params, state, opt, x, y, lr):
            gstate, smul, qstep, qkey = None, None, None, None
            if int8_wire:
                # the stochastic-rounding step counter rides in the opt dict
                # (split out before the optimizer update, advanced after —
                # the same pattern as the guard's scale state); it advances
                # on skipped steps too, keeping select's tree shapes simple
                qstep = opt["qstep"]
                opt = {k: v for k, v in opt.items() if k != "qstep"}
                qkey = jax.random.fold_in(int8_key_root, qstep)
                qkey = jax.random.fold_in(qkey, lax.axis_index("data"))
            if guard is not None:
                opt, gstate = guard.split_opt(opt)
                smul = guard.smul(gstate, lr)
            if overlap:
                # params arrive as this device's flat shard: just-in-time
                # per-bucket all-gather rebuilds the pytree for the forward
                pshard = params
                params = gather_params(pshard)
            if elastic:
                # world-invariant canonical-tree path (no BN — validated
                # at build, so no batch_parallel context is needed)
                ce, correct, valid, new_state, gr = elastic_grads(
                    params, state, x, y, smul)
            else:
                with batch_parallel("data", n):
                    ce, correct, valid, new_state, gr = local_grads(
                        params, state, x, y, smul, qkey)
            if guard is not None:
                # unscale AFTER the (wire-dtype) collective — the scaled
                # values are what rides the wire — then fuse the health
                # pair: the shard's sumsq psums to the global grad norm.
                gr = gr / smul
                sumsq = jnp.sum(jnp.square(gr))
                if shard_update:
                    sumsq = lax.psum(sumsq, "data")
                finite, gnorm = guard.finite(ce, jnp.sqrt(sumsq))
            metrics = {
                "loss": ce,
                "accuracy": correct.astype(jnp.float32)
                / jnp.maximum(1.0, valid.astype(jnp.float32)),
            }
            if guard is not None:
                new_gstate = guard.scaler_update(gstate, finite)
                metrics.update(guard.metrics(finite, gnorm, new_gstate))
            if shard_update:
                if overlap:
                    # params already flat+sharded between steps: the local
                    # shard IS the optimizer's parameter slice
                    ps = pshard
                else:
                    pf = pack_flat(params, meta)
                    ps = lax.dynamic_slice_in_dim(
                        pf, lax.axis_index("data") * shard_len, shard_len)
                new_ps, new_opt = opt_update(ps, gr, opt, lr)
                if guard is not None:
                    # skip-select covers the ZeRO-1 SHARDED slices too: the
                    # untouched old slice all-gathers back, so the
                    # re-assembled params are bitwise the pre-step ones
                    new_ps, new_state, new_opt = guard.select(
                        finite, (new_ps, new_state, new_opt),
                        (ps, state, opt))
                    new_opt = guard.fold_opt(new_opt, new_gstate)
                if qstep is not None:
                    new_opt = {**new_opt, "qstep": qstep + 1}
                # Monolithic engine: out_spec P('data') on the updated slice
                # re-assembles the flat parameter vector across devices —
                # the all-gather happens at the shard_map output boundary.
                # Overlapped engine: the slice STAYS the state (out spec
                # P('data') with no host unpack) and the NEXT step's forward
                # all-gathers it per bucket, just in time.
                return new_ps, new_state, new_opt, metrics
            # compressed allreduce with the replicated update: the explicit
            # psum already ran in the wire dtype; per-leaf optimizer step.
            new_params, new_opt = opt_update(
                params, unpack_flat(gr, meta), opt, lr)
            if guard is not None:
                new_params, new_state, new_opt = guard.select(
                    finite, (new_params, new_state, new_opt),
                    (params, state, opt))
                new_opt = guard.fold_opt(new_opt, new_gstate)
            if qstep is not None:
                new_opt = {**new_opt, "qstep": qstep + 1}
            return new_params, new_state, new_opt, metrics

        flat_spec = P("data") if shard_update else P()
        flat_sh = (NamedSharding(mesh, P("data")) if shard_update
                   else self._replicated)
        opt_specs = {"m": flat_spec}
        opt_shardings = {"m": flat_sh}
        if cfg.resolved_optimizer() == "adam":
            opt_specs.update(v=flat_spec, step=P())
            opt_shardings.update(v=flat_sh, step=self._replicated)
        if int8_wire:
            # replicated int32 stochastic-rounding step counter
            opt_specs.update(qstep=P())
            opt_shardings.update(qstep=self._replicated)
        if guard is not None:
            # dynamic loss-scale state: two replicated scalars in the dict
            opt_specs = guard.opt_state_spec(opt_specs, P())
            opt_shardings = guard.opt_state_spec(opt_shardings,
                                                 self._replicated)
        self._opt_shardings = opt_shardings

        sharded = _shard_map(
            local_step,
            mesh=mesh,
            in_specs=(P("data") if overlap else P(), P(), opt_specs,
                      P("data"), P("data"), P()),
            out_specs=(P("data") if shard_update else P(), P(), opt_specs,
                       P()),
        )

        def step(ts: TrainState, x, y, lr):
            p_out, new_state, new_opt, metrics = sharded(
                ts.params, ts.model_state, ts.opt, x, y, lr)
            if shard_update and not overlap:
                p_out = unpack_flat(p_out, meta)
            # overlapped engine: p_out STAYS the flat device-major sharded
            # vector — no boundary all-gather; the next step (and eval /
            # materialize_params) gathers per bucket on demand
            return TrainState(p_out, new_state, new_opt), metrics

        param_out_sh = (NamedSharding(mesh, P("data")) if overlap
                        else self._replicated)
        jit_step = jax.jit(
            step,
            donate_argnums=(0,),
            in_shardings=(None, self._batch_sharding, self._batch_sharding,
                          None),
            out_shardings=(TrainState(param_out_sh, self._replicated,
                                      opt_shardings), None),
        )
        self._jit_train_step = jit_step  # introspection (tests, tools)
        mode = ("overlapped" if overlap
                else "sharded" if shard_update else "replicated")
        span_args = {"mode": mode, "wire": str(jnp.dtype(wire)),
                     "buckets": meta.num_buckets}
        # Exact per-bucket wire-byte schedule for the rs_bucket/ag_bucket/
        # ar_bucket marker spans: ring RS ships (n-1)/n of the (padded)
        # bucket in the wire dtype, the param AG the same fraction in f32
        # (master weights), and the replicated engine's ring ALLREDUCE
        # ships 2(n-1)/n (RS + AG halves of the same ring — matching
        # comm_stats._ring_allreduce_bytes). Host spans MARK the schedule
        # with exact byte accounting — per-bucket device time lives in the
        # --trace-dir XLA capture, where the async collectives are visible
        # interleaved with compute.
        wire_itemsize = 1 if int8_wire else jnp.dtype(wire).itemsize
        rs_scale = ((n - 1) / n if shard_update
                    else 2.0 * (n - 1) / n if n > 1 else 0.0)
        bucket_sched = [
            {"bucket": b, "offset": meta.bucket_offsets[b],
             "elems": meta.bucket_padded[b],
             "rs_wire_bytes": rs_scale * meta.bucket_padded[b]
             * wire_itemsize,
             "ag_wire_bytes": (n - 1) / n * meta.bucket_padded[b] * 4.0}
            for b in range(meta.num_buckets)
        ]
        self._bucket_schedule = bucket_sched

        def train_step(ts, x, y, lr):
            from ddlbench_tpu.telemetry import get_tracer

            tracer = get_tracer()
            if not tracer.enabled:
                return jit_step(ts, x, y, lr)
            # marks the update phase's dispatch on the host timeline;
            # device time lives in the --trace-dir XLA capture
            with tracer.span("dp_explicit_update", **span_args):
                out = jit_step(ts, x, y, lr)
                for sc in bucket_sched:
                    coll = "rs_bucket" if shard_update else "ar_bucket"
                    with tracer.span(coll, bucket=sc["bucket"],
                                     wire_bytes=sc["rs_wire_bytes"],
                                     dtype=str(jnp.dtype(wire)),
                                     offset=sc["offset"]):
                        pass
                    if shard_update:
                        with tracer.span("ag_bucket", bucket=sc["bucket"],
                                         wire_bytes=sc["ag_wire_bytes"],
                                         dtype="float32", jit=overlap):
                            pass
            return out

        self.train_step = train_step

        if elastic:
            # eval on the same canonical tree: validation losses of an
            # elastic run are world-invariant too (chaosbench's trajectory
            # check compares the per-epoch valid records bitwise)
            def elastic_eval_local(params, state, x, y):
                k_local = elastic // n
                b = x.shape[0] // k_local
                xs = x.reshape(k_local, b, *x.shape[1:])
                ys = y.reshape(k_local, b, *y.shape[1:])

                def slice_body(_, xy):
                    ce_sum, c, c5, v = self._local_eval_sums(
                        params, state, *xy)
                    return 0, (ce_sum, c, c5, v)

                _, (ces, corrs, corr5s, cnts) = lax.scan(
                    slice_body, 0, (xs, ys))
                corr = jnp.sum(corrs)
                corr5 = jnp.sum(corr5s)
                ce_tot = _replicate0(_butterfly(_stack_fold(ces)))
                count = lax.psum(jnp.sum(cnts), "data")
                return {
                    "loss": ce_tot
                    / jnp.maximum(1.0, count.astype(jnp.float32)),
                    "correct": lax.psum(corr, "data"),
                    "correct5": lax.psum(corr5, "data"),
                    "count": count,
                }

            sharded_eval = _shard_map(
                elastic_eval_local, mesh=mesh,
                in_specs=(P(), P(), P("data"), P("data")), out_specs=P())

            def elastic_eval_step(ts, x, y):
                return sharded_eval(self._params_pytree(ts), ts.model_state,
                                    x, y)

            self.eval_step = jax.jit(
                elastic_eval_step,
                in_shardings=(None, self._batch_sharding,
                              self._batch_sharding))

    def _local_eval_sums(self, params, state, x, y):
        """Per-slice eval sums (ce_sum, correct, correct5, count) —
        common.eval_metrics' computation before normalization, so the
        elastic eval can reduce them on the canonical tree."""
        from ddlbench_tpu.models.layers import apply_model
        from ddlbench_tpu.parallel.common import (cast_input, cast_params,
                                                  correct_and_count,
                                                  correct_topk,
                                                  fused_head_eval_sums)

        cfg = self.cfg
        p = cast_params(params, self.compute_dtype)
        xc = cast_input(x, self.compute_dtype)
        if cfg.fused_head_loss and self.model.layers[-1].fused_eval \
                is not None:
            return fused_head_eval_sums(self.model, p, state, xc, y)
        logits, _ = apply_model(self.model, p, state, xc, False)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        maskf = (y >= 0).astype(jnp.float32)
        safe = jnp.maximum(y, 0)
        nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
        correct, valid = correct_and_count(logits, y)
        return jnp.sum(nll * maskf), correct, correct_topk(logits, y), valid

    def flat_meta_for_world(self, world: int, buckets: int):
        """The packed flat layout this MODEL would have at another world
        size — what train/reshard.py permutes an elastic checkpoint
        through (and verifies against the recorded layout)."""
        from ddlbench_tpu.parallel.common import flat_meta

        return flat_meta(self._abs_params, world, buckets=max(1, buckets),
                         leaf_groups=self._leaf_groups)

    def init(self, key) -> TrainState:
        from ddlbench_tpu.distributed import put_global_tree

        params, state, _ = init_model(self.model, key)
        int8_wire = self._explicit and self.wire_dtype == jnp.dtype(jnp.int8)
        if self._explicit and self.shard_update:
            # ZeRO-1: optimizer state lives on the packed flat vector, one
            # contiguous [padded/world] slice per device.
            opt = self._opt_init(
                jnp.zeros((self._flat_meta.padded,), jnp.float32))
            if int8_wire:
                opt = {**opt, "qstep": jnp.zeros((), jnp.int32)}
            if self._guard is not None:
                opt = self._guard.attach_opt_state(opt)
            if self._overlap:
                # overlapped engine: params live SHARDED between steps as
                # the flat device-major vector (broadcast-init parity still
                # holds — every host computes the same seed-deterministic
                # init, each device keeps its 1/world stretch)
                from ddlbench_tpu.parallel.common import (pack_flat,
                                                          to_device_major)

                meta = self._flat_meta
                pflat = to_device_major(pack_flat(params, meta), meta,
                                        self.mesh.devices.size)
                ts = TrainState(pflat, state, opt)
                shardings = TrainState(
                    NamedSharding(self.mesh, P("data")), self._replicated,
                    self._opt_shardings)
                return put_global_tree(ts, shardings)
            ts = TrainState(params, state, opt)
            shardings = TrainState(self._replicated, self._replicated,
                                   self._opt_shardings)
            return put_global_tree(ts, shardings)
        opt = self._opt_init(params)
        if int8_wire:
            opt = {**opt, "qstep": jnp.zeros((), jnp.int32)}
        if self._guard is not None:
            opt = self._guard.attach_opt_state(opt)
        ts = TrainState(params, state, opt)
        # Broadcast-init parity (mnist_horovod.py:230-231): replicate to the
        # mesh — identical on every host since init is seed-deterministic.
        shardings = TrainState(self._replicated, self._replicated,
                               self._replicated)
        if self.cfg.shard_opt_state:
            # ZeRO-1: optimizer state sharded over 'data' (largest divisible
            # dim per leaf), params replicated. Pure placement — XLA shards
            # the update math and all-gathers only the parameter delta. With
            # adam this drops the optimizer memory 2x*params -> 2x/world.
            from ddlbench_tpu.parallel.sharded import _leaf_spec

            n = self.mesh.devices.size

            def leaf_sh(x):
                return NamedSharding(
                    self.mesh, _leaf_spec(x, "data", n, prefer_last=False))

            shardings = TrainState(
                self._replicated, self._replicated,
                jax.tree.map(leaf_sh, ts.opt))
        return put_global_tree(ts, shardings)

    def shard_batch(self, x, y):
        from ddlbench_tpu.distributed import put_global_batch

        return (
            put_global_batch(x, self._batch_sharding),
            put_global_batch(y, self._batch_sharding),
        )

    @property
    def world_size(self) -> int:
        return self.mesh.devices.size
