"""Expert parallelism (EP) — MoE experts sharded over an 'expert' mesh axis.

The reference has no MoE/EP anywhere (SURVEY.md §2E). TPU-native design:

* The mesh's one axis plays a double role, exactly as in production MoE
  systems: the **batch** is sharded over it (data parallelism) AND each MoE
  layer's stacked ``experts`` weight subtree is sharded over it on the expert
  axis (expert parallelism). Every device holds E/n experts and B/n of the
  batch.
* Inside the shard_map, models/moe.py routes each device's local tokens,
  exchanges dispatched token blocks with two ``lax.all_to_all`` collectives
  (there and back), and the expert FFN runs as one batched einsum over the
  local experts — the EP analog of the reference's per-stage communication,
  expressed as a single compiled program.
* Gradient flow falls out of shard_map's transpose: replicated (attention,
  router, embedding) parameters get their all-reduce inserted automatically;
  expert-shard gradients stay local to their device — no collective at all,
  the whole point of EP.
* The Switch router's load-balance loss is collected at trace time
  (models/moe.py collect_aux_losses) and added to the objective with weight
  ``cfg.moe_aux_weight`` — handled uniformly by AxisShardedStrategy (shared
  with sp).

Dropped tokens (beyond expert capacity) pass through residually; capacity is
static so the program has fixed shapes end to end.
"""

from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ddlbench_tpu.models.layers import init_model
from ddlbench_tpu.models.moe import expert_parallel
from ddlbench_tpu.parallel.axis_sharded import AxisShardedStrategy
from ddlbench_tpu.parallel.common import opt_state_sharding
from ddlbench_tpu.parallel.single import TrainState


def expert_param_specs(params, axis: str = "expert"):
    """PartitionSpec pytree for MoE params: leaves under an ``experts`` subtree
    shard dim 0 (the stacked expert axis) over ``axis``; all else replicated."""

    def spec(path, leaf):
        under_experts = any(
            getattr(k, "key", None) == "experts" for k in path
        )
        if under_experts and getattr(leaf, "ndim", 0) >= 1:
            return P(axis, *([None] * (leaf.ndim - 1)))
        return P()

    return jax.tree_util.tree_map_with_path(spec, params)


class EPStrategy(AxisShardedStrategy):
    """strategy='ep': batch + experts sharded over one 'expert' mesh axis."""

    axis_name = "expert"

    def _abstract_params(self):
        # cached: called from several hooks, each would re-trace the full init
        if not hasattr(self, "_p_shapes"):
            self._p_shapes = jax.eval_shape(
                lambda k: init_model(self.model, k)[0], jax.random.key(0)
            )
        return self._p_shapes

    def _check_divisibility(self, n: int) -> None:
        p_shapes = self._abstract_params()
        specs = expert_param_specs(p_shapes, self.axis_name)
        for leaf, sp in zip(
            jax.tree.leaves(p_shapes),
            jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, P)),
        ):
            if len(sp) and sp[0] == self.axis_name and leaf.shape[0] % n:
                raise ValueError(
                    f"{leaf.shape[0]} experts not divisible by {n} devices"
                )

    def _trace_contexts(self):
        return (expert_parallel(self.axis_name),)

    def _param_specs(self):
        if not hasattr(self, "_specs"):
            self._specs = expert_param_specs(
                self._abstract_params(), self.axis_name
            )
        return self._specs

    def _batch_spec(self) -> P:
        return P(self.axis_name)

    def _initial_state_sharding(self, ts: TrainState):
        param_sh = jax.tree.map(
            lambda sp: NamedSharding(self.mesh, sp),
            self._param_specs(),
            is_leaf=lambda x: isinstance(x, P),
        )
        return TrainState(
            params=param_sh,
            model_state=self._replicated,
            opt=opt_state_sharding(self.cfg, param_sh,
                                   self._replicated),
        )
