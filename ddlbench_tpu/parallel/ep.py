"""Expert parallelism (EP) — MoE experts sharded over an 'expert' mesh axis.

The reference has no MoE/EP anywhere (SURVEY.md §2E). TPU-native design:

* The mesh's one axis plays a double role, exactly as in production MoE
  systems: the **batch** is sharded over it (data parallelism) AND each MoE
  layer's stacked ``experts`` weight subtree is sharded over it on the expert
  axis (expert parallelism). Every device holds E/n experts and B/n of the
  batch.
* Inside the shard_map, models/moe.py routes each device's local tokens,
  exchanges dispatched token blocks with two ``lax.all_to_all`` collectives
  (there and back), and the expert FFN runs as one batched einsum over the
  local experts — the EP analog of the reference's per-stage communication,
  expressed as a single compiled program.
* Gradient flow falls out of shard_map's transpose: replicated (attention,
  router, embedding) parameters get their all-reduce inserted automatically;
  expert-shard gradients stay local to their device — no collective at all,
  the whole point of EP.
* The Switch router's load-balance loss is collected at trace time
  (models/moe.py collect_aux_losses) and added to the objective with weight
  ``cfg.moe_aux_weight``; both terms are globally averaged with psum.

Dropped tokens (beyond expert capacity) pass through residually; capacity is
static so the program has fixed shapes end to end.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ddlbench_tpu.config import RunConfig
from ddlbench_tpu.models.layers import LayerModel, apply_model, init_model
from ddlbench_tpu.models.moe import collect_aux_losses, expert_parallel
from ddlbench_tpu.parallel.common import (
    SGDState,
    cast_params,
    sgd_init,
    sgd_update,
)
from ddlbench_tpu.parallel.gpipe import _shard_map
from ddlbench_tpu.parallel.single import TrainState
from ddlbench_tpu.parallel.sp import _local_ce_sums


def expert_param_specs(params, axis: str = "expert"):
    """PartitionSpec pytree for MoE params: leaves under an ``experts`` subtree
    shard dim 0 (the stacked expert axis) over ``axis``; all else replicated."""

    def spec(path, leaf):
        under_experts = any(
            getattr(k, "key", None) == "experts" for k in path
        )
        if under_experts and getattr(leaf, "ndim", 0) >= 1:
            return P(axis, *([None] * (leaf.ndim - 1)))
        return P()

    return jax.tree_util.tree_map_with_path(spec, params)


class EPStrategy:
    """strategy='ep': batch + experts sharded over one 'expert' mesh axis."""

    def __init__(self, model: LayerModel, cfg: RunConfig,
                 mesh: Optional[Mesh] = None,
                 devices: Optional[Sequence[jax.Device]] = None):
        self.model = model
        self.cfg = cfg
        devs = list(devices or jax.devices())[:cfg.num_devices]
        if len(devs) < cfg.num_devices:
            raise ValueError(f"need {cfg.num_devices} devices, have {len(devs)}")
        self.mesh = mesh or Mesh(np.array(devs), axis_names=("expert",))
        self.compute_dtype = jnp.dtype(cfg.compute_dtype)
        mom = cfg.resolved_momentum()
        wd = cfg.resolved_weight_decay()
        aux_w = cfg.moe_aux_weight
        n = self.mesh.devices.size

        # Shapes/specs need one abstract init; cheap (eval_shape, no compute).
        p_shapes = jax.eval_shape(
            lambda k: init_model(model, k)[0], jax.random.key(0)
        )
        self._param_specs = expert_param_specs(p_shapes)
        for leaf, sp in zip(jax.tree.leaves(p_shapes),
                            jax.tree.leaves(self._param_specs,
                                            is_leaf=lambda x: isinstance(x, P))):
            if sp and sp[0] == "expert" and leaf.shape[0] % n:
                raise ValueError(
                    f"{leaf.shape[0]} experts not divisible by {n} devices"
                )
        self._param_sharding = jax.tree.map(
            lambda sp: NamedSharding(self.mesh, sp), self._param_specs,
            is_leaf=lambda x: isinstance(x, P),
        )
        self._replicated = NamedSharding(self.mesh, P())
        self._batch_sharding = NamedSharding(self.mesh, P("expert"))
        cdtype = self.compute_dtype

        def fwd_local(params, state, xl, yl, train: bool):
            aux: list = []
            with expert_parallel("expert"), collect_aux_losses(aux):
                logits, new_state = apply_model(
                    model, cast_params(params, cdtype), state, xl, train
                )
            nll, correct, cnt = _local_ce_sums(logits, yl)
            ce = lax.psum(nll, "expert") / lax.psum(jnp.float32(cnt), "expert")
            aux_loss = (
                lax.psum(sum(aux, jnp.float32(0.0)), "expert") / n
                if aux else jnp.float32(0.0)
            )
            correct = lax.psum(correct, "expert")
            return ce + aux_w * aux_loss, ce, correct, new_state

        def make_sharded(train: bool):
            def inner(params, state, xl, yl):
                return fwd_local(params, state, xl, yl, train)

            return _shard_map(
                inner,
                mesh=self.mesh,
                in_specs=(self._param_specs, P(), P("expert"), P("expert")),
                out_specs=(P(), P(), P(), P()),
            )

        ep_train = make_sharded(True)
        ep_eval = make_sharded(False)

        def train_step(ts: TrainState, x, y, lr):
            def loss_fn(params):
                loss, ce, correct, new_state = ep_train(params, ts.model_state, x, y)
                return loss, (ce, correct, new_state)

            (_, (ce, correct, new_state)), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(ts.params)
            params, opt = sgd_update(ts.params, grads, ts.opt, lr, mom, wd)
            metrics = {
                "loss": ce,  # headline metric stays comparable across strategies
                "accuracy": correct.astype(jnp.float32) / y.size,
            }
            return TrainState(params, new_state, opt), metrics

        def eval_step(ts: TrainState, x, y):
            _, ce, correct, _ = ep_eval(ts.params, ts.model_state, x, y)
            return {
                "loss": ce,
                "correct": correct,
                "count": jnp.asarray(y.size, jnp.int32),
            }

        self.train_step = jax.jit(
            train_step,
            donate_argnums=(0,),
            in_shardings=(None, self._batch_sharding, self._batch_sharding, None),
        )
        self.eval_step = jax.jit(
            eval_step,
            in_shardings=(None, self._batch_sharding, self._batch_sharding),
        )

    def init(self, key) -> TrainState:
        params, state, _ = init_model(self.model, key)
        params = jax.device_put(params, self._param_sharding)
        state = jax.device_put(state, self._replicated)
        opt = jax.device_put(
            sgd_init(params), SGDState(momentum=self._param_sharding)
        )
        return TrainState(params, state, opt)

    def shard_batch(self, x, y):
        return (
            jax.device_put(x, self._batch_sharding),
            jax.device_put(y, self._batch_sharding),
        )

    @property
    def world_size(self) -> int:
        return self.mesh.devices.size
