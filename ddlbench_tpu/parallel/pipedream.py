"""Asynchronous 1F1B pipeline with weight stashing — the reference's PipeDream
engine, TPU-native.

Reference mechanism (pipedream-fork/): StageRuntime owns a stage and helper
threads stream tensors between ranks (runtime/runtime.py, communication.py);
the 1F1B loop runs num_warmup forwards then steady-state
[forward; load_old_params; backward; load_new_params; step]
(image_classification/main_with_runtime.py:432-494); weight stashing keeps
num_versions = warmup+1 clones so backward uses the same weights as that
minibatch's forward (runtime/optimizer.py:58-116); replicated stages are
DDP-wrapped per stage (runtime.py:232-263).

TPU-native design — the whole async schedule is ONE compiled XLA program,
written once over C = S*V model chunks (V = cfg.virtual_stages; the classic
schedule is the V = 1 degenerate case):

* Global clock of H = 2M + 2C - 2 half-ticks; at each half-tick every chunk
  does one forward, one backward, or idles, per the closed-form 1F1B
  timetable
      F(c, f) = c + f + max(0, f - W_c)         W_c = C - 1 - c warmup count
      B(c, b) = 2b + 2C - 1 - c
  (derived from the reference's warmup/steady/drain loop). Chunk c = v*S + s
  lives on device s; a device executes its V chunk-events sequentially
  within the tick. Forward activations ring-ppermute right; gradients left;
  wrap transfers (device S-1 -> 0 fwd, 0 -> S-1 bwd) roll the chunk-slot
  axis; a per-chunk 2-slot queue absorbs the one half-tick of skew between
  activation arrival and use.
* Weight stashing: each chunk carries its packed parameter vector plus a
  [min(C,M), L] stash ring; forward f writes the vector it used into slot
  f mod NSLOT, backward b reads slot b mod NSLOT — so backward grads are
  taken at exactly the forward-time weights (OptimizerWithWeightStashing
  parity, but functional).
* Backward is recompute-based: we stash the stage *input* (not the autograd
  graph) and take jax.vjp of the stage at the stashed (weights, input). This
  trades the reference's activation-stash memory for recompute FLOPs — the
  TPU-friendly choice, and BN batch statistics are bit-identical on recompute.
* The per-microbatch update runs right after each backward (update_interval=1
  semantics); for replicated stages the gradient is psum'd over the 'data'
  mesh axis first (the DDP-per-stage allreduce).
* The reference's helper threads, CV queues, tensor tags, round-robin
  messaging schedule, and gcd/LCM iteration fixes (communication.py:455-521,
  runtime.py:663-690) have no analog: XLA's static schedule replaces all of
  them, and each data-replica column exchanges only with its own column.

Eval reuses the synchronous fill-drain pipeline from GPipeStrategy.
"""

from __future__ import annotations

import math
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ddlbench_tpu.parallel.common import (correct_and_count,
                                          cross_entropy_loss)
from ddlbench_tpu.parallel.gpipe import GPipeStrategy, _shard_map, _vary
from ddlbench_tpu.parallel.packing import pad_vec


class PDTrainState(NamedTuple):
    params: jax.Array  # [S, L] newest weights per stage
    model_state: jax.Array  # [S, Ls] BN running stats
    # optimizer-state dict pytree (common.make_optimizer), leaves [S, X]
    opt: Any


def fwd_mb_at(s: int, S: int, M: int, h):
    """Microbatch index whose forward stage s runs at half-tick h (and validity).

    Timetable (warmup W_s = S-1-s forwards, then one forward per backward):
        F(s, f) = s + f          for f <= W_s   (fill)
        F(s, f) = s + 2f         for f >  W_s   (steady 1F1B; parity s)
        B(s, b) = 2b + 2S-1 - s                 (parity s+1 — never collides)
    """
    W = S - 1 - s
    f_w = h - s
    in_warm = (f_w >= 0) & (f_w <= W) & (f_w < M)
    two_f = h - s
    f_s = two_f // 2
    in_steady = (two_f % 2 == 0) & (f_s > W) & (f_s < M)
    f = jnp.where(in_warm, f_w, f_s)
    return jnp.clip(f, 0, M - 1), in_warm | in_steady


def bwd_mb_at(s: int, S: int, M: int, h):
    two_b = h - (2 * S - 1 - s)
    b = two_b // 2
    valid = (two_b >= 0) & (two_b % 2 == 0) & (b < M)
    return jnp.clip(b, 0, M - 1), valid


class PipeDreamStrategy(GPipeStrategy):
    """strategy='pipedream': async 1F1B + weight stashing over the stage mesh.

    With ``virtual_stages`` V > 1 (interleaved 1F1B — the flagship schedule
    of modern pipeline systems, beyond the reference): each device owns V
    model chunks (chunk c = v*S + s on device s, the gpipe interleaved
    layout) and runs the C = S*V-chunk uniform 1F1B timetable, executing its
    V chunk-events sequentially within each half-tick. Because the C-chunk
    timetable never consumes a same-tick output, co-locating chunks preserves
    the event semantics EXACTLY — the compiled interleaved program matches
    the sequential event-replay simulator run with C stages. Every chunk
    boundary is a device boundary (+1 ring shift); wrap transfers
    (device S-1 -> 0 forward, 0 -> S-1 backward) roll the chunk-slot axis.
    """

    # -- train step --------------------------------------------------------

    def _ts_sharding(self):
        sh = self._stage_sharding
        return PDTrainState(sh, sh, sh)

    def init(self, key) -> PDTrainState:
        ts = super().init(key)
        return PDTrainState(ts.params, ts.model_state, ts.opt)

    def _make_stage_fwd(self, s: int):
        """Shared with the schedule runtime — parallel/pipeline_rt.py
        make_stage_fwd (the vjp-friendly chunk forward both engines'
        recompute-based backwards take vjps of)."""
        from ddlbench_tpu.parallel.pipeline_rt import make_stage_fwd

        return make_stage_fwd(self, s)

    def _make_stage_fwd_fused(self, s: int):
        """Shared with the schedule runtime — parallel/pipeline_rt.py
        make_stage_fwd_fused (fused projection+CE last-chunk variant)."""
        from ddlbench_tpu.parallel.pipeline_rt import make_stage_fwd_fused

        return make_stage_fwd_fused(self, s)

    def _make_train_step(self):
        """Async 1F1B over C = S*V chunks, V per device (class docstring).

        Per half-tick every device runs its V chunk-events sequentially
        (fwd and/or bwd per chunk, per the C-chunk closed-form timetable),
        then one ring ppermute each way moves the [V, A] activation /
        gradient slot buffers; wrap transfers roll the slot axis on the
        receiving edge device. Stash rings, the absorb queue, the optimizer
        state and the macrobatch accumulator all gain a leading V axis.
        """
        S, M, mb = self.num_stages, self.num_microbatches, self.mb
        V = self.vstages
        C = S * V
        H = 2 * M + 2 * C - 2
        NSLOT = min(C, M)
        K = max(1, self.cfg.update_interval)
        opt_update = self._opt_update
        smooth = self.cfg.resolved_label_smoothing()
        aux_w = self.cfg.moe_aux_weight
        mesh = self.mesh
        cdtype = self.compute_dtype
        ring_f = [(i, (i + 1) % S) for i in range(S)] if S > 1 else []
        ring_b = [((i + 1) % S, i) for i in range(S)] if S > 1 else []
        stage_fwds = [self._make_stage_fwd(c) for c in range(C)]
        in_shapes = [self.shapes[self.bounds[c]] for c in range(C)]
        in_sizes = [mb * math.prod(sh) for sh in in_shapes]
        # interior chunk boundaries only (chunk 0's raw input is re-read
        # from xs, never stashed or ring-transferred)
        A = max(in_sizes[1:]) if C > 1 else 1
        fused_last = self._make_stage_fwd_fused(C - 1)

        def make_branch(c: int):
            """Chunk-c event body; same shape-contract as the V=1 branches
            but operating on row v = c // S of the [V, ...] carries."""
            stage_fwd = stage_fwds[c]
            fused_fwd = fused_last if c == C - 1 else None
            if self.cfg.remat_stages:
                stage_fwd = jax.checkpoint(stage_fwd)
                if fused_fwd is not None:
                    fused_fwd = jax.checkpoint(fused_fwd)
            in_shape, in_size = in_shapes[c], in_sizes[c]
            last = c == C - 1

            def unpack_x(buf):
                return buf[:in_size].reshape(mb, *in_shape)

            def branch(carry, xs, ys, h, lr):
                (params, opt_row, g_acc, st_row, stash_p, stash_x,
                 fwd_q, g_in, y_out, gx_out, loss_acc, corr_acc) = carry

                f, valid_f = fwd_mb_at(c, C, M, h)
                b, valid_b = bwd_mb_at(c, C, M, h)

                def do_fwd(op):
                    params, st_row, stash_p, stash_x = op
                    if c == 0:
                        x = lax.dynamic_index_in_dim(xs, f, keepdims=False)
                    else:
                        x = unpack_x(lax.dynamic_index_in_dim(
                            fwd_q, f % 2, keepdims=False))
                    if last and fused_fwd is not None:
                        labels = lax.dynamic_index_in_dim(ys, f,
                                                          keepdims=False)
                        _obj, ce_sum, corr_mb, new_st, _aux = fused_fwd(
                            params, st_row, x, labels)
                        loss_mb = ce_sum / jnp.maximum(
                            1.0, jnp.sum((labels >= 0).astype(jnp.float32)))
                        y_new = jnp.zeros((A,), cdtype)
                    else:
                        y, new_st, _aux = stage_fwd(params, st_row, x)
                        if last:
                            labels = lax.dynamic_index_in_dim(
                                ys, f, keepdims=False)
                            loss_mb = cross_entropy_loss(y, labels)
                            corr_mb = correct_and_count(y, labels)[0]
                            y_new = jnp.zeros((A,), cdtype)
                        else:
                            loss_mb = jnp.zeros((), jnp.float32)
                            corr_mb = jnp.zeros((), jnp.int32)
                            y_new = pad_vec(y.astype(cdtype), A)
                    slot = f % NSLOT
                    stash_p = lax.dynamic_update_index_in_dim(
                        stash_p, params, slot, 0)
                    if c != 0:
                        stash_x = lax.dynamic_update_index_in_dim(
                            stash_x, pad_vec(x.astype(cdtype), A), slot, 0)
                    return jax.tree.map(
                        _vary,
                        (new_st, stash_p, stash_x, y_new, loss_mb, corr_mb))

                def skip_fwd(op):
                    params, st_row, stash_p, stash_x = op
                    return jax.tree.map(
                        _vary,
                        (st_row, stash_p, stash_x, jnp.zeros((A,), cdtype),
                         jnp.zeros((), jnp.float32),
                         jnp.zeros((), jnp.int32)))

                st_row, stash_p, stash_x, y_new, loss_mb, corr_mb = lax.cond(
                    valid_f, do_fwd, skip_fwd,
                    (params, st_row, stash_p, stash_x))
                loss_acc = loss_acc + loss_mb
                corr_acc = corr_acc + corr_mb

                def do_bwd(op):
                    params, opt_row, g_acc, st_row, stash_p, stash_x = op
                    slot = b % NSLOT
                    p_st = lax.dynamic_index_in_dim(stash_p, slot,
                                                    keepdims=False)
                    if c == 0:
                        x_st = lax.dynamic_index_in_dim(xs, b, keepdims=False)
                    else:
                        x_st = unpack_x(lax.dynamic_index_in_dim(
                            stash_x, slot, keepdims=False))
                    if last:
                        labels = lax.dynamic_index_in_dim(ys, b,
                                                          keepdims=False)
                        if fused_fwd is not None:
                            denom = jnp.maximum(
                                1.0,
                                jnp.sum((labels >= 0).astype(jnp.float32)))

                            def loss_of(pv, xv):
                                obj_sum, _, _, _, aux = fused_fwd(
                                    pv, st_row, xv, labels)
                                return obj_sum / denom + aux_w * aux
                        else:
                            def loss_of(pv, xv):
                                y, _, aux = stage_fwd(pv, st_row, xv)
                                return (cross_entropy_loss(y, labels, smooth)
                                        + aux_w * aux)

                        if c == 0:
                            gp = jax.grad(lambda pv: loss_of(pv, x_st))(p_st)
                            gx = None
                        else:
                            gp, gx = jax.grad(loss_of, argnums=(0, 1))(
                                p_st, x_st)
                    else:
                        def fwd_of(pv, xv):
                            y, _, aux = stage_fwd(pv, st_row, xv)
                            return y, aux

                        out_shape = self.shapes[self.bounds[c + 1]]
                        out_size = mb * math.prod(out_shape)
                        g_cot = g_in[:out_size].reshape(mb, *out_shape)
                        if c == 0:
                            (y, aux), vjp_fn = jax.vjp(
                                lambda pv: fwd_of(pv, x_st), p_st)
                            (gp,) = vjp_fn((g_cot.astype(y.dtype),
                                            jnp.float32(aux_w)))
                            gx = None
                        else:
                            (y, aux), vjp_fn = jax.vjp(fwd_of, p_st, x_st)
                            gp, gx = vjp_fn((g_cot.astype(y.dtype),
                                             jnp.float32(aux_w)))
                    gp = lax.psum(gp, "data")
                    gx_new = (jnp.zeros((A,), cdtype) if gx is None
                              else pad_vec(gx.astype(cdtype), A))
                    if K == 1:
                        new_params, new_opt = opt_update(
                            params, gp.astype(jnp.float32), opt_row, lr)
                        return jax.tree.map(
                            _vary, (new_params, new_opt, g_acc, gx_new))
                    g_acc = g_acc + gp.astype(jnp.float32)

                    def step(op):
                        params, opt_row, g_acc = op
                        new_params, new_opt = opt_update(
                            params, g_acc / K, opt_row, lr)
                        return jax.tree.map(
                            _vary,
                            (new_params, new_opt, jnp.zeros_like(g_acc)))

                    def hold(op):
                        return jax.tree.map(_vary, op)

                    params, opt_row, g_acc = lax.cond(
                        (b + 1) % K == 0, step, hold,
                        (params, opt_row, g_acc))
                    return jax.tree.map(
                        _vary, (params, opt_row, g_acc, gx_new))

                def skip_bwd(op):
                    params, opt_row, g_acc, st_row, stash_p, stash_x = op
                    return jax.tree.map(
                        _vary, (params, opt_row, g_acc,
                                jnp.zeros((A,), cdtype)))

                params, opt_row, g_acc, gx_new = lax.cond(
                    valid_b, do_bwd, skip_bwd,
                    (params, opt_row, g_acc, st_row, stash_p, stash_x))

                out = (params, opt_row, g_acc, st_row, stash_p, stash_x,
                       fwd_q, g_in, y_new, gx_new, loss_acc, corr_acc)
                return jax.tree.map(_vary, out)

            return branch

        # branches grouped per chunk-row: branches_v[v][s] is chunk v*S+s
        branches_v = [[make_branch(v * S + s) for s in range(S)]
                      for v in range(V)]

        def inner(params_rows, state_rows, opt_rows, xs, ys, lr):
            # local views -> [V, X] chunk rows: V=1 state is [1, L]
            # (P('stage', None), already the [V, L] layout); V>1 is
            # [V, 1, L] (P(None, 'stage', None))
            if V == 1:
                params = _vary(params_rows)
                st = _vary(state_rows)
                opt = jax.tree.map(_vary, opt_rows)
            else:
                params = _vary(params_rows[:, 0])
                st = _vary(state_rows[:, 0])
                opt = jax.tree.map(lambda a: _vary(a[:, 0]), opt_rows)
            xs = _vary(xs)
            ys = _vary(ys)
            s_idx = lax.axis_index("stage")
            L = params.shape[1]
            GL = L if K > 1 else 1

            def body(carry, h):
                (params, opt, g_acc, st, stash_p, stash_x, fwd_q,
                 x_in, g_in, loss_acc, corr_acc) = carry

                # absorb arrivals into each chunk-row's 2-slot queue, keyed
                # by the producing chunk's schedule at h-1
                for v in range(V):
                    def absorb(s, v=v):
                        cprev = v * S + s - 1
                        if cprev < 0:
                            return (jnp.zeros((), jnp.int32),
                                    jnp.zeros((), jnp.bool_))
                        return fwd_mb_at(cprev, C, M, h - 1)

                    f_in, valid_in = lax.switch(
                        s_idx,
                        [(lambda s=s, v=v: jax.tree.map(_vary, absorb(s, v)))
                         for s in range(S)])
                    q_upd = lax.dynamic_update_index_in_dim(
                        fwd_q[v], x_in[v], f_in % 2, 0)
                    fwd_q = fwd_q.at[v].set(
                        jnp.where(valid_in, q_upd, fwd_q[v]))

                y_out = _vary(jnp.zeros((V, A), cdtype))
                gx_out = _vary(jnp.zeros((V, A), cdtype))
                for v in range(V):
                    carry_v = (params[v],
                               jax.tree.map(lambda a: a[v], opt),
                               g_acc[v], st[v], stash_p[v], stash_x[v],
                               fwd_q[v], g_in[v],
                               _vary(jnp.zeros((A,), cdtype)),
                               _vary(jnp.zeros((A,), cdtype)),
                               loss_acc, corr_acc)
                    (p_v, o_v, ga_v, st_v, sp_v, sx_v, _q, _gi, y_v, gx_v,
                     loss_acc, corr_acc) = lax.switch(
                        s_idx, branches_v[v], carry_v, xs, ys, h, lr)
                    params = params.at[v].set(p_v)
                    opt = jax.tree.map(lambda a, n, v=v: a.at[v].set(n),
                                       opt, o_v)
                    g_acc = g_acc.at[v].set(ga_v)
                    st = st.at[v].set(st_v)
                    stash_p = stash_p.at[v].set(sp_v)
                    stash_x = stash_x.at[v].set(sx_v)
                    y_out = y_out.at[v].set(y_v)
                    gx_out = gx_out.at[v].set(gx_v)

                if ring_f:
                    x_in = lax.ppermute(y_out, "stage", ring_f)
                    g_next = lax.ppermute(gx_out, "stage", ring_b)
                else:
                    x_in, g_next = y_out, gx_out
                # wrap transfers change the chunk-row: device 0's arrivals
                # from S-1 serve chunk (v+1)*S, i.e. slot v+1 (roll +1, the
                # rolled-in slot 0 is last-chunk zeros); device S-1's
                # gradient arrivals from 0 serve chunk v*S + S-1, slot v-1
                x_in = jnp.where(s_idx == 0, jnp.roll(x_in, 1, axis=0), x_in)
                g_next = jnp.where(s_idx == S - 1,
                                   jnp.roll(g_next, -1, axis=0), g_next)
                out = (params, opt, g_acc, st, stash_p, stash_x, fwd_q,
                       x_in, g_next, loss_acc, corr_acc)
                return jax.tree.map(_vary, out), None

            init_carry = jax.tree.map(_vary, (
                params, opt,
                jnp.zeros((V, GL), jnp.float32),
                st,
                jnp.zeros((V, NSLOT, L), jnp.float32),
                jnp.zeros((V, NSLOT, A), cdtype),
                jnp.zeros((V, 2, A), cdtype),
                jnp.zeros((V, A), cdtype),
                jnp.zeros((V, A), cdtype),
                jnp.zeros((), jnp.float32),
                jnp.zeros((), jnp.int32),
            ))
            (params, opt, _ga, st, *_rest, loss_acc, corr_acc) = lax.scan(
                body, init_carry, jnp.arange(H))[0]
            loss = lax.pmean(lax.psum(loss_acc, "stage") / M, "data")
            correct = lax.psum(lax.psum(corr_acc, "stage"), "data")
            st = lax.pmean(st, "data")
            params = lax.pmean(params, "data")
            opt = jax.tree.map(
                lambda a: (lax.pmax(a, "data")
                           if jnp.issubdtype(a.dtype, jnp.integer)
                           else lax.pmean(a, "data")),
                opt)
            if V == 1:
                return params, st, opt, loss, correct
            return (params[:, None], st[:, None],
                    jax.tree.map(lambda a: a[:, None], opt), loss, correct)

        spec = self._chunk_sharding_spec()  # stage rows (V=1) / chunk rows
        pipe = _shard_map(
            inner,
            mesh=mesh,
            in_specs=(spec, spec, spec, P(None, "data"), P(None, "data"),
                      P()),
            out_specs=(spec, spec, spec, P(), P()),
        )

        guard = self._guard

        def train_step(ts: PDTrainState, xs, ys, lr):
            params, st, opt, loss, correct = pipe(
                ts.params, ts.model_state, ts.opt, xs, ys, lr)
            valid = jnp.sum((ys >= 0).astype(jnp.float32))
            metrics = {
                "loss": loss,
                "accuracy": correct.astype(jnp.float32)
                / jnp.maximum(1.0, valid),
            }
            if guard is not None:
                # Stability guard, pipedream flavor: gradients are consumed
                # by per-microbatch updates inside the compiled schedule, so
                # the fused health pair is taken from the post-step
                # parameter DELTA — any NaN/Inf gradient (incl. a nan-grad
                # fault's NaN lr) poisons some update and therefore the
                # delta; the reported "grad_norm" is the update norm
                # ||params_new - params_old|| (documented deviation).
                delta_sq = jnp.sum(jnp.square(
                    (params - ts.params).astype(jnp.float32)))
                finite, gnorm = guard.finite(loss, jnp.sqrt(delta_sq))
                params, st, opt = guard.select(
                    finite, (params, st, opt),
                    (ts.params, ts.model_state, ts.opt))
                metrics.update(guard.metrics(finite, gnorm))
            return PDTrainState(params, st, opt), metrics

        return jax.jit(
            train_step,
            donate_argnums=(0,),
            in_shardings=(self._ts_sharding(), self._batch_sharding,
                          self._batch_sharding, None),
        )
