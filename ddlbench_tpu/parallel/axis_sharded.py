"""Shared scaffolding for the shard_map-based one-axis strategies (sp, ep).

Both sequence parallelism and expert parallelism are the same program shape:
a 1-D mesh, a trace-time context that switches the model into the sharded
execution mode, a shard_map'd forward computing psum-reduced (loss, ce,
correct), value_and_grad around it (shard_map's transpose inserts the
gradient collectives), and the shared SGD update. Subclasses provide only
what actually differs: the axis name, the trace contexts, the param/batch
partition specs, and the initial placement.
"""

from __future__ import annotations

import contextlib
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ddlbench_tpu.config import RunConfig
from ddlbench_tpu.models.layers import LayerModel, apply_model, init_model
from ddlbench_tpu.models.moe import collect_aux_losses
from ddlbench_tpu.parallel.common import (cast_params, correct_topk,
                                          make_optimizer)
from ddlbench_tpu.parallel.gpipe import _shard_map
from ddlbench_tpu.parallel.single import TrainState


def _local_ce_sums(logits, labels, smoothing: float = 0.0):
    """(sum of token NLL, sum of correct, valid count) over the local shard.

    Positions with labels < 0 are ignored (seq2seq masking convention);
    ``smoothing`` applies GNMT-style label smoothing to the NLL sum.
    """
    logits = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    mask = (labels >= 0)
    maskf = mask.astype(jnp.float32)
    safe = jnp.maximum(labels, 0)
    nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    if smoothing:
        nll = (1.0 - smoothing) * nll - smoothing * jnp.mean(logp, axis=-1)
    correct = jnp.sum(((jnp.argmax(logits, -1) == labels) & mask).astype(jnp.int32))
    return jnp.sum(nll * maskf), correct, jnp.sum(maskf)


class AxisShardedStrategy:
    """Base for strategies that shard over ONE named mesh axis via shard_map."""

    axis_name: str

    def __init__(self, model: LayerModel, cfg: RunConfig,
                 mesh: Optional[Mesh] = None,
                 devices: Optional[Sequence[jax.Device]] = None):
        from ddlbench_tpu.guard import device_guard

        self.model = model
        self.cfg = cfg
        devs = list(devices or jax.devices())[:cfg.num_devices]
        if len(devs) < cfg.num_devices:
            raise ValueError(f"need {cfg.num_devices} devices, have {len(devs)}")
        self.mesh = mesh or Mesh(np.array(devs), axis_names=(self.axis_name,))
        self.compute_dtype = jnp.dtype(cfg.compute_dtype)
        self._opt_init, opt_update = make_optimizer(cfg)
        aux_w = cfg.moe_aux_weight
        n = self.mesh.devices.size
        axis = self.axis_name
        self._check_divisibility(n)
        guard = self._guard = device_guard(cfg)  # None = pre-guard program

        self._replicated = NamedSharding(self.mesh, P())
        self._batch_sharding = NamedSharding(self.mesh, self._batch_spec())
        cdtype = self.compute_dtype

        smooth = cfg.resolved_label_smoothing()

        def fwd_local(params, state, xl, yl, train: bool):
            from ddlbench_tpu.parallel.common import (fused_head_eval_sums,
                                                      fused_head_loss_sums,
                                                      head_fusable)

            aux: list = []
            fusable = cfg.fused_head_loss and head_fusable(model)
            use_fused = train and fusable
            use_fused_eval = ((not train) and fusable
                              and model.layers[-1].fused_eval is not None)
            correct5_local = jnp.zeros((), jnp.int32)
            with contextlib.ExitStack() as stack:
                for ctx in self._trace_contexts():
                    stack.enter_context(ctx)
                stack.enter_context(collect_aux_losses(aux))
                if use_fused:
                    # fused projection+CE per shard: local SUMS, psum'd below
                    # exactly like the unfused path's
                    obj_nll, ce_nll, correct, cnt, new_state = (
                        fused_head_loss_sums(
                            model, cast_params(params, cdtype), state, xl, yl,
                            smooth))
                    cnt = cnt.astype(jnp.float32)
                elif use_fused_eval:
                    ce_nll, correct, correct5_local, cnt = (
                        fused_head_eval_sums(
                            model, cast_params(params, cdtype), state, xl, yl))
                    obj_nll = ce_nll
                    cnt = cnt.astype(jnp.float32)
                    new_state = state
                else:
                    logits, new_state = apply_model(
                        model, cast_params(params, cdtype), state, xl, train
                    )
            if not (use_fused or use_fused_eval):
                # training objective may be label-smoothed; the reported ce is not
                obj_nll, correct, cnt = _local_ce_sums(
                    logits, yl, smooth if train else 0.0)
                ce_nll = _local_ce_sums(logits, yl)[0] if (train and smooth) else obj_nll
                if not train:
                    correct5_local = correct_topk(logits, yl)
            count = lax.psum(jnp.float32(cnt), axis)
            obj = lax.psum(obj_nll, axis) / count
            ce = lax.psum(ce_nll, axis) / count
            # MoE router load-balance term, averaged over the axis shards
            # (empty list for dense models).
            aux_loss = lax.psum(sum(aux, jnp.float32(0.0)), axis) / n
            loss = obj + aux_w * aux_loss
            correct = lax.psum(correct, axis)
            # prec@5 is an eval-only metric; train_step discards it, so skip
            # the top-k compute (and its psum) on the hot path
            correct5 = (jnp.zeros((), jnp.int32) if train
                        else lax.psum(correct5_local, axis))
            return loss, ce, correct, correct5, count, new_state

        def make_sharded(train: bool):
            # Guard objective multiplier (loss scale x nan-grad poison
            # carrier): applied INSIDE the shard_map, same reasoning as
            # tpp's pipe fn — an outside-seeded scaled cotangent can fail
            # the axis replication checks; in-shard, the extra P() input is
            # replicated by construction. Unarmed traces take no extra arg
            # and compile the exact pre-guard program.
            guarded = train and guard is not None

            def inner(params, state, xl, yl, *guard_args):
                out = fwd_local(params, state, xl, yl, train)
                if guarded:
                    loss, *rest = out
                    out = (loss * guard_args[0], *rest)
                return out

            in_specs = (self._param_specs(), P(), self._batch_spec(),
                        self._batch_spec())
            if guarded:
                in_specs = in_specs + (P(),)
            return _shard_map(
                inner,
                mesh=self.mesh,
                in_specs=in_specs,
                out_specs=(P(), P(), P(), P(), P(), P()),
            )

        fn_train = make_sharded(True)
        fn_eval = make_sharded(False)

        def train_step(ts: TrainState, x, y, lr):
            # Stability guard (ROADMAP item 4): sp/ep grad THROUGH the
            # shard_map like tpp, so the wiring mirrors tpp's train step.
            gstate, smul, opt_in = None, None, ts.opt
            if guard is not None:
                opt_in, gstate = guard.split_opt(ts.opt)
                smul = guard.smul(gstate, lr)

            def loss_fn(params):
                args = (smul,) if smul is not None else ()
                loss, ce, correct, _c5, count, new_state = fn_train(
                    params, ts.model_state, x, y, *args)
                return loss, (ce, correct, count, new_state)

            (_, (ce, correct, count, new_state)), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(ts.params)
            gm = None
            if guard is not None:
                grads = guard.unscale(grads, smul)
                finite, gnorm = guard.health(ce, grads)
            params, opt = opt_update(ts.params, grads, opt_in, lr)
            if guard is not None:
                params, new_state, opt, gm = guard.commit(
                    finite, gnorm, gstate, (params, new_state, opt),
                    (ts.params, ts.model_state, opt_in))
            metrics = {
                "loss": ce,  # headline metric stays comparable across strategies
                "accuracy": correct.astype(jnp.float32) / jnp.maximum(1.0, count),
            }
            if gm is not None:
                metrics.update(gm)
            return TrainState(params, new_state, opt), metrics

        def eval_step(ts: TrainState, x, y):
            _, ce, correct, correct5, count, _ = fn_eval(
                ts.params, ts.model_state, x, y)
            return {
                "loss": ce,
                "correct": correct,
                "correct5": correct5,
                "count": count.astype(jnp.int32),
            }

        self.train_step = jax.jit(
            train_step,
            donate_argnums=(0,),
            in_shardings=(None, self._batch_sharding, self._batch_sharding, None),
        )
        self.eval_step = jax.jit(
            eval_step,
            in_shardings=(None, self._batch_sharding, self._batch_sharding),
        )

    # ---- subclass hooks -------------------------------------------------

    def _check_divisibility(self, n: int) -> None:
        """Raise if the model/config cannot be split n ways on this axis."""

    def _trace_contexts(self):
        """Context managers entered around the model apply (e.g. the
        sequence_parallel / expert_parallel markers)."""
        return ()

    def _param_specs(self):
        """PartitionSpec (pytree or prefix) for parameters inside shard_map."""
        return P()

    def _batch_spec(self) -> P:
        """PartitionSpec for the (x, y) batch arrays."""
        raise NotImplementedError

    def _initial_state_sharding(self, ts: TrainState):
        """Shardings for device_put of the freshly initialized TrainState."""
        return self._replicated

    # ---- uniform interface ---------------------------------------------

    def init(self, key) -> TrainState:
        from ddlbench_tpu.distributed import put_global_tree

        params, state, _ = init_model(self.model, key)
        opt = self._opt_init(params)
        if self._guard is not None:
            opt = self._guard.attach_opt_state(opt)  # dynamic loss scale
        ts = TrainState(params, state, opt)
        sharding = self._initial_state_sharding(ts)
        if self._guard is not None and isinstance(sharding, TrainState):
            # per-leaf sharding trees (ep) must mirror the guard opt entry
            sharding = TrainState(
                sharding.params, sharding.model_state,
                self._guard.opt_state_spec(sharding.opt, self._replicated))
        return put_global_tree(ts, sharding)

    def shard_batch(self, x, y):
        from ddlbench_tpu.distributed import put_global_batch

        return (
            put_global_batch(x, self._batch_sharding),
            put_global_batch(y, self._batch_sharding),
        )

    @property
    def world_size(self) -> int:
        return self.mesh.devices.size
