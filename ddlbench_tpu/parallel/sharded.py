"""Tensor-parallel and FSDP/ZeRO strategies via sharding annotations.

Neither exists in the reference (SURVEY.md §2E marks TP and FSDP/ZeRO absent,
with TP "recommended — cheap under XLA SPMD"). Under XLA both modes are the
same program as data parallelism with different *placement annotations*; the
SPMD partitioner derives the collectives:

* `tp` (strategy='tp'): parameters sharded on their output-feature axis over a
  'model' mesh axis, batch replicated. XLA partitions every matmul/conv
  channel-wise and inserts the activation all-reduces — Megatron-style tensor
  parallelism without a single explicit collective in user code.
* `fsdp` (strategy='fsdp'): batch sharded over 'data' AND every parameter
  sharded over the same axis (largest divisible dimension). XLA all-gathers
  each layer's weights on use and reduce-scatters gradients — ZeRO-3
  semantics, weights live sharded in HBM.

Both reuse the single-device train-step math; only init/sharding differ.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ddlbench_tpu.config import RunConfig
from ddlbench_tpu.models.layers import LayerModel, init_model
from ddlbench_tpu.parallel.common import make_optimizer, opt_state_sharding
from ddlbench_tpu.parallel.single import TrainState


def _leaf_spec(x: jax.Array, axis: str, size: int, prefer_last: bool) -> P:
    """Choose one divisible dimension to shard (None spec if nothing fits)."""
    if not hasattr(x, "shape") or x.ndim == 0:
        return P()
    dims = range(x.ndim - 1, -1, -1) if prefer_last else range(x.ndim)
    best = None
    for d in dims:
        if x.shape[d] % size == 0 and x.shape[d] >= size:
            if prefer_last:
                best = d
                break
            if best is None or x.shape[d] > x.shape[best]:
                best = d
    if best is None:
        return P()
    spec = [None] * x.ndim
    spec[best] = axis
    return P(*spec)


class _ShardedParamStrategy:
    """Shared machinery: single-step math + per-leaf parameter shardings."""

    axis_name: str
    batch_sharded: bool
    prefer_last: bool

    def __init__(self, model: LayerModel, cfg: RunConfig,
                 devices: Optional[Sequence[jax.Device]] = None):
        from ddlbench_tpu.distributed import make_mesh
        from ddlbench_tpu.guard import device_guard

        self.model = model
        self.cfg = cfg
        self.mesh = make_mesh([(self.axis_name, cfg.num_devices)],
                              devices=devices,
                              dcn_axis=self.axis_name if self.batch_sharded else None)
        self.compute_dtype = jnp.dtype(cfg.compute_dtype)
        self._opt_init, opt_update = make_optimizer(cfg)
        n = self.mesh.devices.size
        guard = self._guard = device_guard(cfg)  # None = pre-guard program

        if self.batch_sharded:
            self._batch_sharding = NamedSharding(self.mesh, P(self.axis_name))
        else:
            self._batch_sharding = NamedSharding(self.mesh, P())

        smooth = cfg.resolved_label_smoothing()

        def train_step(ts: TrainState, x, y, lr):
            from ddlbench_tpu.ops.util import sharded_jit_tracing
            from ddlbench_tpu.parallel.common import loss_and_grads

            # Stability guard (ROADMAP item 4): tp/fsdp run the SAME
            # one-jit step shape as single/dp-GSPMD, so the guard wires in
            # identically — scaled objective, fused (finite, grad_norm)
            # health pair on the metrics path, anomalous updates dropped
            # in-step under skip / dynamic scaling. GSPMD keeps the
            # skip-select elementwise, so sharded params stay sharded.
            gstate, smul, opt_in = None, None, ts.opt
            if guard is not None:
                opt_in, gstate = guard.split_opt(ts.opt)
                smul = guard.smul(gstate, lr)
            with sharded_jit_tracing():  # auto-Pallas unsafe under GSPMD
                ce, (correct, valid), new_state, grads = loss_and_grads(
                    model, cfg, ts.params, ts.model_state, x, y,
                    self.compute_dtype, smooth, obj_scale=smul)
            gm = None
            if guard is not None:
                grads = guard.unscale(grads, smul)
                finite, gnorm = guard.health(ce, grads)
            params, opt = opt_update(ts.params, grads, opt_in, lr)
            if guard is not None:
                params, new_state, opt, gm = guard.commit(
                    finite, gnorm, gstate, (params, new_state, opt),
                    (ts.params, ts.model_state, opt_in))
            metrics = {
                "loss": ce,
                "accuracy": correct.astype(jnp.float32)
                / jnp.maximum(1.0, valid.astype(jnp.float32)),
            }
            if gm is not None:
                metrics.update(gm)
            return TrainState(params, new_state, opt), metrics

        def eval_step(ts: TrainState, x, y):
            from ddlbench_tpu.ops.util import sharded_jit_tracing
            from ddlbench_tpu.parallel.common import eval_metrics

            with sharded_jit_tracing():
                return eval_metrics(model, cfg, ts.params, ts.model_state,
                                    x, y, self.compute_dtype)

        self.train_step = jax.jit(
            train_step,
            donate_argnums=(0,),
            in_shardings=(None, self._batch_sharding, self._batch_sharding, None),
        )
        self.eval_step = jax.jit(
            eval_step,
            in_shardings=(None, self._batch_sharding, self._batch_sharding),
        )

    def _state_sharding(self, ts: TrainState):
        n = self.mesh.devices.size

        def leaf_sh(x):
            return NamedSharding(
                self.mesh, _leaf_spec(x, self.axis_name, n, self.prefer_last)
            )

        param_sh = jax.tree.map(leaf_sh, ts.params)
        opt_sh = opt_state_sharding(self.cfg, param_sh,
                                    NamedSharding(self.mesh, P()))
        if self._guard is not None:
            # dynamic loss-scale state: two replicated scalars in the dict
            opt_sh = self._guard.opt_state_spec(
                opt_sh, NamedSharding(self.mesh, P()))
        return TrainState(
            params=param_sh,
            model_state=jax.tree.map(
                lambda x: NamedSharding(self.mesh, P()), ts.model_state
            ),
            opt=opt_sh,
        )

    def init(self, key) -> TrainState:
        from ddlbench_tpu.distributed import put_global_tree

        params, state, _ = init_model(self.model, key)
        opt = self._opt_init(params)
        if self._guard is not None:
            opt = self._guard.attach_opt_state(opt)  # dynamic loss scale
        ts = TrainState(params, state, opt)
        return put_global_tree(ts, self._state_sharding(ts))

    def shard_batch(self, x, y):
        from ddlbench_tpu.distributed import put_global_batch

        return (
            put_global_batch(x, self._batch_sharding),
            put_global_batch(y, self._batch_sharding),
        )

    @property
    def world_size(self) -> int:
        return self.mesh.devices.size


class TPStrategy(_ShardedParamStrategy):
    """strategy='tp': Megatron-style tensor parallelism from annotations."""

    axis_name = "model"
    batch_sharded = False
    prefer_last = True


class FSDPStrategy(_ShardedParamStrategy):
    """strategy='fsdp': ZeRO-3 — batch and parameters sharded on 'data'."""

    axis_name = "data"
    batch_sharded = True
    prefer_last = False
