"""Synchronous micro-batch pipeline parallelism — the reference's GPipe engine,
TPU-native.

Reference mechanism (benchmark/mnist/mnist_gpipe.py): flatten the model to
nn.Sequential, `balance_by_time` auto-partitions (:215-217), `GPipe(model,
balance, chunks=MICROBATCHES)` (:219) runs a clock-cycle schedule moving
micro-batch j through partition k with per-stage CUDA streams, stash/pop skip
connections across partitions, synchronous flush at the step end.

TPU-native design — the whole schedule is ONE compiled XLA program:

* mesh axes ``('data', 'stage')``; stage s's parameters live on its mesh row as
  a row of a packed ``[S, L]`` matrix (parallel/packing.py);
* `lax.scan` over the M + S - 1 clock ticks; each tick every device runs its
  stage via `lax.switch` and hands its activation to the right neighbor with
  `lax.ppermute` — the TPU analog of the reference's stream copies
  (SURVEY.md §3.4);
* the backward pipeline is not hand-written: `jax.grad` through the
  scan+ppermute forward yields the reversed schedule automatically (ppermute
  transposes to the opposite permutation), and `jax.checkpoint` on each stage
  reproduces torchgpipe's per-(microbatch, stage) activation checkpointing;
* hybrid PPxDP comes from the 'data' mesh axis: batches shard across it and
  shard_map's transpose machinery inserts the gradient all-reduce over ICI.

There is no stash/pop skip machinery: residual blocks are pipeline-atomic
layers (models/layers.py), so skips never cross a stage boundary.
"""

from __future__ import annotations

import math
from typing import Any, List, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.flatten_util import ravel_pytree
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Version-adaptive shard_map (ddlbench_tpu/compat.py); every strategy
# imports the one symbol so the policy cannot drift.
from ddlbench_tpu.compat import shard_map as _shard_map

from ddlbench_tpu.config import RunConfig
from ddlbench_tpu.models.layers import LayerModel, apply_slice, init_model
from ddlbench_tpu.parallel.common import (
    cast_input, cast_params, correct_and_count, correct_topk,
    cross_entropy_loss)
from ddlbench_tpu.parallel.packing import (
    balanced_stage_bounds,
    layer_flop_costs,
    pack_stages,
    pad_vec,
)


from ddlbench_tpu.parallel.common import vary as _vary_axes

_PIPE_AXES = ("data", "stage")


def _vary(v, axes=_PIPE_AXES):
    return _vary_axes(v, axes)


class PipeTrainState(NamedTuple):
    # V = cfg.virtual_stages model chunks per device; layouts:
    #   V=1: [S, L] f32, P('stage', None)        (row s = stage s)
    #   V>1: [V, S, L] f32, P(None, 'stage', None) (row [v, s] = chunk v*S+s)
    params: jax.Array
    model_state: jax.Array  # [S, Ls] / [V, S, Ls], same sharding as params
    # optimizer-state dict pytree (common.make_optimizer): m/v leaves mirror
    # params; the adam step counter is shaped [..., 1] per stage row so every
    # leaf shares the params' stage sharding
    opt: Any


def make_pipe_mesh(num_stages: int, dp_replicas: int,
                   devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    from ddlbench_tpu.distributed import make_mesh

    # 'stage' transfers are bandwidth-hungry: keep them on ICI; the 'data'
    # replica axis may span hosts over DCN.
    return make_mesh(
        [("data", dp_replicas), ("stage", num_stages)],
        devices=devices,
        dcn_axis="data",
    )


class GPipeStrategy:
    """strategy='gpipe': synchronous micro-batch pipeline over a 'stage' mesh axis."""

    def __init__(self, model: LayerModel, cfg: RunConfig,
                 mesh: Optional[Mesh] = None,
                 devices: Optional[Sequence[jax.Device]] = None,
                 stage_bounds: Optional[List[int]] = None):
        self.model = model
        self.cfg = cfg
        self.num_stages = cfg.resolved_stages()
        self.dp = max(1, cfg.dp_replicas)
        # Interleaved schedule (Megatron-style virtual stages): each device
        # owns V model chunks, chunk c = v*S + s living on device s. The
        # synchronous-pipeline bubble shrinks from (S-1) stage-times to
        # (S-1)/V; chunk handoffs become a ring rotation (every boundary is a
        # device boundary). V=1 is the classic schedule.
        self.vstages = max(1, getattr(cfg, "virtual_stages", 1))
        self.num_chunks = self.num_stages * self.vstages
        # Hybrid PP x ZeRO-1 (--dp-shard-update on gpipe): stage parameter
        # rows + optimizer state live SHARDED across the pipe mesh's
        # 'data' axis between steps (device-major bucketed flat layout,
        # parallel/common.py row_flat_meta); the forward all-gathers each
        # bucket just-in-time and the backward reduce-scatters per bucket
        # — optimizer bytes/chip drop /dp, the grad wire halves vs the
        # replicated pmean, and late buckets overlap the drain.
        # Elastic resume (train/reshard.py) reads pipe_shard/_row_meta/dp
        # off this strategy to reshard a checkpoint's rows between dp
        # replica counts (same stage split) — keep those names stable.
        self.pipe_shard = cfg.pipe_shard_engine()
        self.mesh = mesh or make_pipe_mesh(self.num_stages, self.dp, devices)
        self.compute_dtype = jnp.dtype(cfg.compute_dtype)
        self.mb, self.num_microbatches = cfg.resolved_batches()
        self._stage_bounds_override = stage_bounds
        self._built = False
        from ddlbench_tpu.guard import device_guard
        from ddlbench_tpu.parallel.common import make_optimizer

        self._opt_init, self._opt_update = make_optimizer(cfg)
        self._guard = device_guard(cfg)  # None = pre-guard program

    # -- initialization ----------------------------------------------------

    def _chunk_sharding_spec(self) -> P:
        # V=1: [S, L] rows over 'stage'; V>1: [V, S, L] middle axis over it.
        return P("stage", None) if self.vstages == 1 else P(None, "stage", None)

    def _param_spec(self) -> P:
        """Params (and optimizer m/v): the chunk spec, plus — hybrid
        PP x ZeRO-1 — the flat row axis sharded over 'data'."""
        if not self.pipe_shard:
            return self._chunk_sharding_spec()
        return (P("stage", "data") if self.vstages == 1
                else P(None, "stage", "data"))

    def init(self, key) -> PipeTrainState:
        params_list, state_list, shapes = init_model(self.model, key)
        S, V, C = self.num_stages, self.vstages, self.num_chunks
        bounds = getattr(self, "bounds", None)
        if bounds is None:
            if self._stage_bounds_override is not None:
                bounds = list(self._stage_bounds_override)
            else:
                costs = layer_flop_costs(params_list, shapes,
                                          self.model.layers)
                bounds = balanced_stage_bounds(costs, C)
            assert len(bounds) == C + 1 and bounds[0] == 0 and bounds[-1] == len(self.model.layers)
            self.bounds = bounds
            self.shapes = shapes

        params_mat, p_unravels, p_lens = pack_stages(
            [params_list[bounds[c]:bounds[c + 1]] for c in range(C)]
        )
        state_mat, s_unravels, s_lens = pack_stages(
            [state_list[bounds[c]:bounds[c + 1]] for c in range(C)]
        )
        if V > 1:
            # row c = v*S + s -> [v, s] (device s holds its V chunk rows)
            params_mat = params_mat.reshape(V, S, -1)
            state_mat = state_mat.reshape(V, S, -1)

        if self.pipe_shard and not self._built:
            from ddlbench_tpu.parallel.common import (device_major_perm,
                                                      row_flat_meta)

            self._row_meta = row_flat_meta(
                int(params_mat.shape[-1]), self.dp,
                max(1, self.cfg.comm_buckets))
            perm, inv = device_major_perm(self._row_meta, self.dp)
            self._row_perm = jnp.asarray(perm)
            self._row_inv = jnp.asarray(inv)

        if not self._built:
            self._p_unravels, self._p_lens = p_unravels, p_lens
            self._s_unravels, self._s_lens = s_unravels, s_lens
            # Per-device activation buffer: the largest activation crossing a
            # chunk boundary for one microbatch (per data replica). With V>1
            # every chunk boundary is a device boundary.
            interior = [
                self.mb * math.prod(shapes[bounds[c]]) for c in range(1, C)
            ]
            self._act_size = max(interior) if interior else 1
            self._build_steps()

        from ddlbench_tpu.distributed import put_global_batch

        if self.pipe_shard:
            # device-major bucketed relayout of every row, then the 'data'
            # axis shards each device's contiguous 1/dp stretch (the same
            # layout the per-bucket psum_scatter outputs produce — see
            # parallel/common.py to_device_major)
            pad = self._row_meta.padded - params_mat.shape[-1]
            params_mat = jnp.pad(
                params_mat,
                [(0, 0)] * (params_mat.ndim - 1) + [(0, pad)])
            params_mat = jnp.take(params_mat, self._row_perm, axis=-1)

        sharding = NamedSharding(self.mesh, self._chunk_sharding_spec())
        psharding = NamedSharding(self.mesh, self._param_spec())
        params_mat = put_global_batch(params_mat, psharding)
        state_mat = put_global_batch(state_mat, sharding)
        opt = self._opt_init(params_mat,
                             step_like=params_mat.shape[:-1] + (1,))
        if "step" in opt:
            opt = {**opt, "step": put_global_batch(opt["step"], sharding)}
        if self._guard is not None:
            opt = self._guard.attach_opt_state(opt)  # dynamic loss scale
        return PipeTrainState(params_mat, state_mat, opt)

    # -- stage branch construction ----------------------------------------

    def _make_branch(self, c: int, train: bool):
        """Branch for lax.switch: identical signature across chunks.

        ``c`` is the model-chunk index (= stage for V=1; c = v*S + s on
        device s for the interleaved schedule). ``m`` — the microbatch this
        chunk processes this tick — is computed by the caller's timetable.
        """
        C, M, mb, A = self.num_chunks, self.num_microbatches, self.mb, self._act_size
        layers = self.model.layers[self.bounds[c]:self.bounds[c + 1]]
        in_shape = self.shapes[self.bounds[c]]
        p_unravel, p_len = self._p_unravels[c], self._p_lens[c]
        s_unravel, s_len = self._s_unravels[c], self._s_lens[c]
        cdtype = self.compute_dtype
        num_classes = self.model.num_classes
        last = c == C - 1

        smooth = self.cfg.resolved_label_smoothing() if train else 0.0
        from ddlbench_tpu.models.moe import collect_aux_losses

        # Fused projection+CE on the loss stage: the [mb*T, vocab] logits
        # never materialize (ops/fused_xent.py); the eval twin also covers
        # the prec@5 metric.
        head = self.model.layers[-1]
        use_fused = (train and last and self.cfg.fused_head_loss
                     and head.fused_loss is not None)
        use_fused_eval = ((not train) and last and self.cfg.fused_head_loss
                          and head.fused_eval is not None)

        def branch(param_row, state_row, x_buf, xs, ys, m):
            if c == 0:
                x = lax.dynamic_index_in_dim(xs, m, keepdims=False)
            else:
                x = x_buf[: mb * math.prod(in_shape)].reshape(mb, *in_shape)
            params = cast_params(p_unravel(param_row[:p_len]), cdtype)
            states = s_unravel(state_row[:s_len])
            # MoE router load-balance terms of THIS stage's layers are traced
            # into the branch, accumulated in the scan, and added to the
            # objective in _make_pipe_fn (empty for dense models).
            aux: list = []
            if use_fused:
                from ddlbench_tpu.parallel.common import fused_slice_loss_sums

                labels = lax.dynamic_index_in_dim(ys, m, keepdims=False)
                with collect_aux_losses(aux):
                    obj_sum, ce_sum, correct, new_states = (
                        fused_slice_loss_sums(layers, params, states,
                                              cast_input(x, cdtype), labels,
                                              smooth))
                aux_mb = sum(aux, jnp.float32(0.0))
                denom = jnp.maximum(
                    1.0, jnp.sum((labels >= 0).astype(jnp.float32)))
                ce = ce_sum / denom
                loss = obj_sum / denom
                correct5 = jnp.zeros((), jnp.int32)  # train path: discarded
                y_out = jnp.zeros((A,), cdtype)
                new_state_row = pad_vec(
                    ravel_pytree(new_states)[0].astype(jnp.float32),
                    state_row.shape[0],
                )
                return (_vary(y_out), _vary(new_state_row), _vary(loss),
                        _vary(ce), _vary(aux_mb), _vary(correct),
                        _vary(correct5))
            if use_fused_eval:
                from ddlbench_tpu.parallel.common import fused_slice_eval_sums

                labels = lax.dynamic_index_in_dim(ys, m, keepdims=False)
                with collect_aux_losses(aux):
                    ce_sum, correct, correct5, valid = fused_slice_eval_sums(
                        layers, params, states, cast_input(x, cdtype), labels)
                aux_mb = sum(aux, jnp.float32(0.0))
                denom = jnp.maximum(1.0, valid.astype(jnp.float32))
                ce = loss = ce_sum / denom
                return (_vary(jnp.zeros((A,), cdtype)), _vary(state_row),
                        _vary(loss), _vary(ce), _vary(aux_mb), _vary(correct),
                        _vary(correct5))
            with collect_aux_losses(aux):
                y, new_states = apply_slice(layers, params, states,
                                            cast_input(x, cdtype), train)
            aux_mb = sum(aux, jnp.float32(0.0))
            if last:
                labels = lax.dynamic_index_in_dim(ys, m, keepdims=False)
                # loss (the grad path) may be label-smoothed; ce is the
                # reported headline metric, comparable across strategies.
                ce = cross_entropy_loss(y, labels)
                loss = cross_entropy_loss(y, labels, smooth) if smooth else ce
                correct = correct_and_count(y, labels)[0]
                # prec@5 is eval-only; keep the (remat'd) train branch free of
                # the top-k compute — train_step discards it anyway
                correct5 = (jnp.zeros((), jnp.int32) if train
                            else correct_topk(y, labels))
                y_out = jnp.zeros((A,), cdtype)
            else:
                loss = jnp.zeros((), jnp.float32)
                ce = jnp.zeros((), jnp.float32)
                correct = jnp.zeros((), jnp.int32)
                correct5 = jnp.zeros((), jnp.int32)
                y_out = pad_vec(y.astype(cdtype), A)
            new_state_row = pad_vec(
                ravel_pytree(new_states)[0].astype(jnp.float32),
                state_row.shape[0],
            )
            # Constant-valued outputs (zeros) carry no varying-axes annotation;
            # normalize every output's VMA type so lax.switch branches agree.
            return (_vary(y_out), _vary(new_state_row), _vary(loss),
                    _vary(ce), _vary(aux_mb), _vary(correct), _vary(correct5))

        if train and self.cfg.remat_stages:
            branch = jax.checkpoint(branch)
        return branch

    # -- compiled steps ----------------------------------------------------

    def _build_steps(self):
        self._stage_sharding = NamedSharding(self.mesh, self._chunk_sharding_spec())
        self._param_sharding = NamedSharding(self.mesh, self._param_spec())
        self._batch_sharding = NamedSharding(self.mesh, P(None, "data"))
        self._materialize = None  # built lazily (hybrid engine only)
        self.train_step = self._make_train_step()
        self.eval_step = self._make_eval_step()
        self._built = True

    def materialize_params(self, ts: "PipeTrainState"):
        """The plain packed [.., S, L] stage-parameter matrix, replicated
        over 'data' — what host-side consumers (activation logging, tests,
        tools) read. Identity for the replicated engine; the hybrid
        PP x ZeRO-1 engine's between-steps params are the device-major
        padded sharded rows, so this inverts the relayout and drops the
        pad (one jitted gather)."""
        if not self.pipe_shard:
            return ts.params
        if self._materialize is None:
            inv, L = self._row_inv, self._row_meta.length

            def plain(p):
                return jnp.take(p, inv, axis=-1)[..., :L]

            self._materialize = jax.jit(
                plain, out_shardings=self._stage_sharding)
        return self._materialize(ts.params)

    def _make_pipe_fn(self, train: bool):
        """Synchronous fill-drain pipeline fwd (gpipe train fwd and all eval).

        The schedule is DATA (partition/schedule.py fill_drain_timetable):
        chunk c = v*S + s (on device s) runs microbatch m = g*S + r at tick
        t = g*S*V + v*S + s + r — conflict-free and dependency-correct
        (chunk c+1 runs exactly one tick after chunk c, so the handoff is
        always a one-step ring rotation, wrapping S-1 -> 0 between chunk
        groups). The scan body reads its (v, m, valid) triple from the
        table's forward_tick_arrays — the schedule-programmable runtime's
        autodiff mode (parallel/pipeline_rt.py module docstring); the
        backward half of the timetable is jax.grad through this scan,
        inheriting the same schedule reversed. Fill/drain cost is S-1
        CHUNK times instead of the classic (S-1) stage times — the
        interleaved-schedule bubble reduction — at the price of C-1
        rotations per microbatch. Requires M % S == 0 when V > 1.
        """
        S, M, A = self.num_stages, self.num_microbatches, self._act_size
        V, C = self.vstages, self.num_chunks
        mesh = self.mesh
        aux_w = self.cfg.moe_aux_weight if train else 0.0
        branches = [self._make_branch(c, train) for c in range(C)]
        if V == 1:
            perm = [(i, i + 1) for i in range(S - 1)]
        else:
            perm = [(i, (i + 1) % S) for i in range(S)] if S > 1 else []
        from ddlbench_tpu.partition.schedule import fill_drain_timetable

        tt = fill_drain_timetable(S, M, V)
        if train:
            # the TRAIN schedule drives --trace pipe_tick markers; eval
            # always runs fill-drain, and pipedream (async 1F1B train, no
            # static half-tick table) must not inherit this one
            self.timetable = tt
        tv_np, tm_np, tvalid_np = tt.forward_tick_arrays()
        t_v, t_m, t_valid = (jnp.asarray(tv_np), jnp.asarray(tm_np),
                             jnp.asarray(tvalid_np))
        gather_rows = self._make_gather_rows()

        def inner(params_rows, state_rows, xs, ys):
            # params_rows local: [1, L] (V=1) or [V, 1, L]; xs [M, mb, ...]
            # (hybrid PP x ZeRO-1: [1|V, 1?, L/dp] device-major shards,
            # rebuilt to full rows by the per-bucket just-in-time
            # all-gather below — whose TRANSPOSE under jax.grad is the
            # per-bucket psum_scatter that shards the gradients).
            # Mark everything varying over both mesh axes up front so all
            # switch branches produce identical VMA types; the pcast on
            # params transposes to the gradient psum over 'data' (the DP
            # all-reduce) in the backward pass.
            if V == 1:
                param_rows = _vary(params_rows)  # [1, L]
                state_rows = _vary(state_rows)
            else:
                param_rows = _vary(params_rows[:, 0])  # [V, L]
                state_rows = _vary(state_rows[:, 0])
            if gather_rows is not None:
                param_rows = _vary(gather_rows(param_rows))
            xs = _vary(xs)
            ys = _vary(ys)
            s_idx = lax.axis_index("stage")
            T = M * V + S - 1

            def body(carry, t):
                (x_buf, st_rows, loss_acc, ce_acc, aux_acc, corr_acc,
                 corr5_acc) = carry
                v = t_v[t, s_idx]
                valid = t_valid[t, s_idx]
                m = t_m[t, s_idx]
                chunk = v * S + s_idx
                param_row = lax.dynamic_index_in_dim(param_rows, v,
                                                     keepdims=False)
                st_row = lax.dynamic_index_in_dim(st_rows, v, keepdims=False)
                (y_buf, new_st, loss_mb, ce_mb, aux_mb, corr_mb,
                 corr5_mb) = lax.switch(
                    chunk, branches, param_row, st_row, x_buf, xs, ys, m
                )
                st_upd = lax.dynamic_update_index_in_dim(st_rows, new_st, v, 0)
                st_rows = jnp.where(valid, st_upd, st_rows)
                loss_acc = loss_acc + jnp.where(valid, loss_mb, 0.0)
                ce_acc = ce_acc + jnp.where(valid, ce_mb, 0.0)
                aux_acc = aux_acc + jnp.where(valid, aux_mb, 0.0)
                corr_acc = corr_acc + jnp.where(valid, corr_mb, 0)
                corr5_acc = corr5_acc + jnp.where(valid, corr5_mb, 0)
                if perm:
                    x_next = lax.ppermute(y_buf, "stage", perm)
                else:
                    x_next = y_buf
                return (x_next, st_rows, loss_acc, ce_acc, aux_acc, corr_acc,
                        corr5_acc), None

            init_carry = (
                _vary(jnp.zeros((A,), self.compute_dtype)),
                state_rows,
                _vary(jnp.zeros((), jnp.float32)),
                _vary(jnp.zeros((), jnp.float32)),
                _vary(jnp.zeros((), jnp.float32)),
                _vary(jnp.zeros((), jnp.int32)),
                _vary(jnp.zeros((), jnp.int32)),
            )
            (x_buf, st_rows, loss_acc, ce_acc, aux_acc, corr_acc,
             corr5_acc), _ = lax.scan(body, init_carry, jnp.arange(T))
            # Loss lives on the last chunk only; the MoE router aux terms live
            # on whichever chunks hold MoE layers — psum both and fold the
            # weighted aux into the training objective (dp-strategy parity;
            # the reported ce stays the bare metric).
            ce = lax.pmean(lax.psum(ce_acc, "stage") / M, "data")
            aux = lax.pmean(lax.psum(aux_acc, "stage") / M, "data")
            loss = lax.pmean(lax.psum(loss_acc, "stage") / M, "data")
            loss = loss + aux_w * aux
            correct = lax.psum(lax.psum(corr_acc, "stage"), "data")
            correct5 = lax.psum(lax.psum(corr5_acc, "stage"), "data")
            # Sync BN running stats across data replicas (sync-BN choice,
            # documented deviation — SURVEY.md §7).
            st_rows = lax.pmean(st_rows, "data")
            st_out = st_rows if V == 1 else st_rows[:, None]
            return loss, ce, st_out, correct, correct5

        spec = self._chunk_sharding_spec()
        return _shard_map(
            inner,
            mesh=mesh,
            in_specs=(self._param_spec(), spec, P(None, "data"),
                      P(None, "data")),
            out_specs=(P(), P(), spec, P(), P()),
        )

    def _make_gather_rows(self):
        """Hybrid PP x ZeRO-1: per-bucket just-in-time all-gather of the
        local [V?, L/dp] device-major param-row shards back to full plain
        rows, inside the shard_map. Each bucket rides its OWN all-gather
        so the first chunks' compute starts while late buckets are still
        on the wire; under jax.grad each gather transposes to that
        bucket's reduce-scatter, which is where the sharded gradients
        come from in autodiff mode. None when the engine is replicated."""
        if not self.pipe_shard:
            return None
        meta, dp = self._row_meta, self.dp

        def gather_rows(rows):  # [V?, L/dp] -> [V?, L_pad]
            parts = []
            for b in range(meta.num_buckets):
                o = meta.bucket_offsets[b] // dp
                ln = meta.bucket_padded[b] // dp
                parts.append(lax.all_gather(
                    rows[:, o:o + ln], "data", axis=1, tiled=True))
            return (jnp.concatenate(parts, axis=1) if len(parts) > 1
                    else parts[0])

        return gather_rows

    @property
    def _total_samples(self) -> int:
        return self.num_microbatches * self.mb * self.dp

    def _ts_sharding(self):
        sh = self._stage_sharding
        psh = self._param_sharding
        opt_sh = psh
        if self.pipe_shard or (self._guard is not None
                               and self._guard.dynamic):
            # hybrid: m/v ride the params' 'data'-sharded rows while the
            # adam step counter ([.., 1] per stage row) stays on the chunk
            # sharding; dynamic loss-scale scalars additionally break the
            # one-sharding-for-the-whole-subtree shorthand
            from ddlbench_tpu.parallel.common import opt_state_sharding

            opt_sh = opt_state_sharding(self.cfg, psh, sh)
            if self._guard is not None and self._guard.dynamic:
                opt_sh = self._guard.opt_state_spec(
                    opt_sh, NamedSharding(self.mesh, P()))
        return PipeTrainState(psh, sh, opt_sh)

    def _make_train_step(self):
        pipe_train = self._make_pipe_fn(train=True)
        guard = self._guard

        def train_step(ts: PipeTrainState, xs, ys, lr):
            gstate, smul, opt_in = None, None, ts.opt
            if guard is not None:
                opt_in, gstate = guard.split_opt(ts.opt)
                smul = guard.smul(gstate, lr)

            def loss_fn(params_mat):
                loss, ce, new_state, correct, _c5 = pipe_train(
                    params_mat, ts.model_state, xs, ys)
                if smul is not None:  # guard: loss scale / poison carrier
                    loss = loss * smul
                return loss, (ce, new_state, correct)

            (_, (ce, new_state, correct)), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(ts.params)
            gm = None
            if guard is not None:
                grads = guard.unscale(grads, smul)
                finite, gnorm = guard.health(ce, grads)
            params, opt = self._opt_update(ts.params, grads, opt_in, lr)
            if guard is not None:
                params, new_state, opt, gm = guard.commit(
                    finite, gnorm, gstate, (params, new_state, opt),
                    (ts.params, ts.model_state, opt_in))
            # valid label positions (samples, or unmasked tokens for LM /
            # seq2seq workloads)
            valid = jnp.sum((ys >= 0).astype(jnp.float32))
            metrics = {
                "loss": ce,
                "accuracy": correct.astype(jnp.float32) / jnp.maximum(1.0, valid),
            }
            if gm is not None:
                metrics.update(gm)
            return PipeTrainState(params, new_state, opt), metrics

        return jax.jit(
            train_step,
            donate_argnums=(0,),
            in_shardings=(self._ts_sharding(), self._batch_sharding,
                          self._batch_sharding, None),
        )

    def _make_eval_step(self):
        pipe_eval = self._make_pipe_fn(train=False)

        def eval_step(ts, xs, ys):
            loss, _, _, correct, correct5 = pipe_eval(
                ts.params, ts.model_state, xs, ys)
            return {
                "loss": loss,
                "correct": correct,
                "correct5": correct5,
                "count": jnp.sum((ys >= 0).astype(jnp.int32)),
            }

        return jax.jit(
            eval_step,
            in_shardings=(self._ts_sharding(), self._batch_sharding,
                          self._batch_sharding),
        )

    # -- data placement ----------------------------------------------------

    def shard_batch(self, x, y):
        """Global batch [M*mb*dp, ...] -> [M, mb*dp, ...] sharded over 'data'."""
        from ddlbench_tpu.distributed import put_global_batch

        M, mb, dp = self.num_microbatches, self.mb, self.dp
        x = x.reshape(M, dp * mb, *x.shape[1:])
        y = y.reshape(M, dp * mb, *y.shape[1:])
        return (
            put_global_batch(x, self._batch_sharding),
            put_global_batch(y, self._batch_sharding),
        )

    @property
    def world_size(self) -> int:
        return self.mesh.devices.size
