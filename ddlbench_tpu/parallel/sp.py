"""Sequence (context) parallelism with ring attention — a first-class 5th mode.

The reference has no sequence parallelism; its closest analog is the "highres"
512x512 dataset used as an activation-memory stressor (SURVEY.md §5.7). On TPU
the real axis is sequence length: strategy='sp' shards the token dimension of a
transformer across a 'seq' mesh axis, so each chip holds T/n of every
activation; attention runs the ring algorithm (models/transformer.py
ring_attention) rotating K/V blocks over ICI neighbor links with an
online-softmax accumulator, and every pointwise layer (LN, MLP, embeddings,
head, loss) is trivially local. Parameters are replicated; shard_map's
transpose inserts the gradient all-reduce, exactly as in DP.

All step scaffolding lives in AxisShardedStrategy (shared with ep).
"""

from __future__ import annotations

from jax.sharding import PartitionSpec as P

from ddlbench_tpu.models.transformer import sequence_parallel
from ddlbench_tpu.parallel.axis_sharded import AxisShardedStrategy, _local_ce_sums

__all__ = ["SPStrategy", "_local_ce_sums"]


class SPStrategy(AxisShardedStrategy):
    """strategy='sp': activations sharded on the sequence axis, ring attention."""

    axis_name = "seq"

    def _check_divisibility(self, n: int) -> None:
        T = self.model.in_shape[0]
        if T % n:
            raise ValueError(f"sequence length {T} not divisible by {n} devices")

    def _trace_contexts(self):
        return (sequence_parallel(self.axis_name),)

    def _batch_spec(self) -> P:
        return P(None, self.axis_name)
