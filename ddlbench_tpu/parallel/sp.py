"""Sequence (context) parallelism with ring attention — a first-class 5th mode.

The reference has no sequence parallelism; its closest analog is the "highres"
512x512 dataset used as an activation-memory stressor (SURVEY.md §5.7). On TPU
the real axis is sequence length: strategy='sp' shards the token dimension of a
transformer across a 'seq' mesh axis, so each chip holds T/n of every
activation; attention runs the ring algorithm (models/transformer.py
ring_attention) rotating K/V blocks over ICI neighbor links with an
online-softmax accumulator, and every pointwise layer (LN, MLP, embeddings,
head, loss) is trivially local. Parameters are replicated; shard_map's
transpose inserts the gradient all-reduce, exactly as in DP.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ddlbench_tpu.config import RunConfig
from ddlbench_tpu.models.layers import LayerModel, apply_model, init_model
from ddlbench_tpu.models.transformer import sequence_parallel
from ddlbench_tpu.parallel.common import (
    cast_params,
    sgd_init,
    sgd_update,
)
from ddlbench_tpu.parallel.gpipe import _shard_map
from ddlbench_tpu.parallel.single import TrainState


def _local_ce_sums(logits, labels):
    """(sum of token NLL, sum of correct, count) over the local shard."""
    logits = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    correct = jnp.sum((jnp.argmax(logits, -1) == labels).astype(jnp.int32))
    return -jnp.sum(ll), correct, labels.size


class SPStrategy:
    """strategy='sp': activations sharded on the sequence axis, ring attention."""

    def __init__(self, model: LayerModel, cfg: RunConfig,
                 mesh: Optional[Mesh] = None,
                 devices: Optional[Sequence[jax.Device]] = None):
        self.model = model
        self.cfg = cfg
        devs = list(devices or jax.devices())[:cfg.num_devices]
        if len(devs) < cfg.num_devices:
            raise ValueError(f"need {cfg.num_devices} devices, have {len(devs)}")
        self.mesh = mesh or Mesh(np.array(devs), axis_names=("seq",))
        self.compute_dtype = jnp.dtype(cfg.compute_dtype)
        mom = cfg.resolved_momentum()
        wd = cfg.resolved_weight_decay()
        n = self.mesh.devices.size
        T = model.in_shape[0]
        if T % n:
            raise ValueError(f"sequence length {T} not divisible by {n} devices")

        self._replicated = NamedSharding(self.mesh, P())
        self._batch_sharding = NamedSharding(self.mesh, P(None, "seq"))
        cdtype = self.compute_dtype

        def fwd_local(params, state, xl, yl, train: bool):
            from ddlbench_tpu.models.moe import collect_aux_losses

            aux: list = []
            with sequence_parallel("seq"), collect_aux_losses(aux):
                logits, new_state = apply_model(
                    model, cast_params(params, cdtype), state, xl, train
                )
            nll, correct, cnt = _local_ce_sums(logits, yl)
            ce = lax.psum(nll, "seq") / lax.psum(jnp.float32(cnt), "seq")
            # MoE router load-balance term, averaged over sequence shards
            # (empty list for dense models).
            aux_loss = lax.psum(sum(aux, jnp.float32(0.0)), "seq") / n
            loss = ce + cfg.moe_aux_weight * aux_loss
            correct = lax.psum(correct, "seq")
            return loss, ce, correct, new_state

        def make_sharded(train: bool):
            def inner(params, state, xl, yl):
                return fwd_local(params, state, xl, yl, train)

            return _shard_map(
                inner,
                mesh=self.mesh,
                in_specs=(P(), P(), P(None, "seq"), P(None, "seq")),
                out_specs=(P(), P(), P(), P()),
            )

        sp_train = make_sharded(True)
        sp_eval = make_sharded(False)

        def train_step(ts: TrainState, x, y, lr):
            def loss_fn(params):
                loss, ce, correct, new_state = sp_train(params, ts.model_state, x, y)
                return loss, (ce, correct, new_state)

            (_, (ce, correct, new_state)), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(ts.params)
            params, opt = sgd_update(ts.params, grads, ts.opt, lr, mom, wd)
            metrics = {
                "loss": ce,
                "accuracy": correct.astype(jnp.float32) / y.size,
            }
            return TrainState(params, new_state, opt), metrics

        def eval_step(ts: TrainState, x, y):
            _, ce, correct, _ = sp_eval(ts.params, ts.model_state, x, y)
            return {
                "loss": ce,
                "correct": correct,
                "count": jnp.asarray(y.size, jnp.int32),
            }

        self.train_step = jax.jit(
            train_step,
            donate_argnums=(0,),
            in_shardings=(None, self._batch_sharding, self._batch_sharding, None),
        )
        self.eval_step = jax.jit(
            eval_step,
            in_shardings=(None, self._batch_sharding, self._batch_sharding),
        )

    def init(self, key) -> TrainState:
        params, state, _ = init_model(self.model, key)
        ts = TrainState(params, state, sgd_init(params))
        return jax.device_put(ts, self._replicated)

    def shard_batch(self, x, y):
        return (
            jax.device_put(x, self._batch_sharding),
            jax.device_put(y, self._batch_sharding),
        )

    @property
    def world_size(self) -> int:
        return self.mesh.devices.size
